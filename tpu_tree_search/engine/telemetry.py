"""On-device search telemetry for the compiled loop.

The reference prints per-pool search statistics (nodes explored, pruned,
stolen — the boxplot bundle of common/util.h) because B&B performance is
dominated by pruning quality and load balance, not raw FLOPs. The flight
recorder (obs/) covers the host-side lifecycle, but the `lax.while_loop`
inside `jit` — where 99% of the wall time goes — was a black box between
segment boundaries. This module defines the fixed-shape telemetry block
the compiled pop->bound->prune->branch cycle updates with masked adds:

- per-worker popped / branched / pruned counts bucketed by RELATIVE
  depth (bucket k covers depths [k*J/DB, (k+1)*J/DB) — buckets are
  depth fractions, so the block's width is problem-independent);
- a bound-value histogram of pruned vs. surviving children, binned by
  the relative gap |bound - incumbent| / incumbent (bin BB-1 collects
  gaps >= 100%);
- pool-occupancy high-water mark (max live rows ever committed);
- work-steal sent/recv node flow (the balance exchange's view);
- an incumbent-improvement ring of the last RING (iteration, value)
  pairs, plus the total improvement count.

The block is ONE flat int64 vector (`WIDTH` slots, layout below) so it
rides `SearchState` exactly like the existing counters: through the
while_loop carry, the shard_map specs, checkpoint save/load and the
elastic reshard, with zero bespoke plumbing.

Compiled in behind a STATIC flag: `TTS_SEARCH_TELEMETRY=1` (or CLI
`--search-telemetry`) makes `init_state` allocate the `WIDTH`-slot
block; off (the default) allocates a zero-width vector and every update
site is a Python-level `if state.telemetry.shape[-1]` branch, so the
traced program contains NO telemetry ops — the off-mode HLO is the
pre-telemetry program with one empty tuple element. Telemetry is
OBSERVATION-ONLY either way: node/sol/evals/best are bit-identical with
the flag on or off (tests/test_telemetry.py pins this on the golden
instances).
"""

from __future__ import annotations

import numpy as np

# ---------------------------------------------------------------- layout

DEPTH_BUCKETS = 8      # relative-depth buckets for popped/branched/pruned
BOUND_BINS = 8         # relative-gap bins for the bound-value histogram
RING = 8               # incumbent-improvement (iteration, value) pairs

O_POPPED = 0
O_BRANCHED = O_POPPED + DEPTH_BUCKETS
O_PRUNED = O_BRANCHED + DEPTH_BUCKETS
O_HIST_PRUNED = O_PRUNED + DEPTH_BUCKETS
O_HIST_SURV = O_HIST_PRUNED + BOUND_BINS
O_POOL_HW = O_HIST_SURV + BOUND_BINS     # max, not add
O_STEAL_SENT = O_POOL_HW + 1
O_STEAL_RECV = O_STEAL_SENT + 1
O_IMPROVED = O_STEAL_RECV + 1            # ring write cursor / total count
O_RING = O_IMPROVED + 1                  # RING x (iteration, value)
WIDTH = O_RING + 2 * RING

# every slot below O_POOL_HW is a pure count: element-wise summable
# across workers/reshards; the tail needs merge()'s special handling
_COUNT_SLOTS = O_POOL_HW

ENV_FLAG = "TTS_SEARCH_TELEMETRY"


def enabled() -> bool:
    """The static compile-in flag (TTS_SEARCH_TELEMETRY / CLI
    --search-telemetry). Read at state-INIT time: a state keeps the
    width it was born (or checkpointed) with."""
    from ..utils.config import env_flag
    return env_flag(ENV_FLAG)


def enabled_width() -> int:
    return WIDTH if enabled() else 0


# ----------------------------------------------------- traced update ops
# (imported lazily by the engine's step functions; kept here so the
# layout and the ops that write it cannot drift apart)

def depth_bucket(depth, jobs: int):
    """Relative-depth bucket index for int32 depth values in [0, jobs]
    (a popped complete board at depth == jobs clips into the last
    bucket)."""
    import jax.numpy as jnp
    b = depth * DEPTH_BUCKETS // max(jobs, 1)
    return jnp.clip(b, 0, DEPTH_BUCKETS - 1)


def bucket_counts(bucket_idx, mask):
    """(DEPTH_BUCKETS,) int64 masked counts — DEPTH_BUCKETS masked
    reductions, not a scatter (row scatters serialize on TPU; 8 vector
    reductions are noise next to the bound kernels)."""
    import jax.numpy as jnp
    return jnp.stack([
        jnp.sum(mask & (bucket_idx == k), dtype=jnp.int64)
        for k in range(DEPTH_BUCKETS)])


def bound_hist(bounds, mask, best):
    """(BOUND_BINS,) int64 histogram of the relative gap
    |bound - best| / best; the last bin collects gaps >= 100%. With no
    incumbent yet (best = INT_MAX) the gap saturates, so every
    pre-incumbent child lands in that last far-gap bin — the inner bins
    only become informative once a real incumbent exists, which is when
    pruning starts mattering (ub=inf runs: read the last bin as
    "far from the incumbent OR before one existed")."""
    import jax.numpy as jnp
    b = bounds.reshape(-1).astype(jnp.int64)
    ref = jnp.maximum(best.astype(jnp.int64), 1)
    gap = jnp.abs(b - ref)
    bins = jnp.minimum(gap * BOUND_BINS // ref, BOUND_BINS - 1)
    m = mask.reshape(-1)
    return jnp.stack([jnp.sum(m & (bins == k), dtype=jnp.int64)
                      for k in range(BOUND_BINS)])


def step_delta(popped_b, branched_b, pruned_b,
               hist_pruned=None, hist_surv=None):
    """Assemble one step's (WIDTH,) additive delta from the bucketed
    counts; the non-additive tail (high-water, steal flow, ring) stays
    zero — device._commit / the balance round own those slots."""
    import jax.numpy as jnp
    z = jnp.zeros(BOUND_BINS, jnp.int64)
    return jnp.concatenate([
        popped_b, branched_b, pruned_b,
        hist_pruned if hist_pruned is not None else z,
        hist_surv if hist_surv is not None else z,
        jnp.zeros(WIDTH - O_POOL_HW, jnp.int64)])


def commit(tele, delta, new_size, best, prev_best, iters):
    """Fold one step's delta into the telemetry vector: add the counts,
    max the pool high-water mark, and record an incumbent improvement
    (iteration, value) in the ring when `best` beat `prev_best`. The
    caller guards the result with its overflow no-commit select."""
    import jax
    import jax.numpy as jnp
    t = tele + delta
    t = t.at[O_POOL_HW].max(new_size.astype(jnp.int64))
    improved = (best < prev_best).astype(jnp.int64)
    slot = (t[O_IMPROVED] % RING).astype(jnp.int32)
    pair = jnp.stack([(iters + 1).astype(jnp.int64),
                      best.astype(jnp.int64)])
    cur = jax.lax.dynamic_slice(t, (O_RING + 2 * slot,), (2,))
    t = jax.lax.dynamic_update_slice(
        t, jnp.where(improved > 0, pair, cur), (O_RING + 2 * slot,))
    return t.at[O_IMPROVED].add(improved)


# -------------------------------------------------------- host-side views

def _ring_pairs(vec: np.ndarray) -> list[list[int]]:
    """Decode the improvement ring: written (iteration, value) pairs in
    iteration order (value 0 marks an unwritten slot — makespans and
    bound values are strictly positive)."""
    pairs = [(int(vec[O_RING + 2 * k]), int(vec[O_RING + 2 * k + 1]))
             for k in range(RING)]
    pairs = [p for p in pairs if p[1] > 0]
    pairs.sort(key=lambda p: p[0])
    return [list(p) for p in pairs]


def merge(stacked: np.ndarray) -> np.ndarray:
    """Fold a (D, WIDTH) per-worker block into one (WIDTH,) vector —
    the checkpoint/elastic-reshard summation rule: counts sum, the pool
    high-water is the max, and the incumbent ring is rebuilt by
    replaying every worker's recorded improvements in iteration order
    and keeping the strictly-improving tail (per-worker attribution
    does not survive a topology change by definition — the totals do).
    """
    stacked = np.atleast_2d(np.asarray(stacked, np.int64))
    if stacked.shape[-1] == 0:
        return np.zeros(0, np.int64)
    out = stacked.sum(axis=0)
    out[O_POOL_HW] = stacked[:, O_POOL_HW].max()
    pairs: list[tuple[int, int]] = []
    for d in range(stacked.shape[0]):
        pairs.extend((p[0], p[1]) for p in _ring_pairs(stacked[d]))
    pairs.sort(key=lambda p: p[0])
    replay: list[tuple[int, int]] = []
    for it, val in pairs:
        if not replay or val < replay[-1][1]:
            replay.append((it, val))
    replay = replay[-RING:]
    out[O_RING:] = 0
    # Slot placement must keep commit()'s write cursor consistent: the
    # cursor is O_IMPROVED % RING (O_IMPROVED stays the summed total),
    # so the replayed pairs are laid out ENDING at slot (total-1) %
    # RING — the next on-device improvement then lands right after the
    # newest kept pair instead of clobbering it while empty slots
    # remain. Decoding is slot-order-independent (_ring_pairs sorts by
    # iteration), so only the overwrite order depends on this.
    start = (int(out[O_IMPROVED]) - len(replay)) % RING
    for k, (it, val) in enumerate(replay):
        slot = (start + k) % RING
        out[O_RING + 2 * slot] = it
        out[O_RING + 2 * slot + 1] = val
    return out


def summarize(arr) -> dict | None:
    """JSON-safe summary of a telemetry block ((WIDTH,) or (D, WIDTH));
    None for a zero-width (telemetry-off) block. The schema the
    SegmentReport, the service's labeled gauges, bench.py and the
    campaign rows all share."""
    arr = np.asarray(arr, np.int64)
    if arr.shape[-1] == 0:
        return None
    m = merge(np.atleast_2d(arr))
    popped = m[O_POPPED:O_POPPED + DEPTH_BUCKETS]
    branched = m[O_BRANCHED:O_BRANCHED + DEPTH_BUCKETS]
    pruned = m[O_PRUNED:O_PRUNED + DEPTH_BUCKETS]
    evaluated = int(branched.sum() + pruned.sum())
    return {
        "popped": popped.tolist(),
        "branched": branched.tolist(),
        "pruned": pruned.tolist(),
        "bound_hist_pruned":
            m[O_HIST_PRUNED:O_HIST_PRUNED + BOUND_BINS].tolist(),
        "bound_hist_surviving":
            m[O_HIST_SURV:O_HIST_SURV + BOUND_BINS].tolist(),
        "pool_highwater": int(m[O_POOL_HW]),
        "steal_sent": int(m[O_STEAL_SENT]),
        "steal_recv": int(m[O_STEAL_RECV]),
        "improvements": int(m[O_IMPROVED]),
        "incumbent_ring": _ring_pairs(m),
        "pruning_rate": round(float(pruned.sum()) / max(evaluated, 1), 6),
        "frontier_depth": frontier_depth(popped),
    }


def delta_counts(now_vec, prev_vec) -> dict:
    """Window-scoped counts between two merged (WIDTH,) snapshots —
    THE delta reading, shared by run_segmented's per-segment trace
    events and bench.py's timed-window row so neither re-derives the
    layout offsets by hand. Only the additive slots are read; the
    high-water mark and the ring have no window-scoped meaning."""
    d = (np.asarray(now_vec, np.int64)
         - np.asarray(prev_vec, np.int64))
    popped = d[O_POPPED:O_POPPED + DEPTH_BUCKETS]
    branched = int(d[O_BRANCHED:O_BRANCHED + DEPTH_BUCKETS].sum())
    pruned = int(d[O_PRUNED:O_PRUNED + DEPTH_BUCKETS].sum())
    return {
        "popped": int(popped.sum()),
        "branched": branched,
        "pruned": pruned,
        "pruning_rate": round(pruned / max(branched + pruned, 1), 6),
        "frontier_depth": frontier_depth(popped),
        "steal_sent": int(d[O_STEAL_SENT]),
        "steal_recv": int(d[O_STEAL_RECV]),
    }


def frontier_depth(popped_buckets) -> float:
    """Mean relative depth of the popped frontier in [0, 1] (0 = root,
    1 = leaves): the weighted mean bucket midpoint of the popped-node
    depth distribution."""
    popped = np.asarray(popped_buckets, np.float64)
    n = popped.sum()
    if n <= 0:
        return 0.0
    mids = (np.arange(DEPTH_BUCKETS) + 0.5) / DEPTH_BUCKETS
    return round(float((popped * mids).sum() / n), 6)


# --------------------------------------------------- metrics registry view

# every labeled series publish() writes — the service retires these by
# request label at the terminal transition (the cardinality valve, same
# rule as tts_phase_seconds)
SERIES = (
    "tts_search_popped", "tts_search_branched", "tts_search_pruned",
    "tts_search_bound_gap", "tts_search_pruning_rate",
    "tts_search_frontier_depth", "tts_search_pool_highwater",
    "tts_search_steal_sent", "tts_search_steal_recv",
    "tts_search_improvements",
)


def publish(summary: dict, registry, **labels) -> None:
    """Write a summarize() dict into an obs/metrics Registry as labeled
    gauges (gauges, not counters: values are SET from cumulative
    snapshots, and a resumed checkpoint must not double-count). The
    caller supplies identity labels (request=..., tag=...) — the
    per-request scrape surface the ISSUE's pruning-efficiency story
    needs without opening the trace."""
    if not summary:
        return
    g = registry.gauge
    for name, key in (("tts_search_popped", "popped"),
                      ("tts_search_branched", "branched"),
                      ("tts_search_pruned", "pruned")):
        m = g(name, f"{key} nodes by relative-depth bucket (cumulative)")
        for k, v in enumerate(summary[key]):
            m.set(v, bucket=k, **labels)
    m = g("tts_search_bound_gap",
          "child bound-value histogram by relative gap to the incumbent")
    for k, v in enumerate(summary["bound_hist_pruned"]):
        m.set(v, outcome="pruned", bin=k, **labels)
    for k, v in enumerate(summary["bound_hist_surviving"]):
        m.set(v, outcome="surviving", bin=k, **labels)
    g("tts_search_pruning_rate",
      "pruned / evaluated non-leaf children (cumulative)").set(
        summary["pruning_rate"], **labels)
    g("tts_search_frontier_depth",
      "mean relative depth of popped nodes (0=root, 1=leaves)").set(
        summary["frontier_depth"], **labels)
    g("tts_search_pool_highwater",
      "pool-occupancy high-water mark (live rows)").set(
        summary["pool_highwater"], **labels)
    g("tts_search_steal_sent",
      "nodes donated via balance exchanges").set(
        summary["steal_sent"], **labels)
    g("tts_search_steal_recv",
      "nodes received via balance exchanges").set(
        summary["steal_recv"], **labels)
    g("tts_search_improvements",
      "incumbent improvements recorded on-device").set(
        summary["improvements"], **labels)
