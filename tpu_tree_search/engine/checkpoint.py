"""Checkpoint / resume for long searches.

The reference has no checkpointing at all — a killed multi-day run loses
everything (SURVEY.md §5: "Checkpoint/resume: none"). Because the TPU
engine's entire search state is a handful of plain tensors (the pool
arrays, cursors, incumbent, counters), snapshotting is trivial and cheap:
one host fetch + one compressed npz per interval.

`run_segmented` is the production driver: it runs the compiled loop in
bounded segments (max_iters at a time), checkpointing, heartbeat-printing
(the reference's 5000-iteration progress print, pfsp_gpu_cuda.c:324-330)
and stall-detecting between segments — the failure-detection layer the
reference also lacks.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import queue
import threading
import time
import warnings
import zipfile
import zlib

import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracelog
from ..utils import faults
from ..utils.retry import retry_call
from . import telemetry as tele
from .device import SearchState


POOL_FIELDS = ("prmu", "depth", "aux")

# Checkpoint schema version, embedded in every file. Loaders accept
# every version <= CURRENT (older layouts upgrade on load: row-major
# pools transpose, pre-aux files reconstruct); a file from a NEWER
# schema fails loudly (CheckpointSchemaError) instead of being
# misparsed as garbage state.
#   1 (implicit): row-major full-pool snapshots, no aux, no meta
#   2: feature-major live-row snapshots + capacity/pool_layout meta
#   3: = 2 plus embedded CRC32 + explicit schema version
SCHEMA_VERSION = 3

LAST_GOOD_SUFFIX = ".prev"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file is torn/corrupt (bad zip, CRC mismatch,
    missing members). load_resilient treats this as 'skip to the
    last-good snapshot', never 'resume wrong state'."""


class CheckpointSchemaError(RuntimeError):
    """The checkpoint was written by a NEWER schema than this build
    reads. Not corruption — falling back to an older snapshot would
    silently discard valid progress, so this is never swallowed."""


class SegmentTimeout(RuntimeError):
    """A segment exceeded its wall-clock watchdog. Deliberately NOT a
    transient error: a hung device dispatch does not unhang on retry —
    the caller (campaign supervisor) must kill and respawn the process."""


class StaleCheckpointError(RuntimeError):
    """An epoch-stale save was refused: the file on disk carries a
    NEWER lease epoch than the writer (fleet failover — a peer adopted
    this checkpoint family; see service/lease.py). Deliberately NOT
    transient: the stale owner must self-fence, never retry into a
    clobber."""


def _transient_errors() -> tuple:
    """Error types worth retrying: host/filesystem I/O, injected faults,
    and the runtime's transport errors (a dropped remote-TPU tunnel
    surfaces as XlaRuntimeError, an OSError subclass in some versions)."""
    errs = [OSError, faults.InjectedFault]
    try:
        from jax.errors import JaxRuntimeError
        errs.append(JaxRuntimeError)
    except ImportError:
        pass
    return tuple(errs)


TRANSIENT_ERRORS = _transient_errors()


def _retry(fn, what: str, attempts: int, base_s: float):
    """Run `fn` with exponential-backoff retry on transient errors
    (utils/retry.retry_call bound to this module's TRANSIENT_ERRORS).
    Non-transient exceptions (wrong answers, schema errors, timeouts)
    propagate immediately — retrying a deterministic failure only
    delays the loud abort."""
    return retry_call(fn, what=what, attempts=attempts, base_s=base_s,
                      transient=TRANSIENT_ERRORS)


def _with_watchdog(fn, timeout_s: float | None, what: str):
    """Run `fn` under a wall-clock watchdog: raises SegmentTimeout if it
    exceeds `timeout_s` (None/0 disables). The work runs on a daemon
    thread so a genuinely hung device call cannot also hang process
    exit — the supervisor's kill+respawn remains the recovery path; the
    timeout just converts a silent infinite wait into a loud error."""
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: dict = {}
    # the caller's fault plan must ride into the worker thread: a
    # thread-SCOPED plan (faults.scoped — the service's per-request
    # injection) lives in thread-local state the daemon thread cannot
    # see, and injection points inside fn (host_fetch) would silently
    # stop firing whenever the watchdog is armed
    plan = faults.active()

    def target():
        try:
            with faults.scoped(plan):
                box["result"] = fn()
        except BaseException as e:      # noqa: BLE001 — re-raised below
            box["error"] = e

    th = threading.Thread(target=target, daemon=True,
                          name="tts-segment-watchdog")
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        raise SegmentTimeout(
            f"{what} exceeded the {timeout_s:.1f}s wall-clock watchdog "
            "(hung device dispatch?); kill and resume from the last "
            "checkpoint")
    if "error" in box:
        raise box["error"]
    return box["result"]


def _to_np(x) -> np.ndarray:
    """Host copy of a (possibly multihost-sharded) array: plain asarray
    single-controller; allgather the global value under multi-controller
    (where np.asarray on non-addressable shards raises)."""
    if not getattr(x, "is_fully_addressable", True):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _fetch_many(xs: tuple, fire: bool = True) -> tuple:
    """One batched device->host fetch of several small arrays. On a
    remote-TPU runtime every separate np.asarray is a full roundtrip;
    a single device_get puts all transfers in flight together, so the
    batch costs ~one latency instead of len(xs). Multihost shards fall
    back to the collective allgather path per leaf.

    `fire=False` skips the fault-injection hook: checkpoint-state
    fetches reuse this batching but were never an injection point (the
    resilience tests' fail_host_fetch budgets count HEARTBEAT fetches),
    and the budget must not drift when the save path batches too."""
    if fire:
        faults.fire("host_fetch")  # deterministic transient-error hook
    if any(not getattr(x, "is_fully_addressable", True) for x in xs):
        return tuple(_to_np(x) for x in xs)
    import jax
    return tuple(np.asarray(v) for v in jax.device_get(xs))


def _payload_crc(arrays: dict) -> int:
    """CRC32 over every stored array's name, dtype, shape and raw bytes
    (sorted by name, `meta_crc32` itself excluded) — the end-to-end
    integrity check a torn write or bit flip cannot survive. The zip
    layer's per-member CRCs already catch most damage; this one also
    covers damage the zip container cannot see (a member swapped in
    whole, an interrupted rewrite that left a stale-but-valid zip)."""
    crc = 0
    for name in sorted(arrays):
        if name == "meta_crc32":
            continue
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(str(a.dtype).encode(), crc)
        crc = zlib.crc32(np.asarray(a.shape, np.int64).tobytes(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def last_good_path(path: str | pathlib.Path) -> pathlib.Path:
    """The rotating last-good snapshot that rides beside `path`."""
    path = pathlib.Path(path)
    return path.with_name(path.name + LAST_GOOD_SUFFIX)


def resume_path(path: str | pathlib.Path) -> pathlib.Path | None:
    """The file a resume should try first: `path` if present, else its
    last-good sibling (the current file vanished mid-rotation), else
    None (nothing to resume — a stale .tmp from an interrupted first
    save is NOT resumable: it was never fsync'd + renamed, so its
    contents carry no durability promise)."""
    path = pathlib.Path(path)
    if path.exists():
        return path
    prev = last_good_path(path)
    return prev if prev.exists() else None


# checkpoint size buckets (bytes): tests write ~kB snapshots, production
# pools compress to tens-of-MB..GB
_BYTES_BUCKETS = (1e4, 1e5, 1e6, 1e7, 1e8, 1e9)

# segment-gap buckets (seconds): sub-ms when overlapped, up to the cost
# of a full heartbeat + checkpoint round when not
GAP_BUCKETS = (0.0005, 0.002, 0.01, 0.05, 0.2, 1.0, 5.0, 30.0)
GAP_HELP = ("device-idle gap between consecutive segments: dispatch of "
            "segment N+1 minus results-ready of segment N, clamped at 0 "
            "(TTS_OVERLAP drives this to ~0)")


def save(path: str | pathlib.Path, state: SearchState,
         meta: dict | None = None):
    """Snapshot a search state — flight-recorded wrapper around
    :func:`_save_impl` (one `checkpoint.save` span carrying the written
    byte count, plus save-latency/bytes histograms in the metrics
    registry). See `_save_impl` for the format and durability story."""
    with tracelog.span("checkpoint.save", path=str(path)) as sp:
        _save_impl(path, state, meta)
        nbytes = 0
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            pass          # non-writer multihost rank, or racing rotate
        sp.set(bytes=nbytes)
    _record_save_metrics(sp.dur, nbytes)


def _record_save_metrics(dur: float, nbytes: int) -> None:
    """Post-write bookkeeping shared by the sync :func:`save` and the
    async writer thread — one definition so the two drivers' series
    (names, help, buckets) can never drift."""
    reg = obs_metrics.default()
    reg.counter("tts_checkpoint_saves_total",
                "checkpoint snapshots written").inc()
    reg.histogram("tts_checkpoint_save_seconds",
                  "checkpoint save latency (fetch+compress+fsync)"
                  ).observe(dur)
    if nbytes:
        reg.histogram("tts_checkpoint_bytes", "checkpoint file size",
                      buckets=_BYTES_BUCKETS).observe(nbytes)


def _save_impl(path: str | pathlib.Path, state: SearchState,
               meta: dict | None = None):
    """Snapshot a search state (single-device or stacked distributed).

    Only the live pool rows (below the cursor) are fetched and written —
    rows above the cursor are garbage by the engine invariant, and a
    production pool is orders of magnitude larger than its live region
    (fetching + compressing the full arrays made checkpoints cost more
    than the segments they protected). The declared capacity is kept in
    the file so load() re-homes the rows into an identical pool.

    Torn-write-proof by construction: the bytes (with an embedded CRC32
    + schema version) go to a temp file that is flushed and fsync'd
    BEFORE any rename; the previous snapshot rotates to a `.prev`
    last-good sibling and the temp file renames into place. A crash at
    any point leaves either the old snapshot, the rotated last-good, or
    the new snapshot — never a half-written file under the resume path
    (load_resilient picks the newest loadable one).
    """
    arrays = snapshot_arrays(state, meta)
    if arrays is None:
        return                           # non-writer multihost rank
    _write_snapshot(path, arrays)


def snapshot_arrays(state: SearchState, meta: dict | None = None
                    ) -> dict | None:
    """Fetch a state's live rows and assemble the checkpoint payload
    (everything up to, but not including, the schema/CRC stamps). The
    host half of a save, split out so the async writer path can run it
    on the DISPATCH thread — while the device arrays are still valid —
    and hand the host arrays to the writer thread for the compress +
    fsync half (:func:`_write_snapshot`).

    The fetch is ONE batched device_get of every live-row slice — the
    per-leaf roundtrips the old save paid (len(fields) latencies on a
    remote-TPU tunnel) collapse to one.

    Returns None on non-writer multihost ranks: every rank must reach
    this point (the fetches are collective allgathers there), but only
    process 0 may write — concurrent writes + renames of one tmp file
    on a shared filesystem can corrupt or race the checkpoint."""
    sizes = np.atleast_1d(_to_np(state.size))
    n = int(sizes.max())
    leaves = tuple(x[..., :n] if f in POOL_FIELDS else x
                   for f, x in zip(SearchState._fields, state))
    arrays = dict(zip(SearchState._fields,
                      _fetch_many(leaves, fire=False)))
    arrays["meta_capacity"] = np.asarray(state.prmu.shape[-1])
    arrays["meta_pool_layout"] = np.asarray(1)   # 1 = feature-major
    if meta:
        reserved = {"capacity", "pool_layout", "schema_version", "crc32"} \
            & meta.keys()
        if reserved:
            raise ValueError(f"meta keys {sorted(reserved)} are reserved "
                             "by the checkpoint format")
        for k, v in meta.items():
            arrays[f"meta_{k}"] = np.asarray(v)
    import jax
    if jax.process_index() != 0:
        return None
    return arrays


def _existing_lease_epoch(path: pathlib.Path) -> int | None:
    """Best-effort peek of an on-disk snapshot's ``meta_lease_epoch``
    stamp. Absent file, absent stamp, or an unreadable file (mid-crash
    torso — load_resilient's problem, not the fence's) all yield None:
    the fence only refuses when it can PROVE the disk is newer."""
    try:
        with np.load(path) as z:
            if "meta_lease_epoch" in z.files:
                return int(z["meta_lease_epoch"])
    except Exception:  # noqa: BLE001 — any unreadable existing file
        return None    # means "nothing provably newer": proceed
    return None


def _write_snapshot(path: str | pathlib.Path, arrays: dict) -> None:
    """The durable half of a save: stamp schema + CRC, write to a temp
    file, fsync, rotate current -> `.prev` last-good, rename into
    place, fsync the directory. Pure host work on already-fetched
    arrays — exactly what the async checkpoint writer runs off the
    dispatch thread. Idempotent w.r.t. retry (stamps overwrite)."""
    arrays["meta_schema_version"] = np.asarray(SCHEMA_VERSION)
    arrays["meta_crc32"] = np.asarray(_payload_crc(arrays), np.uint32)
    path = pathlib.Path(path)
    # fencing (fleet failover): a save carrying a lease-epoch stamp
    # first peeks the on-disk file's stamp and REFUSES to overwrite a
    # newer one — a fenced-out stale owner can never clobber its
    # adopter's snapshot, even if timing slips. Saves without the
    # stamp (every non-fleet run) pay nothing.
    inc = arrays.get("meta_lease_epoch")
    if inc is not None:
        existing = _existing_lease_epoch(path)
        if existing is not None and existing > int(inc):
            raise StaleCheckpointError(
                f"{path}: on-disk checkpoint carries lease epoch "
                f"{existing} > writer's {int(inc)} — refusing the "
                "stale save")
    tmp = path.with_suffix(".tmp.npz")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    # rotate current -> last-good, then temp -> current. Both renames
    # are atomic; a kill between them leaves no current file and
    # resume_path/load_resilient fall back to the last-good sibling.
    if path.exists():
        os.replace(path, last_good_path(path))
    os.replace(tmp, path)
    try:
        # fsync the directory so the renames themselves are durable
        # (without it a power loss can resurrect the pre-rename view)
        dfd = os.open(path.parent or pathlib.Path("."), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass   # not every filesystem supports directory fsync


class AsyncCheckpointWriter:
    """Single writer thread that takes checkpoint serialization + fsync
    off the segment dispatch thread (half of TTS_OVERLAP — see
    :func:`run_segmented`).

    Ordering and durability:

    - ONE thread, FIFO queue: writes land in submission order, so the
      current/``.prev`` rotation invariant of :func:`_write_snapshot`
      holds exactly as in the sync path — the last-good sibling is
      always the previous successfully written snapshot, never dropped
      or reordered;
    - the queue is BOUNDED (config.ASYNC_CKPT_QUEUE_DEPTH): a dispatch
      thread outrunning the disk blocks in :meth:`enqueue` —
      back-pressure, never an unbounded buffer of multi-MB snapshots
      and never a silently dropped write;
    - the host-fetch half (:func:`snapshot_arrays`) runs on the CALLING
      thread via :meth:`prepare` — the device arrays may be donated to
      the next segment's dispatch immediately afterwards — and only
      the compress + fsync + rotate half crosses the thread;
    - :meth:`drain` blocks until everything queued is ON DISK and
      re-raises the first writer-side error; every overlapped exit path
      drains before returning, so a returned state always has its final
      checkpoint durable (the same contract the sync path gives).

    The writer re-installs the submitting thread's fault plan and trace
    context (request id kept; ``submesh`` dropped so its spans render
    on a dedicated ``tts-ckpt-writer`` Perfetto lane) and runs the same
    post-write hooks the sync path runs, in the same order: the
    checkpoint-roundtrip audit — against counter sums captured at
    prepare() time, so the conservation check spans the async edge —
    and then the ``post_checkpoint`` fault injection."""

    def __init__(self, retry_attempts: int | None = None,
                 retry_base_s: float | None = None,
                 max_pending: int | None = None):
        from ..utils import config as _cfg
        if retry_attempts is None:
            retry_attempts = _cfg.env_int("TTS_RETRY_ATTEMPTS")
        if retry_base_s is None:
            retry_base_s = _cfg.env_float("TTS_RETRY_BASE_S")
        self.retry_attempts = retry_attempts
        self.retry_base_s = retry_base_s
        self._q: queue.Queue = queue.Queue(
            maxsize=max_pending or _cfg.ASYNC_CKPT_QUEUE_DEPTH)
        # the AOTCache close discipline, with TWO locks on purpose:
        # _close_lock makes the closed-check + enqueue atomic against
        # close() (a task slipped in AFTER the shutdown sentinel would
        # never run its task_done, hanging a later drain) — the writer
        # thread NEVER takes it, so a submit blocked on the bounded
        # queue while holding it still drains; _err_lock serializes the
        # error hand-off between the writer and the submitting side. A
        # single shared lock would deadlock: a producer holding it
        # while blocked in the full queue's put() and the writer's
        # error path wanting it before task_done() is an ABBA cycle
        # between the lock and the queue capacity.
        self._close_lock = threading.Lock()
        self._err_lock = threading.Lock()
        self._err: BaseException | None = None   # guarded-by: self._err_lock
        self._closed = False                     # guarded-by: self._close_lock
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="tts-ckpt-writer")
        self._thread.start()

    # ------------------------------------------------- submitting side

    def prepare(self, path, state: SearchState, meta: dict | None = None,
                segment: int | None = None) -> dict | None:
        """Fetch + assemble the snapshot on the CALLING thread (the
        arrays must be read before the pools are donated onward).
        Returns the task for :meth:`enqueue` — or None when this rank
        must not write (non-writer multihost process)."""
        from ..obs import audit as obs_audit
        arrays = snapshot_arrays(state, meta)
        if arrays is None:
            return None
        sums = None
        if obs_audit.roundtrip_enabled():
            host = SearchState(*(arrays[f] for f in SearchState._fields))
            sums = obs_audit.state_sums(host)
        ctx = {**tracelog.current_context(), "submesh": None}
        return {"path": str(path), "arrays": arrays, "sums": sums,
                "segment": segment, "plan": faults.active(), "ctx": ctx}

    def enqueue(self, task: dict | None) -> None:
        """Queue a prepared task; blocks at the back-pressure bound.
        Re-raises the first pending writer-side error first (an
        earlier failed write must not be papered over by later ones)."""
        self._raise_pending()
        if task is None:
            return
        with self._close_lock:
            if self._closed:
                raise RuntimeError("AsyncCheckpointWriter is closed")
            self._q.put(task)

    def submit(self, path, state: SearchState, meta: dict | None = None,
               segment: int | None = None) -> None:
        """prepare() + enqueue() in one call."""
        self.enqueue(self.prepare(path, state, meta, segment=segment))

    def drain(self) -> None:
        """Block until every queued snapshot is on disk; re-raise the
        first writer-side error (a failed final save must fail the run,
        exactly as the sync path would)."""
        self._q.join()
        self._raise_pending()

    def close(self, raise_pending: bool = True) -> None:
        """Drain, stop the thread, optionally surface pending errors
        (False on exception-unwind paths, where masking the original
        error with a writer error would hide the root cause)."""
        with self._close_lock:
            was_closed = self._closed
            if not was_closed:
                self._closed = True
                self._q.put(None)
        if not was_closed:
            self._thread.join()
        if raise_pending:
            self._raise_pending()

    def _raise_pending(self) -> None:
        with self._err_lock:
            err, self._err = self._err, None
        if err is not None:
            raise err

    # ---------------------------------------------------- writer thread

    def _loop(self) -> None:
        while True:
            task = self._q.get()
            try:
                if task is None:
                    return
                self._write_one(task)
            except BaseException as e:  # noqa: BLE001 — surfaced at the
                with self._err_lock:    # next enqueue()/drain()
                    if self._err is None:
                        self._err = e
            finally:
                self._q.task_done()

    def _write_one(self, task: dict) -> None:
        path = task["path"]
        with faults.scoped(task["plan"]), \
                tracelog.get().context(**task["ctx"]):
            with tracelog.span("checkpoint.save", path=path,
                               async_write=True) as sp:
                _retry(lambda: _write_snapshot(path, task["arrays"]),
                       "checkpoint save", self.retry_attempts,
                       self.retry_base_s)
                nbytes = 0
                try:
                    nbytes = os.path.getsize(path)
                except OSError:
                    pass
                sp.set(bytes=nbytes)
            _record_save_metrics(sp.dur, nbytes)
            from ..obs import audit as obs_audit
            if task["sums"] is not None:
                # audit BEFORE the fault injection below, same order as
                # the sync do_save: the injected corruption is a
                # load-side drill, not a write-side failure
                obs_audit.check_checkpoint_roundtrip(path, task["sums"])
            faults.fire("post_checkpoint", segment=task["segment"],
                        path=path)


def load(path: str | pathlib.Path,
         p_times: np.ndarray | None = None) -> tuple[SearchState, dict]:
    """Load a snapshot, verifying integrity first. Pre-aux checkpoints
    (before the pool carried per-node [front | remain] tables) are
    upgraded on load by reconstructing aux from the live rows — pass the
    instance's `p_times` for that; without it such files raise a clear
    error.

    Raises CheckpointCorrupt on a torn/damaged file (bad zip, CRC
    mismatch, missing members — every read error, so a caller never
    resumes wrong state) and CheckpointSchemaError on a file written by
    a newer schema than this build reads."""
    with tracelog.span("checkpoint.load", path=str(path)):
        obs_metrics.default().counter(
            "tts_checkpoint_loads_total",
            "checkpoint load attempts").inc()
        return _load_impl(path, p_times=p_times)


def _load_impl(path: str | pathlib.Path,
               p_times: np.ndarray | None = None
               ) -> tuple[SearchState, dict]:
    path = pathlib.Path(path)
    try:
        with np.load(path) as z:
            # full materialization doubles as the zip-member CRC pass
            # (zipfile verifies each member's own CRC as it inflates)
            raw = {k: z[k] for k in z.files}
    except (zipfile.BadZipFile, zlib.error, OSError, EOFError, ValueError,
            KeyError) as e:
        # zipfile errors can embed whole raw headers — keep the reason
        # human-sized, the chained exception preserves the full detail
        reason = str(e)
        if len(reason) > 200:
            reason = reason[:200] + "... [truncated]"
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable (torn write or "
            f"corruption): {reason}") from e
    version = int(raw.get("meta_schema_version", 2 if "meta_capacity"
                          in raw else 1))
    if version > SCHEMA_VERSION:
        raise CheckpointSchemaError(
            f"checkpoint {path} uses schema version {version}; this "
            f"build reads <= {SCHEMA_VERSION} — upgrade the reader, do "
            "not fall back to an older snapshot")
    if "meta_crc32" in raw:
        want = int(raw["meta_crc32"])
        got = _payload_crc(raw)
        if got != want:
            raise CheckpointCorrupt(
                f"checkpoint {path} failed its embedded CRC32 "
                f"(stored {want:#010x}, recomputed {got:#010x})")
    missing = [f for f in SearchState._fields
               if f not in ("aux", "telemetry") and f not in raw]
    if missing:
        raise CheckpointCorrupt(
            f"checkpoint {path} is missing state fields {missing} "
            "(truncated or partial write)")
    arrays = {f: raw[f] for f in SearchState._fields if f in raw}
    meta = {k[5:]: raw[k] for k in raw if k.startswith("meta_")}
    meta.pop("schema_version", None)
    meta.pop("crc32", None)
    feature_major = bool(meta.pop("pool_layout", 0))
    if not feature_major:
        # legacy row-major snapshot: transpose pool matrices on load; a
        # legacy aux held [front | remain] — the pool now carries only
        # front (remain is reconstructed in-kernel), so keep the first
        # half of its rows
        for f in ("prmu", "aux"):
            if f in arrays:
                arrays[f] = np.swapaxes(arrays[f], -1, -2).copy()
        if "aux" in arrays and arrays["aux"].shape[-2] > 0:
            m = arrays["aux"].shape[-2] // 2
            arrays["aux"] = arrays["aux"][..., :m, :].copy()
    if "capacity" in meta:
        # live-row snapshot: re-home into the declared capacity
        capacity = int(meta.pop("capacity"))
        for f in POOL_FIELDS:
            if f not in arrays:
                continue
            x = arrays[f]
            pad = capacity - x.shape[-1]
            if pad > 0:
                widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
                arrays[f] = np.pad(x, widths)
    if "aux" not in arrays:
        if p_times is None:
            raise ValueError(
                f"{path} is a pre-aux checkpoint; pass p_times to load() "
                "so the per-node pool tables can be reconstructed")
        from ..ops import reference as ref
        prmu = arrays["prmu"]            # feature-major (/, jobs, rows)
        depth = arrays["depth"]
        size = np.atleast_1d(arrays["size"])
        stacked = prmu.ndim == 3
        m = p_times.shape[0]
        aux = np.zeros(prmu.shape[:-2] + (m, prmu.shape[-1]), np.int32)
        for d in range(prmu.shape[0] if stacked else 1):
            n = int(size[d if stacked else 0])
            if stacked:
                aux[d, :, :n] = ref.prefix_front_remain(
                    p_times, prmu[d, :, :n].T, depth[d, :n])[:, :m].T
            else:
                aux[:, :n] = ref.prefix_front_remain(
                    p_times, prmu[:, :n].T, depth[:n])[:, :m].T
        arrays["aux"] = aux
    if "telemetry" not in arrays:
        # pre-telemetry snapshot: reconstruct a zeroed block at the
        # CURRENT flag's width (counters restart from the resume; the
        # saved pool/counter state is untouched either way)
        lead = (arrays["prmu"].shape[0],) if arrays["prmu"].ndim == 3 \
            else ()
        arrays["telemetry"] = np.zeros(lead + (tele.enabled_width(),),
                                       np.int64)
    state = SearchState(*(jnp.asarray(arrays[f])
                          for f in SearchState._fields))
    return state, meta


def load_resilient(path: str | pathlib.Path,
                   p_times: np.ndarray | None = None
                   ) -> tuple[SearchState, dict, pathlib.Path]:
    """Load `path`, falling back to its rotating last-good sibling when
    the current file is torn/corrupt (or missing after an interrupted
    rotation). Returns (state, meta, loaded_path) — callers that priced
    anything off the file (aux dtype, capacity) must use `loaded_path`,
    not `path`.

    A corrupt current snapshot costs at most the work since the
    PREVIOUS checkpoint; it never poisons the run. Only when every
    candidate is unreadable does this raise, listing what was tried.
    CheckpointSchemaError is deliberately not caught: a valid
    newer-schema file must not be silently shadowed by an older one."""
    path = pathlib.Path(path)
    candidates = [path, last_good_path(path)]
    errors = []
    for cand in candidates:
        if not cand.exists():
            errors.append(f"{cand}: missing")
            continue
        try:
            state, meta = load(cand, p_times=p_times)
        except CheckpointCorrupt as e:
            warnings.warn(
                f"skipping corrupt checkpoint {cand}: {e}",
                RuntimeWarning, stacklevel=2)
            errors.append(f"{cand}: {e}")
            tracelog.event("checkpoint.corrupt", path=str(cand),
                           error=str(e)[:200])
            obs_metrics.default().counter(
                "tts_checkpoint_corrupt_total",
                "torn/corrupt snapshots skipped on load").inc()
            if cand == path:
                # Quarantine the torn CURRENT file: leaving it in place
                # lets the next save() rotate it over the good
                # last-good, and a crash between save's two renames
                # would then leave nothing loadable at all. Renamed
                # aside (not unlinked) so the damage stays available
                # for forensics. Process 0 only — on a multi-controller
                # shared filesystem every process runs this resume path
                # and concurrent renames of one file race.
                try:
                    import jax
                    if jax.process_index() == 0:
                        os.replace(cand, str(cand) + ".corrupt")
                        tracelog.event("checkpoint.quarantine",
                                       path=str(cand) + ".corrupt")
                        obs_metrics.default().counter(
                            "tts_checkpoint_quarantines_total",
                            "torn current snapshots renamed aside").inc()
                except OSError:
                    pass
            continue
        if cand != path:
            warnings.warn(
                f"resuming from last-good snapshot {cand} (current "
                "checkpoint torn/missing); work since the previous "
                "checkpoint interval will be redone",
                RuntimeWarning, stacklevel=2)
            tracelog.event("checkpoint.rollback", path=str(cand),
                           wanted=str(path))
            obs_metrics.default().counter(
                "tts_checkpoint_rollbacks_total",
                "resumes served by the rotating last-good sibling").inc()
        return state, meta, cand
    raise CheckpointCorrupt(
        "no loadable checkpoint: " + "; ".join(errors))


def reshard_state(state: SearchState, new_workers: int,
                  squeeze: bool = False) -> SearchState:
    """Elastic resume: re-home an N-worker stacked snapshot (or a
    single-device one) onto `new_workers` pools, so a preempted job
    restarts on whatever slice is available (M < N and M > N both
    work — the failure mode real fleets actually have is "came back
    with a different topology").

    Host-side and lossless: every worker's live rows (rows [0, size) by
    the pool invariant) are concatenated and round-robin striped across
    the M new pools — the same water-filling split the balance
    exchange converges to (parallel/balance.waterfill_counts: per-pool
    counts differ by <= 1) and the same striping idiom as warm-up
    seeding (distributed._shard_frontier). Capacity doubles as needed
    so the widest stripe fits; callers with tighter usable-row limits
    (scratch margins, balance headroom) grow() further on top.

    Counter semantics across the reshard:
    - tree/sol/evals/sent/recv/steals: global totals preserved — summed
      onto worker 0 (only the totals are ever reported; per-worker
      attribution does not survive a topology change by definition);
    - iters: replicated at the old max, so a cumulative per-worker
      iteration ceiling keeps meaning "this much MORE work per worker";
    - best: min-replicated (the incumbent is global);
    - overflow: cleared — the resumed run's first step re-detects a
      genuinely over-full pool via the same lossless no-commit path.

    `squeeze=True` with new_workers=1 returns an UNSTACKED single-device
    state (the shape device.run expects) instead of a (1, ...) stack.
    """
    if new_workers < 1:
        raise ValueError(f"new_workers must be >= 1, got {new_workers}")
    if squeeze and new_workers != 1:
        raise ValueError("squeeze=True requires new_workers == 1")
    from ..parallel import balance as bal

    arrs = SearchState(*(np.asarray(x) for x in state))
    if arrs.prmu.ndim == 2:            # single-device snapshot: lift
        arrs = SearchState(*(a[None, ...] for a in arrs))
    if arrs.prmu.ndim != 3:
        raise ValueError(
            f"reshard_state needs a (D, jobs, capacity) stacked or "
            f"(jobs, capacity) single-device pool, got {arrs.prmu.shape}")
    D, jobs, capacity = arrs.prmu.shape
    A = arrs.aux.shape[1]
    M = new_workers
    if M != D:
        tracelog.event("elastic_reshard", old_workers=int(D),
                       new_workers=int(M))
        obs_metrics.default().counter(
            "tts_elastic_reshards_total",
            "checkpoints re-homed onto a different worker count").inc()
    sizes = np.atleast_1d(arrs.size).astype(np.int64)

    # concatenate live rows in worker order (bottom-to-top per pool)
    live_prmu = np.concatenate(
        [arrs.prmu[d, :, :sizes[d]] for d in range(D)], axis=1)
    live_depth = np.concatenate(
        [arrs.depth[d, :sizes[d]] for d in range(D)])
    live_aux = np.concatenate(
        [arrs.aux[d, :, :sizes[d]] for d in range(D)], axis=1)

    total = int(sizes.sum())
    counts = bal.waterfill_counts(total, M)
    while counts.max() > capacity:
        capacity *= 2

    prmu = np.zeros((M, jobs, capacity), arrs.prmu.dtype)
    depth = np.zeros((M, capacity), arrs.depth.dtype)
    aux = np.zeros((M, A, capacity), arrs.aux.dtype)
    for m in range(M):
        stripe = slice(m, None, M)     # round-robin, water-filled
        n = int(counts[m])
        prmu[m, :, :n] = live_prmu[:, stripe]
        depth[m, :n] = live_depth[stripe]
        aux[m, :, :n] = live_aux[:, stripe]

    def on_zero(total_val, dtype):
        v = np.zeros(M, dtype)
        v[0] = total_val
        return v

    # telemetry follows the tree/sol rule: global totals preserved,
    # merged onto worker 0 (counts summed, pool high-water maxed, the
    # incumbent ring replayed in iteration order — telemetry.merge)
    tw = arrs.telemetry.shape[-1]
    telem = np.zeros((M, tw), np.int64)
    if tw:
        telem[0] = tele.merge(arrs.telemetry)

    out = SearchState(
        telemetry=telem,
        prmu=prmu, depth=depth, aux=aux,
        size=counts.astype(np.int32),
        best=np.full(M, int(np.min(arrs.best)), np.int32),
        tree=on_zero(int(np.sum(arrs.tree)), np.int64),
        sol=on_zero(int(np.sum(arrs.sol)), np.int64),
        iters=np.full(M, int(np.max(arrs.iters)), np.int64),
        evals=on_zero(int(np.sum(arrs.evals)), np.int64),
        sent=on_zero(int(np.sum(arrs.sent)), np.int64),
        recv=on_zero(int(np.sum(arrs.recv)), np.int64),
        steals=on_zero(int(np.sum(arrs.steals)), np.int64),
        overflow=np.zeros(M, bool),
    )
    if squeeze:
        out = SearchState(*(a[0] for a in out))
    return SearchState(*(jnp.asarray(a) for a in out))


def collapse_to_single_device(state: SearchState, chunk: int,
                              jobs: int) -> SearchState:
    """Collapse a stacked (D, jobs, cap) snapshot onto ONE device: the
    elastic reshard to a single squeezed pool, pre-sized for the mesh
    run's TOTAL footprint (D x per-worker capacity — the one pool now
    carries every worker's rows and their future growth) and then
    doubled until the live rows clear the usable-row limit
    (device.row_limit's chunk*jobs scratch margin), so a nearly-full
    stacked snapshot cannot overflow on its first resumed segment.
    Shared by the CLI's and the campaign worker's resume paths — the
    sizing invariant lives in exactly one place."""
    from .device import row_limit

    shape = np.asarray(state.prmu).shape
    if len(shape) != 3:
        return state                     # already single-device
    stacked_total = int(shape[0] * shape[-1])
    out = reshard_state(state, 1, squeeze=True)
    grown = max(int(out.prmu.shape[-1]), stacked_total)
    need = int(np.asarray(out.size).max())
    while row_limit(grown, chunk, jobs) < max(need, 1):
        grown *= 2
    if grown != out.prmu.shape[-1]:
        out = grow(out, grown)
    return out


class PoolOverflow(RuntimeError):
    """Pool capacity exceeded; `.state` is the (resumable) search state."""

    def __init__(self, message: str, state: SearchState):
        super().__init__(message)
        self.state = state


def grow(state: SearchState, new_capacity: int) -> SearchState:
    """Re-home a search state — single-device (jobs, cap) or stacked
    distributed (D, jobs, cap) — into a larger pool, clearing the
    overflow flag(s): the recovery path after an overflow abort (load or
    fetch, grow, resume). Rows above each cursor are garbage by the pool
    invariant, so growth is zero-padding the row axis."""
    capacity = np.asarray(state.prmu).shape[-1]
    if new_capacity < capacity:
        raise ValueError(f"new_capacity {new_capacity} < current {capacity}")
    tracelog.event("pool.grow", capacity=int(capacity),
                   new_capacity=int(new_capacity))
    obs_metrics.default().counter(
        "tts_pool_grows_total", "lossless overflow pool growths").inc()
    pad = new_capacity - capacity

    def pad_rows(x):
        x = np.asarray(x)
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return jnp.asarray(np.pad(x, widths))

    ovf = np.zeros_like(np.asarray(state.overflow))
    return state._replace(prmu=pad_rows(state.prmu),
                          depth=pad_rows(state.depth),
                          aux=pad_rows(state.aux),
                          overflow=jnp.asarray(ovf))


@dataclasses.dataclass
class SegmentReport:
    segment: int
    iters: int
    tree: int
    sol: int
    best: int
    pool_size: int
    elapsed: float
    # distributed runs: per-worker live sizes / cumulative steal counts /
    # incumbents / explored+eval counters (the heartbeat surface the
    # reference's "Still Idle" print, dist:663-668, only hints at, and
    # the inputs the live phase attribution needs — see
    # utils/phase_timing.publish_attribution); None on single-device runs
    per_worker: dict | None = None
    evals: int = 0               # cumulative bound evaluations (total)
    # cumulative on-device search telemetry (telemetry.summarize dict:
    # depth-bucketed popped/branched/pruned, bound histograms, pool
    # high-water, steal flow, incumbent ring, pruning rate); None when
    # the state carries no telemetry block (TTS_SEARCH_TELEMETRY off)
    telemetry: dict | None = None


class _ReportFolder:
    """Per-segment report assembly shared by the sync and overlapped
    segment drivers: fold a fetched counter/telemetry block into the
    per-worker stats dict, the per-segment ``search.telemetry`` delta
    event, the SegmentReport, the explored-node throughput counter and
    the no-progress stall check. ONE implementation, so the on/off
    bit-parity the overlap feature promises extends to everything the
    two drivers record — a schema or semantics change cannot land in
    one driver and silently drift the other."""

    def __init__(self, state: SearchState, t0: float, stall_limit: int,
                 start_iters: int):
        self.t0 = t0
        self.stall_limit = stall_limit
        self.stalls = 0
        self.last = (start_iters, -1, -1)
        # resumed states carry cumulative totals; throughput metrics
        # must count only THIS run's progress. Telemetry width via
        # .shape, never np.asarray: materializing a state leaf here
        # raises on multihost runs (non-addressable shards — the
        # hazard _to_np exists for)
        self.prev_tree = int(np.atleast_1d(_to_np(state.tree)).sum())
        self.tele_w = int(state.telemetry.shape[-1])
        # search-telemetry deltas start from the INCOMING block (a
        # resumed checkpoint's counts must not re-report as segment-1
        # activity)
        self.prev_tele = (
            tele.merge(np.atleast_2d(_to_np(state.telemetry)))
            if self.tele_w else None)
        self.prev_evals = np.atleast_1d(_to_np(state.evals)).copy()
        self.nodes_c = obs_metrics.default().counter(
            "tts_nodes_explored_total",
            "explored-node throughput (segment deltas)")

    def fold(self, fetched: tuple, seg: int) -> SegmentReport:
        (f_iters, f_tree, f_sol, sizes, f_best, f_steals, _f_ovf,
         f_evals) = fetched[:8]
        iters = int(f_iters.max())
        tree = int(f_tree.sum())
        sol = int(f_sol.sum())
        size = int(sizes.sum())
        per_worker = None
        if sizes.ndim:                      # stacked distributed state
            per_worker = {"size": sizes.tolist(),
                          "steals": f_steals.tolist(),
                          "best": f_best.tolist(),
                          "iters": f_iters.tolist(),
                          "evals": f_evals.tolist()}
        tele_summary = None
        if self.tele_w:
            # cumulative summary for the report + a per-segment DELTA
            # event for the trace — the time series Perfetto counter
            # tracks and tools/search_report.py render
            merged = tele.merge(np.atleast_2d(fetched[8]))
            tele_summary = tele.summarize(merged)
            deltas = tele.delta_counts(merged, self.prev_tele)
            evals_d = np.atleast_1d(f_evals) - self.prev_evals
            ev = {}
            if sizes.ndim:
                ev = {"workers": int(sizes.shape[0]),
                      "evals_pw": evals_d.tolist()}
            tracelog.event(
                "search.telemetry", segment=seg, **deltas, pool=size,
                pool_hw=tele_summary["pool_highwater"],
                best=int(f_best.min()),
                improvements=tele_summary["improvements"], **ev)
            self.prev_tele = merged
            self.prev_evals = np.atleast_1d(f_evals).copy()
        # per-segment DELTA, so the counter is live throughput, not the
        # cumulative totals a resumed checkpoint would double-report
        self.nodes_c.inc(max(tree - self.prev_tree, 0))
        self.prev_tree = tree
        return SegmentReport(
            segment=seg, iters=iters, tree=tree, sol=sol,
            best=int(f_best.min()), pool_size=size,
            elapsed=time.perf_counter() - self.t0,
            per_worker=per_worker, evals=int(f_evals.sum()),
            telemetry=tele_summary)

    def check_stall(self, report: SegmentReport) -> None:
        key = (report.iters, report.tree, report.sol)
        if key == self.last:
            self.stalls += 1
            if self.stalls >= self.stall_limit:
                raise RuntimeError(
                    f"search stalled: no progress across {self.stalls} "
                    f"segments (iters={report.iters}, "
                    f"pool={report.pool_size})")
        else:
            self.stalls = 0
        self.last = key


def run_segmented(run_fn, state: SearchState, segment_iters: int = 2048,
                  checkpoint_path: str | None = None,
                  checkpoint_every: int = 1,
                  heartbeat=print, max_segments: int | None = None,
                  max_total_iters: int | None = None,
                  stall_limit: int = 3,
                  raise_on_overflow: bool = True,
                  checkpoint_meta: dict | None = None,
                  post_segment=None,
                  should_stop=None,
                  retry_attempts: int | None = None,
                  retry_base_s: float | None = None,
                  segment_timeout_s: float | None = None,
                  overlap: bool = False,
                  grow_fn=None,
                  stop_pending=None):
    """Drive `run_fn(state, target_total_iters) -> state` to exhaustion in
    bounded segments.

    `run_fn` receives a CUMULATIVE iteration ceiling (matching
    `device.run(..., max_iters=...)`'s semantics: the loop condition is
    `state.iters < max_iters`), not an increment. Targets are offset by the
    incoming state's iteration count, so resuming from a loaded checkpoint
    works.

    - checkpoints every `checkpoint_every` segments when a path is given;
    - calls `post_segment(state) -> state` after each segment, BEFORE the
      heartbeat/checkpoint, so cross-tier effects (the `-C` host
      session's incumbent merge) land in both (engine/hybrid.HostSession);
    - calls `heartbeat(SegmentReport)` after each segment;
    - stops early (after checkpointing) when `should_stop(SegmentReport)`
      returns True — the wall-budget hook for campaign drivers;
    - `checkpoint_meta` may be a CALLABLE returning the meta dict, re-
      evaluated at every save (live values like cumulative wall time);
    - raises RuntimeError after `stall_limit` consecutive segments with no
      progress (tree/sol/iters all unchanged) — a compiled-loop stall is a
      bug, not a state, so fail loudly rather than spin (the reference's
      equivalent symptom is its 10-second "Still Idle" print, dist:663-668);
    - on pool overflow the search state is incomplete: raises RuntimeError
      (after checkpointing, so the state is recoverable) unless
      `raise_on_overflow=False`, in which case the caller must check
      `state.overflow` before trusting the counters.

    Resilience (the layer the reference lacks end to end): segment
    execution, checkpoint writes and the per-segment scalar fetch are
    retried `retry_attempts` times with exponential backoff
    (`retry_base_s * 2^k`) on TRANSIENT errors only (I/O, runtime
    transport, injected faults — see TRANSIENT_ERRORS); a
    `segment_timeout_s` wall-clock watchdog converts a hung device
    dispatch into a loud SegmentTimeout (never retried — the
    supervisor's kill+respawn is the recovery for hangs). Defaults read
    TTS_RETRY_ATTEMPTS (3), TTS_RETRY_BASE_S (0.5) and
    TTS_SEG_TIMEOUT_S (0 = off). Deterministic fault injection for all
    of these lives in utils/faults.py (TTS_FAULTS).

    Overlap (`overlap=True`, the driver side of TTS_OVERLAP —
    engine/distributed.search resolves the flag and supplies the
    hooks): `run_fn` must then be an ASYNC dispatch (returns the next
    state's futures without blocking — _DistDriver.run_async, pool
    leaves donated) and execution pipelines: segment N+1 is dispatched
    BEFORE segment N's counters are fetched, so the heartbeat always
    consumes the PREVIOUS segment's report while the device computes,
    and the device-idle gap between segments (the new
    `tts_segment_gap_seconds` histogram; both modes record it) drops
    to ~0. Checkpoint serialization + fsync move to a bounded-queue
    AsyncCheckpointWriter thread; only the live-row host fetch stays on
    the dispatch thread (checkpoint segments therefore dispatch after
    that fetch — the one per-`checkpoint_every` synchronization the
    format's rotation invariants require). `grow_fn(state) -> state`
    is the lossless overflow recovery (fetch + grow + recommit);
    `stop_pending() -> bool` is a report-free stop probe that skips
    speculative dispatch when a stop was already requested. Exit
    conditions are evaluated one segment later than the sync path
    (the in-flight speculative segment is drained, never discarded —
    it no-ops when the pool is empty or overflowed), so a stop request
    costs at most one extra segment; totals at exhaustion are
    bit-identical to overlap-off. Incompatible with `post_segment`
    (the host-tier merge mutates state the pipeline has already
    donated) — callers must force overlap off alongside a host tier.

    The resilience contract under overlap is NARROWER than sync's: a
    transient error in segment EXECUTION cannot be retried in place —
    the failed dispatch's input pools were donated, so there is no
    prior state to re-run and the retry wrapper around the counter
    fetch can only re-observe the poisoned output. In-place retries
    cover the host-side I/O edges (fetch, save); recovery from a
    failed segment is the OUTER tier's job — checkpoint re-dispatch
    (the service's re-queue path, `load_resilient` standalone), which
    is exactly what the durability layer exists for. Runs that need
    in-place execution retries (no checkpoint, no supervisor) should
    keep overlap off.
    """
    from ..utils import config as _cfg
    if retry_attempts is None:
        retry_attempts = _cfg.env_int("TTS_RETRY_ATTEMPTS")
    if retry_base_s is None:
        retry_base_s = _cfg.env_float("TTS_RETRY_BASE_S")
    if segment_timeout_s is None:
        segment_timeout_s = _cfg.env_float("TTS_SEG_TIMEOUT_S")
    import jax
    if jax.process_count() > 1:
        # Multi-controller: run_fn, save and the scalar fetch all
        # contain COLLECTIVES (process_allgather, the SPMD loop). A
        # per-process retry re-enters its collective alone while the
        # other processes have moved on — mismatched collective order
        # is a distributed hang, strictly worse than the transient it
        # retries. Fail loudly instead; multihost recovery is
        # restart-the-job-level (every process resumes from the shared
        # checkpoint), not retry-in-place. The same reasoning disables
        # overlap: speculative dispatch would reorder collectives
        # against the allgather-bearing fetches.
        retry_attempts = 1
        overlap = False
    if overlap:
        if post_segment is not None:
            raise ValueError(
                "overlap=True is incompatible with post_segment (the "
                "host-tier merge mutates state the pipeline has already "
                "donated); run the host tier with overlap off")
        return _run_segmented_overlap(
            run_fn, state, segment_iters=segment_iters,
            checkpoint_path=checkpoint_path,
            checkpoint_every=checkpoint_every, heartbeat=heartbeat,
            max_segments=max_segments, max_total_iters=max_total_iters,
            stall_limit=stall_limit, raise_on_overflow=raise_on_overflow,
            checkpoint_meta=checkpoint_meta, should_stop=should_stop,
            retry_attempts=retry_attempts, retry_base_s=retry_base_s,
            segment_timeout_s=segment_timeout_s, grow_fn=grow_fn,
            stop_pending=stop_pending)
    t0 = time.perf_counter()
    seg = 0
    start_iters = int(_to_np(state.iters).max())
    folder = _ReportFolder(state, t0, stall_limit, start_iters)
    # device-idle accounting shared with the overlapped driver: the gap
    # between segment N's results landing on the host and segment N+1's
    # dispatch is time the device spends waiting on the host (heartbeat,
    # checkpoint, stop checks) — the exact interval TTS_OVERLAP removes
    gap_hist = obs_metrics.default().histogram(
        "tts_segment_gap_seconds", GAP_HELP, buckets=GAP_BUCKETS)
    results_ready_t = None

    def meta_now(seg):
        base = checkpoint_meta() if callable(checkpoint_meta) \
            else dict(checkpoint_meta or {})
        return {**base, "segment": seg}

    def do_save(s, seg_no):
        _retry(lambda: save(checkpoint_path, s, meta=meta_now(seg_no)),
               "checkpoint save", retry_attempts, retry_base_s)
        # audit hook (TTS_AUDIT=full / TTS_AUDIT_CKPT=1): re-read the
        # snapshot and require bit-identical counters — BEFORE the
        # fault injection below, which may corrupt the file on purpose
        # to exercise the load-side rollback
        from ..obs import audit as obs_audit
        if obs_audit.roundtrip_enabled():
            obs_audit.check_checkpoint_roundtrip(checkpoint_path, s)
        # torn-write / corruption injection targets the just-written
        # file — the load-side rollback to last-good is what it tests
        faults.fire("post_checkpoint", segment=seg_no,
                    path=checkpoint_path)

    def final_save(s, seg):
        # every exit path must leave a CURRENT checkpoint — with
        # checkpoint_every > 1, returning without this leaves the file
        # up to checkpoint_every-1 segments stale and a planned
        # stop-then-resume silently redoes that work
        if checkpoint_path and seg % checkpoint_every != 0:
            do_save(s, seg)

    while True:
        target = start_iters + (seg + 1) * segment_iters
        if max_total_iters is not None:
            target = min(target, start_iters + max_total_iters)
        faults.fire("segment_start", segment=seg + 1)
        # run_fn is functional (the incoming state is untouched on
        # failure), so a retried segment redoes identical work; the
        # watchdog wraps each attempt separately
        prev_state = state
        if results_ready_t is not None:
            gap_hist.observe(max(0.0, time.monotonic() - results_ready_t))
        with tracelog.span("segment", segment=seg + 1) as seg_span:
            state = _retry(
                lambda: _with_watchdog(
                    lambda: run_fn(prev_state, target),
                    segment_timeout_s, f"segment {seg + 1}"),
                "segment execution", retry_attempts, retry_base_s)
            if post_segment is not None:
                state = post_segment(state)
            seg += 1
            # ONE batched host fetch for every per-segment scalar:
            # through a remote-TPU runtime each separate fetch is a full
            # roundtrip (~0.15 s on the tunnel; six of them cost ~0.9 s
            # per segment — measured as the gap between segment wall
            # time and the compiled loop's in-trace step cost,
            # BENCHMARKS.md round 3)
            # the watchdog must cover this fetch too: dispatch is ASYNC,
            # so a hung device computation lets run_fn return its
            # futures instantly and the block happens HERE, waiting on
            # the results
            fetched = _retry(
                lambda: _with_watchdog(
                    lambda: _fetch_many(
                        (state.iters, state.tree, state.sol,
                         state.size, state.best, state.steals,
                         state.overflow, state.evals)
                        + ((state.telemetry,) if folder.tele_w
                           else ())),
                    segment_timeout_s, f"segment {seg} result fetch"),
                "per-segment host fetch", retry_attempts, retry_base_s)
            results_ready_t = time.monotonic()
            f_ovf = fetched[6]
            seg_span.set(iters=int(fetched[0].max()),
                         tree=int(fetched[1].sum()),
                         sol=int(fetched[2].sum()),
                         pool=int(fetched[3].sum()),
                         best=int(fetched[4].min()))
        # fold AFTER the span closes so the `segment` span record still
        # precedes its search.telemetry event in the record stream
        report = folder.fold(fetched, seg)
        iters, size = report.iters, report.pool_size
        obs_metrics.default().histogram(
            "tts_segment_seconds",
            "segment wall latency (execute+fetch)"
            ).observe(seg_span.dur)
        if heartbeat is not None:
            heartbeat(report)
        if checkpoint_path and seg % checkpoint_every == 0:
            do_save(state, seg)
        # preemption injection point: fires at the END of segment k,
        # after any checkpoint that segment wrote. Deliberately NOT
        # checkpoint-aligned — real preemptions are not either; with
        # checkpoint_every > 1 the on-disk snapshot may be up to
        # checkpoint_every-1 segments older and recovery redoes that
        # interval (the kill-then-resume-elsewhere shape elastic
        # resume exists for)
        faults.fire("post_segment", segment=seg)
        if bool(f_ovf.any()):
            final_save(state, seg)
            if raise_on_overflow:
                hint = (f"resume from {checkpoint_path} with a larger "
                        "capacity" if checkpoint_path else
                        "rerun with a larger capacity, or catch "
                        "PoolOverflow and grow() its .state")
                raise PoolOverflow(
                    f"pool overflow at segment {seg} (pool={size}): search "
                    f"incomplete; {hint}", state)
            return state
        if size == 0:
            final_save(state, seg)
            return state
        if should_stop is not None and should_stop(report):
            final_save(state, seg)
            return state
        folder.check_stall(report)
        if max_segments is not None and seg >= max_segments:
            final_save(state, seg)
            return state
        if (max_total_iters is not None
                and iters >= start_iters + max_total_iters):
            final_save(state, seg)
            return state


def _run_segmented_overlap(run_fn, state: SearchState, *, segment_iters,
                           checkpoint_path, checkpoint_every, heartbeat,
                           max_segments, max_total_iters, stall_limit,
                           raise_on_overflow, checkpoint_meta,
                           should_stop, retry_attempts, retry_base_s,
                           segment_timeout_s, grow_fn, stop_pending):
    """The pipelined segment driver behind `run_segmented(overlap=True)`.

    Pipeline shape (see run_segmented's docstring for the contract):
    segment N+1 is dispatched — donated carries, so the in-flight state
    is never copied — BEFORE segment N's counter block is fetched; the
    heartbeat then consumes segment N's report while the device runs
    N+1. Exit conditions found in segment N's report drain the
    in-flight segment (a no-op when the pool is empty or overflowed —
    the compiled loop's condition re-checks both) instead of discarding
    it, so node accounting is bit-identical to the sync driver.
    Checkpoint segments synchronize only for the live-row host fetch;
    compression + fsync run on the AsyncCheckpointWriter thread.

    `segment` spans are emitted with EXPLICIT [dispatch, results-ready]
    timestamps (tracelog.span_at): consecutive spans overlap in wall
    time exactly when the device ran back-to-back, which is what the
    search_report gap table and the tts_segment_gap_seconds histogram
    measure."""
    t0 = time.perf_counter()
    seg = 0
    start_iters = int(_to_np(state.iters).max())
    folder = _ReportFolder(state, t0, stall_limit, start_iters)
    reg = obs_metrics.default()
    gap_hist = reg.histogram("tts_segment_gap_seconds", GAP_HELP,
                             buckets=GAP_BUCKETS)
    seg_hist = reg.histogram("tts_segment_seconds",
                             "segment wall latency (execute+fetch)")
    writer = (AsyncCheckpointWriter(retry_attempts=retry_attempts,
                                    retry_base_s=retry_base_s)
              if checkpoint_path else None)

    def target_for(k: int) -> int:
        t = start_iters + k * segment_iters
        if max_total_iters is not None:
            t = min(t, start_iters + max_total_iters)
        return t

    def meta_now(seg_no):
        base = checkpoint_meta() if callable(checkpoint_meta) \
            else dict(checkpoint_meta or {})
        return {**base, "segment": seg_no}

    def fetch_counters(cur, seg_no):
        # the ONLY per-segment fetch on the hot path: the small
        # counter/telemetry block (the full state is fetched solely on
        # checkpoint segments, via the writer's prepare())
        return _retry(
            lambda: _with_watchdog(
                lambda: _fetch_many(
                    (cur.iters, cur.tree, cur.sol, cur.size, cur.best,
                     cur.steals, cur.overflow, cur.evals)
                    + ((cur.telemetry,) if folder.tele_w else ())),
                segment_timeout_s, f"segment {seg_no} result fetch"),
            "per-segment host fetch", retry_attempts, retry_base_s)

    try:
        faults.fire("segment_start", segment=1)
        dispatch_t = time.monotonic()
        cur = run_fn(state, target_for(1))
        halting = False
        results_ready_t = None
        while True:
            seg += 1
            this_dispatch_t = dispatch_t
            is_ckpt = bool(checkpoint_path) \
                and seg % checkpoint_every == 0

            def can_speculate():
                return (not halting
                        and (max_segments is None or seg < max_segments)
                        and target_for(seg + 1) > target_for(seg)
                        and not (stop_pending is not None
                                 and stop_pending()))

            spec = spec_t = None
            next_fired = False   # fired segment_start for seg+1 yet?
            if not is_ckpt and can_speculate():
                faults.fire("segment_start", segment=seg + 1)
                next_fired = True
                spec_t = time.monotonic()
                spec = run_fn(cur, target_for(seg + 1))

            fetched = fetch_counters(cur, seg)
            prev_ready_t = results_ready_t
            results_ready_t = time.monotonic()
            (f_iters, f_tree, f_sol, sizes, f_best, f_steals, f_ovf,
             f_evals) = fetched[:8]

            # lossless overflow recovery, pipelined edition: the
            # speculative segment no-oped on the overflow flag, so
            # adopt it, grow every pool, and re-run the SAME segment
            # target from exactly where the loop stopped
            while bool(f_ovf.any()) and grow_fn is not None:
                if spec is not None:
                    cur, spec = spec, None
                cur = run_fn(grow_fn(cur), target_for(seg))
                fetched = fetch_counters(cur, seg)
                results_ready_t = time.monotonic()
                (f_iters, f_tree, f_sol, sizes, f_best, f_steals,
                 f_ovf, f_evals) = fetched[:8]

            if is_ckpt:
                # synchronization point: the live rows must be read
                # before the pools are donated to the next dispatch —
                # prepare() on this thread, then dispatch, then hand
                # the compress+fsync to the writer (enqueue may block
                # on back-pressure, but the device is already running)
                task = _retry(
                    lambda: _with_watchdog(
                        lambda: writer.prepare(
                            checkpoint_path, cur, meta_now(seg),
                            segment=seg),
                        segment_timeout_s,
                        f"segment {seg} checkpoint fetch"),
                    "checkpoint state fetch", retry_attempts,
                    retry_base_s)
                if can_speculate():
                    faults.fire("segment_start", segment=seg + 1)
                    next_fired = True
                    spec_t = time.monotonic()
                    spec = run_fn(cur, target_for(seg + 1))
                writer.enqueue(task)

            tracelog.span_at("segment", this_dispatch_t,
                             results_ready_t, segment=seg,
                             iters=int(f_iters.max()),
                             tree=int(f_tree.sum()),
                             sol=int(f_sol.sum()),
                             pool=int(sizes.sum()),
                             best=int(f_best.min()), overlapped=True)
            if prev_ready_t is not None:
                gap_hist.observe(max(0.0, this_dispatch_t - prev_ready_t))
            seg_hist.observe(max(results_ready_t - this_dispatch_t, 0.0))
            report = folder.fold(fetched, seg)
            iters, size = report.iters, report.pool_size
            if heartbeat is not None:
                heartbeat(report)
            faults.fire("post_segment", segment=seg)

            overflow_exit = bool(f_ovf.any())
            exit_now = halting or overflow_exit or size == 0
            if not exit_now and should_stop is not None \
                    and should_stop(report):
                exit_now = True
            if not exit_now and max_segments is not None \
                    and seg >= max_segments:
                exit_now = True
            if not exit_now and max_total_iters is not None \
                    and iters >= start_iters + max_total_iters:
                exit_now = True
            if exit_now:
                if spec is not None:
                    # drain the in-flight speculative segment first: a
                    # no-op on an empty/overflowed pool, at most one
                    # segment of extra work on a stop request — its
                    # output is the state the exit below must persist
                    halting = True
                    cur, dispatch_t = spec, spec_t
                    continue
                if checkpoint_path and seg % checkpoint_every != 0:
                    writer.submit(checkpoint_path, cur, meta_now(seg),
                                  segment=seg)
                if writer is not None:
                    writer.drain()
                if overflow_exit and raise_on_overflow:
                    hint = (f"resume from {checkpoint_path} with a "
                            "larger capacity" if checkpoint_path else
                            "rerun with a larger capacity, or catch "
                            "PoolOverflow and grow() its .state")
                    raise PoolOverflow(
                        f"pool overflow at segment {seg} (pool={size}): "
                        f"search incomplete; {hint}", cur)
                return cur
            folder.check_stall(report)
            if spec is not None:
                cur, dispatch_t = spec, spec_t
            else:
                if not next_fired:
                    # an abandoned speculation (overflow recovery)
                    # already fired this segment's injection point;
                    # firing again would double-spend fault budgets
                    # and break overlap-vs-sync injection parity
                    faults.fire("segment_start", segment=seg + 1)
                dispatch_t = time.monotonic()
                cur = run_fn(cur, target_for(seg + 1))
    finally:
        if writer is not None:
            # success paths drained above; this is the unwind valve —
            # never mask an in-flight exception with a writer error
            writer.close(raise_pending=False)
