"""Checkpoint / resume for long searches.

The reference has no checkpointing at all — a killed multi-day run loses
everything (SURVEY.md §5: "Checkpoint/resume: none"). Because the TPU
engine's entire search state is a handful of plain tensors (the pool
arrays, cursors, incumbent, counters), snapshotting is trivial and cheap:
one host fetch + one compressed npz per interval.

`run_segmented` is the production driver: it runs the compiled loop in
bounded segments (max_iters at a time), checkpointing, heartbeat-printing
(the reference's 5000-iteration progress print, pfsp_gpu_cuda.c:324-330)
and stall-detecting between segments — the failure-detection layer the
reference also lacks.
"""

from __future__ import annotations

import dataclasses
import pathlib
import time

import jax.numpy as jnp
import numpy as np

from .device import SearchState


def save(path: str | pathlib.Path, state: SearchState, meta: dict | None = None):
    """Snapshot a search state (single-device or stacked distributed)."""
    arrays = {f: np.asarray(x) for f, x in zip(SearchState._fields, state)}
    if meta:
        for k, v in meta.items():
            arrays[f"meta_{k}"] = np.asarray(v)
    path = pathlib.Path(path)
    tmp = path.with_suffix(".tmp.npz")
    np.savez_compressed(tmp, **arrays)
    tmp.rename(path)


def load(path: str | pathlib.Path) -> tuple[SearchState, dict]:
    with np.load(pathlib.Path(path)) as z:
        state = SearchState(*(jnp.asarray(z[f]) for f in SearchState._fields))
        meta = {k[5:]: z[k] for k in z.files if k.startswith("meta_")}
    return state, meta


@dataclasses.dataclass
class SegmentReport:
    segment: int
    iters: int
    tree: int
    sol: int
    best: int
    pool_size: int
    elapsed: float


def run_segmented(run_fn, state: SearchState, segment_iters: int = 2048,
                  checkpoint_path: str | None = None,
                  checkpoint_every: int = 1,
                  heartbeat=print, max_segments: int | None = None,
                  stall_limit: int = 3):
    """Drive `run_fn(state, extra_iters) -> state` to exhaustion in bounded
    segments.

    - checkpoints every `checkpoint_every` segments when a path is given;
    - calls `heartbeat(SegmentReport)` after each segment;
    - raises RuntimeError after `stall_limit` consecutive segments with no
      progress (tree/sol/iters all unchanged) — a compiled-loop stall is a
      bug, not a state, so fail loudly rather than spin (the reference's
      equivalent symptom is its 10-second "Still Idle" print, dist:663-668).
    """
    t0 = time.perf_counter()
    seg = 0
    stalls = 0
    last = (int(np.asarray(state.iters).max()), -1, -1)
    while True:
        target = (seg + 1) * segment_iters
        state = run_fn(state, target)
        seg += 1
        iters = int(np.asarray(state.iters).max())
        tree = int(np.asarray(state.tree).sum())
        sol = int(np.asarray(state.sol).sum())
        size = int(np.asarray(state.size).sum())
        if heartbeat is not None:
            heartbeat(SegmentReport(
                segment=seg, iters=iters, tree=tree, sol=sol,
                best=int(np.asarray(state.best).min()), pool_size=size,
                elapsed=time.perf_counter() - t0))
        if checkpoint_path and seg % checkpoint_every == 0:
            save(checkpoint_path, state, meta={"segment": seg})
        if size == 0 or bool(np.asarray(state.overflow).any()):
            return state
        if (iters, tree, sol) == last:
            stalls += 1
            if stalls >= stall_limit:
                raise RuntimeError(
                    f"search stalled: no progress across {stalls} segments "
                    f"(iters={iters}, pool={size})")
        else:
            stalls = 0
        last = (iters, tree, sol)
        if max_segments is not None and seg >= max_segments:
            return state
