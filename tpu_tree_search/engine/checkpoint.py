"""Checkpoint / resume for long searches.

The reference has no checkpointing at all — a killed multi-day run loses
everything (SURVEY.md §5: "Checkpoint/resume: none"). Because the TPU
engine's entire search state is a handful of plain tensors (the pool
arrays, cursors, incumbent, counters), snapshotting is trivial and cheap:
one host fetch + one compressed npz per interval.

`run_segmented` is the production driver: it runs the compiled loop in
bounded segments (max_iters at a time), checkpointing, heartbeat-printing
(the reference's 5000-iteration progress print, pfsp_gpu_cuda.c:324-330)
and stall-detecting between segments — the failure-detection layer the
reference also lacks.
"""

from __future__ import annotations

import dataclasses
import pathlib
import time

import jax.numpy as jnp
import numpy as np

from .device import SearchState


POOL_FIELDS = ("prmu", "depth", "aux")


def _to_np(x) -> np.ndarray:
    """Host copy of a (possibly multihost-sharded) array: plain asarray
    single-controller; allgather the global value under multi-controller
    (where np.asarray on non-addressable shards raises)."""
    if not getattr(x, "is_fully_addressable", True):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _fetch_many(xs: tuple) -> tuple:
    """One batched device->host fetch of several small arrays. On a
    remote-TPU runtime every separate np.asarray is a full roundtrip;
    a single device_get puts all transfers in flight together, so the
    batch costs ~one latency instead of len(xs). Multihost shards fall
    back to the collective allgather path per leaf."""
    if any(not getattr(x, "is_fully_addressable", True) for x in xs):
        return tuple(_to_np(x) for x in xs)
    import jax
    return tuple(np.asarray(v) for v in jax.device_get(xs))


def save(path: str | pathlib.Path, state: SearchState, meta: dict | None = None):
    """Snapshot a search state (single-device or stacked distributed).

    Only the live pool rows (below the cursor) are fetched and written —
    rows above the cursor are garbage by the engine invariant, and a
    production pool is orders of magnitude larger than its live region
    (fetching + compressing the full arrays made checkpoints cost more
    than the segments they protected). The declared capacity is kept in
    the file so load() re-homes the rows into an identical pool.
    """
    sizes = np.atleast_1d(_to_np(state.size))
    n = int(sizes.max())
    arrays = {}
    for f, x in zip(SearchState._fields, state):
        if f in POOL_FIELDS:
            x = x[..., :n]               # feature-major: row axis is last
        arrays[f] = _to_np(x)
    arrays["meta_capacity"] = np.asarray(state.prmu.shape[-1])
    arrays["meta_pool_layout"] = np.asarray(1)   # 1 = feature-major
    if meta:
        if "capacity" in meta:
            raise ValueError("meta key 'capacity' is reserved for the "
                             "pool re-home size")
        for k, v in meta.items():
            arrays[f"meta_{k}"] = np.asarray(v)
    # Multi-controller: every process reaches this point (the _to_np
    # fetches above are COLLECTIVE allgathers, so all ranks must run
    # them and all hold identical data), but only process 0 writes —
    # concurrent writes + renames of the same tmp file on a shared
    # filesystem can corrupt or race the checkpoint. resume reads the
    # same shared path on every process (load() is read-only).
    import jax
    if jax.process_index() != 0:
        return
    path = pathlib.Path(path)
    tmp = path.with_suffix(".tmp.npz")
    np.savez_compressed(tmp, **arrays)
    tmp.rename(path)


def load(path: str | pathlib.Path,
         p_times: np.ndarray | None = None) -> tuple[SearchState, dict]:
    """Load a snapshot. Pre-aux checkpoints (before the pool carried
    per-node [front | remain] tables) are upgraded on load by
    reconstructing aux from the live rows — pass the instance's
    `p_times` for that; without it such files raise a clear error."""
    with np.load(pathlib.Path(path)) as z:
        arrays = {f: z[f] for f in SearchState._fields if f in z.files}
        meta = {k[5:]: z[k] for k in z.files if k.startswith("meta_")}
    feature_major = bool(meta.pop("pool_layout", 0))
    if not feature_major:
        # legacy row-major snapshot: transpose pool matrices on load; a
        # legacy aux held [front | remain] — the pool now carries only
        # front (remain is reconstructed in-kernel), so keep the first
        # half of its rows
        for f in ("prmu", "aux"):
            if f in arrays:
                arrays[f] = np.swapaxes(arrays[f], -1, -2).copy()
        if "aux" in arrays and arrays["aux"].shape[-2] > 0:
            m = arrays["aux"].shape[-2] // 2
            arrays["aux"] = arrays["aux"][..., :m, :].copy()
    if "capacity" in meta:
        # live-row snapshot: re-home into the declared capacity
        capacity = int(meta.pop("capacity"))
        for f in POOL_FIELDS:
            if f not in arrays:
                continue
            x = arrays[f]
            pad = capacity - x.shape[-1]
            if pad > 0:
                widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
                arrays[f] = np.pad(x, widths)
    if "aux" not in arrays:
        if p_times is None:
            raise ValueError(
                f"{path} is a pre-aux checkpoint; pass p_times to load() "
                "so the per-node pool tables can be reconstructed")
        from ..ops import reference as ref
        prmu = arrays["prmu"]            # feature-major (/, jobs, rows)
        depth = arrays["depth"]
        size = np.atleast_1d(arrays["size"])
        stacked = prmu.ndim == 3
        m = p_times.shape[0]
        aux = np.zeros(prmu.shape[:-2] + (m, prmu.shape[-1]), np.int32)
        for d in range(prmu.shape[0] if stacked else 1):
            n = int(size[d if stacked else 0])
            if stacked:
                aux[d, :, :n] = ref.prefix_front_remain(
                    p_times, prmu[d, :, :n].T, depth[d, :n])[:, :m].T
            else:
                aux[:, :n] = ref.prefix_front_remain(
                    p_times, prmu[:, :n].T, depth[:n])[:, :m].T
        arrays["aux"] = aux
    state = SearchState(*(jnp.asarray(arrays[f])
                          for f in SearchState._fields))
    return state, meta


def aux_dtype_of(path) -> np.dtype:
    """The aux dtype a resume of `path` will end up with, read from the
    zip member's npy HEADER only (decompressing the array to learn its
    dtype costs a full second pass over a possibly multi-hundred-MB
    member). Legacy pre-aux checkpoints reconstruct as int32 (load()
    above). Lives here because it encodes this module's file format."""
    import zipfile

    with zipfile.ZipFile(path) as zf:
        if "aux.npy" not in zf.namelist():
            return np.dtype(np.int32)
        try:
            with zf.open("aux.npy") as f:
                version = np.lib.format.read_magic(f)
                if version == (1, 0):
                    _, _, dtype = np.lib.format.read_array_header_1_0(f)
                elif version == (2, 0):
                    _, _, dtype = np.lib.format.read_array_header_2_0(f)
                else:
                    # (3, 0) headers (utf8 field names) share the 2.0
                    # wire format for plain dtypes; parse via numpy's
                    # version-dispatching reader when present, else the
                    # 2.0 reader
                    read = getattr(np.lib.format, "_read_array_header",
                                   None)
                    if read is not None:
                        _, _, dtype = read(f, version)
                    else:
                        _, _, dtype = \
                            np.lib.format.read_array_header_2_0(f)
        except (ValueError, OSError) as e:
            # a corrupt/truncated member must surface as a clear resume
            # error, not an uncaught header-parse exception mid-load
            raise RuntimeError(
                f"unreadable aux.npy header in checkpoint {path}: {e}"
            ) from e
    return np.dtype(dtype)


class PoolOverflow(RuntimeError):
    """Pool capacity exceeded; `.state` is the (resumable) search state."""

    def __init__(self, message: str, state: SearchState):
        super().__init__(message)
        self.state = state


def grow(state: SearchState, new_capacity: int) -> SearchState:
    """Re-home a search state — single-device (jobs, cap) or stacked
    distributed (D, jobs, cap) — into a larger pool, clearing the
    overflow flag(s): the recovery path after an overflow abort (load or
    fetch, grow, resume). Rows above each cursor are garbage by the pool
    invariant, so growth is zero-padding the row axis."""
    capacity = np.asarray(state.prmu).shape[-1]
    if new_capacity < capacity:
        raise ValueError(f"new_capacity {new_capacity} < current {capacity}")
    pad = new_capacity - capacity

    def pad_rows(x):
        x = np.asarray(x)
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return jnp.asarray(np.pad(x, widths))

    ovf = np.zeros_like(np.asarray(state.overflow))
    return state._replace(prmu=pad_rows(state.prmu),
                          depth=pad_rows(state.depth),
                          aux=pad_rows(state.aux),
                          overflow=jnp.asarray(ovf))


@dataclasses.dataclass
class SegmentReport:
    segment: int
    iters: int
    tree: int
    sol: int
    best: int
    pool_size: int
    elapsed: float
    # distributed runs: per-worker live sizes / cumulative steal counts /
    # incumbents (the heartbeat surface the reference's "Still Idle"
    # print, dist:663-668, only hints at); None on single-device runs
    per_worker: dict | None = None


def run_segmented(run_fn, state: SearchState, segment_iters: int = 2048,
                  checkpoint_path: str | None = None,
                  checkpoint_every: int = 1,
                  heartbeat=print, max_segments: int | None = None,
                  max_total_iters: int | None = None,
                  stall_limit: int = 3,
                  raise_on_overflow: bool = True,
                  checkpoint_meta: dict | None = None,
                  post_segment=None,
                  should_stop=None):
    """Drive `run_fn(state, target_total_iters) -> state` to exhaustion in
    bounded segments.

    `run_fn` receives a CUMULATIVE iteration ceiling (matching
    `device.run(..., max_iters=...)`'s semantics: the loop condition is
    `state.iters < max_iters`), not an increment. Targets are offset by the
    incoming state's iteration count, so resuming from a loaded checkpoint
    works.

    - checkpoints every `checkpoint_every` segments when a path is given;
    - calls `post_segment(state) -> state` after each segment, BEFORE the
      heartbeat/checkpoint, so cross-tier effects (the `-C` host
      session's incumbent merge) land in both (engine/hybrid.HostSession);
    - calls `heartbeat(SegmentReport)` after each segment;
    - stops early (after checkpointing) when `should_stop(SegmentReport)`
      returns True — the wall-budget hook for campaign drivers;
    - `checkpoint_meta` may be a CALLABLE returning the meta dict, re-
      evaluated at every save (live values like cumulative wall time);
    - raises RuntimeError after `stall_limit` consecutive segments with no
      progress (tree/sol/iters all unchanged) — a compiled-loop stall is a
      bug, not a state, so fail loudly rather than spin (the reference's
      equivalent symptom is its 10-second "Still Idle" print, dist:663-668);
    - on pool overflow the search state is incomplete: raises RuntimeError
      (after checkpointing, so the state is recoverable) unless
      `raise_on_overflow=False`, in which case the caller must check
      `state.overflow` before trusting the counters.
    """
    t0 = time.perf_counter()
    seg = 0
    stalls = 0
    start_iters = int(_to_np(state.iters).max())
    last = (start_iters, -1, -1)

    def meta_now(seg):
        base = checkpoint_meta() if callable(checkpoint_meta) \
            else dict(checkpoint_meta or {})
        return {**base, "segment": seg}

    def final_save(s, seg):
        # every exit path must leave a CURRENT checkpoint — with
        # checkpoint_every > 1, returning without this leaves the file
        # up to checkpoint_every-1 segments stale and a planned
        # stop-then-resume silently redoes that work
        if checkpoint_path and seg % checkpoint_every != 0:
            save(checkpoint_path, s, meta=meta_now(seg))

    while True:
        target = start_iters + (seg + 1) * segment_iters
        if max_total_iters is not None:
            target = min(target, start_iters + max_total_iters)
        state = run_fn(state, target)
        if post_segment is not None:
            state = post_segment(state)
        seg += 1
        # ONE batched host fetch for every per-segment scalar: through a
        # remote-TPU runtime each separate fetch is a full roundtrip
        # (~0.15 s on the tunnel; six of them cost ~0.9 s per segment —
        # measured as the gap between segment wall time and the compiled
        # loop's in-trace step cost, BENCHMARKS.md round 3)
        fetched = _fetch_many((state.iters, state.tree, state.sol,
                               state.size, state.best, state.steals,
                               state.overflow))
        f_iters, f_tree, f_sol, sizes, f_best, f_steals, f_ovf = fetched
        iters = int(f_iters.max())
        tree = int(f_tree.sum())
        sol = int(f_sol.sum())
        size = int(sizes.sum())
        per_worker = None
        if sizes.ndim:                          # stacked distributed state
            per_worker = {"size": sizes.tolist(),
                          "steals": f_steals.tolist(),
                          "best": f_best.tolist()}
        report = SegmentReport(
            segment=seg, iters=iters, tree=tree, sol=sol,
            best=int(f_best.min()), pool_size=size,
            elapsed=time.perf_counter() - t0, per_worker=per_worker)
        if heartbeat is not None:
            heartbeat(report)
        if checkpoint_path and seg % checkpoint_every == 0:
            save(checkpoint_path, state, meta=meta_now(seg))
        if bool(f_ovf.any()):
            final_save(state, seg)
            if raise_on_overflow:
                hint = (f"resume from {checkpoint_path} with a larger "
                        "capacity" if checkpoint_path else
                        "rerun with a larger capacity, or catch "
                        "PoolOverflow and grow() its .state")
                raise PoolOverflow(
                    f"pool overflow at segment {seg} (pool={size}): search "
                    f"incomplete; {hint}", state)
            return state
        if size == 0:
            final_save(state, seg)
            return state
        if should_stop is not None and should_stop(report):
            final_save(state, seg)
            return state
        if (iters, tree, sol) == last:
            stalls += 1
            if stalls >= stall_limit:
                raise RuntimeError(
                    f"search stalled: no progress across {stalls} segments "
                    f"(iters={iters}, pool={size})")
        else:
            stalls = 0
        last = (iters, tree, sol)
        if max_segments is not None and seg >= max_segments:
            final_save(state, seg)
            return state
        if (max_total_iters is not None
                and iters >= start_iters + max_total_iters):
            final_save(state, seg)
            return state
