"""Checkpoint / resume for long searches.

The reference has no checkpointing at all — a killed multi-day run loses
everything (SURVEY.md §5: "Checkpoint/resume: none"). Because the TPU
engine's entire search state is a handful of plain tensors (the pool
arrays, cursors, incumbent, counters), snapshotting is trivial and cheap:
one host fetch + one compressed npz per interval.

`run_segmented` is the production driver: it runs the compiled loop in
bounded segments (max_iters at a time), checkpointing, heartbeat-printing
(the reference's 5000-iteration progress print, pfsp_gpu_cuda.c:324-330)
and stall-detecting between segments — the failure-detection layer the
reference also lacks.
"""

from __future__ import annotations

import dataclasses
import os
import pathlib
import threading
import time
import warnings
import zipfile
import zlib

import jax.numpy as jnp
import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracelog
from ..utils import faults
from ..utils.retry import retry_call
from . import telemetry as tele
from .device import SearchState


POOL_FIELDS = ("prmu", "depth", "aux")

# Checkpoint schema version, embedded in every file. Loaders accept
# every version <= CURRENT (older layouts upgrade on load: row-major
# pools transpose, pre-aux files reconstruct); a file from a NEWER
# schema fails loudly (CheckpointSchemaError) instead of being
# misparsed as garbage state.
#   1 (implicit): row-major full-pool snapshots, no aux, no meta
#   2: feature-major live-row snapshots + capacity/pool_layout meta
#   3: = 2 plus embedded CRC32 + explicit schema version
SCHEMA_VERSION = 3

LAST_GOOD_SUFFIX = ".prev"


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file is torn/corrupt (bad zip, CRC mismatch,
    missing members). load_resilient treats this as 'skip to the
    last-good snapshot', never 'resume wrong state'."""


class CheckpointSchemaError(RuntimeError):
    """The checkpoint was written by a NEWER schema than this build
    reads. Not corruption — falling back to an older snapshot would
    silently discard valid progress, so this is never swallowed."""


class SegmentTimeout(RuntimeError):
    """A segment exceeded its wall-clock watchdog. Deliberately NOT a
    transient error: a hung device dispatch does not unhang on retry —
    the caller (campaign supervisor) must kill and respawn the process."""


def _transient_errors() -> tuple:
    """Error types worth retrying: host/filesystem I/O, injected faults,
    and the runtime's transport errors (a dropped remote-TPU tunnel
    surfaces as XlaRuntimeError, an OSError subclass in some versions)."""
    errs = [OSError, faults.InjectedFault]
    try:
        from jax.errors import JaxRuntimeError
        errs.append(JaxRuntimeError)
    except ImportError:
        pass
    return tuple(errs)


TRANSIENT_ERRORS = _transient_errors()


def _retry(fn, what: str, attempts: int, base_s: float):
    """Run `fn` with exponential-backoff retry on transient errors
    (utils/retry.retry_call bound to this module's TRANSIENT_ERRORS).
    Non-transient exceptions (wrong answers, schema errors, timeouts)
    propagate immediately — retrying a deterministic failure only
    delays the loud abort."""
    return retry_call(fn, what=what, attempts=attempts, base_s=base_s,
                      transient=TRANSIENT_ERRORS)


def _with_watchdog(fn, timeout_s: float | None, what: str):
    """Run `fn` under a wall-clock watchdog: raises SegmentTimeout if it
    exceeds `timeout_s` (None/0 disables). The work runs on a daemon
    thread so a genuinely hung device call cannot also hang process
    exit — the supervisor's kill+respawn remains the recovery path; the
    timeout just converts a silent infinite wait into a loud error."""
    if not timeout_s or timeout_s <= 0:
        return fn()
    box: dict = {}
    # the caller's fault plan must ride into the worker thread: a
    # thread-SCOPED plan (faults.scoped — the service's per-request
    # injection) lives in thread-local state the daemon thread cannot
    # see, and injection points inside fn (host_fetch) would silently
    # stop firing whenever the watchdog is armed
    plan = faults.active()

    def target():
        try:
            with faults.scoped(plan):
                box["result"] = fn()
        except BaseException as e:      # noqa: BLE001 — re-raised below
            box["error"] = e

    th = threading.Thread(target=target, daemon=True,
                          name="tts-segment-watchdog")
    th.start()
    th.join(timeout_s)
    if th.is_alive():
        raise SegmentTimeout(
            f"{what} exceeded the {timeout_s:.1f}s wall-clock watchdog "
            "(hung device dispatch?); kill and resume from the last "
            "checkpoint")
    if "error" in box:
        raise box["error"]
    return box["result"]


def _to_np(x) -> np.ndarray:
    """Host copy of a (possibly multihost-sharded) array: plain asarray
    single-controller; allgather the global value under multi-controller
    (where np.asarray on non-addressable shards raises)."""
    if not getattr(x, "is_fully_addressable", True):
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(x, tiled=True))
    return np.asarray(x)


def _fetch_many(xs: tuple) -> tuple:
    """One batched device->host fetch of several small arrays. On a
    remote-TPU runtime every separate np.asarray is a full roundtrip;
    a single device_get puts all transfers in flight together, so the
    batch costs ~one latency instead of len(xs). Multihost shards fall
    back to the collective allgather path per leaf."""
    faults.fire("host_fetch")      # deterministic transient-error hook
    if any(not getattr(x, "is_fully_addressable", True) for x in xs):
        return tuple(_to_np(x) for x in xs)
    import jax
    return tuple(np.asarray(v) for v in jax.device_get(xs))


def _payload_crc(arrays: dict) -> int:
    """CRC32 over every stored array's name, dtype, shape and raw bytes
    (sorted by name, `meta_crc32` itself excluded) — the end-to-end
    integrity check a torn write or bit flip cannot survive. The zip
    layer's per-member CRCs already catch most damage; this one also
    covers damage the zip container cannot see (a member swapped in
    whole, an interrupted rewrite that left a stale-but-valid zip)."""
    crc = 0
    for name in sorted(arrays):
        if name == "meta_crc32":
            continue
        a = np.ascontiguousarray(np.asarray(arrays[name]))
        crc = zlib.crc32(name.encode(), crc)
        crc = zlib.crc32(str(a.dtype).encode(), crc)
        crc = zlib.crc32(np.asarray(a.shape, np.int64).tobytes(), crc)
        crc = zlib.crc32(a.tobytes(), crc)
    return crc & 0xFFFFFFFF


def last_good_path(path: str | pathlib.Path) -> pathlib.Path:
    """The rotating last-good snapshot that rides beside `path`."""
    path = pathlib.Path(path)
    return path.with_name(path.name + LAST_GOOD_SUFFIX)


def resume_path(path: str | pathlib.Path) -> pathlib.Path | None:
    """The file a resume should try first: `path` if present, else its
    last-good sibling (the current file vanished mid-rotation), else
    None (nothing to resume — a stale .tmp from an interrupted first
    save is NOT resumable: it was never fsync'd + renamed, so its
    contents carry no durability promise)."""
    path = pathlib.Path(path)
    if path.exists():
        return path
    prev = last_good_path(path)
    return prev if prev.exists() else None


# checkpoint size buckets (bytes): tests write ~kB snapshots, production
# pools compress to tens-of-MB..GB
_BYTES_BUCKETS = (1e4, 1e5, 1e6, 1e7, 1e8, 1e9)


def save(path: str | pathlib.Path, state: SearchState,
         meta: dict | None = None):
    """Snapshot a search state — flight-recorded wrapper around
    :func:`_save_impl` (one `checkpoint.save` span carrying the written
    byte count, plus save-latency/bytes histograms in the metrics
    registry). See `_save_impl` for the format and durability story."""
    with tracelog.span("checkpoint.save", path=str(path)) as sp:
        _save_impl(path, state, meta)
        nbytes = 0
        try:
            nbytes = os.path.getsize(path)
        except OSError:
            pass          # non-writer multihost rank, or racing rotate
        sp.set(bytes=nbytes)
    reg = obs_metrics.default()
    reg.counter("tts_checkpoint_saves_total",
                "checkpoint snapshots written").inc()
    reg.histogram("tts_checkpoint_save_seconds",
                  "checkpoint save latency (fetch+compress+fsync)"
                  ).observe(sp.dur)
    if nbytes:
        reg.histogram("tts_checkpoint_bytes", "checkpoint file size",
                      buckets=_BYTES_BUCKETS).observe(nbytes)


def _save_impl(path: str | pathlib.Path, state: SearchState,
               meta: dict | None = None):
    """Snapshot a search state (single-device or stacked distributed).

    Only the live pool rows (below the cursor) are fetched and written —
    rows above the cursor are garbage by the engine invariant, and a
    production pool is orders of magnitude larger than its live region
    (fetching + compressing the full arrays made checkpoints cost more
    than the segments they protected). The declared capacity is kept in
    the file so load() re-homes the rows into an identical pool.

    Torn-write-proof by construction: the bytes (with an embedded CRC32
    + schema version) go to a temp file that is flushed and fsync'd
    BEFORE any rename; the previous snapshot rotates to a `.prev`
    last-good sibling and the temp file renames into place. A crash at
    any point leaves either the old snapshot, the rotated last-good, or
    the new snapshot — never a half-written file under the resume path
    (load_resilient picks the newest loadable one).
    """
    sizes = np.atleast_1d(_to_np(state.size))
    n = int(sizes.max())
    arrays = {}
    for f, x in zip(SearchState._fields, state):
        if f in POOL_FIELDS:
            x = x[..., :n]               # feature-major: row axis is last
        arrays[f] = _to_np(x)
    arrays["meta_capacity"] = np.asarray(state.prmu.shape[-1])
    arrays["meta_pool_layout"] = np.asarray(1)   # 1 = feature-major
    if meta:
        reserved = {"capacity", "pool_layout", "schema_version", "crc32"} \
            & meta.keys()
        if reserved:
            raise ValueError(f"meta keys {sorted(reserved)} are reserved "
                             "by the checkpoint format")
        for k, v in meta.items():
            arrays[f"meta_{k}"] = np.asarray(v)
    # Multi-controller: every process reaches this point (the _to_np
    # fetches above are COLLECTIVE allgathers, so all ranks must run
    # them and all hold identical data), but only process 0 writes —
    # concurrent writes + renames of the same tmp file on a shared
    # filesystem can corrupt or race the checkpoint. resume reads the
    # same shared path on every process (load() is read-only).
    import jax
    if jax.process_index() != 0:
        return
    arrays["meta_schema_version"] = np.asarray(SCHEMA_VERSION)
    arrays["meta_crc32"] = np.asarray(_payload_crc(arrays), np.uint32)
    path = pathlib.Path(path)
    tmp = path.with_suffix(".tmp.npz")
    with open(tmp, "wb") as f:
        np.savez_compressed(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    # rotate current -> last-good, then temp -> current. Both renames
    # are atomic; a kill between them leaves no current file and
    # resume_path/load_resilient fall back to the last-good sibling.
    if path.exists():
        os.replace(path, last_good_path(path))
    os.replace(tmp, path)
    try:
        # fsync the directory so the renames themselves are durable
        # (without it a power loss can resurrect the pre-rename view)
        dfd = os.open(path.parent or pathlib.Path("."), os.O_RDONLY)
        try:
            os.fsync(dfd)
        finally:
            os.close(dfd)
    except OSError:
        pass   # not every filesystem supports directory fsync


def load(path: str | pathlib.Path,
         p_times: np.ndarray | None = None) -> tuple[SearchState, dict]:
    """Load a snapshot, verifying integrity first. Pre-aux checkpoints
    (before the pool carried per-node [front | remain] tables) are
    upgraded on load by reconstructing aux from the live rows — pass the
    instance's `p_times` for that; without it such files raise a clear
    error.

    Raises CheckpointCorrupt on a torn/damaged file (bad zip, CRC
    mismatch, missing members — every read error, so a caller never
    resumes wrong state) and CheckpointSchemaError on a file written by
    a newer schema than this build reads."""
    with tracelog.span("checkpoint.load", path=str(path)):
        obs_metrics.default().counter(
            "tts_checkpoint_loads_total",
            "checkpoint load attempts").inc()
        return _load_impl(path, p_times=p_times)


def _load_impl(path: str | pathlib.Path,
               p_times: np.ndarray | None = None
               ) -> tuple[SearchState, dict]:
    path = pathlib.Path(path)
    try:
        with np.load(path) as z:
            # full materialization doubles as the zip-member CRC pass
            # (zipfile verifies each member's own CRC as it inflates)
            raw = {k: z[k] for k in z.files}
    except (zipfile.BadZipFile, zlib.error, OSError, EOFError, ValueError,
            KeyError) as e:
        # zipfile errors can embed whole raw headers — keep the reason
        # human-sized, the chained exception preserves the full detail
        reason = str(e)
        if len(reason) > 200:
            reason = reason[:200] + "... [truncated]"
        raise CheckpointCorrupt(
            f"checkpoint {path} is unreadable (torn write or "
            f"corruption): {reason}") from e
    version = int(raw.get("meta_schema_version", 2 if "meta_capacity"
                          in raw else 1))
    if version > SCHEMA_VERSION:
        raise CheckpointSchemaError(
            f"checkpoint {path} uses schema version {version}; this "
            f"build reads <= {SCHEMA_VERSION} — upgrade the reader, do "
            "not fall back to an older snapshot")
    if "meta_crc32" in raw:
        want = int(raw["meta_crc32"])
        got = _payload_crc(raw)
        if got != want:
            raise CheckpointCorrupt(
                f"checkpoint {path} failed its embedded CRC32 "
                f"(stored {want:#010x}, recomputed {got:#010x})")
    missing = [f for f in SearchState._fields
               if f not in ("aux", "telemetry") and f not in raw]
    if missing:
        raise CheckpointCorrupt(
            f"checkpoint {path} is missing state fields {missing} "
            "(truncated or partial write)")
    arrays = {f: raw[f] for f in SearchState._fields if f in raw}
    meta = {k[5:]: raw[k] for k in raw if k.startswith("meta_")}
    meta.pop("schema_version", None)
    meta.pop("crc32", None)
    feature_major = bool(meta.pop("pool_layout", 0))
    if not feature_major:
        # legacy row-major snapshot: transpose pool matrices on load; a
        # legacy aux held [front | remain] — the pool now carries only
        # front (remain is reconstructed in-kernel), so keep the first
        # half of its rows
        for f in ("prmu", "aux"):
            if f in arrays:
                arrays[f] = np.swapaxes(arrays[f], -1, -2).copy()
        if "aux" in arrays and arrays["aux"].shape[-2] > 0:
            m = arrays["aux"].shape[-2] // 2
            arrays["aux"] = arrays["aux"][..., :m, :].copy()
    if "capacity" in meta:
        # live-row snapshot: re-home into the declared capacity
        capacity = int(meta.pop("capacity"))
        for f in POOL_FIELDS:
            if f not in arrays:
                continue
            x = arrays[f]
            pad = capacity - x.shape[-1]
            if pad > 0:
                widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
                arrays[f] = np.pad(x, widths)
    if "aux" not in arrays:
        if p_times is None:
            raise ValueError(
                f"{path} is a pre-aux checkpoint; pass p_times to load() "
                "so the per-node pool tables can be reconstructed")
        from ..ops import reference as ref
        prmu = arrays["prmu"]            # feature-major (/, jobs, rows)
        depth = arrays["depth"]
        size = np.atleast_1d(arrays["size"])
        stacked = prmu.ndim == 3
        m = p_times.shape[0]
        aux = np.zeros(prmu.shape[:-2] + (m, prmu.shape[-1]), np.int32)
        for d in range(prmu.shape[0] if stacked else 1):
            n = int(size[d if stacked else 0])
            if stacked:
                aux[d, :, :n] = ref.prefix_front_remain(
                    p_times, prmu[d, :, :n].T, depth[d, :n])[:, :m].T
            else:
                aux[:, :n] = ref.prefix_front_remain(
                    p_times, prmu[:, :n].T, depth[:n])[:, :m].T
        arrays["aux"] = aux
    if "telemetry" not in arrays:
        # pre-telemetry snapshot: reconstruct a zeroed block at the
        # CURRENT flag's width (counters restart from the resume; the
        # saved pool/counter state is untouched either way)
        lead = (arrays["prmu"].shape[0],) if arrays["prmu"].ndim == 3 \
            else ()
        arrays["telemetry"] = np.zeros(lead + (tele.enabled_width(),),
                                       np.int64)
    state = SearchState(*(jnp.asarray(arrays[f])
                          for f in SearchState._fields))
    return state, meta


def load_resilient(path: str | pathlib.Path,
                   p_times: np.ndarray | None = None
                   ) -> tuple[SearchState, dict, pathlib.Path]:
    """Load `path`, falling back to its rotating last-good sibling when
    the current file is torn/corrupt (or missing after an interrupted
    rotation). Returns (state, meta, loaded_path) — callers that priced
    anything off the file (aux dtype, capacity) must use `loaded_path`,
    not `path`.

    A corrupt current snapshot costs at most the work since the
    PREVIOUS checkpoint; it never poisons the run. Only when every
    candidate is unreadable does this raise, listing what was tried.
    CheckpointSchemaError is deliberately not caught: a valid
    newer-schema file must not be silently shadowed by an older one."""
    path = pathlib.Path(path)
    candidates = [path, last_good_path(path)]
    errors = []
    for cand in candidates:
        if not cand.exists():
            errors.append(f"{cand}: missing")
            continue
        try:
            state, meta = load(cand, p_times=p_times)
        except CheckpointCorrupt as e:
            warnings.warn(
                f"skipping corrupt checkpoint {cand}: {e}",
                RuntimeWarning, stacklevel=2)
            errors.append(f"{cand}: {e}")
            tracelog.event("checkpoint.corrupt", path=str(cand),
                           error=str(e)[:200])
            obs_metrics.default().counter(
                "tts_checkpoint_corrupt_total",
                "torn/corrupt snapshots skipped on load").inc()
            if cand == path:
                # Quarantine the torn CURRENT file: leaving it in place
                # lets the next save() rotate it over the good
                # last-good, and a crash between save's two renames
                # would then leave nothing loadable at all. Renamed
                # aside (not unlinked) so the damage stays available
                # for forensics. Process 0 only — on a multi-controller
                # shared filesystem every process runs this resume path
                # and concurrent renames of one file race.
                try:
                    import jax
                    if jax.process_index() == 0:
                        os.replace(cand, str(cand) + ".corrupt")
                        tracelog.event("checkpoint.quarantine",
                                       path=str(cand) + ".corrupt")
                        obs_metrics.default().counter(
                            "tts_checkpoint_quarantines_total",
                            "torn current snapshots renamed aside").inc()
                except OSError:
                    pass
            continue
        if cand != path:
            warnings.warn(
                f"resuming from last-good snapshot {cand} (current "
                "checkpoint torn/missing); work since the previous "
                "checkpoint interval will be redone",
                RuntimeWarning, stacklevel=2)
            tracelog.event("checkpoint.rollback", path=str(cand),
                           wanted=str(path))
            obs_metrics.default().counter(
                "tts_checkpoint_rollbacks_total",
                "resumes served by the rotating last-good sibling").inc()
        return state, meta, cand
    raise CheckpointCorrupt(
        "no loadable checkpoint: " + "; ".join(errors))


def reshard_state(state: SearchState, new_workers: int,
                  squeeze: bool = False) -> SearchState:
    """Elastic resume: re-home an N-worker stacked snapshot (or a
    single-device one) onto `new_workers` pools, so a preempted job
    restarts on whatever slice is available (M < N and M > N both
    work — the failure mode real fleets actually have is "came back
    with a different topology").

    Host-side and lossless: every worker's live rows (rows [0, size) by
    the pool invariant) are concatenated and round-robin striped across
    the M new pools — the same water-filling split the balance
    exchange converges to (parallel/balance.waterfill_counts: per-pool
    counts differ by <= 1) and the same striping idiom as warm-up
    seeding (distributed._shard_frontier). Capacity doubles as needed
    so the widest stripe fits; callers with tighter usable-row limits
    (scratch margins, balance headroom) grow() further on top.

    Counter semantics across the reshard:
    - tree/sol/evals/sent/recv/steals: global totals preserved — summed
      onto worker 0 (only the totals are ever reported; per-worker
      attribution does not survive a topology change by definition);
    - iters: replicated at the old max, so a cumulative per-worker
      iteration ceiling keeps meaning "this much MORE work per worker";
    - best: min-replicated (the incumbent is global);
    - overflow: cleared — the resumed run's first step re-detects a
      genuinely over-full pool via the same lossless no-commit path.

    `squeeze=True` with new_workers=1 returns an UNSTACKED single-device
    state (the shape device.run expects) instead of a (1, ...) stack.
    """
    if new_workers < 1:
        raise ValueError(f"new_workers must be >= 1, got {new_workers}")
    if squeeze and new_workers != 1:
        raise ValueError("squeeze=True requires new_workers == 1")
    from ..parallel import balance as bal

    arrs = SearchState(*(np.asarray(x) for x in state))
    if arrs.prmu.ndim == 2:            # single-device snapshot: lift
        arrs = SearchState(*(a[None, ...] for a in arrs))
    if arrs.prmu.ndim != 3:
        raise ValueError(
            f"reshard_state needs a (D, jobs, capacity) stacked or "
            f"(jobs, capacity) single-device pool, got {arrs.prmu.shape}")
    D, jobs, capacity = arrs.prmu.shape
    A = arrs.aux.shape[1]
    M = new_workers
    if M != D:
        tracelog.event("elastic_reshard", old_workers=int(D),
                       new_workers=int(M))
        obs_metrics.default().counter(
            "tts_elastic_reshards_total",
            "checkpoints re-homed onto a different worker count").inc()
    sizes = np.atleast_1d(arrs.size).astype(np.int64)

    # concatenate live rows in worker order (bottom-to-top per pool)
    live_prmu = np.concatenate(
        [arrs.prmu[d, :, :sizes[d]] for d in range(D)], axis=1)
    live_depth = np.concatenate(
        [arrs.depth[d, :sizes[d]] for d in range(D)])
    live_aux = np.concatenate(
        [arrs.aux[d, :, :sizes[d]] for d in range(D)], axis=1)

    total = int(sizes.sum())
    counts = bal.waterfill_counts(total, M)
    while counts.max() > capacity:
        capacity *= 2

    prmu = np.zeros((M, jobs, capacity), arrs.prmu.dtype)
    depth = np.zeros((M, capacity), arrs.depth.dtype)
    aux = np.zeros((M, A, capacity), arrs.aux.dtype)
    for m in range(M):
        stripe = slice(m, None, M)     # round-robin, water-filled
        n = int(counts[m])
        prmu[m, :, :n] = live_prmu[:, stripe]
        depth[m, :n] = live_depth[stripe]
        aux[m, :, :n] = live_aux[:, stripe]

    def on_zero(total_val, dtype):
        v = np.zeros(M, dtype)
        v[0] = total_val
        return v

    # telemetry follows the tree/sol rule: global totals preserved,
    # merged onto worker 0 (counts summed, pool high-water maxed, the
    # incumbent ring replayed in iteration order — telemetry.merge)
    tw = arrs.telemetry.shape[-1]
    telem = np.zeros((M, tw), np.int64)
    if tw:
        telem[0] = tele.merge(arrs.telemetry)

    out = SearchState(
        telemetry=telem,
        prmu=prmu, depth=depth, aux=aux,
        size=counts.astype(np.int32),
        best=np.full(M, int(np.min(arrs.best)), np.int32),
        tree=on_zero(int(np.sum(arrs.tree)), np.int64),
        sol=on_zero(int(np.sum(arrs.sol)), np.int64),
        iters=np.full(M, int(np.max(arrs.iters)), np.int64),
        evals=on_zero(int(np.sum(arrs.evals)), np.int64),
        sent=on_zero(int(np.sum(arrs.sent)), np.int64),
        recv=on_zero(int(np.sum(arrs.recv)), np.int64),
        steals=on_zero(int(np.sum(arrs.steals)), np.int64),
        overflow=np.zeros(M, bool),
    )
    if squeeze:
        out = SearchState(*(a[0] for a in out))
    return SearchState(*(jnp.asarray(a) for a in out))


def collapse_to_single_device(state: SearchState, chunk: int,
                              jobs: int) -> SearchState:
    """Collapse a stacked (D, jobs, cap) snapshot onto ONE device: the
    elastic reshard to a single squeezed pool, pre-sized for the mesh
    run's TOTAL footprint (D x per-worker capacity — the one pool now
    carries every worker's rows and their future growth) and then
    doubled until the live rows clear the usable-row limit
    (device.row_limit's chunk*jobs scratch margin), so a nearly-full
    stacked snapshot cannot overflow on its first resumed segment.
    Shared by the CLI's and the campaign worker's resume paths — the
    sizing invariant lives in exactly one place."""
    from .device import row_limit

    shape = np.asarray(state.prmu).shape
    if len(shape) != 3:
        return state                     # already single-device
    stacked_total = int(shape[0] * shape[-1])
    out = reshard_state(state, 1, squeeze=True)
    grown = max(int(out.prmu.shape[-1]), stacked_total)
    need = int(np.asarray(out.size).max())
    while row_limit(grown, chunk, jobs) < max(need, 1):
        grown *= 2
    if grown != out.prmu.shape[-1]:
        out = grow(out, grown)
    return out


class PoolOverflow(RuntimeError):
    """Pool capacity exceeded; `.state` is the (resumable) search state."""

    def __init__(self, message: str, state: SearchState):
        super().__init__(message)
        self.state = state


def grow(state: SearchState, new_capacity: int) -> SearchState:
    """Re-home a search state — single-device (jobs, cap) or stacked
    distributed (D, jobs, cap) — into a larger pool, clearing the
    overflow flag(s): the recovery path after an overflow abort (load or
    fetch, grow, resume). Rows above each cursor are garbage by the pool
    invariant, so growth is zero-padding the row axis."""
    capacity = np.asarray(state.prmu).shape[-1]
    if new_capacity < capacity:
        raise ValueError(f"new_capacity {new_capacity} < current {capacity}")
    tracelog.event("pool.grow", capacity=int(capacity),
                   new_capacity=int(new_capacity))
    obs_metrics.default().counter(
        "tts_pool_grows_total", "lossless overflow pool growths").inc()
    pad = new_capacity - capacity

    def pad_rows(x):
        x = np.asarray(x)
        widths = [(0, 0)] * (x.ndim - 1) + [(0, pad)]
        return jnp.asarray(np.pad(x, widths))

    ovf = np.zeros_like(np.asarray(state.overflow))
    return state._replace(prmu=pad_rows(state.prmu),
                          depth=pad_rows(state.depth),
                          aux=pad_rows(state.aux),
                          overflow=jnp.asarray(ovf))


@dataclasses.dataclass
class SegmentReport:
    segment: int
    iters: int
    tree: int
    sol: int
    best: int
    pool_size: int
    elapsed: float
    # distributed runs: per-worker live sizes / cumulative steal counts /
    # incumbents / explored+eval counters (the heartbeat surface the
    # reference's "Still Idle" print, dist:663-668, only hints at, and
    # the inputs the live phase attribution needs — see
    # utils/phase_timing.publish_attribution); None on single-device runs
    per_worker: dict | None = None
    evals: int = 0               # cumulative bound evaluations (total)
    # cumulative on-device search telemetry (telemetry.summarize dict:
    # depth-bucketed popped/branched/pruned, bound histograms, pool
    # high-water, steal flow, incumbent ring, pruning rate); None when
    # the state carries no telemetry block (TTS_SEARCH_TELEMETRY off)
    telemetry: dict | None = None


def run_segmented(run_fn, state: SearchState, segment_iters: int = 2048,
                  checkpoint_path: str | None = None,
                  checkpoint_every: int = 1,
                  heartbeat=print, max_segments: int | None = None,
                  max_total_iters: int | None = None,
                  stall_limit: int = 3,
                  raise_on_overflow: bool = True,
                  checkpoint_meta: dict | None = None,
                  post_segment=None,
                  should_stop=None,
                  retry_attempts: int | None = None,
                  retry_base_s: float | None = None,
                  segment_timeout_s: float | None = None):
    """Drive `run_fn(state, target_total_iters) -> state` to exhaustion in
    bounded segments.

    `run_fn` receives a CUMULATIVE iteration ceiling (matching
    `device.run(..., max_iters=...)`'s semantics: the loop condition is
    `state.iters < max_iters`), not an increment. Targets are offset by the
    incoming state's iteration count, so resuming from a loaded checkpoint
    works.

    - checkpoints every `checkpoint_every` segments when a path is given;
    - calls `post_segment(state) -> state` after each segment, BEFORE the
      heartbeat/checkpoint, so cross-tier effects (the `-C` host
      session's incumbent merge) land in both (engine/hybrid.HostSession);
    - calls `heartbeat(SegmentReport)` after each segment;
    - stops early (after checkpointing) when `should_stop(SegmentReport)`
      returns True — the wall-budget hook for campaign drivers;
    - `checkpoint_meta` may be a CALLABLE returning the meta dict, re-
      evaluated at every save (live values like cumulative wall time);
    - raises RuntimeError after `stall_limit` consecutive segments with no
      progress (tree/sol/iters all unchanged) — a compiled-loop stall is a
      bug, not a state, so fail loudly rather than spin (the reference's
      equivalent symptom is its 10-second "Still Idle" print, dist:663-668);
    - on pool overflow the search state is incomplete: raises RuntimeError
      (after checkpointing, so the state is recoverable) unless
      `raise_on_overflow=False`, in which case the caller must check
      `state.overflow` before trusting the counters.

    Resilience (the layer the reference lacks end to end): segment
    execution, checkpoint writes and the per-segment scalar fetch are
    retried `retry_attempts` times with exponential backoff
    (`retry_base_s * 2^k`) on TRANSIENT errors only (I/O, runtime
    transport, injected faults — see TRANSIENT_ERRORS); a
    `segment_timeout_s` wall-clock watchdog converts a hung device
    dispatch into a loud SegmentTimeout (never retried — the
    supervisor's kill+respawn is the recovery for hangs). Defaults read
    TTS_RETRY_ATTEMPTS (3), TTS_RETRY_BASE_S (0.5) and
    TTS_SEG_TIMEOUT_S (0 = off). Deterministic fault injection for all
    of these lives in utils/faults.py (TTS_FAULTS).
    """
    from ..utils import config as _cfg
    if retry_attempts is None:
        retry_attempts = int(os.environ.get(
            "TTS_RETRY_ATTEMPTS", _cfg.RETRY_ATTEMPTS_DEFAULT))
    if retry_base_s is None:
        retry_base_s = float(os.environ.get(
            "TTS_RETRY_BASE_S", _cfg.RETRY_BASE_S_DEFAULT))
    if segment_timeout_s is None:
        segment_timeout_s = float(os.environ.get(
            "TTS_SEG_TIMEOUT_S", _cfg.SEGMENT_TIMEOUT_S_DEFAULT))
    import jax
    if jax.process_count() > 1:
        # Multi-controller: run_fn, save and the scalar fetch all
        # contain COLLECTIVES (process_allgather, the SPMD loop). A
        # per-process retry re-enters its collective alone while the
        # other processes have moved on — mismatched collective order
        # is a distributed hang, strictly worse than the transient it
        # retries. Fail loudly instead; multihost recovery is
        # restart-the-job-level (every process resumes from the shared
        # checkpoint), not retry-in-place.
        retry_attempts = 1
    t0 = time.perf_counter()
    seg = 0
    stalls = 0
    start_iters = int(_to_np(state.iters).max())
    # resumed states carry cumulative totals; throughput metrics must
    # count only THIS run's progress
    prev_tree = int(np.atleast_1d(_to_np(state.tree)).sum())
    # search-telemetry deltas start from the INCOMING block (a resumed
    # checkpoint's counts must not re-report as segment-1 activity).
    # Width via .shape, never np.asarray: materializing a state leaf
    # here raises on multihost runs (non-addressable shards — the
    # hazard _to_np exists for)
    tele_w = int(state.telemetry.shape[-1])
    prev_tele = (tele.merge(np.atleast_2d(_to_np(state.telemetry)))
                 if tele_w else None)
    prev_evals = np.atleast_1d(_to_np(state.evals)).copy()
    last = (start_iters, -1, -1)

    def meta_now(seg):
        base = checkpoint_meta() if callable(checkpoint_meta) \
            else dict(checkpoint_meta or {})
        return {**base, "segment": seg}

    def do_save(s, seg_no):
        _retry(lambda: save(checkpoint_path, s, meta=meta_now(seg_no)),
               "checkpoint save", retry_attempts, retry_base_s)
        # audit hook (TTS_AUDIT=full / TTS_AUDIT_CKPT=1): re-read the
        # snapshot and require bit-identical counters — BEFORE the
        # fault injection below, which may corrupt the file on purpose
        # to exercise the load-side rollback
        from ..obs import audit as obs_audit
        if obs_audit.roundtrip_enabled():
            obs_audit.check_checkpoint_roundtrip(checkpoint_path, s)
        # torn-write / corruption injection targets the just-written
        # file — the load-side rollback to last-good is what it tests
        faults.fire("post_checkpoint", segment=seg_no,
                    path=checkpoint_path)

    def final_save(s, seg):
        # every exit path must leave a CURRENT checkpoint — with
        # checkpoint_every > 1, returning without this leaves the file
        # up to checkpoint_every-1 segments stale and a planned
        # stop-then-resume silently redoes that work
        if checkpoint_path and seg % checkpoint_every != 0:
            do_save(s, seg)

    while True:
        target = start_iters + (seg + 1) * segment_iters
        if max_total_iters is not None:
            target = min(target, start_iters + max_total_iters)
        faults.fire("segment_start", segment=seg + 1)
        # run_fn is functional (the incoming state is untouched on
        # failure), so a retried segment redoes identical work; the
        # watchdog wraps each attempt separately
        prev_state = state
        with tracelog.span("segment", segment=seg + 1) as seg_span:
            state = _retry(
                lambda: _with_watchdog(
                    lambda: run_fn(prev_state, target),
                    segment_timeout_s, f"segment {seg + 1}"),
                "segment execution", retry_attempts, retry_base_s)
            if post_segment is not None:
                state = post_segment(state)
            seg += 1
            # ONE batched host fetch for every per-segment scalar:
            # through a remote-TPU runtime each separate fetch is a full
            # roundtrip (~0.15 s on the tunnel; six of them cost ~0.9 s
            # per segment — measured as the gap between segment wall
            # time and the compiled loop's in-trace step cost,
            # BENCHMARKS.md round 3)
            # the watchdog must cover this fetch too: dispatch is ASYNC,
            # so a hung device computation lets run_fn return its
            # futures instantly and the block happens HERE, waiting on
            # the results
            fetched = _retry(
                lambda: _with_watchdog(
                    lambda: _fetch_many(
                        (state.iters, state.tree, state.sol,
                         state.size, state.best, state.steals,
                         state.overflow, state.evals)
                        + ((state.telemetry,) if tele_w else ())),
                    segment_timeout_s, f"segment {seg} result fetch"),
                "per-segment host fetch", retry_attempts, retry_base_s)
            (f_iters, f_tree, f_sol, sizes, f_best, f_steals, f_ovf,
             f_evals) = fetched[:8]
            iters = int(f_iters.max())
            tree = int(f_tree.sum())
            sol = int(f_sol.sum())
            size = int(sizes.sum())
            seg_span.set(iters=iters, tree=tree, sol=sol, pool=size,
                         best=int(f_best.min()))
        per_worker = None
        if sizes.ndim:                          # stacked distributed state
            per_worker = {"size": sizes.tolist(),
                          "steals": f_steals.tolist(),
                          "best": f_best.tolist(),
                          "iters": f_iters.tolist(),
                          "evals": f_evals.tolist()}
        tele_summary = None
        if tele_w:
            # cumulative summary for the report + a per-segment DELTA
            # event for the trace — the time series Perfetto counter
            # tracks and tools/search_report.py render
            merged = tele.merge(np.atleast_2d(fetched[8]))
            tele_summary = tele.summarize(merged)
            deltas = tele.delta_counts(merged, prev_tele)
            evals_d = np.atleast_1d(f_evals) - prev_evals
            ev = {}
            if sizes.ndim:
                ev = {"workers": int(sizes.shape[0]),
                      "evals_pw": evals_d.tolist()}
            tracelog.event(
                "search.telemetry", segment=seg, **deltas,
                pool=size,
                pool_hw=tele_summary["pool_highwater"],
                best=int(f_best.min()),
                improvements=tele_summary["improvements"], **ev)
            prev_tele = merged
            prev_evals = np.atleast_1d(f_evals).copy()
        report = SegmentReport(
            segment=seg, iters=iters, tree=tree, sol=sol,
            best=int(f_best.min()), pool_size=size,
            elapsed=time.perf_counter() - t0, per_worker=per_worker,
            evals=int(f_evals.sum()), telemetry=tele_summary)
        reg = obs_metrics.default()
        reg.histogram("tts_segment_seconds",
                      "segment wall latency (execute+fetch)"
                      ).observe(seg_span.dur)
        # per-segment DELTA, so the counter is live throughput, not the
        # cumulative totals a resumed checkpoint would double-report
        reg.counter("tts_nodes_explored_total",
                    "explored-node throughput (segment deltas)"
                    ).inc(max(tree - prev_tree, 0))
        prev_tree = tree
        if heartbeat is not None:
            heartbeat(report)
        if checkpoint_path and seg % checkpoint_every == 0:
            do_save(state, seg)
        # preemption injection point: fires at the END of segment k,
        # after any checkpoint that segment wrote. Deliberately NOT
        # checkpoint-aligned — real preemptions are not either; with
        # checkpoint_every > 1 the on-disk snapshot may be up to
        # checkpoint_every-1 segments older and recovery redoes that
        # interval (the kill-then-resume-elsewhere shape elastic
        # resume exists for)
        faults.fire("post_segment", segment=seg)
        if bool(f_ovf.any()):
            final_save(state, seg)
            if raise_on_overflow:
                hint = (f"resume from {checkpoint_path} with a larger "
                        "capacity" if checkpoint_path else
                        "rerun with a larger capacity, or catch "
                        "PoolOverflow and grow() its .state")
                raise PoolOverflow(
                    f"pool overflow at segment {seg} (pool={size}): search "
                    f"incomplete; {hint}", state)
            return state
        if size == 0:
            final_save(state, seg)
            return state
        if should_stop is not None and should_stop(report):
            final_save(state, seg)
            return state
        if (iters, tree, sol) == last:
            stalls += 1
            if stalls >= stall_limit:
                raise RuntimeError(
                    f"search stalled: no progress across {stalls} segments "
                    f"(iters={iters}, pool={size})")
        else:
            stalls = 0
        last = (iters, tree, sol)
        if max_segments is not None and seg >= max_segments:
            final_save(state, seg)
            return state
        if (max_total_iters is not None
                and iters >= start_iters + max_total_iters):
            final_save(state, seg)
            return state
