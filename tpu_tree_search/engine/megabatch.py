"""Request megabatching: the compiled SPMD search loop vmapped over a
leading instance axis.

The reference engine's throughput move is bulk offload — amortize one
kernel launch over a chunk of nodes (`evaluate_gpu`, PAPER.md L3). This
module is the serving analog applied ACROSS requests instead of within
one: B same-shape-class instances are stacked into ONE compiled loop, so
one dispatch bounds children for hundreds of tenants and a traffic mix
dominated by small instances stops stranding the mesh (one request per
submesh regardless of size — ROADMAP item 3).

Layout: every `SearchState` leaf gains a batch dim right after the
worker axis — pools `(D, B, J, capacity)`, depth `(D, B, capacity)`,
counters/best/size `(D, B)`, telemetry `(D, B, WIDTH)` — sharded over
the worker axis exactly like the solo loop. Inside the shard_map the
per-worker leaves are `(B, ...)` and the loop body is
`jax.vmap(member_body)`: the SAME macro-iteration the solo loop runs
(`engine/distributed.member_body` — balance_period local steps, the
pmin incumbent exchange, one balance round), so a batched member's
explored tree is BIT-IDENTICAL to its solo run (test-pinned).

Per-instance semantics the batch preserves exactly:

- **termination masks**: the outer `lax.while_loop` carries every
  member; a member whose global pool drains (or that hits its own
  iteration target, or overflows) fails its per-member `active` mask
  and its lanes FREEZE — `jnp.where(mask, new, old)` keeps its state
  bit-stable while the rest of the batch keeps exploring. The loop
  exits when no member is active.
- **per-instance `bound_cap`**: a `(B,)` traced input folded into each
  member's incumbent at loop entry (`min(best, bound_cap[b])` — the
  IncumbentBoard's cross-request exchange, per member, no retrace).
- **per-instance budgets**: `max_iters` is a `(B,)` traced cumulative
  ceiling, so the segmented driver freezes a stopped member (its target
  stops advancing) without recompiling or stalling its batchmates.
- **exact accounting**: counters, telemetry blocks and the
  node-conservation audit are all per member (sliced off the batch
  axis); checkpoints are written per request by slicing the batch state
  down to the solo `(D, ...)` layout, so preempt/resume, crash replay
  and elastic reshard run through the UNMODIFIED checkpoint machinery
  — a batched member's snapshot is indistinguishable from a solo one.

What batching deliberately does NOT change: pool capacity is shared
(one compiled shape), so an overflowing member grows the whole batch;
execution is lockstep, so a batch's wall clock is its slowest member
(the batch-former keys on problem + shape class + lb to keep members
comparable); the overlap/donation pipeline and the `-C` host tier stay
solo-mode features.
"""

from __future__ import annotations

import dataclasses
import time

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..obs import audit as obs_audit
from ..obs import tracelog
from ..parallel.mesh import WORKER_AXIS, shard_map
from . import distributed as dist
from . import telemetry as tele
from .device import I32_MAX, SearchState
from .distributed import DistResult

AX = WORKER_AXIS


def _register_barrier_batching() -> None:
    """jax 0.4.x ships no vmap rule for `optimization_barrier` (the
    fusion fence the PFSP step leans on — engine/device._regather), so
    vmapping the step would raise NotImplementedError. The rule is
    trivially shape-transparent — bind the barrier on the batched
    operands, pass the batch dims through — and this is exactly the
    rule later jax versions ship upstream; registration is gated so a
    pin that already has one keeps it."""
    try:
        from jax._src.lax import lax as _lax_src
        from jax.interpreters import batching
        prim = getattr(_lax_src, "optimization_barrier_p", None)
        if prim is None or prim in batching.primitive_batchers:
            return

        def _ob_batcher(args, dims, **params):
            return prim.bind(*args, **params), dims

        batching.primitive_batchers[prim] = _ob_batcher
    except Exception:  # noqa: BLE001 — a moved private module on a
        # future pin must not break import; the loop build would then
        # surface the missing rule loudly
        pass


_register_barrier_batching()


class MemberIncompatible(ValueError):
    """One member's RESUME STATE cannot join this batch (cross-problem
    checkpoint, legacy aux dtype, different telemetry width) — the
    batch key groups by request attributes and cannot see checkpoint
    contents. Typed, with the offending member index, so the service
    can demote THAT member to a solo dispatch and requeue its innocent
    batchmates instead of dead-lettering all of them on a batch-wide
    exception."""

    def __init__(self, member: int, reason: str):
        super().__init__(reason)
        self.member = member


# --------------------------------------------------------------- stacking


def stack_states(states: list, capacity: int | None = None
                 ) -> SearchState:
    """Stack B solo host states (leaves `(D, ...)`) into one batched
    state (leaves `(D, B, ...)`) at `capacity` pool rows (default: the
    widest member). Members at a smaller capacity are zero-padded on
    the row axis — exactly `checkpoint.grow`'s rule (rows above the
    cursor are garbage by the pool invariant) without materializing a
    grown copy per member: the batched leaves are allocated ONCE and
    each member writes its slice, so a B-member stack moves ~one batch
    of bytes instead of three (member grow + stack + commit)."""
    _POOL_LEAVES = ("prmu", "depth", "aux")
    D = np.asarray(states[0].prmu).shape[0]
    B = len(states)
    if capacity is None:
        capacity = max(np.asarray(s.prmu).shape[-1] for s in states)
    out = {}
    for name in SearchState._fields:
        leaves = [np.asarray(getattr(s, name)) for s in states]
        shape = list(leaves[0].shape)
        if name in _POOL_LEAVES:
            shape[-1] = int(capacity)
        arr = np.zeros([D, B] + shape[1:], leaves[0].dtype)
        for b, leaf in enumerate(leaves):
            if name in _POOL_LEAVES:
                arr[:, b, ..., :leaf.shape[-1]] = leaf
            else:
                arr[:, b] = leaf
        out[name] = arr
    return SearchState(**out)


def slice_member(state: SearchState, b: int) -> SearchState:
    """One member's solo-shaped view `(D, ...)` of a batched state —
    the per-request checkpoint/result extraction."""
    return SearchState(*(x[:, b] for x in state))


# ------------------------------------------------------------ the loop


def build_batched_loop(mesh, tables, make_local_step,
                       balance_period: int, transfer_cap: int,
                       min_transfer: int, limit: int, batch: int):
    """Compile the batched SPMD loop: signature
    `run(tables, max_iters, bound_cap, *state)` like the solo loop
    (engine/distributed.build_dist_loop) except `max_iters` and
    `bound_cap` are `(B,)` per-member vectors and every problem-table
    leaf and state leaf carries the batch dim. The member body is the
    SOLO body (distributed.member_body) under `jax.vmap` — shared code,
    not a reimplementation — with per-member activity masks supplying
    the batched termination semantics."""

    def worker_loop(tables, max_iters, bound_cap, *state_leaves):
        s = dist._local_state(*state_leaves)       # leaves (B, ...)
        # the per-member incumbent fold at loop entry, exactly where
        # the solo loop folds its scalar cap
        s = s._replace(best=jnp.minimum(s.best, bound_cap))

        def member(tables_b, *leaves):
            m = SearchState(*leaves)
            body = dist.member_body(tables_b, make_local_step,
                                    balance_period, transfer_cap,
                                    min_transfer, limit)
            return tuple(body(m))

        vbody = jax.vmap(member)

        def active(st: SearchState):
            # per-member (B,) activity: global work remains, no worker
            # of the member overflowed, own iteration target not hit —
            # the solo cond, vectorized over the batch
            has_work = jax.lax.psum(st.size, AX) > 0
            ok = jax.lax.psum(st.overflow.astype(jnp.int32), AX) == 0
            return has_work & ok & (st.iters < max_iters)

        def cond(st: SearchState):
            return active(st).any()

        def body(st: SearchState):
            mask = active(st)
            new = SearchState(*vbody(tables, *st))
            sel = lambda n, o: jnp.where(  # noqa: E731
                mask.reshape((batch,) + (1,) * (n.ndim - 1)), n, o)
            return SearchState(*(sel(n, o) for n, o in zip(new, st)))

        return dist._expand(jax.lax.while_loop(cond, body, s))

    spec_state = tuple(P(AX) for _ in SearchState._fields)
    spec_tables = jax.tree.map(lambda _: P(), tables)
    return jax.jit(shard_map(
        worker_loop, mesh,
        in_specs=(spec_tables, P(), P()) + spec_state,
        out_specs=spec_state))


class BatchedDriver:
    """Compiles/caches the batched loop per pool capacity (the solo
    `_DistDriver` shape, minus the donation/overlap tier). The executor
    key is the SOLO key plus a `("batch", B)` suffix, so the AOT disk
    tier persists/replays one batched compile fleet-wide and a batched
    executable can never alias a solo one."""

    def __init__(self, mesh, tables, make_local_step, balance_period: int,
                 transfer_cap: int, min_transfer: int, limit_fn,
                 batch: int, loop_cache=None, loop_key: tuple = ()):
        self.mesh = mesh
        self.tables = tables
        self.make_local_step = make_local_step
        self.balance_period = balance_period
        self.transfer_cap = transfer_cap
        self.min_transfer = min_transfer
        self.limit_fn = limit_fn
        self.batch = batch
        self.n_recv = mesh.devices.size * transfer_cap
        self._loops: dict[int, object] = {}
        self.spec_state = tuple(P(AX) for _ in SearchState._fields)
        self.loop_cache = loop_cache
        self.loop_key = tuple(loop_key) + ("batch", int(batch)) + tuple(
            int(d.id) for d in mesh.devices.flat)

    def limit(self, capacity: int) -> int:
        # the SAME tightened usable-row bound as the solo driver at
        # identical knobs — required for bit-parity (the balance
        # round's overflow predicate reads it)
        return min(self.limit_fn(capacity), capacity - self.n_recv)

    def _loop(self, capacity: int):
        if capacity not in self._loops:
            build = lambda: build_batched_loop(  # noqa: E731
                self.mesh, self.tables, self.make_local_step,
                self.balance_period, self.transfer_cap,
                self.min_transfer, limit=self.limit(capacity),
                batch=self.batch)
            if self.loop_cache is not None:
                key = self.loop_key + (capacity, self.balance_period,
                                       self.transfer_cap,
                                       self.min_transfer,
                                       self.limit(capacity))
                self._loops[capacity] = self.loop_cache.get_or_build(
                    key, build)
            else:
                self._loops[capacity] = build()
        return self._loops[capacity]

    def commit(self, state: SearchState) -> SearchState:
        return SearchState(*(dist._to_mesh(self.mesh, s, x)
                             for s, x in zip(self.spec_state, state)))

    def run_once(self, state: SearchState, max_iters_b,
                 bound_caps_b) -> SearchState:
        """ONE dispatch of the batched loop (no overflow recovery here:
        the segmented driver grows the whole batch and re-dispatches —
        the host-side half of the solo `run` loop)."""
        capacity = state.prmu.shape[-1]
        targets = jnp.asarray(np.asarray(max_iters_b),
                              state.iters.dtype)
        caps = jnp.asarray(
            np.asarray([I32_MAX if c is None else int(c)
                        for c in bound_caps_b]), jnp.int32)
        return SearchState(*self._loop(capacity)(
            self.tables, targets, caps, *state))


# ----------------------------------------------------------- host driver


@dataclasses.dataclass
class MemberSpec:
    """One request's slice of a batch dispatch. The engine knobs that
    must AGREE across the batch (problem, table shape, lb, chunk,
    capacity, balance knobs, segment geometry) live on `serve_batch`;
    everything per-request lives here."""

    table: np.ndarray
    init_ub: int | None = None
    checkpoint_path: str | None = None
    # dict or callable merged into every checkpoint meta this member
    # writes (the service rides its cumulative spent_s clock on it)
    checkpoint_meta_extra: object = None
    incumbent_key: str | None = None


class _Member:
    """Per-member host-side bookkeeping inside one batch dispatch."""

    def __init__(self, idx: int, spec: MemberSpec):
        self.idx = idx
        self.spec = spec
        self.warmup_tree = 0
        self.warmup_sol = 0
        self.start_iters = 0
        self.frozen_target: int | None = None   # set on stop: the
        #                                         member's lanes idle
        self.active = True
        self.stopped = False     # stop (vs drained) at deactivation
        self.folder = None       # checkpoint._ReportFolder
        self.client = None       # incumbent BoardClient
        self.result: DistResult | None = None
        self.last_saved_seg = -1


def _stack_tables(prob, tables_list):
    return jax.tree.map(lambda *xs: jnp.stack([jnp.asarray(x)
                                               for x in xs]),
                        *tables_list)


def serve_batch(specs: list, problem="pfsp", lb_kind: int = 1,
                mesh=None, chunk: int | None = None,
                capacity: int | None = None,
                balance_period: int | None = None,
                transfer_cap: int | None = None,
                min_transfer: int | None = None,
                min_seed: int = 32,
                segment_iters: int = 512,
                checkpoint_every: int = 1,
                heartbeat=None, member_stop=None, on_member_done=None,
                on_member_stopped=None,
                stop_event=None, loop_cache=None,
                incumbent_board=None, tuner=None,
                stall_limit: int = 3) -> list:
    """Solve B same-shape-class instances in ONE compiled batched loop,
    segmented — the megabatch execution engine the service dispatches a
    formed batch to.

    Per-member hooks (all optional, `b` is the member index):
    `heartbeat(b, SegmentReport)` after every segment;
    `member_stop(b, SegmentReport) -> bool` asks whether to stop the
    member at this boundary (cancel/deadline/preempt — the member is
    checkpointed and its lanes freeze, batchmates continue);
    `on_member_done(b, DistResult)` fires the moment a member's pool
    drains (its terminal state need not wait for the batch);
    `on_member_stopped(b, DistResult)` fires the moment a stop takes
    effect, with the member's checkpointed partial result — the
    service finalizes a cancelled/deadline member THERE, at the
    boundary, instead of holding it RUNNING until the batch drains.
    `stop_event` stops the WHOLE batch at the next boundary (every
    active member checkpoints — the preempt/shutdown path).

    Returns the per-member DistResult list: `complete=True` members
    drained; others stopped with partial counters (their checkpoints
    resume — solo or in a later batch, bit-identically).

    `chunk=None`/`balance_period=None` resolve through the tuner's
    batched key (cache else the batched measured-defaults row — never
    a probe, and never the SOLO serving row silently: the batched
    fallback is its own explicit table row)."""
    from ..tune import defaults as tune_defaults
    from . import checkpoint, incumbent as inc_mod

    prob = dist._resolve_problem(problem)
    if not specs:
        raise ValueError("serve_batch needs at least one MemberSpec")
    if mesh is None:
        from ..parallel.mesh import worker_mesh
        mesh = worker_mesh(None)
    n_dev = mesh.devices.size
    B = len(specs)
    tables0 = np.asarray(specs[0].table)
    for sp in specs:
        if np.asarray(sp.table).shape != tables0.shape:
            raise ValueError(
                "all batch members must share one table shape, got "
                f"{np.asarray(sp.table).shape} vs {tables0.shape}")
    jobs = prob.slots(tables0)
    aux_rows = prob.aux_rows(tables0)
    adt = prob.aux_dtype(tables0)
    if chunk is None or balance_period is None:
        if tuner is not None:
            params = tuner.resolve(jobs, tables0.shape[0], lb_kind,
                                   n_workers=n_dev, allow_probe=False,
                                   problem=prob.name, batch=B)
        else:
            params = tune_defaults.params_for(
                "serving", jobs, tables0.shape[0], problem=prob.name,
                batch=B)
        if chunk is None:
            chunk = params.chunk
            if transfer_cap is None and params.transfer_cap:
                transfer_cap = params.transfer_cap
        if balance_period is None:
            balance_period = params.balance_period
        tracelog.event("tuner.resolve", chunk=chunk,
                       balance_period=balance_period,
                       source=params.source, batch=B)
    if capacity is None:
        capacity = prob.default_capacity(tables0)
    if transfer_cap is None:
        transfer_cap = dist.default_transfer_cap(
            chunk, jobs, aux_rows, n_dev, aux_itemsize=adt.itemsize)
    min_transfer = min_transfer or 2 * chunk

    def make_local_step(t, limit):
        # fused stays "off" (the default) under megabatch: the batched
        # loop vmaps the step over the instance axis, and a vmapped
        # pallas_call has no hardware batching rule — the matmul
        # pipeline is the batched route until the fused kernels grow a
        # native batch dim
        return prob.make_step(t, lb_kind, chunk, 1024, limit)

    driver = BatchedDriver(
        mesh, _stack_tables(prob, [prob.make_tables(np.asarray(sp.table))
                                   for sp in specs]),
        make_local_step, balance_period, transfer_cap, min_transfer,
        limit_fn=lambda cap: prob.usable_rows(cap, chunk, jobs),
        batch=B, loop_cache=loop_cache,
        # the solo key prefix (problem, pool width, table lead dim, lb,
        # chunk, aux dtype) — _problem_driver's layout — so the
        # ("batch", B) suffix is the ONLY difference from a solo key
        loop_key=(prob.name, jobs, int(tables0.shape[0]), lb_kind,
                  chunk, str(adt)))

    members = [_Member(i, sp) for i, sp in enumerate(specs)]

    # ---- per-member seed-or-resume, to ONE common capacity.
    # Each member runs the SOLO rules (warmup target, init_best fold,
    # frontier striping, elastic reshard, capacity pre-grow) so its
    # state at segment 0 is bit-identical to what a solo dispatch at
    # the same knobs would build; the common capacity is the max over
    # members' solo requirements (growth is content-preserving).
    host_states: list[SearchState] = []
    need_caps: list[int] = []
    for m in members:
        sp = m.spec
        table = np.asarray(sp.table)
        resumed = None
        if sp.checkpoint_path and checkpoint.resume_path(
                sp.checkpoint_path):
            resumed = checkpoint.load_resilient(
                sp.checkpoint_path,
                p_times=table if prob.name == "pfsp" else None)[:2]
            saved_prob = resumed[1].get("problem")
            saved_prob = ("pfsp" if saved_prob is None
                          else str(np.asarray(saved_prob)))
            if saved_prob != prob.name:
                raise MemberIncompatible(
                    m.idx,
                    f"checkpoint {sp.checkpoint_path} was written by "
                    f"problem {saved_prob!r}; refusing to resume it as "
                    f"{prob.name!r}")
        if resumed is not None:
            host_state, meta = resumed
            if len(np.asarray(meta.get("host_depth", []))):
                # a -C host-tier checkpoint carries carved-out seed
                # nodes; the batched engine has no host tier — push
                # them back so no subtree is lost
                from . import hybrid
                host_state = hybrid.restore_host_share(
                    host_state,
                    np.asarray(meta["host_prmu"], np.int16),
                    np.asarray(meta["host_depth"], np.int16), table)
            shape = np.asarray(host_state.prmu).shape
            if len(shape) != 3 or shape[0] != n_dev:
                pre_sums = (obs_audit.state_sums(host_state)
                            if obs_audit.enabled() else None)
                host_state = checkpoint.reshard_state(host_state, n_dev)
                if pre_sums is not None:
                    obs_audit.check_reshard(pre_sums, host_state,
                                            edge="elastic_resume")
            m.warmup_tree = int(meta.get("warmup_tree", 0))
            m.warmup_sol = int(meta.get("warmup_sol", 0))
            cap = host_state.prmu.shape[-1]
            need = int(np.asarray(host_state.size).max())
            while driver.limit(cap) < max(need, 1):
                cap *= 2
            if cap != host_state.prmu.shape[-1]:
                host_state = checkpoint.grow(host_state, cap)
            host_states.append(host_state)
            need_caps.append(cap)
        else:
            with tracelog.span("bfs_warmup", problem=prob.name,
                               member=m.idx,
                               target=min_seed * n_dev) as ws:
                fr = prob.warmup(table, lb_kind, sp.init_ub,
                                 target=min_seed * n_dev)
                ws.set(frontier=len(fr.depth), tree=fr.tree)
            init_best = (fr.best if sp.init_ub is None
                         else min(fr.best, int(sp.init_ub)))
            fr.aux = prob.seed_aux(table, fr.prmu, fr.depth)
            m.warmup_tree, m.warmup_sol = fr.tree, fr.sol
            # the member RUNS at the common serving capacity (the solo
            # pre-grow rule decides need_caps), but its stripes are
            # BUILT at the smallest capacity that admits them —
            # striping is front-aligned, so the layout at any larger
            # capacity is this plus zero rows, which stack_states pads
            # without a per-member full-capacity allocation
            cap = capacity
            stripe = -(-max(len(fr.depth), 1) // n_dev)
            while driver.limit(cap) < max(stripe, 1):
                cap *= 2
            need_caps.append(cap)
            seed_cap = 256
            while (seed_cap < cap
                   and driver.limit(seed_cap) < max(stripe, 1)):
                seed_cap *= 2
            seed_cap = min(seed_cap, cap)
            leaves = dist._shard_frontier(
                fr, n_dev, seed_cap, jobs, init_best,
                limit=driver.limit(seed_cap))
            host_states.append(SearchState(*leaves))

    common_cap = max(need_caps)
    # resumed members may carry a different aux dtype (a legacy int32
    # snapshot) or telemetry width (a flag flip across lifetimes) — a
    # batch must be homogeneous to stack. Blame a member that differs
    # from the MAJORITY, typed so the service demotes it to solo
    def _homogeneous(values, what: str) -> None:
        if len(set(values)) <= 1:
            return
        modal = max(set(values), key=values.count)
        offender = next(i for i, v in enumerate(values) if v != modal)
        raise MemberIncompatible(
            offender,
            f"batch member {offender} carries {what} "
            f"{values[offender]!r} (batch majority: {modal!r}); "
            "re-serve the legacy-checkpoint request solo")

    _homogeneous([np.asarray(s.aux).dtype for s in host_states],
                 "pool aux dtype")
    _homogeneous([int(np.asarray(s.telemetry).shape[-1])
                  for s in host_states], "telemetry block width")

    t0 = time.perf_counter()
    for m, hs in zip(members, host_states):
        m.start_iters = int(np.asarray(hs.iters).max())
        m.folder = checkpoint._ReportFolder(hs, t0, stall_limit,
                                            m.start_iters)
        if incumbent_board is not None:
            m.client = inc_mod.BoardClient(
                incumbent_board,
                m.spec.incumbent_key
                or inc_mod.share_key(np.asarray(m.spec.table),
                                     problem=prob.name))
            m.client.publish(int(np.asarray(hs.best).min()))

    state = driver.commit(stack_states(host_states,
                                       capacity=common_cap))
    del host_states

    def member_meta(m: _Member) -> dict:
        extra = m.spec.checkpoint_meta_extra
        extra = (extra() if callable(extra) else dict(extra or {}))
        return {"warmup_tree": m.warmup_tree, "warmup_sol": m.warmup_sol,
                "problem": prob.name,
                "host_prmu": np.zeros((0, jobs), np.int16),
                "host_depth": np.zeros(0, np.int16), **extra}

    # ONE whole-batch host fetch per save boundary, shared by every
    # member saving at it: per-member device slicing + fetch costs
    # ~30 ms x B per boundary (measured: +0.6 s on a 16-member batch),
    # while one batched fetch plus numpy slicing is ~flat in B
    host_cache: dict = {"seg": -1, "state": None}

    def _host_state(st: SearchState, seg: int) -> SearchState:
        if host_cache["seg"] != seg:
            host_cache["seg"] = seg
            host_cache["state"] = dist.fetch_state(st)
        return host_cache["state"]

    def save_member(m: _Member, st: SearchState, seg: int) -> None:
        if not m.spec.checkpoint_path:
            return
        snap = slice_member(_host_state(st, seg), m.idx)
        checkpoint.save(m.spec.checkpoint_path, snap,
                        meta={**member_meta(m), "segment": seg})
        if obs_audit.roundtrip_enabled():
            obs_audit.check_checkpoint_roundtrip(
                m.spec.checkpoint_path, snap)
        m.last_saved_seg = seg

    def finish_member(m: _Member, st: SearchState, fetched,
                      complete: bool) -> DistResult:
        f = {k: (np.asarray(v)[:, m.idx] if v is not None else None)
             for k, v in fetched.items()}
        best = int(f["best"].min())
        if m.client is not None:
            m.client.publish(best)
        telemetry = None
        if f.get("telemetry") is not None and f["telemetry"].size:
            # summarize merges the (D, W) stack itself — merging here
            # first would replay the ring twice and drop same-iteration
            # non-monotone improvements the solo path keeps
            telemetry = tele.summarize(f["telemetry"])
        res = DistResult(
            explored_tree=int(f["tree"].sum()) + m.warmup_tree,
            explored_sol=int(f["sol"].sum()) + m.warmup_sol,
            best=best, telemetry=telemetry,
            per_device={
                "tree": f["tree"], "sol": f["sol"], "iters": f["iters"],
                "evals": f["evals"], "sent": f["sent"],
                "recv": f["recv"], "steals": f["steals"],
                "final_size": f["size"],
            },
            warmup_tree=m.warmup_tree, warmup_sol=m.warmup_sol,
            complete=complete, problem=prob.name)
        if obs_audit.enabled():
            obs_audit.check_result(res)
        m.result = res
        m.active = False
        return res

    seg = 0
    names = ("iters", "tree", "sol", "size", "best", "steals",
             "overflow", "evals", "sent", "recv")
    tele_on = int(state.telemetry.shape[-1]) > 0
    from ..utils import faults
    with tracelog.span("batch.execute", batch=B, problem=prob.name,
                       jobs=jobs, chunk=chunk) as bs:
        while any(m.active for m in members):
            # the same deterministic injection points run_segmented
            # fires, so the chaos/crash drill kinds (kill_server,
            # delay_segment, ...) cover batched execution too
            faults.fire("segment_start", segment=seg + 1)
            targets = []
            caps = []
            for m in members:
                if not m.active:
                    # frozen: the recorded iteration count — the cond
                    # is already false for this member
                    targets.append(m.frozen_target or m.start_iters)
                    caps.append(None)
                else:
                    targets.append(m.start_iters
                                   + (seg + 1) * segment_iters)
                    caps.append(m.client.cap() if m.client else None)
            out = driver.run_once(state, targets, caps)
            fetched_t = checkpoint._fetch_many(
                tuple(getattr(out, n) for n in names)
                + ((out.telemetry,) if tele_on else ()))
            fetched = dict(zip(names, fetched_t))
            fetched["telemetry"] = fetched_t[len(names)] if tele_on \
                else None
            if bool(np.asarray(fetched["overflow"]).any()):
                # lossless whole-batch growth, the solo driver.run
                # recovery at batch granularity: fetch, double, recommit,
                # re-dispatch the SAME targets (not a new segment)
                grown = checkpoint.grow(dist.fetch_state(out),
                                        out.prmu.shape[-1] * 2)
                state = driver.commit(grown)
                continue
            state = out
            seg += 1
            batch_stop = stop_event is not None and stop_event.is_set()
            for m in members:
                if not m.active:
                    continue
                rep = m.folder.fold(
                    tuple(np.asarray(fetched[n])[:, m.idx]
                          for n in ("iters", "tree", "sol", "size",
                                    "best", "steals", "overflow",
                                    "evals"))
                    + ((np.asarray(
                        fetched["telemetry"])[:, m.idx],)
                       if tele_on else ()), seg)
                if m.client is not None:
                    m.client.publish(rep.best)
                if heartbeat is not None:
                    heartbeat(m.idx, rep)
                if rep.pool_size == 0:
                    # no drain-save (checked BEFORE the periodic save:
                    # at checkpoint_every=1 the drain boundary would
                    # otherwise write a snapshot the DONE finalize
                    # unlinks moments later): a drained member's
                    # snapshot records an empty pool nobody will
                    # resume, and a crash between drain and the ledger
                    # terminal replays the request to the same
                    # bit-identical result. (The solo driver's
                    # exit-save predates serving and is kept there for
                    # the CLI resume contract.)
                    res = finish_member(m, state, fetched,
                                        complete=True)
                    if on_member_done is not None:
                        on_member_done(m.idx, res)
                    continue
                stop = batch_stop or (
                    member_stop is not None and member_stop(m.idx, rep))
                if stop:
                    save_member(m, state, seg)
                    m.frozen_target = rep.iters
                    m.stopped = True
                    res = finish_member(m, state, fetched,
                                        complete=False)
                    if on_member_stopped is not None:
                        on_member_stopped(m.idx, res)
                    continue
                if m.spec.checkpoint_path \
                        and seg % checkpoint_every == 0:
                    save_member(m, state, seg)
                m.folder.check_stall(rep)
            # after the boundary's heartbeats and saves, like
            # run_segmented's post-checkpoint injection point
            faults.fire("post_segment", segment=seg)
        bs.set(segments=seg,
               done=sum(1 for m in members
                        if m.result is not None and m.result.complete))
    return [m.result for m in members]
