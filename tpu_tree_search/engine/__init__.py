from . import sequential

__all__ = ["sequential"]
