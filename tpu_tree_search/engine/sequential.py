"""Sequential B&B oracle engines (host-side, exact reference semantics).

These are the correctness oracles: slow, simple, and byte-exact in their
counting semantics with the reference's sequential programs
(reference: pfsp/pfsp_c.c:26-73, nqueens/nqueens_c.c:99-148). The TPU
engines are validated against the `(explored_tree, explored_sol, best)`
triple these produce. With `ub=opt` the PFSP tree is exploration-order
independent (the incumbent never improves), so the counts here must match
the device engines exactly; with `ub=inf` only the final optimum must match.

Counting semantics (reference: PFSP_lib.c:7-129):
- `explored_tree` += 1 for every non-leaf child whose bound beats the
  incumbent (i.e. every node *pushed*); the root is pushed but not counted.
- `explored_sol`  += 1 for every leaf child evaluated (feasible or not).
- a leaf child with bound < best improves the incumbent and is not pushed.
N-Queens differs (reference: nqueens_c.c:99-117): all safe children are
pushed (including complete boards), and a popped node at depth N counts as
a solution.
"""

from __future__ import annotations

import dataclasses
import time

import numpy as np

from ..ops import reference as ref
from ..problems import nqueens as nq
from ..problems.pfsp import PFSPInstance

INT_MAX = 2**31 - 1

LB1_D = 0  # incremental all-children one-machine bound ("lb1_d")
LB1 = 1    # full one-machine bound
LB2 = 2    # two-machine Johnson bound


@dataclasses.dataclass
class SearchResult:
    explored_tree: int
    explored_sol: int
    best: int
    complete: bool = True   # False: truncated (max_nodes / deadline_s)


def pfsp_search(instance: PFSPInstance, lb: int = LB1,
                init_ub: int | None = None,
                max_nodes: int | None = None,
                deadline_s: float | None = None) -> SearchResult:
    """Depth-first B&B over one PFSP instance (reference: pfsp_c.c:26-73).

    `init_ub=None` means an infinite initial incumbent (`-u 0`); pass the
    known optimum for the `-u 1` mode. `max_nodes` caps popped nodes for
    truncated-search tests (None = run to completion). `deadline_s` is a
    wall-clock budget: the Python oracle is the slowest component of
    every verification run, and an oracle call that outgrows its test
    budget should degrade to a truncated result (complete=False) a
    caller can detect, not hang the suite — the same fail-loud posture
    the engine's own watchdog takes (engine/checkpoint.run_segmented).
    """
    jobs, machines = instance.jobs, instance.machines
    lb1 = ref.make_lb1_data(instance.p_times)
    lb2 = ref.make_lb2_data(lb1) if lb == LB2 else None

    best = INT_MAX if init_ub is None else int(init_ub)
    tree = 0
    sol = 0

    # stack of (prmu int16[jobs], depth); root = identity at depth 0
    stack: list[tuple[np.ndarray, int]] = [
        (np.arange(jobs, dtype=np.int16), 0)
    ]
    popped = 0
    deadline = (None if deadline_s is None
                else time.perf_counter() + deadline_s)

    while stack:
        if max_nodes is not None and popped >= max_nodes:
            break
        if (deadline is not None and popped % 256 == 0
                and time.perf_counter() > deadline):
            break
        prmu, depth = stack.pop()
        popped += 1
        limit1 = depth - 1  # forward branching invariant

        if lb == LB1_D:
            lb_begin = ref.lb1_children_bounds(lb1, prmu, limit1, jobs)

        for i in range(depth, jobs):
            child = prmu.copy()
            child[depth], child[i] = child[i], child[depth]
            if lb == LB1:
                bound = ref.lb1_bound(lb1, child, limit1 + 1, jobs)
            elif lb == LB1_D:
                bound = int(lb_begin[int(prmu[i])])
            else:
                bound = ref.lb2_bound(lb1, lb2, child, limit1 + 1, jobs, best)

            if depth + 1 == jobs:           # leaf: complete schedule
                sol += 1
                if bound < best:
                    best = bound
            elif bound < best:              # feasible internal node
                stack.append((child, depth + 1))
                tree += 1

    return SearchResult(explored_tree=tree, explored_sol=sol, best=best,
                        complete=not stack)


def nqueens_search(n: int, g: int = 1,
                   max_nodes: int | None = None) -> SearchResult:
    """Depth-first N-Queens backtracking (reference: nqueens_c.c:119-148)."""
    tree = 0
    sol = 0
    stack: list[tuple[np.ndarray, int]] = [(np.arange(n, dtype=np.int16), 0)]
    popped = 0

    while stack:
        if max_nodes is not None and popped >= max_nodes:
            break
        board, depth = stack.pop()
        popped += 1
        if depth == n:
            sol += 1
        for j in range(depth, n):
            if nq.is_safe(board, depth, int(board[j])):
                child = board.copy()
                child[depth], child[j] = child[j], child[depth]
                stack.append((child, depth + 1))
                tree += 1

    # `g` only scales the safety-check work in the reference; results are
    # independent of it, so the oracle ignores it.
    del g
    return SearchResult(explored_tree=tree, explored_sol=sol, best=sol,
                        complete=not stack)
