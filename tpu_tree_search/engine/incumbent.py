"""Cross-request incumbent sharing: the process-wide best-bound board.

The reference's distributed engine gets a large part of its win from the
MPI best-makespan exchange (PAPER.md's inter-node redistribution +
best-bound broadcast): every rank prunes against the GLOBALLY best
incumbent, not its own. Our search service multiplexes concurrent
requests onto disjoint submeshes — until this module, two requests
solving the same instance each pruned only against their own best, so
both explored subtrees the other had already bounded away.

`IncumbentBoard` is the in-process analogue of that MPI exchange: a
thread-safe map from problem-instance identity to the best makespan any
request has found. At every segment boundary a participating search

- PUBLISHES its current best (a min-fold: the board only tightens), and
- FOLDS the board's value in as the next segment's pruning ceiling — a
  traced ``bound_cap`` scalar input to the compiled loop
  (engine/distributed.build_dist_loop applies ``min(best, bound_cap)``
  at loop entry), so folding never retraces or recompiles.

Monotonicity is the safety story: a fold can only TIGHTEN pruning
(``min`` both ways), which preserves correctness — any published value
is the makespan of a real schedule of the same instance, hence a valid
upper bound for every sharer. `BoardClient` audits this on every fold
(obs/audit's ``incumbent_monotone`` invariant: the ceiling handed to a
request never loosens) and counts exchanges in
``tts_incumbent_folds_total{direction}`` ("out" = this search improved
the board, "in" = the board tightened this search).

Keying: :func:`instance_key` hashes the processing-time table (shape +
bytes), so only requests on the SAME instance share; an optional
``group`` namespaces further (the service maps
``SearchRequest.share_group`` here — tenants can opt a tag family into
or out of a sharing pool).

The board is owned by the service layer (service/server.SearchServer
builds one when sharing is enabled — the ``TTS_SHARE_INCUMBENT`` flag
or the ``share_incumbent`` knob) and handed to
``engine/distributed.search`` per request; the engine itself never
consults process globals, so standalone runs are byte-for-byte
unaffected.
"""

from __future__ import annotations

import hashlib
import threading

import numpy as np

from ..obs import metrics as obs_metrics
from ..obs import tracelog

__all__ = ["IncumbentBoard", "BoardClient", "instance_key"]

# engine/device.I32_MAX, the "no incumbent yet" sentinel — mirrored
# here (cheap int, no jax import) so publish can refuse it: the
# sentinel is not the makespan of any real schedule, and boarding it
# would book a bogus direction=out exchange and pollute /status
_NO_INCUMBENT = np.iinfo(np.int32).max


def instance_key(p_times, group: str | None = None) -> str:
    """Problem-instance identity: a content hash of the processing-time
    table (dtype-normalized, shape included), optionally namespaced by
    `group`. Two requests share incumbents iff their keys match."""
    p = np.ascontiguousarray(np.asarray(p_times, dtype=np.int64))
    h = hashlib.sha1()
    h.update(np.asarray(p.shape, np.int64).tobytes())
    h.update(p.tobytes())
    digest = h.hexdigest()[:16]
    return f"{group}/{digest}" if group else digest


def share_key(table, problem: str = "pfsp",
              group: str | None = None) -> str:
    """THE cross-request share-key rule, problem-aware: PFSP keys keep
    their pre-plugin form (bare digest / group-namespaced), every other
    problem is namespaced by its registry name so two problems with
    bit-identical tables can never exchange bounds. The server's
    dispatch and engine/distributed.search's default both resolve keys
    HERE — two call sites deriving the namespace independently would
    drift and silently stop sharing."""
    if problem != "pfsp":
        group = f"{problem}:{group}" if group else problem
    return instance_key(table, group=group)


class IncumbentBoard:
    """Thread-safe best-bound map; values only ever decrease (min-fold).

    The write path is :meth:`publish`, the read path :meth:`peek`;
    both are O(1) dict operations under one lock — segment boundaries
    are the only callers, so contention is structurally negligible
    against a segment's device compute.

    Bounded: at most `max_keys` distinct instance keys
    (TTS_INCUMBENT_MAX_KEYS, same bounded-observability stance as the
    metrics cardinality valve) — entries persist past request
    completion on purpose (a later same-instance request warm-starts
    from the known best), so a long-lived many-tenant server evicts
    the least-recently-updated key instead of growing without bound.
    Eviction only forfeits that warm-start tightening; monotonicity
    makes a missing entry always safe."""

    def __init__(self, max_keys: int | None = None):
        from ..utils import config as _cfg
        if max_keys is None:
            max_keys = _cfg.env_int("TTS_INCUMBENT_MAX_KEYS")
        self._lock = threading.Lock()
        self._max_keys = max(1, int(max_keys))
        self._best: dict[str, int] = {}   # guarded-by: self._lock

    def publish(self, key: str, value: int, source: str = "") -> bool:
        """Min-fold `value` into the board; True iff it improved the
        global best for `key` (the "out" direction of the exchange)."""
        value = int(value)
        with self._lock:
            cur = self._best.get(key)
            if cur is not None and cur <= value:
                return False
            # re-insert to mark recency (dict order = update order),
            # then evict the stalest keys past the bound
            self._best.pop(key, None)
            self._best[key] = value
            while len(self._best) > self._max_keys:
                self._best.pop(next(iter(self._best)))
        obs_metrics.default().counter(
            "tts_incumbent_folds_total",
            "cross-request incumbent exchanges by direction "
            "(out = published an improvement to the board, "
            "in = folded a tighter global bound into a search)"
            ).inc(direction="out")
        tracelog.event("incumbent.publish", key=key, value=value,
                       prev=cur, source=source or None)
        return True

    def peek(self, key: str) -> int | None:
        """Current global best for `key` (None = nothing published)."""
        with self._lock:
            return self._best.get(key)

    def snapshot(self) -> dict:
        """JSON-safe view for status APIs: {key: best}."""
        with self._lock:
            return dict(self._best)

    def __len__(self) -> int:
        with self._lock:
            return len(self._best)


class BoardClient:
    """One search's binding to a board: publish/fold with the monotone
    audit and the direction-labeled fold counters built in. The engine
    calls :meth:`cap` once per segment dispatch and :meth:`publish`
    once per heartbeat — both cheap, both host-side."""

    def __init__(self, board: IncumbentBoard, key: str,
                 source: str = ""):
        self.board = board
        self.key = key
        self.source = source
        self._last_cap: int | None = None   # last ceiling handed out
        self._last_best: int | None = None  # last local best seen

    def publish(self, best) -> bool:
        best = int(best)
        if best >= _NO_INCUMBENT:
            return False    # nothing found yet — sentinel, not a bound
        self._last_best = (best if self._last_best is None
                           else min(self._last_best, best))
        return self.board.publish(self.key, best, source=self.source)

    def cap(self) -> int | None:
        """The pruning ceiling for the next segment (None = no fold).
        Folds ONLY when the board is strictly tighter than this
        search's own best: the board's entry for a lone request is its
        own published best, and folding that global min into every
        worker would pre-broadcast the incumbent ahead of the engine's
        own balance-round exchange — changing per-worker node
        accounting even with nothing shared. Skipping the self-fold
        keeps a single participating request bit-identical to an
        unshared run (pinned by tests/test_overlap.py) while a
        genuinely tighter peer bound still folds. Audited monotone:
        the board can only tighten, so a ceiling LOOSER than one
        previously handed out means the exchange itself is broken —
        that is an audit failure, and the loose value is clamped so
        the search still never regresses."""
        g = self.board.peek(self.key)
        if g is None or (self._last_best is not None
                         and g >= self._last_best):
            return None
        from ..obs import audit as obs_audit
        audit_on = obs_audit.enabled()
        if self._last_cap is not None and g > self._last_cap:
            # never true by construction (publish is a min-fold); the
            # auditor exists to catch exactly the "never true" breaking.
            # The clamp is safety, not observability — it stays even
            # with TTS_AUDIT=0.
            if audit_on:
                obs_audit.check_incumbent_fold(self.key, self._last_cap,
                                               g)
            g = self._last_cap
        elif audit_on and (self._last_cap is None or g < self._last_cap):
            obs_audit.check_incumbent_fold(self.key, self._last_cap, g)
        if self._last_best is None or g < self._last_best:
            # the board is about to tighten this search's pruning —
            # the "in" direction of the exchange
            obs_metrics.default().counter(
                "tts_incumbent_folds_total",
                "cross-request incumbent exchanges by direction "
                "(out = published an improvement to the board, "
                "in = folded a tighter global bound into a search)"
                ).inc(direction="in")
            tracelog.event("incumbent.fold", key=self.key, value=g,
                           local_best=self._last_best,
                           source=self.source or None)
            self._last_best = g
        self._last_cap = g
        return g
