"""Multi-device distributed PFSP engine: one SPMD program over the mesh.

The reference needs three nested runtimes for this — OpenMP threads per
node (pfsp_multigpu_cuda.c:143), MPI ranks across nodes with a dedicated
communicator thread (pfsp_dist_multigpu_cuda.c:283, 364-469), and CUDA
streams per GPU. Here the whole hierarchy is one `shard_map`ped program
over a 1-D worker mesh: every worker owns a private HBM pool and runs the
same compiled loop; every `balance_period` steps the workers

  - share the incumbent via `pmin` (the per-round Allreduce MIN of
    `best_l`, dist:369-374, and the intra-node `checkBest` CAS,
    pfsp_multigpu_cuda.c:30-50, in one op),
  - rebalance pools via all_gather + all_to_all (see parallel/balance.py),

and the loop predicate `psum(has_work) > 0` *is* the distributed
termination detection (`globalTermination`'s Allgather of has-work flags,
dist:69-88, moved on-device).

Phase schedule mirrors the reference's 3-step scheme (dist:193-205,
864-882): a replicated-cost host BFS warm-up generates a frontier of at
least `min_seed * workers` nodes (step 1), round-robin striding assigns
each worker its stripe (`roundRobin_distribution`, Pool_atom.c:14-36),
the SPMD loop explores (step 2), and exhaustion needs no step-3 drain
because the collective balance keeps feeding idle workers until the
global pool is empty.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..obs import audit as obs_audit
from ..obs import tracelog
from ..ops import pallas_fused
from ..ops import reference as ref
from ..ops.batched import BoundTables
from ..parallel import balance as bal
from ..parallel.mesh import WORKER_AXIS, shard_map, worker_mesh
from . import sequential as seq
from . import telemetry as tele
from .device import I32_MAX, SearchState

AX = WORKER_AXIS

# donation under shard_map is best-effort: a backend that cannot alias
# a given buffer falls back to a copy and warns per execution — noise,
# not an error, on the CPU test mesh (the overlapped driver still gets
# async dispatch; only the zero-copy carry is backend-dependent).
# run_async scopes the suppression to its own donating dispatch so
# importing this module never mutes the diagnostic for anyone else's
# donate_argnums code.
import warnings as _warnings  # noqa: E402

# per-worker byte budget for one balance round's all_to_all buffers
# (each way); caps the DEFAULT transfer_cap at production shapes — see
# default_transfer_cap() and tools/bench_balance.py for the measured
# tradeoff
BALANCE_BYTE_BUDGET = 64 << 20


def default_transfer_cap(chunk: int, jobs: int, machines: int,
                         n_dev: int, aux_itemsize: int = 4) -> int:
    """Default balance transfer cap: 4*chunk, byte-budgeted. The
    all_to_all moves (2J + aux_itemsize*A + 2) bytes per column over
    D*transfer_cap columns each way per worker; at production shapes
    (chunk 32768, 20x20, D=8) the uncapped default is ~122 MB of
    exchange buffer per worker per round — the cap bounds it to
    BALANCE_BYTE_BUDGET. `aux_itemsize` is the pool aux dtype's width
    (2 for the int16 classes, device.aux_dtype). SHARED by search() and
    the CSV phase profiler (cli) so the profiled exchange is the one
    production runs."""
    bytes_per_col = 2 * jobs + aux_itemsize * machines + 2
    budget_cols = BALANCE_BYTE_BUDGET // (bytes_per_col * max(n_dev, 1))
    return max(min(4 * chunk, budget_cols), 256)


# ---------------------------------------------------------------------------
# Step 1: host BFS warm-up (breadth generates parallelism; reference runs
# this replicated on every rank, dist:198-205 — here once on the host)

_native_warned = False


def _warn_native_unavailable(e: Exception) -> None:
    """A broken native toolchain must degrade LOUDLY, not silently — the
    pure-Python warm-up produces identical results but is orders of
    magnitude slower, which would otherwise look like a perf regression
    with no cause."""
    global _native_warned
    if not _native_warned:
        _native_warned = True
        import warnings
        warnings.warn(
            f"native host runtime unavailable ({e!r}); falling back to "
            "the pure-Python warm-up (identical results, much slower). "
            "Check `g++` and tpu_tree_search/native/__init__.py:build.",
            RuntimeWarning, stacklevel=3)


@dataclasses.dataclass
class Frontier:
    prmu: np.ndarray    # (n, jobs) int16
    depth: np.ndarray   # (n,) int16
    tree: int           # counters accumulated during warm-up
    sol: int
    best: int
    aux: np.ndarray | None = None  # (n, A) per-node pool tables, in the
                                   # pool's aux dtype (device.aux_dtype)


def bfs_warmup(p_times: np.ndarray, lb_kind: int, init_ub: int | None,
               target: int, use_native: bool = True) -> Frontier:
    """Pop-front BFS until the frontier holds >= target nodes (or the tree
    is exhausted). Same decompose semantics as the oracle, so warm-up
    counters + device counters add up to the sequential totals.

    Uses the native C++ runtime when available (tpu_tree_search/native);
    the pure-Python path below is the validated fallback/oracle.
    """
    if use_native:
        try:
            from .. import native
            prmu, depth, tree, sol, best = native.bfs_frontier(
                p_times, lb_kind, init_ub, target)
            return Frontier(prmu=prmu, depth=depth, tree=tree, sol=sol,
                            best=best)
        except Exception as e:
            _warn_native_unavailable(e)  # loud fallback, same results
    jobs = p_times.shape[1]
    lb1 = ref.make_lb1_data(p_times)
    lb2 = ref.make_lb2_data(lb1) if lb_kind == seq.LB2 else None
    best = seq.INT_MAX if init_ub is None else int(init_ub)
    tree = sol = 0

    from collections import deque
    frontier: deque[tuple[np.ndarray, int]] = deque(
        [(np.arange(jobs, dtype=np.int16), 0)]
    )
    while frontier and len(frontier) < target:
        prmu, depth = frontier.popleft()
        limit1 = depth - 1
        if lb_kind == seq.LB1_D:
            lb_begin = ref.lb1_children_bounds(lb1, prmu, limit1, jobs)
        for i in range(depth, jobs):
            child = prmu.copy()
            child[depth], child[i] = child[i], child[depth]
            if lb_kind == seq.LB1:
                bound = ref.lb1_bound(lb1, child, limit1 + 1, jobs)
            elif lb_kind == seq.LB1_D:
                bound = int(lb_begin[int(prmu[i])])
            else:
                bound = ref.lb2_bound(lb1, lb2, child, limit1 + 1, jobs, best)
            if depth + 1 == jobs:
                sol += 1
                if bound < best:
                    best = bound
            elif bound < best:
                frontier.append((child, depth + 1))
                tree += 1

    if frontier:
        prmu = np.stack([f[0] for f in frontier]).astype(np.int16)
        depth = np.array([f[1] for f in frontier], dtype=np.int16)
    else:
        prmu = np.zeros((0, jobs), np.int16)
        depth = np.zeros((0,), np.int16)
    return Frontier(prmu=prmu, depth=depth, tree=tree, sol=sol, best=best)


# ---------------------------------------------------------------------------
# Step 2: the SPMD search loop


def _balance_round(s: SearchState, transfer_cap: int,
                   min_transfer: int, limit: int) -> SearchState:
    """One collective steal-half exchange (see parallel/balance.py).

    `limit` is the usable-row bound every commit must respect; the loop
    builder reserves `D * transfer_cap` rows of headroom above it (and
    runs the local steps against the same tightened limit), so the
    receive block write is ALWAYS in bounds — an overflowing round never
    clamps onto live rows.

    The round is globally transactional: each worker's would-overflow
    flag (known before any data moves — a worker receives exactly
    plan[:, me].sum() nodes) is psum'd, and if any worker would
    overflow, no worker exchanges or commits. The loop then exits on the
    overflow flag and the driver grows every pool and RESUMES from this
    state, losing nothing.

    The pack/exchange/unpack (the gathers, the all_to_all, the sort) is
    cond-gated on the plan being non-empty and fitting — a balanced
    steady state pays one all_gather of the sizes, one tiny psum, and a
    zero-block scratch write.
    """
    J, capacity = s.prmu.shape
    A = s.aux.shape[0]
    D = jax.lax.psum(1, AX)
    sizes = jax.lax.all_gather(s.size, AX)                  # (D,)
    plan = bal.exchange_plan(sizes, transfer_cap, min_transfer)
    me = jax.lax.axis_index(AX)
    my_out = plan[me]                                       # (D,)
    total_out = my_out.sum(dtype=jnp.int32)
    total_in = plan[:, me].sum(dtype=jnp.int32)
    base = s.size - total_out
    n_recv = plan.shape[0] * transfer_cap
    # Would-overflow is known BEFORE the exchange (each worker receives
    # exactly plan[:, me].sum() nodes) and is decided globally: if ANY
    # worker would overflow, NO worker exchanges or commits — every node
    # keeps living in exactly one pool, the loop exits on the flag, and
    # the driver grows every pool and resumes losslessly (the round-1
    # design restarted from the warm-up frontier, discarding all
    # explored work).
    ovf = jax.lax.psum((base + total_in > limit).astype(jnp.int32), AX) > 0
    # identical on every worker (plan and ovf are pure functions of the
    # all_gathered sizes), so the cond below cannot diverge across the
    # mesh and the collectives inside it are safe
    do_flow = (plan.sum() > 0) & ~ovf

    def do_exchange(_):
        # pack donated nodes (from the stack top) into per-receiver blocks
        offs = jnp.cumsum(my_out, dtype=jnp.int32) - my_out
        k = jnp.arange(transfer_cap, dtype=jnp.int32)
        rows = base + offs[:, None] + k[None, :]            # (D, cap)
        send_mask = k[None, :] < my_out[:, None]
        rows_c = jnp.clip(rows, 0, capacity - 1).reshape(-1)
        buf_prmu = jnp.take(s.prmu, rows_c, axis=1)         # (J, D*cap)
        buf_aux = jnp.take(s.aux, rows_c, axis=1)           # (A, D*cap)
        buf_depth = jnp.where(send_mask.reshape(-1),
                              s.depth[rows_c], -1)[None, :]  # -1 = hole

        # all_to_all exchanges the per-receiver blocks (the D axis must
        # be the split axis exactly)
        def exchange(x):
            rows = x.shape[0]
            blocks = x.reshape(rows, D, transfer_cap)
            return jax.lax.all_to_all(blocks, AX, 1, 1) \
                .reshape(rows, D * transfer_cap)

        rbuf_prmu = exchange(buf_prmu)
        rbuf_aux = exchange(buf_aux)
        rbuf_depth = exchange(buf_depth)

        # compact received nodes to the front of the block (same
        # scatter-free scheme as device.step)
        flat_depth = rbuf_depth.reshape(-1)
        push = flat_depth >= 0
        order = jnp.argsort(~push, stable=True)
        return (jnp.take(rbuf_prmu, order, axis=1),
                jnp.take(rbuf_aux, order, axis=1),
                jnp.take(flat_depth, order).astype(jnp.int16),
                push.sum(dtype=jnp.int32))

    def no_exchange(_):
        return (jnp.zeros((J, n_recv), s.prmu.dtype),
                jnp.zeros((A, n_recv), s.aux.dtype),
                jnp.full((n_recv,), -1, s.depth.dtype),
                jnp.int32(0))

    recv_prmu, recv_aux, recv_depth, n_push = jax.lax.cond(
        do_flow, do_exchange, no_exchange, 0)

    # Commit (a skipped/aborted round routes its zero block to the
    # scratch rows above `limit` — in bounds by the loop builder's
    # headroom reservation, and never read because rows above the
    # cursor are garbage by the pool invariant).
    zero = jnp.zeros((), base.dtype)
    write_at = jnp.where(do_flow, base, jnp.asarray(limit, base.dtype))
    keep = lambda new, old: jnp.where(do_flow, new, old)  # noqa: E731
    telem = s.telemetry
    if telem.shape[-1] > 0:
        # steal-flow telemetry mirrors the sent/recv counters below,
        # under the same committed-round guard
        t = telem.at[tele.O_STEAL_SENT].add(total_out.astype(jnp.int64))
        t = t.at[tele.O_STEAL_RECV].add(n_push.astype(jnp.int64))
        telem = keep(t, telem)
    return s._replace(
        telemetry=telem,
        prmu=jax.lax.dynamic_update_slice(s.prmu, recv_prmu,
                                          (zero, write_at)),
        depth=jax.lax.dynamic_update_slice(s.depth, recv_depth,
                                           (write_at,)),
        aux=jax.lax.dynamic_update_slice(s.aux, recv_aux, (zero, write_at)),
        size=keep(base + n_push, s.size),
        sent=keep(s.sent + total_out.astype(jnp.int64), s.sent),
        recv=keep(s.recv + n_push.astype(jnp.int64), s.recv),
        steals=keep(s.steals + (n_push > 0).astype(jnp.int64), s.steals),
        overflow=s.overflow | ovf,
    )


def _local_state(*leaves):
    return SearchState(*(x[0] for x in leaves))


def _expand(s: SearchState):
    return tuple(x[None, ...] for x in s)


def member_body(tables, make_local_step, balance_period: int,
                transfer_cap: int, min_transfer: int, limit: int):
    """One macro-iteration of the SPMD loop for ONE instance:
    `balance_period` local steps, the pmin incumbent exchange, one
    balance round. Shared by :func:`build_dist_loop` (the solo loop)
    and engine/megabatch.build_batched_loop (the same body vmapped over
    a leading instance axis), so the batched member semantics can never
    drift from the solo loop — the bit-parity contract between a
    megabatched request and its solo run rests on this being ONE
    function."""
    local_step = make_local_step(tables, limit)

    def body(s: SearchState) -> SearchState:
        s = jax.lax.fori_loop(0, balance_period,
                              lambda _, x: local_step(x), s)
        s = s._replace(best=jax.lax.pmin(s.best, AX))
        return _balance_round(s, transfer_cap, min_transfer, limit)

    return body


def build_dist_loop(mesh, tables, make_local_step,
                    balance_period: int, transfer_cap: int,
                    min_transfer: int, limit: int,
                    donate_pools: bool = False):
    """Compile a distributed search loop for any problem: state sharded
    over the worker axis, problem tables replicated.

    `make_local_step(tables, limit)` returns the problem's
    SearchState -> SearchState step, bounded to `limit` usable rows —
    the SAME tightened limit the balance round commits against, chosen
    by the driver so both the step scratch block and the balance receive
    block fit above it (see _balance_round).

    The compiled function has signature
    `run(tables, max_iters, bound_cap, *state)` with `max_iters` a
    TRACED cumulative per-worker iteration ceiling (like device.run's)
    and `bound_cap` a TRACED pruning ceiling folded into the incumbent
    at loop entry (`min(best, bound_cap)` — pass I32_MAX for "no cap").
    The cap is how cross-request incumbent sharing reaches the compiled
    loop without a retrace (engine/incumbent.py); with the cap at
    I32_MAX the fold is the identity, so non-sharing runs are
    bit-identical to the pre-cap loop. Segmented drivers pass a new
    ceiling/cap every segment and hit the compile cache.

    `donate_pools=True` donates the pool leaves (prmu/depth/aux) to the
    XLA call, so the while-loop carry aliases the input buffers instead
    of copying them — the overlapped driver's dispatch
    (_DistDriver.run_async) requires it; the caller must treat the
    input state's pool arrays as CONSUMED."""

    def worker_loop(tables, max_iters, bound_cap, *state_leaves):
        s = _local_state(*state_leaves)
        s = s._replace(best=jnp.minimum(s.best, bound_cap))

        def cond(s: SearchState):
            has_work = jax.lax.psum(s.size, AX) > 0
            ok = jax.lax.psum(s.overflow.astype(jnp.int32), AX) == 0
            return has_work & ok & (s.iters < max_iters)

        body = member_body(tables, make_local_step, balance_period,
                           transfer_cap, min_transfer, limit)

        return _expand(jax.lax.while_loop(cond, body, s))

    spec_state = tuple(P(AX) for _ in SearchState._fields)
    spec_tables = jax.tree.map(lambda _: P(), tables)
    sharded = shard_map(
        worker_loop, mesh,
        in_specs=(spec_tables, P(), P()) + spec_state,
        out_specs=spec_state,
    )
    if donate_pools:
        # args: 0=tables, 1=max_iters, 2=bound_cap, 3=prmu, 4=depth,
        # 5=aux (SearchState field order), then the scalar leaves
        return jax.jit(sharded, donate_argnums=(3, 4, 5))
    return jax.jit(sharded)


# ---------------------------------------------------------------------------
# Host entry point


class DistResult:
    def __init__(self, explored_tree, explored_sol, best, per_device,
                 warmup_tree, warmup_sol, complete=True, telemetry=None,
                 problem: str = "pfsp"):
        self.explored_tree = explored_tree
        self.explored_sol = explored_sol
        self.best = best
        self.per_device = per_device        # dict of (D,) arrays for stats
        self.warmup_tree = warmup_tree
        self.warmup_sol = warmup_sol
        self.complete = complete            # all pools drained
        self.telemetry = telemetry          # telemetry.summarize dict
                                            # (None when the block is off)
        self.problem = problem              # registry name; the audit
                                            # keys its conservation
                                            # identity off the plugin's
                                            # accounting semantics


def _shard_frontier(fr: Frontier, n_dev: int, capacity: int, jobs: int,
                    init_best: int, limit: int | None = None):
    """Round-robin stripe the frontier across workers
    (reference: roundRobin_distribution, Pool_atom.c:14-36). `limit`
    (device.row_limit) bounds each stripe so seeding respects the
    engine's usable-row invariant."""
    if limit is None:
        limit = capacity
    aux_w = 0 if fr.aux is None else fr.aux.shape[1]
    prmu = np.zeros((n_dev, jobs, capacity), np.int16)
    depth = np.zeros((n_dev, capacity), np.int16)
    aux = np.zeros((n_dev, aux_w, capacity),
                   fr.aux.dtype if aux_w else np.int32)
    sizes = np.zeros(n_dev, np.int32)
    for d in range(n_dev):
        stripe_p = fr.prmu[d::n_dev]
        stripe_d = fr.depth[d::n_dev]
        n = len(stripe_d)
        assert n <= limit
        prmu[d, :, :n] = stripe_p.T
        depth[d, :n] = stripe_d
        if aux_w:
            aux[d, :, :n] = fr.aux[d::n_dev].T
        sizes[d] = n
    return (
        jnp.asarray(prmu), jnp.asarray(depth), jnp.asarray(aux),
        jnp.asarray(sizes),
        jnp.full((n_dev,), init_best, jnp.int32),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, bool),
        jnp.zeros((n_dev, tele.enabled_width()), jnp.int64),
    )


def _fetch(x) -> np.ndarray:
    """Bring a possibly globally-sharded per-device array to every host.

    Single-controller (the normal case): a plain fetch. Multi-controller
    (--multihost): the output spans non-addressable devices, so gather it
    with multihost_utils tiled=True (the array is already global (D,...);
    tiled=False would RE-STACK per-process and is rejected for
    non-addressable inputs). Every process ends up with the full array —
    the reference's stats Gather-to-rank-0 (dist:817-832) except every
    rank gets the totals."""
    from .checkpoint import _to_np
    return _to_np(x)


def _to_mesh(mesh, spec_leaf, x):
    """Commit one host-built state leaf to the mesh.

    Multi-controller JAX rejects plain host arrays as jit inputs over a
    global mesh; every process holds the identical global value (the
    warm-up is replicated, like the reference's step 1 on every rank,
    dist:198-205), so build the global array from per-shard callbacks."""
    if jax.process_count() > 1:
        from jax.sharding import NamedSharding
        sharding = NamedSharding(mesh, spec_leaf)
        return jax.make_array_from_callback(
            np.shape(x), sharding, lambda idx: np.asarray(x)[idx])
    if np.asarray(x).size == 0:
        # a zero-width leaf (the telemetry block with the flag off) is
        # DEAD in the loop body, so sharding propagation cannot pin it:
        # lowered from a plain host array it compiles REPLICATED, while
        # every later segment passes the loop's P(AX)-sharded output —
        # an AOT executable then rejects the second call and falls back
        # to jit (one hidden recompile per served shape). Commit it on
        # the worker axis explicitly, like abstract_state does for the
        # pre-warm lowering, so call 1 and call N agree.
        from jax.sharding import NamedSharding
        return jax.device_put(x, NamedSharding(mesh, spec_leaf))
    return x


def fetch_state(state: SearchState) -> SearchState:
    """Fetch every state leaf to host numpy (multihost: allgather the
    global value so every process holds it — needed for checkpointing
    and pool growth)."""
    return SearchState(*(_fetch(x) for x in state))


class _DistDriver:
    """Compiles/caches the SPMD loop per pool capacity and runs it with
    lossless overflow recovery: on overflow the stacked state is fetched,
    every pool re-homed into double the capacity (checkpoint.grow), the
    loop rebuilt for the new shapes, and the search RESUMED from exactly
    where it stopped — no explored work is ever discarded (the round-1
    design restarted overflowing runs from the warm-up frontier).

    `limit_fn(capacity)` is the problem's usable-row bound (e.g.
    device.row_limit); the driver tightens it so the balance receive
    block also fits above the limit (see _balance_round)."""

    def __init__(self, mesh, tables, make_local_step, balance_period: int,
                 transfer_cap: int, min_transfer: int, limit_fn,
                 loop_cache=None, loop_key: tuple = ()):
        self.mesh = mesh
        self.tables = tables
        self.make_local_step = make_local_step
        self.balance_period = balance_period
        self.transfer_cap = transfer_cap
        self.min_transfer = min_transfer
        self.limit_fn = limit_fn
        self.n_recv = mesh.devices.size * transfer_cap
        self._loops: dict[int, object] = {}
        self.spec_state = tuple(P(AX) for _ in SearchState._fields)
        # Cross-driver executable reuse: `loop_cache` is any object with
        # get_or_build(key, build) (service/executors.ExecutorCache).
        # The compiled loop takes the problem TABLES as a runtime
        # argument, so it depends only on shapes/specialization — two
        # same-shape instances (e.g. all ten Taillard ta021-030) at the
        # same lb/chunk on the same submesh share ONE trace + compile.
        # `loop_key` carries the caller-side specialization (problem
        # kind, jobs, machines, lb_kind, chunk, aux dtype); the driver
        # appends everything else the trace closes over (device
        # identities, capacity, balance knobs, row limit).
        self.loop_cache = loop_cache
        self.loop_key = tuple(loop_key) + tuple(
            int(d.id) for d in mesh.devices.flat)

    def limit(self, capacity: int) -> int:
        return min(self.limit_fn(capacity), capacity - self.n_recv)

    def _loop(self, capacity: int, donate: bool = False):
        memo_key = (capacity, donate)
        if memo_key not in self._loops:
            build = lambda: build_dist_loop(  # noqa: E731
                self.mesh, self.tables, self.make_local_step,
                self.balance_period, self.transfer_cap, self.min_transfer,
                limit=self.limit(capacity), donate_pools=donate)
            if self.loop_cache is not None:
                # consult the shared cache ONCE per driver+capacity (the
                # local memo absorbs the per-segment lookups), so its
                # hit/miss counters read as requests-that-reused /
                # actual-compiles
                key = self.loop_key + (capacity, self.balance_period,
                                       self.transfer_cap,
                                       self.min_transfer,
                                       self.limit(capacity))
                if donate:
                    # a donating executable has different buffer-alias
                    # semantics: it must never be handed to a caller
                    # that expects its inputs to survive
                    key = key + ("donate",)
                self._loops[memo_key] = self.loop_cache.get_or_build(
                    key, build)
            else:
                self._loops[memo_key] = build()
        return self._loops[memo_key]

    def commit(self, state: SearchState) -> SearchState:
        """Commit host-built state leaves to the mesh."""
        return SearchState(*(_to_mesh(self.mesh, s, x)
                             for s, x in zip(self.spec_state, state)))

    @staticmethod
    def _cap(bound_cap) -> jnp.ndarray:
        return jnp.asarray(I32_MAX if bound_cap is None else bound_cap,
                           jnp.int32)

    def run(self, state: SearchState, max_iters=None,
            bound_cap=None) -> SearchState:
        """Run until exhaustion or the cumulative per-worker iteration
        ceiling, growing pools and resuming on overflow. `bound_cap`
        (optional) is folded into the incumbent at loop entry — the
        cross-request incumbent-sharing input (None = I32_MAX = the
        identity fold)."""
        from . import checkpoint

        ceiling = (np.iinfo(np.int64).max if max_iters is None
                   else int(max_iters))
        while True:
            capacity = state.prmu.shape[-1]
            out = SearchState(*self._loop(capacity)(
                self.tables, jnp.asarray(ceiling, jnp.int64),
                self._cap(bound_cap), *state))
            if not bool(_fetch(out.overflow).any()):
                return out
            grown = checkpoint.grow(fetch_state(out), capacity * 2)
            state = self.commit(grown)

    def run_async(self, state: SearchState, max_iters,
                  bound_cap=None) -> SearchState:
        """Dispatch ONE compiled-loop invocation and return its output
        futures WITHOUT blocking — the overlapped segment driver's
        dispatch hook. The pool leaves of `state` are DONATED (the
        while-loop carry aliases them; zero copies in flight), so the
        caller must not touch state.prmu/depth/aux afterwards; the
        scalar counter leaves stay fetchable. Overflow is NOT checked
        here — the overlapped driver reads the flag from its async
        counter fetch and recovers via grow_fn."""
        capacity = state.prmu.shape[-1]
        with _warnings.catch_warnings():
            _warnings.filterwarnings(
                "ignore", message="Some donated buffers were not usable")
            return SearchState(*self._loop(capacity, donate=True)(
                self.tables, jnp.asarray(int(max_iters), jnp.int64),
                self._cap(bound_cap), *state))

    def seed(self, frontier: Frontier, capacity: int, jobs: int,
             init_best: int) -> SearchState:
        """Stripe a warm-up frontier across the workers, pre-growing the
        pool until a stripe fits under the usable-row limit."""
        n_dev = self.mesh.devices.size
        stripe = -(-max(len(frontier.depth), 1) // n_dev)
        while self.limit(capacity) < max(stripe, 1):
            capacity *= 2
        state = _shard_frontier(frontier, n_dev, capacity, jobs, init_best,
                                limit=self.limit(capacity))
        return self.commit(SearchState(*state))

    # -------------------------------------------------- AOT pre-warm

    def abstract_state(self, jobs: int, aux_rows: int, aux_dtype,
                       capacity: int) -> SearchState:
        """The loop's state signature as jax.ShapeDtypeStructs — the
        serializable lowering inputs the boot pre-warm compiles from
        (no pool allocation, no search). Shardings are pinned to the
        worker axis explicitly: abstract lowering would otherwise pick
        a replicated sharding for zero-sized leaves (the telemetry
        block when the flag is off) and the executable would then
        reject the real, axis-sharded calls."""
        from jax.sharding import NamedSharding
        n_dev = self.mesh.devices.size
        shard = NamedSharding(self.mesh, P(AX))

        def sds(shape, dt):
            return jax.ShapeDtypeStruct(shape, jnp.dtype(dt),
                                        sharding=shard)

        # honor the x64 config the same way the real zeros do
        i64 = jnp.zeros((), jnp.int64).dtype
        counters = {f: sds((n_dev,), i64)
                    for f in ("tree", "sol", "iters", "evals", "sent",
                              "recv", "steals")}
        return SearchState(
            prmu=sds((n_dev, jobs, capacity), jnp.int16),
            depth=sds((n_dev, capacity), jnp.int16),
            aux=sds((n_dev, aux_rows, capacity), aux_dtype),
            size=sds((n_dev,), jnp.int32),
            best=sds((n_dev,), jnp.int32),
            overflow=sds((n_dev,), jnp.bool_),
            telemetry=sds((n_dev, tele.enabled_width()), i64),
            **counters)

    def warm(self, capacity: int, jobs: int, aux_rows: int, aux_dtype,
             donate: bool = False, via: str = "prewarm") -> str:
        """Ready the compiled loop for `capacity` WITHOUT running a
        search: disk-deserialize when the AOT cache holds the key, else
        compile from abstract shapes (and persist). Returns the
        executor entry's warm verdict ("warm"/"disk"/"compile"/
        "skipped"); "skipped" when no executor cache is injected (a
        plain jit build has nothing to pre-ready) or the AOT path
        rejects the program. `via` labels the ledger record ("prewarm"
        boot warms, "ladder" rung pre-readies) — both are PLANNED
        compiles the health layer's compile_storm must not count."""
        entry = self._loop(capacity, donate=donate)
        warm_fn = getattr(entry, "warm", None)
        if warm_fn is None:
            return "skipped"
        from jax.sharding import NamedSharding
        repl = NamedSharding(self.mesh, P())
        abs_tables = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(np.shape(x),
                                           jnp.asarray(x).dtype,
                                           sharding=repl),
            self.tables)
        max_iters = jax.ShapeDtypeStruct(
            (), jnp.zeros((), jnp.int64).dtype, sharding=repl)
        bound_cap = jax.ShapeDtypeStruct((), jnp.dtype(jnp.int32),
                                         sharding=repl)
        state = self.abstract_state(jobs, aux_rows, aux_dtype, capacity)
        return warm_fn(abs_tables, max_iters, bound_cap, *state, via=via)


def _resolve_problem(problem):
    """Registry-name-or-plugin-object -> plugin object (lazy import:
    the problems package imports engine modules from inside methods)."""
    if isinstance(problem, str):
        from .. import problems as problems_pkg
        return problems_pkg.get(problem)
    return problem


def _problem_driver(problem, mesh, tables, table, lb_kind: int,
                    chunk: int, balance_period: int, transfer_cap: int,
                    min_transfer: int, adt, loop_cache,
                    limit_fn=None, fused: str = "off") -> "_DistDriver":
    """ONE construction shared by the serving path (search) and the
    boot pre-warm (prewarm), for ANY registered problem: the loop key
    and every trace-specializing knob come from here, so a pre-warmed
    executable is key-identical to the one a real request at the same
    knobs builds — a warm that readied a different key would be pure
    waste. The key leads with the problem's registry name plus the pool
    width and the table's leading dimension — together they pin the
    instance-table SHAPE (the trace specialization; values are runtime
    arguments) for every registered problem, and PFSP keys keep their
    pre-plugin ``pfsp/jobs/machines/...`` layout (test-pinned; persisted
    AOT entries stay addressable), so two problems can never alias one
    executable. `limit_fn` overrides the usable-row bound (the
    chunk-ladder passes the unified across-rung limit; None = this
    chunk's own row_limit)."""
    jobs = problem.slots(table)
    if not getattr(problem, "supports_fused", False):
        # a problem whose make_step IGNORES the mode must not key two
        # program-identical executables apart (or invalidate its warm
        # AOT entries when the knob flips between boots)
        fused = "off"

    def make_local_step(t, limit):
        return problem.make_step(t, lb_kind, chunk, 1024, limit,
                                 fused=fused)

    # the fused mode joins the key only when ON, so every persisted
    # AOT/executor entry of the unfused route keeps its exact pre-fused
    # identity (the same suffix discipline as the megabatch batch dim)
    return _DistDriver(
        mesh, tables, make_local_step, balance_period, transfer_cap,
        min_transfer,
        limit_fn=limit_fn or (lambda cap: problem.usable_rows(cap, chunk,
                                                              jobs)),
        loop_cache=loop_cache,
        loop_key=(problem.name, jobs, int(np.asarray(table).shape[0]),
                  lb_kind, chunk, str(adt))
        + (("fused", fused) if fused != "off" else ()))


def _ladder_plan(problem, mesh, tables, table, lb_kind: int, chunk: int,
                 balance_period: int, transfer_cap: int | None,
                 min_transfer: int | None, adt, loop_cache,
                 rung_profile=None, fused_mode: str = "off"
                 ) -> tuple[tuple, dict]:
    """One _DistDriver per chunk-ladder rung (engine/ladder.rungs_for),
    all built against a UNIFIED usable-row limit: the minimum over
    rungs of each rung's own scratch-margin + balance-headroom bound.
    A state committed by ANY rung is then in-bounds for every other
    rung, so the controller may switch in either direction at a
    segment boundary without an out-of-bounds block write ever being
    possible (the clamp of a dynamic_update_slice would corrupt live
    rows silently — this invariant is what makes switching safe, see
    engine/ladder.py).

    `transfer_cap` / `min_transfer` are the CALLER's explicit values
    (applied to every rung when given — a cap sized for the tuned
    chunk over-reserves for the small rungs, which is safe); None
    derives each rung's own (the byte-budget rule / 2*chunk).

    Shared by search() and prewarm() so a boot-warmed rung executable
    is key-identical to the one a ladder search builds.

    `rung_profile` (tune/defaults Params.rung_modes — the tuner's
    per-rung probe results) replaces the STATIC per-bound rung floor
    with measured admission (ladder.rungs_from_profile: a rung joins
    only when its probed ms/iter beats the tuned chunk's — subsuming
    the PR-9 LB2>=256 constant for probed shapes) and selects each
    rung's kernel-vs-matmul pipeline (ladder.fused_for) under the
    `fused_mode` master switch."""
    from .ladder import (fused_for, min_rung_for, rungs_for,
                         rungs_from_profile)

    jobs, aux_rows = problem.slots(table), problem.aux_rows(table)
    n_dev = mesh.devices.size
    rungs = rungs_from_profile(chunk, rung_profile,
                               fused_mode=fused_mode)
    if rungs is None:
        rungs = rungs_for(chunk, min_chunk=min_rung_for(lb_kind))
    cfgs = []
    for c in rungs:
        tc = (transfer_cap if transfer_cap is not None
              else default_transfer_cap(c, jobs, aux_rows, n_dev,
                                        aux_itemsize=adt.itemsize))
        mt = min_transfer if min_transfer is not None else 2 * c
        cfgs.append((c, tc, mt))

    def unified_limit(cap: int) -> int:
        return min(min(problem.usable_rows(cap, c, jobs),
                       cap - n_dev * tc)
                   for c, tc, _ in cfgs)

    drivers = {
        c: _problem_driver(problem, mesh, tables, table, lb_kind, c,
                           balance_period, tc, mt, adt, loop_cache,
                           limit_fn=unified_limit,
                           fused=fused_for(c, rung_profile, fused_mode))
        for c, tc, mt in cfgs}
    return tuple(sorted(drivers)), drivers


def prewarm(p_times: np.ndarray, lb_kind: int = 1, chunk: int = 64,
            capacity: int | None = None, balance_period: int = 4,
            min_seed: int = 32, n_devices: int | None = None,
            mesh=None, transfer_cap: int | None = None,
            min_transfer: int | None = None, loop_cache=None,
            donate: bool = False, ladder: bool | None = None,
            problem="pfsp", rung_profile=None) -> str:
    """Ready the distributed loop's executable for this shape WITHOUT
    running a search — the serve-boot pre-warm entry (cli `serve
    --prewarm` / SearchServer.prewarm_boot drive it per submesh and
    shape family). Only the SHAPE and dtypes of `p_times` matter (the
    tables are runtime arguments of the compiled loop): a synthetic
    table in the Taillard value range warms the executable every real
    instance of the class will reuse.

    Returns the warm verdict: "disk" (deserialized from the AOT cache,
    zero compiles), "compile" (fresh compile, persisted when an AOT
    cache rides the executor cache), "warm" (already ready —
    idempotent), or "skipped" (no executor cache / AOT path rejected /
    multi-controller).

    `ladder` (None = the TTS_LADDER env flag): when the chunk ladder is
    on, every rung's executable is warmed — key-identically to what a
    ladder search builds (_ladder_plan is shared) — so a served
    request's mid-search rung switch never stalls on a compile. The
    returned verdict is the tuned (top) rung's."""
    from ..utils import config as _cfg

    if jax.process_count() > 1:
        return "skipped"   # multi-controller warm needs rank
        # coordination (the pod-scale arc, ROADMAP item 1)
    if mesh is None:
        mesh = worker_mesh(n_devices)
    prob = _resolve_problem(problem)
    table = np.asarray(p_times)
    jobs, aux_rows = prob.slots(table), prob.aux_rows(table)
    if capacity is None:
        capacity = prob.default_capacity(table)
    tables = prob.make_tables(table)
    adt = prob.aux_dtype(table)
    if ladder is None:
        ladder = _cfg.env_flag(_cfg.LADDER_FLAG)
    # the fused-route mode joins the executable key (_problem_driver),
    # so the warm must resolve it exactly as a real request would —
    # warming the unfused key under TTS_FUSED=1 would be pure waste.
    # `rung_profile` (the tuned entry's rung_modes mask, when the
    # caller resolved one) must ride along for the same reason: a
    # profile changes both the rung SET (rungs_from_profile) and each
    # rung's fused suffix (fused_for), so warming without it would
    # build keys a tuned dispatch never asks for.
    fused_mode = pallas_fused.resolve_mode(None)
    drivers = None
    if ladder:
        rungs, drivers = _ladder_plan(
            prob, mesh, tables, table, lb_kind, chunk, balance_period,
            transfer_cap, min_transfer, adt, loop_cache,
            rung_profile=rung_profile, fused_mode=fused_mode)
        if len(rungs) < 2:
            drivers = None             # single rung: plain path
    if drivers is not None:
        driver = drivers[max(drivers)]
    else:
        from .ladder import fused_for
        if transfer_cap is None:
            transfer_cap = default_transfer_cap(
                chunk, jobs, aux_rows, mesh.devices.size,
                aux_itemsize=adt.itemsize)
        min_transfer = min_transfer or 2 * chunk
        driver = _problem_driver(prob, mesh, tables, table, lb_kind,
                                 chunk, balance_period, transfer_cap,
                                 min_transfer, adt, loop_cache,
                                 fused=fused_for(chunk, rung_profile,
                                                 fused_mode))
    # mirror seed()'s capacity pre-grow rule with the warm-up target as
    # the stripe estimate: at production capacities the loop never
    # fires (limit >> min_seed); at toy capacities it keeps the warmed
    # key aligned with what a fresh request would actually build
    while driver.limit(capacity) < max(min_seed, 1):
        capacity *= 2
    with tracelog.span("executor.prewarm", problem=prob.name, jobs=jobs,
                       machines=aux_rows, lb_kind=lb_kind, chunk=chunk,
                       capacity=capacity, donate=donate,
                       ladder=bool(drivers)) as sp:
        how = driver.warm(capacity, jobs, aux_rows, adt, donate=donate)
        if drivers is not None:
            for c, d in drivers.items():
                if d is not driver:
                    d.warm(capacity, jobs, aux_rows, adt,
                           donate=donate, via="ladder")
        sp.set(how=how)
    return how


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           n_devices: int | None = None, chunk: int | None = 64,
           capacity: int = 1 << 17, balance_period: int | None = 4,
           transfer_cap: int | None = None, min_transfer: int | None = None,
           min_seed: int = 32, max_rounds: int | None = None,
           tables: BoundTables | None = None, mesh=None,
           segment_iters: int | None = None,
           checkpoint_path: str | None = None,
           checkpoint_every: int = 1,
           heartbeat=None, host_fraction: int = 0,
           host_threads: int = 0,
           stop_event=None, should_stop=None,
           loop_cache=None, checkpoint_meta_extra=None,
           overlap: bool | None = None,
           incumbent_board=None, incumbent_key=None,
           ladder: bool | None = None, tuner=None,
           problem="pfsp") -> DistResult:
    """Distributed B&B over all available devices (the flagship engine;
    capability parity with pfsp_dist_multigpu_cuda.c's pfsp_search).

    `balance_period=4` is a MEASURED default (round 4): on real TPU
    hardware the cond-gated balance round is free — the full SPMD
    program costs 6.40 ms/iter at period 4 vs 6.64 at period 1 and
    6.53 at period 16 on identical ta021 state
    (tools/bench_balance_period.py, ±2% noise) — so the period is
    chosen for SPREAD, where the CPU-mesh sensitivity table
    (BENCHMARKS.md) shows per-worker tree CV 0.16 at period 4 vs 0.20
    at period 16. The CPU mesh's wall-clock preference for sparse
    periods is an artifact of host-serialized collectives; do not
    retune this knob on the virtual mesh.

    With `segment_iters`/`checkpoint_path` the loop runs in bounded
    segments with heartbeat + checkpoint/resume between them — the
    distributed durability layer the reference lacks entirely (its only
    stall tooling is a 10-second "Still Idle" print, dist:663-668). A
    checkpoint written here re-loads with its warm-up counters, so a
    resumed run's totals match an uninterrupted one exactly.

    `host_fraction > 0` runs the `-C` heterogeneous host tier BESIDE the
    device mesh (the reference's CPU workers inside the distributed
    flagship, dist:471-741): a native async session seeded with every
    host_fraction-th warm-up node (on resume: rows carved off the top of
    the checkpointed pools), incumbents merged both ways at every
    segment boundary — a host tier forces segmented execution so the
    exchange points exist.

    Resume is ELASTIC: a checkpoint written by an N-worker mesh loads
    on whatever mesh is available — the pools are resharded
    (checkpoint.reshard_state: concatenate + water-fill) when worker
    counts differ, so a preempted job restarts on a smaller or larger
    slice with no explored node lost. A torn/corrupt current snapshot
    rolls back to its rotating last-good sibling
    (checkpoint.load_resilient) instead of poisoning the run.

    Service hooks (service/server.py drives these): `stop_event` (any
    object with is_set()) and/or `should_stop(SegmentReport)` force
    segmented execution and stop the search cleanly at the next segment
    boundary — with a `checkpoint_path` the final state is saved first,
    so a preempted request later RESUMES (possibly on a different-sized
    submesh via the elastic reshard) instead of restarting.
    `loop_cache` (get_or_build(key, build)) shares the compiled SPMD
    loop across searches with identical specialization — the
    serve-many-compile-once path (service/executors.ExecutorCache).
    `checkpoint_meta_extra` (dict or callable returning one) is merged
    into every checkpoint's meta — the service rides its cumulative
    spent_s clock on it so compute budgets survive preempt/resume
    across server lifetimes.

    `overlap` (None = the TTS_OVERLAP env flag) pipelines segmented
    execution: the next segment is dispatched — donated pool carries —
    before the previous segment's counters are fetched, and checkpoint
    serialization moves to a writer thread, so the device never idles
    on the host between segments (checkpoint.run_segmented's overlap
    contract; bit-identical node accounting on or off). Forced off
    beside a `-C` host tier (its per-segment incumbent merge needs the
    synchronous boundary) and under multi-controller JAX.

    `incumbent_board` / `incumbent_key` (service-provided; see
    engine/incumbent.py) joins this search to the cross-request
    best-bound exchange: every segment boundary publishes the current
    best and folds the board's global best in as the next segment's
    pruning ceiling — a traced input, never a retrace, monotone-only
    by construction (and audited). `incumbent_key` defaults to the
    instance's content hash.

    `chunk=None` / `balance_period=None` defers the knob to ADAPTIVE
    resolution: a persisted tuned entry when a `tuner`
    (tune/tuner.Autotuner) is supplied, else the measured-defaults
    table (tune/defaults.py) — never a probe on this path (the tuner's
    request-time tier is cache-or-defaults; probing happens at
    boot/bench time).

    `ladder` (None = the TTS_LADDER env flag; default off) enables
    CHUNK-LADDER execution on the segmented path: 2-3 pre-built chunk
    rungs (engine/ladder.rungs_for — each its own ExecutorCache/AOT
    entry, no retrace at runtime) with the rung switched only at
    segment boundaries, driven by the per-segment pool-occupancy
    signal, so ramp-up and drain run small-chunk steps instead of
    underfilled tuned-chunk ones. Off is bit-identical to the
    pre-ladder driver (the flag never reaches this path); on, a
    fixed-incumbent run explores the identical node set and every
    audit invariant holds across switches (tests pin TTS_AUDIT_HARD).
    The live rung rides checkpoint meta (``ladder_rung``) so resume
    replays on the recorded rung. Ladder yields to a `-C` host tier
    and to multi-controller meshes (like overlap), and engages only
    when segmented execution runs — it switches at segment
    boundaries, and a one-shot exhaustion run has none. A rung's loop
    grown past its pre-warmed capacity (overflow recovery) recompiles
    lazily on its next use, booked as a normal unplanned compile.

    `problem` (registry name or plugin object, default "pfsp") selects
    the workload: `p_times` is then the problem's 2-D instance table
    (problems/base.py documents the per-problem format), the plugin
    supplies the step pipeline / warm-up / aux seeding, and every
    executable/tuning/checkpoint key carries the problem name. A
    checkpoint records its problem and a cross-problem resume is
    REFUSED — a pool of TSP tours re-homed under a PFSP step would be
    silent garbage. The `-C` host tier follows plugin opt-in
    (supports_host_tier): PFSP gets the native runtime, TSP/knapsack
    the generic host_children session (hybrid.PyHostSession);
    host_fraction > 0 for a problem without one raises the typed
    problems/base.HostTierUnsupported."""
    from ..utils import config as _cfg
    from . import checkpoint, hybrid, incumbent as inc_mod

    prob = _resolve_problem(problem)
    table = np.asarray(p_times)
    if mesh is None:
        mesh = worker_mesh(n_devices)
    n_dev = mesh.devices.size
    jobs = prob.slots(table)
    if host_fraction > 0 and not prob.supports_host_tier:
        from ..problems.base import HostTierUnsupported
        raise HostTierUnsupported(prob.name)
    rung_profile = None
    fused_mode = pallas_fused.resolve_mode(None)
    if chunk is None or balance_period is None:
        # adaptive-dispatch resolution for the knobs the caller left
        # open: tuned cache entry (zero probes — the hot path must
        # never probe) else the measured-defaults table
        from ..tune import defaults as tune_defaults
        if tuner is not None:
            params = tuner.resolve(jobs, table.shape[0], lb_kind,
                                   n_workers=n_dev, allow_probe=False,
                                   problem=prob.name)
        else:
            params = tune_defaults.params_for("serving", jobs,
                                              table.shape[0],
                                              problem=prob.name)
        if chunk is None:
            chunk = params.chunk
            if transfer_cap is None and params.transfer_cap:
                transfer_cap = params.transfer_cap
        if balance_period is None:
            balance_period = params.balance_period
        # the tuner's per-rung kernel-vs-matmul profitability mask
        # (Params.rung_modes) rides into rung construction below
        rung_profile = params.rung_modes
        tracelog.event("tuner.resolve", chunk=chunk,
                       balance_period=balance_period,
                       source=params.source,
                       evals_per_s=params.evals_per_s,
                       fused=fused_mode,
                       rung_profile=bool(rung_profile))
    if tables is None:
        tables = prob.make_tables(table)
    adt = prob.aux_dtype(table)
    resumed = None
    if checkpoint_path and checkpoint.resume_path(checkpoint_path):
        # load BEFORE sizing the balance buffers: resume keeps the
        # SAVED pools' aux dtype (an old int32-aux checkpoint stays
        # int32, and a pre-aux legacy file is RECONSTRUCTED as int32 by
        # checkpoint.load), so the byte budget must be priced off the
        # loaded state, not the fresh-run dtype
        resumed = checkpoint.load_resilient(
            checkpoint_path,
            p_times=table if prob.name == "pfsp" else None)[:2]
        # a snapshot records its problem (pre-stamp legacy snapshots
        # are all PFSP); a cross-problem resume is refused — the pool
        # rows only mean anything under the problem that wrote them
        saved_prob = resumed[1].get("problem")
        saved_prob = ("pfsp" if saved_prob is None
                      else str(np.asarray(saved_prob)))
        if saved_prob != prob.name:
            raise ValueError(
                f"checkpoint {checkpoint_path} was written by problem "
                f"{saved_prob!r}; refusing to resume it as "
                f"{prob.name!r} (pick a fresh tag/checkpoint path)")
        adt = np.asarray(resumed[0].aux).dtype
    if ladder is None:
        ladder = _cfg.env_flag(_cfg.LADDER_FLAG)
    # the ladder switches at segment boundaries, so it engages only
    # when segmented execution will run; a host tier keeps the single
    # driver (its per-segment merge is enough moving parts) and
    # multi-controller stays on the one-loop path, like overlap
    use_ladder = (bool(ladder)
                  and (segment_iters is not None
                       or checkpoint_path is not None
                       or stop_event is not None
                       or should_stop is not None)
                  and host_fraction == 0
                  and jax.process_count() == 1)
    ladder_drivers = None
    if use_ladder:
        # rung drivers get the caller's EXPLICIT transfer knobs (None
        # derives per rung) and one unified limit — see _ladder_plan
        rungs, ladder_drivers = _ladder_plan(
            prob, mesh, tables, table, lb_kind, chunk, balance_period,
            transfer_cap, min_transfer, adt, loop_cache,
            rung_profile=rung_profile, fused_mode=fused_mode)
        if len(rungs) < 2:
            ladder_drivers = None      # chunk too small to ladder:
            #                            plain single-driver path
    if transfer_cap is None:
        transfer_cap = default_transfer_cap(chunk, jobs,
                                            prob.aux_rows(table),
                                            mesh.devices.size,
                                            aux_itemsize=adt.itemsize)
    min_transfer = min_transfer or 2 * chunk

    if ladder_drivers is not None:
        driver = ladder_drivers[chunk]   # the tuned top rung — also
        #   the seed/resume/commit driver (all rungs share its limit)
    else:
        from .ladder import fused_for
        driver = _problem_driver(prob, mesh, tables, table, lb_kind,
                                 chunk, balance_period, transfer_cap,
                                 min_transfer, adt, loop_cache,
                                 fused=fused_for(chunk, rung_profile,
                                                 fused_mode))

    session = None
    meta_rung = None          # the checkpoint's recorded ladder rung
    h_prmu = np.zeros((0, jobs), np.int16)
    h_depth = np.zeros(0, np.int16)
    if resumed is not None:
        host_state, meta = resumed
        if "ladder_rung" in meta:
            # resume replays on the rung the checkpoint recorded: the
            # pool snapshot alone would misread a mid-ramp save
            meta_rung = int(np.asarray(meta["ladder_rung"]))
        shape = np.asarray(host_state.prmu).shape
        if len(shape) != 3 or shape[0] != n_dev:
            # elastic resume: re-split the snapshot's pools across THIS
            # mesh (preemption rarely hands back the same topology)
            old_workers = shape[0] if len(shape) == 3 else 1
            import warnings
            warnings.warn(
                f"resharding checkpoint {checkpoint_path} from "
                f"{old_workers} to {n_dev} workers (elastic resume)",
                RuntimeWarning, stacklevel=2)
            # audit hook: the elastic reshard must conserve every
            # summed counter, the pooled node count and the incumbent
            # (obs/audit — a drift here is silent wrong answers later)
            pre_sums = (obs_audit.state_sums(host_state)
                        if obs_audit.enabled() else None)
            host_state = checkpoint.reshard_state(host_state, n_dev)
            if pre_sums is not None:
                obs_audit.check_reshard(pre_sums, host_state,
                                        edge="elastic_resume")
        # re-home into a capacity whose usable-row limit (scratch margin
        # + balance headroom) covers the fullest resharded pool
        cap0 = cap = host_state.prmu.shape[-1]
        need = int(np.asarray(host_state.size).max())
        while driver.limit(cap) < max(need, 1):
            cap *= 2
        if cap != cap0:
            host_state = checkpoint.grow(host_state, cap)
        # a checkpoint written by a -C run carries the host tier's seed
        # nodes (they were carved OUT of the pools): resume must either
        # re-seed the session from them or push them back — dropping
        # them would silently lose subtrees
        saved_p = np.asarray(meta.get("host_prmu",
                                      np.zeros((0, jobs))), np.int16)
        saved_d = np.asarray(meta.get("host_depth", np.zeros(0)),
                             np.int16)
        if host_fraction > 0:
            if len(saved_d):
                h_prmu, h_depth = saved_p, saved_d
            else:
                host_state, h_prmu, h_depth = hybrid.pop_host_share(
                    host_state, host_fraction)
            if len(h_depth):
                session = hybrid.make_session(
                    prob, table, h_prmu, h_depth, lb_kind,
                    int(np.asarray(host_state.best).min()),
                    n_threads=host_threads)
        elif len(saved_d):
            host_state = hybrid.restore_host_share(
                host_state, saved_p, saved_d, table, problem=prob)
        fr = Frontier(prmu=np.zeros((0, jobs), np.int16),
                      depth=np.zeros(0, np.int16),
                      tree=int(meta.get("warmup_tree", 0)),
                      sol=int(meta.get("warmup_sol", 0)),
                      best=int(np.asarray(host_state.best).min()))
        state = driver.commit(host_state)
    else:
        with tracelog.span("bfs_warmup", problem=prob.name,
                           target=min_seed * n_dev) as ws:
            fr = prob.warmup(table, lb_kind, init_ub,
                             target=min_seed * n_dev)
            ws.set(frontier=len(fr.depth), tree=fr.tree)
        init_best = (fr.best if init_ub is None
                     else min(fr.best, int(init_ub)))
        dmask, h_prmu, h_depth = hybrid.split_host_share(
            fr.prmu, fr.depth, host_fraction)
        if len(h_depth):
            session = hybrid.make_session(prob, table, h_prmu, h_depth,
                                          lb_kind, init_best,
                                          n_threads=host_threads)
            fr.prmu, fr.depth = fr.prmu[dmask], fr.depth[dmask]
        fr.aux = prob.seed_aux(table, fr.prmu, fr.depth)
        state = driver.seed(fr, capacity, jobs, init_best)

    if overlap is None:
        overlap = _cfg.env_flag(_cfg.OVERLAP_FLAG)
    # the host tier's per-segment incumbent merge (post_segment) needs
    # the synchronous boundary; overlap yields to it. Multi-controller
    # must also stay sync HERE, not only in run_segmented's own guard:
    # the choice of run_fn below follows use_overlap, and handing the
    # sync driver the donating non-growing run_async would turn every
    # overflow into a hard PoolOverflow instead of a lossless grow.
    use_overlap = (bool(overlap) and session is None
                   and jax.process_count() == 1)

    ladder_ctl = None
    if ladder_drivers is not None:
        from .ladder import RungController
        ladder_ctl = RungController(ladder_drivers, n_dev)
        ladder_ctl.start(int(np.atleast_1d(_fetch(state.size)).sum()),
                         meta_rung=meta_rung)
        # Pre-ready EVERY rung — the current one included — from
        # abstract shapes, so a mid-search switch never stalls on a
        # fresh trace+compile and all rung compiles are booked as
        # PLANNED (via="ladder": the compile_storm rule must not read
        # a ladder boot as executable-reuse breaking). Warming all
        # rungs is also a CORRECTNESS requirement on the AOT path, not
        # just a latency one: abstract warms pin every input/output to
        # the explicit worker-axis sharding (_DistDriver.
        # abstract_state), so any rung's output state feeds any other
        # rung's strict AOT executable; an entry compiled from REAL
        # first-call args instead infers a replicated sharding for the
        # zero-width telemetry leaf and then REJECTS the cross-rung
        # handoff ("input sharding does not match") — a booked jit
        # fallback, correct but a silent perf and accounting loss.
        cap_now = int(state.prmu.shape[-1])
        for c, d in ladder_drivers.items():
            d.warm(cap_now, jobs, prob.aux_rows(table), adt,
                   donate=use_overlap, via="ladder")

    client = None
    if incumbent_board is not None:
        client = inc_mod.BoardClient(
            incumbent_board,
            incumbent_key or inc_mod.share_key(table,
                                               problem=prob.name))
        # seed the exchange with this search's starting incumbent (a
        # resumed checkpoint's best, or the warm-up/init_ub bound) so
        # same-instance peers tighten before our first segment lands
        client.publish(int(np.atleast_1d(_fetch(state.best)).min()))

    max_iters = (None if max_rounds is None
                 else max_rounds * balance_period)
    stop_fn = None
    if stop_event is not None or should_stop is not None:
        def stop_fn(rep):
            return ((stop_event is not None and stop_event.is_set())
                    or (should_stop is not None and should_stop(rep)))
    if (segment_iters is None and checkpoint_path is None
            and session is None and stop_fn is None):
        # the segmented path below is spanned per segment inside
        # run_segmented; this is the only otherwise-unobserved run shape
        with tracelog.span("engine.run", workers=n_dev):
            out = driver.run(state, max_iters,
                             bound_cap=client.cap() if client else None)
    else:
        ckpt_meta = {"warmup_tree": fr.tree, "warmup_sol": fr.sol,
                     # the snapshot's problem stamp: resume refuses a
                     # cross-problem re-home (checked above)
                     "problem": prob.name,
                     # the host tier's seed rides every checkpoint so a
                     # killed -C run can be resumed without losing the
                     # carved subtrees (re-exploring the share from its
                     # seed is exactly-once: the killed session's work
                     # was never committed anywhere)
                     "host_prmu": (h_prmu if session else
                                   np.zeros((0, jobs), np.int16)),
                     "host_depth": (h_depth if session else
                                    np.zeros(0, np.int16))}
        if checkpoint_meta_extra is not None:
            base_meta = ckpt_meta

            def ckpt_meta():
                extra = (checkpoint_meta_extra()
                         if callable(checkpoint_meta_extra)
                         else checkpoint_meta_extra)
                return {**base_meta, **extra}

        if ladder_ctl is not None:
            # the rung for the NEXT segment was chosen at the last
            # boundary (hb's observe below); every rung driver shares
            # the unified limit, so switching never invalidates the
            # carried state
            base_meta0 = ckpt_meta

            def ckpt_meta():
                base = (base_meta0() if callable(base_meta0)
                        else dict(base_meta0))
                return {**base, "ladder_rung": ladder_ctl.current_chunk}

        grow_fn = stop_pending = None
        if use_overlap:
            # async dispatch with donated pool carries; overflow
            # recovery and exit draining live in the overlapped driver
            def run_fn(s, target):
                drv = (ladder_ctl.driver() if ladder_ctl is not None
                       else driver)
                return drv.run_async(
                    s, target, bound_cap=client.cap() if client else None)

            def grow_fn(s):
                return driver.commit(checkpoint.grow(
                    fetch_state(s), s.prmu.shape[-1] * 2))

            if stop_event is not None:
                stop_pending = stop_event.is_set
        else:
            def run_fn(s, target):
                drv = (ladder_ctl.driver() if ladder_ctl is not None
                       else driver)
                return drv.run(
                    s, max_iters=target,
                    bound_cap=client.cap() if client else None)

        def hb(rep):
            if ladder_ctl is not None:
                # rung selection for the NEXT dispatch: this boundary's
                # pool-occupancy signal (under overlap the next segment
                # is already in flight, so the switch lands one
                # boundary later — accounting is exact either way)
                ladder_ctl.observe(rep.pool_size, segment=rep.segment)
            # resource-observability heartbeat hook: one device-memory
            # / host-RSS sweep per segment (obs/resource publishes the
            # tts_device_bytes_* gauges and a resource.sample trace
            # event, which Perfetto renders as memory lanes beside the
            # pool/steal counter lanes). Observation-only — a failed
            # sweep must never stop the search.
            try:
                from ..obs import resource as obs_resource
                obs_resource.sample_now()
            except Exception:  # noqa: BLE001
                pass
            if client is not None:
                # the cross-request exchange's publish half: fold this
                # submesh's freshest best into the board every segment
                client.publish(rep.best)
            if heartbeat is not None:
                heartbeat(rep)

        out = checkpoint.run_segmented(
            run_fn, state, segment_iters=segment_iters or 2048,
            checkpoint_path=checkpoint_path, heartbeat=hb,
            checkpoint_every=checkpoint_every,
            max_total_iters=max_iters, checkpoint_meta=ckpt_meta,
            post_segment=(session.post_segment if session else None),
            should_stop=stop_fn, overlap=use_overlap, grow_fn=grow_fn,
            stop_pending=stop_pending)

    h_tree = h_sol = h_expanded = 0
    host_stats = {}
    best = int(_fetch(out.best).min())
    if client is not None:
        client.publish(best)   # the final fold: peers prune against it
    if session is not None:
        session.offer(best)      # freshest device bound before the join
        h_tree, h_sol, h_best, h_expanded = session.join()
        best = min(best, h_best)
        host_stats = {
            "host_tree": [h_tree], "host_sol": [h_sol],
            "host_expanded": [h_expanded],
            "exchanges": [session.exchanges],
            "host_improved": [session.host_improved],
            "dev_improved": [session.dev_improved],
        }

    tree_dev = _fetch(out.tree)
    sol_dev = _fetch(out.sol)
    sizes = _fetch(out.size)
    iters_dev = _fetch(out.iters)
    steals_dev = _fetch(out.steals)
    tracelog.event(
        "engine.complete", workers=n_dev,
        tree=int(tree_dev.sum()) + fr.tree + h_tree, best=best,
        iters=int(iters_dev.max()),
        balance_rounds=int(iters_dev.max()) // max(balance_period, 1),
        steals=int(steals_dev.sum()),
        complete=int(sizes.sum()) == 0)
    telemetry = None
    if out.telemetry.shape[-1] > 0:
        telemetry = tele.summarize(_fetch(out.telemetry))
    res = DistResult(
        explored_tree=int(tree_dev.sum()) + fr.tree + h_tree,
        explored_sol=int(sol_dev.sum()) + fr.sol + h_sol,
        best=best,
        telemetry=telemetry,
        per_device={
            "tree": tree_dev, "sol": sol_dev,
            "iters": iters_dev,
            "evals": _fetch(out.evals),
            "sent": _fetch(out.sent),
            "recv": _fetch(out.recv),
            "steals": steals_dev,
            "final_size": sizes,
            **host_stats,
        },
        warmup_tree=fr.tree, warmup_sol=fr.sol,
        complete=int(sizes.sum()) == 0,
        problem=prob.name,
    )
    if obs_audit.enabled():
        # node-conservation audit on every result (host-side sums over
        # already-fetched counters — microseconds against a search);
        # failures surface as audit.fail events, tts_audit_failures
        # counters and the health layer's `audit` alert (or raise
        # under TTS_AUDIT_HARD=1)
        obs_audit.check_result(res)
    return res
