"""Multi-device distributed PFSP engine: one SPMD program over the mesh.

The reference needs three nested runtimes for this — OpenMP threads per
node (pfsp_multigpu_cuda.c:143), MPI ranks across nodes with a dedicated
communicator thread (pfsp_dist_multigpu_cuda.c:283, 364-469), and CUDA
streams per GPU. Here the whole hierarchy is one `shard_map`ped program
over a 1-D worker mesh: every worker owns a private HBM pool and runs the
same compiled loop; every `balance_period` steps the workers

  - share the incumbent via `pmin` (the per-round Allreduce MIN of
    `best_l`, dist:369-374, and the intra-node `checkBest` CAS,
    pfsp_multigpu_cuda.c:30-50, in one op),
  - rebalance pools via all_gather + all_to_all (see parallel/balance.py),

and the loop predicate `psum(has_work) > 0` *is* the distributed
termination detection (`globalTermination`'s Allgather of has-work flags,
dist:69-88, moved on-device).

Phase schedule mirrors the reference's 3-step scheme (dist:193-205,
864-882): a replicated-cost host BFS warm-up generates a frontier of at
least `min_seed * workers` nodes (step 1), round-robin striding assigns
each worker its stripe (`roundRobin_distribution`, Pool_atom.c:14-36),
the SPMD loop explores (step 2), and exhaustion needs no step-3 drain
because the collective balance keeps feeding idle workers until the
global pool is empty.
"""

from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import PartitionSpec as P

from ..ops import batched, reference as ref
from ..ops.batched import BoundTables
from ..parallel import balance as bal
from ..parallel.mesh import WORKER_AXIS, shard_map, worker_mesh
from . import sequential as seq
from .device import SearchState, row_limit as device_row_limit, step

AX = WORKER_AXIS


# ---------------------------------------------------------------------------
# Step 1: host BFS warm-up (breadth generates parallelism; reference runs
# this replicated on every rank, dist:198-205 — here once on the host)


@dataclasses.dataclass
class Frontier:
    prmu: np.ndarray    # (n, jobs) int16
    depth: np.ndarray   # (n,) int16
    tree: int           # counters accumulated during warm-up
    sol: int
    best: int
    aux: np.ndarray | None = None  # (n, A) int32 per-node pool tables


def bfs_warmup(p_times: np.ndarray, lb_kind: int, init_ub: int | None,
               target: int, use_native: bool = True) -> Frontier:
    """Pop-front BFS until the frontier holds >= target nodes (or the tree
    is exhausted). Same decompose semantics as the oracle, so warm-up
    counters + device counters add up to the sequential totals.

    Uses the native C++ runtime when available (tpu_tree_search/native);
    the pure-Python path below is the validated fallback/oracle.
    """
    if use_native:
        try:
            from .. import native
            prmu, depth, tree, sol, best = native.bfs_frontier(
                p_times, lb_kind, init_ub, target)
            return Frontier(prmu=prmu, depth=depth, tree=tree, sol=sol,
                            best=best)
        except Exception:
            pass  # fall through to the Python implementation
    jobs = p_times.shape[1]
    lb1 = ref.make_lb1_data(p_times)
    lb2 = ref.make_lb2_data(lb1) if lb_kind == seq.LB2 else None
    best = seq.INT_MAX if init_ub is None else int(init_ub)
    tree = sol = 0

    from collections import deque
    frontier: deque[tuple[np.ndarray, int]] = deque(
        [(np.arange(jobs, dtype=np.int16), 0)]
    )
    while frontier and len(frontier) < target:
        prmu, depth = frontier.popleft()
        limit1 = depth - 1
        if lb_kind == seq.LB1_D:
            lb_begin = ref.lb1_children_bounds(lb1, prmu, limit1, jobs)
        for i in range(depth, jobs):
            child = prmu.copy()
            child[depth], child[i] = child[i], child[depth]
            if lb_kind == seq.LB1:
                bound = ref.lb1_bound(lb1, child, limit1 + 1, jobs)
            elif lb_kind == seq.LB1_D:
                bound = int(lb_begin[int(prmu[i])])
            else:
                bound = ref.lb2_bound(lb1, lb2, child, limit1 + 1, jobs, best)
            if depth + 1 == jobs:
                sol += 1
                if bound < best:
                    best = bound
            elif bound < best:
                frontier.append((child, depth + 1))
                tree += 1

    if frontier:
        prmu = np.stack([f[0] for f in frontier]).astype(np.int16)
        depth = np.array([f[1] for f in frontier], dtype=np.int16)
    else:
        prmu = np.zeros((0, jobs), np.int16)
        depth = np.zeros((0,), np.int16)
    return Frontier(prmu=prmu, depth=depth, tree=tree, sol=sol, best=best)


# ---------------------------------------------------------------------------
# Step 2: the SPMD search loop


def _balance_round(s: SearchState, transfer_cap: int,
                   min_transfer: int, limit: int) -> SearchState:
    """One collective steal-half exchange (see parallel/balance.py).
    `limit` is the usable-row bound (device.row_limit) every commit must
    respect so the engine's block writes stay in bounds."""
    J, capacity = s.prmu.shape
    D = jax.lax.psum(1, AX)
    sizes = jax.lax.all_gather(s.size, AX)                  # (D,)
    plan = bal.exchange_plan(sizes, transfer_cap, min_transfer)
    me = jax.lax.axis_index(AX)
    my_out = plan[me]                                       # (D,)
    total_out = my_out.sum(dtype=jnp.int32)

    # pack donated nodes (from the stack top) into per-receiver blocks
    offs = jnp.cumsum(my_out, dtype=jnp.int32) - my_out     # exclusive prefix
    base = s.size - total_out
    k = jnp.arange(transfer_cap, dtype=jnp.int32)
    rows = base + offs[:, None] + k[None, :]                # (D, cap)
    send_mask = k[None, :] < my_out[:, None]
    rows_c = jnp.clip(rows, 0, capacity - 1).reshape(-1)    # (D*cap,)
    buf_prmu = jnp.take(s.prmu, rows_c, axis=1)             # (J, D*cap)
    buf_aux = jnp.take(s.aux, rows_c, axis=1)               # (A, D*cap)
    buf_depth = jnp.where(send_mask.reshape(-1),
                          s.depth[rows_c], -1)[None, :]     # -1 = hole

    # all_to_all exchanges the per-receiver blocks (the D axis must be
    # the split axis exactly)
    def exchange(x):
        rows = x.shape[0]
        blocks = x.reshape(rows, D, transfer_cap)
        return jax.lax.all_to_all(blocks, AX, 1, 1) \
            .reshape(rows, D * transfer_cap)

    rbuf_prmu = exchange(buf_prmu)
    rbuf_aux = exchange(buf_aux)
    rbuf_depth = exchange(buf_depth)

    # push received nodes (compacting column gather + block write onto
    # the new top, same scatter-free scheme as device.step)
    flat_depth = rbuf_depth.reshape(-1)
    push = flat_depth >= 0
    n_push = push.sum(dtype=jnp.int32)
    order = jnp.argsort(~push, stable=True)
    recv_prmu = jnp.take(rbuf_prmu, order, axis=1)
    recv_aux = jnp.take(rbuf_aux, order, axis=1)
    recv_depth = jnp.take(flat_depth, order).astype(jnp.int16)
    new_size = base + n_push
    n_recv = flat_depth.shape[0]
    # The block write needs n_recv free columns above `base`; when it
    # would clamp (or the cursor would pass the limit) the overflow flag
    # aborts the round and the caller restarts with a larger pool — a
    # distributed overflow always restarts from the frontier, so the
    # clamped write never feeds a resumed search.
    ovf = (base + n_recv > capacity) | (new_size > limit)
    zero = jnp.zeros((), base.dtype)
    return s._replace(
        prmu=jax.lax.dynamic_update_slice(s.prmu, recv_prmu, (zero, base)),
        depth=jax.lax.dynamic_update_slice(s.depth, recv_depth, (base,)),
        aux=jax.lax.dynamic_update_slice(s.aux, recv_aux, (zero, base)),
        size=jnp.where(ovf, s.size, new_size),
        sent=s.sent + total_out.astype(jnp.int64),
        recv=s.recv + n_push.astype(jnp.int64),
        steals=s.steals + (n_push > 0).astype(jnp.int64),
        overflow=s.overflow | ovf,
    )


def _local_state(*leaves):
    return SearchState(*(x[0] for x in leaves))


def _expand(s: SearchState):
    return tuple(x[None, ...] for x in s)


def build_dist_loop(mesh, tables, make_local_step,
                    balance_period: int, transfer_cap: int,
                    min_transfer: int, max_rounds: int | None = None,
                    limit: int | None = None):
    """Compile a distributed search loop for any problem: state sharded over
    the worker axis, problem tables replicated. `make_local_step(tables)`
    returns the problem's SearchState -> SearchState step. `limit` is the
    per-worker usable-row bound (device.row_limit); defaults to the full
    pool capacity for steps that reserve no scratch margin."""

    def worker_loop(tables, *state_leaves):
        s = _local_state(*state_leaves)

        def cond(s: SearchState):
            has_work = jax.lax.psum(s.size, AX) > 0
            ok = jax.lax.psum(s.overflow.astype(jnp.int32), AX) == 0
            go = has_work & ok
            if max_rounds is not None:
                go = go & (s.iters < max_rounds * balance_period)
            return go

        local_step = make_local_step(tables)

        def body(s: SearchState):
            s = jax.lax.fori_loop(0, balance_period,
                                  lambda _, x: local_step(x), s)
            s = s._replace(best=jax.lax.pmin(s.best, AX))
            row_bound = s.prmu.shape[-1] if limit is None else limit
            return _balance_round(s, transfer_cap, min_transfer, row_bound)

        return _expand(jax.lax.while_loop(cond, body, s))

    spec_state = tuple(P(AX) for _ in SearchState._fields)
    spec_tables = jax.tree.map(lambda _: P(), tables)
    return jax.jit(shard_map(
        worker_loop, mesh,
        in_specs=(spec_tables,) + spec_state,
        out_specs=spec_state,
    ))


# ---------------------------------------------------------------------------
# Host entry point


class DistResult:
    def __init__(self, explored_tree, explored_sol, best, per_device,
                 warmup_tree, warmup_sol, complete=True):
        self.explored_tree = explored_tree
        self.explored_sol = explored_sol
        self.best = best
        self.per_device = per_device        # dict of (D,) arrays for stats
        self.warmup_tree = warmup_tree
        self.warmup_sol = warmup_sol
        self.complete = complete            # all pools drained


def _shard_frontier(fr: Frontier, n_dev: int, capacity: int, jobs: int,
                    init_best: int, limit: int | None = None):
    """Round-robin stripe the frontier across workers
    (reference: roundRobin_distribution, Pool_atom.c:14-36). `limit`
    (device.row_limit) bounds each stripe so seeding respects the
    engine's usable-row invariant."""
    if limit is None:
        limit = capacity
    aux_w = 0 if fr.aux is None else fr.aux.shape[1]
    prmu = np.zeros((n_dev, jobs, capacity), np.int16)
    depth = np.zeros((n_dev, capacity), np.int16)
    aux = np.zeros((n_dev, aux_w, capacity), np.int32)
    sizes = np.zeros(n_dev, np.int32)
    for d in range(n_dev):
        stripe_p = fr.prmu[d::n_dev]
        stripe_d = fr.depth[d::n_dev]
        n = len(stripe_d)
        assert n <= limit
        prmu[d, :, :n] = stripe_p.T
        depth[d, :n] = stripe_d
        if aux_w:
            aux[d, :, :n] = fr.aux[d::n_dev].T
        sizes[d] = n
    return (
        jnp.asarray(prmu), jnp.asarray(depth), jnp.asarray(aux),
        jnp.asarray(sizes),
        jnp.full((n_dev,), init_best, jnp.int32),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64), jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, jnp.int64),
        jnp.zeros(n_dev, bool),
    )


def _fetch(x) -> np.ndarray:
    """Bring a possibly globally-sharded per-device array to every host.

    Single-controller (the normal case): a plain fetch. Multi-controller
    (--multihost): the output spans non-addressable devices, so gather it
    with multihost_utils (every process ends up with the full (D,) array,
    matching the reference's stats Gather-to-rank-0, dist:817-832, except
    every rank gets the totals)."""
    if jax.process_count() > 1:
        from jax.experimental import multihost_utils
        return np.asarray(multihost_utils.process_allgather(
            x, tiled=False)).reshape(-1)
    return np.asarray(x)


def _to_mesh(mesh, spec_leaf, x):
    """Commit one host-built state leaf to the mesh.

    Multi-controller JAX rejects plain host arrays as jit inputs over a
    global mesh; every process holds the identical global value (the
    warm-up is replicated, like the reference's step 1 on every rank,
    dist:198-205), so build the global array from per-shard callbacks."""
    if jax.process_count() > 1:
        from jax.sharding import NamedSharding
        sharding = NamedSharding(mesh, spec_leaf)
        return jax.make_array_from_callback(
            np.shape(x), sharding, lambda idx: np.asarray(x)[idx])
    return x


def run_with_retry(mesh, tables, make_local_step, frontier: Frontier,
                   capacity: int, chunk: int, jobs: int, init_best: int,
                   balance_period: int, transfer_cap: int,
                   min_transfer: int, max_rounds: int | None,
                   limit_fn) -> SearchState:
    """Seed the mesh from a frontier and run the SPMD loop, growing the
    pool capacity and retrying on overflow (shared by the PFSP and
    N-Queens distributed engines).

    `limit_fn(capacity)` is the per-worker usable-row bound."""
    # a stripe must fit under the usable-row limit: pre-grow rather than
    # fail seeding (the graceful path the overflow retry provides mid-run)
    stripe = -(-max(len(frontier.depth), 1) // mesh.devices.size)
    while limit_fn(capacity) < stripe:
        capacity *= 2

    spec_state = tuple(P(AX) for _ in SearchState._fields)
    while True:
        run = build_dist_loop(mesh, tables, make_local_step, balance_period,
                              transfer_cap, min_transfer, max_rounds,
                              limit=limit_fn(capacity))
        state = _shard_frontier(frontier, mesh.devices.size, capacity, jobs,
                                init_best, limit=limit_fn(capacity))
        state = tuple(_to_mesh(mesh, s, x)
                      for s, x in zip(spec_state, state))
        out = SearchState(*run(tables, *state))
        if not bool(_fetch(out.overflow).any()):
            return out
        capacity *= 2


def search(p_times: np.ndarray, lb_kind: int = 1, init_ub: int | None = None,
           n_devices: int | None = None, chunk: int = 64,
           capacity: int = 1 << 17, balance_period: int = 4,
           transfer_cap: int | None = None, min_transfer: int | None = None,
           min_seed: int = 32, max_rounds: int | None = None,
           tables: BoundTables | None = None, mesh=None) -> DistResult:
    """Distributed B&B over all available devices (the flagship engine;
    capability parity with pfsp_dist_multigpu_cuda.c's pfsp_search)."""
    if mesh is None:
        mesh = worker_mesh(n_devices)
    n_dev = mesh.devices.size
    jobs = p_times.shape[1]
    if tables is None:
        tables = batched.make_tables(p_times)
    transfer_cap = transfer_cap or 4 * chunk
    min_transfer = min_transfer or 2 * chunk

    fr = bfs_warmup(p_times, lb_kind, init_ub, target=min_seed * n_dev)
    fr.aux = ref.prefix_front_remain(
        p_times, fr.prmu, fr.depth)[:, :p_times.shape[0]]
    init_best = fr.best if init_ub is None else min(fr.best, int(init_ub))

    def make_local_step(t):
        return functools.partial(step, t, lb_kind, chunk)

    out = run_with_retry(
        mesh, tables, make_local_step, fr, capacity, chunk, jobs, init_best,
        balance_period, transfer_cap, min_transfer, max_rounds,
        limit_fn=lambda cap: device_row_limit(cap, chunk, jobs))

    tree_dev = _fetch(out.tree)
    sol_dev = _fetch(out.sol)
    sizes = _fetch(out.size)
    return DistResult(
        explored_tree=int(tree_dev.sum()) + fr.tree,
        explored_sol=int(sol_dev.sum()) + fr.sol,
        best=int(_fetch(out.best).min()),
        per_device={
            "tree": tree_dev, "sol": sol_dev,
            "iters": _fetch(out.iters),
            "evals": _fetch(out.evals),
            "sent": _fetch(out.sent),
            "recv": _fetch(out.recv),
            "steals": _fetch(out.steals),
            "final_size": sizes,
        },
        warmup_tree=fr.tree, warmup_sol=fr.sol,
        complete=int(sizes.sum()) == 0,
    )
