from . import taillard, pfsp, nqueens

__all__ = ["taillard", "pfsp", "nqueens"]
