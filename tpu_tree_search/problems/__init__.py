"""Problem plugins: the workload layer of the generic B&B engine.

Importing this package registers the built-in plugins (PFSP, N-Queens,
TSP, 0/1 knapsack) in the registry; `get(name)` is the single
resolution point the engine, service, spool and CLI share. See
problems/base.py for the protocol.
"""

from . import base, knapsack, nqueens, pfsp, taillard, tsp
from .base import BranchOut, Problem, get, names, register

__all__ = ["base", "taillard", "pfsp", "nqueens", "tsp", "knapsack",
           "BranchOut", "Problem", "get", "names", "register"]
