"""Problem-plugin protocol and registry: one generic B&B engine, many
workloads.

The reference cleanly separates problem definition (L1) and bounding
(L2) from the search engine — `Node`/branching and the LB functions are
swappable per problem while the multi-pool DFS core is shared (PAPER.md
layer map). This module is that separation for the TPU engine: a
:class:`Problem` is a *singleton plugin* that tells the problem-blind
pipeline (engine/device.generic_step, engine/distributed.search)
everything problem-specific:

- **static shape spec** from one 2-D instance table (`slots` — the pool
  node width, `aux_rows`/`aux_dtype` — the per-node side tables,
  `shape_class` — the tuning-table key);
- **jittable callables**: `branch` (the dense child grid + evaluated
  mask), `bound` (child bound values; at leaf children the bound must
  equal the exact objective, the PFSP convention), `is_leaf_cols`,
  `make_step` (the optional Pallas fast-path hook — PFSP overrides it
  with the specialized engine/device.step pipeline; the default builds
  engine/device.generic_step from branch/bound);
- **host-side seeding**: `root` / `seed_aux` / `warmup` (the BFS
  frontier generator the distributed seeding consumes);
- **accounting semantics**: `leaf_in_evals` picks between the two
  counting conventions the reference ships — PFSP-style (every
  evaluated leaf child counts as a solution; solutions are never
  pushed) and N-Queens-style (all safe children are pushed, a POPPED
  complete node counts as a solution) — and the node-conservation
  auditor (obs/audit) keys its invariant off it;
- **telemetry labels** for the per-problem observability surface.

THE UNIVERSAL INSTANCE FORMAT is one 2-D integer table (the thing every
transport — spool payloads, the request ledger, checkpoints, HTTP
bodies — already knows how to carry as `p_times`):

=========  ======================  =====================================
problem    table shape             meaning
=========  ======================  =====================================
pfsp       (machines, jobs)        processing times
nqueens    (g, n)                  board size n; g safety-check repeats
                                   ride the SHAPE (static, like every
                                   trace-specializing knob)
tsp        (n, n)                  city distance matrix
knapsack   (3, n)                  rows: weights, values,
                                   [capacity, 0, ...]
=========  ======================  =====================================

Registration is import-time (`problems/__init__` registers the four
built-ins); `get(name)` is the single resolution point the engine,
service and CLI share.
"""

from __future__ import annotations

from typing import Any, NamedTuple

import numpy as np

I32_MAX = 2**31 - 1


class HostTierUnsupported(ValueError):
    """Typed refusal for `-C host_fraction > 0` on a problem whose
    plugin has no host tier (`Problem.supports_host_tier` is False).
    Subclasses ValueError so pre-existing callers that caught the old
    untyped refusal keep working; service admission and the CLI match
    on the type to reject the request instead of crashing the worker."""

    def __init__(self, problem: str):
        self.problem = problem
        super().__init__(
            f"the -C host tier is not supported for problem "
            f"{problem!r} (no host_children/host-session support; "
            f"set supports_host_tier on the plugin to enable it)")


class BranchOut(NamedTuple):
    """One step's dense child grid, feature-major like the pool.

    `children` is (J, C) int16 with C = chunk * branching-factor;
    `evaluated` marks the real child columns (invalid parents and
    below-depth slots are masked off). `extras` is an opaque pytree
    `branch` hands to `bound` so shared intermediates (edge costs,
    feasibility masks) are computed once.
    """

    children: Any        # (J, C) int16
    child_depth: Any     # (C,) int16
    child_aux: Any       # (A, C) int32 (cast to the pool dtype at write)
    evaluated: Any       # (C,) bool
    extras: Any = ()


class Problem:
    """Base plugin. Subclasses are stateless singletons — every
    per-instance quantity must derive from the instance table (values
    at trace time are runtime arguments; anything static must ride the
    table's SHAPE, exactly like jit static args)."""

    name: str = ""
    # PFSP-style accounting (True): every evaluated leaf child counts
    # toward `sol` and leaves are never pushed; the audit identity is
    # branched + pruned + sol == evals. N-Queens-style (False): all
    # surviving children are pushed (complete nodes included), a popped
    # complete node counts as a solution; branched + pruned == evals.
    leaf_in_evals: bool = True
    # the -C heterogeneous host tier (engine/hybrid): PFSP runs the
    # native C++ runtime, other opted-in plugins get the generic
    # Python session over host_children (hybrid.PyHostSession). The
    # engine raises HostTierUnsupported for host_fraction > 0 on a
    # plugin that has not opted in.
    supports_host_tier: bool = False
    # whether make_step consumes the fused Pallas route's mode
    # (ops/pallas_fused — PFSP-only): drivers and tuning-cache keys
    # gate their ("fused", mode) suffix on it, so a problem whose
    # step IGNORES the mode never splits program-identical
    # executables or optima across key variants
    supports_fused: bool = False
    lb_kinds: tuple = (1,)
    default_lb: int = 1
    # children per popped parent; None = slots (permutation problems'
    # dense (chunk, J) child grid). The engine sizes the pool's
    # scratch margin off this, so a low-branching problem (knapsack:
    # 2) does not reserve chunk*J rows it can never write.
    branch_factor: int | None = None
    # identity labels merged into the per-request telemetry gauges
    # (engine/telemetry.publish) so /metrics rows are self-describing
    telemetry_labels: dict = {"objective": "bound"}

    # ------------------------------------------------------ static spec

    def validate(self, table: np.ndarray) -> str | None:
        """Admission-side table validation; a rejection reason or None."""
        raise NotImplementedError

    def slots(self, table: np.ndarray) -> int:
        """Pool node width J (the prmu row length)."""
        raise NotImplementedError

    def aux_rows(self, table: np.ndarray) -> int:
        return 0

    def aux_dtype(self, table: np.ndarray) -> np.dtype:
        return np.dtype(np.int32)

    def branching(self, table: np.ndarray) -> int:
        """Children per parent (the child-grid width per popped node)."""
        return self.branch_factor or self.slots(table)

    def usable_rows(self, capacity: int, chunk: int, slots: int) -> int:
        """Usable pool rows: capacity minus the chunk*branching scratch
        margin (the generalization of engine/device.row_limit — an
        overflowing step routes its full-width block write there, so
        every commit point must keep size <= this)."""
        return max(capacity - chunk * (self.branch_factor or slots), 0)

    def default_capacity(self, table: np.ndarray) -> int:
        return 1 << 18

    def make_tables(self, table: np.ndarray):
        """Replicated jittable pytree of problem tables. Only shapes and
        dtypes specialize the trace — the values are runtime arguments,
        so same-shape instances share one compiled loop."""
        raise NotImplementedError

    # -------------------------------------------------- host-side seed

    def root(self, table: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
        """Seed rows: ((n0, J) int16 nodes, (n0,) int16 depths)."""
        raise NotImplementedError

    def seed_aux(self, table: np.ndarray, prmu: np.ndarray,
                 depth: np.ndarray) -> np.ndarray | None:
        """(n, A) per-node aux rows for host-built nodes (None when
        A == 0). Must agree exactly with what `branch` maintains."""
        return None

    def warmup(self, table: np.ndarray, lb_kind: int,
               init_ub: int | None, target: int):
        """Host BFS frontier of >= `target` nodes (or the exhausted
        tree) with warm-up counters — the distributed seeding input
        (engine/distributed.Frontier). Default: generic pop-front BFS
        over :meth:`host_children`."""
        from ..engine.distributed import Frontier
        from collections import deque

        best = I32_MAX if init_ub is None else int(init_ub)
        tree = sol = 0
        prmu0, depth0 = self.root(table)
        frontier: deque = deque(
            (np.asarray(p, np.int16), int(d))
            for p, d in zip(prmu0, depth0))
        while frontier and len(frontier) < target:
            node, depth = frontier.popleft()
            if not self.leaf_in_evals and depth == self.slots(table):
                sol += 1
                continue
            for child, cdepth, bound, is_leaf in self.host_children(
                    table, node, depth, best, lb_kind=lb_kind):
                if self.leaf_in_evals and is_leaf:
                    sol += 1
                    if bound < best:
                        best = bound
                elif bound < best:
                    frontier.append((child, cdepth))
                    tree += 1
        J = self.slots(table)
        if frontier:
            prmu = np.stack([f[0] for f in frontier]).astype(np.int16)
            depth = np.array([f[1] for f in frontier], np.int16)
        else:
            prmu = np.zeros((0, J), np.int16)
            depth = np.zeros(0, np.int16)
        return Frontier(prmu=prmu, depth=depth, tree=tree, sol=sol,
                        best=best)

    def host_children(self, table: np.ndarray, node: np.ndarray,
                      depth: int, best: int, *, lb_kind: int = 1):
        """Host-side oracle branching: yield (child, child_depth,
        bound, is_leaf) for every evaluated child of one node —
        the warm-up generator, the `-C` host tier's generic session
        (engine/hybrid.PyHostSession) and the conformance tests'
        reference semantics. Must match `branch`+`bound` exactly for
        the same `lb_kind` (plugins with one bound tier may ignore
        the keyword)."""
        raise NotImplementedError

    # ------------------------------------------------- jittable engine

    def branch(self, tables, p_prmu, p_depth, p_aux, valid) -> BranchOut:
        """Dense child grid of a popped block. Feature-major popped
        inputs: p_prmu (J, B) int16, p_depth (B,) int32 (invalid
        columns zeroed), p_aux (A, B) int32, valid (B,) bool."""
        raise NotImplementedError

    def bound(self, tables, lb_kind: int, br: BranchOut, best):
        """(C,) int32 child bounds. Convention: for `leaf_in_evals`
        problems a LEAF child's bound is its exact objective (the PFSP
        complete-schedule-LB==makespan identity the incumbent update
        relies on); unbounded problems return 0 (survive) / I32_MAX
        (infeasible)."""
        raise NotImplementedError

    def is_leaf_cols(self, tables, br: BranchOut):
        """(C,) bool: which child columns are complete solutions."""
        import jax.numpy as jnp
        J = br.children.shape[0]
        return br.child_depth.astype(jnp.int32) == J

    def make_step(self, tables, lb_kind: int, chunk: int, tile: int,
                  limit: int | None, fused: str = "off"):
        """SearchState -> SearchState step callable. The default wires
        the generic pop/bound/prune/branch/compact pipeline
        (engine/device.generic_step); plugins with a specialized
        (Pallas) pipeline override this — the fast-path hook. `fused`
        is the resolved fused-kernel mode (ops/pallas_fused — "off" |
        "hw" | "interpret", always a STATIC string by the time it gets
        here); the generic pipeline has no fused kernels and ignores
        it, PFSP's override threads it into the device step's gate."""
        import functools

        from ..engine.device import generic_step
        del fused
        return functools.partial(generic_step, self, tables, lb_kind,
                                 chunk, tile=tile, limit=limit)

    # ------------------------------------------------------- reporting

    def display_objective(self, best: int) -> int:
        """Human-facing objective from the engine's minimized `best`
        (knapsack negates: the engine minimizes -value)."""
        return int(best)

    def engine_objective(self, value: int) -> int:
        """The inverse of :meth:`display_objective`: a human-facing
        objective value (e.g. a CLI --ub seed) converted into the
        engine's minimized domain. Every caller that accepts an
        objective from a user must route it through here — seeding a
        knapsack incumbent with a raw positive value would silently
        disable pruning instead of tightening it."""
        return int(value)

    def __repr__(self) -> str:  # pragma: no cover - debug nicety
        return f"<Problem {self.name!r}>"


# --------------------------------------------------------------- registry

_REGISTRY: dict[str, Problem] = {}


def register(problem: Problem) -> Problem:
    """Register a plugin singleton under `problem.name` (idempotent for
    the same object; a name collision with a DIFFERENT object raises —
    two definitions of one problem would silently fork semantics)."""
    if not problem.name:
        raise ValueError("problem plugins must set a non-empty .name")
    prior = _REGISTRY.get(problem.name)
    if prior is not None and prior is not problem:
        raise ValueError(f"problem {problem.name!r} is already "
                         f"registered by {prior!r}")
    _REGISTRY[problem.name] = problem
    return problem


def get(name: str) -> Problem:
    """The single resolution point: engine, service, spool and CLI all
    resolve problem names here."""
    try:
        return _REGISTRY[name]
    except KeyError:
        raise KeyError(
            f"unknown problem {name!r} (registered: {sorted(_REGISTRY)})"
        ) from None


def names() -> list[str]:
    return sorted(_REGISTRY)
