"""TSP problem plugin: DFS over partial tours with a nearest-neighbor-sum
lower bound.

A node is a partial tour: cities at positions `0..depth-1` of `prmu`
are the fixed path prefix (city 0 is pinned at position 0, the standard
WLOG normalization, so the root sits at depth 1); branching is the same
prefix-swap scheme as PFSP — the children of a node at depth `d` append
each unvisited city by swapping `prmu[d] <-> prmu[i]` for `i in
d..n-1`. A child at depth n is a complete tour whose objective closes
the cycle back to city 0.

Lower bound (the assignment-relaxation family's cheap member): the
remaining route leaves each of {current endpoint} ∪ {unvisited cities}
through exactly one outgoing edge, and every outgoing edge of city `v`
costs at least `minout[v] = min_{u != v} D[v, u]`, so

    LB(child) = prefix_cost + D[endpoint, appended] + Σ minout(v)
                over v in {appended} ∪ unvisited

is admissible. The suffix minout-sum is computed on the PARENT
permutation (positions >= depth hold exactly that set, and prefix-swap
branching permutes within the suffix), so the whole child grid bounds
in O(n) vector ops per parent. `aux` carries one row: the prefix path
cost, maintained incrementally like PFSP's front vectors.

`lb_kind=2` is the Held–Karp spanning-tree relaxation (the 1-tree
family): the remaining route of any child of a parent at depth `d` is a
Hamiltonian path from the appended city through the unvisited cities
back to the start — a spanning tree of S = {suffix cities} ∪ {start},
and S is the SAME set for every child of one parent. So one MST per
POPPED PARENT (not per child) lower-bounds every child's completion:

    LB2(child) = prefix_cost + D[endpoint, appended] + MST(S)

Weights are symmetrized (`wsym = min(D, D.T)`) so the undirected MST
stays admissible for asymmetric instances. The traced MST is a
vectorized Prim — n-1 masked min-reductions over a (B, n) candidate
distance matrix with first-index argmin tie-breaks; any tie-break
yields the same TOTAL weight (the MST value is unique even when the
tree is not), so the host oracle needs no tie-break coordination.
Leaf children keep the exact closing-edge objective under both tiers.

The instance table is the (n, n) int32 distance matrix (asymmetric
allowed; the diagonal is ignored).
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

from . import base

I32_MAX = base.I32_MAX


class TSPTables(NamedTuple):
    d: object        # (n, n) int32 distance matrix
    dt: object       # (n, n) int32 transpose (leaf return-edge gathers)
    minout: object   # (n,) int32 min outgoing edge per city
    wsym: object     # (n, n) int32 min(D, D.T): lb2's undirected weights


def _minout(d: np.ndarray) -> np.ndarray:
    n = d.shape[0]
    masked = d.astype(np.int64) + np.where(np.eye(n, dtype=bool),
                                           np.int64(2**31), 0)
    return masked.min(axis=1).astype(np.int32)


def _wsym(d: np.ndarray) -> np.ndarray:
    d = np.asarray(d, np.int32)
    return np.minimum(d, d.T)


def _host_mst(wsym: np.ndarray, members: np.ndarray, start: int) -> int:
    """Prim over the member vertex set — the lb2 host oracle. Mirrors
    the traced loop in :meth:`TSPProblem.bound` structurally; the MST
    total is tie-break independent, so exact agreement is free."""
    INF = np.int64(2**62)
    w = wsym.astype(np.int64)
    in_tree = np.zeros(len(members), bool)
    in_tree[start] = True
    dist = np.where(members & ~in_tree, w[start], INF)
    total = 0
    for _ in range(int(members.sum())):
        j = int(dist.argmin())
        if dist[j] >= INF:
            break
        total += int(dist[j])
        in_tree[j] = True
        dist = np.where(members & ~in_tree, np.minimum(dist, w[j]), INF)
    return total


@dataclasses.dataclass(frozen=True)
class TSPInstance:
    """A TSP instance (distance matrix) plus test helpers."""

    n: int
    d: np.ndarray            # (n, n) int32

    @staticmethod
    def synthetic(n: int, seed: int = 0, coord_range: int = 100
                  ) -> "TSPInstance":
        """Random Euclidean (rounded-integer) instance — metric, so the
        bound prunes meaningfully and small cases brute-force fast."""
        rng = np.random.default_rng(seed)
        pts = rng.integers(0, coord_range, size=(n, 2))
        diff = pts[:, None, :] - pts[None, :, :]
        d = np.sqrt((diff ** 2).sum(-1)).round().astype(np.int32)
        np.fill_diagonal(d, 0)
        return TSPInstance(n=n, d=d)

    def tour_length(self, tour: np.ndarray) -> int:
        t = np.asarray(tour, np.int64)
        return int(self.d[t, np.roll(t, -1)].sum())

    def brute_force_optimum(self) -> int:
        import itertools

        assert self.n <= 10, "brute force only for tiny instances"
        best = None
        for perm in itertools.permutations(range(1, self.n)):
            tour = np.array((0,) + perm)
            length = self.tour_length(tour)
            best = length if best is None else min(best, length)
        return int(best)


# A pinned golden instance: 6 cities, optimum verified by exhaustive
# enumeration (tests re-derive it by brute force AND assert this
# constant so the table and the number cannot drift apart).
GOLDEN_D = np.array([
    [0, 10, 15, 20, 8, 25],
    [10, 0, 35, 25, 12, 18],
    [15, 35, 0, 30, 16, 28],
    [20, 25, 30, 0, 14, 22],
    [8, 12, 16, 14, 0, 9],
    [25, 18, 28, 22, 9, 0],
], np.int32)
GOLDEN_OPTIMUM = 95


class TSPProblem(base.Problem):
    name = "tsp"
    leaf_in_evals = True
    supports_host_tier = True    # generic host tier over host_children
    lb_kinds = (1, 2)        # 1 = NN-sum, 2 = Held–Karp MST relaxation
    default_lb = 1
    telemetry_labels = {"objective": "tour_length"}

    def validate(self, table: np.ndarray) -> str | None:
        t = np.asarray(table)
        if t.ndim != 2 or t.shape[0] != t.shape[1] or t.shape[0] < 3:
            return (f"tsp table must be a square (n>=3, n) distance "
                    f"matrix, got shape {t.shape}")
        if t.shape[0] > 512:
            return f"tsp supports n <= 512 cities, got {t.shape[0]}"
        if (t < 0).any() or int(t.max(initial=0)) > 10**6:
            return "tsp distances must be in [0, 1e6]"
        return None

    def slots(self, table: np.ndarray) -> int:
        return int(np.asarray(table).shape[0])

    def aux_rows(self, table: np.ndarray) -> int:
        return 1             # prefix path cost

    def make_tables(self, table: np.ndarray) -> TSPTables:
        import jax.numpy as jnp
        d = np.asarray(table, np.int32)
        return TSPTables(d=jnp.asarray(d), dt=jnp.asarray(d.T.copy()),
                         minout=jnp.asarray(_minout(d)),
                         wsym=jnp.asarray(_wsym(d)))

    def root(self, table: np.ndarray):
        n = self.slots(table)
        # city 0 pinned at position 0: the root is the identity
        # permutation at depth 1 (prefix-swap never touches position 0)
        return (np.arange(n, dtype=np.int16)[None, :],
                np.ones(1, np.int16))

    def seed_aux(self, table: np.ndarray, prmu: np.ndarray,
                 depth: np.ndarray) -> np.ndarray:
        d = np.asarray(table, np.int64)
        out = np.zeros((len(depth), 1), np.int32)
        for k, (p, dep) in enumerate(zip(np.asarray(prmu, np.int64),
                                         np.asarray(depth))):
            out[k, 0] = int(d[p[:dep - 1], p[1:dep]].sum()) \
                if dep > 1 else 0
        return out

    def host_children(self, table: np.ndarray, node: np.ndarray,
                      depth: int, best: int, *, lb_kind: int = 1):
        d = np.asarray(table, np.int64)
        mo = _minout(np.asarray(table)).astype(np.int64)
        n = len(node)
        prefix = node[:depth].astype(np.int64)
        cost = int(d[prefix[:-1], prefix[1:]].sum())
        suffix_mo = int(mo[node[depth:].astype(np.int64)].sum())
        end = int(node[depth - 1])
        if lb_kind == 2 and depth + 1 < n:
            # one MST per parent: S = suffix ∪ {start} is child-invariant
            members = np.zeros(n, bool)
            members[node[depth:].astype(np.int64)] = True
            members[int(node[0])] = True
            mst = _host_mst(_wsym(table), members, int(node[0]))
        else:
            mst = 0
        for i in range(depth, n):
            child = node.copy()
            child[depth], child[i] = child[i], child[depth]
            appended = int(node[i])
            new_cost = cost + int(d[end, appended])
            if depth + 1 == n:
                bound = new_cost + int(d[appended, int(node[0])])
            elif lb_kind == 2:
                bound = new_cost + mst
            else:
                bound = new_cost + suffix_mo
            yield child, depth + 1, bound, depth + 1 == n

    # ------------------------------------------------ jittable engine

    def branch(self, tables: TSPTables, p_prmu, p_depth, p_aux, valid):
        import jax.numpy as jnp

        from ..engine.device import make_children
        n = tables.d.shape[0]
        board = p_prmu.T.astype(jnp.int32)              # (B, n)
        B = board.shape[0]
        pos = jnp.arange(n, dtype=jnp.int32)[None, :]
        # endpoint city prmu[depth-1] via masked sum (root depth >= 1;
        # invalid columns have depth 0 and are masked off downstream)
        endpoint = jnp.sum(
            jnp.where(pos == (p_depth - 1)[:, None], board, 0), axis=1)
        d_end = jnp.take(tables.d, endpoint, axis=0)    # (B, n)
        edge = jnp.take_along_axis(d_end, board, axis=1)
        d_ret = jnp.take(tables.dt, board[:, 0], axis=0)
        ret = jnp.take_along_axis(d_ret, board, axis=1)  # D[city, start]
        mo = jnp.take(tables.minout, board)             # (B, n)
        suffix_mo = jnp.sum(
            jnp.where(pos >= p_depth[:, None], mo, 0), axis=1)
        new_cost = p_aux[0][:, None] + edge             # (B, n)

        evaluated = ((pos >= p_depth[:, None])
                     & valid[:, None]).reshape(-1)
        children = make_children(board.astype(jnp.int16),
                                 p_depth).reshape(B * n, n).T
        child_depth = jnp.broadcast_to((p_depth + 1)[:, None], (B, n)) \
            .reshape(-1).astype(jnp.int16)
        # lb2's per-parent MST vertex set S = suffix ∪ {start} in city
        # space (a permutation scatter); carried for every tier — XLA
        # dead-code-eliminates it when bound() never reads it (lb1)
        members = jnp.zeros((B, n), bool).at[
            jnp.arange(B)[:, None], board].set(pos >= p_depth[:, None])
        members = members.at[jnp.arange(B), board[:, 0]].set(True)
        return base.BranchOut(
            children=children, child_depth=child_depth,
            child_aux=new_cost.reshape(1, -1),
            evaluated=evaluated,
            extras=(ret.reshape(-1),
                    jnp.broadcast_to(suffix_mo[:, None],
                                     (B, n)).reshape(-1),
                    members, board[:, 0]))

    def bound(self, tables: TSPTables, lb_kind: int, br, best):
        import jax.numpy as jnp
        n = tables.d.shape[0]
        ret, suffix_mo, members, start = br.extras
        new_cost = br.child_aux[0]
        leaf = br.child_depth.astype(jnp.int32) == n
        if lb_kind == 2:
            # Held–Karp MST relaxation, one Prim run per popped parent
            # (see module docstring): n-1 masked min-reductions over the
            # (B, n) candidate-edge matrix, scanned with fori_loop
            import jax
            B = members.shape[0]
            INF = jnp.int64(2**62)
            rows = jnp.arange(B)
            w = tables.wsym.astype(jnp.int64)
            in_tree = jnp.zeros((B, n), bool).at[rows, start].set(True)
            dist = jnp.where(members & ~in_tree,
                             jnp.take(w, start, axis=0), INF)

            def prim_step(_, carry):
                in_tree, dist, total = carry
                j = jnp.argmin(dist, axis=1)        # first-index ties
                dmin = jnp.take_along_axis(dist, j[:, None], axis=1)[:, 0]
                add = dmin < INF
                total = total + jnp.where(add, dmin, 0)
                in_tree = in_tree.at[rows, j].set(in_tree[rows, j] | add)
                wj = jnp.take(w, j, axis=0)          # (B, n)
                dist = jnp.where(members & ~in_tree,
                                 jnp.minimum(dist, wj), INF)
                return in_tree, dist, total

            total = jnp.zeros(B, jnp.int64)
            _, _, mst = jax.lax.fori_loop(
                0, n - 1, prim_step, (in_tree, dist, total))
            lb = jnp.broadcast_to(mst[:, None].astype(jnp.int32),
                                  (B, n)).reshape(-1)
        else:
            lb = suffix_mo
        # a complete tour's "bound" is its exact length (closing edge
        # back to the start) — the LB==objective-at-leaves convention
        return jnp.where(leaf, new_cost + ret,
                         new_cost + lb).astype(jnp.int32)


PROBLEM = base.register(TSPProblem())
