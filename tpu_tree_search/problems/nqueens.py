"""N-Queens problem definition (permutation-based backtracking).

The reference's proof-of-concept workload (reference: nqueens/lib/
NQueens_node.h:11-17, nqueens/nqueens_c.c:80-117). A node is a permutation
`board` of column->row assignments plus a `depth`: queens `0..depth-1` are
placed (one per column, rows given by `board`), the rest are candidate rows.
Branching swaps `board[depth] <-> board[j]` for each `j in depth..N-1`
whose row is diagonal-safe against the placed prefix; the permutation
scheme makes row-conflicts impossible by construction so only diagonals
are checked. A node at depth N is a solution.

`g` replicates the safety check g times to scale arithmetic intensity for
benchmarking (reference: nqueens_c.c:80-96); it does not change results.

Known solution counts (OEIS A000170) are the correctness oracle.
"""

from __future__ import annotations

import numpy as np

from . import base

# Total solutions of N-Queens for N = 0..17 (OEIS A000170).
SOLUTION_COUNTS = (
    1, 1, 0, 0, 2, 10, 4, 40, 92, 352, 724, 2680, 14200, 73712,
    365596, 2279184, 14772512, 95815104,
)


def root_node(n: int) -> tuple[np.ndarray, int]:
    """Root = identity board at depth 0 (reference: NQueens_node.c:7-13)."""
    return np.arange(n, dtype=np.int16), 0


def is_safe(board: np.ndarray, depth: int, row: int) -> bool:
    """Diagonal-safety of placing `row` in column `depth` against the prefix
    (reference: nqueens_c.c:80-96)."""
    placed = np.asarray(board[:depth], dtype=np.int64)
    dist = depth - np.arange(depth, dtype=np.int64)
    return bool(np.all((placed != row - dist) & (placed != row + dist)))


def table(n: int, g: int = 1) -> np.ndarray:
    """The N-Queens instance table: shape (g, n) — both knobs ride the
    SHAPE (they specialize the trace, like every static engine knob);
    the values are unused."""
    return np.zeros((max(int(g), 1), int(n)), np.int32)


class NQueensProblem(base.Problem):
    """N-Queens as a plugin of the generic engine.

    The jittable callables are op-for-op the pipeline the deleted
    `engine/nqueens_device.nq_step` ran (same safety kernel, same child
    grid, same masks), driven through device.generic_step — node/sol/
    evals counts are bit-identical to the pre-refactor fork (parity
    tests pin them against the sequential oracle, which the fork also
    matched exactly).
    """

    name = "nqueens"
    leaf_in_evals = False      # sols are POPPED complete boards; all
    #                            safe children (complete ones included)
    #                            are pushed — reference nqueens_c.c
    supports_host_tier = False
    lb_kinds = (0,)            # no bound function exists
    default_lb = 0
    telemetry_labels = {"objective": "none"}

    def validate(self, table: np.ndarray) -> str | None:
        t = np.asarray(table)
        if t.ndim != 2 or t.shape[0] < 1 or not 4 <= t.shape[1] <= 32:
            return (f"nqueens table must be (g>=1, 4<=n<=32), got "
                    f"shape {t.shape}")
        return None

    def slots(self, table: np.ndarray) -> int:
        return int(np.asarray(table).shape[1])

    def make_tables(self, table: np.ndarray):
        import jax.numpy as jnp
        return jnp.asarray(np.asarray(table), jnp.int32)

    def root(self, table: np.ndarray):
        n = self.slots(table)
        return (np.arange(n, dtype=np.int16)[None, :],
                np.zeros(1, np.int16))

    def host_children(self, table: np.ndarray, node: np.ndarray,
                      depth: int, best: int, *, lb_kind: int = 1):
        n = self.slots(table)
        for j in range(depth, n):
            ok = is_safe(node, depth, int(node[j]))
            child = node.copy()
            child[depth], child[j] = child[j], child[depth]
            yield child, depth + 1, (0 if ok else base.I32_MAX), \
                depth + 1 == n

    # ------------------------------------------------ jittable engine

    def branch(self, tables, p_prmu, p_depth, p_aux, valid):
        import jax.numpy as jnp

        from ..engine.device import make_children
        from ..ops import nqueens_ops
        g, n = tables.shape                 # STATIC: knobs ride the shape
        board = p_prmu.T                    # (B, n) row-major, as nq_step
        B = board.shape[0]
        safe = nqueens_ops.safe_children(board, p_depth, valid, g=g)
        children = make_children(board, p_depth).reshape(B * n, n).T
        child_depth = jnp.broadcast_to((p_depth + 1)[:, None], (B, n)) \
            .reshape(-1).astype(jnp.int16)
        evaluated = ((jnp.arange(n)[None, :] >= p_depth[:, None])
                     & valid[:, None]).reshape(-1)
        return base.BranchOut(
            children=children, child_depth=child_depth,
            child_aux=jnp.zeros((0, B * n), jnp.int32),
            evaluated=evaluated, extras=safe.reshape(-1))

    def bound(self, tables, lb_kind: int, br, best):
        import jax.numpy as jnp
        # no bound function: 0 = safe (always survives the I32_MAX
        # incumbent), I32_MAX = unsafe (never does)
        return jnp.where(br.extras, 0, 2**31 - 1).astype(jnp.int32)


PROBLEM = base.register(NQueensProblem())


def search(n: int, g: int = 1, chunk: int = 64, capacity: int = 1 << 18,
           max_iters: int | None = None):
    """Single-device N-Queens through the generic engine (the drop-in
    for the deleted nqueens_device.search)."""
    from ..engine import device
    return device.solve(PROBLEM, table(n, g), lb_kind=0, chunk=chunk,
                        capacity=capacity, max_iters=max_iters)


def search_distributed(n: int, g: int = 1, n_devices: int | None = None,
                       chunk: int = 64, capacity: int = 1 << 17,
                       balance_period: int = 4, min_seed: int = 32,
                       transfer_cap: int | None = None,
                       min_transfer: int | None = None, mesh=None):
    """Distributed N-Queens through the generic SPMD engine (the
    drop-in for the deleted nqueens_device.search_distributed, with
    its exact 4*chunk / 2*chunk transfer defaults — the byte-budgeted
    default_transfer_cap floor would re-size tiny-chunk test runs)."""
    from ..engine import distributed
    return distributed.search(
        table(n, g), problem="nqueens", lb_kind=0, n_devices=n_devices,
        chunk=chunk, capacity=capacity, balance_period=balance_period,
        min_seed=min_seed, transfer_cap=transfer_cap or 4 * chunk,
        min_transfer=min_transfer or 2 * chunk, mesh=mesh)
