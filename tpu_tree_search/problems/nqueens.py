"""N-Queens problem definition (permutation-based backtracking).

The reference's proof-of-concept workload (reference: nqueens/lib/
NQueens_node.h:11-17, nqueens/nqueens_c.c:80-117). A node is a permutation
`board` of column->row assignments plus a `depth`: queens `0..depth-1` are
placed (one per column, rows given by `board`), the rest are candidate rows.
Branching swaps `board[depth] <-> board[j]` for each `j in depth..N-1`
whose row is diagonal-safe against the placed prefix; the permutation
scheme makes row-conflicts impossible by construction so only diagonals
are checked. A node at depth N is a solution.

`g` replicates the safety check g times to scale arithmetic intensity for
benchmarking (reference: nqueens_c.c:80-96); it does not change results.

Known solution counts (OEIS A000170) are the correctness oracle.
"""

from __future__ import annotations

import numpy as np

# Total solutions of N-Queens for N = 0..17 (OEIS A000170).
SOLUTION_COUNTS = (
    1, 1, 0, 0, 2, 10, 4, 40, 92, 352, 724, 2680, 14200, 73712,
    365596, 2279184, 14772512, 95815104,
)


def root_node(n: int) -> tuple[np.ndarray, int]:
    """Root = identity board at depth 0 (reference: NQueens_node.c:7-13)."""
    return np.arange(n, dtype=np.int16), 0


def is_safe(board: np.ndarray, depth: int, row: int) -> bool:
    """Diagonal-safety of placing `row` in column `depth` against the prefix
    (reference: nqueens_c.c:80-96)."""
    placed = np.asarray(board[:depth], dtype=np.int64)
    dist = depth - np.arange(depth, dtype=np.int64)
    return bool(np.all((placed != row - dist) & (placed != row + dist)))
