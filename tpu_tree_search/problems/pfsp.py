"""PFSP problem definition: node layout and branching scheme.

A B&B node for the Permutation Flowshop Scheduling Problem is a partial
permutation: jobs at positions `0..depth-1` of `prmu` are the fixed prefix
(already scheduled), the rest are unscheduled. The reference stores
`(int16 depth, int16 limit1, int16 prmu[MAX_JOBS])`
(reference: pfsp/lib/PFSP_node.h:15-20); with the forward-only branching
rule every engine uses (`child.limit1 = parent.limit1 + 1`,
PFSP_lib.c:13-16), `limit1 == depth - 1` is an invariant, so the TPU node
is just `(depth, prmu)` and `limit1` is derived.

Branching ("decompose", reference: PFSP_lib.c:7-42): the children of a node
at depth `d` are obtained by swapping `prmu[d] <-> prmu[i]` for each
`i in d..jobs-1`, fixing one more job at the front. A child with
`depth == jobs` is a complete schedule (leaf).

Device layout is struct-of-arrays: a pool of N nodes is
`prmu: int16[N, jobs]`, `depth: int16[N]` resident in HBM.
"""

from __future__ import annotations

import dataclasses

import numpy as np

from . import taillard


@dataclasses.dataclass(frozen=True)
class PFSPInstance:
    """A PFSP instance plus the static shape info engines specialize on.

    The reference hardcodes MAX_JOBS/MAX_MACHINES at compile time
    (pfsp/lib/macro.h:9-11); here the concrete (jobs, machines) are static
    arguments baked into `jit`, chosen per instance.
    """

    inst_id: int            # Taillard instance id (1..120), 0 for synthetic
    jobs: int
    machines: int
    p_times: np.ndarray     # (machines, jobs) int32

    @staticmethod
    def from_taillard(inst: int) -> "PFSPInstance":
        p, n, m = taillard.instance(inst)
        return PFSPInstance(inst_id=inst, jobs=n, machines=m, p_times=p)

    @staticmethod
    def synthetic(jobs: int, machines: int, seed: int = 0,
                  low: int = 1, high: int = 99) -> "PFSPInstance":
        """Random instance for tests (brute-forceable at small `jobs`)."""
        rng = np.random.default_rng(seed)
        p = rng.integers(low, high + 1, size=(machines, jobs), dtype=np.int32)
        return PFSPInstance(inst_id=0, jobs=jobs, machines=machines, p_times=p)

    @property
    def optimum(self) -> int | None:
        return taillard.optimal_makespan(self.inst_id) if self.inst_id else None

    def makespan(self, permutation: np.ndarray) -> int:
        """Cmax of a complete permutation (reference: c_bound_simple.c:92-106)."""
        perm = np.asarray(permutation)
        completion = np.zeros(self.machines, dtype=np.int64)
        for job in perm:
            completion[0] += self.p_times[0, job]
            for mach in range(1, self.machines):
                completion[mach] = max(completion[mach - 1], completion[mach]) \
                    + self.p_times[mach, job]
        return int(completion[-1])

    def brute_force_optimum(self) -> int:
        """Exhaustive optimum for tiny instances (test oracle only)."""
        import itertools

        assert self.jobs <= 9, "brute force only for tiny instances"
        best = np.inf
        for perm in itertools.permutations(range(self.jobs)):
            best = min(best, self.makespan(np.array(perm)))
        return int(best)


def root_node(jobs: int) -> tuple[np.ndarray, int]:
    """Root = identity permutation at depth 0 (reference: PFSP_node.c:7-14)."""
    return np.arange(jobs, dtype=np.int16), 0


ROOT_DEPTH = 0
