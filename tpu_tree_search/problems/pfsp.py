"""PFSP problem definition: node layout and branching scheme.

A B&B node for the Permutation Flowshop Scheduling Problem is a partial
permutation: jobs at positions `0..depth-1` of `prmu` are the fixed prefix
(already scheduled), the rest are unscheduled. The reference stores
`(int16 depth, int16 limit1, int16 prmu[MAX_JOBS])`
(reference: pfsp/lib/PFSP_node.h:15-20); with the forward-only branching
rule every engine uses (`child.limit1 = parent.limit1 + 1`,
PFSP_lib.c:13-16), `limit1 == depth - 1` is an invariant, so the TPU node
is just `(depth, prmu)` and `limit1` is derived.

Branching ("decompose", reference: PFSP_lib.c:7-42): the children of a node
at depth `d` are obtained by swapping `prmu[d] <-> prmu[i]` for each
`i in d..jobs-1`, fixing one more job at the front. A child with
`depth == jobs` is a complete schedule (leaf).

Device layout is struct-of-arrays: a pool of N nodes is
`prmu: int16[N, jobs]`, `depth: int16[N]` resident in HBM.
"""

from __future__ import annotations

import dataclasses
import functools

import numpy as np

from . import base, taillard


@dataclasses.dataclass(frozen=True)
class PFSPInstance:
    """A PFSP instance plus the static shape info engines specialize on.

    The reference hardcodes MAX_JOBS/MAX_MACHINES at compile time
    (pfsp/lib/macro.h:9-11); here the concrete (jobs, machines) are static
    arguments baked into `jit`, chosen per instance.
    """

    inst_id: int            # Taillard instance id (1..120), 0 for synthetic
    jobs: int
    machines: int
    p_times: np.ndarray     # (machines, jobs) int32

    @staticmethod
    def from_taillard(inst: int) -> "PFSPInstance":
        p, n, m = taillard.instance(inst)
        return PFSPInstance(inst_id=inst, jobs=n, machines=m, p_times=p)

    @staticmethod
    def synthetic(jobs: int, machines: int, seed: int = 0,
                  low: int = 1, high: int = 99) -> "PFSPInstance":
        """Random instance for tests (brute-forceable at small `jobs`)."""
        rng = np.random.default_rng(seed)
        p = rng.integers(low, high + 1, size=(machines, jobs), dtype=np.int32)
        return PFSPInstance(inst_id=0, jobs=jobs, machines=machines, p_times=p)

    @property
    def optimum(self) -> int | None:
        return taillard.optimal_makespan(self.inst_id) if self.inst_id else None

    def makespan(self, permutation: np.ndarray) -> int:
        """Cmax of a complete permutation (reference: c_bound_simple.c:92-106)."""
        perm = np.asarray(permutation)
        completion = np.zeros(self.machines, dtype=np.int64)
        for job in perm:
            completion[0] += self.p_times[0, job]
            for mach in range(1, self.machines):
                completion[mach] = max(completion[mach - 1], completion[mach]) \
                    + self.p_times[mach, job]
        return int(completion[-1])

    def brute_force_optimum(self) -> int:
        """Exhaustive optimum for tiny instances (test oracle only)."""
        import itertools

        assert self.jobs <= 9, "brute force only for tiny instances"
        best = np.inf
        for perm in itertools.permutations(range(self.jobs)):
            best = min(best, self.makespan(np.array(perm)))
        return int(best)


def root_node(jobs: int) -> tuple[np.ndarray, int]:
    """Root = identity permutation at depth 0 (reference: PFSP_node.c:7-14)."""
    return np.arange(jobs, dtype=np.int16), 0


ROOT_DEPTH = 0


class PFSPProblem(base.Problem):
    """PFSP as a plugin of the generic engine.

    The flagship workload keeps its specialized pipeline: `make_step`
    is the Pallas fast-path hook onto engine/device.step (the two-phase
    LB2 prefilter, tiered compaction, feature-major kernels) — the
    protocol's `branch`/`bound` decomposition is deliberately NOT used
    on the hot path, which is exactly what the hook exists for. Host
    seeding (root/seed_aux/warmup) and the static spec route through
    the same single functions the engine always used, so a search
    driven through the plugin is op-identical to the pre-refactor one.
    """

    name = "pfsp"
    leaf_in_evals = True
    supports_host_tier = True
    supports_fused = True
    lb_kinds = (0, 1, 2)
    default_lb = 1
    telemetry_labels = {"objective": "makespan"}

    def validate(self, table: np.ndarray) -> str | None:
        p = np.asarray(table)
        if p.ndim != 2 or p.shape[0] < 1 or p.shape[1] < 2:
            return (f"p_times must be a (machines, jobs>=2) table, "
                    f"got shape {p.shape}")
        return None

    def slots(self, table: np.ndarray) -> int:
        return int(np.asarray(table).shape[1])

    def aux_rows(self, table: np.ndarray) -> int:
        return int(np.asarray(table).shape[0])

    def aux_dtype(self, table: np.ndarray) -> np.dtype:
        from ..engine.device import aux_dtype
        return aux_dtype(np.asarray(table))

    def default_capacity(self, table: np.ndarray) -> int:
        from ..engine.device import default_capacity
        t = np.asarray(table)
        return default_capacity(t.shape[1], t.shape[0])

    def make_tables(self, table: np.ndarray):
        from ..ops import batched
        return batched.make_tables(np.asarray(table))

    def root(self, table: np.ndarray):
        n = self.slots(table)
        return (np.arange(n, dtype=np.int16)[None, :],
                np.zeros(1, np.int16))

    def seed_aux(self, table: np.ndarray, prmu: np.ndarray,
                 depth: np.ndarray) -> np.ndarray:
        from ..ops import reference as ref
        t = np.asarray(table)
        m = t.shape[0]
        adt = self.aux_dtype(t)
        if len(depth) == 0:
            return np.zeros((0, m), adt)
        return ref.prefix_front_remain(t, prmu, depth)[:, :m].astype(adt)

    def warmup(self, table: np.ndarray, lb_kind: int,
               init_ub: int | None, target: int):
        from ..engine import distributed
        return distributed.bfs_warmup(np.asarray(table), lb_kind,
                                      init_ub, target)

    def host_children(self, table: np.ndarray, node: np.ndarray,
                      depth: int, best: int, *, lb_kind: int = 1):
        # the host oracle stays on lb1 regardless of lb_kind — PFSP's
        # native -C tier (engine/hybrid.HostSession) owns lb2 hosting
        from ..ops import reference as ref
        p = np.asarray(table)
        jobs = p.shape[1]
        lb1 = ref.make_lb1_data(p)
        for i in range(depth, jobs):
            child = node.copy()
            child[depth], child[i] = child[i], child[depth]
            bound = ref.lb1_bound(lb1, child, depth, jobs)
            yield child, depth + 1, int(bound), depth + 1 == jobs

    def make_step(self, tables, lb_kind: int, chunk: int, tile: int,
                  limit: int | None, fused: str = "off"):
        from ..engine.device import step
        return functools.partial(step, tables, lb_kind, chunk,
                                 tile=tile, limit=limit, fused=fused)


PROBLEM = base.register(PFSPProblem())
