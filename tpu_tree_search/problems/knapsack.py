"""0/1 knapsack problem plugin with the fractional-relaxation bound.

A node is a decision prefix: items `0..depth-1` (in density-sorted
order — `make_tables` sorts once, and every host helper uses the same
deterministic order) are decided, `prmu[i]` ∈ {0, 1} records the
choice. Branching factor is 2 (skip / take), so the child grid is
(chunk, 2) instead of the permutation problems' (chunk, n). `aux`
carries two rows: accumulated weight and accumulated value.

The engine minimizes, so the objective is the NEGATED total value:
``bound = -(value + fractional_ub(remaining))``. The fractional
relaxation (Dantzig bound) greedily fills the residual capacity in
density order and takes a fraction of the first item that does not
fit; the floor of the fractional term keeps the bound integral AND
admissible (the integer optimum is an integer below the real-valued
relaxation). An over-capacity "take" child is infeasible and bounds to
I32_MAX. A child at depth n is a leaf whose bound is exactly -value.

`lb_kind=2` is the Martello–Toth refinement: with break item `k` (the
first sorted item past `s` that no longer fits after the greedy fill,
residual r̄), the integer optimum either SKIPS k — at most
U0 = z̄ + floor(r̄·v[k+1]/w[k+1]) (items past k are no denser than
k+1) — or TAKES k, which must displace w[k]−r̄ weight of density at
least v[k-1]/w[k-1] from the greedy prefix:
U1 = z̄ + v[k] − ceil((w[k]−r̄)·v[k-1]/w[k-1]). U2 = max(U0, U1)
covers both cases and never exceeds the Dantzig bound. Subproblem
twist the textbook form doesn't need: items before `s` are FIXED, so
U1 is only valid when the greedy prefix is non-empty (k−1 ≥ s); when
k == s the take-k case is infeasible outright and U2 = U0. The ceil
(not floor) on the displaced-value term keeps U1 admissible.

Instance table (3, n) int32: row 0 weights (>= 1), row 1 values
(>= 0), row 2 is [capacity, 0, ...].
"""

from __future__ import annotations

import dataclasses
from typing import NamedTuple

import numpy as np

from . import base

I32_MAX = base.I32_MAX


class KnapsackTables(NamedTuple):
    w: object        # (n,) int32 weights, density-sorted descending
    v: object        # (n,) int32 values, same order
    cap: object      # () int32 capacity
    cumw: object     # (n+1,) int32 prefix weight sums over the order


def make_table(weights, values, capacity: int) -> np.ndarray:
    """Assemble the (3, n) instance table."""
    w = np.asarray(weights, np.int32)
    v = np.asarray(values, np.int32)
    assert w.shape == v.shape and w.ndim == 1
    cap_row = np.zeros_like(w)
    cap_row[0] = int(capacity)
    return np.stack([w, v, cap_row])


def _sorted_items(table: np.ndarray):
    """(weights, values, capacity, order) in density-descending order —
    THE deterministic order every traced and host-side helper shares
    (stable index tie-break, so equal densities cannot reorder between
    builds)."""
    t = np.asarray(table)
    w = t[0].astype(np.int64)
    v = t[1].astype(np.int64)
    cap = int(t[2, 0])
    order = np.lexsort((np.arange(len(w)), -(v / np.maximum(w, 1))))
    return w[order].astype(np.int32), v[order].astype(np.int32), cap, \
        order


def _fractional_ub(w: np.ndarray, v: np.ndarray, start: int,
                   rem_cap: int) -> int:
    """Host-side Dantzig bound over sorted items[start:] at `rem_cap`
    residual capacity (the oracle the traced bound must match)."""
    total = 0
    r = int(rem_cap)
    for i in range(start, len(w)):
        if int(w[i]) <= r:
            r -= int(w[i])
            total += int(v[i])
        else:
            total += (r * int(v[i])) // max(int(w[i]), 1)
            break
    return total


def _mt_ub(w: np.ndarray, v: np.ndarray, start: int,
           rem_cap: int) -> int:
    """Host-side Martello–Toth bound over sorted items[start:] (the
    lb_kind=2 oracle the traced bound must match). See the module
    docstring for the U0/U1/U2 derivation and the k-1 >= start
    subproblem validity twist."""
    n = len(w)
    r = int(rem_cap)
    z = 0
    k = start
    while k < n and int(w[k]) <= r:
        r -= int(w[k])
        z += int(v[k])
        k += 1
    if k >= n:
        return z
    u0 = z + ((r * int(v[k + 1])) // int(w[k + 1]) if k + 1 < n else 0)
    if k - 1 >= start:
        need = int(w[k]) - r
        lost = -((-need * int(v[k - 1])) // int(w[k - 1]))  # ceil div
        return max(u0, z + int(v[k]) - lost)
    return u0


@dataclasses.dataclass(frozen=True)
class KnapsackInstance:
    """A knapsack instance plus test helpers."""

    weights: np.ndarray
    values: np.ndarray
    capacity: int

    @property
    def table(self) -> np.ndarray:
        return make_table(self.weights, self.values, self.capacity)

    @staticmethod
    def synthetic(n: int, seed: int = 0) -> "KnapsackInstance":
        rng = np.random.default_rng(seed)
        w = rng.integers(1, 50, size=n, dtype=np.int32)
        v = rng.integers(1, 100, size=n, dtype=np.int32)
        return KnapsackInstance(weights=w, values=v,
                                capacity=int(w.sum()) // 2)

    def optimum(self) -> int:
        """Exact optimal value by dynamic programming (test oracle)."""
        dp = np.zeros(self.capacity + 1, np.int64)
        for w, v in zip(self.weights, self.values):
            w, v = int(w), int(v)
            if w <= self.capacity:
                dp[w:] = np.maximum(dp[w:], dp[:-w] + v)
        return int(dp.max())


# Pinned golden instances of known optimum (Kreher & Stinson's classic
# P01/P02 test set; the tests ALSO re-derive each optimum by DP so the
# constants cannot drift from the data).
GOLDEN = {
    "p01": (KnapsackInstance(
        weights=np.array([23, 31, 29, 44, 53, 38, 63, 85, 89, 82]),
        values=np.array([92, 57, 49, 68, 60, 43, 67, 84, 87, 72]),
        capacity=165), 309),
    "p02": (KnapsackInstance(
        weights=np.array([12, 7, 11, 8, 9]),
        values=np.array([24, 13, 23, 15, 16]),
        capacity=26), 51),
}


class KnapsackProblem(base.Problem):
    name = "knapsack"
    leaf_in_evals = True
    supports_host_tier = True    # generic host tier over host_children
    lb_kinds = (1, 2)        # 1 = Dantzig fractional, 2 = Martello–Toth
    default_lb = 1
    telemetry_labels = {"objective": "neg_value"}

    def validate(self, table: np.ndarray) -> str | None:
        t = np.asarray(table)
        if t.ndim != 2 or t.shape[0] != 3 or not 2 <= t.shape[1] <= 4096:
            return (f"knapsack table must be (3, 2<=n<=4096) "
                    f"[weights; values; capacity row], got shape "
                    f"{t.shape}")
        if (t[0] < 1).any():
            return "knapsack weights must be >= 1"
        if (t[1] < 0).any() or int(t[1].max()) > 2**20:
            return "knapsack values must be in [0, 2^20]"
        if int(t[2, 0]) < 0:
            return "knapsack capacity must be >= 0"
        # the traced bound accumulates weight/value sums in int32
        # (cumw prefix sums, int_val, ub = V + int_val + frac): totals
        # past 2^30 would wrap silently and turn the 'proven' optimum
        # into garbage — refuse at admission instead
        if int(t[0].astype(np.int64).sum()) > 2**30:
            return "knapsack weights must sum to <= 2^30 (int32 bound)"
        if int(t[1].astype(np.int64).sum()) > 2**30:
            return "knapsack values must sum to <= 2^30 (int32 bound)"
        return None

    def slots(self, table: np.ndarray) -> int:
        return int(np.asarray(table).shape[1])

    def aux_rows(self, table: np.ndarray) -> int:
        return 2             # [accumulated weight, accumulated value]

    branch_factor = 2        # skip / take (the engine sizes the pool's
    #                          scratch margin off this, not off slots)

    def make_tables(self, table: np.ndarray) -> KnapsackTables:
        import jax.numpy as jnp
        w, v, cap, _ = _sorted_items(table)
        cumw = np.zeros(len(w) + 1, np.int32)
        np.cumsum(w, out=cumw[1:])
        return KnapsackTables(w=jnp.asarray(w), v=jnp.asarray(v),
                              cap=jnp.asarray(np.int32(cap)),
                              cumw=jnp.asarray(cumw))

    def root(self, table: np.ndarray):
        n = self.slots(table)
        return (np.zeros((1, n), np.int16), np.zeros(1, np.int16))

    def seed_aux(self, table: np.ndarray, prmu: np.ndarray,
                 depth: np.ndarray) -> np.ndarray:
        w, v, _, _ = _sorted_items(table)
        out = np.zeros((len(depth), 2), np.int32)
        for k, (p, dep) in enumerate(zip(np.asarray(prmu, np.int64),
                                         np.asarray(depth))):
            taken = p[:dep] > 0
            out[k, 0] = int(w[:dep][taken].sum())
            out[k, 1] = int(v[:dep][taken].sum())
        return out

    def host_children(self, table: np.ndarray, node: np.ndarray,
                      depth: int, best: int, *, lb_kind: int = 1):
        w, v, cap, _ = _sorted_items(table)
        n = len(w)
        ub_fn = _mt_ub if lb_kind == 2 else _fractional_ub
        taken = node[:depth] > 0
        weight = int(w[:depth][taken].sum())
        value = int(v[:depth][taken].sum())
        is_leaf = depth + 1 == n
        for take in (0, 1):
            child = node.copy()
            child[depth] = take
            cw = weight + take * int(w[depth])
            cv = value + take * int(v[depth])
            if cw > cap:
                bound = I32_MAX
            else:
                bound = -(cv + ub_fn(w, v, depth + 1, cap - cw))
            yield child, depth + 1, bound, is_leaf

    # ------------------------------------------------ jittable engine

    def branch(self, tables: KnapsackTables, p_prmu, p_depth, p_aux,
               valid):
        import jax.numpy as jnp
        n = tables.w.shape[0]
        B = p_prmu.shape[1]
        d = jnp.clip(p_depth, 0, n - 1)
        w_it = jnp.take(tables.w, d)
        v_it = jnp.take(tables.v, d)
        weight, value = p_aux[0], p_aux[1]
        pos = jnp.arange(n, dtype=jnp.int32)[:, None]
        skip_b = jnp.where(pos == p_depth[None, :], 0, p_prmu) \
            .astype(jnp.int16)
        take_b = jnp.where(pos == p_depth[None, :], 1, p_prmu) \
            .astype(jnp.int16)
        # column order b*2 + s (s=0 skip, s=1 take): LIFO pops explore
        # "take" first, finding greedy-ish incumbents early
        children = jnp.stack([skip_b, take_b], axis=2).reshape(n, 2 * B)
        child_depth = jnp.broadcast_to((p_depth + 1)[:, None], (B, 2)) \
            .reshape(-1).astype(jnp.int16)
        new_w = jnp.stack([weight, weight + w_it], axis=1).reshape(-1)
        new_v = jnp.stack([value, value + v_it], axis=1).reshape(-1)
        evaluated = jnp.broadcast_to(valid[:, None], (B, 2)).reshape(-1)
        return base.BranchOut(
            children=children, child_depth=child_depth,
            child_aux=jnp.stack([new_w, new_v], axis=0),
            evaluated=evaluated, extras=new_w <= tables.cap)

    def bound(self, tables: KnapsackTables, lb_kind: int, br, best):
        import jax.numpy as jnp
        n = tables.w.shape[0]
        feasible = br.extras
        s = br.child_depth.astype(jnp.int32)          # first undecided
        W, V = br.child_aux[0], br.child_aux[1]
        r = tables.cap - W                            # (C,) residual
        base_w = jnp.take(tables.cumw, jnp.minimum(s, n))
        rel = tables.cumw[None, 1:] - base_w[:, None]  # (C, n) incl-i
        idx = jnp.arange(n, dtype=jnp.int32)[None, :]
        # weights >= 1 make `rel` strictly increasing over the suffix,
        # so the fit mask is a prefix of items s..n-1 (Dantzig greedy)
        can = (idx >= s[:, None]) & (rel <= r[:, None])
        int_val = jnp.sum(jnp.where(can, tables.v[None, :], 0), axis=1)
        taken_w = jnp.sum(jnp.where(can, tables.w[None, :], 0), axis=1)
        k = s + can.sum(axis=1, dtype=jnp.int32)      # first overflow
        has_frac = k < n
        kc = jnp.clip(k, 0, n - 1)
        wk = jnp.take(tables.w, kc).astype(jnp.int64)
        vk = jnp.take(tables.v, kc).astype(jnp.int64)
        rbar = (r - taken_w).astype(jnp.int64)        # residual at k
        if lb_kind == 2:
            # Martello–Toth U2 = max(U0, U1) — module docstring has the
            # derivation; all products in int64 (sums are <= 2^30 by
            # admission, but products of two such cross int32)
            kp = jnp.clip(k + 1, 0, n - 1)
            wk1 = jnp.take(tables.w, kp).astype(jnp.int64)
            vk1 = jnp.take(tables.v, kp).astype(jnp.int64)
            u0 = jnp.where(k + 1 < n, (rbar * vk1) // wk1, 0)
            km = jnp.clip(k - 1, 0, n - 1)
            wm = jnp.take(tables.w, km).astype(jnp.int64)
            vm = jnp.take(tables.v, km).astype(jnp.int64)
            need = wk - rbar
            lost = (need * vm + wm - 1) // wm          # ceil division
            u1 = vk - lost
            # items before s are fixed: U1 needs a non-empty greedy
            # prefix to displace from (k-1 >= s), else take-k is
            # infeasible and U0 alone covers the skip-k case
            frac = jnp.where(
                has_frac,
                jnp.where(k - 1 >= s, jnp.maximum(u0, u1), u0),
                0).astype(jnp.int32)
        else:
            frac = jnp.where(has_frac,
                             (rbar * vk) // jnp.maximum(wk, 1),
                             0).astype(jnp.int32)
        ub = V + int_val + frac
        return jnp.where(feasible, -ub, I32_MAX).astype(jnp.int32)

    def display_objective(self, best: int) -> int:
        """The engine minimizes -value; report the value."""
        return -int(best)

    def engine_objective(self, value: int) -> int:
        """A user-facing value bound seeds the incumbent as -value."""
        return -int(value)


PROBLEM = base.register(KnapsackProblem())
