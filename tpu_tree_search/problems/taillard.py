"""Taillard PFSP benchmark instances, regenerated from the published seeds.

The 120 standard instances of the Permutation Flowshop Scheduling Problem
(Taillard, EJOR 1993) are defined by a Lehmer linear congruential generator
and a per-instance seed; no data files are needed. This module reproduces
the exact processing-time matrices the reference engine uses
(reference: pfsp/lib/c_taillard.c:76-105) including the quirk that the
uniform draw divides in *float32* before widening to float64 — bit-for-bit
matrix equality with the C code requires replicating that.

Also carries the proven optimal makespans of all 120 instances
(reference: pfsp/lib/c_taillard.c:32-44), which double as the correctness
oracle: a correct B&B run seeded with `ub=opt` must terminate and report
exactly this value.
"""

from __future__ import annotations

import numpy as np

# Per-instance seeds for the processing-time generator, ta001..ta120
# (reference: pfsp/lib/c_taillard.c:6-30; originally Taillard 1993).
TIME_SEEDS = (
    873654221, 379008056, 1866992158, 216771124, 495070989,
    402959317, 1369363414, 2021925980, 573109518, 88325120,
    587595453, 1401007982, 873136276, 268827376, 1634173168,
    691823909, 73807235, 1273398721, 2065119309, 1672900551,
    479340445, 268827376, 1958948863, 918272953, 555010963,
    2010851491, 1519833303, 1748670931, 1923497586, 1829909967,
    1328042058, 200382020, 496319842, 1203030903, 1730708564,
    450926852, 1303135678, 1273398721, 587288402, 248421594,
    1958948863, 575633267, 655816003, 1977864101, 93805469,
    1803345551, 49612559, 1899802599, 2013025619, 578962478,
    1539989115, 691823909, 655816003, 1315102446, 1949668355,
    1923497586, 1805594913, 1861070898, 715643788, 464843328,
    896678084, 1179439976, 1122278347, 416756875, 267829958,
    1835213917, 1328833962, 1418570761, 161033112, 304212574,
    1539989115, 655816003, 960914243, 1915696806, 2013025619,
    1168140026, 1923497586, 167698528, 1528387973, 993794175,
    450926852, 1462772409, 1021685265, 83696007, 508154254,
    1861070898, 26482542, 444956424, 2115448041, 118254244,
    471503978, 1215892992, 135346136, 1602504050, 160037322,
    551454346, 519485142, 383947510, 1968171878, 540872513,
    2013025619, 475051709, 914834335, 810642687, 1019331795,
    2056065863, 1342855162, 1325809384, 1988803007, 765656702,
    1368624604, 450181436, 1927888393, 1759567256, 606425239,
    19268348, 1298201670, 2041736264, 379756761, 28837162,
)

# Proven optimal makespans ta001..ta120 (reference: pfsp/lib/c_taillard.c:32-44).
OPTIMAL_MAKESPAN = (
    1278, 1359, 1081, 1293, 1235, 1195, 1234, 1206, 1230, 1108,      # 20x5
    1582, 1659, 1496, 1377, 1419, 1397, 1484, 1538, 1593, 1591,      # 20x10
    2297, 2099, 2326, 2223, 2291, 2226, 2273, 2200, 2237, 2178,      # 20x20
    2724, 2834, 2621, 2751, 2863, 2829, 2725, 2683, 2552, 2782,      # 50x5
    2991, 2867, 2839, 3063, 2976, 3006, 3093, 3037, 2897, 3065,      # 50x10
    3846, 3699, 3640, 3719, 3610, 3679, 3704, 3691, 3741, 3755,      # 50x20
    5493, 5268, 5175, 5014, 5250, 5135, 5246, 5094, 5448, 5322,      # 100x5
    5770, 5349, 5676, 5781, 5467, 5303, 5595, 5617, 5871, 5845,      # 100x10
    6173, 6183, 6252, 6254, 6285, 6331, 6223, 6372, 6247, 6404,      # 100x20
    10862, 10480, 10922, 10889, 10524, 10329, 10854, 10730, 10438, 10675,  # 200x10
    11158, 11160, 11281, 11275, 11259, 11176, 11337, 11301, 11146, 11284,  # 200x20
    26040, 26500, 26371, 26456, 26334, 26469, 26389, 26560, 26005, 26457,  # 500x20
)

# Instances never solved to optimality in the reference's campaigns
# (reference: pfsp/launch_scripts/mgpu_launch.sh:96) - useful to know when
# choosing benchmark workloads.
UNSOLVED_IN_REFERENCE_CAMPAIGNS = frozenset(
    {51, 54, 55, 59, 60, 81, 85, 86, 87, 88, 89, 102}
)


def nb_jobs(inst: int) -> int:
    """Number of jobs of instance ta{inst} (reference: c_taillard.c:46-53)."""
    if inst > 110:
        return 500
    if inst > 90:
        return 200
    if inst > 60:
        return 100
    if inst > 30:
        return 50
    return 20


def nb_machines(inst: int) -> int:
    """Number of machines of instance ta{inst} (reference: c_taillard.c:55-69)."""
    if inst > 110 or inst > 100:
        return 20
    if inst > 90:
        return 10
    if inst > 80:
        return 20
    if inst > 70:
        return 10
    if inst > 60:
        return 5
    if inst > 50:
        return 20
    if inst > 40:
        return 10
    if inst > 30:
        return 5
    if inst > 20:
        return 20
    if inst > 10:
        return 10
    return 5


def optimal_makespan(inst: int) -> int:
    """Proven optimal makespan of ta{inst} (reference: c_taillard.c:71-74)."""
    return OPTIMAL_MAKESPAN[inst - 1]


def _lehmer_next(seed: int) -> int:
    """One step of the Lehmer LCG used by Taillard's generator.

    x <- 16807 * x mod (2^31 - 1), computed with Schrage's decomposition
    exactly as the published generator does (reference: c_taillard.c:76-88).
    """
    m = 2147483647
    a = 16807
    b = 127773
    c = 2836
    k = seed // b
    seed = a * (seed % b) - k * c
    if seed < 0:
        seed += m
    return seed


def _unif_0_99(seed: int) -> tuple[int, int]:
    """Draw uniform in [1, 99] the way the reference does.

    The reference divides in single precision — `(float)seed / (float)m`
    (c_taillard.c:85) — before scaling in double; replicating that float32
    rounding is required for bit-identical matrices.
    """
    seed = _lehmer_next(seed)
    q = np.float32(seed) / np.float32(2147483647)
    value = 1 + int(float(q) * 99.0)
    return seed, value


def processing_times(inst: int, dtype=np.int32) -> np.ndarray:
    """Processing-time matrix of ta{inst}, shape (machines, jobs).

    Row-major machine-by-job layout, matching the reference's `ptm[i*N+j]`
    indexing (c_taillard.c:100-104): `p[m, j]` is the processing time of
    job `j` on machine `m`.
    """
    n = nb_jobs(inst)
    m = nb_machines(inst)
    seed = TIME_SEEDS[inst - 1]
    out = np.empty((m, n), dtype=dtype)
    for i in range(m):
        for j in range(n):
            seed, v = _unif_0_99(seed)
            out[i, j] = v
    return out


def instance(inst: int) -> tuple[np.ndarray, int, int]:
    """(processing_times, jobs, machines) of ta{inst} (c_taillard.c:107-113)."""
    p = processing_times(inst)
    return p, p.shape[1], p.shape[0]
