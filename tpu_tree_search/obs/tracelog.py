"""Structured span/event log — the flight recorder's write path.

A process-wide, thread-safe recorder of what the search runtime did and
when: ``event(name, **attrs)`` records a point-in-time fact,
``span(name, **attrs)`` brackets a duration (context manager; one record
at exit carrying the start timestamp and the measured duration). Records
land in a bounded ring buffer (old records drop silently — the recorder
must never become the memory leak it exists to debug) and, when a sink
is configured, are appended as JSON-lines to a file as they happen, so a
killed process leaves a durable record up to its last write. The sink is
size-capped too (``TTS_TRACE_MAX_MB``, default 64, 0 disables): at the
cap it rotates to a single ``.1`` sibling and restarts, so a month-long
serve session's recorder is bounded on disk as well as in RAM.

Record schema (one JSON object per line in the sink)::

    {"kind": "span" | "event",
     "name": "request.dispatch",
     "ts":   12.345678,          # seconds on this recorder's monotonic
                                 # clock (t0 = recorder creation)
     "dur":  0.25,               # spans only: seconds
     "seq":  417,                # process-wide ordering tiebreak
     "pid":  31337, "thread": "tts-service-exec-0",
     ...flat attributes: request_id, submesh, segment, ...}

The sink file starts with one ``{"kind": "meta", ...}`` line mapping the
monotonic clock to wall time (``t0_unix``), so offline readers can
reconstruct absolute times.

Ambient context: :func:`context` installs thread-local attributes merged
into every record the thread emits while inside it. The service wraps
each request's executor thread in ``context(request_id=..., submesh=...)``
so the engine-level spans it drives (segments, checkpoint saves, retry
events) are attributable to the request WITHOUT threading ids through
every engine API.

Module-level :func:`span` / :func:`event` write to the process-global
recorder (lazily built; ``TTS_TRACE_FILE`` configures its sink,
``TTS_TRACE_RING`` its capacity). Tests swap the global with
:func:`install` for isolation.
"""

from __future__ import annotations

import collections
import contextlib
import itertools
import json
import os
import threading
import time

__all__ = ["TraceLog", "get", "install", "span", "event", "context",
           "span_at", "current_context"]


def _json_safe(v):
    """Attrs must serialize without surprises; anything exotic becomes
    its repr rather than poisoning the whole sink line."""
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:                       # numpy scalars and friends
        return v.item()
    except (AttributeError, ValueError):
        return repr(v)


class _Span:
    """Handle yielded by :meth:`TraceLog.span`; carries the measured
    duration after exit (``.dur``) and accepts late attributes via
    :meth:`set` (e.g. a result computed inside the span)."""

    __slots__ = ("name", "attrs", "t_start", "dur")

    def __init__(self, name: str, attrs: dict):
        self.name = name
        self.attrs = attrs
        self.t_start = 0.0
        self.dur = 0.0

    def set(self, **attrs) -> None:
        self.attrs.update(attrs)


class TraceLog:
    """Thread-safe bounded span/event recorder with an optional JSONL
    file sink. See the module docstring for the record schema."""

    def __init__(self, capacity: int = 16384,
                 sink_path: str | os.PathLike | None = None,
                 max_sink_bytes: int | None = None):
        self.t0 = time.monotonic()
        self.t0_unix = time.time()
        self._lock = threading.Lock()
        self._buf: collections.deque[dict] = collections.deque(
            maxlen=max(int(capacity), 1))   # guarded-by: self._lock
        self._seq = itertools.count()
        self._tls = threading.local()
        self._sink = None        # guarded-by: self._lock
        self._sink_bytes = 0     # guarded-by: self._lock
        self.rotations = 0       # guarded-by: self._lock
        # size-capped rotation (TTS_TRACE_MAX_MB, 0 disables): at the
        # cap the sink rolls to a `.1` sibling and restarts — a long
        # serve session's recorder is bounded at ~2x the cap on disk
        if max_sink_bytes is None:
            try:
                from ..utils.config import env_float
                mb = env_float("TTS_TRACE_MAX_MB")
            except ImportError:  # keep the recorder usable solo
                mb = 64.0
            max_sink_bytes = int(mb * (1 << 20))
        self.max_sink_bytes = max(int(max_sink_bytes), 0)
        self.dropped = 0           # guarded-by: self._lock
        #                            (records evicted from the ring)
        # fan-out listeners (the durable obs-store sink subscribes
        # here): called OUTSIDE the lock with the finished record — a
        # slow listener must not serialize the recorder — and a raising
        # listener is dropped, never propagated
        self._listeners: list = []
        if sink_path:
            self.set_sink(sink_path)

    def add_listener(self, fn) -> None:
        """Subscribe `fn(record)` to every emitted record."""
        self._listeners.append(fn)

    def remove_listener(self, fn) -> None:
        try:
            self._listeners.remove(fn)
        except ValueError:
            pass

    # ------------------------------------------------------------- sink

    def set_sink(self, path: str | os.PathLike | None) -> None:
        """Start (or stop, with None) appending records to a JSONL file.
        Opening writes the meta line that anchors the monotonic clock."""
        with self._lock:
            if self._sink is not None:
                self._sink.close()
                self._sink = None
            if path is None:
                return
            path = os.fspath(path)
            d = os.path.dirname(path)
            if d:
                os.makedirs(d, exist_ok=True)
            try:
                self._sink_bytes = os.path.getsize(path)
            except OSError:
                self._sink_bytes = 0
            self._sink = open(path, "a", buffering=1)   # line-buffered
            meta = json.dumps({"kind": "meta", "t0_unix": self.t0_unix,
                               "pid": os.getpid()}) + "\n"
            self._sink.write(meta)
            self._sink_bytes += len(meta)
            self._sink_path = path

    def _rotate_locked(self) -> None:    # holds: self._lock
        """Roll the sink to `<path>.1` (replacing any previous rollover)
        and restart it fresh; caller holds the lock. A rotation failure
        downgrades to sink-off — the recorder must never raise."""
        path = self._sink_path
        try:
            self._sink.close()
            os.replace(path, path + ".1")
            self._sink_bytes = 0
            self._sink = open(path, "a", buffering=1)
            meta = json.dumps(
                {"kind": "meta", "t0_unix": self.t0_unix,
                 "pid": os.getpid(), "rotation": self.rotations + 1})
            self._sink.write(meta + "\n")
            self._sink_bytes += len(meta) + 1
            self.rotations += 1
        except (OSError, ValueError):
            self._sink = None

    @property
    def sink_path(self) -> str | None:
        return getattr(self, "_sink_path", None) if self._sink else None

    # ---------------------------------------------------------- context

    @contextlib.contextmanager
    def context(self, **attrs):
        """Thread-local ambient attributes merged into every record this
        thread emits inside the block (nestable; inner wins on clash)."""
        stack = getattr(self._tls, "ctx", None)
        if stack is None:
            stack = self._tls.ctx = []
        stack.append({k: _json_safe(v) for k, v in attrs.items()})
        try:
            yield
        finally:
            stack.pop()

    def _ambient(self) -> dict:
        out = {}
        for layer in getattr(self._tls, "ctx", ()):
            out.update(layer)
        return out

    def current_context(self) -> dict:
        """This thread's merged ambient attributes — the hand-off for
        work delegated to ANOTHER thread (the async checkpoint writer
        re-installs it so its records keep the request identity)."""
        return dict(self._ambient())

    # ------------------------------------------------------------ write

    def _emit(self, rec: dict) -> None:
        with self._lock:
            rec["seq"] = next(self._seq)
            if len(self._buf) == self._buf.maxlen:
                self.dropped += 1
            self._buf.append(rec)
            if self._sink is not None:
                try:
                    line = json.dumps(rec) + "\n"
                    self._sink.write(line)
                    self._sink_bytes += len(line)
                    if self.max_sink_bytes \
                            and self._sink_bytes >= self.max_sink_bytes:
                        self._rotate_locked()
                except (OSError, ValueError):
                    # a torn sink (disk full, closed fd) must never take
                    # the search down; the ring buffer keeps recording
                    self._sink = None
        for fn in list(self._listeners):
            try:
                fn(rec)
            except Exception:
                self.remove_listener(fn)

    def event(self, name: str, **attrs) -> dict:
        """Record a point-in-time event; returns the record."""
        rec = {"kind": "event", "name": name,
               "ts": round(time.monotonic() - self.t0, 6),
               "pid": os.getpid(),
               "thread": threading.current_thread().name,
               **self._ambient(),
               **{k: _json_safe(v) for k, v in attrs.items()}}
        self._emit(rec)
        return rec

    @contextlib.contextmanager
    def span(self, name: str, **attrs):
        """Bracket a duration. One record is emitted at exit (so the
        ring holds only completed work); its ``ts`` is the span START.
        An exception inside the span is recorded as ``error=<repr>`` and
        re-raised — a failed operation leaves a trace, not a hole."""
        sp = _Span(name, {k: _json_safe(v) for k, v in attrs.items()})
        ambient = self._ambient()
        t_start = time.monotonic()
        sp.t_start = t_start - self.t0
        try:
            yield sp
        except BaseException as e:
            sp.attrs.setdefault("error", repr(e))
            raise
        finally:
            sp.dur = time.monotonic() - t_start
            self._emit({"kind": "span", "name": name,
                        "ts": round(sp.t_start, 6),
                        "dur": round(sp.dur, 6),
                        "pid": os.getpid(),
                        "thread": threading.current_thread().name,
                        **ambient, **sp.attrs})

    def span_at(self, name: str, t_start: float, t_end: float,
                **attrs) -> None:
        """Emit a completed span with EXPLICIT monotonic timestamps
        (``time.monotonic()`` values). The overlapped segment driver
        needs this: its ``segment`` spans cover [dispatch, results
        ready] — an interval that straddles other host work and the
        NEXT segment's dispatch, so no ``with`` block can bracket it.
        Consecutive spans emitted this way may overlap in wall time;
        gap analyses (tools/search_report.py) clamp negatives to 0."""
        self._emit({"kind": "span", "name": name,
                    "ts": round(t_start - self.t0, 6),
                    "dur": round(max(t_end - t_start, 0.0), 6),
                    "pid": os.getpid(),
                    "thread": threading.current_thread().name,
                    **self._ambient(),
                    **{k: _json_safe(v) for k, v in attrs.items()}})

    # ------------------------------------------------------------- read

    def records(self) -> list[dict]:
        """Snapshot of the ring buffer, oldest first."""
        with self._lock:
            return list(self._buf)

    def clear(self) -> None:
        with self._lock:
            self._buf.clear()
            self.dropped = 0

    def __len__(self) -> int:
        with self._lock:
            return len(self._buf)


# ----------------------------------------------------------- global log

_global: TraceLog | None = None
_global_lock = threading.Lock()


def get() -> TraceLog:
    """The process-global recorder (built lazily from TTS_TRACE_FILE /
    TTS_TRACE_RING on first use)."""
    global _global
    with _global_lock:
        if _global is None:
            try:
                from ..utils.config import env_int, env_str
                capacity = env_int("TTS_TRACE_RING")
                sink = env_str("TTS_TRACE_FILE")
            except ImportError:     # keep the recorder usable solo
                capacity, sink = 16384, None
            _global = TraceLog(capacity=capacity, sink_path=sink)
        return _global


def install(log: TraceLog | None) -> TraceLog:
    """Swap the process-global recorder (tests; None re-arms the lazy
    env-driven build). Returns the previous one, if any."""
    global _global
    with _global_lock:
        prev = _global
        _global = log
        return prev


def span(name: str, **attrs):
    """`get().span(...)` — the instrumentation sites' one-liner."""
    return get().span(name, **attrs)


def event(name: str, **attrs) -> dict:
    """`get().event(...)` — the instrumentation sites' one-liner."""
    return get().event(name, **attrs)


def context(**attrs):
    """`get().context(...)` — ambient attributes for this thread."""
    return get().context(**attrs)


def span_at(name: str, t_start: float, t_end: float, **attrs) -> None:
    """`get().span_at(...)` — explicit-timestamp span emission."""
    get().span_at(name, t_start, t_end, **attrs)


def current_context() -> dict:
    """`get().current_context()` — this thread's ambient attributes."""
    return get().current_context()
