"""OpenTelemetry export for the flight recorder — the ROADMAP follow-on.

The tracelog record schema (obs/tracelog: flat span/event JSON with a
monotonic ``ts`` anchored to wall time by the sink's meta line) maps
1:1 onto OTLP:

- records are grouped into one OTLP **trace per request**
  (``request_id`` attribute; records without one share a ``session``
  trace), under a synthetic root span covering the group's time range —
  so Jaeger/Tempo show each served request as one trace beside the rest
  of a fleet;
- ``kind: "span"`` records become child **spans** (start = t0 + ts,
  end = start + dur, every flat attribute preserved);
- ``kind: "event"`` records become **span events** on the group root
  (same name, same attributes, exact timestamp).

Two layers, so the container never needs opentelemetry installed:

- :func:`records_to_otlp` — the pure mapping, producing the OTLP/JSON
  (``resourceSpans``/``scopeSpans``) encoding with no dependency at
  all. Tests pin the 1:1 schema against it.
- :func:`export` — ships records through the OpenTelemetry **SDK**
  (``TracerProvider`` + OTLP exporter) when it is importable, and
  NO-OPS with a single warning when it is not. The import is guarded
  per call: ``opentelemetry`` may exist as a bare namespace/API package
  (it does in this repo's container) — the gate probes the SDK and the
  OTLP exporter, the parts an export actually needs.

Usage::

    from tpu_tree_search.obs import otel, tracelog
    otel.export(tracelog.get().records(),
                endpoint="http://localhost:4318/v1/traces")

or ``serve --otel-endpoint http://...:4318/v1/traces`` to export the
session's ring buffer at server shutdown.
"""

from __future__ import annotations

import os
import struct
import time
import warnings
import zlib

__all__ = ["available", "records_to_otlp", "export",
           "IncrementalExporter"]

SERVICE_NAME = "tpu_tree_search"
_SESSION_GROUP = "session"

_warned = False


def _sdk():
    """The guarded SDK import: (trace_api, TracerProvider, Resource,
    SimpleSpanProcessor, OTLPSpanExporter) or None when any piece is
    missing. `opentelemetry` alone proves nothing — the API package
    installs as a namespace shell without the SDK."""
    try:
        from opentelemetry import trace as trace_api
        from opentelemetry.exporter.otlp.proto.http.trace_exporter \
            import OTLPSpanExporter
        from opentelemetry.sdk.resources import Resource
        from opentelemetry.sdk.trace import TracerProvider
        from opentelemetry.sdk.trace.export import SimpleSpanProcessor
    except ImportError:
        return None
    return (trace_api, TracerProvider, Resource, SimpleSpanProcessor,
            OTLPSpanExporter)


def available() -> bool:
    """True when the OpenTelemetry SDK + OTLP exporter are importable."""
    return _sdk() is not None


# ------------------------------------------------------------ pure mapping

def _attr_value(v):
    """One OTLP AnyValue (the JSON encoding's tagged union)."""
    if isinstance(v, bool):
        return {"boolValue": v}
    if isinstance(v, int):
        return {"intValue": str(v)}       # OTLP/JSON int64s are strings
    if isinstance(v, float):
        return {"doubleValue": v}
    if v is None:
        return {"stringValue": ""}
    if isinstance(v, (list, tuple)):
        return {"arrayValue": {"values": [_attr_value(x) for x in v]}}
    return {"stringValue": str(v)}


def _attrs(rec: dict, skip=("kind", "name", "ts", "dur", "seq")) -> list:
    return [{"key": k, "value": _attr_value(v)}
            for k, v in rec.items() if k not in skip]


def _span_id(*parts) -> str:
    """Deterministic 8-byte span id from the record identity (CRC64-ish
    via two CRC32s) — deterministic so re-exports of the same log are
    idempotent on the backend."""
    seed = "\x00".join(str(p) for p in parts)
    a = zlib.crc32(seed.encode())
    b = zlib.crc32(seed.encode()[::-1], 0xDEADBEEF)
    return struct.pack(">II", a, b).hex()


def _trace_id(group: str, t0_unix: float) -> str:
    return _span_id(group, t0_unix) + _span_id(t0_unix, group)


def _anchor(records: list[dict], t0_unix: float | None) -> float:
    """Wall-clock anchor for the records' monotonic ts (the sink meta
    line's value when the caller has it; defaults to now minus the
    largest ts — a best-effort anchor for ring snapshots)."""
    if t0_unix is not None:
        return t0_unix
    horizon = max((float(r.get("ts", 0.0)) + float(r.get("dur", 0.0))
                   for r in records), default=0.0)
    return time.time() - horizon


def _grouped(records: list[dict]) -> list[tuple[str, list[dict]]]:
    """One OTLP trace per request_id (records without one share the
    session group), sorted for deterministic export order — THE
    grouping rule, shared by the pure mapping and the SDK export so
    the pinned schema and the shipped spans cannot drift."""
    groups: dict[str, list[dict]] = {}
    for r in records:
        if r.get("kind") == "meta":
            continue
        groups.setdefault(str(r.get("request_id") or _SESSION_GROUP),
                          []).append(r)
    return sorted(groups.items())


def records_to_otlp(records: list[dict],
                    service_name: str = SERVICE_NAME,
                    t0_unix: float | None = None) -> dict:
    """Map tracelog records to the OTLP/JSON trace encoding (pure — no
    opentelemetry import). `t0_unix` anchors the records' monotonic
    clock to wall time (see _anchor)."""
    records = [r for r in records if r.get("kind") != "meta"]
    t0_unix = _anchor(records, t0_unix)

    def ns(ts: float) -> str:
        return str(int((t0_unix + ts) * 1e9))

    spans = []
    for group, recs in _grouped(records):
        trace_id = _trace_id(group, t0_unix)
        root_id = _span_id(group, "root", t0_unix)
        lo = min(float(r.get("ts", 0.0)) for r in recs)
        hi = max(float(r.get("ts", 0.0)) + float(r.get("dur", 0.0))
                 for r in recs)
        events = []
        children = []
        for r in recs:
            ts = float(r.get("ts", 0.0))
            if r.get("kind") == "span":
                children.append({
                    "traceId": trace_id,
                    "spanId": _span_id(group, r.get("name"), ts,
                                       r.get("seq")),
                    "parentSpanId": root_id,
                    "name": str(r.get("name", "?")),
                    "kind": 1,                    # SPAN_KIND_INTERNAL
                    "startTimeUnixNano": ns(ts),
                    "endTimeUnixNano": ns(ts + float(r.get("dur", 0.0))),
                    "attributes": _attrs(r),
                })
            else:
                events.append({
                    "name": str(r.get("name", "?")),
                    "timeUnixNano": ns(ts),
                    "attributes": _attrs(r),
                })
        spans.append({
            "traceId": trace_id, "spanId": root_id,
            "name": group, "kind": 1,
            "startTimeUnixNano": ns(lo), "endTimeUnixNano": ns(hi),
            "attributes": [{"key": "tts.group",
                            "value": _attr_value(group)}],
            "events": events,
        })
        spans.extend(children)

    return {"resourceSpans": [{
        "resource": {"attributes": [
            {"key": "service.name", "value": _attr_value(service_name)},
            {"key": "process.pid", "value": _attr_value(os.getpid())},
        ]},
        "scopeSpans": [{
            "scope": {"name": "tpu_tree_search.obs.tracelog"},
            "spans": spans,
        }],
    }]}


# ----------------------------------------------------------- SDK export

def export(records: list[dict], endpoint: str | None = None,
           service_name: str = SERVICE_NAME,
           t0_unix: float | None = None) -> int:
    """Export tracelog records as OTLP spans/events via the
    OpenTelemetry SDK. Returns the number of OTLP spans shipped; when
    the SDK is NOT installed this is a clean no-op returning 0 (one
    RuntimeWarning per process) — observability extras must never take
    the search down or force a dependency into the container.

    `endpoint` is the OTLP/HTTP traces URL (default: the SDK's own
    OTEL_EXPORTER_OTLP_* environment handling)."""
    global _warned
    sdk = _sdk()
    if sdk is None:
        if not _warned:
            _warned = True
            warnings.warn(
                "opentelemetry SDK not installed; OTel export skipped "
                "(pip install opentelemetry-sdk "
                "opentelemetry-exporter-otlp-proto-http to enable)",
                RuntimeWarning, stacklevel=2)
        return 0
    trace_api, TracerProvider, Resource, SimpleSpanProcessor, \
        OTLPSpanExporter = sdk
    records = [r for r in records if r.get("kind") != "meta"]
    if not records:
        return 0
    t0_unix = _anchor(records, t0_unix)

    def ns(ts: float) -> int:
        return int((t0_unix + ts) * 1e9)

    provider = TracerProvider(resource=Resource.create(
        {"service.name": service_name}))
    exporter = (OTLPSpanExporter(endpoint=endpoint) if endpoint
                else OTLPSpanExporter())
    provider.add_span_processor(SimpleSpanProcessor(exporter))
    tracer = provider.get_tracer("tpu_tree_search.obs.tracelog")

    def flat(rec):
        # same value semantics as _attr_value, in the SDK's native
        # types: None -> "", primitive lists kept, the rest stringified
        out = {}
        for k, v in rec.items():
            if k in ("kind", "name", "ts", "dur", "seq"):
                continue
            if v is None:
                out[k] = ""
            elif isinstance(v, (str, bool, int, float)):
                out[k] = v
            elif isinstance(v, (list, tuple)) and all(
                    isinstance(x, (str, bool, int, float)) for x in v):
                out[k] = list(v)
            else:
                out[k] = str(v)
        return out

    n = 0
    for group, recs in _grouped(records):
        lo = min(float(r.get("ts", 0.0)) for r in recs)
        hi = max(float(r.get("ts", 0.0)) + float(r.get("dur", 0.0))
                 for r in recs)
        root = tracer.start_span(group, start_time=ns(lo),
                                 attributes={"tts.group": group})
        ctx = trace_api.set_span_in_context(root)
        n += 1
        for r in recs:
            ts = float(r.get("ts", 0.0))
            if r.get("kind") == "span":
                sp = tracer.start_span(str(r.get("name", "?")),
                                       context=ctx, start_time=ns(ts),
                                       attributes=flat(r))
                sp.end(end_time=ns(ts + float(r.get("dur", 0.0))))
                n += 1
            else:
                root.add_event(str(r.get("name", "?")),
                               attributes=flat(r), timestamp=ns(ts))
        root.end(end_time=ns(hi))
    provider.shutdown()
    return n


class IncrementalExporter:
    """Repeated export without duplication: tracks the tracelog ``seq``
    watermark (every record carries the process-wide monotonic counter)
    and each :meth:`flush` ships only records newer than the last one
    shipped. This is what ``serve --otel-interval-s`` drives — a
    kill -9'd server has exported everything up to its last interval
    instead of nothing — and a final shutdown flush through the SAME
    instance ships only the tail. Span/trace ids are deterministic
    (CRC of the record identity), so a request whose records land in
    two flushes still renders as one trace on the backend."""

    def __init__(self, endpoint: str | None = None,
                 service_name: str = SERVICE_NAME):
        self.endpoint = endpoint
        self.service_name = service_name
        self.last_seq = -1
        self.spans = 0       # cumulative spans shipped
        self.flushes = 0     # flushes that shipped anything

    def flush(self, records: list[dict]) -> int:
        """Export the records past the watermark; returns spans shipped
        (0 when nothing is new or the SDK is absent)."""
        fresh = [r for r in records
                 if int(r.get("seq", -1)) > self.last_seq]
        if not fresh:
            return 0
        n = export(fresh, endpoint=self.endpoint,
                   service_name=self.service_name)
        # watermark moves AFTER the export: an exporter exception leaves
        # it in place so the next flush retries the same tail
        self.last_seq = max(int(r.get("seq", -1)) for r in fresh)
        if n:
            self.spans += n
            self.flushes += 1
        return n
