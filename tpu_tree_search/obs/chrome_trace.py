"""Chrome ``trace_event`` JSON: timeline export + XLA-trace parsing.

Two halves, one file format:

- **Export** (:func:`to_chrome`, :func:`write_chrome`): convert the
  flight recorder's span/event records (obs/tracelog) to the Chrome
  trace-event format, so a whole serve session — request dispatches,
  preemptions, elastic reshards, checkpoint I/O — opens as a timeline
  in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``. Tracks
  (pid/tid lanes) are derived from the records' attributes: one lane
  per submesh (every record the service's executor threads emit carries
  a ``submesh`` attribute via the recorder's ambient context), one lane
  per remaining thread; point events render as instants on their lane.

- **Import** (:func:`load_xla_trace`, :func:`self_times`): parse the
  traces ``jax.profiler`` writes (the same Chrome format, gzipped) and
  compute per-op SELF times — duration minus directly-contained
  children, because control-flow ops like ``while`` span their bodies
  and summing raw durations double-counts. This parsing used to live
  privately in ``tools/trace_selftime.py``; it moved here so every
  profiling tool (tools/profile_step.py, tools/validate_attribution.py,
  tools/trace_selftime.py) shares one implementation.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import pathlib

__all__ = ["to_chrome", "write_chrome", "read_jsonl",
           "load_xla_trace", "self_times", "bucket_of",
           "bucketed_self_times", "SELF_TIME_BUCKETS"]


# ------------------------------------------------------------------ export

def _track_of(rec: dict) -> str:
    """The timeline lane for a record: submesh-grouped when the record
    carries one (the per-submesh view the ISSUE's flight-recorder story
    needs — which request ran WHERE), else the emitting thread."""
    if "submesh" in rec and rec["submesh"] is not None:
        return f"submesh-{rec['submesh']}"
    return str(rec.get("thread", "main"))


# per-segment search-telemetry events (engine/checkpoint.run_segmented)
# additionally render as Perfetto COUNTER tracks — one lane per counter
# per submesh, next to the span lanes: the pruning-rate / frontier-depth
# / pool-fill time series the compiled loop was a black box for
COUNTER_EVENT = "search.telemetry"
COUNTER_KEYS = ("pruning_rate", "frontier_depth", "pool",
                "steal_sent", "steal_recv")

# resource-sampler sweeps (obs/resource) render as memory COUNTER lanes
# beside the search counters: host RSS plus one in-use/peak pair per
# device, so an HBM ramp lines up with the pool growth that caused it
RESOURCE_EVENT = "resource.sample"

# lane-state transitions (obs/capacity.LaneLedger) render as
# RETROSPECTIVE state slices on a dedicated per-lane track: the event
# fires when a state is LEFT and carries the full duration just spent
# in it, so the slice is drawn backwards from the transition timestamp
LANE_STATE_EVENT = "lane.state"


def _lane_state_slice(rec: dict) -> dict | None:
    """The ``X`` slice a ``lane.state`` transition contributes to its
    ``lane-<submesh>-state`` track: name = the state being left,
    spanning [ts − seconds, ts]. Zero-duration flickers are kept (dur
    0) — Perfetto renders them as ticks, and dropping them would hide
    real scheduler churn."""
    if rec.get("name") != LANE_STATE_EVENT or rec.get("submesh") is None:
        return None
    try:
        dur = max(float(rec.get("seconds", 0.0)), 0.0)
        ts = float(rec.get("ts", 0.0))
    except (TypeError, ValueError):
        return None
    return {"name": str(rec.get("prev", "?")),
            "ts": round((ts - dur) * 1e6, 3),
            "dur": round(dur * 1e6, 3),
            "track": f"lane-{rec['submesh']}-state"}


def _lifeline_of(rec: dict) -> str | None:
    """The per-request LIFELINE lane a record also lands on: every
    ``request.*`` lifecycle event repeats as an instant on one
    ``request-<tag or id>`` track, so a single request's whole story —
    admit, dispatches, preemptions, adoption, terminal — reads as one
    horizontal line instead of being scattered across the submesh lanes
    it actually ran on. Keyed by tag when the record carries one (the
    tag is the identity that SURVIVES a failover re-admission under a
    fresh rid, so both lifetimes land on the same lane)."""
    name = str(rec.get("name", ""))
    if not name.startswith("request."):
        return None
    ident = rec.get("tag") or rec.get("request_id")
    if ident is None:
        return None
    return f"request-{ident}"


def _counter_samples(rec: dict) -> list[tuple[str, float]]:
    """(counter_name, value) pairs a record contributes to Perfetto
    counter tracks; empty for non-counter events."""
    name = rec.get("name")
    if name == COUNTER_EVENT:
        return [(k, rec[k]) for k in COUNTER_KEYS if k in rec]
    if name == RESOURCE_EVENT:
        out = []
        if rec.get("host_rss_bytes") is not None:
            out.append(("host_rss_bytes", rec["host_rss_bytes"]))
        for d in rec.get("devices") or ():
            if not isinstance(d, dict) or d.get("bytes_in_use") is None:
                continue
            out.append((f"device{d.get('id', '?')} bytes_in_use",
                        d["bytes_in_use"]))
            if d.get("peak_bytes_in_use") is not None:
                out.append((f"device{d.get('id', '?')} bytes_peak",
                            d["peak_bytes_in_use"]))
        return out
    return []


def to_chrome(records: list[dict]) -> dict:
    """Convert tracelog records (ring snapshot or JSONL lines) to a
    Chrome trace dict: spans -> complete ``X`` events, point events ->
    instant ``i`` events, plus thread-name metadata so the lanes are
    labeled. Timestamps are the records' monotonic seconds as µs.
    ``search.telemetry`` events additionally emit ``C`` counter samples
    (COUNTER_KEYS), so Perfetto draws per-submesh counter tracks; the
    instant event is kept too — its args carry the full per-segment
    record for tools/search_report.py's Chrome-format path.
    ``request.*`` lifecycle events additionally repeat on a
    per-request LIFELINE lane (see :func:`_lifeline_of`)."""
    tids: dict[str, int] = {}
    events = []
    for rec in records:
        if rec.get("kind") == "meta":
            continue
        track = _track_of(rec)
        tid = tids.setdefault(track, len(tids))
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "name", "ts", "dur", "pid",
                             "thread", "seq")}
        base = {"name": rec.get("name", "?"), "pid": 0, "tid": tid,
                "ts": round(float(rec.get("ts", 0.0)) * 1e6, 3),
                "args": args}
        if rec.get("kind") == "span":
            events.append({**base, "ph": "X",
                           "dur": round(float(rec.get("dur", 0.0)) * 1e6,
                                        3)})
        else:
            events.append({**base, "ph": "i", "s": "t"})
            for key, val in _counter_samples(rec):
                events.append({
                    "ph": "C", "pid": 0, "tid": tid,
                    "name": f"{key} ({track})",
                    "ts": base["ts"],
                    "args": {key.split(" ")[-1]: val}})
            lifeline = _lifeline_of(rec)
            if lifeline is not None and lifeline != track:
                lf_tid = tids.setdefault(lifeline, len(tids))
                events.append({**base, "tid": lf_tid,
                               "ph": "i", "s": "t"})
            sl = _lane_state_slice(rec)
            if sl is not None:
                st_tid = tids.setdefault(sl["track"], len(tids))
                events.append({"name": sl["name"], "ph": "X",
                               "pid": 0, "tid": st_tid,
                               "ts": sl["ts"], "dur": sl["dur"],
                               "args": {"state": sl["name"]}})
    meta = [{"ph": "M", "name": "thread_name", "pid": 0, "tid": tid,
             "args": {"name": track}} for track, tid in tids.items()]
    # sorted lanes first, then events in timestamp order: Perfetto does
    # not require it, but a human reading the raw JSON does
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome(path: str | os.PathLike,
                 records: list[dict] | None = None) -> str:
    """Write a Chrome trace JSON of `records` (default: the global
    recorder's ring buffer). Returns the path written."""
    if records is None:
        from . import tracelog
        records = tracelog.get().records()
    path = pathlib.Path(path)
    if path.parent != pathlib.Path(""):
        path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome(records)))
    return str(path)


def read_jsonl(path: str | os.PathLike) -> list[dict]:
    """Read a tracelog JSONL sink back into records (meta lines and the
    occasional torn final line from a killed process are skipped)."""
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue                  # torn tail write
            if rec.get("kind") != "meta":
                out.append(rec)
    return out


# ------------------------------------------------------------------ import

def load_xla_trace(log_dir: str | os.PathLike) -> list[dict]:
    """Load every trace-event from a ``jax.profiler`` trace directory
    (the gzipped Chrome JSON under plugins/profile/<run>/)."""
    paths = glob.glob(os.path.join(
        os.fspath(log_dir), "plugins", "profile", "*",
        "*.trace.json.gz"))
    ev = []
    for p in paths:
        with gzip.open(p, "rt") as f:
            ev.extend(json.load(f).get("traceEvents", []))
    return ev


# runtime bookkeeping events in the CPU backend's executor lanes — not
# ops, never charge time to them
_CPU_LANE_NOISE = ("ThreadpoolListener::", "ThunkExecutor")


def self_times(events: list[dict], lane: str | None = None):
    """Per-op SELF time (µs) and counts from Chrome trace events.

    Chrome-trace ``X`` events in the device lane nest by timestamp
    containment (control-flow ops like while/conditional span their
    bodies); summing raw durations double-counts, so each op's duration
    is charged minus its directly-contained children. Nesting is only
    meaningful within one (pid, tid) lane — events are grouped first so
    multi-core traces don't cross-attribute children.

    `lane=None` auto-detects: the accelerator backends' ``"XLA Ops"``
    lanes when the trace has any, else the CPU backend's executor
    lanes (``tf_XLA*`` thread names, runtime bookkeeping events
    filtered out) — so the same call attributes a TPU trace and the
    CPU traces CI produces.
    """
    tn = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tn[(e["pid"], e["tid"])] = e["args"]["name"]
    if lane is None:
        lane = ("XLA Ops" if any(n == "XLA Ops" for n in tn.values())
                else "tf_XLA")

    def in_lane(name) -> bool:
        name = str(name)
        return name == lane or (lane == "tf_XLA"
                                and name.startswith("tf_XLA"))

    lanes = collections.defaultdict(list)
    for e in events:
        if (e.get("ph") == "X" and "dur" in e
                and in_lane(tn.get((e.get("pid"), e.get("tid"))))
                and not str(e.get("name", "")).startswith(
                    _CPU_LANE_NOISE)):
            lanes[(e["pid"], e["tid"])].append(e)
    self_us = collections.Counter()
    counts = collections.Counter()
    for xs in lanes.values():
        # sort by start asc, duration desc so parents precede children
        xs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # (end_ts, name) of open enclosing events
        for e in xs:
            ts, dur, name = e["ts"], e["dur"], e["name"]
            while stack and stack[-1][0] <= ts:
                stack.pop()
            self_us[name] += dur
            counts[name] += 1
            if stack:
                self_us[stack[-1][1]] -= dur
            stack.append((ts + dur, name))
    return self_us, counts


# the search step's phase buckets, matched against (lowercased) op
# names — shared by tools/profile_step.py, tools/search_report.py and
# the `profile` CLI subcommand so every self-time table groups ops the
# same way
SELF_TIME_BUCKETS = (
    ("lb2_pair_sweep", ("lb2_bounds",)),
    ("expand_kernel", ("expand_bounds", "pallas")),
    ("sort", ("sort",)),
    ("gather", ("gather", "take", "fusion.")),
    ("scatter_write", ("dynamic_update_slice", "dynamic-update-slice",
                       "scatter")),
    ("copy_concat_pad", ("copy", "concatenate", "pad")),
)


def bucket_of(name: str) -> str:
    low = str(name).lower()
    for bucket, subs in SELF_TIME_BUCKETS:
        if any(s in low for s in subs):
            return bucket
    return "other"


def bucketed_self_times(self_us) -> "collections.Counter":
    """Fold a per-op self-time Counter into the step's phase buckets."""
    out = collections.Counter()
    for name, d in self_us.items():
        out[bucket_of(name)] += d
    return out
