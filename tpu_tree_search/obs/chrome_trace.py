"""Chrome ``trace_event`` JSON: timeline export + XLA-trace parsing.

Two halves, one file format:

- **Export** (:func:`to_chrome`, :func:`write_chrome`): convert the
  flight recorder's span/event records (obs/tracelog) to the Chrome
  trace-event format, so a whole serve session — request dispatches,
  preemptions, elastic reshards, checkpoint I/O — opens as a timeline
  in Perfetto (https://ui.perfetto.dev) or ``chrome://tracing``. Tracks
  (pid/tid lanes) are derived from the records' attributes: one lane
  per submesh (every record the service's executor threads emit carries
  a ``submesh`` attribute via the recorder's ambient context), one lane
  per remaining thread; point events render as instants on their lane.

- **Import** (:func:`load_xla_trace`, :func:`self_times`): parse the
  traces ``jax.profiler`` writes (the same Chrome format, gzipped) and
  compute per-op SELF times — duration minus directly-contained
  children, because control-flow ops like ``while`` span their bodies
  and summing raw durations double-counts. This parsing used to live
  privately in ``tools/trace_selftime.py``; it moved here so every
  profiling tool (tools/profile_step.py, tools/validate_attribution.py,
  tools/trace_selftime.py) shares one implementation.
"""

from __future__ import annotations

import collections
import glob
import gzip
import json
import os
import pathlib

__all__ = ["to_chrome", "write_chrome", "read_jsonl",
           "load_xla_trace", "self_times"]


# ------------------------------------------------------------------ export

def _track_of(rec: dict) -> str:
    """The timeline lane for a record: submesh-grouped when the record
    carries one (the per-submesh view the ISSUE's flight-recorder story
    needs — which request ran WHERE), else the emitting thread."""
    if "submesh" in rec and rec["submesh"] is not None:
        return f"submesh-{rec['submesh']}"
    return str(rec.get("thread", "main"))


# per-segment search-telemetry events (engine/checkpoint.run_segmented)
# additionally render as Perfetto COUNTER tracks — one lane per counter
# per submesh, next to the span lanes: the pruning-rate / frontier-depth
# / pool-fill time series the compiled loop was a black box for
COUNTER_EVENT = "search.telemetry"
COUNTER_KEYS = ("pruning_rate", "frontier_depth", "pool",
                "steal_sent", "steal_recv")


def to_chrome(records: list[dict]) -> dict:
    """Convert tracelog records (ring snapshot or JSONL lines) to a
    Chrome trace dict: spans -> complete ``X`` events, point events ->
    instant ``i`` events, plus thread-name metadata so the lanes are
    labeled. Timestamps are the records' monotonic seconds as µs.
    ``search.telemetry`` events additionally emit ``C`` counter samples
    (COUNTER_KEYS), so Perfetto draws per-submesh counter tracks; the
    instant event is kept too — its args carry the full per-segment
    record for tools/search_report.py's Chrome-format path."""
    tids: dict[str, int] = {}
    events = []
    for rec in records:
        if rec.get("kind") == "meta":
            continue
        track = _track_of(rec)
        tid = tids.setdefault(track, len(tids))
        args = {k: v for k, v in rec.items()
                if k not in ("kind", "name", "ts", "dur", "pid",
                             "thread", "seq")}
        base = {"name": rec.get("name", "?"), "pid": 0, "tid": tid,
                "ts": round(float(rec.get("ts", 0.0)) * 1e6, 3),
                "args": args}
        if rec.get("kind") == "span":
            events.append({**base, "ph": "X",
                           "dur": round(float(rec.get("dur", 0.0)) * 1e6,
                                        3)})
        else:
            events.append({**base, "ph": "i", "s": "t"})
            if rec.get("name") == COUNTER_EVENT:
                for key in COUNTER_KEYS:
                    if key in rec:
                        events.append({
                            "ph": "C", "pid": 0, "tid": tid,
                            "name": f"{key} ({track})",
                            "ts": base["ts"],
                            "args": {key: rec[key]}})
    meta = [{"ph": "M", "name": "thread_name", "pid": 0, "tid": tid,
             "args": {"name": track}} for track, tid in tids.items()]
    # sorted lanes first, then events in timestamp order: Perfetto does
    # not require it, but a human reading the raw JSON does
    events.sort(key=lambda e: (e["ts"], -e.get("dur", 0)))
    return {"traceEvents": meta + events, "displayTimeUnit": "ms"}


def write_chrome(path: str | os.PathLike,
                 records: list[dict] | None = None) -> str:
    """Write a Chrome trace JSON of `records` (default: the global
    recorder's ring buffer). Returns the path written."""
    if records is None:
        from . import tracelog
        records = tracelog.get().records()
    path = pathlib.Path(path)
    if path.parent != pathlib.Path(""):
        path.parent.mkdir(parents=True, exist_ok=True)
    path.write_text(json.dumps(to_chrome(records)))
    return str(path)


def read_jsonl(path: str | os.PathLike) -> list[dict]:
    """Read a tracelog JSONL sink back into records (meta lines and the
    occasional torn final line from a killed process are skipped)."""
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if not ln:
                continue
            try:
                rec = json.loads(ln)
            except json.JSONDecodeError:
                continue                  # torn tail write
            if rec.get("kind") != "meta":
                out.append(rec)
    return out


# ------------------------------------------------------------------ import

def load_xla_trace(log_dir: str | os.PathLike) -> list[dict]:
    """Load every trace-event from a ``jax.profiler`` trace directory
    (the gzipped Chrome JSON under plugins/profile/<run>/)."""
    paths = glob.glob(os.path.join(
        os.fspath(log_dir), "plugins", "profile", "*",
        "*.trace.json.gz"))
    ev = []
    for p in paths:
        with gzip.open(p, "rt") as f:
            ev.extend(json.load(f).get("traceEvents", []))
    return ev


def self_times(events: list[dict], lane: str = "XLA Ops"):
    """Per-op SELF time (µs) and counts from Chrome trace events.

    Chrome-trace ``X`` events in the device lane nest by timestamp
    containment (control-flow ops like while/conditional span their
    bodies); summing raw durations double-counts, so each op's duration
    is charged minus its directly-contained children. Nesting is only
    meaningful within one (pid, tid) lane — events are grouped first so
    multi-core traces don't cross-attribute children.
    """
    tn = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tn[(e["pid"], e["tid"])] = e["args"]["name"]
    lanes = collections.defaultdict(list)
    for e in events:
        if (e.get("ph") == "X" and "dur" in e
                and tn.get((e.get("pid"), e.get("tid"))) == lane):
            lanes[(e["pid"], e["tid"])].append(e)
    self_us = collections.Counter()
    counts = collections.Counter()
    for xs in lanes.values():
        # sort by start asc, duration desc so parents precede children
        xs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # (end_ts, name) of open enclosing events
        for e in xs:
            ts, dur, name = e["ts"], e["dur"], e["name"]
            while stack and stack[-1][0] <= ts:
                stack.pop()
            self_us[name] += dur
            counts[name] += 1
            if stack:
                self_us[stack[-1][1]] -= dur
            stack.append((ts + dur, name))
    return self_us, counts
