"""Online search-tree size / progress estimation (host-side).

The operator's first question — "how far along is this request and
will it meet its deadline?" — has no answer in raw B&B counters: the
explored-node count grows monotonically but the TOTAL tree size is
unknown until the search completes, and wall time spans orders of
magnitude across instances of the same shape.  This module estimates
the total online, in the Knuth '75 / weighted-backtrack-estimator
family (Kilby, Slaney, Thiebaux & Walsh, AAAI 2006 — see PAPERS.md):
instead of probing random root-to-leaf paths, it reuses what the
engine already measures every segment.

Inputs (all already in ``SegmentReport``, zero new device work):

- the cumulative on-device telemetry block (``engine/telemetry.py``)
  when ``TTS_SEARCH_TELEMETRY`` is on: per-depth-bucket popped /
  branched / pruned counts plus the mean relative frontier depth;
- otherwise the aggregate counters every report carries — cumulative
  explored nodes (``tree``) and the live pool size.

Model: B&B exploration below the current frontier is a subcritical
branching process.  Per depth bucket ``k`` the SURVIVOR ratio

    rho_k = (branched_k - pruned_k) / popped_k

is the measured mean number of children of an expanded node that
survive pruning.  The expected total progeny of one open node at
bucket ``k`` then satisfies the cascade

    T_k = 1 + rho_k * T_{k+1}

closed at the deepest bucket with the geometric total ``1/(1-rho)``
(``rho`` clamped below 1 — a supercritical tail has no finite
expectation, so the clamp is the estimator admitting "at least this
much").  Remaining work is ``pool_size * T_f`` where ``f`` is the
bucket of the mean frontier depth; estimated total tree size is
``nodes_done + remaining``.  Without telemetry the same model is
driven by one aggregate ratio from segment deltas: each popped node
is one explored node, so ``rho = 1 + delta_pool / delta_tree``.

Estimates are EWMA-smoothed across segments and published behind a
warmup gate (min segments AND min nodes) so early wild estimates
never reach a gauge.  The PUBLISHED progress is clamped monotone
non-decreasing and strictly below 1.0 until the terminal state
force-finalizes it — so dashboards never show progress moving
backwards and 1.0 always means DONE.

The estimator is pure host-side stdlib (no JAX, no numpy): the server
updates it from heartbeat callbacks and serializes its state as a
flat float vector riding checkpoint meta, so resume / elastic reshard
/ failover adoption continue the estimate instead of restarting cold.
"""

from __future__ import annotations

import math

from ..utils import config as cfg

__all__ = ["ProgressEstimator", "DEPTH_BUCKETS"]

# mirror of engine.telemetry.DEPTH_BUCKETS without importing the
# engine (this module must stay importable with JAX absent)
DEPTH_BUCKETS = 8

# survivor-ratio clamp: above this the branching process is treated as
# (barely) subcritical so the geometric tail stays finite.  1/(1-0.95)
# = 20x multiplier at the deepest band — deliberately conservative;
# the acceptance bar is a factor-of-4 at the half-node point, and an
# over-estimate only makes progress pessimistic (never >1.0 early).
_RHO_MAX = 0.95

# serialized-state layout version (first element of to_list())
_STATE_VERSION = 1.0


class ProgressEstimator:
    """Online tree-size/progress/ETA estimate for ONE request.

    Call :meth:`update` once per segment report (cumulative counters),
    read ``progress`` / ``eta_s`` / ``est_total`` after it returns
    True (warmup passed).  :meth:`finalize` pins the terminal value.
    """

    def __init__(self, *,
                 warmup_segments: int | None = None,
                 warmup_nodes: int | None = None,
                 alpha: float | None = None,
                 depth_hint: float | None = None):
        self.warmup_segments = (
            cfg.env_int("TTS_PROGRESS_WARMUP_SEGMENTS")
            if warmup_segments is None else warmup_segments)
        self.warmup_nodes = (
            cfg.env_int("TTS_PROGRESS_WARMUP_NODES")
            if warmup_nodes is None else warmup_nodes)
        self.alpha = (cfg.env_float("TTS_PROGRESS_EWMA")
                      if alpha is None else alpha)
        # total tree depth in LEVELS when the caller knows it (jobs /
        # cities / items — the server passes the instance's first
        # shape axis).  It bounds the cascade horizon: without it the
        # deepest bucket closes with the INFINITE geometric tail, and
        # during the early no-pruning expansion phase (rho at the
        # clamp) that inflates remaining work to ~20x the pool where
        # the finite-depth closure correctly caps it at about
        # pool * levels-still-below-the-frontier
        self.depth_hint = float(depth_hint or 0.0)
        # cumulative witnesses from the latest update
        self.segments = 0          # update() calls observed
        self.nodes = 0.0           # cumulative explored nodes
        self.pool = 0.0            # live open nodes
        # EWMA state
        self.remaining = 0.0       # smoothed estimated remaining nodes
        self.rate = 0.0            # smoothed nodes/s (live segments)
        self.published = 0.0       # monotone published progress
        self.done = False          # finalize() called
        # previous-update witnesses for the aggregate-delta fallback
        # and the rate clock (elapsed resets per dispatch)
        self._prev_nodes = 0.0
        self._prev_pool = 0.0
        self._prev_elapsed = 0.0

    # ------------------------------------------------------------ update

    def update(self, *, tree: float, pool: float, elapsed: float,
               telemetry: dict | None = None) -> bool:
        """Fold one segment report (CUMULATIVE tree count, live pool,
        wall seconds since dispatch start, optional cumulative
        telemetry summarize dict).  Returns True when the estimate is
        past warmup and publishable."""
        if self.done:
            return True
        tree = float(tree)
        pool = float(pool)
        d_nodes = tree - self._prev_nodes
        d_pool = pool - self._prev_pool
        d_elapsed = float(elapsed) - self._prev_elapsed
        self.segments += 1
        self.nodes = tree
        self.pool = pool
        raw = self._raw_remaining(telemetry, d_nodes, d_pool)
        if raw is not None:
            self.remaining = (raw if self.remaining <= 0.0
                              else self.alpha * raw
                              + (1.0 - self.alpha) * self.remaining)
        # node rate over this window; elapsed restarts every dispatch,
        # so a negative delta (resume/preempt boundary) skips the rate
        # sample rather than poisoning the EWMA
        if d_elapsed > 0.0 and d_nodes >= 0.0:
            r = d_nodes / d_elapsed
            self.rate = (r if self.rate <= 0.0
                         else self.alpha * r
                         + (1.0 - self.alpha) * self.rate)
        self._prev_nodes = tree
        self._prev_pool = pool
        self._prev_elapsed = max(float(elapsed), 0.0)
        if self.ready:
            # monotone publish: never below what we already showed,
            # never 1.0 before the terminal state says so
            self.published = min(0.999,
                                 max(self.published, self._raw_progress))
        return self.ready

    def _raw_remaining(self, telemetry: dict | None,
                       d_nodes: float, d_pool: float) -> float | None:
        """One un-smoothed remaining-work estimate, or None when this
        window carries no usable signal (empty pool = nothing left;
        zero expansion = no new evidence)."""
        if self.pool <= 0.0:
            return 0.0
        if telemetry is not None:
            est = self._depth_resolved(telemetry)
            if est is not None:
                return est
        if d_nodes <= 0.0:
            return None
        rho = min(1.0 + d_pool / d_nodes, _RHO_MAX)
        if rho <= 0.0:
            # frontier collapsing faster than it pops: the open nodes
            # themselves are (about) all that remains
            return self.pool
        return self.pool / (1.0 - rho)

    def _depth_resolved(self, tele: dict) -> float | None:
        """Remaining work from the per-bucket survivor-ratio cascade;
        None when the block has no usable per-bucket counts."""
        popped = tele.get("popped")
        branched = tele.get("branched")
        pruned = tele.get("pruned")
        if not popped or not branched or not pruned:
            return None
        n = len(popped)
        rho = []
        for k in range(n):
            p = float(popped[k])
            if p <= 0.0:
                rho.append(None)       # unvisited band: no evidence
                continue
            surv = max(float(branched[k]) - float(pruned[k]), 0.0)
            rho.append(min(surv / p, _RHO_MAX))
        if all(r is None for r in rho):
            return None
        # fill unvisited bands with the nearest measured shallower
        # band (depth-correlated pruning: deeper bands prune harder,
        # so borrowing shallow ratios over-estimates — safe direction)
        last = next(r for r in rho if r is not None)
        for k in range(n):
            if rho[k] is None:
                rho[k] = last
            else:
                last = rho[k]
        # total-progeny cascade.  With a depth hint each bucket spans
        # `levels = depth / n_buckets` tree LEVELS, so a bucket's own
        # progeny is the FINITE geometric sum over those levels and it
        # passes rho^levels survivors on to the next bucket; without a
        # hint the deepest bucket closes with the infinite tail
        cascade = [0.0] * n
        levels = self.depth_hint / n if self.depth_hint > 0.0 else None

        def own(r: float) -> float:
            # sum_{i=0}^{levels-1} r^i (== levels as r -> 1)
            if levels is None:
                return 1.0
            if abs(1.0 - r) < 1e-9:
                return levels
            return (1.0 - r ** levels) / (1.0 - r)

        if levels is None:
            cascade[-1] = 1.0 / (1.0 - min(rho[-1], _RHO_MAX))
            for k in range(n - 2, -1, -1):
                cascade[k] = 1.0 + rho[k] * cascade[k + 1]
        else:
            cascade[-1] = own(rho[-1])
            for k in range(n - 2, -1, -1):
                cascade[k] = own(rho[k]) \
                    + rho[k] ** levels * cascade[k + 1]
        f = float(tele.get("frontier_depth", 0.0))
        band = min(max(int(f * (n - 1)), 0), n - 1)
        return self.pool * cascade[band]

    # -------------------------------------------------------- properties

    @property
    def ready(self) -> bool:
        """Warmup gate: both minimums met (or already finalized)."""
        return self.done or (self.segments >= self.warmup_segments
                             and self.nodes >= self.warmup_nodes)

    @property
    def _raw_progress(self) -> float:
        total = self.nodes + max(self.remaining, 0.0)
        if total <= 0.0:
            return 0.0
        return self.nodes / total

    @property
    def progress(self) -> float | None:
        """Published progress in [0, 1] — monotone non-decreasing,
        exactly 1.0 only after :meth:`finalize`.  None during warmup."""
        if self.done:
            return 1.0
        return self.published if self.ready else None

    @property
    def est_total(self) -> float | None:
        """Estimated total tree size (nodes); None during warmup."""
        if self.done:
            return self.nodes
        if not self.ready:
            return None
        return self.nodes + max(self.remaining, 0.0)

    def eta_s(self, fallback_rate: float | None = None) -> float | None:
        """Estimated seconds of execution remaining.  Uses the live
        node-rate EWMA, falling back to `fallback_rate` (the tuner's
        measured per-shape evals/s) before the first live window; None
        during warmup or with no rate at all."""
        if self.done:
            return 0.0
        if not self.ready:
            return None
        rate = self.rate if self.rate > 0.0 else (fallback_rate or 0.0)
        if rate <= 0.0:
            return None
        return max(self.remaining, 0.0) / rate

    def finalize(self) -> None:
        """Terminal pin: the search completed, so the estimate becomes
        exact — progress 1.0, remaining 0, ETA 0."""
        self.done = True
        self.remaining = 0.0
        self.published = 1.0

    # ----------------------------------------------------- serialization

    def to_list(self) -> list[float]:
        """Flat float vector for checkpoint meta (np.asarray-safe).
        Captures everything :meth:`from_list` needs to continue the
        estimate warm across resume / reshard / adoption."""
        return [_STATE_VERSION,
                float(self.segments), self.nodes, self.pool,
                self.remaining, self.rate, self.published,
                1.0 if self.done else 0.0,
                self._prev_nodes, self._prev_pool, self.depth_hint]

    @classmethod
    def from_list(cls, vec, **kw) -> "ProgressEstimator | None":
        """Rebuild from :meth:`to_list` output (any float sequence);
        None on an unrecognized/short vector — callers fall back to a
        cold estimator rather than crash on foreign meta."""
        try:
            v = [float(x) for x in vec]
        except (TypeError, ValueError):
            return None
        if len(v) < 10 or not math.isclose(v[0], _STATE_VERSION):
            return None
        est = cls(**kw)
        est.segments = int(v[1])
        est.nodes = v[2]
        est.pool = v[3]
        est.remaining = v[4]
        est.rate = v[5]
        est.published = v[6]
        est.done = v[7] >= 1.0
        est._prev_nodes = v[8]
        est._prev_pool = v[9]
        if len(v) > 10 and v[10] > 0.0:
            est.depth_hint = v[10]
        # elapsed is per-dispatch wall time: a restored estimator is
        # by definition on a NEW dispatch, so the rate clock restarts
        est._prev_elapsed = 0.0
        return est

    def snapshot(self, fallback_rate: float | None = None) -> dict:
        """JSON-safe block for the request's progress snapshot."""
        out = {"segments": self.segments}
        p = self.progress
        if p is not None:
            out["progress_ratio"] = round(p, 4)
            out["est_tree_size"] = round(self.est_total)
            eta = self.eta_s(fallback_rate)
            if eta is not None:
                out["eta_s"] = round(eta, 1)
        return out
