"""Durable fleet flight recorder: the observability STORE.

The engine outlives any single process — the request ledger replays a
kill -9, the failover watcher adopts an orphaned peer's ledger — but
metrics history, health alert lifecycles and trace rings are
process-scoped: they evaporate at exit and zero at boot. This module is
the durability tier under them: an append-only time-series + event
store in the fleet/ledger directory, written with exactly the
``service/ledger.py`` discipline (CRC-stamped JSONL records, fsync'd
batches, segment rotation, corrupt-tail truncation + quarantine) and
replayed at boot so dashboards, health history rings, SLO burn windows
and whitelisted ``tts_*`` counters RESUME instead of restarting from
zero.

Differences from the request ledger, on purpose:

- **Per-writer segment files** (``obs-<writer>-NNNNNNNN.jsonl``): N
  fleet peers share one store directory; each appends only to its own
  segment family (the PR-16 quarantine rule), so there is no cross-host
  write contention and no lock. Replay reads EVERY writer's segments
  (merged by wall time) but repairs — truncates/quarantines — only its
  own: a peer's active segment may legitimately end in a torn line
  while that peer is alive.
- **Bounded-queue sink**: observability must never block the scheduler.
  ``append()`` enqueues; a writer thread drains batches and pays one
  flush+fsync per batch. A full queue DROPS the record (counted) —
  the opposite trade from the checkpoint writer, which blocks, because
  a lost metric sample is a shrug and a lost checkpoint is data loss.
- **Time-based retention, not state compaction**: the ledger compacts
  to absolute state; a time-series store has no absolute form, so at
  rotation whole own-writer segments whose newest record is older than
  the retention window are pruned.
- **Wall-clock timestamps**: tracelog records carry monotonic seconds
  (right for intra-process ordering); store records are stamped with
  ``time.time()`` so windows — the SLO burn rates — compose across
  process lifetimes and hosts.

Record schema (``{"k": kind, "t": wall_s, "w": writer, ...}``):

- ``boot``: one per store open (pid) — lifetime delimiter;
- ``sample``: a metrics snapshot — ``counters``/``gauges`` as
  ``[name, labels, value]`` triples (taken on the resource-sampler
  cadence);
- ``event``: a whitelisted tracelog event (alert transitions,
  remediation/failover/portfolio/batch/request lifecycle), flattened.

Stdlib-only: the ``journey`` CLI subcommand and the lint leg load this
module without the accelerator stack.
"""

from __future__ import annotations

import json
import os
import pathlib
import queue
import threading
import time
import zlib

__all__ = ["ObsStore", "read_store", "resume_counters",
           "RESUME_COUNTERS", "EVENT_PREFIXES", "TERMINAL_EVENTS"]

SEGMENT_PREFIX = "obs-"
SEGMENT_SUFFIX = ".jsonl"
QUARANTINE_SUFFIX = ".corrupt"

BATCH_MAX = 256          # records drained per flush+fsync
DRAIN_POLL_S = 0.2       # writer-thread wakeup when the queue is idle

# tracelog event names the sink persists (prefix match): the durable
# subset is the CONTROL-PLANE story — request lifecycle, alerting,
# remediation, failover, racing, batching — not the per-segment
# telemetry firehose (that stays in the ring / TTS_TRACE_FILE tier)
EVENT_PREFIXES = (
    "request.", "alert.", "remediation.", "failover.", "portfolio.",
    "batch.", "server.", "takeover", "ledger.replay", "journey.",
    # lane-state transitions (obs/capacity.py, TTS_CAPACITY): bounded
    # by scheduler transitions, not per-segment — and only emitted at
    # all when the capacity layer is on, so the off-path store content
    # is unchanged
    "lane.",
)

# request terminal-state events (server._finalize) — the SLO burn
# rules' inputs; mapped to the terminal state they witness
TERMINAL_EVENTS = {
    "request.done": "DONE",
    "request.cancelled": "CANCELLED",
    "request.deadline": "DEADLINE",
    "request.failed": "FAILED",
}

# counters re-seeded from the last replayed snapshot so /metrics
# resumes across a restart. A WHITELIST, not "every counter":
# ledger-fed counters (tts_server_restarts_total, tts_ledger_*) are
# already resumed by the ledger's own replay and would double-count,
# the store's own counters describe THIS lifetime's I/O, and
# engine-tier counters live in the process-global registry (seeding
# them into the server registry would expose the name twice).
RESUME_COUNTERS = (
    "tts_requests_submitted_total",
    "tts_requests_total",
    "tts_preemptions_total",
    "tts_redispatches_total",
    "tts_batches_formed_total",
    "tts_batch_requests_total",
    "tts_portfolio_races_total",
    "tts_portfolio_members_total",
    "tts_alerts_fired_total",
    "tts_takeovers_total",
    # lane-state seconds (obs/capacity.py): the utilization history
    # that must survive kill -9 — the LaneLedger re-seeds its per-state
    # accumulators from the replayed series at boot (replayed seconds
    # tracked separately so conservation stays exact per lifetime)
    "tts_lane_seconds_total",
)

# gauges snapshotted into every sample record — the health monitor's
# history-ring signals, so /dashboard sparklines resume after a boot
SAMPLE_GAUGES = (
    "tts_queue_depth",
    "tts_submeshes_busy",
    "tts_device_bytes_in_use",
    "tts_host_rss_bytes",
    # per-shape-class ρ (obs/capacity.py): exists only with the
    # capacity layer on, so off-path samples are unchanged
    "tts_capacity_utilization",
)


def _canonical(rec: dict) -> bytes:
    return json.dumps(rec, sort_keys=True,
                      separators=(",", ":")).encode()


def _line(rec: dict) -> bytes:
    body = _canonical(rec)
    return json.dumps({"c": zlib.crc32(body),
                       "r": rec}, sort_keys=True,
                      separators=(",", ":")).encode() + b"\n"


def _parse_line(raw: bytes) -> dict | None:
    """One wrapped record, or None on any damage (torn/garbled/CRC)."""
    try:
        outer = json.loads(raw.decode())
        rec = outer["r"]
        if not isinstance(rec, dict):
            return None
        if zlib.crc32(_canonical(rec)) != int(outer["c"]):
            return None
        return rec
    except (ValueError, KeyError, TypeError, UnicodeDecodeError):
        return None


def _safe_writer(writer: str) -> str:
    """Writer ids land in file names; keep them path-safe."""
    return "".join(c if (c.isalnum() or c in "._=+") else "_"
                   for c in str(writer)) or "writer"


def _scan_segment(data: bytes):
    """Yield (record_or_None, end_offset_of_good_prefix) pairs the way
    the ledger's replay walks a segment: byte scan, no readline — a
    torn line is detected at its exact offset."""
    pos = good_end = 0
    while pos < len(data):
        nl = data.find(b"\n", pos)
        raw, nxt = ((data[pos:], len(data)) if nl < 0
                    else (data[pos:nl], nl + 1))
        if raw:
            rec = _parse_line(raw)
            if rec is None:
                yield None, good_end
                return
            yield rec, nxt
        pos = good_end = nxt


def read_store(root: str | os.PathLike) -> list[dict]:
    """Read-only merge of every writer's segments in `root`, sorted by
    wall time. Damaged lines (and everything after them within their
    segment) are skipped, never repaired — the reader may not own the
    files it reads. The tools/CLI entry point."""
    root = pathlib.Path(root)
    out: list[dict] = []
    if not root.is_dir():
        return out
    for seg in sorted(root.iterdir()):
        if not (seg.name.startswith(SEGMENT_PREFIX)
                and seg.name.endswith(SEGMENT_SUFFIX)):
            continue
        try:
            data = seg.read_bytes()
        except OSError:
            continue
        for rec, _end in _scan_segment(data):
            if rec is None:
                break
            out.append(rec)
    out.sort(key=lambda r: r.get("t", 0.0))
    return out


def resume_counters(registry, records: list[dict], writer: str) -> int:
    """Re-seed whitelisted counters from the newest replayed snapshot
    this writer authored, so a restarted server's /metrics continues
    the series instead of restarting at zero. Returns the number of
    series seeded. Ledger-fed counters are deliberately absent from
    RESUME_COUNTERS (the ledger replay already feeds them)."""
    from . import metric_names
    last = None
    for rec in records:
        if rec.get("k") == "sample" and rec.get("w") == writer:
            last = rec
    if last is None:
        return 0
    seeded = 0
    for name, labels, value in last.get("counters") or ():
        if name not in RESUME_COUNTERS or not value:
            continue
        meta = metric_names.REGISTRY.get(name)
        doc = meta.doc if meta is not None else name
        try:
            registry.counter(name, doc).inc(
                float(value), **dict(labels or {}))
        except (TypeError, ValueError):
            continue
        seeded += 1
    return seeded


class ObsStore:
    """One process's handle on the shared observability store.

    Constructing it REPLAYS every writer's segments in `root` (same
    contract as the request ledger: read ``records()`` / ``replayed``
    / ``truncated`` before appending), repairs only this writer's
    family, journals a ``boot`` record, and starts the bounded-queue
    writer thread. All appends go through :meth:`append` — enqueue-only,
    never raises, never blocks.
    """

    def __init__(self, root: str | os.PathLike, writer: str,
                 registry=None,
                 segment_records: int = 4096,
                 retain_s: float = 86400.0,
                 queue_depth: int = 4096,
                 fsync: bool = True):
        self.root = pathlib.Path(root)
        self.root.mkdir(parents=True, exist_ok=True)
        self.writer = _safe_writer(writer)
        self.segment_records = max(2, int(segment_records))
        self.retain_s = float(retain_s)
        self.fsync = fsync
        self._lock = threading.Lock()
        self._fh = None                 # guarded-by: self._lock
        self._seg_index = 0             # guarded-by: self._lock
        self._seg_records = 0           # guarded-by: self._lock
        self._closed = False
        self.records = 0                # appended this lifetime
        self.replayed = 0               # good records replayed at boot
        self.truncated = 0              # corrupt-tail records discarded
        self.quarantined_segments = 0
        self.dropped = 0                # queue-full drops
        self.write_errors = 0
        # terminal-request history (wall_t, state, spent_s, tenant) —
        # the SLO burn rules' window source; seeded by replay, extended
        # live. Bounded: burn windows never exceed the slow window, and
        # retention prunes the disk copy.
        self.terminals: list[tuple] = []
        self._terminal_keep = 65536
        self._replayed_records: list[dict] = []
        self._m_records = self._m_replayed = self._m_truncated = None
        if registry is not None:
            self._m_records = registry.counter(
                "tts_obs_store_records_total",
                "flight-recorder store records appended (batched "
                "fsync'd CRC JSONL)")
            self._m_replayed = registry.counter(
                "tts_obs_store_replayed_total",
                "flight-recorder store records replayed at boot "
                "(all writers)")
            self._m_truncated = registry.counter(
                "tts_obs_store_truncated_total",
                "corrupt-tail store records discarded at replay "
                "(own segments only)")
        self._replay()
        self._q: queue.Queue = queue.Queue(maxsize=max(2, queue_depth))
        self._stop = threading.Event()
        self._thread = threading.Thread(
            target=self._drain_loop, name="obs-store-writer",
            daemon=True)
        self._thread.start()
        self._sampler: threading.Thread | None = None
        self.append("boot", pid=os.getpid())

    # ----------------------------------------------------------- replay

    def _own(self, seg: pathlib.Path) -> bool:
        return seg.name.startswith(
            f"{SEGMENT_PREFIX}{self.writer}-")

    def _segments(self, own_only: bool = False) -> list[pathlib.Path]:
        segs = sorted(p for p in self.root.iterdir()
                      if p.name.startswith(SEGMENT_PREFIX)
                      and p.name.endswith(SEGMENT_SUFFIX))
        if own_only:
            segs = [p for p in segs if self._own(p)]
        return segs

    def _replay(self) -> None:
        corrupt = False
        for seg in self._segments():
            own = self._own(seg)
            if corrupt and own:
                # own segments after the first own corruption are
                # suspect (written after bytes this replay refused):
                # set them aside, exactly the ledger's rule
                self.quarantined_segments += 1
                try:
                    os.replace(seg, str(seg) + QUARANTINE_SUFFIX)
                except OSError:
                    pass
                continue
            try:
                data = seg.read_bytes()
            except OSError:
                continue
            good_end = len(data)
            damaged = False
            for rec, end in _scan_segment(data):
                if rec is None:
                    damaged, good_end = True, end
                    break
                self._note(rec)
                self._replayed_records.append(rec)
                self.replayed += 1
            if not damaged:
                continue
            if not own:
                continue      # a live peer's torn tail is not ours to cut
            corrupt = True
            bad = [ln for ln in data[good_end:].split(b"\n") if ln]
            self.truncated += len(bad)
            try:
                with open(seg, "r+b") as f:
                    f.truncate(good_end)
                    f.flush()
                    os.fsync(f.fileno())
            except OSError:
                pass
        if self._m_replayed is not None and self.replayed:
            self._m_replayed.inc(self.replayed)
        if self._m_truncated is not None and self.truncated:
            self._m_truncated.inc(self.truncated)
        own = self._segments(own_only=True)
        if own:
            last = own[-1]
            # replay runs before the writer thread exists, but these
            # fields are declared lock-guarded: keep the discipline
            with self._lock:
                self._seg_index = int(
                    last.name[:-len(SEGMENT_SUFFIX)].rsplit("-", 1)[-1])
                try:
                    self._seg_records = sum(
                        1 for ln in last.read_bytes().split(b"\n")
                        if ln)
                except OSError:
                    self._seg_records = 0
        self._replayed_records.sort(key=lambda r: r.get("t", 0.0))
        self.terminals.sort(key=lambda row: row[0])

    def records_replayed(self) -> list[dict]:
        """The boot replay's merged record list (all writers, sorted by
        wall time) — the dashboard/health/counter resume feed."""
        return list(self._replayed_records)

    def _note(self, rec: dict) -> None:
        """Fold one record into the in-memory indexes (replay + live)."""
        state = TERMINAL_EVENTS.get(rec.get("name", ""))
        if rec.get("k") == "event" and state is not None:
            self.terminals.append(
                (float(rec.get("t", 0.0)), state,
                 float(rec.get("spent_s") or 0.0),
                 rec.get("tenant") or "-"))
            del self.terminals[:-self._terminal_keep]

    def terminal_history(self, since_s: float | None = None) -> list:
        """(wall_t, state, spent_s, tenant) rows, oldest first —
        optionally only those newer than `since_s` (wall clock)."""
        with self._lock:
            rows = list(self.terminals)
        if since_s is not None:
            rows = [r for r in rows if r[0] >= since_s]
        return rows

    # ----------------------------------------------------------- append

    def append(self, kind: str, **fields) -> None:
        """Enqueue one record for the writer thread. Never raises and
        never blocks: a full queue drops the record (counted) — the
        flight recorder must not become back-pressure on the
        scheduler."""
        if self._closed:
            return
        rec = {"k": kind, "t": time.time(), "w": self.writer, **fields}
        with self._lock:
            self._note(rec)
        try:
            self._q.put_nowait(rec)
        except queue.Full:
            self.dropped += 1

    def on_trace_event(self, rec: dict) -> None:
        """TraceLog listener: persist the control-plane event subset.
        Tracelog timestamps are monotonic; the store re-stamps with
        wall clock at enqueue (cross-lifetime windows need it)."""
        if rec.get("kind") != "event":
            return
        name = rec.get("name", "")
        if not name.startswith(EVENT_PREFIXES):
            return
        fields = {k: v for k, v in rec.items()
                  if k not in ("kind", "ts", "seq", "thread")
                  and _jsonable(v)}
        self.append("event", **fields)

    # ------------------------------------------------------------- sink

    def _seg_path(self, index: int) -> pathlib.Path:
        return self.root / (f"{SEGMENT_PREFIX}{self.writer}-"
                            f"{index:08d}{SEGMENT_SUFFIX}")

    def _drain_loop(self) -> None:
        while True:
            try:
                rec = self._q.get(timeout=DRAIN_POLL_S)
            except queue.Empty:
                if self._stop.is_set():
                    return
                continue
            batch = [rec]
            while len(batch) < BATCH_MAX:
                try:
                    batch.append(self._q.get_nowait())
                except queue.Empty:
                    break
            self._write_batch(batch)
            if self._stop.is_set() and self._q.empty():
                return

    def _write_batch(self, batch: list[dict]) -> None:
        """One flush+fsync per batch; errors degrade durability loudly
        (write_errors) but never propagate — the ledger's stance."""
        with self._lock:
            try:
                if self._fh is None:
                    if self._seg_index == 0:
                        self._seg_index = 1
                    self._fh = open(self._seg_path(self._seg_index),
                                    "ab")
                self._fh.write(b"".join(_line(r) for r in batch))
                self._fh.flush()
                if self.fsync:
                    os.fsync(self._fh.fileno())
            except OSError:
                self.write_errors += len(batch)
                return
            self._seg_records += len(batch)
            self.records += len(batch)
            if self._seg_records >= self.segment_records:
                self._rotate_locked()
        if self._m_records is not None:
            self._m_records.inc(len(batch))

    def _rotate_locked(self) -> None:   # holds: self._lock
        try:
            self._fh.close()
        except OSError:
            pass
        self._fh = None
        self._seg_index += 1
        self._seg_records = 0
        # time-based retention: prune OWN closed segments whose newest
        # write is past the window (mtime — the last append's time)
        if self.retain_s <= 0:
            return
        horizon = time.time() - self.retain_s
        for seg in self._segments(own_only=True)[:-1]:
            try:
                if seg.stat().st_mtime < horizon:
                    seg.unlink()
            except OSError:
                pass

    # -------------------------------------------------------- sampling

    def start_sampling(self, sample_fn, interval_s: float) -> None:
        """Snapshot `sample_fn()` (a dict of sample-record fields) every
        `interval_s` seconds on a daemon thread — the resource-sampler
        cadence. One immediate sample is taken up front."""
        if interval_s <= 0 or self._sampler is not None:
            return
        self.sample_now(sample_fn)

        def loop():
            while not self._stop.wait(interval_s):
                self.sample_now(sample_fn)

        self._sampler = threading.Thread(
            target=loop, name="obs-store-sampler", daemon=True)
        self._sampler.start()

    def sample_now(self, sample_fn) -> None:
        try:
            fields = sample_fn() or {}
        except Exception:
            return
        self.append("sample", **fields)

    # ----------------------------------------------------------- close

    def flush(self, timeout_s: float = 5.0) -> None:
        """Best-effort wait for the queue to drain (tests, drain path)."""
        deadline = time.monotonic() + timeout_s
        while not self._q.empty() and time.monotonic() < deadline:
            time.sleep(0.01)

    def snapshot(self) -> dict:
        return {
            "dir": str(self.root), "writer": self.writer,
            "records": self.records, "replayed": self.replayed,
            "truncated": self.truncated,
            "quarantined_segments": self.quarantined_segments,
            "dropped": self.dropped, "write_errors": self.write_errors,
            "segment_index": self._seg_index,
        }

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        self._stop.set()
        self._thread.join(timeout=5.0)
        if self._sampler is not None:
            self._sampler.join(timeout=1.0)
        with self._lock:
            if self._fh is not None:
                try:
                    self._fh.flush()
                    if self.fsync:
                        os.fsync(self._fh.fileno())
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None


def _jsonable(v) -> bool:
    if v is None or isinstance(v, (bool, int, float, str)):
        return True
    if isinstance(v, (list, tuple)):
        return all(_jsonable(x) for x in v)
    if isinstance(v, dict):
        return all(isinstance(k, str) and _jsonable(x)
                   for k, x in v.items())
    return False
