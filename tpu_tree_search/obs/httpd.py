"""HTTP front-end for the search service: health/metrics reads AND the
submit/cancel write path.

The ROADMAP service follow-on, on stdlib ``http.server`` — no new
dependencies, threaded so a slow scrape never blocks another. Sits in
FRONT of a running :class:`~tpu_tree_search.service.SearchServer`:

- ``GET /healthz``  — liveness: ``200 {"status": "ok"}`` while serving,
  ``503`` once the server is closing (load balancers drain on it);
- ``GET /metrics``  — Prometheus text exposition: the server's own
  registry (requests, queue, submeshes, executor cache) followed by the
  process-global engine registry (checkpoints, retries, faults,
  segments);
- ``GET /status``   — the full JSON status snapshot
  (``SearchServer.status_snapshot()``);
- ``GET /trace``    — the flight recorder's ring buffer as Chrome
  trace-event JSON (save it, open in Perfetto);
- ``GET /alerts``   — the health rules engine's alert lifecycle
  snapshot (obs/health; the ``doctor`` CLI's verdict input);
- ``GET /capacity`` — the lane-state ledger + shape-class capacity
  model document (obs/capacity; per-lane state seconds, per-class
  ρ/headroom/predicted wait, and the what-if partition advisor);
  empty-but-valid with ``TTS_CAPACITY=0``;
- ``GET /dashboard`` — self-contained HTML operational dashboard
  (obs/dashboard; stdlib only, zero external assets);
- ``GET /journey?tag=`` — the flight recorder's request journeys
  (obs/journey): one stitched cross-lifetime timeline per logical
  request, reconstructed from the ledger/fleet dirs and the durable
  event store; empty-but-valid without durable inputs;
- ``POST /submit``  — admit a request; the JSON body uses the SAME
  payload schema as the file spool (service/spool.py: ``inst`` or
  ``p_times``, ``lb``, ``ub``, ``priority``, ``deadline_s``, ``tag``,
  ...). Returns ``200 {"request_id": ...}``; a full queue or closing
  server answers ``429``/``503`` with the reason, a malformed payload
  ``400`` — the spool is no longer the only way in;
- ``POST /cancel``  — body ``{"request_id": ...}``; returns
  ``200 {"cancelled": bool}`` (false = already terminal), ``404`` for
  an unknown id;
- ``POST /profile?duration_s=N`` — capture-on-demand: start the XLA
  profiler against the LIVE process for N seconds (default 1, capped
  at ``utils.config.PROFILE_MAX_DURATION_S``) and return the artifact
  directory (``obs/profiler``; the TensorBoard profile layout
  ``tools/search_report.py`` / ``tools/trace_selftime.py`` attribute
  self-time from). One capture at a time: a concurrent request gets
  ``409``; a closing server ``503``. The artifact root is
  ``--profile-dir`` (default: a ``profiles/`` dir under the server's
  workdir), one fresh subdirectory per capture.

Usage::

    httpd = start_http_server(server, port=9100)    # port=0: ephemeral
    ...
    httpd.close()

Wired into the CLI as ``serve --http-port N`` (off by default).
"""

from __future__ import annotations

import json
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

from . import chrome_trace, metrics, profiler, tracelog

__all__ = ["start_http_server", "ObsHttpd"]


class _Handler(BaseHTTPRequestHandler):
    # the ObsHttpd instance is attached to the server object
    protocol_version = "HTTP/1.1"

    def log_message(self, fmt, *args):  # noqa: D102 — silence stderr;
        pass                            # requests are counted in metrics

    def _send(self, code: int, body: str, ctype: str) -> None:
        data = body.encode()
        self.send_response(code)
        self.send_header("Content-Type", ctype)
        self.send_header("Content-Length", str(len(data)))
        self.end_headers()
        self.wfile.write(data)

    GET_PATHS = ("/healthz", "/metrics", "/status", "/trace", "/alerts",
                 "/capacity", "/dashboard", "/journey", "/")
    POST_PATHS = ("/submit", "/cancel", "/profile")

    def _query(self) -> dict:
        qs = self.path.split("?", 1)[1] if "?" in self.path else ""
        return {k: v[-1] for k, v in
                urllib.parse.parse_qs(qs).items()}

    def do_GET(self) -> None:  # noqa: N802 — http.server API
        obs: "ObsHttpd" = self.server.obs  # type: ignore[attr-defined]
        self._route({"/healthz": obs.healthz, "/metrics": obs.metrics,
                     "/status": obs.status, "/trace": obs.trace,
                     "/alerts": obs.alerts, "/capacity": obs.capacity,
                     "/dashboard": obs.dashboard,
                     "/journey": lambda: obs.journey(self._query()),
                     "/": obs.index}, other_method=self.POST_PATHS)

    def do_POST(self) -> None:  # noqa: N802 — http.server API
        obs: "ObsHttpd" = self.server.obs  # type: ignore[attr-defined]
        try:
            n = int(self.headers.get("Content-Length") or 0)
            body = self.rfile.read(n) if n else b""
        except (OSError, ValueError):
            body = b""
        self._route({"/submit": lambda: obs.submit(body),
                     "/cancel": lambda: obs.cancel(body),
                     "/profile": lambda: obs.profile(self._query())},
                    other_method=self.GET_PATHS)

    def _route(self, handlers: dict, other_method: tuple = ()) -> None:
        obs: "ObsHttpd" = self.server.obs  # type: ignore[attr-defined]
        path = self.path.split("?", 1)[0].rstrip("/") or "/"
        try:
            handler = handlers.get(path)
            if handler is None:
                if path in other_method:
                    # known endpoint, wrong verb: 405, not a
                    # self-contradictory 404 that lists the path it
                    # just claimed not to know
                    obs.http_requests.inc(path="<405>")
                    want = ("GET" if path in self.GET_PATHS else "POST")
                    self._send(405, json.dumps(
                        {"error": f"{path} requires {want}"}) + "\n",
                        "application/json")
                    return
                obs.http_requests.inc(path="<404>")
                self._send(404, json.dumps(
                    {"error": f"unknown path {path!r}",
                     "endpoints": ["/healthz", "/metrics", "/status",
                                   "/trace", "/alerts", "/capacity",
                                   "/dashboard", "/journey", "/submit",
                                   "/cancel", "/profile"]})
                    + "\n", "application/json")
                return
            obs.http_requests.inc(path=path)
            code, body, ctype = handler()
            self._send(code, body, ctype)
        except BrokenPipeError:
            pass        # client went away mid-response; nothing to do
        except Exception as e:  # noqa: BLE001 — a scrape bug must not
            # kill the serving thread; report it to the scraper instead
            self._send(500, json.dumps({"error": repr(e)}) + "\n",
                       "application/json")


class ObsHttpd:
    """A running observability HTTP server (see module docstring).
    `server` is duck-typed: anything with ``status_snapshot()`` and a
    ``_closing`` event works; None serves metrics/trace only."""

    def __init__(self, server=None, host: str = "127.0.0.1",
                 port: int = 0, registries=None,
                 trace: tracelog.TraceLog | None = None,
                 profile_dir: str | None = None,
                 health_monitor=None):
        self.server = server
        self.trace_log = trace
        self._profile_dir = profile_dir
        self.health_monitor = health_monitor
        regs = list(registries) if registries is not None else []
        if not regs:
            if server is not None and getattr(server, "metrics", None) \
                    is not None:
                regs.append(server.metrics)
            regs.append(metrics.default())
        self.registries = regs
        self.http_requests = self.registries[0].counter(
            "tts_http_requests_total",
            "observability endpoint hits by path")
        self._httpd = ThreadingHTTPServer((host, port), _Handler)
        self._httpd.daemon_threads = True
        self._httpd.obs = self  # type: ignore[attr-defined]
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True,
            name="tts-obs-httpd")
        self._thread.start()

    @property
    def url(self) -> str:
        return f"http://{self.host}:{self.port}"

    def close(self) -> None:
        self._httpd.shutdown()
        self._httpd.server_close()
        self._thread.join(timeout=10)

    def __enter__(self) -> "ObsHttpd":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    # ------------------------------------------------------------ routes

    def _closing(self) -> bool:
        evt = getattr(self.server, "_closing", None)
        return bool(evt is not None and evt.is_set())

    def index(self):
        return 200, json.dumps(
            {"service": "tpu_tree_search",
             "endpoints": ["/healthz", "/metrics", "/status", "/trace",
                           "/alerts", "/capacity", "/dashboard",
                           "/journey", "/submit", "/cancel",
                           "/profile"]}) + "\n", \
            "application/json"

    def healthz(self):
        if self.server is None:
            return 200, '{"status": "ok", "server": null}\n', \
                "application/json"
        if self._closing():
            return 503, '{"status": "closing"}\n', "application/json"
        return 200, '{"status": "ok"}\n', "application/json"

    def metrics(self):
        text = "".join(r.to_prometheus() for r in self.registries)
        return 200, text, "text/plain; version=0.0.4; charset=utf-8"

    def status(self):
        if self.server is None:
            body = {"server": None,
                    "metrics": [r.to_json() for r in self.registries]}
        else:
            body = self.server.status_snapshot()
        return 200, json.dumps(body) + "\n", "application/json"

    def trace(self):
        log = self.trace_log or tracelog.get()
        body = json.dumps(chrome_trace.to_chrome(log.records()))
        return 200, body, "application/json"

    def _monitor(self):
        """The health monitor in play: an explicitly attached one, else
        the server's own (SearchServer.health)."""
        if self.health_monitor is not None:
            return self.health_monitor
        return getattr(self.server, "health", None)

    def alerts(self):
        """GET /alerts: the rules engine's lifecycle snapshot. A server
        without a monitor answers an empty-but-valid document so fleet
        scrapers need no special case."""
        mon = self._monitor()
        if mon is None:
            body = {"enabled": False, "firing": 0, "alerts": []}
        else:
            body = {"enabled": True, **mon.alerts_snapshot()}
        return 200, json.dumps(body) + "\n", "application/json"

    def capacity(self):
        """GET /capacity: the lane-state ledger + shape-class capacity
        model document (obs/capacity), with the what-if partition
        advisor. A server without the capacity layer (TTS_CAPACITY=0,
        or no server attached) answers an empty-but-valid document so
        fleet scrapers need no special case."""
        srv = self.server
        snap = (srv.capacity_snapshot()
                if srv is not None and hasattr(srv, "capacity_snapshot")
                else None)
        if snap is None:
            body = {"enabled": False}
        else:
            body = {"enabled": True, **snap}
        return 200, json.dumps(body) + "\n", "application/json"

    def journey(self, query: dict):
        """GET /journey?tag=: the flight recorder's cross-lifetime
        request timelines (obs/journey), stitched from the server's
        ledger/fleet dirs and durable event store. A server without
        ledger or store answers an empty-but-valid document — journeys
        need durable inputs, not a special-cased client."""
        srv = self.server
        if srv is None or not hasattr(srv, "journeys"):
            body = {"enabled": False, "journeys": []}
        else:
            js = srv.journeys(tag=query.get("tag") or None)
            body = {"enabled": True, "count": len(js), "journeys": js}
        return 200, json.dumps(body) + "\n", "application/json"

    def dashboard(self):
        """GET /dashboard: the self-contained HTML view (stdlib only,
        no external assets — save it and it still renders)."""
        from . import dashboard as dash
        snapshot = (self.server.status_snapshot()
                    if self.server is not None else None)
        mon = self._monitor()
        html = dash.render_server(
            snapshot,
            mon.alerts_snapshot() if mon is not None else None,
            dict(mon.history) if mon is not None else None)
        return 200, html, "text/html; charset=utf-8"

    # ------------------------------------------------------- write path

    @staticmethod
    def _json_body(body: bytes) -> dict:
        payload = json.loads(body.decode() if body else "")
        if not isinstance(payload, dict):
            raise ValueError("payload must be a JSON object")
        return payload

    def submit(self, body: bytes):
        """POST /submit: admit one request (spool payload schema)."""
        if self.server is None:
            return 503, json.dumps(
                {"error": "no search server attached"}) + "\n", \
                "application/json"
        # spool's payload parser is THE request schema — one wire format
        # whether a request arrives as a file or an HTTP body
        from ..service.queueing import AdmissionError
        from ..service.spool import request_from_payload
        try:
            payload = self._json_body(body)
            request = request_from_payload(payload)
        except (ValueError, KeyError, TypeError,
                json.JSONDecodeError) as e:
            return 400, json.dumps({"error": str(e)}) + "\n", \
                "application/json"
        try:
            rid = self.server.submit(request)
        except AdmissionError as e:
            code = 503 if self._closing() else 429
            return code, json.dumps({"error": str(e)}) + "\n", \
                "application/json"
        # real state, not an assumed "QUEUED": the ledger's idempotent
        # re-serve path can answer with an already-DONE request id
        try:
            state = self.server.status(rid)["state"]
        except KeyError:
            state = "QUEUED"
        return 200, json.dumps(
            {"request_id": rid, "state": state}) + "\n", \
            "application/json"

    @property
    def profile_dir(self) -> str:
        """The capture artifact root (created lazily): the configured
        one, else ``<server workdir>/profiles``, else a temp dir."""
        if self._profile_dir is None:
            wd = getattr(self.server, "workdir", None)
            if wd is not None:
                self._profile_dir = str(wd / "profiles") \
                    if hasattr(wd, "__truediv__") \
                    else f"{wd}/profiles"
            else:
                import tempfile
                self._profile_dir = tempfile.mkdtemp(
                    prefix="tts_profiles_")
        return self._profile_dir

    def profile(self, query: dict):
        """POST /profile?duration_s=N: capture-on-demand against the
        live process. Returns the artifact directory; 409 while another
        capture runs, 503 on a closing server, 400 on a bad duration."""
        from ..utils import config as cfg
        if self._closing():
            return 503, json.dumps(
                {"error": "server closing"}) + "\n", "application/json"
        try:
            duration_s = float(query.get("duration_s", 1.0))
            if not 0 < duration_s <= cfg.PROFILE_MAX_DURATION_S:
                raise ValueError(
                    f"duration_s must be in (0, "
                    f"{cfg.PROFILE_MAX_DURATION_S}]")
        except (TypeError, ValueError) as e:
            return 400, json.dumps({"error": str(e)}) + "\n", \
                "application/json"
        sess = profiler.session()
        try:
            artifact = sess.capture(duration_s,
                                    sess.fresh_dir(self.profile_dir))
        except profiler.ProfilerBusyError as e:
            return 409, json.dumps({"error": str(e)}) + "\n", \
                "application/json"
        return 200, json.dumps(
            {"artifact": artifact, "duration_s": duration_s,
             "hint": "python tools/search_report.py <artifact>"}) \
            + "\n", "application/json"

    def cancel(self, body: bytes):
        """POST /cancel: cancel a queued/running request by id."""
        if self.server is None:
            return 503, json.dumps(
                {"error": "no search server attached"}) + "\n", \
                "application/json"
        try:
            rid = self._json_body(body)["request_id"]
        except (ValueError, KeyError, json.JSONDecodeError) as e:
            return 400, json.dumps(
                {"error": f"body must be "
                          f'{{"request_id": ...}}: {e}'}) + "\n", \
                "application/json"
        try:
            cancelled = self.server.cancel(rid)
        except KeyError:
            return 404, json.dumps(
                {"error": f"unknown request id {rid!r}"}) + "\n", \
                "application/json"
        return 200, json.dumps(
            {"request_id": rid, "cancelled": bool(cancelled)}) + "\n", \
            "application/json"


def start_http_server(server=None, host: str = "127.0.0.1",
                      port: int = 0, registries=None,
                      trace: tracelog.TraceLog | None = None,
                      profile_dir: str | None = None,
                      health_monitor=None) -> ObsHttpd:
    """Start the observability HTTP front-end on `host:port` (port 0
    binds an ephemeral port — read ``.port``). Returns the running
    :class:`ObsHttpd`; call ``.close()`` (or use as a context manager)
    to stop it. `health_monitor` overrides the server's own
    (``SearchServer.health``) behind ``/alerts`` and ``/dashboard``."""
    return ObsHttpd(server=server, host=host, port=port,
                    registries=registries, trace=trace,
                    profile_dir=profile_dir,
                    health_monitor=health_monitor)
