"""Unified observability layer: flight recorder for the search runtime.

The reference engine's only observability is per-phase wall-clock
counters dumped to CSV at exit (PFSP_statistic.c); until this layer the
repo mirrored that shape — post-hoc attribution, an ad-hoc status dict,
and no durable record of retries, faults or preemptions. A production
scheduler that preempts, reshards, retries and rolls back checkpoints is
undebuggable without a flight recorder that shows *what happened, when,
on which submesh*. This package is that recorder:

- :mod:`~tpu_tree_search.obs.tracelog` — structured span/event log
  (thread-safe ring buffer + optional JSONL sink) threaded through the
  service scheduler, the segmented engine driver, checkpoint I/O, the
  retry tier and the fault injector;
- :mod:`~tpu_tree_search.obs.metrics` — counters/gauges/histograms with
  JSON and Prometheus-text exposition; the service's status snapshot is
  built on top of it;
- :mod:`~tpu_tree_search.obs.chrome_trace` — converts the span log to
  Chrome ``trace_event`` JSON so a whole serve session opens in
  Perfetto (and owns the XLA-profiler-trace parsing the profiling tools
  share);
- :mod:`~tpu_tree_search.obs.httpd` — ``/healthz`` ``/metrics``
  ``/status`` ``/trace`` HTTP front-end over a running SearchServer
  (stdlib ``http.server``; the ROADMAP service follow-on), plus the
  ``/submit`` ``/cancel`` write path and on-demand ``/profile``;
- :mod:`~tpu_tree_search.obs.profiler` — the process's ONE door to the
  XLA profiler: a thread-safe one-at-a-time capture session behind
  ``POST /profile``, the ``profile`` CLI subcommand and the profiling
  tools (no direct ``jax.profiler`` calls anywhere else);
- :mod:`~tpu_tree_search.obs.resource` — device-memory / host-RSS
  sampler: ``tts_device_bytes_*`` and ``tts_host_rss_bytes`` gauges
  plus ``resource.sample`` trace events rendered as Perfetto memory
  lanes;
- :mod:`~tpu_tree_search.obs.health` — the operational judge: an
  SLO/anomaly rules engine with a pending→firing→resolved alert
  lifecycle (``tts_alerts`` gauges, ``alert.*`` trace events,
  ``GET /alerts``);
- :mod:`~tpu_tree_search.obs.audit` — node-conservation auditor:
  machine-checked engine invariants (telemetry-vs-counter exactness,
  reshard/checkpoint conservation) surfaced as the `audit` alert rule,
  with a hard-fail CI mode;
- :mod:`~tpu_tree_search.obs.aggregate` — fleet scrape-and-merge of N
  servers' ``/metrics`` + ``/status`` + ``/alerts`` into one
  origin-labeled view (the ``doctor`` CLI's input);
- :mod:`~tpu_tree_search.obs.dashboard` — self-contained HTML
  dashboard (``GET /dashboard``; stdlib only, no external assets).

Everything here is observation-only: instrumentation records
timestamps and counters, it never changes what the engine explores —
served node counts stay bit-identical with the recorder on or off.
"""

from . import (aggregate, audit, chrome_trace, dashboard,  # noqa: F401
               health, metrics, profiler, resource, tracelog)

__all__ = ["tracelog", "metrics", "chrome_trace", "profiler",
           "resource", "health", "audit", "aggregate", "dashboard"]
