"""Fleet capacity & utilization observability (``TTS_CAPACITY``).

Two cooperating models turn the serving fleet from "busy/idle booleans"
into a measured capacity plan — the planning input ROADMAP item 7's
split/merge scheduler will consume:

- :class:`LaneLedger` — a per-submesh-slot **lane-state ledger**: an
  exact state machine (``idle`` / ``compiling`` / ``executing`` /
  ``draining`` / ``quarantined`` / ``batch-frozen``) driven from the
  scheduler's existing transition points. Every transition closes the
  open interval into a per-state accumulator AND the
  ``tts_lane_seconds_total{lane,state}`` counter, and emits a
  ``lane.state`` trace event (rendered as retrospective state slices on
  a per-lane Perfetto track by obs/chrome_trace). The audit-style
  invariant: per-lane state seconds sum EXACTLY to the lane's
  wall-clock lifetime — conservation holds under preempt, quarantine,
  failover, and mid-batch member freeze, because time is only ever
  moved from the open interval into exactly one state's accumulator.
  The counter rides the PR-18 durable store's resume whitelist, so a
  restarted server seeds the ledger (:meth:`LaneLedger.seed`) and
  utilization history survives ``kill -9``; replayed seconds are
  tracked separately so the invariant stays statable per lifetime.

- :class:`CapacityModel` — a **shape-class capacity model**: per
  (problem shape class, tenant) arrival rates λ from admission events
  (sliding window, ``TTS_CAPACITY_WINDOW_S``), joined with per-class
  service rates seeded from the TuningCache's measured evals/s and
  corrected by observed segment throughput (EWMA,
  ``TTS_CAPACITY_EWMA``), and mean evals-per-request from terminals.
  E[S] = evals_per_request / evals_per_s gives per-class utilization
  ρ = λ·E[S]/c over c healthy lanes, headroom 1−ρ, and a Little's-law
  (M/M/c-flavored) predicted queue wait W_q ≈ E[S]·ρ/(c·(1−ρ)). The
  **what-if advisor** (:meth:`CapacityModel.what_if`) predicts req/s
  and queue wait for alternative submesh partitions of the same device
  count under linear per-device rate scaling.

Everything here is observation-only and lock-self-contained: callers
(the scheduler under its lock, heartbeat threads without it, the
health daemon) never need the server lock — a racing ``sync`` can at
worst label a sliver of time with the neighboring state, never lose or
double-count it. Stays import-light (stdlib + sibling obs modules).
"""

from __future__ import annotations

import collections
import threading
import time

from . import tracelog
from ..utils import config as cfg

__all__ = ["LANE_STATES", "LaneLedger", "CapacityModel",
           "LANE_SECONDS_METRIC"]

LANE_STATES = ("idle", "compiling", "executing", "draining",
               "quarantined", "batch-frozen")

LANE_SECONDS_METRIC = "tts_lane_seconds_total"
LANE_SECONDS_DOC = ("wall-clock seconds each submesh lane spent in "
                    "each scheduler state (conserved: states sum to "
                    "lane lifetime)")

# admission-stamp ring bound per (shape, tenant) class — enough for any
# window at serving arrival rates; a flood beyond it only degrades the
# λ estimate, never memory
_ADMITS_CAP = 8192


class _Lane:
    __slots__ = ("state", "since", "entered", "acc", "replayed")

    def __init__(self, now: float):
        self.state = "idle"
        self.since = now        # start of the UNACCOUNTED open interval
        self.entered = now      # when the current state was entered
        self.acc: dict[str, float] = {}
        self.replayed = 0.0     # seconds seeded from a prior lifetime


class LaneLedger:
    """Per-lane state accounting with an exact conservation invariant:
    for every lane, ``sum(seconds.values()) == lifetime_s`` (to float
    addition precision), where lifetime is seconds since construction
    plus any replayed prior-lifetime seconds."""

    def __init__(self, registry, lanes, now: float | None = None):
        now = time.monotonic() if now is None else now
        self._lock = threading.Lock()
        self.born = now
        self._counter = registry.counter(LANE_SECONDS_METRIC,
                                         LANE_SECONDS_DOC)
        self._lanes: dict[int, _Lane] = {  # guarded-by: self._lock
            int(i): _Lane(now) for i in lanes}

    # ------------------------------------------------------- accounting

    def seed(self, lane: int, state: str, seconds: float) -> None:
        """Adopt `seconds` of prior-lifetime time in `state` (resumed
        from the durable store's counter replay — the counter itself
        already carries the value, so only the accumulator and the
        replayed ledger move)."""
        with self._lock:
            ln = self._lanes.setdefault(int(lane), _Lane(self.born))
            ln.acc[state] = ln.acc.get(state, 0.0) + float(seconds)
            ln.replayed += float(seconds)

    def transition(self, lane: int, state: str,
                   now: float | None = None) -> None:
        """Move `lane` to `state`; a no-op when already there. Closes
        the open interval into the OUTGOING state's accumulator and
        counter, and emits a ``lane.state`` trace event carrying the
        full duration of the state being left (chrome_trace renders it
        as a retrospective slice)."""
        now = time.monotonic() if now is None else now
        with self._lock:
            ln = self._lanes.setdefault(int(lane), _Lane(now))
            if state == ln.state:
                return
            prev, dur = ln.state, max(now - ln.entered, 0.0)
            self._close(ln, lane, now)
            ln.state, ln.since, ln.entered = state, now, now
        tracelog.event("lane.state", submesh=int(lane), state=state,
                       prev=prev, seconds=dur)

    def flush(self, now: float | None = None) -> None:
        """Close every lane's open interval into its accumulator and
        counter WITHOUT changing state — called before each durable
        sample so persisted counters are current, and at close so the
        final interval is never lost."""
        now = time.monotonic() if now is None else now
        with self._lock:
            for lane, ln in self._lanes.items():
                self._close(ln, lane, now)
                ln.since = now

    def _close(self, ln: _Lane, lane: int, now: float) -> None:
        # holds: self._lock
        delta = now - ln.since
        if delta <= 0:
            return
        ln.acc[ln.state] = ln.acc.get(ln.state, 0.0) + delta
        self._counter.inc(delta, lane=int(lane), state=ln.state)

    # --------------------------------------------------------- reading

    def state_of(self, lane: int) -> str:
        with self._lock:
            ln = self._lanes.get(int(lane))
            return ln.state if ln is not None else "idle"

    def snapshot(self, now: float | None = None) -> list[dict]:
        """Per-lane view: current state, per-state seconds (accumulated
        + the open interval), lifetime, replayed prior-lifetime
        seconds, utilization (executing fraction of lifetime), and the
        conservation error (≈0 by construction)."""
        now = time.monotonic() if now is None else now
        out = []
        with self._lock:
            for lane in sorted(self._lanes):
                ln = self._lanes[lane]
                secs = dict(ln.acc)
                secs[ln.state] = secs.get(ln.state, 0.0) \
                    + max(now - ln.since, 0.0)
                life = max(now - self.born, 0.0) + ln.replayed
                out.append({
                    "lane": lane,
                    "state": ln.state,
                    "seconds": {k: secs[k] for k in sorted(secs)},
                    "lifetime_s": life,
                    "replayed_s": ln.replayed,
                    "utilization": (secs.get("executing", 0.0) / life
                                    if life > 0 else 0.0),
                    "conservation_error_s":
                        sum(secs.values()) - life,
                })
        return out

    def conservation_errors(self, now: float | None = None) -> dict:
        """lane -> |sum(state seconds) − lifetime| (the audit value the
        tests pin to ~0)."""
        return {r["lane"]: abs(r["conservation_error_s"])
                for r in self.snapshot(now)}


class _ShapeStats:
    __slots__ = ("rate_seed", "rate_obs", "evals_per_req",
                 "service_obs", "terminals")

    def __init__(self):
        self.rate_seed: float | None = None   # tuner evals/s
        self.rate_obs: float | None = None    # observed evals/s EWMA
        self.evals_per_req: float | None = None
        self.service_obs: float | None = None  # measured E[S] EWMA
        self.terminals = 0


class CapacityModel:
    """Shape-class demand/capacity model (see module docstring). All
    hooks are cheap and self-locked; ``snapshot()`` also refreshes the
    ``tts_capacity_*`` gauges so the health daemon's evaluation cadence
    drives the published series."""

    def __init__(self, registry, window_s: float | None = None,
                 ewma: float | None = None,
                 now: float | None = None):
        self._lock = threading.Lock()
        self._registry = registry
        self.window_s = float(window_s if window_s is not None
                              else cfg.env_float("TTS_CAPACITY_WINDOW_S"))
        self.ewma = float(ewma if ewma is not None
                          else cfg.env_float("TTS_CAPACITY_EWMA"))
        self.born = time.monotonic() if now is None else now
        # (shape, tenant) -> deque of admission monotonic stamps
        self._admits: dict[tuple, collections.deque] = {}
        self._shapes: dict[str, _ShapeStats] = {}
        # tenant -> (EWMA observed dispatch/queue wait, count)
        self._waits: dict[str, list] = {}
        self._g_util = registry.gauge(
            "tts_capacity_utilization",
            "per-shape-class ρ = arrival demand over healthy-lane "
            "capacity (1.0 = saturated)")
        self._g_head = registry.gauge(
            "tts_capacity_headroom",
            "per-shape-class spare capacity fraction (1 − ρ)")
        self._g_wait = registry.gauge(
            "tts_capacity_predicted_wait_s",
            "Little's-law predicted queue wait per shape class")

    # ---------------------------------------------------------- hooks

    def _ewma(self, old: float | None, new: float) -> float:
        if old is None:
            return float(new)
        return (1 - self.ewma) * old + self.ewma * float(new)

    def on_admit(self, shape: str, tenant: str,
                 now: float | None = None) -> None:
        now = time.monotonic() if now is None else now
        with self._lock:
            dq = self._admits.get((shape, tenant))
            if dq is None:
                dq = self._admits[(shape, tenant)] = collections.deque(
                    maxlen=_ADMITS_CAP)
            dq.append(now)

    def seed_rate(self, shape: str, evals_per_s) -> None:
        """Adopt the TuningCache's measured evals/s for a shape class
        (the dispatch-time seed; observed throughput refines it)."""
        if not evals_per_s:
            return
        with self._lock:
            st = self._shapes.setdefault(shape, _ShapeStats())
            st.rate_seed = float(evals_per_s)

    def on_progress(self, shape: str, evals_per_s: float) -> None:
        """Observed segment throughput (heartbeat tree/elapsed)."""
        if not evals_per_s or evals_per_s <= 0:
            return
        with self._lock:
            st = self._shapes.setdefault(shape, _ShapeStats())
            st.rate_obs = self._ewma(st.rate_obs, evals_per_s)

    def on_terminal(self, shape: str, explored,
                    service_s=None) -> None:
        """A finished request's explored-node total -> per-class mean
        service demand (evals per request, EWMA). `service_s` (the
        request's cumulative execution seconds) additionally feeds a
        DIRECT measured-E[S] estimate — the fallback that keeps the
        model live when requests finish inside their first segment
        (no heartbeat throughput) and the tuner has no seed."""
        with self._lock:
            st = self._shapes.setdefault(shape, _ShapeStats())
            if explored and explored > 0:
                st.evals_per_req = self._ewma(st.evals_per_req,
                                              explored)
            if service_s is not None and service_s > 0:
                st.service_obs = self._ewma(st.service_obs, service_s)
            st.terminals += 1

    def on_queue_wait(self, tenant: str, wait_s: float) -> None:
        """Observed admission-to-dispatch wait, per tenant (the
        measured counterpart the predicted W_q is judged against)."""
        with self._lock:
            w = self._waits.setdefault(str(tenant), [None, 0])
            w[0] = self._ewma(w[0], max(float(wait_s), 0.0))
            w[1] += 1

    # -------------------------------------------------------- modeling

    def _service_s(self, st: _ShapeStats) -> float | None:
        """E[S]: mean per-request lane seconds for a shape class, from
        mean evals/request over the best rate estimate (observed EWMA
        when available, else the tuner seed)."""
        rate = st.rate_obs if st.rate_obs else st.rate_seed
        if not rate or not st.evals_per_req:
            return st.service_obs
        return st.evals_per_req / rate

    @staticmethod
    def _wait(service_s: float, rho: float, lanes: int) -> float | None:
        if rho >= 1.0 or lanes <= 0:
            return None     # saturated: the queue grows without bound
        return service_s * rho / (lanes * (1.0 - rho))

    def snapshot(self, healthy_lanes: int, total_lanes: int,
                 total_devices: int,
                 now: float | None = None) -> dict:
        """The full capacity document (/capacity, status_snapshot's
        ``capacity`` key): per-class rows, overall ρ/headroom/predicted
        wait + req/s for the current partition, per-tenant observed
        waits, and the what-if partition table. Refreshes the
        ``tts_capacity_*`` gauges as a side effect."""
        now = time.monotonic() if now is None else now
        c = max(int(healthy_lanes), 0)
        with self._lock:
            window = max(min(self.window_s, now - self.born), 1e-6)
            classes, demand, lam_total = [], 0.0, 0.0
            lam_known, s_known = 0.0, []
            for (shape, tenant), dq in sorted(self._admits.items()):
                while dq and dq[0] < now - self.window_s:
                    dq.popleft()
                lam = len(dq) / window
                lam_total += lam
                st = self._shapes.get(shape)
                s = self._service_s(st) if st is not None else None
                rho = head = wait = None
                if s is not None and c > 0:
                    demand += lam * s
                    lam_known += lam
                    s_known.append(s)
                    rho = lam * s / c
                    head = 1.0 - rho
                    wait = self._wait(s, rho, c)
                classes.append({
                    "shape": shape, "tenant": tenant,
                    "arrival_per_s": lam, "service_s": s,
                    "utilization": rho, "headroom": head,
                    "predicted_wait_s": wait,
                })
            # overall ρ is None only before ANY service estimate exists
            # (the doctor/CLI columns' documented contract) — a warmed
            # but momentarily idle fleet reports ρ=0, not "unknown".
            # With the arrival window drained, s_agg falls back to the
            # unweighted class mean so the what-if advisor stays live.
            overall = demand / c if (c > 0 and s_known) else None
            s_agg = (demand / lam_known if lam_known > 0
                     else (sum(s_known) / len(s_known)
                           if s_known else None))
            doc = {
                "healthy_lanes": c,
                "lanes": int(total_lanes),
                "devices": int(total_devices),
                "window_s": window,
                "arrival_per_s": lam_total,
                "utilization": overall,
                "headroom": (1.0 - overall
                             if overall is not None else None),
                "predicted_wait_s": (
                    self._wait(s_agg, overall, c)
                    if overall is not None else None),
                "predicted_req_per_s": (c / s_agg if s_agg else None),
                "classes": classes,
                "tenants": {t: {"observed_wait_s": w[0], "waits": w[1]}
                            for t, w in sorted(self._waits.items())},
                "what_if": self._what_if(
                    s_agg, lam_known, int(total_lanes),
                    int(total_devices)),
            }
        self._publish(classes)
        return doc

    def _what_if(self, s_agg, lam, lanes: int, devices: int) -> list:
        """Predicted req/s and queue wait for every partition of the
        SAME devices into n equal lanes (n | devices), under linear
        per-device rate scaling: per-lane E[S] scales with lane width,
        so total throughput is partition-invariant while queue wait
        favors fewer, fatter lanes — the quantified tradeoff against
        per-lane blast radius."""
        if not s_agg or lanes <= 0 or devices <= 0:
            return []
        rows = []
        for n in range(1, devices + 1):
            if devices % n:
                continue
            per = devices // n
            s_n = s_agg * (devices / lanes) / per
            rho = lam * s_n / n
            rows.append({
                "lanes": n, "devices_per_lane": per,
                "service_s": s_n,
                "predicted_req_per_s": n / s_n,
                "utilization": rho,
                "predicted_wait_s": self._wait(s_n, rho, n),
                "current": n == lanes,
            })
        return rows

    def _publish(self, classes: list[dict]) -> None:
        # outside self._lock — gauge writes take the metric's own lock
        for row in classes:
            labels = {"shape": row["shape"], "tenant": row["tenant"]}
            if row["utilization"] is not None:
                self._g_util.set(row["utilization"], **labels)
                self._g_head.set(row["headroom"], **labels)
            if row["predicted_wait_s"] is not None:
                self._g_wait.set(row["predicted_wait_s"], **labels)

    def close(self) -> None:
        """Retire the published gauge series (the per-request-family
        retirement discipline: a closed server leaves no stale
        capacity series behind in a shared registry)."""
        for name in ("tts_capacity_utilization", "tts_capacity_headroom",
                     "tts_capacity_predicted_wait_s"):
            self._registry.remove_matching(name)
