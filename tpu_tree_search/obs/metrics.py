"""Unified metrics registry: counters, gauges, histograms.

One registry replaces the repo's scattered counter dicts (the service's
hand-rolled ``self.counters``, the executor cache's bare ints, the
retry tier's warnings-only accounting). Metric types follow the
Prometheus model — monotonic ``Counter``, settable ``Gauge`` (optionally
callback-backed so live values like queue depth are read at scrape
time), bucketed ``Histogram`` — all label-aware, all thread-safe, with
two expositions:

- :meth:`Registry.to_json` — nested JSON for ``status_snapshot()`` and
  the ``/status`` endpoint;
- :meth:`Registry.to_prometheus` — the Prometheus text format for
  ``/metrics`` (``# HELP`` / ``# TYPE`` / ``name{label="v"} value``,
  histograms as cumulative ``_bucket{le=...}`` + ``_sum`` + ``_count``).

Scoping: engine-level instrumentation (checkpoint I/O, retries, faults,
segments) writes to the process-global default registry
(:func:`default`; swap with :func:`install` for test isolation). The
search server builds its OWN registry for request/queue/cache metrics —
two servers in one process (the test suite does this constantly) must
not bleed counters into each other — and the HTTP front-end exposes
both, server-scoped first.

Metric names use the ``tts_`` prefix and Prometheus conventions
(``_total`` for counters, base units in the name). The full name table
lives in README.md's Observability section.
"""

from __future__ import annotations

import math
import threading
import time

__all__ = ["Counter", "Gauge", "Histogram", "Registry", "default",
           "install", "DEFAULT_BUCKETS"]

# latency-shaped default buckets (seconds): checkpoint saves and segment
# times span ~1 ms (tests, tiny instances) to minutes (production pools)
DEFAULT_BUCKETS = (0.001, 0.005, 0.02, 0.1, 0.5, 2.0, 10.0, 60.0, 300.0)


def _label_key(labels: dict) -> tuple:
    return tuple(sorted((str(k), str(v)) for k, v in labels.items()))


def _fmt_labels(key: tuple) -> str:
    if not key:
        return ""
    inner = ",".join(f'{k}="{v}"' for k, v in key)
    return "{" + inner + "}"


def _fmt_value(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    f = float(v)
    return str(int(f)) if f.is_integer() else repr(f)


class _Metric:
    """Shared label-series bookkeeping for all three metric types."""

    kind = "untyped"

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self._lock = threading.Lock()
        self._series: dict[tuple, object] = {}  # guarded-by: self._lock
        # cardinality valve (set by the owning Registry): a NEW label
        # set beyond the cap is dropped (and reported via _on_drop)
        # instead of growing the metric without bound — a leaked
        # per-request label degrades one metric, not the process
        self._series_cap: int | None = None
        self._on_drop = None

    def _admit(self, key: tuple) -> bool:
        """Whether a write to `key` may proceed (caller holds the
        lock). Existing series always update; only NEW series count
        against the cap."""
        if (key in self._series or self._series_cap is None
                or len(self._series) < self._series_cap):
            return True
        if self._on_drop is not None:
            self._on_drop(self.name)
        return False

    def _labelnames(self) -> list[tuple]:
        with self._lock:
            return sorted(self._series)

    def remove_matching(self, **labels) -> int:
        """Drop every series whose labels include these pairs; returns
        how many were dropped. The cardinality valve for per-request
        label series (tts_phase_seconds{request=...}): the publisher
        removes a request's series at its terminal transition so a
        long-serving process cannot accumulate series without bound."""
        want = {(str(k), str(v)) for k, v in labels.items()}
        with self._lock:
            keys = [k for k in self._series if want <= set(k)]
            for k in keys:
                del self._series[k]
            return len(keys)


class Counter(_Metric):
    """Monotonic counter; `inc()` only goes up."""

    kind = "counter"

    def inc(self, n: float = 1, **labels) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease ({n})")
        key = _label_key(labels)
        with self._lock:
            if self._admit(key):
                self._series[key] = self._series.get(key, 0) + n

    def value(self, **labels) -> float:
        with self._lock:
            return self._series.get(_label_key(labels), 0)

    def value_matching(self, **labels) -> float:
        """Sum every series whose labels include these pairs — the
        read-side aggregate for a family that grew an extra label
        (tts_requests_total{state,tenant}: `value_matching(state="done")`
        still answers "how many DONE" across all tenants)."""
        want = {(str(k), str(v)) for k, v in labels.items()}
        with self._lock:
            return sum(v for k, v in self._series.items()
                       if want <= set(k))

    def samples(self) -> list[tuple[str, tuple, float]]:
        # no synthetic zero sample when only labeled series exist (or
        # none yet): an unlabeled `name 0` that vanishes once the first
        # labeled increment lands reads as a stale/reset series to a
        # scraper — Prometheus convention is series appear on first use
        with self._lock:
            items = sorted(self._series.items())
        return [(self.name, k, v) for k, v in items]

    def to_json(self):
        with self._lock:
            if set(self._series) <= {()}:
                return self._series.get((), 0)
            return {_fmt_labels(k) or "": v
                    for k, v in sorted(self._series.items())}


class Gauge(_Metric):
    """Settable instantaneous value; `set_fn` registers a zero-label
    callback evaluated at scrape time (live queue depth, occupancy)."""

    kind = "gauge"

    def __init__(self, name: str, help: str = ""):
        super().__init__(name, help)
        self._fn = None

    def set(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            if self._admit(key):
                self._series[key] = float(v)

    def inc(self, n: float = 1, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            if self._admit(key):
                self._series[key] = self._series.get(key, 0) + n

    def dec(self, n: float = 1, **labels) -> None:
        self.inc(-n, **labels)

    def set_fn(self, fn) -> None:
        self._fn = fn

    def value(self, **labels) -> float:
        if self._fn is not None and not labels:
            return float(self._fn())
        with self._lock:
            return self._series.get(_label_key(labels), 0.0)

    def samples(self) -> list[tuple[str, tuple, float]]:
        if self._fn is not None:
            try:
                return [(self.name, (), float(self._fn()))]
            except Exception:  # noqa: BLE001 — scrape must not die on
                return []      # a callback racing server shutdown
        with self._lock:
            items = sorted(self._series.items())
        return [(self.name, k, v) for k, v in items]

    def to_json(self):
        if self._fn is not None:
            try:
                return float(self._fn())
            except Exception:  # noqa: BLE001
                return None
        with self._lock:
            if set(self._series) <= {()}:
                return self._series.get((), 0.0)
            return {_fmt_labels(k) or "": v
                    for k, v in sorted(self._series.items())}


class _HistSeries:
    __slots__ = ("counts", "sum", "count")

    def __init__(self, n_buckets: int):
        self.counts = [0] * n_buckets
        self.sum = 0.0
        self.count = 0


class Histogram(_Metric):
    """Cumulative-bucket histogram (Prometheus semantics: bucket `le=x`
    counts every observation <= x; `+Inf` == `_count`)."""

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 buckets: tuple = DEFAULT_BUCKETS):
        super().__init__(name, help)
        self.buckets = tuple(sorted(float(b) for b in buckets))

    def observe(self, v: float, **labels) -> None:
        key = _label_key(labels)
        with self._lock:
            s = self._series.get(key)
            if s is None:
                if not self._admit(key):
                    return
                s = self._series[key] = _HistSeries(len(self.buckets))
            for i, b in enumerate(self.buckets):
                if v <= b:
                    s.counts[i] += 1
            s.sum += v
            s.count += 1

    def snapshot(self, **labels) -> dict:
        with self._lock:
            s = self._series.get(_label_key(labels))
            if s is None:
                return {"count": 0, "sum": 0.0}
            return {"count": s.count, "sum": s.sum,
                    "buckets": dict(zip(map(str, self.buckets),
                                        s.counts))}

    def snapshot_matching(self, **labels) -> dict:
        """Merged snapshot over every series whose labels include these
        pairs — the histogram counterpart of ``Counter.value_matching``
        for a family that grew an extra label
        (tts_queue_wait_seconds{tenant}: ``snapshot_matching()`` still
        answers the all-tenants p99 the health rule judges)."""
        want = {(str(k), str(v)) for k, v in labels.items()}
        counts = [0] * len(self.buckets)
        total, count = 0.0, 0
        with self._lock:
            for k, s in self._series.items():
                if not want <= set(k):
                    continue
                for i, n in enumerate(s.counts):
                    counts[i] += n
                total += s.sum
                count += s.count
        if count == 0:
            return {"count": 0, "sum": 0.0}
        return {"count": count, "sum": total,
                "buckets": dict(zip(map(str, self.buckets), counts))}

    def to_json(self):
        with self._lock:
            keys = sorted(self._series)
        out = {_fmt_labels(k) or "": self.snapshot(**dict(k))
               for k in keys}
        if set(out) <= {""}:
            return out.get("", {"count": 0, "sum": 0.0})
        return out


class Registry:
    """A named collection of metrics with get-or-create accessors (the
    instrumentation sites' idiom: `REG.counter("tts_x_total").inc()`
    is safe to call from anywhere, any number of times)."""

    # the per-metric cap's own accounting metric: exempt from the cap
    # (its cardinality is bounded by the number of metric NAMES) and
    # never dropped, or the valve could silence its own report
    DROPPED = "tts_metrics_dropped_total"

    def __init__(self, namespace: str = "",
                 max_series_per_metric: int | None = None):
        self.namespace = namespace
        self._lock = threading.Lock()
        self._metrics: dict[str, _Metric] = {}  # guarded-by: self._lock
        self.created_unix = time.time()
        if max_series_per_metric is None:
            try:
                from ..utils.config import env_int
                # env_int falls back to the registry default on a
                # typo'd value — a bad knob must not take down every
                # Registry() construction in the process
                max_series_per_metric = env_int("TTS_METRIC_MAX_SERIES")
            except ImportError:     # keep the registry usable solo
                max_series_per_metric = 2048
        self.max_series_per_metric = (max_series_per_metric
                                      if max_series_per_metric
                                      and max_series_per_metric > 0
                                      else None)

    def _dropped(self, metric_name: str) -> None:
        self.counter(self.DROPPED,
                     "label sets dropped by the per-metric cardinality "
                     "cap").inc(metric=metric_name)

    def _get(self, cls, name: str, help: str, **kw) -> _Metric:
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = self._metrics[name] = cls(name, help, **kw)
                if name != self.DROPPED:
                    m._series_cap = self.max_series_per_metric
                    m._on_drop = self._dropped
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as {m.kind}")
            return m

    def counter(self, name: str, help: str = "") -> Counter:
        return self._get(Counter, name, help)

    def gauge(self, name: str, help: str = "") -> Gauge:
        return self._get(Gauge, name, help)

    def histogram(self, name: str, help: str = "",
                  buckets: tuple = DEFAULT_BUCKETS) -> Histogram:
        return self._get(Histogram, name, help, buckets=buckets)

    def metrics(self) -> list[_Metric]:
        with self._lock:
            return [self._metrics[k] for k in sorted(self._metrics)]

    def remove_matching(self, name: str, **labels) -> int:
        """Drop `name`'s series whose labels include these pairs;
        returns how many were dropped (0 when the metric was never
        created — unlike `reg.gauge(name).remove_matching(...)`, this
        does not materialize an empty metric just to clean it)."""
        with self._lock:
            m = self._metrics.get(name)
        return m.remove_matching(**labels) if m is not None else 0

    # -------------------------------------------------------- exposition

    def to_json(self) -> dict:
        """Nested JSON view: {metric_name: value | {labels: value}}."""
        return {m.name: m.to_json() for m in self.metrics()}

    def to_prometheus(self) -> str:
        """Prometheus text exposition format (version 0.0.4)."""
        lines = []
        for m in self.metrics():
            if m.help:
                lines.append(f"# HELP {m.name} {m.help}")
            lines.append(f"# TYPE {m.name} {m.kind}")
            if isinstance(m, Histogram):
                with m._lock:
                    keys = sorted(m._series)
                for k in (keys or [()]):
                    snap = m.snapshot(**dict(k))
                    acc_labels = dict(k)
                    for b in m.buckets:
                        bl = _fmt_labels(_label_key(
                            {**acc_labels, "le": _fmt_value(b)}))
                        n = snap.get("buckets", {}).get(str(b), 0)
                        lines.append(f"{m.name}_bucket{bl} {n}")
                    bl = _fmt_labels(_label_key(
                        {**acc_labels, "le": "+Inf"}))
                    lines.append(f"{m.name}_bucket{bl} {snap['count']}")
                    sl = _fmt_labels(k)
                    lines.append(
                        f"{m.name}_sum{sl} {_fmt_value(snap['sum'])}")
                    lines.append(f"{m.name}_count{sl} {snap['count']}")
            else:
                for name, k, v in m.samples():
                    lines.append(f"{name}{_fmt_labels(k)} {_fmt_value(v)}")
        return "\n".join(lines) + "\n"


# -------------------------------------------------------- default registry

_default: Registry | None = None
_default_lock = threading.Lock()


def default() -> Registry:
    """The process-global registry engine-level instrumentation writes
    to (checkpoint/retry/fault/segment metrics)."""
    global _default
    with _default_lock:
        if _default is None:
            _default = Registry("tts")
        return _default


def install(reg: Registry | None) -> Registry | None:
    """Swap the process-global registry (tests; None re-arms the lazy
    build). Returns the previous one."""
    global _default
    with _default_lock:
        prev = _default
        _default = reg
        return prev
