"""Node-conservation auditor: machine-checked engine invariants.

The reference engine's only correctness story is "the explored-tree
count matches the paper's table"; the repo's golden suite pins the same
thing offline. This module checks the *live* invariants — the node
accounting identities that must hold at every segment, result, and
checkpoint/elastic-reshard/preempt-resume edge — and records every
check as a :class:`Finding`, so an accounting drift surfaces as a
machine-readable audit failure (and an `audit` health alert,
obs/health.py) instead of a wrong answer a human notices weeks later.

Invariants (exact equalities, not tolerances):

- ``children_conservation`` — every evaluated child is branched, pruned
  or a leaf: ``branched + pruned + sol == evals`` (telemetry bucket
  sums vs. engine counters; needs the telemetry block compiled in);
- ``branched_is_tree`` — telemetry's branched total equals the engine's
  explored-tree counter; the bound histograms bin exactly the pruned /
  surviving children;
- ``steal_flow`` — telemetry steal sent/recv equals the balance tier's
  sent/recv counters;
- ``node_conservation`` — a result's totals decompose exactly into
  warm-up + device + host-tier counts, and ``complete`` is true iff
  every pool drained;
- ``reshard_conservation`` — an elastic reshard (N -> M workers)
  preserves every summed counter, the pooled node count and the
  incumbent;
- ``checkpoint_roundtrip`` — a just-written checkpoint loads back with
  bit-identical counters (CRC-level corruption surfaces as a failure,
  not a silently wrong resume).

Wiring: ``engine/distributed.search`` audits every result and every
elastic-reshard resume when :func:`enabled` (``TTS_AUDIT``, default on
— the checks are host-side numpy sums, microseconds against a search);
``checkpoint.run_segmented`` re-reads and verifies each snapshot when
:func:`roundtrip_enabled` (``TTS_AUDIT=full`` / ``TTS_AUDIT_CKPT=1`` —
off by default: it re-reads the file it just wrote). ``TTS_AUDIT_HARD=1``
turns any failure into a raised :class:`AuditError` — the CI mode where
an accounting drift fails the build instead of filing an alert.

Every check lands in the process-global metrics registry
(``tts_audit_checks_total`` / ``tts_audit_failures_total`` by
invariant) and the flight recorder (``audit.check`` events, failures
flagged); :func:`recent_failures` is the read side the health layer's
`audit` rule consumes.
"""

from __future__ import annotations

import collections
import dataclasses
import threading
import time

import numpy as np

from ..utils import config as _cfg
from . import metrics, tracelog

__all__ = ["AuditError", "Finding", "enabled", "hard", "roundtrip_enabled",
           "record", "findings", "recent_failures", "clear_findings",
           "check_result", "check_state", "state_sums", "check_reshard",
           "check_checkpoint_roundtrip", "check_incumbent_fold"]


class AuditError(RuntimeError):
    """An engine invariant failed under TTS_AUDIT_HARD=1."""


@dataclasses.dataclass
class Finding:
    invariant: str
    ok: bool
    detail: dict
    t_unix: float

    def to_json(self) -> dict:
        return {"invariant": self.invariant, "ok": self.ok,
                "detail": self.detail, "t_unix": self.t_unix}


# recent findings, process-wide: the health layer's `audit` rule and
# /alerts read this ring; bounded so a flapping invariant cannot leak
_FINDINGS: collections.deque[Finding] = collections.deque(
    maxlen=256)   # guarded-by: _LOCK
_LOCK = threading.Lock()


def enabled() -> bool:
    """Result/reshard auditing (TTS_AUDIT; default ON — the checks are
    host-side sums over already-fetched counters)."""
    return (_cfg.env_str("TTS_AUDIT") or "1").strip().lower() not in (
        "0", "off", "false", "no")


def hard() -> bool:
    """CI mode: any failed invariant raises AuditError."""
    return _cfg.env_flag("TTS_AUDIT_HARD")


def roundtrip_enabled() -> bool:
    """Checkpoint re-read verification (TTS_AUDIT=full or
    TTS_AUDIT_CKPT=1); off by default — it re-reads every snapshot."""
    if (_cfg.env_str("TTS_AUDIT") or "").strip().lower() == "full":
        return True
    return _cfg.env_flag("TTS_AUDIT_CKPT")


def record(invariant: str, ok: bool, **detail) -> Finding:
    """Register one check outcome: ring + counters + trace event (and
    the hard-mode raise). Every check path below funnels through here
    so the exposition cannot drift from the checks."""
    f = Finding(invariant=invariant, ok=bool(ok),
                detail={k: _json_safe(v) for k, v in detail.items()},
                t_unix=time.time())
    with _LOCK:
        _FINDINGS.append(f)
    reg = metrics.default()
    reg.counter("tts_audit_checks_total",
                "audit invariant evaluations").inc(invariant=invariant)
    if not f.ok:
        reg.counter("tts_audit_failures_total",
                    "failed audit invariants").inc(invariant=invariant)
        tracelog.event("audit.fail", invariant=invariant, **f.detail)
        if hard():
            raise AuditError(
                f"audit invariant {invariant!r} failed: {f.detail}")
    else:
        tracelog.event("audit.check", invariant=invariant, ok=True)
    return f


def findings(n: int | None = None) -> list[Finding]:
    """Most recent findings, oldest first (all when `n` is None)."""
    with _LOCK:
        out = list(_FINDINGS)
    return out if n is None else out[-n:]


def recent_failures(window_s: float | None = None) -> list[Finding]:
    """Failed findings, optionally only those younger than `window_s`
    — the health layer's `audit` rule input."""
    cutoff = time.time() - window_s if window_s else None
    return [f for f in findings() if not f.ok
            and (cutoff is None or f.t_unix >= cutoff)]


def clear_findings() -> None:
    """Drop the ring (tests; 'recovery' for the audit alert)."""
    with _LOCK:
        _FINDINGS.clear()


def _json_safe(v):
    if isinstance(v, (str, int, float, bool)) or v is None:
        return v
    if isinstance(v, (list, tuple)):
        return [_json_safe(x) for x in v]
    if isinstance(v, dict):
        return {str(k): _json_safe(x) for k, x in v.items()}
    try:
        return v.item()
    except (AttributeError, ValueError):
        return repr(v)


# ------------------------------------------------------------- the checks


def _sol_in_evals(problem: str) -> bool:
    """Whether the problem's accounting counts solutions among the
    evaluated children (PFSP-style: branched + pruned + sol == evals)
    or among popped nodes (N-Queens-style: branched + pruned == evals)
    — problems/base.Problem.leaf_in_evals, resolved by registry name
    so the auditor and the engine cannot drift."""
    try:
        from ..problems import get
        return bool(get(problem).leaf_in_evals)
    except Exception:  # noqa: BLE001 — unknown/legacy name: PFSP rule
        return True


def check_result(res) -> list[Finding]:
    """Audit a DistResult: telemetry-vs-counter exactness and total
    node conservation (engine/distributed.search calls this on every
    result when `enabled()`). The conservation identity is problem-
    parameterized via the result's `problem` name (see _sol_in_evals);
    everything else is problem-blind."""
    out = []
    pd = res.per_device
    dev_tree = int(np.asarray(pd.get("tree", [0])).sum())
    dev_sol = int(np.asarray(pd.get("sol", [0])).sum())
    dev_evals = int(np.asarray(pd.get("evals", [0])).sum())
    host_tree = int(np.asarray(pd.get("host_tree", [0])).sum())
    host_sol = int(np.asarray(pd.get("host_sol", [0])).sum())
    out.append(record(
        "node_conservation",
        res.explored_tree == res.warmup_tree + dev_tree + host_tree
        and res.explored_sol == res.warmup_sol + dev_sol + host_sol,
        explored_tree=res.explored_tree, warmup_tree=res.warmup_tree,
        device_tree=dev_tree, host_tree=host_tree,
        explored_sol=res.explored_sol, warmup_sol=res.warmup_sol,
        device_sol=dev_sol, host_sol=host_sol))
    final = pd.get("final_size")
    if final is not None:
        out.append(record(
            "complete_means_drained",
            bool(res.complete) == (int(np.asarray(final).sum()) == 0),
            complete=bool(res.complete),
            pool=int(np.asarray(final).sum())))
    t = res.telemetry
    if t is not None:
        out.extend(_check_telemetry(
            t, tree=dev_tree, sol=dev_sol, evals=dev_evals,
            sent=int(np.asarray(pd.get("sent", [0])).sum()),
            recv=int(np.asarray(pd.get("recv", [0])).sum()),
            sol_in_evals=_sol_in_evals(
                getattr(res, "problem", "pfsp"))))
    return out


def _check_telemetry(summary: dict, tree: int, sol: int, evals: int,
                     sent: int | None = None,
                     recv: int | None = None,
                     sol_in_evals: bool = True) -> list[Finding]:
    """Telemetry bucket sums vs. engine counters (the ISSUE's
    popped = pruned + branched-consumed identity, in this engine's
    terms: every evaluated child is branched, pruned or a leaf —
    leaves counting toward `evals` only under PFSP-style accounting,
    see _sol_in_evals)."""
    out = []
    branched = int(sum(summary["branched"]))
    pruned = int(sum(summary["pruned"]))
    out.append(record("branched_is_tree", branched == tree,
                      branched=branched, tree=tree))
    want_evals = branched + pruned + (sol if sol_in_evals else 0)
    out.append(record("children_conservation",
                      want_evals == evals,
                      branched=branched, pruned=pruned, sol=sol,
                      sol_in_evals=sol_in_evals, evals=evals))
    out.append(record(
        "bound_hist_exact",
        sum(summary["bound_hist_pruned"]) == pruned
        and sum(summary["bound_hist_surviving"]) == branched,
        hist_pruned=sum(summary["bound_hist_pruned"]), pruned=pruned,
        hist_surviving=sum(summary["bound_hist_surviving"]),
        branched=branched))
    if sent is not None and recv is not None:
        out.append(record("steal_flow",
                          summary["steal_sent"] == sent
                          and summary["steal_recv"] == recv,
                          tele_sent=summary["steal_sent"], sent=sent,
                          tele_recv=summary["steal_recv"], recv=recv))
    return out


def state_sums(state) -> dict:
    """Summed counters of a host-side SearchState (single-device or
    stacked): the conserved quantities an elastic reshard / checkpoint
    roundtrip must preserve exactly."""
    def s(x):
        return int(np.asarray(x, np.int64).sum())

    out = {"size": s(state.size), "tree": s(state.tree),
           "sol": s(state.sol), "evals": s(state.evals),
           "iters_max": int(np.atleast_1d(
               np.asarray(state.iters, np.int64)).max()),
           "sent": s(state.sent), "recv": s(state.recv),
           "best": int(np.atleast_1d(
               np.asarray(state.best, np.int64)).min())}
    tele_w = int(state.telemetry.shape[-1])
    if tele_w:
        from ..engine import telemetry as tele
        block = np.atleast_2d(np.asarray(state.telemetry, np.int64))
        # only the additive slots are reshard-invariant; the high-water
        # mark and the ring merge, they don't sum
        out["telemetry_counts"] = int(
            block[:, :tele.O_POOL_HW].sum())
    return out


def check_reshard(before: dict, after_state, edge: str = "reshard"
                  ) -> list[Finding]:
    """Conservation across an elastic reshard (or any state re-homing):
    `before` is `state_sums()` of the pre-edge state."""
    after = state_sums(after_state)
    out = []
    for key, pre in before.items():
        post = after.get(key)
        out.append(record(f"{edge}_conservation", post == pre,
                          quantity=key, before=pre, after=post))
    return out


def check_checkpoint_roundtrip(path, state) -> list[Finding]:
    """Re-read a just-written checkpoint and require bit-identical
    counters. A load failure (torn write, CRC mismatch) is itself a
    failed finding — the write was supposed to be durable.

    `state` may be a SearchState OR a precomputed `state_sums()` dict —
    the async checkpoint writer (engine/checkpoint.AsyncCheckpointWriter)
    computes the sums on the dispatch thread while the arrays are still
    in hand and audits the on-disk bytes from its own thread, so the
    conservation check spans the async edge, not just the sync one."""
    from ..engine import checkpoint
    expect = state if isinstance(state, dict) else state_sums(state)
    try:
        loaded, meta = checkpoint.load(path)
    except Exception as e:  # noqa: BLE001 — the finding carries it
        return [record("checkpoint_roundtrip", False,
                       path=str(path), error=repr(e))]
    got = state_sums(loaded)
    return [record("checkpoint_roundtrip", got == expect,
                   path=str(path), expect=expect, got=got)]


def check_incumbent_fold(key: str, prev_cap, new_cap) -> Finding:
    """Monotonicity of the cross-request incumbent exchange
    (engine/incumbent.BoardClient calls this on every fold the board
    hands a search): a pruning ceiling must never LOOSEN — the board is
    a min-fold by construction, so ``new_cap > prev_cap`` means the
    exchange itself is broken (a stale read, a clobbered entry) and a
    search could prune less than it already safely did."""
    ok = prev_cap is None or int(new_cap) <= int(prev_cap)
    return record("incumbent_monotone", ok, key=str(key),
                  prev_cap=(None if prev_cap is None else int(prev_cap)),
                  new_cap=int(new_cap))


def check_state(state, edge: str = "segment",
                problem: str = "pfsp") -> list[Finding]:
    """Audit a host-side state's internal telemetry/counter exactness
    (per-segment hook; no-op without the telemetry block)."""
    tele_w = int(state.telemetry.shape[-1])
    if not tele_w:
        return []
    from ..engine import telemetry as tele
    summary = tele.summarize(np.asarray(state.telemetry))
    sums = state_sums(state)
    out = _check_telemetry(summary, tree=sums["tree"], sol=sums["sol"],
                           evals=sums["evals"], sent=sums["sent"],
                           recv=sums["recv"],
                           sol_in_evals=_sol_in_evals(problem))
    for f in out:
        f.detail["edge"] = edge
    return out
