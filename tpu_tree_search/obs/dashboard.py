"""Self-contained HTML dashboard for one server or a whole fleet.

``GET /dashboard`` (obs/httpd) renders a serve session; the ``doctor``
CLI renders a fleet scrape (obs/aggregate) to a file. Pure stdlib
string building — no script tags, no external fonts/CSS/JS, so the
page opens from an air-gapped artifact store exactly as it opened
live (the CI leg uploads it as a build artifact).

Layout follows the repo's dataviz conventions: a stat-tile row for the
headline numbers, single-series sparklines (2px line, direct label, no
legend) fed by the health monitor's history rings, an alert panel
using the reserved status palette (icon + label, never color alone),
and plain tables for requests — values wear text ink, marks carry the
color. Light and dark are both selected via CSS custom properties.
"""

from __future__ import annotations

import html
import time

__all__ = ["render_server", "render_fleet", "sparkline_svg"]

_CSS = """
:root { color-scheme: light dark; }
body { margin: 0; padding: 24px; background: var(--surface-1);
  color: var(--text-primary);
  font: 14px/1.45 system-ui, -apple-system, sans-serif; }
body {
  --surface-1: #fcfcfb; --surface-2: #f1f0ee;
  --text-primary: #0b0b0b; --text-secondary: #52514e;
  --grid: #e3e2de; --series-1: #2a78d6;
  --good: #0ca30c; --warning: #fab219; --serious: #ec835a;
  --critical: #d03b3b; }
@media (prefers-color-scheme: dark) {
  body { --surface-1: #1a1a19; --surface-2: #262624;
    --text-primary: #ffffff; --text-secondary: #c3c2b7;
    --grid: #3a3935; --series-1: #3987e5; } }
h1 { font-size: 18px; margin: 0 0 4px; }
h2 { font-size: 13px; margin: 28px 0 8px; color: var(--text-secondary);
  text-transform: uppercase; letter-spacing: .06em; }
.sub { color: var(--text-secondary); margin: 0 0 20px; }
.tiles { display: flex; flex-wrap: wrap; gap: 12px; }
.tile { background: var(--surface-2); border-radius: 8px;
  padding: 12px 16px; min-width: 120px; }
.tile .v { font-size: 24px; font-weight: 600; }
.tile .k { color: var(--text-secondary); font-size: 12px; }
.tile.bad .v { color: var(--critical); }
table { border-collapse: collapse; width: 100%; }
th { text-align: left; color: var(--text-secondary); font-weight: 500;
  font-size: 12px; }
th, td { padding: 6px 10px 6px 0;
  border-bottom: 1px solid var(--grid); }
td.num { font-variant-numeric: tabular-nums; }
.sev { font-weight: 600; }
.sev.critical { color: var(--critical); }
.sev.warn { color: var(--warning); }
.sev.info { color: var(--text-secondary); }
.state-firing { color: var(--critical); font-weight: 600; }
.state-pending { color: var(--serious); }
.state-resolved { color: var(--good); }
.sparks { display: flex; flex-wrap: wrap; gap: 16px; }
.spark { background: var(--surface-2); border-radius: 8px;
  padding: 10px 14px; }
.spark .k { color: var(--text-secondary); font-size: 12px; }
.spark .v { font-weight: 600; margin-left: 8px; }
.ok { color: var(--good); } .err { color: var(--critical); }
.mono { font-family: ui-monospace, monospace; font-size: 12px; }
.stripe { display: flex; height: 14px; width: 320px;
  border-radius: 3px; overflow: hidden; background: var(--surface-2); }
.stripe span { display: block; height: 100%; }
.st-idle { background: var(--grid); }
.st-compiling { background: var(--warning); }
.st-executing { background: var(--good); }
.st-draining { background: var(--serious); }
.st-quarantined { background: var(--critical); }
.st-batch-frozen { background: var(--series-1); }
footer { margin-top: 32px; color: var(--text-secondary);
  font-size: 12px; }
"""

_SEV_ICON = {"critical": "▲", "warn": "●", "info": "○"}
_STATE_ICON = {"firing": "▲", "pending": "●",
               "resolved": "✓"}


def _esc(v) -> str:
    return html.escape(str(v))


def sparkline_svg(points, width: int = 180, height: int = 36) -> str:
    """One series as an inline SVG polyline (2px stroke, no axes — the
    tile label and last value carry the reading; a <title> supplies
    the hover detail without any script)."""
    vals = [float(v) for _, v in points]
    if not vals:
        return ""
    lo, hi = min(vals), max(vals)
    span = (hi - lo) or 1.0
    n = len(vals)
    pts = " ".join(
        f"{(i * (width - 4) / max(n - 1, 1) + 2):.1f},"
        f"{(height - 3 - (v - lo) / span * (height - 6)):.1f}"
        for i, v in enumerate(vals))
    return (
        f'<svg width="{width}" height="{height}" role="img" '
        f'aria-label="min {lo:g}, max {hi:g}">'
        f"<title>min {lo:g} · max {hi:g} · last {vals[-1]:g}</title>"
        f'<polyline points="{pts}" fill="none" stroke="var(--series-1)" '
        'stroke-width="2" stroke-linejoin="round" '
        'stroke-linecap="round"/></svg>')


def _fmt(v) -> str:
    if isinstance(v, float):
        if abs(v) >= 1e9:
            return f"{v / 1e9:.2f}G"
        if abs(v) >= 1e6:
            return f"{v / 1e6:.2f}M"
        if v.is_integer():
            return str(int(v))
        return f"{v:.3f}"
    return str(v)


def _tile(label: str, value, bad: bool = False) -> str:
    cls = "tile bad" if bad else "tile"
    return (f'<div class="{cls}"><div class="v">{_esc(_fmt(value))}'
            f'</div><div class="k">{_esc(label)}</div></div>')


def _alert_rows(alerts: list[dict], with_origin: bool = False) -> str:
    if not alerts:
        return ('<tr><td colspan="6" class="ok">'
                "✓ no alerts recorded</td></tr>")
    rows = []
    for a in alerts:
        sev = a.get("severity", "warn")
        state = a.get("state", "?")
        origin = (f"<td>{_esc(a.get('origin', ''))}</td>"
                  if with_origin else "")
        detail = ", ".join(f"{k}={_fmt(v) if isinstance(v, float) else v}"
                           for k, v in (a.get("detail") or {}).items())
        rows.append(
            f"<tr>{origin}"
            f'<td class="sev {_esc(sev)}">{_SEV_ICON.get(sev, "?")} '
            f"{_esc(sev)}</td>"
            f"<td>{_esc(a.get('rule'))}</td>"
            f'<td class="state-{_esc(state)}">'
            f"{_STATE_ICON.get(state, '')} {_esc(state)}</td>"
            f'<td class="num">{a.get("fired_count", 0)}</td>'
            f'<td class="mono">{_esc(detail)}</td></tr>')
    return "".join(rows)


def _eta_cell(r: dict) -> tuple[str, str]:
    """(progress, eta) cells from a request snapshot's estimate block
    (obs/estimate) — em-dashes while warming up / estimation off."""
    est = ((r.get("progress") or {}).get("estimate") or {})
    p = est.get("progress_ratio")
    eta = est.get("eta_s")
    return (f"{p * 100:.1f}%" if p is not None else "—",
            f"{eta:g}" if eta is not None else "—")


def _request_rows(reqs: list[dict], with_origin: bool = False) -> str:
    if not reqs:
        return '<tr><td colspan="11">no requests</td></tr>'
    rows = []
    for r in sorted(reqs, key=lambda r: str(r.get("id"))):
        origin = (f"<td>{_esc(r.get('origin', ''))}</td>"
                  if with_origin else "")
        prog = r.get("progress") or {}
        res = r.get("result") or {}
        best = res.get("best", prog.get("best", ""))
        pct, eta = _eta_cell(r)
        rows.append(
            f"<tr>{origin}<td>{_esc(r.get('id'))}</td>"
            f"<td>{_esc(r.get('state'))}</td>"
            f'<td class="num">{_esc(r.get("submesh", ""))}</td>'
            f'<td class="num">{r.get("dispatches", 0)}</td>'
            f'<td class="num">{r.get("preemptions", 0)}</td>'
            f'<td class="num">{_esc(r.get("spent_s", ""))}</td>'
            f'<td class="num">{_esc(pct)}</td>'
            f'<td class="num">{_esc(eta)}</td>'
            f'<td class="num">{_esc(best)}</td>'
            f'<td class="mono">{_esc(r.get("error") or "")}</td></tr>')
    return "".join(rows)


def _lane_rows(cap: dict | None) -> str:
    """Per-lane utilization stripes from the capacity snapshot's
    ``lanes_detail`` (obs/capacity.LaneLedger): one horizontal stripe
    per lane, segment width = fraction of lifetime in each state (the
    reserved status palette carries the state; the title attribute and
    the utilization cell carry the numbers)."""
    lanes = (cap or {}).get("lanes_detail") or []
    if not lanes:
        return ""
    rows = []
    for ln in lanes:
        life = ln.get("lifetime_s") or 0.0
        segs = []
        for state, secs in sorted((ln.get("seconds") or {}).items()):
            frac = (secs / life * 100.0) if life > 0 else 0.0
            if frac < 0.05:
                continue
            segs.append(
                f'<span class="st-{_esc(state)}" '
                f'style="width:{frac:.2f}%" '
                f'title="{_esc(state)} {secs:.1f}s '
                f'({frac:.1f}%)"></span>')
        util = ln.get("utilization")
        util_cell = f"{util * 100:.1f}%" if util is not None else "—"
        rows.append(
            f'<tr><td class="num">{_esc(ln.get("lane"))}</td>'
            f"<td>{_esc(ln.get('state'))}</td>"
            f'<td><div class="stripe">{"".join(segs)}</div></td>'
            f'<td class="num">{util_cell}</td>'
            f'<td class="num">{life:.1f}</td></tr>')
    return (
        "<h2>Lanes</h2><table><tr><th>lane</th><th>state</th>"
        "<th>time in state</th><th>executing</th><th>lifetime s</th>"
        f"</tr>{''.join(rows)}</table>")


def _page(title: str, sub: str, body: str) -> str:
    return (
        "<!doctype html><html><head><meta charset='utf-8'>"
        f"<title>{_esc(title)}</title><style>{_CSS}</style></head>"
        f"<body><h1>{_esc(title)}</h1><p class='sub'>{_esc(sub)}</p>"
        f"{body}<footer>generated "
        f"{time.strftime('%Y-%m-%d %H:%M:%S')} · tpu_tree_search "
        "operational dashboard · self-contained (no external assets)"
        "</footer></body></html>")


def _remediation_rows(rem: dict | None) -> str:
    """The self-healing journal tail (service/remediate snapshot)."""
    actions = (rem or {}).get("actions") or []
    if not actions:
        mode = (rem or {}).get("mode", "observe")
        return (f'<tr><td colspan="4" class="ok">✓ no remediation '
                f"activity ({_esc(mode)} mode)</td></tr>")
    rows = []
    for a in reversed(actions[-12:]):
        outcome = a.get("outcome", "?")
        cls = ("ok" if outcome in ("applied", "observed")
               else "err" if outcome in ("failed", "error") else "")
        detail = ", ".join(
            f"{k}={_fmt(v) if isinstance(v, float) else v}"
            for k, v in (a.get("detail") or {}).items())
        rows.append(
            f"<tr><td>{_esc(a.get('rule'))}</td>"
            f"<td>{_esc(a.get('action'))}</td>"
            f'<td class="{cls}">{_esc(outcome)}</td>'
            f'<td class="mono">{_esc(detail)}</td></tr>')
    return "".join(rows)


def render_server(snapshot: dict | None, alerts: dict | None,
                  history: dict | None) -> str:
    """One serve session: stat tiles, alert panel, self-healing
    journal, sparklines from the health monitor's history rings,
    request table."""
    snapshot = snapshot or {}
    alerts = alerts or {}
    firing = alerts.get("firing", 0)
    queue = snapshot.get("queue") or {}
    subs = snapshot.get("submeshes") or []
    busy = sum(1 for s in subs if s.get("running"))
    counters = snapshot.get("counters") or {}
    cache = snapshot.get("executor_cache") or {}
    rem = snapshot.get("remediation") or {}
    n_quar = len(rem.get("quarantined") or [])
    paused = rem.get("admission_paused")
    led = snapshot.get("ledger") or {}
    led_tiles = []
    if led:
        from .aggregate import recovered_live
        led_tiles = [
            _tile("restarts", led.get("restarts", 0)),
            _tile("recovered", recovered_live(led)),
            _tile("ledger lag s", led.get("lag_s")
                  if led.get("lag_s") is not None else "—"),
        ]
    fo = snapshot.get("failover") or {}
    if fo:
        peers = fo.get("peers") or []
        peers_down = sum(1 for p in peers
                         if p.get("expired") and not p.get("released"))
        led_tiles += [
            _tile("failover", "FENCED" if fo.get("fenced")
                  else fo.get("mode", "observe"),
                  bad=bool(fo.get("fenced"))),
            _tile("lease epoch",
                  (fo.get("lease") or {}).get("epoch", "—")),
            _tile("peers down", peers_down, bad=peers_down > 0),
            _tile("takeovers", fo.get("takeovers", 0)),
        ]
    tiles = "".join([
        _tile("firing alerts", firing, bad=firing > 0),
        _tile("queue depth", queue.get("depth", 0)),
        _tile("submeshes busy", f"{busy}/{len(subs)}"),
        _tile("quarantined", n_quar, bad=n_quar > 0),
        _tile("admission", "paused" if paused else "open",
              bad=bool(paused)),
        _tile("done", counters.get("done", 0)),
        _tile("failed", counters.get("failed", 0),
              bad=counters.get("failed", 0) > 0),
        _tile("preemptions", counters.get("preemptions", 0)),
        _tile("cache hit/miss", f"{cache.get('hits', 0)}/"
                                f"{cache.get('misses', 0)}"),
    ] + led_tiles)
    sparks = []
    for name, points in sorted((history or {}).items()):
        svg = sparkline_svg(points)
        if not svg:
            continue
        last = points[-1][1]
        sparks.append(f'<div class="spark"><span class="k">'
                      f"{_esc(name)}</span><span class='v'>"
                      f"{_esc(_fmt(float(last)))}</span><br>{svg}</div>")
    body = (
        f'<div class="tiles">{tiles}</div>'
        "<h2>Alerts</h2><table><tr><th>severity</th><th>rule</th>"
        "<th>state</th><th>fired</th><th>detail</th></tr>"
        f"{_alert_rows(alerts.get('alerts') or [])}</table>"
        f"<h2>Self-healing ({_esc(rem.get('mode', 'observe'))} mode)"
        "</h2><table><tr><th>rule</th><th>action</th><th>outcome</th>"
        f"<th>detail</th></tr>{_remediation_rows(rem)}</table>"
        + (f"<h2>Trends</h2><div class='sparks'>{''.join(sparks)}</div>"
           if sparks else "")
        + _lane_rows(snapshot.get("capacity"))
        + "<h2>Requests</h2><table><tr><th>id</th><th>state</th>"
          "<th>submesh</th><th>disp</th><th>preempt</th>"
          "<th>spent s</th><th>progress</th><th>eta s</th>"
          "<th>best</th><th>error</th></tr>"
        + _request_rows(list((snapshot.get("requests") or {}).values()))
        + "</table>")
    up = snapshot.get("uptime_s")
    return _page("tpu_tree_search — server health",
                 f"uptime {up}s · {len(subs)} submesh(es) · "
                 f"{alerts.get('evaluations', 0)} health sweeps", body)


def render_fleet(merged: dict) -> str:
    """A fleet scrape (obs/aggregate.merge): per-server verdicts, all
    alerts and requests origin-labeled."""
    servers = merged.get("servers") or []
    firing = merged.get("firing", 0)
    down = sum(1 for s in servers if not s["ok"])
    quarantined = sum(s.get("quarantined") or 0 for s in servers)
    paused = sum(1 for s in servers if s.get("admission_paused"))
    tiles = "".join([
        _tile("servers", len(servers)),
        _tile("unreachable", down, bad=down > 0),
        _tile("firing alerts", firing, bad=firing > 0),
        _tile("quarantined submeshes", quarantined,
              bad=quarantined > 0),
        _tile("admission paused", paused, bad=paused > 0),
        _tile("fenced", sum(1 for s in servers if s.get("fenced")),
              bad=any(s.get("fenced") for s in servers)),
        _tile("requests", len(merged.get("requests") or [])),
    ])
    srv_rows = []
    for s in servers:
        ok = s["ok"] and s.get("healthz") == "ok"
        degraded = bool(s.get("quarantined"))
        mark = (f'<span class="err">✗ '
                f"{_esc(s.get('error') or s.get('healthz'))}</span>"
                if not ok else
                '<span class="sev warn">● degraded</span>'
                if degraded else '<span class="ok">✓ ok</span>')
        rem = ((f"{s.get('quarantined')} quarantined"
                if s.get("quarantined") else "")
               + (" · paused" if s.get("admission_paused") else ""))
        led = ("—" if s.get("restarts") is None else
               f"{s.get('restarts')} restart(s) · "
               f"{s.get('recovered_requests')} recovered · "
               f"lag {s.get('ledger_lag_s')}s")
        if s.get("failover_mode") is None and not s.get("fenced"):
            fo_cell = "—"
        else:
            fo_cell = (f"{s.get('failover_mode')} · "
                       f"epoch {s.get('lease_epoch')} · "
                       f"{s.get('peers_down') or 0} down · "
                       f"{s.get('takeovers') or 0} takeover(s)")
            if s.get("fenced"):
                # icon + word, never color alone (the palette rule)
                fo_cell = "✗ FENCED · " + fo_cell
        util = s.get("utilization")
        util_cell = f"{util * 100:.0f}%" if util is not None else "—"
        srv_rows.append(
            f"<tr><td>{_esc(s['origin'])}</td><td>{mark}</td>"
            f'<td class="num">{_esc(s.get("firing", "-"))}</td>'
            f'<td class="num">{_esc(s.get("queue_depth", "-"))}</td>'
            f'<td class="num">{_esc(s.get("submeshes_busy", "-"))}/'
            f"{_esc(s.get('submeshes', '-'))}</td>"
            f'<td class="num">{_esc(util_cell)}</td>'
            f"<td>{_esc(rem or '—')}</td>"
            f"<td>{_esc(led)}</td>"
            f"<td>{_esc(fo_cell)}</td>"
            f'<td class="num">{_esc(s.get("requests", 0))}</td>'
            f'<td class="num">{_esc(s.get("uptime_s", "-"))}</td></tr>')
    body = (
        f'<div class="tiles">{tiles}</div>'
        "<h2>Servers</h2><table><tr><th>origin</th><th>health</th>"
        "<th>firing</th><th>queue</th><th>busy</th><th>ρ</th>"
        "<th>remediation</th><th>ledger</th><th>failover</th>"
        "<th>requests</th>"
        f"<th>uptime s</th></tr>{''.join(srv_rows)}</table>"
        "<h2>Alerts</h2><table><tr><th>origin</th><th>severity</th>"
        "<th>rule</th><th>state</th><th>fired</th><th>detail</th></tr>"
        f"{_alert_rows(merged.get('alerts') or [], with_origin=True)}"
        "</table>"
        "<h2>Requests</h2><table><tr><th>origin</th><th>id</th>"
        "<th>state</th><th>submesh</th><th>disp</th><th>preempt</th>"
        "<th>spent s</th><th>progress</th><th>eta s</th>"
        "<th>best</th><th>error</th></tr>"
        f"{_request_rows(merged.get('requests') or [], with_origin=True)}"
        "</table>")
    return _page("tpu_tree_search — fleet health",
                 f"{len(servers)} server(s) scraped", body)
