"""Request-journey reconstruction: one causal timeline per LOGICAL
request, across process lifetimes and hosts.

The serving stack already journals everything needed to answer "what
happened to this request" — it just journals it in pieces: the request
ledger's admit/dispatch/budget/preempt/terminal records (per owner,
wall-clock stamped), boot records delimiting process lifetimes,
takeover records from the failover watcher, and — since the id-lineage
fix riding this module — ``origin_rid``/``origin_owner`` stamps on
every takeover re-admission, so the fresh rid an adopter assigns is
machine-linked to the orphan rid it continues. This module stitches
those pieces:

- every ledger record is attributed to an ``(owner, lifetime)`` —
  owner = the ledger directory's name, lifetime = the count of ``boot``
  records seen before it;
- rids chain into one logical journey via ``origin_rid`` links
  (takeover re-admission) and ``portfolio`` membership records (parent
  -> member fan-out); a ledger replay after kill -9 keeps the SAME rid,
  so restarts need no link at all;
- the journey's budget story is the ordered sequence of ``spent_s``
  witnesses (admit carry-over, budget heartbeats, preempt/terminal
  snapshots) — monotone by construction when nothing was lost;
- durable-store events (obs/store.py) matching the journey's rids/tags
  enrich the timeline when a store is given.

Everything here is stdlib-only and read-only: the ``journey`` CLI
subcommand runs it before the accelerator stack bootstraps, and the
tools load it against a dead fleet's directory.
"""

from __future__ import annotations

import json
import os
import pathlib

from .store import _scan_segment, read_store

__all__ = ["load_ledger_dir", "fleet_ledger_dirs", "build_journeys",
           "find_journeys", "render_journey"]

LEDGER_SEGMENT_PREFIX = "seg-"
LEDGER_SEGMENT_SUFFIX = ".jsonl"

# terminal request states (mirrors service/request.TERMINAL_STATES;
# kept local: stdlib-only module)
_TERMINAL = frozenset({"DONE", "CANCELLED", "DEADLINE", "FAILED"})

_EPS = 1e-6      # spent_s witnesses may round; monotone up to this


# ------------------------------------------------------------- loading

def load_ledger_dir(root: str | os.PathLike) -> list[dict]:
    """CRC-verified records of one ledger directory, in append order.
    Damaged lines (and the rest of their segment) are skipped, never
    repaired — this reader may be pointed at a LIVE peer's ledger."""
    root = pathlib.Path(root)
    out: list[dict] = []
    if not root.is_dir():
        return out
    for seg in sorted(root.iterdir()):
        if not (seg.name.startswith(LEDGER_SEGMENT_PREFIX)
                and seg.name.endswith(LEDGER_SEGMENT_SUFFIX)):
            continue
        try:
            data = seg.read_bytes()
        except OSError:
            continue
        for rec, _end in _scan_segment(data):
            if rec is None:
                break
            out.append(rec)
    return out


def fleet_ledger_dirs(fleet_root: str | os.PathLike) -> list[str]:
    """Every subdirectory of `fleet_root` that holds ledger segments —
    the failover watcher's peer-scan rule."""
    root = pathlib.Path(fleet_root)
    if not root.is_dir():
        return []
    out = []
    for child in sorted(root.iterdir()):
        if not child.is_dir():
            continue
        if any(p.name.startswith(LEDGER_SEGMENT_PREFIX)
               and p.name.endswith(LEDGER_SEGMENT_SUFFIX)
               for p in child.iterdir()):
            out.append(str(child))
    return out


# ------------------------------------------------------------ stitching

class _Node:
    """Per-(owner, rid) event accumulator before chaining."""

    __slots__ = ("owner", "rid", "tag", "tenant", "events", "admit_t",
                 "origin", "carried_s", "terminal", "members",
                 "parent")

    def __init__(self, owner: str, rid: str):
        self.owner = owner
        self.rid = rid
        self.tag = None
        self.tenant = None
        self.events: list[dict] = []
        self.admit_t = None
        self.origin = None          # (owner, rid) this one continues
        self.carried_s = 0.0
        self.terminal = None        # terminal state string
        self.members: list[str] = []   # portfolio member rids (parent)
        self.parent = None          # portfolio parent rid (member)


def _owner_name(path: str) -> str:
    return pathlib.Path(path).name or str(path)


def build_journeys(records_by_owner: dict[str, list[dict]],
                   store_records: list[dict] | None = None
                   ) -> list[dict]:
    """Stitch journeys from per-owner ledger records (see module
    docstring). Returns one JSON-safe dict per logical request, newest
    root admit first."""
    nodes: dict[tuple, _Node] = {}
    lifetimes: dict[tuple, dict] = {}   # (owner, lifetime) -> meta

    def node(owner: str, rid) -> _Node | None:
        if rid is None:
            return None
        key = (owner, str(rid))
        n = nodes.get(key)
        if n is None:
            n = nodes[key] = _Node(owner, str(rid))
        return n

    for owner, records in records_by_owner.items():
        life = 0
        for rec in records:
            kind = rec.get("k")
            t = rec.get("t")
            if kind == "boot":
                life += 1
                lt = lifetimes.setdefault((owner, life), {
                    "owner": owner, "lifetime": life,
                    "boot_t": t, "pid": rec.get("pid"),
                    "records": 0, "takeover": False})
                continue
            lt = lifetimes.setdefault((owner, life), {
                "owner": owner, "lifetime": life, "boot_t": t,
                "pid": rec.get("pid"), "records": 0,
                "takeover": False})
            lt["records"] += 1
            lt["last_t"] = t
            if kind == "takeover":
                lt["takeover"] = True
                continue
            n = node(owner, rec.get("rid"))
            if n is None:
                continue
            ev = {"t": t, "owner": owner, "lifetime": life,
                  "kind": kind}
            if kind == "admit":
                n.tag = rec.get("tag") or n.tag
                n.tenant = rec.get("tenant") or n.tenant
                n.admit_t = t
                n.carried_s = float(rec.get("spent_s") or 0.0)
                if rec.get("origin_rid"):
                    n.origin = (str(rec.get("origin_owner") or owner),
                                str(rec["origin_rid"]))
                    ev["origin_rid"] = rec["origin_rid"]
                    ev["origin_owner"] = rec.get("origin_owner")
                ev["spent_s"] = n.carried_s
            elif kind == "restore":
                # compaction's absolute entry: synthesize the admit
                # story the dropped incremental records told
                entry = rec.get("entry") or {}
                n.tag = entry.get("tag") or n.tag
                n.tenant = entry.get("tenant") or n.tenant
                if n.admit_t is None:
                    n.admit_t = t
                n.carried_s = float(entry.get("spent_s") or 0.0)
                if entry.get("origin_rid"):
                    n.origin = (
                        str(entry.get("origin_owner") or owner),
                        str(entry["origin_rid"]))
                term = entry.get("terminal")
                if term is not None:
                    n.terminal = entry.get("state")
                ev["spent_s"] = n.carried_s
            elif kind == "budget":
                ev["spent_s"] = float(rec.get("spent_s") or 0.0)
                if rec.get("progress") is not None:
                    # the estimator's published ratio rides the same
                    # throttled budget record (service/server
                    # _ledger_budget) — per-lifetime progress marks on
                    # the timeline; absent when TTS_PROGRESS=0
                    ev["progress"] = float(rec["progress"])
            elif kind == "preempt":
                ev["spent_s"] = float(rec.get("spent_s") or 0.0)
                ev["hold"] = bool(rec.get("hold"))
            elif kind == "failure":
                ev["error"] = rec.get("error")
                ev["submesh"] = rec.get("submesh")
                ev["spent_s"] = float(rec.get("spent_s") or 0.0)
            elif kind == "dispatch":
                ev["submesh"] = rec.get("submesh")
            elif kind == "terminal":
                snap = rec.get("snapshot") or {}
                n.terminal = rec.get("state")
                ev["state"] = n.terminal
                if snap.get("spent_s") is not None:
                    ev["spent_s"] = float(snap["spent_s"])
                if snap.get("batch"):
                    ev["batch"] = snap["batch"]
                if snap.get("tenant"):
                    n.tenant = snap["tenant"]
            elif kind == "portfolio":
                n.members = [str(m) for m in rec.get("members") or ()]
                for m in n.members:
                    mn = node(owner, m)
                    mn.parent = n.rid
                ev["members"] = n.members
            n.events.append(ev)

    # ---- chain rids into logical journeys (origin + portfolio links)
    root_of: dict[tuple, tuple] = {}

    def find_root(key: tuple) -> tuple:
        seen = set()
        while key not in seen:
            seen.add(key)
            n = nodes.get(key)
            if n is None:
                return key
            if n.origin is not None and n.origin in nodes:
                key = n.origin
                continue
            if n.parent is not None:
                pkey = (n.owner, n.parent)
                if pkey in nodes:
                    key = pkey
                    continue
            return key
        return key

    groups: dict[tuple, list[_Node]] = {}
    for key, n in nodes.items():
        root = root_of.setdefault(key, find_root(key))
        groups.setdefault(root, []).append(n)

    journeys = []
    for root_key, members in groups.items():
        journeys.append(_assemble(root_key, nodes, members, lifetimes,
                                  store_records))
    journeys.sort(key=lambda j: j.get("admit_t") or 0.0, reverse=True)
    return journeys


def _assemble(root_key: tuple, nodes: dict, members: list,
              lifetimes: dict, store_records) -> dict:
    root = nodes.get(root_key)
    chain = sorted(members, key=lambda n: (n.admit_t or 0.0))
    events: list[dict] = []
    for n in chain:
        for ev in n.events:
            ev = dict(ev)
            ev["rid"] = n.rid
            events.append(ev)
    events.sort(key=lambda e: (e.get("t") or 0.0))

    # budget story: ordered spent_s witnesses across the whole chain.
    # Portfolio members each run their own clock, so monotonicity is
    # judged per rid and the journey total is the root/winner lane's.
    witnesses: dict[str, list] = {}
    for ev in events:
        if "spent_s" in ev:
            witnesses.setdefault(ev["rid"], []).append(ev["spent_s"])
    monotone = all(
        all(b >= a - _EPS for a, b in zip(ws, ws[1:]))
        for ws in witnesses.values())
    spent = max((ws[-1] for ws in witnesses.values()), default=0.0)

    lanes = sorted({(e["owner"], e["lifetime"]) for e in events})
    lifes = []
    for key in lanes:
        meta = dict(lifetimes.get(key) or
                    {"owner": key[0], "lifetime": key[1]})
        mine = [e for e in events
                if (e["owner"], e["lifetime"]) == key]
        meta["events"] = len(mine)
        meta["first_t"] = mine[0].get("t")
        meta["last_t"] = mine[-1].get("t")
        sp = [e["spent_s"] for e in mine if "spent_s" in e]
        if sp:
            meta["spent_end_s"] = sp[-1]
        # per-lifetime progress marks (estimator ratios riding the
        # budget records): where the estimate stood when this lifetime
        # ended — a resumed lifetime starting near its predecessor's
        # progress_end is the warm-continuation witness
        pr = [e["progress"] for e in mine if "progress" in e]
        if pr:
            meta["progress_end"] = pr[-1]
        lifes.append(meta)

    admits = sum(1 for e in events
                 if e["kind"] == "admit" and "origin_rid" not in e
                 and nodes.get((e["owner"], e["rid"])) is not None
                 and nodes[(e["owner"], e["rid"])].parent is None)
    # terminal of the LOGICAL request: the last rid in the chain that
    # is not a portfolio member lane (members cancel when a sibling
    # wins — those terminals are lane detail, not the journey's)
    top = [n for n in chain if n.parent is None]
    terminals = sum(1 for n in top if n.terminal is not None)
    state = None
    for n in top:
        if n.terminal is not None:
            state = n.terminal
    if state is None:
        state = "LIVE"

    tags = [n.tag for n in chain if n.tag]
    tenant = next((n.tenant for n in chain if n.tenant), "-")
    batches = sorted({e["batch"] for e in events if e.get("batch")})
    out = {
        "tag": tags[0] if tags else (root.tag if root else None),
        "tenant": tenant,
        "root": {"owner": root_key[0], "rid": root_key[1]},
        "rids": [{"owner": n.owner, "rid": n.rid,
                  "origin": (list(n.origin) if n.origin else None),
                  "portfolio_parent": n.parent,
                  "terminal": n.terminal}
                 for n in chain],
        "admit_t": chain[0].admit_t if chain else None,
        "admits": admits,
        "terminals": terminals,
        "state": state,
        "spent_s": round(spent, 3),
        "budget_monotone": monotone,
        "preemptions": sum(1 for e in events if e["kind"] == "preempt"),
        "failures": sum(1 for e in events if e["kind"] == "failure"),
        "dispatches": sum(1 for e in events if e["kind"] == "dispatch"),
        "takeovers": sum(1 for e in events
                         if e["kind"] == "admit"
                         and "origin_rid" in e),
        "batches": batches,
        "lifetimes": lifes,
        "events": events,
    }
    if any(n.members for n in chain):
        parent = next(n for n in chain if n.members)
        out["portfolio"] = {"k": len(parent.members),
                            "members": parent.members}
    if store_records:
        out["store_events"] = _store_events_for(out, store_records)
    return out


def _store_events_for(journey: dict, store_records: list[dict]
                      ) -> list[dict]:
    """Durable-store events matching the journey's rids or tags —
    alert/remediation/failover context around the request's own
    records."""
    rids = {r["rid"] for r in journey["rids"]}
    tags = {journey.get("tag")} - {None}
    out = []
    for rec in store_records:
        if rec.get("k") != "event":
            continue
        if (rec.get("request_id") in rids or rec.get("rid") in rids
                or rec.get("orphan_id") in rids
                or (rec.get("tag") and rec.get("tag") in tags)):
            out.append(rec)
    return out


# ------------------------------------------------------------- querying

def find_journeys(ledger_dirs=None, fleet_dir=None, store=None,
                  tag: str | None = None) -> list[dict]:
    """Load + stitch + filter in one call (the httpd/CLI entry).
    `ledger_dirs` is an iterable of ledger directories; `fleet_dir`
    adds every peer ledger under it; `store` is the obs-store
    directory (optional enrichment). `tag` filters to journeys whose
    tag or any rid matches."""
    dirs = [str(d) for d in (ledger_dirs or [])]
    if fleet_dir:
        for d in fleet_ledger_dirs(fleet_dir):
            if d not in dirs:
                dirs.append(d)
    by_owner: dict[str, list] = {}
    for d in dirs:
        recs = load_ledger_dir(d)
        if recs:
            by_owner.setdefault(_owner_name(d), []).extend(recs)
    store_records = read_store(store) if store else None
    journeys = build_journeys(by_owner, store_records)
    if tag:
        journeys = [j for j in journeys
                    if j.get("tag") == tag
                    or any(r["rid"] == tag for r in j["rids"])]
    return journeys


# ------------------------------------------------------------ rendering

def render_journey(j: dict) -> str:
    """Human-readable single-journey report (the CLI's default view)."""
    lines = [
        f"journey  tag={j.get('tag')}  tenant={j.get('tenant')}  "
        f"state={j.get('state')}",
        f"  rids: " + " -> ".join(
            f"{r['owner']}/{r['rid']}"
            + (f" (origin {r['origin'][0]}/{r['origin'][1]})"
               if r.get("origin") else "")
            for r in j["rids"] if not r.get("portfolio_parent")),
        f"  admits={j['admits']} terminals={j['terminals']} "
        f"dispatches={j['dispatches']} preemptions={j['preemptions']} "
        f"failures={j['failures']} takeovers={j['takeovers']}",
        f"  spent_s={j['spent_s']} "
        f"budget_monotone={j['budget_monotone']}",
    ]
    if j.get("portfolio"):
        lines.append(f"  portfolio: k={j['portfolio']['k']} "
                     f"members={','.join(j['portfolio']['members'])}")
    if j.get("batches"):
        lines.append(f"  batches: {','.join(map(str, j['batches']))}")
    lines.append("  lifetimes:")
    for lt in j["lifetimes"]:
        span = ""
        if lt.get("first_t") is not None and lt.get("last_t") is not None:
            span = f" span={lt['last_t'] - lt['first_t']:.1f}s"
        prog = (f" progress_end={lt['progress_end'] * 100:.1f}%"
                if lt.get("progress_end") is not None else "")
        lines.append(
            f"    {lt['owner']} #{lt['lifetime']} pid={lt.get('pid')} "
            f"events={lt.get('events', 0)}"
            f" spent_end_s={lt.get('spent_end_s', '-')}{prog}"
            f"{' TAKEOVER' if lt.get('takeover') else ''}{span}")
    return "\n".join(lines)


def to_json(journeys: list[dict]) -> str:
    return json.dumps({"journeys": journeys}, indent=2, sort_keys=True)
