"""On-demand XLA profiler capture — the process's ONE profiling door.

``jax.profiler.start_trace``/``stop_trace`` are process-global: two
concurrent captures corrupt each other's artifact (the profiler writes
one TensorBoard run dir at a time) and jax itself raises mid-capture.
:class:`ProfilerSession` serializes them behind a non-blocking lock —
one capture at a time, a second caller gets :class:`ProfilerBusyError`
immediately (the HTTP front-end maps it to ``409 Conflict``) instead of
a corrupted trace or a surprise exception from inside jax.

Every profiler entry point in the repo routes through here — the
``POST /profile`` endpoint on a live serve session (obs/httpd), the
``profile`` CLI subcommand, ``tools/profile_step.py`` and
``tools/validate_attribution.py`` — so the mutual exclusion holds
across all of them. **No direct ``jax.profiler`` calls outside this
module**; the trace-around-a-block helper that used to live in
``utils/device_info.py`` is this module's :func:`trace`.

Artifacts land as the standard TensorBoard profile layout
(``<dir>/plugins/profile/<run>/*.trace.json.gz``), parseable by
``obs/chrome_trace.load_xla_trace`` and renderable by
``tools/search_report.py`` / ``tools/trace_selftime.py`` — self-time
attribution next to the flight recorder's counter lanes. Each capture
is itself flight-recorded (a ``profiler.capture`` span with the
artifact path) and counted (``tts_profile_captures_total``).
"""

from __future__ import annotations

import contextlib
import os
import threading
import time

from . import metrics, tracelog

__all__ = ["ProfilerBusyError", "ProfilerSession", "session", "trace",
           "capture"]


class ProfilerBusyError(RuntimeError):
    """A capture is already running (the profiler is process-global and
    strictly one-at-a-time); retry after it stops."""


class ProfilerSession:
    """Thread-safe one-at-a-time wrapper over the jax profiler.

    ``start(log_dir)`` / ``stop()`` bracket a capture by hand (the HTTP
    endpoint and the CLI use :meth:`capture`, the tools use the
    :meth:`trace` context manager). A second ``start`` while a capture
    runs raises :class:`ProfilerBusyError` without touching jax.
    """

    def __init__(self, registry=None):
        self._lock = threading.Lock()
        self._log_dir: str | None = None
        self._t_start = 0.0
        self._registry = registry
        self._seq = 0

    @property
    def active(self) -> bool:
        return self._log_dir is not None

    @property
    def log_dir(self) -> str | None:
        return self._log_dir

    def _counter(self):
        reg = self._registry if self._registry is not None \
            else metrics.default()
        return reg.counter("tts_profile_captures_total",
                           "completed on-demand profiler captures")

    # ------------------------------------------------------------ start/stop

    def start(self, log_dir: str | os.PathLike) -> str:
        """Begin a capture into `log_dir` (created if needed); returns
        the artifact root. Raises ProfilerBusyError when one is already
        running — never corrupts an in-flight capture."""
        import jax

        if not self._lock.acquire(blocking=False):
            raise ProfilerBusyError(
                f"a profiler capture is already running "
                f"(into {self._log_dir!r})")
        log_dir = os.fspath(log_dir)
        try:
            os.makedirs(log_dir, exist_ok=True)
            jax.profiler.start_trace(log_dir)
        except BaseException:
            self._lock.release()
            raise
        self._log_dir = log_dir
        self._t_start = time.monotonic()
        self._seq += 1
        return log_dir

    def stop(self) -> str:
        """End the running capture; returns the artifact root (the
        directory ``load_xla_trace`` parses). Raises RuntimeError when
        no capture is running."""
        import jax

        if self._log_dir is None:
            raise RuntimeError("no profiler capture is running")
        log_dir = self._log_dir
        dur = time.monotonic() - self._t_start
        try:
            jax.profiler.stop_trace()
        finally:
            self._log_dir = None
            self._lock.release()
        tracelog.event("profiler.capture", logdir=log_dir,
                       duration_s=round(dur, 3))
        self._counter().inc()
        return log_dir

    # ------------------------------------------------------------ high level

    @contextlib.contextmanager
    def trace(self, log_dir: str | os.PathLike):
        """Capture around a code block (the tools' idiom: warm up, then
        trace exactly the timed window)."""
        self.start(log_dir)
        try:
            yield
        finally:
            self.stop()

    def capture(self, duration_s: float,
                log_dir: str | os.PathLike) -> str:
        """Timed capture: start, sleep `duration_s` while the workload
        runs in its own threads, stop. Returns the artifact root. The
        capture-on-demand primitive behind ``POST /profile`` — whatever
        the devices execute during the window lands in the trace."""
        self.start(log_dir)
        try:
            time.sleep(max(float(duration_s), 0.0))
        finally:
            log_dir = self.stop()
        return log_dir

    def fresh_dir(self, root: str | os.PathLike) -> str:
        """A unique capture directory under `root` (each capture gets
        its own TensorBoard run dir so artifacts never interleave).
        The directory is CREATED here — reservation, not just a name —
        so two racing callers can never be handed the same path."""
        root = os.fspath(root)
        os.makedirs(root, exist_ok=True)
        stamp = time.strftime("%Y%m%d-%H%M%S")
        base = os.path.join(root, f"capture-{stamp}")
        path, n = base, 0
        while True:
            try:
                os.makedirs(path, exist_ok=False)
                return path
            except FileExistsError:
                n += 1
                path = f"{base}-{n}"


# ------------------------------------------------------- process singleton

_session: ProfilerSession | None = None
_session_lock = threading.Lock()


def session() -> ProfilerSession:
    """THE process-wide profiler session (the jax profiler is global, so
    its guard must be too)."""
    global _session
    with _session_lock:
        if _session is None:
            _session = ProfilerSession()
        return _session


def trace(log_dir: str | os.PathLike):
    """``session().trace(...)`` — the tools' one-liner (replaces the
    deleted ``utils.device_info.trace``)."""
    return session().trace(log_dir)


def capture(duration_s: float, log_dir: str | os.PathLike) -> str:
    """``session().capture(...)`` — timed capture-on-demand."""
    return session().capture(duration_s, log_dir)
