"""Operational health: the SLO/anomaly rules engine over the obs stack.

PRs 3-5 built a deep *recording* stack; nothing in the repo *judged* it
— a wedged submesh or a pruning collapse was only visible to a human
reading Perfetto. This module is the judge: a :class:`HealthMonitor`
evaluates a set of :class:`Rule`\\ s over the live registries and the
server snapshot on a fixed interval (a daemon thread per server, or
on-demand :meth:`HealthMonitor.evaluate_now`), drives each through the
``pending -> firing -> resolved`` alert lifecycle, and publishes every
transition three ways:

- flight-recorder events ``alert.pending`` / ``alert.firing`` /
  ``alert.resolved`` (rule, severity, detail);
- ``tts_alerts{rule,severity}`` gauges (0 = inactive/resolved, 0.5 =
  pending, 1 = firing) plus ``tts_alerts_fired_total{rule}``;
- :meth:`HealthMonitor.alerts_snapshot` — the JSON behind
  ``GET /alerts`` and the ``doctor`` CLI's exit code.

Built-in rule family (:func:`default_rules`; every threshold is an
env-overridable ``TTS_HEALTH_*`` knob, defaults in utils/config.py):

``queue_wait``      windowed p99 of ``tts_queue_wait_seconds`` over the
                    SLO threshold (the admission queue is melting);
``stall``           a RUNNING request's heartbeat age exceeded the
                    limit (wedged submesh / hung dispatch — the live
                    version of the reference's "Still Idle" print);
``pruning_collapse`` a RUNNING request's ``tts_search_pruning_rate``
                    fell to ~zero after enough evaluated children —
                    the search is brute-forcing, the bound is broken;
``mem_headroom``    ``tts_device_bytes_in_use / _limit`` above the
                    fraction — the next pool growth will OOM;
``compile_storm``   fresh unplanned XLA compiles per evaluation
                    interval over the limit — executable reuse has
                    stopped working (shape churn, cache-key
                    regression). Disk-AOT-cache replays, boot pre-warm
                    compiles and chunk-ladder rung pre-readies
                    (``via="ladder"``) do NOT count: a restarted
                    server mass-loading its cache — or a ladder search
                    readying its 2-3 rungs — is the cold-start/
                    adaptive-dispatch machinery working, not a storm;
``audit``           obs/audit recorded a failed node-conservation
                    invariant inside the window (severity critical);
``perf``            a ``perf_sentry --json`` verdict file says FAIL
                    (wire CI's artifact via ``TTS_HEALTH_PERF_JSON``).

The monitor also samples a small history ring per evaluation (queue
depth, busy submeshes, heartbeat age, device bytes, firing count) —
the sparkline feed for ``GET /dashboard`` (obs/dashboard.py).

Everything here is observation-only: rules READ snapshots and
registries, never the engine — search results are bit-identical with
the monitor on or off (pinned in tests/test_health.py).
"""

from __future__ import annotations

import dataclasses
import json
import math
import os
import threading
import time

from ..utils import config as cfg
from . import audit, metrics, tracelog

__all__ = ["Alert", "Rule", "HealthMonitor", "Thresholds",
           "default_rules", "PENDING", "FIRING", "RESOLVED"]

PENDING = "pending"
FIRING = "firing"
RESOLVED = "resolved"

_SEVERITY_ORDER = {"critical": 0, "page": 0, "warn": 1, "info": 2}


@dataclasses.dataclass
class Thresholds:
    """The rule family's knobs; :meth:`from_env` reads TTS_HEALTH_*
    through the config accessors (defaults come from the knob
    registry — one source, lint-checked)."""

    queue_wait_p99_s: float = cfg.HEALTH_QUEUE_WAIT_P99_S_DEFAULT
    stall_s: float = cfg.HEALTH_STALL_S_DEFAULT
    stall_warmup_s: float = cfg.HEALTH_STALL_WARMUP_S_DEFAULT
    mem_frac: float = cfg.HEALTH_MEM_FRAC_DEFAULT
    compile_storm: float = cfg.HEALTH_COMPILE_STORM_DEFAULT
    pruning_min_rate: float = cfg.HEALTH_PRUNING_MIN_RATE_DEFAULT
    pruning_min_nodes: float = cfg.HEALTH_PRUNING_MIN_NODES_DEFAULT
    audit_window_s: float = cfg.HEALTH_AUDIT_WINDOW_S_DEFAULT
    perf_json: str | None = None
    # saturation rule (obs/capacity.py's overall ρ; fires on sustained
    # demand over capacity BEFORE the reactive queue_wait p99 can)
    saturation: float = cfg.HEALTH_SATURATION_DEFAULT
    saturation_for_s: float = cfg.HEALTH_SATURATION_FOR_S_DEFAULT
    # SLO burn-rate rules (durable-store terminal history; see the
    # config module's SLO_* block for the window semantics)
    slo_error_budget: float = cfg.SLO_ERROR_BUDGET_DEFAULT
    slo_latency_target_s: float = cfg.SLO_LATENCY_TARGET_S_DEFAULT
    slo_latency_budget: float = cfg.SLO_LATENCY_BUDGET_DEFAULT
    slo_burn_fast_s: float = cfg.SLO_BURN_FAST_S_DEFAULT
    slo_burn_slow_s: float = cfg.SLO_BURN_SLOW_S_DEFAULT
    slo_burn_threshold: float = cfg.SLO_BURN_THRESHOLD_DEFAULT
    # per-tenant overrides (TTS_HEALTH_TENANT_OVERRIDES, a JSON map
    # tenant -> {field: value}): an overridden tenant is judged by its
    # OWN thresholds in the SLO burn and predictive risk rules, with
    # its own tenant-labeled burn series; every other tenant keeps the
    # flat values above
    tenant_overrides: dict = dataclasses.field(default_factory=dict)

    def for_tenant(self, tenant: str | None) -> "Thresholds":
        """This threshold set with `tenant`'s overrides applied (the
        flat set itself for unknown tenants / unknown fields — a typo'd
        override field degrades, never crashes a rule)."""
        over = self.tenant_overrides.get(tenant or "-")
        if not over:
            return self
        known = {f.name for f in dataclasses.fields(self)
                 if f.name != "tenant_overrides"}
        return dataclasses.replace(self, **{
            k: v for k, v in over.items() if k in known})

    @classmethod
    def from_env(cls) -> "Thresholds":
        raw = cfg.env_str("TTS_HEALTH_TENANT_OVERRIDES")
        overrides: dict = {}
        if raw:
            try:
                parsed = json.loads(raw)
                if isinstance(parsed, dict):
                    overrides = {str(t): dict(o)
                                 for t, o in parsed.items()
                                 if isinstance(o, dict)}
            except (ValueError, TypeError):
                # the repo-wide knob stance: a malformed env value
                # degrades to the default, never takes the process down
                pass
        return cls(
            tenant_overrides=overrides,
            queue_wait_p99_s=cfg.env_float("TTS_HEALTH_QUEUE_WAIT_P99_S"),
            stall_s=cfg.env_float("TTS_HEALTH_STALL_S"),
            stall_warmup_s=cfg.env_float("TTS_HEALTH_STALL_WARMUP_S"),
            mem_frac=cfg.env_float("TTS_HEALTH_MEM_FRAC"),
            compile_storm=cfg.env_float("TTS_HEALTH_COMPILE_STORM"),
            pruning_min_rate=cfg.env_float(
                "TTS_HEALTH_PRUNING_MIN_RATE"),
            pruning_min_nodes=cfg.env_float(
                "TTS_HEALTH_PRUNING_MIN_NODES"),
            audit_window_s=cfg.env_float("TTS_HEALTH_AUDIT_WINDOW_S"),
            perf_json=cfg.env_str("TTS_HEALTH_PERF_JSON"),
            saturation=cfg.env_float("TTS_HEALTH_SATURATION"),
            saturation_for_s=cfg.env_float(
                "TTS_HEALTH_SATURATION_FOR_S"),
            slo_error_budget=cfg.env_float("TTS_SLO_ERROR_BUDGET"),
            slo_latency_target_s=cfg.env_float(
                "TTS_SLO_LATENCY_TARGET_S"),
            slo_latency_budget=cfg.env_float("TTS_SLO_LATENCY_BUDGET"),
            slo_burn_fast_s=cfg.env_float("TTS_SLO_BURN_FAST_S"),
            slo_burn_slow_s=cfg.env_float("TTS_SLO_BURN_SLOW_S"),
            slo_burn_threshold=cfg.env_float("TTS_SLO_BURN_THRESHOLD"))


@dataclasses.dataclass
class Rule:
    """One condition. `check(ctx) -> (active, detail)`; `for_s` is the
    dwell an active condition must hold before pending turns firing
    (0 = fire on first active evaluation)."""

    name: str
    check: object                 # callable(ctx) -> (bool, dict)
    severity: str = "warn"
    for_s: float = 0.0
    description: str = ""


@dataclasses.dataclass
class Alert:
    """Lifecycle record of one rule's alert."""

    rule: str
    severity: str
    state: str = PENDING
    since_unix: float = 0.0        # condition first seen active
    firing_since_unix: float | None = None
    resolved_unix: float | None = None
    fired_count: int = 0           # pending->firing transitions
    detail: dict = dataclasses.field(default_factory=dict)

    def to_json(self) -> dict:
        return dataclasses.asdict(self)


class _Ctx:
    """What a rule sees at evaluation time. `snapshot` is computed at
    most once per evaluation (rules share it)."""

    def __init__(self, monitor: "HealthMonitor", now: float):
        self.monitor = monitor
        self.server = monitor.server
        self.registry = monitor.registry
        self.thresholds = monitor.thresholds
        self.now = now
        self._snapshot = None

    @property
    def snapshot(self) -> dict | None:
        if self._snapshot is None and self.server is not None:
            # duck-typed: rule tests attach bare stubs (a cache-only
            # server has no request table, and that is fine)
            fn = getattr(self.server, "status_snapshot", None)
            if fn is not None:
                self._snapshot = fn()
        return self._snapshot

    def gauge_samples(self, name: str) -> list[tuple[dict, float]]:
        """Every (labels, value) sample of a gauge/counter across the
        monitor's registries."""
        out = []
        for reg in self.monitor.registries:
            for m in reg.metrics():
                if m.name == name and hasattr(m, "samples"):
                    out.extend((dict(k), v) for _, k, v in m.samples())
        return out


# ------------------------------------------------------- built-in rules


def _hist_delta_quantile(prev: dict | None, snap: dict,
                         q: float) -> tuple[float | None, int]:
    """Quantile upper bound over the WINDOW between two cumulative
    histogram snapshots (None when the window saw no observations).
    Returns (quantile, window_count)."""
    n = snap.get("count", 0) - (prev or {}).get("count", 0)
    if n <= 0:
        return None, 0
    prev_b = (prev or {}).get("buckets", {})
    target = q * n
    for key, c in sorted(snap.get("buckets", {}).items(),
                         key=lambda kv: float(kv[0])):
        if c - prev_b.get(key, 0) >= target:
            return float(key), n
    return math.inf, n


def default_rules(thresholds: Thresholds) -> list[Rule]:
    """The built-in rule family (closures hold per-monitor state)."""
    th = thresholds
    state: dict = {"qw_prev": None, "misses_prev": None}

    def queue_wait(ctx):
        srv = ctx.server
        if srv is None or getattr(srv, "metrics", None) is None:
            return False, {}
        h = srv.metrics.histogram("tts_queue_wait_seconds")
        # matching, not exact: the family carries a tenant label, and
        # the flat rule judges the all-tenants window (an unlabeled
        # snapshot() of a labeled family is the empty series)
        snap = h.snapshot_matching()
        p99, n = _hist_delta_quantile(state["qw_prev"], snap, 0.99)
        state["qw_prev"] = snap
        if p99 is None:
            return False, {}
        return p99 > th.queue_wait_p99_s, {
            "p99_s": p99, "window_count": n,
            "threshold_s": th.queue_wait_p99_s}

    def stall(ctx):
        ages = getattr(ctx.server, "heartbeat_ages", lambda: {})()
        if not ages:
            return False, {}
        # a request whose CURRENT dispatch has not heartbeat yet is
        # still warming up: the gap includes XLA trace+compile on an
        # executor-cache miss, which runs to minutes legitimately —
        # judge it against the larger warmup threshold instead of
        # false-firing a critical alert. Per DISPATCH, not per
        # lifetime: a preempted request resuming on a cold submesh
        # pays that compile again, and judging it by its old progress
        # would re-fire stall mid-compile (and, under remediation,
        # ping-pong the request between submeshes). Servers without
        # the dispatch_heartbeats snapshot key (older/duck-typed) fall
        # back to the empty-progress heuristic.
        reqs = (ctx.snapshot or {}).get("requests", {})
        worst = None
        for rid, age in ages.items():
            snap_r = reqs.get(rid) or {}
            if "dispatch_heartbeats" in snap_r:
                warming = not snap_r["dispatch_heartbeats"]
            else:
                warming = not snap_r.get("progress")
            limit = th.stall_warmup_s if warming else th.stall_s
            if age > limit and (worst is None or age > worst[1]):
                worst = (rid, age, limit, warming)
        if worst is None:
            return False, {}
        # the submesh the stall was OBSERVED on rides the detail: a
        # remediation action executing later must not act on a fresh
        # dispatch that already moved elsewhere
        return True, {
            "request_id": worst[0],
            "submesh": (reqs.get(worst[0]) or {}).get("submesh"),
            "heartbeat_age_s": round(worst[1], 3),
            "threshold_s": worst[2], "warming": worst[3]}

    def pruning_collapse(ctx):
        rates = ctx.gauge_samples("tts_search_pruning_rate")
        popped = ctx.gauge_samples("tts_search_popped")
        running = _running_ids(ctx)
        worst = None
        for labels, rate in rates:
            rid = labels.get("request")
            if rid is None or (running is not None
                               and rid not in running):
                continue
            nodes = sum(v for lb, v in popped
                        if lb.get("request") == rid)
            if nodes >= th.pruning_min_nodes \
                    and rate < th.pruning_min_rate:
                if worst is None or rate < worst[1]:
                    worst = (rid, rate, nodes)
        if worst is None:
            return False, {}
        return True, {"request_id": worst[0], "pruning_rate": worst[1],
                      "popped": worst[2],
                      "threshold_rate": th.pruning_min_rate}

    def mem_headroom(ctx):
        use = {tuple(sorted(lb.items())): v
               for lb, v in ctx.gauge_samples("tts_device_bytes_in_use")}
        worst = None
        for lb, limit in ctx.gauge_samples("tts_device_bytes_limit"):
            if limit <= 0:
                continue
            u = use.get(tuple(sorted(lb.items())))
            if u is None:
                continue
            frac = u / limit
            if frac > th.mem_frac and (worst is None
                                       or frac > worst[1]):
                worst = (lb.get("device"), frac, u, limit)
        if worst is None:
            return False, {}
        return True, {"device": worst[0], "frac": round(worst[1], 4),
                      "bytes_in_use": worst[2], "bytes_limit": worst[3],
                      "threshold_frac": th.mem_frac}

    def compile_storm(ctx):
        cache = getattr(ctx.server, "cache", None)
        if cache is None:
            return False, {}
        # count TRUE unplanned fresh compiles (ExecutorCache.
        # storm_signal: disk-AOT-cache replays and operator-requested
        # pre-warm compiles excluded) — a restarted server mass-
        # replaying its executable cache from disk at boot is the
        # cold-start FIX working, not a storm. Duck-typed caches
        # without the signal fall back to the pre-PR-8 miss delta.
        signal_fn = getattr(cache, "storm_signal", None)
        if signal_fn is not None:
            compiles = int(signal_fn())
            kind = "compiles"
        else:
            compiles = cache.snapshot().get("misses", 0)
            kind = "misses"
        prev, state["misses_prev"] = state["misses_prev"], compiles
        if prev is None:
            return False, {}
        delta = compiles - prev
        detail = {f"{kind}_in_interval": delta,
                  f"{kind}_total": compiles,
                  "threshold": th.compile_storm}
        aot = getattr(ctx.server, "aot", None)
        if aot is not None:
            # the plain counter, NOT snapshot(): snapshot lists the
            # cache directory, which can be slow on fleet storage —
            # too heavy for every health-evaluation interval
            detail["aot_cache_hits"] = aot.hits
        return delta >= th.compile_storm, detail

    def audit_rule(ctx):
        fails = audit.recent_failures(th.audit_window_s)
        if not fails:
            return False, {}
        last = fails[-1]
        return True, {"failures_in_window": len(fails),
                      "invariant": last.invariant,
                      "detail": last.detail,
                      "window_s": th.audit_window_s}

    def peer_down(ctx):
        # fleet failover (service/failover.py): the watcher's last scan
        # rides the status snapshot's `failover` key. Any peer whose
        # lease EXPIRED without being released is a down server whose
        # ledger holds orphaned requests — critical whether or not
        # TTS_FAILOVER is armed (observe-only fleets page an operator
        # instead of self-adopting). Duck-typed: non-fleet servers
        # (no watcher, snapshot key absent/None) never fire.
        watcher = getattr(ctx.server, "watcher", None)
        fo = (watcher.snapshot() if watcher is not None
              else (ctx.snapshot or {}).get("failover") or {})
        peers = fo.get("peers") or []
        down = [p for p in peers
                if p.get("expired") and not p.get("released")]
        if not down:
            return False, {}
        worst = max(down, key=lambda p: p.get("age_s") or 0.0)
        return True, {"peers_down": len(down),
                      "dir": worst.get("dir"),
                      "owner": worst.get("owner"),
                      "epoch": worst.get("epoch"),
                      "age_s": worst.get("age_s"),
                      "ttl_s": worst.get("ttl_s"),
                      "mode": fo.get("mode"),
                      "takeovers": fo.get("takeovers")}

    def _burn_windows(ctx, slo: str, bad_fn, tth=None, tenant=None):
        """Multi-window burn rate over the DURABLE store's terminal
        history (obs/store.py): bad_fraction/budget per window, so a
        budget spent across three restarts and a takeover still burns.
        Publishes tts_slo_burn_rate{slo,window} and fires only when
        BOTH windows exceed the threshold — fast alone is a blip, slow
        alone is stale history. No store attached = never active
        (bit-identical to the pre-store rule family). With `tenant`,
        the window narrows to that tenant's terminals, `tth` supplies
        its overridden budget/threshold, and the burn series carries a
        tenant label."""
        store = getattr(ctx.monitor, "store", None)
        if store is None:
            return False, {}
        tth = tth or th
        budget = (tth.slo_error_budget if slo == "error"
                  else tth.slo_latency_budget)
        if budget <= 0:
            return False, {}
        now = time.time()
        rows = store.terminal_history(now - tth.slo_burn_slow_s)
        if tenant is not None:
            rows = [r for r in rows
                    if (r[3] if len(r) > 3 else "-") == tenant]
        burns = {}
        counts = {}
        for window, span in (("fast", tth.slo_burn_fast_s),
                             ("slow", tth.slo_burn_slow_s)):
            in_w = [r for r in rows if r[0] >= now - span]
            bad = sum(1 for r in in_w if bad_fn(r))
            burns[window] = ((bad / len(in_w)) / budget
                             if in_w else 0.0)
            counts[window] = (bad, len(in_w))
        g = ctx.registry.gauge(
            "tts_slo_burn_rate",
            "SLO burn rate (bad_fraction/budget) per window, computed "
            "over the durable store's terminal history")
        extra = {} if tenant is None else {"tenant": tenant}
        for window, burn in burns.items():
            g.set(round(burn, 4), slo=slo, window=window, **extra)
        active = (burns["fast"] > tth.slo_burn_threshold
                  and burns["slow"] > tth.slo_burn_threshold)
        return active, {
            "slo": slo, "budget": budget,
            **({"tenant": tenant} if tenant is not None else {}),
            "burn_fast": round(burns["fast"], 4),
            "burn_slow": round(burns["slow"], 4),
            "bad_fast": counts["fast"][0],
            "total_fast": counts["fast"][1],
            "bad_slow": counts["slow"][0],
            "total_slow": counts["slow"][1],
            "threshold": tth.slo_burn_threshold}

    def _tenant_burns(ctx, slo: str, bad_for) -> list[dict]:
        """The per-tenant half of a burn rule: every overridden tenant
        judged against ITS thresholds over ITS terminals (its own
        tenant-labeled burn series). Returns the active details."""
        fired = []
        for tenant in sorted(th.tenant_overrides):
            tth = th.for_tenant(tenant)
            bad_fn = bad_for(tth)
            if bad_fn is None:
                continue
            active, detail = _burn_windows(ctx, slo, bad_fn,
                                           tth=tth, tenant=tenant)
            if active:
                fired.append(detail)
        return fired

    def slo_error_burn(ctx):
        bad = lambda r: r[1] == "FAILED"  # noqa: E731
        active, detail = _burn_windows(ctx, "error", bad)
        per_tenant = _tenant_burns(ctx, "error", lambda tth: bad)
        if per_tenant:
            detail = {**detail, "tenants": per_tenant}
        return active or bool(per_tenant), detail

    def slo_latency_burn(ctx):
        def bad_for(tth):
            target = tth.slo_latency_target_s
            if target <= 0:
                return None
            return lambda r: r[2] > target
        active = False
        detail: dict = {}
        flat = bad_for(th)
        if flat is not None:
            active, detail = _burn_windows(ctx, "latency", flat)
        per_tenant = _tenant_burns(ctx, "latency", bad_for)
        if per_tenant:
            detail = {**detail, "tenants": per_tenant}
        return active or bool(per_tenant), detail

    def _predicted(r) -> tuple[float, float] | None:
        """(spent_s, predicted_total_s) for one RUNNING request block,
        None without a published ETA (warmup / estimation off)."""
        if r.get("state") != "RUNNING":
            return None
        est = (r.get("progress") or {}).get("estimate") or {}
        eta = est.get("eta_s")
        if eta is None:
            return None
        spent = float(r.get("spent_s") or 0.0)
        return spent, spent + float(eta)

    def deadline_risk(ctx):
        """Predictive: fires BEFORE the deadline miss — a RUNNING
        request whose estimated remaining time plus spent budget
        exceeds its compute deadline, while there is still time to
        preempt, re-tier or raise the budget (the terminal counter
        only moves after the budget is gone)."""
        reqs = (ctx.snapshot or {}).get("requests") or {}
        worst, at_risk = None, 0
        for rid, r in reqs.items():
            d = r.get("deadline_s")
            pred = _predicted(r)
            if d is None or pred is None:
                continue
            spent, predicted = pred
            over = predicted - float(d)
            if over <= 0:
                continue
            at_risk += 1
            if worst is None or over > worst["over_s"]:
                worst = {"request": rid, "tenant": r.get("tenant"),
                         "deadline_s": d,
                         "spent_s": round(spent, 1),
                         "predicted_total_s": round(predicted, 1),
                         "over_s": round(over, 1)}
        if worst is None:
            return False, {}
        return True, {**worst, "at_risk": at_risk}

    def slo_latency_risk(ctx):
        """The latency SLO's predictive twin: a RUNNING request whose
        predicted total latency (spent + ETA) exceeds its TENANT's
        latency target will land as an SLO violation at its terminal —
        fire while it can still be helped. Overridden tenants are
        judged by their own target (Thresholds.for_tenant)."""
        reqs = (ctx.snapshot or {}).get("requests") or {}
        worst, at_risk = None, 0
        for rid, r in reqs.items():
            tenant = r.get("tenant") or "-"
            target = th.for_tenant(tenant).slo_latency_target_s
            pred = _predicted(r)
            if target <= 0 or pred is None:
                continue
            spent, predicted = pred
            over = predicted - target
            if over <= 0:
                continue
            at_risk += 1
            if worst is None or over > worst["over_s"]:
                worst = {"request": rid, "tenant": tenant,
                         "target_s": target,
                         "spent_s": round(spent, 1),
                         "predicted_total_s": round(predicted, 1),
                         "over_s": round(over, 1)}
        if worst is None:
            return False, {}
        return True, {**worst, "at_risk": at_risk}

    def saturation(ctx):
        """Sustained demand over capacity (obs/capacity's overall ρ) —
        the forecast that fires BEFORE the reactive queue_wait p99 can:
        ρ moves with admissions and measured service rates, while the
        p99 needs a window of already-late dispatches to breach. Reads
        the shared snapshot, so the health cadence also drives the
        tts_capacity_* gauge refresh."""
        cap = (ctx.snapshot or {}).get("capacity")
        if not cap:
            return False, {}
        rho = cap.get("utilization")
        if rho is None:        # no terminal yet: demand unmeasurable
            return False, {}
        if rho <= th.saturation:
            return False, {}
        worst = None
        for row in cap.get("classes") or []:
            u = row.get("utilization")
            if u is not None and (worst is None
                                  or u > worst["utilization"]):
                worst = row
        detail = {"utilization": round(rho, 4),
                  "threshold": th.saturation,
                  "arrival_per_s": round(cap.get("arrival_per_s", 0.0),
                                         4),
                  "healthy_lanes": cap.get("healthy_lanes")}
        if cap.get("predicted_wait_s") is not None:
            detail["predicted_wait_s"] = round(
                cap["predicted_wait_s"], 3)
        if worst is not None:
            detail["worst_class"] = (f"{worst['shape']}/"
                                     f"{worst['tenant']}")
        return True, detail

    def perf(ctx):
        path = th.perf_json
        if not path or not os.path.exists(path):
            return False, {}
        try:
            with open(path) as f:
                verdict = json.load(f)
        except (OSError, json.JSONDecodeError) as e:
            return True, {"path": path, "error": repr(e)}
        if verdict.get("verdict") != "FAIL":
            return False, {}
        return True, {"path": path, "round": verdict.get("round"),
                      "n_fail": verdict.get("n_fail"),
                      "reasons": verdict.get("reasons", [])[:4]}

    return [
        Rule("queue_wait", queue_wait, severity="warn",
             description="queue-wait p99 over the SLO threshold"),
        Rule("stall", stall, severity="critical",
             description="RUNNING request heartbeat age over the limit "
                         "(wedged submesh / hung dispatch)"),
        Rule("pruning_collapse", pruning_collapse, severity="warn",
             description="search pruning rate collapsed to ~zero"),
        Rule("mem_headroom", mem_headroom, severity="critical",
             description="device memory in-use/limit over the fraction"),
        Rule("compile_storm", compile_storm, severity="warn",
             description="fresh unplanned compiles per interval over "
                         "the limit (executable reuse broken; disk-"
                         "cache replays, pre-warm and ladder-rung "
                         "warms excluded)"),
        Rule("audit", audit_rule, severity="critical",
             description="a node-conservation invariant failed "
                         "(obs/audit.py)"),
        Rule("perf", perf, severity="warn",
             description="perf_sentry --json verdict is FAIL"),
        Rule("peer_down", peer_down, severity="critical",
             description="a fleet peer's ledger lease expired without "
                         "release (host down, requests orphaned; "
                         "observe-only fleets need an operator)"),
        Rule("slo_error_burn", slo_error_burn, severity="critical",
             description="error-budget burn over threshold in BOTH the "
                         "fast and slow window (durable history — "
                         "survives restarts and takeovers)"),
        Rule("slo_latency_burn", slo_latency_burn, severity="warn",
             description="latency-budget burn over threshold in both "
                         "windows (spent_s over the target counts "
                         "against the budget)"),
    ] + ([
        # exists only while the capacity layer is on: with
        # TTS_CAPACITY=0 the rule LIST itself is the pre-capacity one
        # (the /alerts rules block stays bit-identical). Sits BEFORE
        # the progress pair — their end-of-list position is pinned.
        Rule("saturation", saturation, severity="warn",
             for_s=th.saturation_for_s,
             description="sustained shape-class demand over healthy-"
                         "lane capacity (predictive — fires before the "
                         "queue_wait p99 breaches)"),
    ] if cfg.env_flag("TTS_CAPACITY") else []) + ([
        # the predictive pair exists only while progress estimation is
        # on: with TTS_PROGRESS=0 the rule LIST itself is the pre-
        # estimator one (the /alerts rules block stays bit-identical)
        Rule("deadline_risk", deadline_risk, severity="warn",
             description="a RUNNING request's spent + estimated "
                         "remaining time exceeds its compute deadline "
                         "(predictive — fires before the miss)"),
        Rule("slo_latency_risk", slo_latency_risk, severity="warn",
             description="a RUNNING request's predicted total latency "
                         "exceeds its tenant's latency target "
                         "(predictive; per-tenant thresholds)"),
    ] if cfg.env_flag("TTS_PROGRESS") else [])


def _running_ids(ctx) -> set | None:
    snap = ctx.snapshot
    if snap is None:
        return None
    return {rid for rid, r in snap.get("requests", {}).items()
            if r.get("state") == "RUNNING"}


# ----------------------------------------------------------- the monitor


class HealthMonitor:
    """Evaluates rules on an interval and owns the alert lifecycle.

    `server` is duck-typed (anything with ``status_snapshot()``,
    optionally ``heartbeat_ages()``, ``cache``, ``queue``, ``slots``);
    None evaluates the registry-only rules. `registry` is where the
    ``tts_alerts`` gauges land (the server's own registry on a serve
    session, so ``/metrics`` carries them); rules read from `registry`
    AND the process-global default (engine metrics live there).
    `interval_s <= 0` disables the daemon — :meth:`evaluate_now` still
    works on demand (the doctor/test path).
    """

    HISTORY = 360        # evaluations kept per history series

    def __init__(self, server=None, registry=None,
                 rules: list[Rule] | None = None,
                 thresholds: Thresholds | None = None,
                 interval_s: float | None = None,
                 autostart: bool = True, store=None):
        # the durable obs store (obs/store.py) the slo_* burn rules
        # window over; None (default) keeps the rule family exactly
        # process-scoped. The server assigns it post-construction too
        # (store wiring happens after the monitor exists).
        self.store = store
        self.server = server
        self.registry = registry if registry is not None \
            else metrics.default()
        self.thresholds = thresholds or Thresholds.from_env()
        self.rules = (rules if rules is not None
                      else default_rules(self.thresholds))
        if interval_s is None:
            interval_s = cfg.env_float("TTS_HEALTH_INTERVAL_S")
        self.interval_s = float(interval_s)
        self.alerts: dict[str, Alert] = {}    # guarded-by: self._lock
        self.history: dict[str, list] = {}    # guarded-by: self._lock
        # alert-transition subscribers (the remediation controller's
        # trigger feed): fn(rule_name, transition, alert_json) called
        # AFTER the evaluation sweep releases the lock — a listener may
        # take server/controller locks of its own without ordering
        # against this monitor's
        self.listeners: list = []             # guarded-by: self._lock
        self._g_alerts = self.registry.gauge(
            "tts_alerts",
            "alert state by rule (0 inactive, 0.5 pending, 1 firing)")
        self._c_fired = self.registry.counter(
            "tts_alerts_fired_total", "pending->firing transitions")
        self._c_evals = self.registry.counter(
            "tts_health_evaluations_total", "health rule sweeps")
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None  # guarded-by: self._lock
        self.evaluations = 0     # guarded-by: self._lock
        if autostart and self.interval_s > 0:
            self.start()

    @property
    def registries(self) -> list:
        regs = [self.registry]
        dflt = metrics.default()
        if dflt is not self.registry:
            regs.append(dflt)
        return regs

    # --------------------------------------------------------- lifecycle

    def start(self) -> None:
        with self._lock:
            if self._thread is not None or self.interval_s <= 0:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True, name="tts-health")
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            try:
                self.evaluate_now()
            except Exception:  # noqa: BLE001 — the judge must not die
                pass           # on a snapshot racing server shutdown

    def stop(self) -> None:
        self._stop.set()
        th = self._thread
        if th is not None:
            # join OUTSIDE the lock: the daemon may be mid-evaluate_now
            # (which holds it); taking the lock before the join would
            # deadlock a stop() racing an evaluation sweep
            th.join(timeout=5)
        with self._lock:
            self._thread = None

    def close(self) -> None:
        self.stop()
        # retire the alert gauges: a closed server must not keep
        # publishing rule series (same valve as the resource sampler)
        self.registry.remove_matching("tts_alerts")
        self.registry.remove_matching("tts_slo_burn_rate")

    # --------------------------------------------------------- durability

    def seed_history(self, samples: list[dict]) -> int:
        """Refill the history rings from replayed obs-store ``sample``
        records (boot resume): each record's ``history`` dict maps ring
        name -> value at wall time ``t``. Rows older than what the ring
        would have seen are kept anyway — the rings are bounded at
        HISTORY either way. Returns rows seeded."""
        seeded = 0
        with self._lock:
            for rec in samples:
                hist = rec.get("history")
                t = rec.get("t")
                if not isinstance(hist, dict) or t is None:
                    continue
                for name, value in hist.items():
                    if value is None:
                        continue
                    ring = self.history.setdefault(name, [])
                    ring.append((round(float(t), 3), value))
                    seeded += 1
            for ring in self.history.values():
                ring.sort(key=lambda row: row[0])
                del ring[:-self.HISTORY]
        return seeded

    def history_sample(self) -> dict:
        """The CURRENT history-ring signals as one dict — what the obs
        store persists per sample record (the inverse of
        :meth:`seed_history`)."""
        with self._lock:
            return {name: ring[-1][1]
                    for name, ring in self.history.items() if ring}

    # -------------------------------------------------------- evaluation

    def add_listener(self, fn) -> None:
        """Subscribe to alert transitions: ``fn(rule_name, transition,
        alert_json)`` with transition in {"pending", "firing",
        "resolved"}. Called outside the monitor's lock, after each
        sweep; a raising listener is recorded and dropped from that
        sweep's fan-out, never a monitor crash."""
        with self._lock:
            self.listeners.append(fn)

    def evaluate_now(self) -> dict:
        """One sweep: run every rule, advance lifecycles, publish, and
        append the history sample. Returns `alerts_snapshot()`."""
        now = time.time()
        ctx = _Ctx(self, now)
        transitions: list[tuple[str, str, dict]] = []
        with self._lock:
            self.evaluations += 1
            self._c_evals.inc()
            for rule in self.rules:
                try:
                    active, detail = rule.check(ctx)
                except Exception as e:  # noqa: BLE001 — a broken rule is
                    # a finding about the rule, never a monitor crash
                    tracelog.event("alert.rule_error", rule=rule.name,
                                   error=repr(e))
                    continue
                self._advance(rule, bool(active), detail or {}, now,
                              transitions)
            self._sample_history(ctx, now)
            listeners = list(self.listeners)
        # fan transitions out OUTSIDE the lock: a listener (the
        # remediation controller) takes server locks of its own, and a
        # lock-ordering edge monitor->server would deadlock against the
        # server's own snapshot calls into this monitor
        for rule_name, transition, alert_json in transitions:
            for fn in listeners:
                try:
                    fn(rule_name, transition, alert_json)
                except Exception as e:  # noqa: BLE001 — observer tier
                    tracelog.event("alert.listener_error",
                                   rule=rule_name, error=repr(e))
        return self.alerts_snapshot()

    def _advance(self, rule: Rule, active: bool, detail: dict,
                 now: float, transitions: list | None = None
                 ) -> None:    # holds: self._lock
        def note(state: str, a: Alert) -> None:
            if transitions is not None:
                transitions.append((rule.name, state, a.to_json()))

        a = self.alerts.get(rule.name)
        labels = {"rule": rule.name, "severity": rule.severity}
        if active:
            if a is None or a.state == RESOLVED:
                a = Alert(rule=rule.name, severity=rule.severity,
                          state=PENDING, since_unix=now, detail=detail,
                          fired_count=a.fired_count if a else 0)
                self.alerts[rule.name] = a
                tracelog.event("alert.pending", **labels, **detail)
                self._g_alerts.set(0.5, **labels)
                note(PENDING, a)
            a.detail = detail
            if a.state == PENDING and now - a.since_unix >= rule.for_s:
                a.state = FIRING
                a.firing_since_unix = now
                a.fired_count += 1
                self._c_fired.inc(rule=rule.name)
                tracelog.event("alert.firing", **labels, **detail)
                self._g_alerts.set(1.0, **labels)
                note(FIRING, a)
        elif a is not None and a.state != RESOLVED:
            was_firing = a.state == FIRING
            a.state = RESOLVED
            a.resolved_unix = now
            self._g_alerts.set(0.0, **labels)
            if was_firing:
                tracelog.event("alert.resolved", **labels,
                               firing_s=round(
                                   now - (a.firing_since_unix or now),
                                   3))
                note(RESOLVED, a)
            # an unconfirmed pending that cleared is not an incident:
            # no resolved event, and the record drops so /alerts shows
            # only confirmed history
            elif a.fired_count == 0:
                del self.alerts[rule.name]

    def _sample_history(self, ctx: _Ctx, now: float) -> None:
        # holds: self._lock
        def push(name, value):
            if value is None:
                return
            ring = self.history.setdefault(name, [])
            ring.append((round(now, 3), value))
            del ring[:-self.HISTORY]

        srv = self.server
        if srv is not None:
            if getattr(srv, "queue", None) is not None:
                push("queue_depth", len(srv.queue))
            slots = getattr(srv, "slots", None)
            if slots is not None:
                push("submeshes_busy",
                     sum(1 for s in slots if s.record is not None))
            ages = getattr(srv, "heartbeat_ages", lambda: {})()
            push("heartbeat_age_max_s",
                 round(max(ages.values()), 3) if ages else 0.0)
            # mean published progress over RUNNING requests (the
            # dashboard's progress sparkline). Data-driven: with the
            # estimator off no request ever carries an estimate, so the
            # ring never exists — history output stays bit-identical
            vals = [
                ((r.get("progress") or {}).get("estimate") or {})
                .get("progress_ratio")
                for r in ((ctx.snapshot or {}).get("requests") or {})
                .values() if r.get("state") == "RUNNING"]
            vals = [v for v in vals if v is not None]
            if vals:
                push("progress_mean",
                     round(sum(vals) / len(vals), 4))
            # overall ρ + mean lane-executing fraction (the dashboard's
            # utilization sparklines). Data-driven like progress_mean:
            # with the capacity layer off the snapshot never carries
            # the key, so the rings never exist — bit-identical history
            cap = (ctx.snapshot or {}).get("capacity")
            if cap:
                rho = cap.get("utilization")
                if rho is not None:
                    push("capacity_utilization", round(rho, 4))
                lanes = cap.get("lanes_detail") or []
                if lanes:
                    push("lane_executing_frac", round(
                        sum(r.get("utilization", 0.0) for r in lanes)
                        / len(lanes), 4))
        use = ctx.gauge_samples("tts_device_bytes_in_use")
        if use:
            push("device_bytes_in_use", sum(v for _, v in use))
        rss = ctx.gauge_samples("tts_host_rss_bytes")
        if rss:
            push("host_rss_bytes", rss[0][1])
        push("alerts_firing",
             sum(1 for a in self.alerts.values() if a.state == FIRING))

    # -------------------------------------------------------------- read

    def firing(self) -> list[Alert]:
        with self._lock:
            return sorted(
                (a for a in self.alerts.values() if a.state == FIRING),
                key=lambda a: _SEVERITY_ORDER.get(a.severity, 9))

    def alerts_snapshot(self) -> dict:
        """JSON behind GET /alerts (and the doctor verdict)."""
        with self._lock:
            alerts = sorted(
                self.alerts.values(),
                key=lambda a: (a.state != FIRING,
                               _SEVERITY_ORDER.get(a.severity, 9),
                               a.rule))
            return {
                "t": time.time(),
                "interval_s": self.interval_s,
                "evaluations": self.evaluations,
                "firing": sum(1 for a in alerts if a.state == FIRING),
                "rules": [{"name": r.name, "severity": r.severity,
                           "description": r.description}
                          for r in self.rules],
                "alerts": [a.to_json() for a in alerts],
            }
