"""Fleet aggregation: scrape N servers' observability into one view.

One SearchServer exposes ``/healthz`` ``/metrics`` ``/status``
``/alerts``; a pod runs many. This module is the control-plane
groundwork for the multi-host arc (ROADMAP item 1): scrape every
server, label everything by its origin, and merge into a single fleet
snapshot the ``doctor`` CLI judges and ``obs/dashboard.py`` renders.
Stdlib only (``urllib``) — the aggregator must run anywhere a shell
does, including the CI doctor-smoke leg.

The pieces:

- :func:`parse_prometheus` — text exposition -> ``(name, labels,
  value)`` samples (the inverse of metrics.Registry.to_prometheus,
  enough of the format for our own output);
- :func:`scrape_one` / :func:`scrape` — fetch one/many servers'
  endpoints; a down server becomes ``ok: False`` with the error, never
  an exception (a fleet view that dies when one member does is
  useless exactly when it is needed);
- :func:`merge` — one fleet dict: per-server verdict rows, all
  requests and firing alerts with an ``origin`` field, and every
  metric sample re-labeled ``{origin="host:port"}``;
- :func:`fleet_to_prometheus` — the merged samples back out as text
  exposition (feed a real Prometheus one aggregated target);
- :func:`verdict` — the doctor's judgment: healthy iff every server
  scraped, answered healthz 200, and has zero firing alerts.
"""

from __future__ import annotations

import json
import time
import urllib.error
import urllib.request

__all__ = ["parse_prometheus", "scrape_one", "scrape", "merge",
           "fleet_to_prometheus", "verdict", "recovered_live",
           "fleet_lease_report", "needs_takeover"]


def recovered_live(ledger: dict | None) -> int:
    """LIVE work brought back by a ledger replay (queued/active/held).
    Replayed terminal snapshots are idempotency bookkeeping, not
    recovered requests — counting them would make a routine restart
    read as thousands recovered. THE definition for the doctor column
    and the dashboard tile (obs/dashboard), so the two cannot drift."""
    return sum(v for k, v in ((ledger or {}).get("recovered")
                              or {}).items() if k != "terminal")


def parse_prometheus(text: str) -> list[tuple[str, dict, float]]:
    """Parse text exposition into (name, labels, value) samples.
    Comment/blank lines skip; unparseable lines skip (a scraper must
    not die on one odd sample)."""
    out = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln or ln.startswith("#"):
            continue
        try:
            body, _, val = ln.rpartition(" ")
            if "{" in body:
                name, _, rest = body.partition("{")
                labels = {}
                for pair in _split_labels(rest.rstrip("}")):
                    k, _, v = pair.partition("=")
                    labels[k.strip()] = v.strip().strip('"')
            else:
                name, labels = body, {}
            out.append((name.strip(), labels,
                        float("inf") if val == "+Inf" else float(val)))
        except ValueError:
            continue
    return out


def _split_labels(s: str) -> list[str]:
    """Split `a="x",b="y,z"` on commas outside quotes."""
    parts, buf, in_q = [], [], False
    for ch in s:
        if ch == '"':
            in_q = not in_q
        if ch == "," and not in_q:
            parts.append("".join(buf))
            buf = []
        else:
            buf.append(ch)
    if buf:
        parts.append("".join(buf))
    return [p for p in parts if p.strip()]


# transient-scrape retry budget: a fleet doctor run races server boots
# and GC pauses; one refused connect must not mark a live peer DOWN.
# Bounded backoff 0.1 * 2^k keeps the worst case well under a second.
SCRAPE_RETRIES = 3
SCRAPE_BACKOFF_S = 0.1


def _get(url: str, timeout: float, retries: int = SCRAPE_RETRIES):
    for attempt in range(retries):
        try:
            with urllib.request.urlopen(url, timeout=timeout) as r:
                return r.status, r.read().decode()
        except urllib.error.HTTPError:
            # the server ANSWERED — a non-2xx is a health fact for the
            # caller to judge, not a flake to retry
            raise
        except OSError:
            if attempt == retries - 1:
                raise
            time.sleep(SCRAPE_BACKOFF_S * (2 ** attempt))
    raise AssertionError("unreachable")  # pragma: no cover


def scrape_one(url: str, timeout: float = 5.0) -> dict:
    """Scrape one server's /healthz /status /metrics /alerts. `url` is
    the base (http://host:port). Never raises: an unreachable server
    returns ``ok: False`` with the error string."""
    url = url.rstrip("/")
    origin = url.split("://", 1)[-1]
    out = {"origin": origin, "url": url, "ok": True, "error": None,
           "healthz": None, "status": None, "alerts": None,
           "metrics": []}
    try:
        code, body = _get(url + "/healthz", timeout)
        out["healthz"] = {"code": code, **json.loads(body)}
    except urllib.error.HTTPError as e:
        # a draining server answers 503 — that is a health FACT, not a
        # scrape failure
        try:
            out["healthz"] = {"code": e.code, **json.loads(e.read())}
        except (ValueError, OSError):
            out["healthz"] = {"code": e.code}
    except (OSError, ValueError) as e:
        out.update(ok=False, error=f"healthz: {e}")
        return out
    for key, path, parse in (("status", "/status", json.loads),
                             ("alerts", "/alerts", json.loads),
                             ("metrics", "/metrics", parse_prometheus)):
        try:
            code, body = _get(url + path, timeout)
            out[key] = parse(body)
        except (OSError, ValueError) as e:
            # /alerts may not exist on an older server; only the core
            # endpoints are load-bearing for the fleet view
            if key == "alerts":
                out[key] = None
            else:
                out.update(ok=False, error=f"{path}: {e}")
                return out
    return out


def scrape(urls: list[str], timeout: float = 5.0) -> dict:
    """Scrape every server; returns {"t", "servers": [scrape_one...]}"""
    return {"t": time.time(),
            "servers": [scrape_one(u, timeout=timeout) for u in urls]}


def merge(fleet: dict) -> dict:
    """Fold a `scrape()` result into one fleet view (see module doc)."""
    servers, requests, alerts, samples = [], [], [], []
    for s in fleet["servers"]:
        origin = s["origin"]
        row = {"origin": origin, "ok": s["ok"], "error": s["error"],
               "healthz": (s["healthz"] or {}).get("status"),
               "firing": None, "queue_depth": None, "submeshes": None,
               "submeshes_busy": None, "requests": 0, "uptime_s": None,
               "aot_cache": None, "quarantined": 0,
               "admission_paused": None,
               # crash-safe serving (service/ledger): None on a server
               # running without a ledger
               "restarts": None, "recovered_requests": None,
               "ledger_lag_s": None,
               # fleet failover (service/failover): None outside fleet
               # mode (snapshot parity with a PR-12 server)
               "fenced": None, "lease_epoch": None,
               "failover_mode": None, "peers_down": None,
               "takeovers": None,
               # bound-portfolio racing (service/portfolio): None on a
               # server that never raced (snapshot parity)
               "portfolio": None,
               # progress/ETA estimation (obs/estimate): None when no
               # request carries a published estimate (warmup or
               # TTS_PROGRESS=0 — snapshot parity)
               "progress_mean": None, "eta_max_s": None,
               # capacity model (obs/capacity): overall ρ and headroom;
               # None with TTS_CAPACITY=0 or before the model has a
               # service-time estimate (snapshot parity)
               "utilization": None, "capacity_headroom": None}
        st = s.get("status")
        if st:
            row["uptime_s"] = st.get("uptime_s")
            row["queue_depth"] = (st.get("queue") or {}).get("depth")
            subs = st.get("submeshes") or []
            row["submeshes"] = len(subs)
            row["submeshes_busy"] = sum(
                1 for m in subs if m.get("running"))
            # the zero-compile cold-start tier's stats (None when the
            # server runs without a disk AOT cache) — the doctor
            # surfaces them per server
            row["aot_cache"] = st.get("aot_cache")
            # the self-healing tier's degraded-configuration facts:
            # active submesh quarantines and a paused admission valve
            # (service/remediate) — the doctor's degraded verdict input
            rem = st.get("remediation") or {}
            row["quarantined"] = len(rem.get("quarantined") or [])
            row["admission_paused"] = rem.get("admission_paused")
            # the durable-ledger facts: restart count, requests this
            # lifetime recovered by replay, and journal staleness —
            # the doctor's crash-recovery columns
            led = st.get("ledger")
            if led:
                row["restarts"] = led.get("restarts")
                row["recovered_requests"] = recovered_live(led)
                row["ledger_lag_s"] = led.get("lag_s")
            # the fleet-failover facts: fencing state, lease epoch,
            # watcher mode and how many peers look down from HERE —
            # the doctor's failover columns
            fo = st.get("failover")
            if fo:
                row["fenced"] = fo.get("fenced")
                row["lease_epoch"] = (fo.get("lease") or {}).get("epoch")
                row["failover_mode"] = fo.get("mode")
                row["takeovers"] = fo.get("takeovers")
                peers = fo.get("peers")
                if peers is not None:
                    row["peers_down"] = sum(
                        1 for p in peers
                        if p.get("expired") and not p.get("released"))
            # the portfolio-racing totals (service/portfolio): active/
            # won races and members cancelled at first proof — the
            # doctor's portfolio column; per-race winner configs ride
            # each parent request snapshot's `portfolio` block below
            row["portfolio"] = st.get("portfolio")
            # the capacity columns: demand over healthy-lane capacity
            # and what is left — the doctor's saturation forecast input
            cap = st.get("capacity")
            if cap:
                row["utilization"] = cap.get("utilization")
                row["capacity_headroom"] = cap.get("headroom")
            reqs = st.get("requests") or {}
            row["requests"] = len(reqs)
            # the predictive columns: mean published progress over the
            # server's RUNNING requests, and the LONGEST ETA (when this
            # server expects to finish its current work)
            progs, etas = [], []
            for rid, snap in reqs.items():
                requests.append({"origin": origin, **snap})
                if snap.get("state") != "RUNNING":
                    continue
                est = ((snap.get("progress") or {})
                       .get("estimate") or {})
                if est.get("progress_ratio") is not None:
                    progs.append(float(est["progress_ratio"]))
                if est.get("eta_s") is not None:
                    etas.append(float(est["eta_s"]))
            if progs:
                row["progress_mean"] = round(sum(progs) / len(progs), 4)
            if etas:
                row["eta_max_s"] = round(max(etas), 1)
        al = s.get("alerts")
        if al is not None:
            row["firing"] = al.get("firing", 0)
            for a in al.get("alerts", []):
                alerts.append({"origin": origin, **a})
        for name, labels, value in s.get("metrics") or []:
            samples.append((name, {**labels, "origin": origin}, value))
        servers.append(row)
    firing = [a for a in alerts if a.get("state") == "firing"]
    return {"t": fleet["t"], "servers": servers, "requests": requests,
            "alerts": alerts, "firing": len(firing),
            "metrics": samples}


def fleet_to_prometheus(merged: dict) -> str:
    """Re-render the merged samples as text exposition (origin-labeled;
    types are lost in the roundtrip, so everything exports untyped)."""
    lines = []
    for name, labels, value in merged["metrics"]:
        inner = ",".join(f'{k}="{v}"'
                         for k, v in sorted(labels.items()))
        v = "+Inf" if value == float("inf") else (
            str(int(value)) if float(value).is_integer() else repr(value))
        lines.append(f"{name}{{{inner}}} {v}")
    return "\n".join(lines) + "\n"


def fleet_lease_report(fleet_dir) -> list[dict]:
    """Every peer's lease read straight off the shared fleet root — no
    HTTP, so it works exactly when scraping does not: a DOWN server
    cannot answer /status, but its lease file says whether it is
    DOWN-with-lease-held (alive-ish or freshly dead: wait out the TTL)
    or DOWN-lease-expired (requests orphaned: takeover needed, doctor
    exit code 2). Lazily imports the service lease module; [] when the
    dir is empty/unreadable."""
    import pathlib

    from ..service import lease as lease_mod
    rows = []
    try:
        subdirs = sorted(p for p in pathlib.Path(fleet_dir).iterdir()
                         if p.is_dir())
    except OSError:
        return rows
    for d in subdirs:
        info = lease_mod.read_lease(d)
        if info is None:
            continue
        rows.append({"dir": str(d), "owner": info.owner,
                     "epoch": info.epoch,
                     "age_s": round(info.age_s(), 3),
                     "ttl_s": info.ttl_s,
                     "released": info.released,
                     "expired": info.expired()})
    return rows


def needs_takeover(lease_report: list[dict]) -> list[dict]:
    """The rows of a :func:`fleet_lease_report` that demand action:
    expired WITHOUT release = a dead owner's orphaned ledger. THE
    definition behind doctor exit code 2, so the CLI and tests cannot
    drift."""
    return [r for r in lease_report
            if r.get("expired") and not r.get("released")]


def verdict(merged: dict,
            lease_report: list[dict] | None = None) -> tuple[bool,
                                                             list[str]]:
    """The doctor's judgment: (healthy, reasons). Healthy iff every
    server scraped, healthz says ok, zero alerts are firing, and no
    server is serving in a degraded (quarantined-submesh)
    configuration — a fleet routing around a held-out submesh works,
    but it is running on reduced capacity and a human should know.

    With a `lease_report` (doctor --fleet-dir), DOWN servers split two
    ways: an expired unreleased lease is DOWN-lease-expired (orphaned
    requests, takeover needed — exit code 2 via
    :func:`needs_takeover`); an unreachable server while every lease
    is still live is DOWN-with-lease-held (restarting or paused: wait
    out the TTL before any takeover)."""
    reasons = []
    if lease_report:
        expired = needs_takeover(lease_report)
        for r in expired:
            reasons.append(
                f"{r['dir']}: DOWN-lease-expired — owner {r['owner']} "
                f"epoch {r['epoch']} silent {r['age_s']}s > ttl "
                f"{r['ttl_s']}s; requests orphaned (takeover needed)")
        held = [r for r in lease_report
                if not r.get("expired") and not r.get("released")]
        if held and not expired \
                and any(not s["ok"] for s in merged["servers"]):
            reasons.append(
                f"fleet: unreachable server(s) but {len(held)} "
                "lease(s) still live — DOWN-with-lease-held: owner may "
                "be restarting; wait out the TTL before takeover")
    for s in merged["servers"]:
        if not s["ok"]:
            reasons.append(f"{s['origin']}: unreachable ({s['error']})")
        elif s["healthz"] not in ("ok",):
            reasons.append(f"{s['origin']}: healthz={s['healthz']!r}")
        if s.get("firing"):
            reasons.append(f"{s['origin']}: {s['firing']} firing "
                           "alert(s)")
        if s.get("quarantined"):
            reasons.append(
                f"{s['origin']}: DEGRADED — {s['quarantined']} "
                f"submesh(es) quarantined of {s.get('submeshes')}")
    for a in merged["alerts"]:
        if a.get("state") == "firing":
            reasons.append(
                f"{a['origin']}: [{a.get('severity')}] {a.get('rule')} "
                f"{json.dumps(a.get('detail', {}), sort_keys=True)}")
    return (not reasons), reasons
