"""The metric-name registry: every ``tts_*`` series the stack emits.

ONE checked-in table for every metric name that can appear on
``/metrics`` — the registry the static analyzer
(``tpu_tree_search/analysis/metric_registry.py``, via
``tools/tts_lint.py``) reconciles against the actual emit sites, so a
renamed counter cannot silently orphan a health rule, a dashboard
query, or a README row (the README "Metric registry" table is GENERATED
from this dict by ``tools/tts_lint.py --write-docs``).

Rules enforced by the lint:

- every literal ``tts_*`` name at a ``counter()`` / ``gauge()`` /
  ``histogram()`` call (emit site) or a ``gauge_samples()`` /
  ``remove_matching()`` call (reference site) must have a row here;
- every row here must have at least one emit site inside
  ``tpu_tree_search/`` (no dead registry rows).

Keep imports stdlib-only: the lint leg loads this module without the
accelerator stack.
"""

from __future__ import annotations

import dataclasses

__all__ = ["Metric", "REGISTRY"]


@dataclasses.dataclass(frozen=True)
class Metric:
    name: str
    kind: str       # "counter" | "gauge" | "histogram"
    labels: str     # comma list of label keys, "" when unlabeled
    doc: str        # one line; lands in the generated README table


def _table(*rows: Metric) -> dict:
    out = {}
    for m in rows:
        if m.name in out:
            raise ValueError(f"duplicate metric {m.name}")
        out[m.name] = m
    return out


REGISTRY: dict[str, Metric] = _table(
    # --- service: requests and queueing
    Metric("tts_requests_submitted_total", "counter", "", "admissions"),
    Metric("tts_requests_total", "counter", "state,tenant",
           "terminal states (done/cancelled/deadline/failed) by "
           "accounting tenant ('-' = unattributed)"),
    Metric("tts_preemptions_total", "counter", "",
           "higher-priority preemptions (checkpoint + requeue)"),
    Metric("tts_redispatches_total", "counter", "",
           "re-dispatches after a submesh failure"),
    Metric("tts_request_spent_seconds", "histogram", "",
           "per-request accumulated execution time"),
    Metric("tts_queue_wait_seconds", "histogram", "tenant",
           "admission-to-dispatch wait by accounting tenant (under "
           "megabatching: observed at batch-close, so held batch "
           "members are counted)"),
    # --- request megabatching (engine/megabatch + the batch-former)
    Metric("tts_batches_formed_total", "counter", "reason",
           "batches closed by the former (reason=size|age)"),
    Metric("tts_batch_size", "histogram", "",
           "requests per closed batch"),
    Metric("tts_batch_requests_total", "counter", "",
           "requests dispatched through a multi-request batch"),
    Metric("tts_batch_drain_idle_seconds", "histogram", "",
           "per closed megabatch: lane-seconds members sat frozen "
           "waiting for batchmates to drain (the continuous-batching "
           "motivation number)"),
    # --- bound-portfolio racing (service/portfolio)
    Metric("tts_portfolio_races_total", "counter", "outcome",
           "portfolio races by outcome (won/deadline/cancelled/"
           "failed)"),
    Metric("tts_portfolio_members_total", "counter", "role",
           "portfolio members by terminal role (winner/lost_*)"),
    Metric("tts_portfolio_active", "gauge", "",
           "portfolio races currently unresolved"),
    Metric("tts_queue_depth", "gauge", "", "live admission-queue depth"),
    Metric("tts_queue_peak_depth", "gauge", "",
           "high-water queue depth since server start"),
    Metric("tts_queue_rejected", "gauge", "",
           "admissions rejected at the depth bound"),
    Metric("tts_submeshes", "gauge", "",
           "submesh slots partitioned at startup"),
    Metric("tts_submeshes_busy", "gauge", "",
           "submeshes currently running a request"),
    Metric("tts_phase_seconds", "gauge", "phase,worker,request,tenant",
           "live kernel/gen_child/balance/idle attribution; series "
           "retire at the request's terminal state"),
    # --- executor + AOT caches
    Metric("tts_executor_cache_hits_total", "counter", "",
           "requests served from an already-compiled loop"),
    Metric("tts_executor_cache_misses_total", "counter", "",
           "compiled-loop builds (traces/compiles paid)"),
    Metric("tts_executor_cache_entries", "gauge", "",
           "distinct compiled loops held"),
    Metric("tts_compile_seconds", "histogram", "",
           "trace+compile wall seconds per new executable (disk "
           "replays excluded)"),
    Metric("tts_aot_cache_hits_total", "counter", "",
           "executables deserialized from the disk AOT cache"),
    Metric("tts_aot_cache_misses_total", "counter", "",
           "disk AOT lookups with no loadable entry"),
    Metric("tts_aot_cache_errors_total", "counter", "",
           "corrupt/unreadable/unserializable AOT entries (corrupt "
           "ones quarantined)"),
    Metric("tts_deserialize_seconds", "histogram", "",
           "disk AOT deserialize+load wall seconds per hit"),
    # --- tuner
    Metric("tts_tuner_cache_hits_total", "counter", "",
           "tuned params replayed from the tuning cache (zero probes)"),
    Metric("tts_tuner_cache_misses_total", "counter", "",
           "tuning-cache lookups with no loadable entry"),
    Metric("tts_tuner_probes_total", "counter", "",
           "warmed probe executions (candidate measurements)"),
    Metric("tts_tuner_probe_seconds", "histogram", "",
           "wall seconds per tuning sweep (all candidates of a shape)"),
    # --- checkpoints / resilience
    Metric("tts_checkpoint_saves_total", "counter", "",
           "checkpoint snapshots written"),
    Metric("tts_checkpoint_save_seconds", "histogram", "",
           "checkpoint save latency (fetch+compress+fsync)"),
    Metric("tts_checkpoint_bytes", "histogram", "",
           "checkpoint file size"),
    Metric("tts_checkpoint_loads_total", "counter", "",
           "checkpoint loads"),
    Metric("tts_checkpoint_corrupt_total", "counter", "",
           "corrupt snapshots detected at load"),
    Metric("tts_checkpoint_quarantines_total", "counter", "",
           "corrupt snapshots renamed *.corrupt"),
    Metric("tts_checkpoint_rollbacks_total", "counter", "",
           "resumes that fell back to the .prev last-good snapshot"),
    Metric("tts_elastic_reshards_total", "counter", "",
           "N->M worker elastic resumes"),
    Metric("tts_pool_grows_total", "counter", "",
           "lossless pool-overflow recoveries (fetch+grow+recommit)"),
    Metric("tts_retries_total", "counter", "what",
           "one increment per retried transient"),
    Metric("tts_faults_injected_total", "counter", "point,fault",
           "deterministic fault injections that fired"),
    # --- segments / engine throughput
    Metric("tts_segment_seconds", "histogram", "", "segment latency"),
    Metric("tts_segment_gap_seconds", "histogram", "",
           "device-idle gap between segments (TTS_OVERLAP drives it "
           "to ~0)"),
    Metric("tts_nodes_explored_total", "counter", "",
           "explored-node throughput (segment deltas)"),
    Metric("tts_incumbent_folds_total", "counter", "direction",
           "cross-request incumbent exchanges (out=published, "
           "in=folded)"),
    Metric("tts_ladder_switches_total", "counter", "direction",
           "chunk-ladder rung switches at segment boundaries"),
    # --- on-device search telemetry (TTS_SEARCH_TELEMETRY=1)
    Metric("tts_search_popped", "gauge", "bucket,request,tag,tenant",
           "nodes popped by relative-depth bucket"),
    Metric("tts_search_branched", "gauge", "bucket,request,tag,tenant",
           "children branched by relative-depth bucket"),
    Metric("tts_search_pruned", "gauge", "bucket,request,tag,tenant",
           "children pruned by relative-depth bucket"),
    Metric("tts_search_bound_gap", "gauge",
           "outcome,bin,request,tag,tenant",
           "child bound-value histogram, pruned vs surviving"),
    Metric("tts_search_pruning_rate", "gauge", "request,tag,tenant",
           "pruned/evaluated ratio"),
    Metric("tts_search_frontier_depth", "gauge", "request,tag,tenant",
           "mean relative frontier depth (0=root, 1=leaves)"),
    Metric("tts_search_pool_highwater", "gauge", "request,tag,tenant",
           "peak pool occupancy"),
    Metric("tts_search_steal_sent", "gauge", "request,tag,tenant",
           "work-stealing rows sent"),
    Metric("tts_search_steal_recv", "gauge", "request,tag,tenant",
           "work-stealing rows received"),
    Metric("tts_search_improvements", "gauge", "request,tag,tenant",
           "incumbent improvements found"),
    # --- resources
    Metric("tts_device_bytes_in_use", "gauge", "device,platform",
           "per-device HBM in use"),
    Metric("tts_device_bytes_peak", "gauge", "device,platform",
           "per-device peak HBM"),
    Metric("tts_device_bytes_limit", "gauge", "device,platform",
           "per-device memory limit"),
    Metric("tts_host_rss_bytes", "gauge", "",
           "host process resident set"),
    # --- crash-safe serving (service/ledger.py)
    Metric("tts_server_restarts_total", "counter", "",
           "server boots that replayed prior request-ledger state "
           "(fed from the ledger's boot count, so it survives the "
           "registry reset a restart is)"),
    Metric("tts_ledger_records_total", "counter", "kind",
           "request-ledger records appended (each fsync'd before the "
           "transition it journals is acknowledged)"),
    Metric("tts_ledger_replayed_total", "counter", "",
           "ledger records replayed at boot"),
    Metric("tts_ledger_truncated_total", "counter", "",
           "corrupt-tail ledger records discarded at replay "
           "(truncate-to-last-good)"),
    Metric("tts_ledger_errors_total", "counter", "",
           "failed ledger appends (ENOSPC/IO): crash-durability "
           "degraded until the disk recovers — alert on it"),
    # --- self-healing (service/remediate.py)
    Metric("tts_remediations_total", "counter", "rule,action,outcome",
           "remediation decisions (outcome: applied/observed/"
           "rate_limited/noop/skipped/failed/error/restored)"),
    Metric("tts_quarantined_submeshes", "gauge", "",
           "submesh slots currently held out of the partition"),
    Metric("tts_admission_paused", "gauge", "",
           "1 while the remediation controller holds admission paused"),
    # --- fleet failover (service/lease.py + service/failover.py)
    Metric("tts_lease_epoch", "gauge", "",
           "fencing epoch of the ledger lease this server holds"),
    Metric("tts_lease_renewals_total", "counter", "",
           "successful ledger-lease renewals"),
    Metric("tts_lease_lost_total", "counter", "",
           "lease losses (epoch bumped by an adopter / owner changed): "
           "the server self-fenced"),
    Metric("tts_takeovers_total", "counter", "outcome",
           "expired peer leases handled by the failover watcher "
           "(outcome: adopted/observed/lost_race/error)"),
    # --- fleet flight recorder (obs/store.py + SLO burn rules)
    Metric("tts_obs_store_records_total", "counter", "",
           "flight-recorder records appended to the durable store"),
    Metric("tts_obs_store_replayed_total", "counter", "",
           "flight-recorder records replayed at boot (all writers)"),
    Metric("tts_obs_store_truncated_total", "counter", "",
           "corrupt-tail flight-recorder records discarded at replay "
           "(own segments truncated to last-good)"),
    Metric("tts_slo_burn_rate", "gauge", "slo,window",
           "SLO error-budget burn rate over the durable terminal "
           "history (slo: error/latency; window: fast/slow; 1.0 = "
           "spending exactly the budget; per-tenant override series "
           "add a tenant label)"),
    # --- progress / ETA estimation (obs/estimate.py; per-request
    #     series retire at the terminal state like every per-request
    #     family)
    Metric("tts_progress_ratio", "gauge", "request,tag,tenant",
           "estimated fraction of the search tree explored (monotone "
           "after warmup; published only past the warmup gate)"),
    Metric("tts_eta_seconds", "gauge", "request,tag,tenant",
           "estimated execution seconds remaining (estimated remaining "
           "nodes over the measured node rate)"),
    Metric("tts_est_tree_size", "gauge", "request,tag,tenant",
           "estimated total search-tree size in nodes (Knuth-family "
           "online estimate from depth-bucket branching/pruning)"),
    # --- fleet capacity & utilization (obs/capacity.py, TTS_CAPACITY)
    Metric("tts_lane_seconds_total", "counter", "lane,state",
           "wall-clock seconds each submesh lane spent per scheduler "
           "state (idle/compiling/executing/draining/quarantined/"
           "batch-frozen; conserved — states sum to lane lifetime)"),
    Metric("tts_capacity_utilization", "gauge", "shape,tenant",
           "per-shape-class ρ = arrival demand over healthy-lane "
           "capacity (1.0 = saturated)"),
    Metric("tts_capacity_headroom", "gauge", "shape,tenant",
           "per-shape-class spare capacity fraction (1 − ρ)"),
    Metric("tts_capacity_predicted_wait_s", "gauge", "shape,tenant",
           "Little's-law predicted queue wait per shape class"),
    # --- health / audit / meta
    Metric("tts_alerts", "gauge", "rule,severity",
           "alert state by rule (0 inactive, 0.5 pending, 1 firing)"),
    Metric("tts_alerts_fired_total", "counter", "rule",
           "pending->firing transitions"),
    Metric("tts_health_evaluations_total", "counter", "",
           "health rule sweeps"),
    Metric("tts_audit_checks_total", "counter", "invariant",
           "audit invariant evaluations"),
    Metric("tts_audit_failures_total", "counter", "invariant",
           "failed audit invariants"),
    Metric("tts_http_requests_total", "counter", "path",
           "observability endpoint hits"),
    Metric("tts_profile_captures_total", "counter", "",
           "completed on-demand profiler captures"),
    Metric("tts_metrics_dropped_total", "counter", "metric",
           "label sets dropped by the per-metric cardinality cap"),
)
