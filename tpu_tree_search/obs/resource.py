"""Resource sampler: device-memory and host-RSS gauges + trace lanes.

The flight recorder and the search telemetry show *what the search did*;
this module shows *what the hardware paid for it*. A
:class:`ResourceSampler` publishes, per addressable device,

- ``tts_device_bytes_in_use{device=,platform=}`` — live HBM (or, on
  backends without ``memory_stats``, the summed bytes of live jax
  arrays on that device — the CPU-mesh approximation the test suite
  runs on);
- ``tts_device_bytes_peak{device=,platform=}`` — the backend's peak
  allocation when it reports one, else the high-water of the samples
  this process took;
- ``tts_device_bytes_limit{device=,platform=}`` — the allocator budget
  (absent when the backend has none);
- ``tts_host_rss_bytes`` — the process's resident set

into a metrics registry, and records each sweep as a
``resource.sample`` event in the trace log, which
``obs/chrome_trace.to_chrome`` renders as Perfetto COUNTER tracks —
memory lanes next to the pool/steal lanes, so an HBM ramp lines up
with the pool growth that caused it.

Two ways to drive it: a daemon thread on a fixed cadence (the serve
path — ``SearchServer`` owns one and retires its series on close), or
one-shot :func:`sample_now` calls (the segmented engine driver samples
at every heartbeat, so even standalone runs get a per-segment memory
timeline). Device introspection itself lives in
``utils/device_info.py``.
"""

from __future__ import annotations

import threading

from ..utils import device_info
from . import metrics, tracelog

__all__ = ["ResourceSampler", "sample_now", "GAUGES"]

# every gauge a sampler writes — retired per-sampler via retire()
GAUGES = ("tts_device_bytes_in_use", "tts_device_bytes_peak",
          "tts_device_bytes_limit", "tts_host_rss_bytes")

# peak-allocation high-water per device id, PROCESS-wide: the peak is a
# fact about the process's allocator, not about whichever sampler (or
# registry) happened to observe it, so one-shot heartbeat samples and
# per-server daemon samplers accumulate into the same table
_PEAKS: dict[str, int] = {}
_PEAKS_LOCK = threading.Lock()

# daemon samplers currently running in this process. While one is
# active, one-shot heartbeat sweeps (sample_now) record their trace
# event but skip the gauge writes: the serve-session /metrics
# concatenates the server registry and the process-global one, and the
# same series name appearing in both is an invalid Prometheus
# exposition (duplicate samples).
_ACTIVE_DAEMONS = 0


class ResourceSampler:
    """Periodic (or on-demand) device-memory / host-RSS publisher.

    `registry` defaults to the process-global one; the search server
    passes its per-server registry so ``/metrics`` carries the gauges.
    `period_s <= 0` disables the thread — :meth:`sample` still works
    on demand.
    """

    def __init__(self, registry=None, period_s: float = 0.0,
                 trace: bool = True, autostart: bool = True):
        self.registry = registry if registry is not None \
            else metrics.default()
        self.period_s = float(period_s)
        self.trace = trace
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: threading.Thread | None = None
        self._g_use = self.registry.gauge(
            "tts_device_bytes_in_use",
            "per-device live allocation (backend memory_stats, or live "
            "jax-array bytes where the backend reports none)")
        self._g_peak = self.registry.gauge(
            "tts_device_bytes_peak",
            "per-device peak allocation (backend-reported, else the "
            "high-water of this process's samples)")
        self._g_limit = self.registry.gauge(
            "tts_device_bytes_limit",
            "per-device allocator budget (absent without one)")
        self._g_rss = self.registry.gauge(
            "tts_host_rss_bytes", "host process resident set size")
        if autostart and self.period_s > 0:
            self.start()

    # ------------------------------------------------------------- sampling

    def sample(self, publish: bool = True) -> dict:
        """One sweep: read, publish gauges (unless ``publish=False`` —
        trace event only), record the trace event. Returns the sample
        (the heartbeat hook forwards it)."""
        devices = device_info.memory_snapshot()
        rss = device_info.host_rss_bytes()
        with self._lock:
            for d in devices:
                key = str(d["id"])
                labels = {"device": key, "platform": d["platform"]}
                use = d.get("bytes_in_use")
                if use is not None:
                    peak = d.get("peak_bytes_in_use")
                    with _PEAKS_LOCK:
                        if peak is None:
                            peak = max(_PEAKS.get(key, 0), use)
                        _PEAKS[key] = max(_PEAKS.get(key, 0), peak)
                    d["peak_bytes_in_use"] = peak
                    if publish:
                        self._g_use.set(use, **labels)
                        self._g_peak.set(peak, **labels)
                if publish and d.get("bytes_limit") is not None:
                    self._g_limit.set(d["bytes_limit"], **labels)
            if publish and rss is not None:
                self._g_rss.set(rss)
        sample = {"host_rss_bytes": rss, "devices": devices}
        if self.trace:
            tracelog.event("resource.sample", **sample)
        return sample

    # -------------------------------------------------------------- thread

    def start(self) -> None:
        global _ACTIVE_DAEMONS
        with self._lock:
            if self._thread is not None or self.period_s <= 0:
                return
            self._stop.clear()
            self._thread = threading.Thread(
                target=self._loop, daemon=True,
                name="tts-resource-sampler")
            with _PEAKS_LOCK:
                _ACTIVE_DAEMONS += 1
            self._thread.start()

    def _loop(self) -> None:
        while not self._stop.wait(self.period_s):
            try:
                self.sample()
            except Exception:  # noqa: BLE001 — a failed sweep (backend
                pass           # racing shutdown) must not kill the thread

    def stop(self) -> None:
        global _ACTIVE_DAEMONS
        self._stop.set()
        th = self._thread
        if th is not None:
            th.join(timeout=5)
            with _PEAKS_LOCK:
                _ACTIVE_DAEMONS -= 1
        self._thread = None

    def retire(self) -> None:
        """Drop every series this sampler published (the cardinality
        valve the server pulls on close, same rule as the per-request
        phase/telemetry series)."""
        for name in GAUGES:
            self.registry.remove_matching(name)

    def close(self) -> None:
        self.stop()
        self.retire()


# cached one-shot samplers for the heartbeat hook (sample_now fires
# once per segment — no per-sweep object construction on that path).
# The scratch instance exists because even CREATING the gauges in the
# exposed default registry would add duplicate # TYPE lines next to a
# daemon's registry; its registry is never exposed anywhere.
_oneshot: "ResourceSampler | None" = None
_scratch: "ResourceSampler | None" = None


def sample_now(registry=None, trace: bool = True) -> dict:
    """One-shot sweep into `registry` (default: the process-global one)
    — the segmented engine's heartbeat hook. While a daemon sampler is
    active in the process (a serve session), the sweep records only
    the trace event: the daemon owns the gauges, and the same series
    in two exposed registries would be an invalid exposition."""
    global _oneshot, _scratch
    with _PEAKS_LOCK:
        publish = _ACTIVE_DAEMONS == 0
    if registry is not None:
        return ResourceSampler(registry=registry, period_s=0.0,
                               trace=trace,
                               autostart=False).sample(publish=publish)
    if not publish:
        if _scratch is None:
            _scratch = ResourceSampler(registry=metrics.Registry(
                "scratch"), period_s=0.0, autostart=False)
        sampler = _scratch
    else:
        # re-resolve when tests swap the process-global registry
        if _oneshot is None \
                or _oneshot.registry is not metrics.default():
            _oneshot = ResourceSampler(period_s=0.0, autostart=False)
        sampler = _oneshot
    sampler.trace = trace
    return sampler.sample(publish=publish)
