"""Validate phase_timing.attribute against profiler traces.

phase_timing attributes wall time from measured unit costs x counters
(kernel/compaction/balance/idle). Its `kernel_time` column BRACKETS
pop + mask + dense bound — the same semantics as the reference's
kernel timer, which wraps the whole evaluate_gpu region including
copies and launch (PFSP_statistic.c:69-112) — NOT the bound op alone.
This script therefore reports TWO ground truths per bound, each with
its own error bar (VERDICT r3 #9 / r4 #8):

- bracket vs traced bracket: the attributed per-step kernel cost
  against the device self-time of an independently traced
  pop+mask+bound loop — same semantics, so this is THE error bar for
  the attribution itself (target <=10% for both bounds).
- op share (informational): the attributed kernel share of device time
  against the trace share of the bound OP alone. For LB2 the dense
  sweeps dominate the bracket so the two nearly coincide (~3%); for
  LB1 the bound op is a small part of its bracket, so this pair
  differs by DEFINITION (~2.4x) — the number documents the gap, it is
  not an attribution error.

    python tools/validate_attribution.py [--iters 30] [--chunk 32768]
"""

import argparse
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tpu_tree_search.engine import device  # noqa: E402
from tpu_tree_search.obs import profiler, tracelog  # noqa: E402
from tpu_tree_search.obs.chrome_trace import (load_xla_trace,  # noqa: E402
                                              self_times)
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402
from tpu_tree_search.utils import phase_timing  # noqa: E402

KERNEL_OPS = ("expand_bounds", "lb2_bounds", "pallas")


def trace_kernel_share(log_dir):
    self_us, _ = self_times(load_xla_trace(log_dir))
    total = sum(self_us.values())
    kern = sum(v for k, v in self_us.items()
               if any(s in k.lower() for s in KERNEL_OPS))
    return kern / total if total else 0.0, total / 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--warm", type=int, default=400)
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    jobs = p.shape[1]

    for lb in (1, 2):
        state = device.init_state(jobs, 1 << 22, ub, p_times=p)
        state = device.run(tables, state, lb, args.chunk,
                           max_iters=args.warm)
        state.size.block_until_ready()

        # the attribution's unit costs, measured on the same shapes
        prof = phase_timing.profile_phases(tables, state, lb, args.chunk)

        log_dir = tempfile.mkdtemp(prefix=f"tts_attr_lb{lb}_")
        with tracelog.span("validate_attribution.traced_window",
                           lb=lb, logdir=log_dir) as win_sp:
            with profiler.trace(log_dir):
                out = device.run(tables, state, lb, args.chunk,
                                 max_iters=args.warm + args.iters)
                out.size.block_until_ready()
        elapsed = win_sp.dur
        evals = int(out.evals) - int(state.evals)
        iters = int(out.iters) - int(state.iters)

        att = phase_timing.attribute(prof, elapsed, [evals], [iters])
        att_kernel = float(att["kernel_time"][0])
        att_share = att_kernel / elapsed

        trace_share, trace_total_s = trace_kernel_share(log_dir)
        # compare against the DEVICE-time share too: wall includes
        # dispatch/host gaps the device never sees
        att_dev_share = att_kernel / trace_total_s if trace_total_s else 0

        # INDEPENDENT bracket ground truth: trace the same
        # pop+mask+bound loop the unit cost was measured on, and take
        # its device self-time per rep — same semantics as the
        # attributed kernel bracket, so |error| here is the
        # attribution's real error bar for BOTH bounds.
        import jax
        import jax.numpy as jnp
        # 256 reps (r5, was 64): the LB1 bracket is ~0.3 ms, so per-rep
        # wall slack that the two-trip differencing cannot cancel
        # (device scheduling bubbles, loop-carry overhead) amortizes
        # only with a long window — K=64 read +38.6% on LB1 (r4)
        from tpu_tree_search.utils import config as _cfg
        K = _cfg.env_int("TTS_BRACKET_REPS")

        def make_loop(reps):
            @jax.jit
            def bracket_loop(s):
                def body(i, acc):
                    return acc + phase_timing._pop_and_bound(
                        tables,
                        s._replace(size=jnp.maximum(s.size - i * 128, 1)),
                        lb, args.chunk, 1024).sum(dtype=jnp.float32)
                return jax.lax.fori_loop(0, reps, body, jnp.float32(0.0))
            return bracket_loop

        loop1, loop2 = make_loop(K), make_loop(2 * K)

        def wall(fn):
            fn(state).block_until_ready()        # compile outside
            with tracelog.span("validate_attribution.bracket_wall",
                               lb=lb) as sp:
                fn(state).block_until_ready()
            return sp.dur

        # two trip counts, differenced: one dispatch through the remote
        # runtime costs ~10-100 ms of wall that a single-K measurement
        # folds into the per-rep cost (the LB1 bracket is ~0.3 ms, so a
        # K=64 single measurement read 4x too high)
        bracket_wall_per_rep = (wall(loop2) - wall(loop1)) / K
        bracket_loop = loop2
        bdir = tempfile.mkdtemp(prefix=f"tts_bracket_lb{lb}_")
        with profiler.trace(bdir):
            bracket_loop(state).block_until_ready()
        bracket_self, _ = self_times(load_xla_trace(bdir))
        bracket_dev_per_rep = sum(bracket_self.values()) / 1e6 / (2 * K)
        # Same loop, wall-timed vs trace device self-time: this
        # validates the attribution's MEASUREMENT method (the unit
        # costs phase_timing wall-times in compiled loops) at matching
        # pop+mask+bound semantics for both bounds. It deliberately
        # does NOT use prof["bound"] for LB2, which is already scaled
        # by the production sweep-tier fraction (phase_timing
        # profile_phases) and would spuriously compare a scaled number
        # against the unscaled dense trace; the tier scaling is
        # arithmetic applied after measurement, not measurement.
        err_bracket = ((bracket_wall_per_rep - bracket_dev_per_rep)
                       / max(bracket_dev_per_rep, 1e-12))

        print(f"lb={lb}: BRACKET unit cost (wall, in-loop) "
              f"{bracket_wall_per_rep*1e3:.3f} ms vs traced device "
              f"self-time {bracket_dev_per_rep*1e3:.3f} ms -> error "
              f"{err_bracket:+6.1%} (same pop+mask+bound semantics; "
              f"the attribution measurement's error bar)")
        print(f"lb={lb}: OP SHARE attributed kernel share of wall "
              f"{att_share:6.1%}, of device time {att_dev_share:6.1%} "
              f"| bound-op-only trace share {trace_share:6.1%} "
              f"| bracket-vs-op definitional ratio "
              f"{att_dev_share / trace_share if trace_share else 0:4.2f}x"
              f" (wall {elapsed:.2f}s, device {trace_total_s:.2f}s, "
              f"{iters} iters)")


if __name__ == "__main__":
    main()
