"""Validate phase_timing.attribute against a profiler trace (VERDICT r3 #9).

phase_timing attributes wall time from measured unit costs x counters
(kernel/compaction/balance/idle). This script checks its kernel share
against ground truth from a jax.profiler trace of the same steady-state
window, for one LB1 and one LB2 ta021 run, and prints the error margin.

    python tools/validate_attribution.py [--iters 30] [--chunk 32768]
"""

import argparse
import os
import sys
import tempfile
import time

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trace_selftime import load, self_times  # noqa: E402

import numpy as np  # noqa: E402

from tpu_tree_search.engine import device  # noqa: E402
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402
from tpu_tree_search.utils import device_info, phase_timing  # noqa: E402

KERNEL_OPS = ("expand_bounds", "lb2_bounds", "pallas")


def trace_kernel_share(log_dir):
    self_us, _ = self_times(load(log_dir))
    total = sum(self_us.values())
    kern = sum(v for k, v in self_us.items()
               if any(s in k.lower() for s in KERNEL_OPS))
    return kern / total if total else 0.0, total / 1e6


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--warm", type=int, default=400)
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    jobs = p.shape[1]

    for lb in (1, 2):
        state = device.init_state(jobs, 1 << 22, ub, p_times=p)
        state = device.run(tables, state, lb, args.chunk,
                           max_iters=args.warm)
        state.size.block_until_ready()

        # the attribution's unit costs, measured on the same shapes
        prof = phase_timing.profile_phases(tables, state, lb, args.chunk)

        log_dir = tempfile.mkdtemp(prefix=f"tts_attr_lb{lb}_")
        t0 = time.perf_counter()
        with device_info.trace(log_dir):
            out = device.run(tables, state, lb, args.chunk,
                             max_iters=args.warm + args.iters)
            out.size.block_until_ready()
        elapsed = time.perf_counter() - t0
        evals = int(out.evals) - int(state.evals)
        iters = int(out.iters) - int(state.iters)

        att = phase_timing.attribute(prof, elapsed, [evals], [iters])
        att_kernel = float(att["kernel_time"][0])
        att_share = att_kernel / elapsed

        trace_share, trace_total_s = trace_kernel_share(log_dir)
        # compare against the DEVICE-time share too: wall includes
        # dispatch/host gaps the device never sees
        att_dev_share = att_kernel / trace_total_s if trace_total_s else 0

        print(f"lb={lb}: attribute kernel share of WALL "
              f"{att_share:6.1%}  of device time {att_dev_share:6.1%}  "
              f"| trace ground truth {trace_share:6.1%}  "
              f"| error vs device-share "
              f"{abs(att_dev_share - trace_share):5.1%} "
              f"(wall {elapsed:.2f}s, device {trace_total_s:.2f}s, "
              f"{iters} iters)")


if __name__ == "__main__":
    main()
