"""Per-request latency/preemption table from a flight-recorder trace.

Reads any trace artifact the observability layer produces —

- the JSONL event log (obs/tracelog's file sink, `serve --trace-file`,
  TTS_TRACE_FILE, the campaign's `trace_file` row pointer),
- the Chrome trace-event JSON (obs/chrome_trace.write_chrome, the
  `/trace` endpoint) — detected by the leading ``{"traceEvents": ...}``,
- the DURABLE flight-recorder store (obs/store; TTS_OBS_STORE): a
  store directory or one ``obs-*.jsonl`` CRC segment — detected by the
  wrapped ``{"c": <crc>, "r": {...}}`` line format

— and prints one row per request: terminal state, queue wait, total
latency, execution seconds (summed `request.execute` spans), dispatch /
preemption / checkpoint-save counts. Store input additionally renders
PER-JOURNEY tables (one logical request across lifetimes/hosts:
lifetimes, writers, preemptions, batch/portfolio membership, budget
spent per lifetime) — store records span process lifetimes, so the
cross-restart story exists only there. Doubles as the CI artifact's
well-formedness check (tests/test_obs.py runs it against the formats).

    python tools/trace_summary.py /tmp/tts-trace.jsonl
    python tools/trace_summary.py /tmp/tts-trace.chrome.json
    python tools/trace_summary.py /tmp/tts-store/          # store dir
    python tools/trace_summary.py /tmp/tts-store/obs-host-ldg-00000001.jsonl
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

TERMINALS = ("done", "cancelled", "deadline", "failed")

# pseudo-row pooling request-less remediation events (quarantine,
# readmit, admission pause) so the footer count is complete; never
# rendered as a request row
SERVER_ROW = "<server>"


def _store_to_records(store_recs: list[dict]) -> list[dict]:
    """Durable-store records (obs/store schema: ``{"k", "t", "w", ...}``)
    normalized to tracelog shape. Events keep their flattened
    attributes; ``boot`` records become ``store.boot`` markers (the
    lifetime delimiters the journey tables count); ``sample``
    time-series records are dropped (no request story in them). Every
    record keeps its ``writer`` — the per-host identity the single-
    process trace formats never needed."""
    out = []
    for r in store_recs:
        kind = r.get("k")
        if kind == "event":
            rec = {key: v for key, v in r.items()
                   if key not in ("k", "t", "w")}
            rec.setdefault("name", "?")
        elif kind == "boot":
            rec = {"name": "store.boot", "pid": r.get("pid")}
        else:
            continue
        rec["ts"] = float(r.get("t", 0.0))
        rec["writer"] = r.get("w", "?")
        out.append(rec)
    return out


def load_records(path: str) -> list[dict]:
    """Normalize any trace format to tracelog-shaped records
    (name/ts[s]/dur[s] + flat attributes). A directory, or a file whose
    first line is a CRC-wrapped ``{"c": ..., "r": {...}}`` record, is
    read as the durable flight-recorder store (obs/store)."""
    if os.path.isdir(path):
        from tpu_tree_search.obs.store import read_store
        return _store_to_records(read_store(path))
    with open(path) as f:
        head = f.read(4096).lstrip()
    if head.startswith("{"):
        try:
            first = json.loads(head.splitlines()[0])
        except (json.JSONDecodeError, IndexError):
            first = None
        if isinstance(first, dict) and set(first) == {"c", "r"}:
            # one store segment: CRC-scan it exactly the way the store
            # replays its own files (stop at the first damaged line)
            from tpu_tree_search.obs.store import _scan_segment
            recs = []
            with open(path, "rb") as f:
                for rec, _end in _scan_segment(f.read()):
                    if rec is None:
                        break
                    recs.append(rec)
            return _store_to_records(recs)
    if head.startswith("{") and '"traceEvents"' in head:
        # Chrome trace: events carry the original attributes in `args`,
        # timestamps/durations in µs
        with open(path) as f:
            doc = json.load(f)
        out = []
        for e in doc.get("traceEvents", []):
            if e.get("ph") not in ("X", "i"):
                continue
            rec = {"name": e.get("name", "?"),
                   "ts": float(e.get("ts", 0.0)) / 1e6,
                   **(e.get("args") or {})}
            if e["ph"] == "X":
                rec["dur"] = float(e.get("dur", 0.0)) / 1e6
            out.append(rec)
        return out
    from tpu_tree_search.obs.chrome_trace import read_jsonl
    return read_jsonl(path)


def summarize(records: list[dict]) -> dict[str, dict]:
    """Fold records into one summary dict per request id."""
    reqs: dict[str, dict] = {}

    def req(rid):
        return reqs.setdefault(rid, {
            "state": "?", "admit_ts": None, "first_dispatch_ts": None,
            "terminal_ts": None, "dispatches": 0, "preemptions": 0,
            "checkpoints": 0, "retries": 0, "faults": 0, "exec_s": 0.0,
            "failures": 0, "failure_log": [], "remediations": 0,
            "submeshes": set(),
            # bound-portfolio racing (service/portfolio): set on the
            # PARENT row by the portfolio.fanout / portfolio.win events
            "pf_k": None, "pf_winner": None, "pf_config": None,
            "pf_cancelled": None})

    for r in sorted(records, key=lambda r: (r.get("ts", 0.0),
                                            r.get("seq", 0))):
        rid = r.get("request_id")
        name = r.get("name", "")
        if rid is None:
            if name.startswith("remediation."):
                # server-level actions (quarantine/readmit/pause)
                # carry no request id; pool them under a pseudo-row so
                # the footer's remediation count stays complete (the
                # render skips the row in the per-request table)
                req(SERVER_ROW)["remediations"] += 1
            continue
        s = req(rid)
        if name == "request.admit":
            s["admit_ts"] = r["ts"]
        elif name == "request.dispatch":
            s["dispatches"] += 1
            if s["first_dispatch_ts"] is None:
                s["first_dispatch_ts"] = r["ts"]
            if r.get("submesh") is not None:
                s["submeshes"].add(r["submesh"])
        elif name == "request.preempt":
            s["preemptions"] += 1
        elif name == "request.execute":
            s["exec_s"] += float(r.get("dur", 0.0))
            if r.get("submesh") is not None:
                s["submeshes"].add(r["submesh"])
        elif name == "checkpoint.save":
            s["checkpoints"] += 1
        elif name == "retry":
            s["retries"] += 1
        elif name == "fault.injected":
            s["faults"] += 1
        elif name == "request.dispatch_failure":
            # one per dispatch failure INCLUDING the terminal one
            # (request.redispatch only marks the requeue path) — the
            # post-hoc failure_log the self-healing tier keeps on the
            # RequestRecord, rebuilt from the flight recorder so a
            # dead-lettered FAILED request is diagnosable from the
            # trace alone
            s["failures"] += 1
            s["failure_log"].append(
                {"submesh": r.get("submesh"),
                 "attempt": r.get("attempt"),
                 "error": r.get("error")})
        elif name == "portfolio.fanout":
            s["pf_k"] = r.get("k")
        elif name == "portfolio.win":
            s["pf_winner"] = r.get("winner")
            s["pf_config"] = r.get("config")
            s["pf_cancelled"] = r.get("cancelled")
        elif name.startswith("remediation."):
            s["remediations"] += 1
        elif name.startswith("request.") \
                and name.split(".", 1)[1] in TERMINALS:
            s["state"] = name.split(".", 1)[1].upper()
            # a span-less event: its ts IS the terminal instant
            s["terminal_ts"] = r["ts"]
    return reqs


def render(reqs: dict[str, dict]) -> str:
    hdr = (f"{'request':<10} {'state':<9} {'wait_s':>8} {'latency_s':>10} "
           f"{'exec_s':>8} {'disp':>4} {'pre':>4} {'fail':>4} "
           f"{'ckpt':>4} {'retry':>5} {'sibs':>4} {'winner':<9} "
           f"{'cxl':>3}  submeshes")
    lines = [hdr, "-" * len(hdr)]

    def f(a, b):
        return f"{b - a:.3f}" if a is not None and b is not None else "-"

    rows = {rid: s for rid, s in reqs.items() if rid != SERVER_ROW}
    for rid in sorted(rows):
        s = rows[rid]
        lines.append(
            f"{rid:<10} {s['state']:<9} "
            f"{f(s['admit_ts'], s['first_dispatch_ts']):>8} "
            f"{f(s['admit_ts'], s['terminal_ts']):>10} "
            f"{s['exec_s']:>8.3f} {s['dispatches']:>4} "
            f"{s['preemptions']:>4} {s['failures']:>4} "
            f"{s['checkpoints']:>4} "
            f"{s['retries']:>5} "
            f"{str(s['pf_k']) if s['pf_k'] is not None else '-':>4} "
            f"{s['pf_winner'] or '-':<9} "
            f"{str(s['pf_cancelled']) if s['pf_cancelled'] is not None else '-':>3}  "
            f"{sorted(s['submeshes'])}")
    n_pre = sum(s["preemptions"] for s in rows.values())
    n_fail = sum(s["failures"] for s in rows.values())
    n_rem = sum(s["remediations"] for s in reqs.values())
    lines.append(f"{len(rows)} request(s), {n_pre} preemption(s), "
                 f"{n_fail} dispatch failure(s), "
                 f"{n_rem} remediation record(s)")
    # the per-race story of every portfolio parent: siblings raced,
    # winning config, losers cancelled (the win event's full payload —
    # the table columns above are the compressed view)
    for rid in sorted(rows):
        s = rows[rid]
        if s["pf_k"] is None:
            continue
        lines.append(f"\nportfolio {rid}: siblings={s['pf_k']} "
                     f"winner={s['pf_winner'] or '-'} "
                     f"cancelled={s['pf_cancelled']} "
                     f"winner_config={s['pf_config']}")
    # the per-failure story for anything that failed (a dead-lettered
    # request's trail: which submesh, which attempt, which error)
    for rid in sorted(rows):
        s = rows[rid]
        if not s["failure_log"]:
            continue
        lines.append(f"\nfailure log {rid} ({s['state']}):")
        for i, e in enumerate(s["failure_log"], 1):
            lines.append(f"  {i}. submesh={e.get('submesh')} "
                         f"attempt={e.get('attempt')}: "
                         f"{e.get('error')}")
    return "\n".join(lines)


def journeys_from_store(records: list[dict]) -> dict[str, dict]:
    """Per-JOURNEY summaries from store-shaped records (they carry
    ``writer``): one logical request per tag, followed across process
    lifetimes and hosts. A lifetime is one (writer, boot era) — the
    ``store.boot`` markers delimit eras; a journey spanning two
    lifetimes of one writer is a crash+restart, spanning two writers a
    failover takeover. Budget is the max ``spent_s`` witnessed per
    lifetime — cumulative across the journey when the ledger carried it
    over (the budget-continuity check the CI journey leg pins)."""
    era: dict[str, int] = {}
    journeys: dict[str, dict] = {}
    for r in sorted(records, key=lambda r: r.get("ts", 0.0)):
        w = r.get("writer", "?")
        name = r.get("name", "")
        if name == "store.boot":
            era[w] = era.get(w, 0) + 1
            continue
        if not name.startswith("request."):
            continue
        tag = r.get("tag") or r.get("request_id")
        if tag is None:
            continue
        j = journeys.setdefault(str(tag), {
            "rids": [], "writers": [], "lifetimes": {},
            "preemptions": 0, "dispatches": 0, "takeovers": 0,
            "batches": [], "pf_k": None, "state": "LIVE",
            "tenant": "-"})
        rid = r.get("request_id")
        if rid is not None and rid not in j["rids"]:
            j["rids"].append(rid)
        if w not in j["writers"]:
            j["writers"].append(w)
        life = (w, era.get(w, 1))
        lf = j["lifetimes"].setdefault(life, {
            "events": 0, "dispatches": 0, "preemptions": 0,
            "spent_end_s": 0.0})
        lf["events"] += 1
        if r.get("spent_s") is not None:
            lf["spent_end_s"] = max(lf["spent_end_s"],
                                    float(r["spent_s"]))
        if r.get("tenant") not in (None, "-"):
            j["tenant"] = r["tenant"]
        if name == "request.preempt":
            j["preemptions"] += 1
            lf["preemptions"] += 1
        elif name == "request.dispatch":
            j["dispatches"] += 1
            lf["dispatches"] += 1
        elif name == "request.adopted":
            j["takeovers"] += 1
        elif name == "portfolio.fanout":
            j["pf_k"] = r.get("k")
        elif name.split(".", 1)[-1] in TERMINALS:
            j["state"] = name.split(".", 1)[-1].upper()
        b = r.get("batch") or r.get("batch_id")
        if b is not None and b not in j["batches"]:
            j["batches"].append(b)
    return journeys


def render_journeys(journeys: dict[str, dict]) -> str:
    lines = ["request journeys (durable store: one logical request "
             "across lifetimes/hosts)"]
    for tag in sorted(journeys):
        j = journeys[tag]
        lines.append(
            f"\njourney {tag}: state={j['state']} "
            f"tenant={j['tenant']} rids={j['rids']} "
            f"lifetimes={len(j['lifetimes'])} "
            f"writers={len(j['writers'])} "
            f"takeovers={j['takeovers']} "
            f"dispatches={j['dispatches']} "
            f"preemptions={j['preemptions']} "
            f"batches={j['batches'] or '-'} "
            f"portfolio_k={j['pf_k'] if j['pf_k'] is not None else '-'}")
        for (w, n) in sorted(j["lifetimes"]):
            lf = j["lifetimes"][(w, n)]
            lines.append(
                f"  lifetime {w}#{n}: events={lf['events']} "
                f"dispatches={lf['dispatches']} "
                f"preempts={lf['preemptions']} "
                f"budget_end_s={lf['spent_end_s']:.3f}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-request latency/preemption table from a "
                    "flight-recorder trace (JSONL, Chrome JSON, or an "
                    "obs-store directory/segment)")
    ap.add_argument("trace", help="trace file path")
    args = ap.parse_args(argv)
    records = load_records(args.trace)
    if not records:
        print(f"error: no trace records in {args.trace}",
              file=sys.stderr)
        return 1
    reqs = summarize(records)
    if not reqs:
        print(f"error: {len(records)} records but no request ids in "
              f"{args.trace} (not a service trace?)", file=sys.stderr)
        return 1
    print(render(reqs))
    if any("writer" in r for r in records):
        journeys = journeys_from_store(records)
        if journeys:
            print()
            print(render_journeys(journeys))
    return 0


if __name__ == "__main__":
    sys.exit(main())
