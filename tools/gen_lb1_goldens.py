"""Generate LB1 / LB1_d goldens from the reference's own library.

VERDICT r4 missing-item 3: the repo's LB1 tree counts (the basis of the
"published V100 table is de facto LB2" finding, BENCHMARKS.md) were
never goldened against the reference the way the LB2 counts are
(tests/golden/pfsp_lb2_ub1.jsonl). This script drives the reference's
verbatim decompose/lb1_bound/lb1_children_bounds through the
matrix-input wrapper (.ref_build/wrap/pfsp/pfsp_mat.c — the same
oracle binary tools/gen_matrix_goldens.py uses) on every 20-job
Taillard instance at ub=opt (sgpu_launch.sh:84 pins `-l 1`;
PFSP_lib.c:7-43 is the counting semantics being pinned).

Billion-node LB1 trees (the ta022/27/29/30 class) are goldened as
PREFIXES: the wrapper stops after a fixed number of popped parents and
records the exact counters at that point. The native engine reproduces
the same DFS order as the reference (LIFO pool, slot-order child
pushes), so prefix counts are exact invariants; rows record
`expanded < max_nodes` as `complete` so full-tree rows double as
order-independent goldens for the device engine.

    python tools/gen_lb1_goldens.py [--budget 500000]

Writes tests/golden/pfsp_lb1_ub1.jsonl (lb=1) and
tests/golden/pfsp_lb1d_ub1.jsonl (lb=0).
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
WRAPPER = os.path.join(REPO, ".ref_build", "wrap", "pfsp", "pfsp_mat.out")


def reference_counts(wrapper, p, lb, ub, max_nodes):
    with tempfile.NamedTemporaryFile("w", suffix=".mat", delete=False) as f:
        f.write(f"{p.shape[0]} {p.shape[1]}\n")
        for row in p:
            f.write(" ".join(map(str, row)) + "\n")
        path = f.name
    try:
        out = subprocess.run(
            [wrapper, path, str(lb), str(ub), str(max_nodes)],
            capture_output=True, text=True, timeout=600, check=True)
    finally:
        os.unlink(path)
    golden = [ln for ln in out.stdout.splitlines()
              if ln.startswith("GOLDEN ")][0]
    expanded = [ln for ln in out.stdout.splitlines()
                if ln.startswith("EXPANDED ")][0]
    tree, sol, best = (int(x) for x in golden.split()[1:])
    return tree, sol, best, int(expanded.split()[1])


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--wrapper", default=WRAPPER)
    ap.add_argument("--budget", type=int, default=500_000,
                    help="popped-parent cap for the prefix goldens")
    args = ap.parse_args()

    if not os.path.exists(args.wrapper):
        raise SystemExit(
            f"{args.wrapper} missing — compile it first (see "
            "tools/gen_matrix_goldens.py --help for the recipe; set "
            "MAX_JOBS=50 in lib/macro.h)")

    from tpu_tree_search import native  # noqa: E402
    from tpu_tree_search.problems import taillard  # noqa: E402

    for lb, fname in ((1, "pfsp_lb1_ub1.jsonl"), (0, "pfsp_lb1d_ub1.jsonl")):
        rows = []
        for inst in range(1, 31):
            p = np.asarray(taillard.processing_times(inst), np.int32)
            ub = int(taillard.optimal_makespan(inst))
            tree, sol, best, expanded = reference_counts(
                args.wrapper, p, lb, ub, args.budget)
            complete = expanded < args.budget
            # cross-check the native engine right here — a golden that
            # the in-repo oracle cannot reproduce must never be written
            nt, ns, nb, ne = native.search(
                p, lb_kind=lb, init_ub=ub,
                max_nodes=0 if complete else args.budget)
            assert (nt, ns, nb) == (tree, sol, best), (
                f"native disagrees with reference on ta{inst:03d} lb{lb}: "
                f"native=({nt},{ns},{nb}) ref=({tree},{sol},{best})")
            rows.append({"inst": inst, "lb": lb, "ub": 1, "tree": tree,
                         "sol": sol, "best": best,
                         "complete": complete,
                         "max_nodes": 0 if complete else args.budget})
            print(f"ta{inst:03d} lb{lb}: tree={tree} sol={sol} best={best}"
                  f" {'complete' if complete else f'prefix@{args.budget}'}",
                  flush=True)
        out = os.path.join(REPO, "tests", "golden", fname)
        with open(out, "w") as f:
            for r in rows:
                f.write(json.dumps(r) + "\n")
        print(f"wrote {len(rows)} rows to {out}")


if __name__ == "__main__":
    main()
