"""Measure the SPMD program's per-step tax on ONE real chip.

VERDICT r4 #4: the pod-scale projection multiplies the single-device
chip rate by the CPU-mesh's device-count invariance; the missing term
is what the distributed program itself costs per step on real hardware
— shard_map, the cond-gated balance round, the pmin incumbent fold.
That term is measurable on a mesh of ONE real chip: the program is the
full SPMD loop (same collectives, degenerate membership), so its
per-iteration cost against the plain single-device loop is exactly the
per-chip overhead (collective latency at D>1 rides ICI and is priced
separately by the CPU-mesh invariance tests).

Method: ONE pool state, warmed past the ramp with `device.run`, is the
common input; the plain `jit(while(step))` loop and the full
`build_dist_loop` program (stacked to a 1-chip mesh) are then timed on
IDENTICAL state and iteration windows, warming each executable at its
final input signature first. Two earlier methodologies gave garbage and
are kept out on purpose: timing two *independently warmed* searches
compares different pool states (±10% swings either way), and timing a
window whose input signature differs from its warm-up catches a fresh
XLA compile (~100 s) inside the window — the first version of this tool
reported a fictitious 2700% "tax" that way.

    python tools/bench_spmd_tax.py [--inst 21] [--lb 2] [--chunk 32768]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tpu_tree_search.utils import compile_cache  # noqa: E402

compile_cache.enable()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_tree_search.engine import device, distributed  # noqa: E402
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.parallel.mesh import worker_mesh  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--lb", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--warm", type=int, default=500)
    ap.add_argument("--iters", type=int, default=300)
    ap.add_argument("--balance-period", type=int, default=4)
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    jobs, machines = p.shape[1], p.shape[0]
    chunk, lb = args.chunk, args.lb

    state = device.init_state(jobs, args.capacity, ub, p_times=p)
    state = device.run(tables, state, lb, chunk, max_iters=args.warm)
    state.size.block_until_ready()
    assert not bool(state.overflow) and int(state.size) > 0
    base = int(state.iters)
    target = base + args.iters

    def timed(call):
        call()  # warm/compile at the exact final input signature
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            call()
            best = min(best, time.perf_counter() - t0)
        return best / args.iters * 1e3

    # plain single-device loop (device.run's compiled while_loop)
    def single():
        out = device.run(tables, state, lb, chunk, max_iters=target)
        out.size.block_until_ready()

    ms_single = timed(single)

    # the full SPMD program on a 1-chip mesh, same state stacked
    adt = device.aux_dtype(p)
    tc = distributed.default_transfer_cap(chunk, jobs, machines, 1,
                                          aux_itemsize=adt.itemsize)
    limit = min(device.row_limit(args.capacity, chunk, jobs),
                args.capacity - tc)

    def mls(t, lim):
        return functools.partial(device.step, t, lb, chunk, limit=lim)

    loop = distributed.build_dist_loop(
        worker_mesh(1), tables, mls, args.balance_period, tc,
        2 * chunk, limit)
    stacked = tuple(x[None] for x in state)

    def dist():
        out = loop(tables, jnp.int64(target),
                   jnp.int32(distributed.I32_MAX), *stacked)
        jax.block_until_ready(out)

    ms_dist = timed(dist)

    print(json.dumps({
        "inst": args.inst, "lb": lb, "chunk": chunk,
        "balance_period": args.balance_period,
        "window_iters": args.iters, "repeats": args.repeats,
        "single_ms_per_iter": round(ms_single, 4),
        "dist1_ms_per_iter": round(ms_dist, 4),
        "spmd_tax_pct": round((ms_dist / ms_single - 1) * 100, 2),
    }))


if __name__ == "__main__":
    main()
