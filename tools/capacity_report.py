"""Lane utilization & capacity report from a flight-recorder artifact.

The offline twin of ``GET /capacity`` / the ``capacity`` CLI: where
those read a LIVE server, this reads what the observability layer left
behind — so a post-mortem answers "was the fleet saturated?" without a
process to scrape. Accepts every trace artifact the layer produces
(same detection rules as tools/trace_summary.py):

- the JSONL event log (obs/tracelog's file sink, TTS_TRACE_FILE),
- the Chrome trace-event JSON (obs/chrome_trace, ``/trace``) — the
  lane-state story rides the retrospective slices' ``lane.state``
  instants and ``X`` events,
- the DURABLE store (obs/store; TTS_OBS_STORE): a directory or one
  ``obs-*.jsonl`` CRC segment. Unlike trace_summary, ``sample``
  records are KEPT — the persisted ``tts_lane_seconds_total``
  counters and ``tts_capacity_utilization`` gauges ride them, and
  they are the only cross-restart (kill -9 surviving) source.

Prints per-lane state-seconds tables (from ``lane.state`` transition
events), the persisted per-lane counters with each lane's executing
fraction, and the last-known per-shape-class utilization gauges.

    python tools/capacity_report.py /tmp/tts-trace.jsonl
    python tools/capacity_report.py /tmp/tts-trace.chrome.json
    python tools/capacity_report.py /tmp/tts-store/          # store dir
"""

import argparse
import collections
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

LANE_EVENT = "lane.state"
LANE_COUNTER = "tts_lane_seconds_total"
UTIL_GAUGE = "tts_capacity_utilization"


def load(path: str):
    """(events, samples): tracelog-shaped records and raw store
    ``sample`` records. Non-store formats have no samples."""
    if os.path.isdir(path):
        from tpu_tree_search.obs.store import read_store
        return _split_store(read_store(path))
    with open(path) as f:
        head = f.read(4096).lstrip()
    first = None
    if head.startswith("{"):
        try:
            first = json.loads(head.splitlines()[0])
        except (json.JSONDecodeError, IndexError):
            first = None
    if isinstance(first, dict) and set(first) == {"c", "r"}:
        from tpu_tree_search.obs.store import _scan_segment
        recs = []
        with open(path, "rb") as f:
            for rec, _end in _scan_segment(f.read()):
                if rec is None:
                    break
                recs.append(rec)
        return _split_store(recs)
    if head.startswith("{") and '"traceEvents"' in head:
        with open(path) as f:
            doc = json.load(f)
        out = []
        for e in doc.get("traceEvents", []):
            if e.get("ph") not in ("X", "i"):
                continue
            rec = {"name": e.get("name", "?"),
                   "ts": float(e.get("ts", 0.0)) / 1e6,
                   **(e.get("args") or {})}
            out.append(rec)
        return out, []
    from tpu_tree_search.obs.chrome_trace import read_jsonl
    return read_jsonl(path), []


def _split_store(store_recs: list) -> tuple:
    events, samples = [], []
    for r in store_recs:
        kind = r.get("k")
        if kind == "event":
            rec = {key: v for key, v in r.items()
                   if key not in ("k", "t", "w")}
            rec.setdefault("name", "?")
            rec["ts"] = float(r.get("t", 0.0))
            rec["writer"] = r.get("w", "?")
            events.append(rec)
        elif kind == "sample":
            samples.append(r)
    return events, samples


def lane_seconds_from_events(events: list) -> dict:
    """lane -> {state: seconds, ...} summed from ``lane.state``
    transition events (each carries the full duration of the state
    being LEFT), plus a transition count."""
    lanes = collections.defaultdict(lambda: {
        "seconds": collections.Counter(), "transitions": 0,
        "last_state": None})
    for rec in events:
        if rec.get("name") != LANE_EVENT:
            continue
        lane = rec.get("submesh")
        if lane is None:
            continue
        row = lanes[lane]
        row["seconds"][str(rec.get("prev", "?"))] += float(
            rec.get("seconds", 0.0) or 0.0)
        row["transitions"] += 1
        row["last_state"] = rec.get("state")
    return {k: {"seconds": dict(v["seconds"]),
                "transitions": v["transitions"],
                "last_state": v["last_state"]}
            for k, v in sorted(lanes.items(), key=lambda kv: str(kv[0]))}


def lane_seconds_from_samples(samples: list) -> dict:
    """lane -> {state: seconds} from the LAST persisted
    ``tts_lane_seconds_total`` counters per writer (counters are
    cumulative; the final sample of a lifetime carries its total).
    Multiple writers (a fleet store / restarts resuming the counter)
    take the per-(writer, lane, state) max, then the max across
    writers — a resumed counter already includes its predecessor."""
    per = {}     # (writer, lane, state) -> value (last wins)
    for s in samples:
        w = s.get("w", "?")
        for name, labels, value in s.get("counters") or []:
            if name != LANE_COUNTER or not isinstance(labels, dict):
                continue
            key = (w, labels.get("lane"), labels.get("state"))
            per[key] = float(value)
    out = collections.defaultdict(dict)
    for (_w, lane, state), v in per.items():
        cur = out[lane].get(state)
        if cur is None or v > cur:
            out[lane][state] = v
    return {k: out[k] for k in sorted(out, key=str)}


def class_utilization(samples: list) -> dict:
    """(shape, tenant) -> last-known ρ gauge value."""
    out = {}
    for s in samples:
        for name, labels, value in s.get("gauges") or []:
            if name != UTIL_GAUGE or not isinstance(labels, dict):
                continue
            out[(labels.get("shape", "?"),
                 labels.get("tenant", "?"))] = float(value)
    return out


def _lane_table(title: str, lanes: dict) -> list:
    lines = [title]
    for lane, row in lanes.items():
        secs = row.get("seconds", row)
        total = sum(secs.values())
        ex = secs.get("executing", 0.0)
        states = "  ".join(f"{k}={secs[k]:.2f}s"
                           for k in sorted(secs, key=lambda k: -secs[k]))
        extra = ""
        if isinstance(row, dict) and "transitions" in row:
            extra = (f"  transitions={row['transitions']}"
                     f"  last={row['last_state']}")
        frac = (ex / total * 100.0) if total > 0 else 0.0
        lines.append(f"  lane {lane}: exec={frac:5.1f}% "
                     f"total={total:.2f}s  [{states}]{extra}")
    if len(lines) == 1:
        lines.append("  (none)")
    return lines


def report(path: str, as_json: bool = False) -> str:
    events, samples = load(path)
    ev_lanes = lane_seconds_from_events(events)
    ct_lanes = lane_seconds_from_samples(samples)
    classes = class_utilization(samples)
    if as_json:
        return json.dumps({
            "path": path,
            "lane_events": ev_lanes,
            "lane_counters": ct_lanes,
            "class_utilization": {
                f"{shape}/{tenant}": v
                for (shape, tenant), v in sorted(classes.items())},
        }, indent=1)
    lines = [f"# capacity report: {path}",
             f"# {len(events)} event(s), {len(samples)} sample(s)"]
    lines += _lane_table("lane state seconds (from lane.state "
                         "transitions — closed intervals only):",
                         ev_lanes)
    if samples:
        lines += _lane_table(
            "persisted lane counters (tts_lane_seconds_total, "
            "survives kill -9):",
            {k: {"seconds": v} for k, v in ct_lanes.items()})
        lines.append("last-known shape-class utilization "
                     "(tts_capacity_utilization):")
        for (shape, tenant), v in sorted(classes.items()):
            lines.append(f"  {shape} tenant={tenant}: rho={v:.3f}")
        if not classes:
            lines.append("  (none)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="lane utilization & capacity report from a trace "
                    "artifact (JSONL / Chrome JSON / durable store)")
    ap.add_argument("path", help="trace file or store directory")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    print(report(args.path, as_json=args.json))
    return 0


if __name__ == "__main__":
    sys.exit(main())
