"""Micro-benchmark: TPU gather formulations at the LB2 step's exact
compaction shapes (ta021, chunk 32768: N = 655,360 child slots).

The round-3 step profile (BENCHMARKS.md) pins 2.56 ms of the 6.83 ms
LB2 step in six column gathers over feature-major (rows, N) blocks —
~17 GB/s effective, 2% of v5e HBM bandwidth, because gathering along
the minor (lane) axis is element/latency-bound on TPU. This tool
measures the alternatives before the engine commits to one:

  fm   jnp.take(src (rows, N) i32, idx, axis=1)   [current engine path]
  rm   jnp.take(src (N, rows) i32, idx, axis=0)   row-major: each
       gathered row is a contiguous rows*4B run (DMA-friendly)
  rmT  rm + transpose of the (t, rows) result back to feature-major
       (what the engine would actually pay, since the sweeps and the
       pool are feature-major)
  fmT  transpose src to (N, rows) on the fly, rm gather, transpose back
       (no engine refactor needed — pays 2 transposes per gather)

Timing: each variant runs inside ONE compiled fori_loop (the ~190 ms
remote-tunnel dispatch floor would swamp per-call timing); the gathered
block is reduced into the carry so XLA cannot hoist the gather, and the
index vector is rolled by the loop counter so iterations are not CSE'd.
"""

import time

import jax
import jax.numpy as jnp
import numpy as np

ITERS = 200


def _time_loop(fn, *args, iters=ITERS):
    @jax.jit
    def loop(args):
        def body(i, carry):
            acc, args = carry
            out = fn(i, *args)
            return acc + out, args
        acc0 = jnp.zeros((), jnp.int32)
        acc, _ = jax.lax.fori_loop(0, iters, body, (acc0, args))
        return acc

    out = loop(args).block_until_ready()  # compile + warm
    t0 = time.perf_counter()
    out = loop(args).block_until_ready()
    dt = time.perf_counter() - t0
    return dt / iters * 1e3, int(out)  # ms per iteration


def bench_shape(rows, srcN, t, label):
    rng = np.random.default_rng(0)
    src_fm = jnp.asarray(rng.integers(0, 1000, (rows, srcN), np.int32))
    src_rm = jnp.asarray(np.ascontiguousarray(np.asarray(src_fm).T))
    # replace=True: round-1 regathers index chunk-wide parents from
    # N/4 child slots, so indices repeat (children share parents)
    idx = jnp.asarray(np.sort(rng.choice(srcN, t, replace=True))
                      .astype(np.int32))

    def vary(i, ix):
        # cheap per-iteration perturbation (defeats CSE/hoisting);
        # stays in-range, preserves sortedness shape-wise
        return jax.lax.optimization_barrier((ix + i) % srcN)

    def g_fm(i, src, ix):
        out = jnp.take(src, vary(i, ix), axis=1)
        return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

    def g_rm(i, src, ix):
        out = jnp.take(src, vary(i, ix), axis=0)
        return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

    def g_rmT(i, src, ix):
        out = jnp.take(src, vary(i, ix), axis=0)
        out = jax.lax.optimization_barrier(out).T
        return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

    def g_fmT(i, src, ix):
        srcT = jax.lax.optimization_barrier(src.T)
        out = jnp.take(srcT, vary(i, ix), axis=0)
        out = jax.lax.optimization_barrier(out).T
        return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

    res = {}
    for name, fn, args in (("fm", g_fm, (src_fm, idx)),
                           ("rm", g_rm, (src_rm, idx)),
                           ("rmT", g_rmT, (src_rm, idx)),
                           ("fmT", g_fmT, (src_fm, idx))):
        ms, _ = _time_loop(fn, *args)
        res[name] = ms
    gb = rows * t * 4 / 1e9
    print(f"{label:34s} rows={rows:3d} srcN={srcN:7d} t={t:7d}  "
          + "  ".join(f"{k}={v:7.3f}ms ({gb / (v / 1e3):5.1f}GB/s)"
                      for k, v in res.items()))
    return res


def bench_src_width(rows, srcN, t, label, dtype=jnp.int32):
    """Direct fm gather cost vs allocated source width (cliff hunt)."""
    rng = np.random.default_rng(0)
    src = jnp.asarray(rng.integers(0, 1000, (rows, srcN))
                      .astype(np.int32)).astype(dtype)
    idx = jnp.asarray(np.sort(rng.choice(srcN, t, replace=True))
                      .astype(np.int32))

    def g(i, src, ix):
        ix = jax.lax.optimization_barrier((ix + i) % srcN)
        out = jnp.take(src, ix, axis=1)
        return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

    ms, _ = _time_loop(g, src, idx)
    mb = rows * srcN * src.dtype.itemsize / 1e6
    print(f"{label:34s} rows={rows:3d} srcN={srcN:7d} ({mb:6.1f}MB) "
          f"t={t:7d}  {ms:7.3f}ms  {ms / t * 1e6:6.1f}ns/idx")
    return ms


def main():
    J, M, B = 20, 20, 32768
    N = B * J
    print(f"devices: {jax.devices()}")
    # round-1 regather sources are chunk-wide (parents)
    bench_shape(J + M + 1, B, N // 4, "round1 regather (parents)")
    # round-2 mid-compaction: children+aux_plus over N-wide blocks
    bench_shape(J + M + 3, N, 3 * N // 32, "round2 mid-compaction")
    # round-3 final compaction
    bench_shape(J + M + 1, N, N // 16, "round3 final compaction")
    # sensitivity: single wide gather at round-1 width over N-wide source
    bench_shape(J + M + 1, N, N // 4, "N-wide source at N/4")

    print("\n--- source-width cliff (fm gather, fixed t=61440) ---")
    for s in (32768, 65536, 98304, 131072, 163840, 327680, 655360):
        bench_src_width(41, s, 61440, f"src width {s}")

    print("\n--- row scaling (srcN=655360, t=61440) ---")
    for r in (1, 2, 8, 21, 41):
        bench_src_width(r, N, 61440, f"rows {r}")

    print("\n--- 1-row (N,)-source composition takes ---")
    for t in (40960, 61440, 163840, 655360):
        bench_src_width(1, N, t, f"compose t={t}")

    print("\n--- dtype effect (rows=20, srcN=655360, t=61440) ---")
    bench_src_width(20, N, 61440, "i32", jnp.int32)
    bench_src_width(20, N, 61440, "i16", jnp.int16)

    print("\n--- chunk-wide source, t scaling (rows=41, srcN=32768) ---")
    for t in (40960, 61440, 163840):
        bench_src_width(41, B, t, f"parents t={t}")

    print("\n--- slice-then-gather from N-wide source (the engine fix) ---")
    rng = np.random.default_rng(1)
    for rows, s, t in ((43, N // 4, 3 * N // 32), (41, 3 * N // 32, N // 16),
                       (43, N // 4, N // 4), (41, N // 16, N // 16)):
        src = jnp.asarray(rng.integers(0, 1000, (rows, N), np.int32))
        idx = jnp.asarray(np.sort(rng.choice(s, t, replace=True))
                          .astype(np.int32))

        def g(i, src, ix, s=s):
            ix = jax.lax.optimization_barrier((ix + i) % s)
            sub = jax.lax.optimization_barrier(
                jax.lax.slice(src, (0, 0), (src.shape[0], s)))
            out = jnp.take(sub, ix, axis=1)
            return jax.lax.optimization_barrier(out).sum(dtype=jnp.int32)

        ms, _ = _time_loop(g, src, idx)
        print(f"slice N->{s:7d} t={t:7d} rows={rows}   {ms:7.3f}ms")


if __name__ == "__main__":
    main()
