"""Generate DEEP wide-instance goldens from the reference oracle.

The in-tree reference mains only take Taillard ids, whose 50-job
instances at ub=opt are either pruned at the root (trees of 0-3 nodes —
the round-2 "wide goldens") or explode past 2^31 nodes (ta031). This
script crafts synthetic 40-50-job instances whose trees land in the
10^4..10^6 range at a FIXED valid ub (the identity schedule's makespan —
any fixed ub makes the explored set traversal-order invariant, which is
the property the parity tests need; it does not have to be the optimum),
then goldens them against the REFERENCE's own decompose/lb2_bound driven
through the matrix-input wrapper main (.ref_build/wrap/pfsp/pfsp_mat.c,
compiled with MAX_JOBS=50 per the reference's own recipe).

Writes tests/golden/pfsp_lb2_matrix.jsonl: one JSON per line with the
matrix inline plus the reference counts.

    python tools/gen_matrix_goldens.py [--wrapper PATH] [--max-cases 3]
"""

import argparse
import json
import os
import subprocess
import sys
import tempfile

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_tree_search import native  # noqa: E402

WRAPPER = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), ".ref_build", "wrap", "pfsp",
    "pfsp_mat.out")


def identity_makespan(p):
    m, n = p.shape
    front = np.zeros(m, np.int64)
    for j in range(n):
        acc = 0
        for k in range(m):
            acc = max(acc, front[k]) + p[k, j]
            front[k] = acc
    return int(front[-1])


def reference_counts(wrapper, p, lb, ub):
    with tempfile.NamedTemporaryFile("w", suffix=".mat", delete=False) as f:
        f.write(f"{p.shape[0]} {p.shape[1]}\n")
        for row in p:
            f.write(" ".join(map(str, row)) + "\n")
        path = f.name
    try:
        out = subprocess.run([wrapper, path, str(lb), str(ub)],
                             capture_output=True, text=True, timeout=600,
                             check=True)
    finally:
        os.unlink(path)
    line = [ln for ln in out.stdout.splitlines()
            if ln.startswith("GOLDEN ")][0]
    tree, sol, best = line.split()[1:]
    return int(tree), int(sol), int(best)


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--wrapper", default=WRAPPER)
    ap.add_argument("--max-cases", type=int, default=3)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "tests", "golden", "pfsp_lb2_matrix.jsonl"))
    args = ap.parse_args()

    if not os.path.exists(args.wrapper):
        raise SystemExit(
            f"{args.wrapper} missing — compile it first:\n"
            "  cd .ref_build/wrap/pfsp && gcc -O3 -o pfsp_mat.out "
            "pfsp_mat.c lib/PFSP_lib.c lib/Pool_atom.c lib/PFSP_node.c "
            "lib/c_bound_simple.c lib/c_bound_johnson.c lib/c_taillard.c "
            "-lm")

    from tpu_tree_search.problems import taillard

    cases = []
    # REAL Taillard wide instances across the three wide code paths:
    # ta033 (50x5: P=10, one-shot dense LB2 + 2-word mask), ta041
    # (50x10: P=45), ta051 (50x20: P=190, strong-pair prefilter +
    # 2-word mask). At ub=opt these trees are 0-3 nodes or billions
    # (measured: every ta032-ta050 at ub=opt is one or the other), so
    # BISECT a fixed valid ub between 1 and the published makespan to
    # land the tree in [1e4, 1.2e5] — the parity invariant only needs a
    # FIXED ub, not the optimum (the search then proves no schedule
    # beats it, driving the same decompose/bound code to depth).
    # 50x20 instances: the one wide class whose tree-vs-ub landscape has
    # a usable gradient (every 50x5 / 50x10 instance probed jumps from
    # <300 nodes to >3M in one ub step — the weak-bound classes
    # degenerate to near-exhaustive top levels the moment the root
    # survives). Three instances cover the prefilter + 2-word-mask path
    # at depth; the few-pair dense path keeps its root-level goldens +
    # unit tests.
    CAP = 130_000
    for inst in (51, 52, 53):
        p = np.asarray(taillard.processing_times(inst), np.int32)
        jobs, machines = p.shape[1], p.shape[0]
        lo, hi = 1, int(taillard.optimal_makespan(inst))
        hit = None
        for _ in range(18):
            ub = (lo + hi) // 2
            tree, sol, best, expanded = native.search(
                p, lb_kind=2, init_ub=ub, max_nodes=CAP)
            print(f"# ta{inst:03d} ub={ub}: tree={tree} "
                  f"expanded={expanded}", flush=True)
            if expanded >= CAP or tree >= 120_000:
                hi = ub
            elif tree < 10_000:
                lo = ub
            else:
                hit = (ub, tree, sol, best)
                break
            if hi - lo <= 1:
                break
        if hit is None:
            # the tree-vs-ub landscape CLIFFS on some instances (ta033:
            # 1 node at ub=2601, >130k at 2602) — probe the big side of
            # the cliff once with a wider cap and take it if <= 1e6
            tree, sol, best, expanded = native.search(
                p, lb_kind=2, init_ub=hi, max_nodes=3_000_000)
            print(f"# ta{inst:03d} cliff ub={hi}: tree={tree} "
                  f"expanded={expanded}", flush=True)
            if expanded < 3_000_000 and 10_000 <= tree <= 2_900_000:
                hit = (hi, tree, sol, best)
        if hit is None:
            print(f"# ta{inst:03d}: no ub landed in the window, skipped",
                  flush=True)
            continue
        ub, tree, sol, best = hit
        rt, rs, rb = reference_counts(args.wrapper, p, 2, ub)
        assert (rt, rs, rb) == (tree, sol, best), (
            f"native disagrees with reference on ta{inst:03d}: "
            f"native=({tree},{sol},{best}) ref=({rt},{rs},{rb})")
        cases.append({
            "jobs": jobs, "machines": machines, "seed": inst,
            "ub": ub, "tree": rt, "sol": rs, "best": rb,
            "p": p.flatten().tolist(),
        })
        print(f"ta{inst:03d} ({jobs}x{machines}): tree={rt} sol={rs} "
              f"best={rb} (fixed ub={ub})", flush=True)
        if len(cases) >= args.max_cases:
            break

    if len(cases) < 2:
        raise SystemExit("fewer than 2 qualifying cases; widen the sweep")
    with open(args.out, "w") as f:
        for c in cases:
            f.write(json.dumps(c) + "\n")
    print(f"wrote {len(cases)} cases to {args.out}")


if __name__ == "__main__":
    main()
