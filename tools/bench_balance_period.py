"""Ground the balance-period default with ON-CHIP cost data.

VERDICT r4 #9: the round-3 sensitivity table measured balance_period on
the virtual CPU mesh, where collectives serialize on the host — its
wall-clock preference for sparse periods (16 beat 4 by 1.7x) is an
artifact of that backend, and the default was never defended.

This tool prices the period where it matters: the per-iteration cost of
the FULL SPMD program (build_dist_loop on a 1-chip mesh) at each
period, on IDENTICAL warmed state and windows (the same-state method of
tools/bench_spmd_tax.py — both prior methodologies documented there
gave garbage). The spread side of the tradeoff (per-worker tree CV vs
period) is backend-independent and comes from the round-3 CPU-mesh
table; this measurement supplies the missing cost side.

    python tools/bench_balance_period.py [--inst 21] [--lb 2]
"""

import argparse
import functools
import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tpu_tree_search.utils import compile_cache  # noqa: E402

compile_cache.enable()

import jax  # noqa: E402
import jax.numpy as jnp  # noqa: E402

from tpu_tree_search.engine import device, distributed  # noqa: E402
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.parallel.mesh import worker_mesh  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--lb", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--warm", type=int, default=500)
    ap.add_argument("--iters", type=int, default=256)
    ap.add_argument("--periods", type=int, nargs="*",
                    default=[1, 2, 4, 8, 16, 64])
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    jobs, machines = p.shape[1], p.shape[0]
    chunk, lb = args.chunk, args.lb

    state = device.init_state(jobs, args.capacity, ub, p_times=p)
    state = device.run(tables, state, lb, chunk, max_iters=args.warm)
    state.size.block_until_ready()
    assert not bool(state.overflow) and int(state.size) > 0
    target = int(state.iters) + args.iters
    stacked = tuple(x[None] for x in state)

    adt = device.aux_dtype(p)
    tc = distributed.default_transfer_cap(chunk, jobs, machines, 1,
                                          aux_itemsize=adt.itemsize)
    limit = min(device.row_limit(args.capacity, chunk, jobs),
                args.capacity - tc)

    def mls(t, lim):
        return functools.partial(device.step, t, lb, chunk, limit=lim)

    rows = []
    for period in args.periods:
        loop = distributed.build_dist_loop(worker_mesh(1), tables, mls,
                                           period, tc, 2 * chunk, limit)

        def call():
            out = loop(tables, jnp.int64(target),
                       jnp.int32(distributed.I32_MAX), *stacked)
            jax.block_until_ready(out)

        call()  # compile+warm at the final signature
        best = float("inf")
        for _ in range(args.repeats):
            t0 = time.perf_counter()
            call()
            best = min(best, time.perf_counter() - t0)
        ms = best / args.iters * 1e3
        rows.append({"balance_period": period,
                     "ms_per_iter": round(ms, 4)})
        print(json.dumps(rows[-1]), flush=True)

    print(json.dumps({"inst": args.inst, "lb": lb, "chunk": chunk,
                      "window_iters": args.iters,
                      "rows": rows,
                      "note": "identical warmed state across periods"}))


if __name__ == "__main__":
    main()
