"""Ground the balance-period default with ON-CHIP cost data.

VERDICT r4 #9: the round-3 sensitivity table measured balance_period on
the virtual CPU mesh, where collectives serialize on the host — its
wall-clock preference for sparse periods (16 beat 4 by 1.7x) is an
artifact of that backend, and the default was never defended.

This tool prices the period where it matters: the per-iteration cost of
the FULL SPMD program at each period, on IDENTICAL warmed state and
windows. The measurement harness itself now lives in
tpu_tree_search/tune/probe.py (ProbeHarness / measure_balance_periods)
— the SAME warmed same-state method the offline Autotuner's probes
run, so this sweep and the tuner can never measure different things;
this file is the thin CLI that survives for operators who want the
hand-run sweep. The spread side of the tradeoff (per-worker tree CV vs
period) is backend-independent and comes from the round-3 CPU-mesh
table (BENCHMARKS.md); this measurement supplies the cost side.

    python tools/bench_balance_period.py [--inst 21] [--lb 2]
"""

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_tree_search.utils import compile_cache  # noqa: E402

compile_cache.enable()

from tpu_tree_search.problems import taillard  # noqa: E402
from tpu_tree_search.tune.probe import measure_balance_periods  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--lb", type=int, default=2)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--capacity", type=int, default=1 << 22)
    ap.add_argument("--warm", type=int, default=500)
    ap.add_argument("--iters", type=int, default=256)
    ap.add_argument("--periods", type=int, nargs="*",
                    default=[1, 2, 4, 8, 16, 64])
    ap.add_argument("--repeats", type=int, default=3)
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    rows = measure_balance_periods(
        p, args.lb, args.chunk, args.periods, capacity=args.capacity,
        warm_iters=args.warm, window_iters=args.iters,
        repeats=args.repeats, init_ub=ub)
    for row in rows:
        print(json.dumps(row), flush=True)
    print(json.dumps({"inst": args.inst, "lb": args.lb,
                      "chunk": args.chunk,
                      "window_iters": args.iters,
                      "rows": rows,
                      "note": "identical warmed state across periods "
                              "(tune/probe.ProbeHarness)"}))


if __name__ == "__main__":
    main()
