"""Exclusive (self-time) op profile from a jax.profiler Chrome trace.

Chrome-trace 'X' events in the device 'XLA Ops' lane nest by timestamp
containment (control-flow ops like while/conditional span their bodies).
Summing raw durations double-counts; this computes each op's SELF time
(duration minus directly-contained children) and aggregates by op name.

    python tools/trace_selftime.py /tmp/tts_trace_lb2 [--top 40]
"""

import argparse
import collections
import glob
import gzip
import json
import os


def load(log_dir):
    paths = glob.glob(os.path.join(
        log_dir, "plugins", "profile", "*", "*.trace.json.gz"))
    ev = []
    for p in paths:
        with gzip.open(p, "rt") as f:
            ev.extend(json.load(f).get("traceEvents", []))
    return ev


def self_times(events, lane="XLA Ops"):
    tn = {}
    for e in events:
        if e.get("ph") == "M" and e.get("name") == "thread_name":
            tn[(e["pid"], e["tid"])] = e["args"]["name"]
    # nesting is only meaningful within one (pid, tid) lane — group
    # first so multi-core traces don't cross-attribute children
    lanes = collections.defaultdict(list)
    for e in events:
        if (e.get("ph") == "X" and "dur" in e
                and tn.get((e.get("pid"), e.get("tid"))) == lane):
            lanes[(e["pid"], e["tid"])].append(e)
    self_us = collections.Counter()
    counts = collections.Counter()
    for xs in lanes.values():
        # sort by start asc, duration desc so parents precede children
        xs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []  # (end_ts, name) of open enclosing events
        for e in xs:
            ts, dur, name = e["ts"], e["dur"], e["name"]
            while stack and stack[-1][0] <= ts:
                stack.pop()
            self_us[name] += dur
            counts[name] += 1
            if stack:
                self_us[stack[-1][1]] -= dur
            stack.append((ts + dur, name))
    return self_us, counts


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--iters", type=int, default=None,
                    help="divide totals by this many loop iterations")
    args = ap.parse_args()
    self_us, counts = self_times(load(args.logdir))
    total = sum(self_us.values())
    print(f"total device self-time: {total/1e3:.2f} ms"
          + (f"  ({total/1e3/args.iters:.3f} ms/iter)" if args.iters
             else ""))
    hdr = f"{'self_ms':>10} {'ms/iter':>8} {'count':>6}  name"
    print(hdr)
    for name, s in self_us.most_common(args.top):
        per = f"{s/1e3/args.iters:8.3f}" if args.iters else " " * 8
        print(f"{s/1e3:10.2f} {per} {counts[name]:6d}  {name[:100]}")


if __name__ == "__main__":
    main()
