"""Exclusive (self-time) op profile from a jax.profiler Chrome trace.

Thin CLI over :mod:`tpu_tree_search.obs.chrome_trace`, which owns the
trace parsing (it used to live here privately; tools/profile_step.py and
tools/validate_attribution.py now share the same implementation).
Chrome-trace 'X' events in the device 'XLA Ops' lane nest by timestamp
containment; this prints each op's SELF time (duration minus
directly-contained children) aggregated by op name.

    python tools/trace_selftime.py /tmp/tts_trace_lb2 [--top 40]
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_tree_search.obs.chrome_trace import (load_xla_trace,  # noqa: E402
                                              self_times)

# backward-compatible aliases (this module WAS the implementation)
load = load_xla_trace


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("logdir")
    ap.add_argument("--top", type=int, default=40)
    ap.add_argument("--iters", type=int, default=None,
                    help="divide totals by this many loop iterations")
    args = ap.parse_args()
    self_us, counts = self_times(load_xla_trace(args.logdir))
    total = sum(self_us.values())
    print(f"total device self-time: {total/1e3:.2f} ms"
          + (f"  ({total/1e3/args.iters:.3f} ms/iter)" if args.iters
             else ""))
    hdr = f"{'self_ms':>10} {'ms/iter':>8} {'count':>6}  name"
    print(hdr)
    for name, s in self_us.most_common(args.top):
        per = f"{s/1e3/args.iters:8.3f}" if args.iters else " " * 8
        print(f"{s/1e3:10.2f} {per} {counts[name]:6d}  {name[:100]}")


if __name__ == "__main__":
    main()
