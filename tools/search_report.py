"""Per-segment search-telemetry table from a flight-recorder trace —
and self-time attribution from an on-demand profiler capture.

Given a FILE, reads any trace artifact (the JSONL event log, the
Chrome trace-event JSON, or a durable-store ``obs-*.jsonl`` segment —
same detection as tools/trace_summary.py; a store directory works too
and renders the per-journey tables) and
folds the ``search.telemetry`` events the segmented engine driver emits
when TTS_SEARCH_TELEMETRY / --search-telemetry is on
(engine/checkpoint.run_segmented; the on-device block itself is
engine/telemetry.py) into two tables:

- **pruning efficiency**: one row per (request, segment) — nodes
  popped/branched/pruned that segment, the pruning rate, the mean
  relative frontier depth (0 = root, 1 = leaves), live pool size,
  steal flow and the incumbent;
- **load imbalance**: for distributed segments (the event carries
  per-worker eval deltas), min/max/mean evals per worker and the
  max/mean imbalance factor — the starved-worker view the reference's
  boxplot stats print per pool;
- **segment gaps**: device idle between consecutive ``segment`` spans
  (dispatch -> results-ready intervals; needs no telemetry flag) —
  run it on a TTS_OVERLAP=0 and a TTS_OVERLAP=1 trace of the same
  workload and the table IS the overlap win: the gap column collapses
  to ~0 when the pipelined driver dispatches ahead of the fetch.

Given a DIRECTORY — an XLA profiler artifact, i.e. what
``POST /profile``, the `profile` CLI subcommand or
tools/profile_step.py leave behind — it renders the **self-time
attribution** instead: per-op device self-times
(obs/chrome_trace.self_times, control-flow nesting excluded) folded
into the step's phase buckets, so the hardware-side view lands next to
the search-side counter lanes.

    python tools/search_report.py /tmp/tts-trace.jsonl
    python tools/search_report.py /tmp/tts-trace.chrome.json
    python tools/search_report.py /tmp/profiles/capture-.../   # XLA dir

Doubles as the CI artifact renderer: the telemetry CI leg uploads this
table next to the serve-session traces (tests/test_telemetry.py writes
the trace, the workflow runs this on it).
"""

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(
    0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from trace_summary import (journeys_from_store,  # noqa: E402
                           load_records, render_journeys)

TELEMETRY_EVENT = "search.telemetry"
SEGMENT_SPAN = "segment"


def fold(records: list[dict]) -> dict[str, list[dict]]:
    """search.telemetry events grouped by request id ('-' when the run
    was not served), in (ts, segment) order."""
    out: dict[str, list[dict]] = {}
    for r in sorted(records, key=lambda r: (r.get("ts", 0.0),
                                            r.get("seq", 0))):
        if r.get("name") != TELEMETRY_EVENT:
            continue
        out.setdefault(str(r.get("request_id") or "-"), []).append(r)
    return out


def _imbalance(evals_pw: list) -> tuple[float, float, float, float]:
    n = max(len(evals_pw), 1)
    mean = sum(evals_pw) / n
    return (min(evals_pw, default=0), max(evals_pw, default=0), mean,
            (max(evals_pw, default=0) / mean) if mean > 0 else 0.0)


def render(groups: dict[str, list[dict]]) -> str:
    hdr = (f"{'request':<10} {'seg':>4} {'popped':>9} {'branched':>9} "
           f"{'pruned':>9} {'prune%':>7} {'frontier':>8} {'pool':>9} "
           f"{'steal s/r':>11} {'best':>7}")
    lines = ["pruning efficiency (per segment)", hdr, "-" * len(hdr)]
    imb_rows = []
    for rid in sorted(groups):
        for r in groups[rid]:
            lines.append(
                f"{rid:<10} {r.get('segment', 0):>4} "
                f"{r.get('popped', 0):>9} {r.get('branched', 0):>9} "
                f"{r.get('pruned', 0):>9} "
                f"{100.0 * float(r.get('pruning_rate', 0.0)):>6.1f}% "
                f"{float(r.get('frontier_depth', 0.0)):>8.3f} "
                f"{r.get('pool', 0):>9} "
                f"{str(r.get('steal_sent', 0)) + '/' + str(r.get('steal_recv', 0)):>11} "
                f"{r.get('best', 0):>7}")
            if r.get("evals_pw"):
                imb_rows.append((rid, r))
    if imb_rows:
        hdr2 = (f"{'request':<10} {'seg':>4} {'workers':>7} "
                f"{'min_evals':>10} {'max_evals':>10} {'mean':>10} "
                f"{'max/mean':>8}")
        lines += ["", "load imbalance (per-worker evals per segment)",
                  hdr2, "-" * len(hdr2)]
        for rid, r in imb_rows:
            lo, hi, mean, factor = _imbalance(r["evals_pw"])
            lines.append(
                f"{rid:<10} {r.get('segment', 0):>4} "
                f"{len(r['evals_pw']):>7} {int(lo):>10} {int(hi):>10} "
                f"{mean:>10.1f} {factor:>8.2f}")
    n_seg = sum(len(v) for v in groups.values())
    lines.append("")
    lines.append(f"{len(groups)} run(s), {n_seg} telemetry segment(s)")
    return "\n".join(lines)


def segment_gaps(records: list[dict]) -> dict[str, dict]:
    """Device-idle gaps between consecutive ``segment`` spans, grouped
    by request id ('-' for unserved runs).

    A segment span covers [dispatch, results-ready]; the gap between
    span N's end and span N+1's start is time the device waited on the
    host (heartbeat, checkpoint write, stop checks). With TTS_OVERLAP
    the next dispatch lands BEFORE the previous results return, so
    consecutive spans overlap and the gap clamps to 0 — running this
    table on a before/after pair of traces is the overlap win, measured.
    """
    spans: dict[str, list[dict]] = {}
    for r in records:
        if r.get("name") == SEGMENT_SPAN and "dur" in r:
            spans.setdefault(str(r.get("request_id") or "-"),
                             []).append(r)
    out: dict[str, dict] = {}
    for rid, ss in spans.items():
        ss.sort(key=lambda r: (float(r.get("ts", 0.0)),
                               r.get("segment", 0)))
        gaps = []
        for prev, cur in zip(ss, ss[1:]):
            end = float(prev["ts"]) + float(prev.get("dur", 0.0))
            gaps.append(max(0.0, float(cur["ts"]) - end))
        busy = sum(float(r.get("dur", 0.0)) for r in ss)
        out[rid] = {
            "segments": len(ss),
            "overlapped": sum(1 for r in ss if r.get("overlapped")),
            "busy_s": busy,
            "gap_total_s": sum(gaps),
            "gap_mean_ms": (1e3 * sum(gaps) / len(gaps)) if gaps else 0.0,
            "gap_max_ms": 1e3 * max(gaps, default=0.0),
            "gap_share": (sum(gaps) / (busy + sum(gaps))
                          if busy + sum(gaps) > 0 else 0.0),
        }
    return out


def render_gaps(gaps: dict[str, dict]) -> str:
    hdr = (f"{'request':<10} {'segs':>5} {'ovl':>4} {'busy_s':>9} "
           f"{'gap_s':>8} {'gap_mean':>9} {'gap_max':>9} {'idle%':>6}")
    lines = ["", "segment gaps (device idle between segment spans; "
                 "~0 with TTS_OVERLAP)", hdr, "-" * len(hdr)]
    for rid in sorted(gaps):
        g = gaps[rid]
        lines.append(
            f"{rid:<10} {g['segments']:>5} {g['overlapped']:>4} "
            f"{g['busy_s']:>9.3f} {g['gap_total_s']:>8.3f} "
            f"{g['gap_mean_ms']:>7.1f}ms {g['gap_max_ms']:>7.1f}ms "
            f"{100.0 * g['gap_share']:>5.1f}%")
    return "\n".join(lines)


def render_selftime(log_dir: str, top: int = 20) -> str | None:
    """Self-time attribution table from an XLA profiler artifact dir
    (None when the directory holds no parseable trace)."""
    from tpu_tree_search.obs.chrome_trace import (bucket_of,
                                                  bucketed_self_times,
                                                  load_xla_trace,
                                                  self_times)
    events = load_xla_trace(log_dir)
    if not events:
        return None
    self_us, counts = self_times(events)
    total = sum(self_us.values())
    if total <= 0:
        return None
    lines = [f"self-time attribution ({log_dir})",
             f"device self-time total: {total / 1e3:.2f} ms", "",
             f"{'bucket':<16} {'self_ms':>10} {'share':>7}",
             "-" * 36]
    for bucket, us in bucketed_self_times(self_us).most_common():
        lines.append(f"{bucket:<16} {us / 1e3:>10.2f} "
                     f"{100.0 * us / total:>6.1f}%")
    lines += ["", f"top {top} ops by device self-time:",
              f"{'self_ms':>10} {'count':>6}  {'bucket':<16} name",
              "-" * 70]
    for name, us in self_us.most_common(top):
        lines.append(f"{us / 1e3:>10.2f} {counts[name]:>6}  "
                     f"{bucket_of(name):<16} {str(name)[:80]}")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="per-segment pruning-efficiency / load-imbalance "
                    "table from a flight-recorder trace (JSONL or "
                    "Chrome JSON) with search telemetry enabled — or "
                    "self-time attribution from an XLA profiler "
                    "artifact directory (POST /profile, `profile`, "
                    "tools/profile_step.py)")
    ap.add_argument("trace", help="trace file path, or an XLA profiler "
                                  "artifact directory")
    ap.add_argument("--top", type=int, default=20,
                    help="ops listed in the self-time table")
    args = ap.parse_args(argv)
    if os.path.isdir(args.trace) and not glob.glob(
            os.path.join(args.trace, "obs-*.jsonl")):
        # no durable-store segments -> an XLA profiler artifact dir
        # (a store directory falls through to load_records below)
        table = render_selftime(args.trace, top=args.top)
        if table is None:
            print(f"error: no XLA trace events under {args.trace} "
                  "(expected plugins/profile/<run>/*.trace.json.gz, "
                  "or obs-*.jsonl store segments)",
                  file=sys.stderr)
            return 1
        print(table)
        return 0
    records = load_records(args.trace)
    if not records:
        print(f"error: no trace records in {args.trace}",
              file=sys.stderr)
        return 1
    groups = fold(records)
    gaps = segment_gaps(records)
    if not groups and not gaps:
        # the durable store persists the control-plane subset, not the
        # telemetry firehose: its report IS the per-journey view
        journeys = journeys_from_store(records)
        if journeys:
            print(render_journeys(journeys))
            return 0
        print(f"error: {len(records)} records but no "
              f"'{TELEMETRY_EVENT}' events or '{SEGMENT_SPAN}' spans "
              f"in {args.trace} — was the run started with "
              "TTS_SEARCH_TELEMETRY=1 / --search-telemetry, or "
              "segmented at all?", file=sys.stderr)
        return 1
    if groups:
        print(render(groups))
    else:
        print(f"# no '{TELEMETRY_EVENT}' events (TTS_SEARCH_TELEMETRY "
              "off) — segment-gap table only", file=sys.stderr)
    if gaps:
        print(render_gaps(gaps))
    return 0


if __name__ == "__main__":
    sys.exit(main())
