#!/usr/bin/env python
"""tts-lint CLI: run the repo's static invariant analyzers.

    python tools/tts_lint.py                  # human report, exit != 0
                                              # on any unwaived finding
    python tools/tts_lint.py --json out.json  # machine-readable report
    python tools/tts_lint.py --checkers knobs,metrics
    python tools/tts_lint.py --write-docs     # regenerate the README
                                              # knob/metric registry
                                              # tables, then lint

Checkers: trace_safety (host-sync/nondeterminism hazards reachable from
jit entry points), locks (guarded-by annotation discipline + lock-order
cycles), knobs (TTS_* single-sourcing in utils/config.py), metrics
(tts_* name registry reconciliation). See
tpu_tree_search/analysis/__init__.py and README.md "Static analysis".

Waivers: .tts-lint-waivers.json at the repo root maps a finding's
stable fingerprint to a WRITTEN reason. The CI lint leg runs this
script blocking — an unwaived finding fails the build.
"""

from __future__ import annotations

import argparse
import json
import pathlib
import sys

sys.path.insert(0, str(pathlib.Path(__file__).resolve().parents[1]))

from tpu_tree_search import analysis  # noqa: E402
from tpu_tree_search.analysis import docs  # noqa: E402


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        prog="tts_lint", description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("--root", default=None,
                    help="tree to lint (default: this checkout)")
    ap.add_argument("--json", metavar="PATH", default=None,
                    help="also write the JSON findings report here "
                         "('-' for stdout)")
    ap.add_argument("--checkers", default=None,
                    help="comma list: " + ",".join(analysis.CHECKERS))
    ap.add_argument("--write-docs", action="store_true",
                    help="regenerate the README generated registry "
                         "blocks before linting")
    args = ap.parse_args(argv)

    checkers = None
    if args.checkers:
        checkers = [c.strip() for c in args.checkers.split(",")
                    if c.strip()]
        unknown = set(checkers) - set(analysis.CHECKERS)
        if unknown:
            ap.error(f"unknown checker(s): {sorted(unknown)}")

    if args.write_docs:
        changed = docs.write_docs(args.root)
        print("regenerated README block(s): "
              + (", ".join(changed) if changed else "none (up to date)"))

    report = analysis.run_all(args.root, checkers=checkers)
    if args.json:
        payload = json.dumps(report.to_json(), indent=2, sort_keys=True)
        if args.json == "-":
            print(payload)
        else:
            pathlib.Path(args.json).write_text(payload + "\n")
            print(f"json report: {args.json}")
    print(report.render())
    return 0 if report.ok else 1


if __name__ == "__main__":
    sys.exit(main())
