"""Campaign driver: solve Taillard instances end-to-end on one chip,
with a per-instance wall budget and partial-progress reporting.

Generalizes tools/run_single_device_table.py (VERDICT r3 #7, the 20x20
table) to the reference's wider campaign groups (VERDICT r4 #1): the
50-job groups its intra-node driver enumerates
(/root/reference/pfsp/launch_scripts/mgpu_launch.sh:51-58 — ta031-ta050
and ta052/53/56/57/58) and any other instance list, at either bound.

Per instance: solve to the PROVEN optimum (ub=opt, pool drained) within
the budget, else stop at the budget and record the partial row — tree
so far, sustained pushed-nodes/s and eval rate — so infeasible
instances get a measured rate + extrapolation instead of silence.
Overflow grows the pool losslessly (checkpoint.grow) and continues.

    TTS_BUDGET_S=7200 nohup python -u tools/run_campaign.py 31 32 ... \
        > /tmp/campaign.log 2>&1 &

Env: TTS_BUDGET_S (default 7200), TTS_LB (default 2), TTS_CHUNK
(default 32768), TTS_CAMPAIGN_OUT (default /tmp/campaign.jsonl).
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tpu_tree_search.utils import compile_cache  # noqa: E402

compile_cache.enable()

import jax  # noqa: E402

from tpu_tree_search.engine import checkpoint, device  # noqa: E402
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402

OUT = os.environ.get("TTS_CAMPAIGN_OUT", "/tmp/campaign.jsonl")
LB = int(os.environ.get("TTS_LB", "2"))
CHUNK = int(os.environ.get("TTS_CHUNK", "32768"))
BUDGET_S = float(os.environ.get("TTS_BUDGET_S", "7200"))
SEG = int(os.environ.get("TTS_SEG", "2000"))


def fetch(state):
    vals = jax.device_get((state.iters, state.tree, state.sol, state.best,
                           state.size, state.evals, state.overflow))
    return [int(np.asarray(v).max()) for v in vals[:-1]] + \
        [bool(np.asarray(vals[-1]).any())]


def solve(inst: int, lb: int, budget_s: float) -> dict:
    p = taillard.processing_times(inst)
    ub = taillard.optimal_makespan(inst)
    m, jobs = p.shape
    tables = batched.make_tables(p)
    # pre-size: weak-bound classes peak in the tens of millions of live
    # rows; the floor covers the chunk*jobs scratch margin (row_limit).
    # TTS_CAPACITY overrides (the round-4 probes measured the 50x5 class
    # peaking just past the 1<<24 default — one avoidable grow cycle,
    # each a multi-GB pool fetch through the remote tunnel).
    capacity = int(os.environ.get("TTS_CAPACITY", "0")) or \
        max(device.default_capacity(jobs, m), 4 * CHUNK * jobs)
    state = device.init_state(jobs, capacity, ub, p_times=p)
    t0 = time.perf_counter()
    target = 0
    grows = 0
    last_hb = t0
    while True:
        target += SEG
        out = device.run(tables, state, lb, CHUNK, max_iters=target)
        iters, tree, sol, best, size, evals, overflow = fetch(out)
        now = time.perf_counter()
        if overflow:
            capacity *= 2
            grows += 1
            print(f"  [grow] capacity -> {capacity} (pool={size})",
                  flush=True)
            state = checkpoint.grow(out, capacity)
            target = iters  # next loop adds SEG on top of where we are
            continue
        state = out
        if now - last_hb > 30 or size == 0:
            print(f"  [seg] iters={iters} tree={tree} pool={size} "
                  f"best={best} t={now - t0:.1f}s", flush=True)
            last_hb = now
        if size == 0 or now - t0 > budget_s:
            break
    elapsed = time.perf_counter() - t0
    done = size == 0
    row = {"inst": inst, "jobs": jobs, "machines": m, "lb": lb,
           "done": done, "elapsed_s": round(elapsed, 2),
           "tree": tree, "sol": sol, "best": best, "evals": evals,
           "iters": iters, "capacity": capacity, "grows": grows,
           "pool_at_stop": size,
           "pushed_per_s": round(tree / elapsed, 1),
           "evals_per_s": round(evals / elapsed, 1)}
    if done:
        assert best == ub, (inst, best, ub)
    return row


def main():
    done = set()
    if os.path.exists(OUT):
        with open(OUT) as f:
            done = {(json.loads(ln)["inst"], json.loads(ln)["lb"])
                    for ln in f if ln.strip()}
    insts = [int(x) for x in sys.argv[1:]]
    for inst in insts:
        if (inst, LB) in done:
            print(f"ta{inst:03d} lb{LB}: already done, skipping",
                  flush=True)
            continue
        print(f"ta{inst:03d} lb{LB}: solving (budget {BUDGET_S:.0f}s)...",
              flush=True)
        try:
            row = solve(inst, LB, BUDGET_S)
        except AssertionError:
            # solve()'s best==optimum check: a WRONG ANSWER is never a
            # transient — abort the campaign loudly
            raise
        except Exception as e:
            # the remote tunnel occasionally drops a compile/execute
            # mid-flight (BENCHMARKS.md documents the stall/crash
            # classes); one fresh attempt, then move on so one bad
            # instance cannot eat the campaign
            print(f"ta{inst:03d} lb{LB}: attempt failed ({e}); "
                  "retrying once", flush=True)
            time.sleep(30)
            try:
                row = solve(inst, LB, BUDGET_S)
            except AssertionError:
                raise
            except Exception as e2:
                print(f"ta{inst:03d} lb{LB}: FAILED twice ({e2}); "
                      "skipping", flush=True)
                continue
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        tag = "SOLVED" if row["done"] else "partial"
        print(f"ta{inst:03d} lb{LB}: {tag} t={row['elapsed_s']}s "
              f"tree={row['tree']} pushed/s={row['pushed_per_s']}",
              flush=True)


if __name__ == "__main__":
    main()
