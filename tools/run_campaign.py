"""Campaign driver: solve Taillard instances end-to-end with a
per-instance compute budget, partial-progress reporting, and automatic
recovery.

TWO EXECUTION MODES:

- **serve (default)**: the campaign is the first client of the search
  service (tpu_tree_search/service/): ONE long-lived process submits
  every selected instance to an in-process SearchServer, polls, and
  writes the same JSONL rows. No per-instance process spin-up, and the
  executable cache compiles each (jobs x machines, lb, submesh) shape
  ONCE for the whole campaign instead of once per instance —
  `--submeshes K` additionally solves K instances concurrently on a
  partitioned mesh. Budget exhaustion maps to the service's DEADLINE
  state (checkpoint kept; a rerun with a larger TTS_BUDGET_S resumes
  it), and the legacy checkpoint naming is preserved, so in-flight
  legacy checkpoints resume under serve mode (elastically resharded).
- **--no-serve (DEPRECATED, kept for one release)**: the original
  process-per-instance supervisor below — worker subprocess per
  instance, heartbeat-age stall kill + respawn. Still the right tool
  when the device runtime itself is expected to wedge whole processes
  (the remote-TPU tunnel stalls it was built for); the serve path keeps
  everything in one process and cannot kill a truly hung dispatch.

Legacy architecture (--no-serve), per-instance wall budget and
AUTOMATIC STALL RECOVERY:

Generalizes tools/run_single_device_table.py (VERDICT r3 #7, the 20x20
table) to the reference's wider campaign groups (VERDICT r4 #1): the
50-job groups its intra-node driver enumerates
(/root/reference/pfsp/launch_scripts/mgpu_launch.sh:51-58 — ta031-ta050
and ta052/53/56/57/58) and any other instance list, at either bound.

Architecture (VERDICT r4 #8): each instance runs in a WORKER SUBPROCESS
that heartbeats a JSON status line per segment and checkpoints every
--checkpoint-every segments; the supervisor in this process watches the
heartbeat age and, when it exceeds ~4x the recent segment pace (a hung
device dispatch — the ~600 s tunnel stalls BENCHMARKS.md documents), kills
the worker's process group and respawns it resuming from the last
checkpoint. Search determinism (fixed chunk, DFS order) makes the
redo-from-checkpoint lossless: final counters are bit-identical to an
unkilled run (tests/test_dist_durability.py::test_supervisor_stall_resume).
The reference's only stall tooling is a 10 s "Still Idle" print
(pfsp_dist_multigpu_cuda.c:663-668) — it never recovers.

Per instance: solve to the PROVEN optimum (ub=opt by default, pool
drained) within the budget, else stop at the budget and record the
partial row — tree so far, sustained pushed-nodes/s and eval rate — so
infeasible instances get a measured rate + extrapolation instead of
silence. Overflow grows the pool losslessly (checkpoint.grow) and
continues.

    TTS_BUDGET_S=7200 nohup python -u tools/run_campaign.py 31 32 ... \
        > /tmp/campaign.log 2>&1 &

Env: TTS_BUDGET_S (default 7200), TTS_LB (default 2), TTS_CHUNK
(default 32768), TTS_CAMPAIGN_OUT (default /tmp/campaign.jsonl),
TTS_WORKDIR (status/checkpoint files, default /tmp), TTS_SEG (default
2000 iters/segment), TTS_CKPT_EVERY (segments between checkpoints,
default 8), TTS_UB ("opt" | "inf", default opt), TTS_SUBMESHES (serve
mode: concurrent submeshes, default 1), TTS_STALL_GRACE
(seconds before the first heartbeat may be declared dead, default 900 —
covers a cold 50x20 compile), TTS_MAX_RESTARTS (default 50).
Resilience knobs ride through to the worker's run_segmented:
TTS_RETRY_ATTEMPTS / TTS_RETRY_BASE_S (transient-error backoff) and
TTS_SEG_TIMEOUT_S (per-segment wall watchdog — the in-process
complement of this supervisor's heartbeat-age kill).
TTS_SEARCH_TELEMETRY=1 compiles the on-device search-telemetry block
into every solve (engine/telemetry.py): rows gain a `telemetry` column
(pruning rate, frontier depth, pool high-water, steal flow) and the
serve-mode trace carries per-segment search.telemetry events
(tools/search_report.py renders them). Checkpoints are
atomic + checksummed with a rotating `.prev` last-good; a worker that
finds its current snapshot torn rolls back to the last-good one
(engine/checkpoint.load_resilient). A budget-exhausted PARTIAL row
keeps its checkpoint, and a rerun with a larger TTS_BUDGET_S resumes
it instead of skipping (only `done` rows retire their checkpoints).
Test hooks (worker side): TTS_TEST_STALL_AT_SEG=N — after writing
segment N's heartbeat, hang forever (simulates a dead tunnel
dispatch); TTS_FAULTS — deterministic fault injection
(utils/faults.py: kill_after_segment / corrupt_checkpoint /
delay_segment / fail_host_fetch), inherited by every respawned worker.
"""

import json
import os
import signal
import subprocess
import sys
import time
import zipfile
import zlib

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

# knob reads go through the lint-checked registry accessors
# (utils/config.KNOBS — defaults live there, tts_lint enforces the
# single-sourcing); apply_platform_override() still runs before any
# device use, so the early package import does not pin the backend
from tpu_tree_search.utils import config as _cfg  # noqa: E402

OUT = _cfg.env_str("TTS_CAMPAIGN_OUT")
WORKDIR = _cfg.env_str("TTS_WORKDIR")
LB = _cfg.env_int("TTS_LB")
CHUNK = _cfg.env_int("TTS_CHUNK")
BUDGET_S = _cfg.env_float("TTS_BUDGET_S")
SEG = _cfg.env_int("TTS_SEG")
CKPT_EVERY = _cfg.env_int("TTS_CKPT_EVERY")
UB_MODE = _cfg.env_str("TTS_UB")
STALL_GRACE = _cfg.env_float("TTS_STALL_GRACE")
STALL_FACTOR = _cfg.env_float("TTS_STALL_FACTOR")
# the floor sits ABOVE the documented ~633 s self-clearing tunnel
# stalls (BENCHMARKS.md): killing a merely-stalled dispatch crashes the
# remote TPU worker, and every process that attaches afterwards hangs
# in init for many minutes — the cure is far worse than the wait
# (measured: a 156 s-floor kill mid-stall turned a ~600 s delay into a
# crashed worker + reconnect hang + lost unsaved segments). The
# supervisor exists for PERMANENT hangs; ~12 min detection latency is
# noise on the multi-hour runs it protects.
STALL_MIN = _cfg.env_float("TTS_STALL_MIN")
MAX_RESTARTS = _cfg.env_int("TTS_MAX_RESTARTS")
# consecutive worker deaths with no iteration progress before giving
# up: 5, not fewer — after a remote-worker crash the first several
# respawns can each burn the full init grace just reconnecting
DEAD_LIMIT = _cfg.env_int("TTS_DEAD_LIMIT")


def paths(inst: int, lb: int):
    base = os.path.join(WORKDIR, f"tts_ta{inst:03d}_lb{lb}")
    return base + ".status.jsonl", base + ".ckpt.npz"


def _telemetry_columns(block_or_summary) -> dict:
    """Search-efficiency columns for a result row, from either a raw
    state.telemetry block (legacy worker) or a DistResult.telemetry
    summary dict (serve mode); {} when telemetry is off — rows from
    telemetry-off campaigns keep their exact historical schema."""
    s = block_or_summary
    if s is None:
        return {}
    if not isinstance(s, dict):
        import numpy as np
        if not np.asarray(s).size:
            return {}
        from tpu_tree_search.engine import telemetry as tele
        s = tele.summarize(np.asarray(s))
    return {"telemetry": {
        "pruning_rate": s["pruning_rate"],
        "frontier_depth": s["frontier_depth"],
        "pool_highwater": s["pool_highwater"],
        "branched": sum(s["branched"]),
        "pruned": sum(s["pruned"]),
        "steal_sent": s["steal_sent"],
        "steal_recv": s["steal_recv"],
        "improvements": s["improvements"],
    }}


# the rotating last-good sibling every atomic save leaves beside the
# checkpoint (engine/checkpoint.LAST_GOOD_SUFFIX — duplicated here so
# the supervisor process never imports jax: attaching a second process
# to a remote TPU runtime conflicts with its own worker)
def last_good(path: str) -> str:
    return path + ".prev"


def unlink_checkpoint(ckpt_path: str) -> None:
    for p in (ckpt_path, last_good(ckpt_path)):
        if os.path.exists(p):
            os.unlink(p)


# ----------------------------------------------------------------- worker

def worker_main(inst: int) -> None:
    """Solve one instance via checkpoint.run_segmented (THE segmented
    driver — this function only adds the status-file heartbeat, the wall
    budget, and overflow growth), heartbeating + checkpointing.

    Resumes from the checkpoint file if it exists (the pool arrays AND
    every counter live in the SearchState the checkpoint stores, so the
    resumed run continues the exact count sequence)."""
    from tpu_tree_search.utils import compile_cache

    compile_cache.enable()

    import numpy as np

    import jax

    # honor a JAX_PLATFORMS=cpu request (the CPU-mesh tests): without
    # this the "CPU" durability tests silently ran their workers on the
    # live TPU (the sitecustomize preload pins the TPU plugin)
    from tpu_tree_search.utils import device_info

    device_info.apply_platform_override()

    from tpu_tree_search.engine import checkpoint, device
    from tpu_tree_search.ops import batched
    from tpu_tree_search.problems import taillard

    lb = LB
    status_path, ckpt_path = paths(inst, lb)
    stall_at = _cfg.env_int("TTS_TEST_STALL_AT_SEG")

    def emit(rec: dict) -> None:
        rec["t"] = time.time()
        with open(status_path, "a") as f:
            f.write(json.dumps(rec) + "\n")

    p = taillard.processing_times(inst)
    ub = taillard.optimal_makespan(inst) if UB_MODE == "opt" else None
    m, jobs = p.shape
    tables = batched.make_tables(p)
    capacity = _cfg.env_int("TTS_POOL_ROWS") or \
        max(device.default_capacity(jobs, m), 4 * CHUNK * jobs)
    grows = 0
    spent_before = 0.0
    warm_tree = warm_sol = 0
    state = None
    if checkpoint.resume_path(ckpt_path):
        # load_resilient: a torn current snapshot (the worker was killed
        # mid-save) rolls back to the rotating last-good sibling instead
        # of crash-looping the respawn cycle
        try:
            state, meta, used = checkpoint.load_resilient(ckpt_path,
                                                          p_times=p)
        except checkpoint.CheckpointSchemaError as e:
            # a newer-schema checkpoint is an operator problem (wrong
            # build), not damage: abort the campaign loudly via the
            # fatal channel — the supervisor would otherwise respawn
            # the same crash DEAD_LIMIT times and silently drop the
            # instance
            emit({"kind": "fatal", "reason": str(e)[:300]})
            sys.exit(3)
        except checkpoint.CheckpointCorrupt as e:
            # EVERY candidate unreadable: delete the husks and restart
            # the instance from scratch — losing the (garbage) file is
            # recovery, crash-looping until DEAD_LIMIT is not.
            # CheckpointSchemaError stays fatal on purpose (a valid
            # newer-format file is an operator problem, not damage).
            emit({"kind": "corrupt_restart", "reason": str(e)[:200]})
            unlink_checkpoint(ckpt_path)
    if state is not None:
        if str(used) != str(ckpt_path):
            emit({"kind": "rollback", "path": str(used)})
        if np.asarray(meta.get("host_depth", np.zeros(0))).size:
            # a -C distributed checkpoint carries carved host-tier seed
            # rows; silently dropping them would lose subtrees — refuse
            # loudly, the distributed engine owns that resume path
            emit({"kind": "fatal",
                  "reason": "checkpoint carries a host-tier share; "
                            "resume it with the distributed engine"})
            sys.exit(3)
        if np.asarray(state.prmu).ndim == 3:
            # a stacked distributed checkpoint (e.g. TTS_WORKDIR pointed
            # at a file the distributed engine wrote): collapse it onto
            # this single device instead of dying on the shape — the
            # shared helper owns the sizing invariant (footprint +
            # usable-row headroom)
            state = checkpoint.collapse_to_single_device(state, CHUNK,
                                                         jobs)
            emit({"kind": "reshard", "workers": 1})
        # warm-up counters live in the checkpoint's meta, not the state
        # (distributed.search tracks them the same way); carry them so
        # the final row's accounting stays exact across elastic resumes
        warm_tree = int(meta.get("warmup_tree", 0))
        warm_sol = int(meta.get("warmup_sol", 0))
        capacity = state.prmu.shape[-1]
        grows = int(meta.get("grows", 0))
        spent_before = float(meta.get("spent_s", 0.0))
        if bool(np.asarray(state.overflow).any()):
            # killed right after an overflow checkpoint: grow NOW or the
            # resumed loop would exit immediately forever
            capacity *= 2
            grows += 1
            state = checkpoint.grow(state, capacity)
            emit({"kind": "grow", "capacity": capacity})
        emit({"kind": "resume", "iters": int(np.asarray(state.iters).max()),
              "capacity": capacity, "spent_s": spent_before})
    else:
        state = device.init_state(jobs, capacity, ub, p_times=p)

    t0 = time.perf_counter()

    def spent_now(elapsed: float) -> float:
        return spent_before + elapsed

    def hb(rep):
        # the worker clock (t0), NOT rep.elapsed: run_segmented restarts
        # its elapsed at every overflow-grow re-entry, which would reset
        # the wall budget after each grow
        emit({"kind": "seg", "seg": rep.segment, "iters": rep.iters,
              "tree": rep.tree, "sol": rep.sol, "best": rep.best,
              "size": rep.pool_size, "capacity": capacity,
              "spent_s": round(spent_now(time.perf_counter() - t0), 2)})
        if rep.segment % CKPT_EVERY == 0:
            # run_segmented saves right after this callback; the marker
            # tells the supervisor to allow a long heartbeat gap for the
            # save (a multi-hundred-MB pool fetch through the tunnel)
            emit({"kind": "ckpt_start", "seg": rep.segment})
        if stall_at and rep.segment >= stall_at:
            emit({"kind": "test_stall", "seg": rep.segment})
            time.sleep(10 ** 6)  # simulated dead dispatch (test hook)

    def run_fn(s, target):
        return device.run(tables, s, lb, CHUNK, max_iters=target)

    while True:
        def mk_meta():
            return {"inst": inst, "lb": lb, "chunk": CHUNK,
                    "ub_mode": UB_MODE, "grows": grows,
                    "warmup_tree": warm_tree, "warmup_sol": warm_sol,
                    "spent_s": round(
                        spent_now(time.perf_counter() - t0), 2)}

        try:
            state = checkpoint.run_segmented(
                run_fn, state, segment_iters=SEG,
                checkpoint_path=ckpt_path, checkpoint_every=CKPT_EVERY,
                heartbeat=hb, checkpoint_meta=mk_meta,
                should_stop=lambda rep: spent_now(
                    time.perf_counter() - t0) > BUDGET_S)
            break
        except checkpoint.PoolOverflow as e:
            capacity *= 2
            grows += 1
            emit({"kind": "grow", "capacity": capacity})
            state = checkpoint.grow(e.state, capacity)

    fetched = jax.device_get((state.iters, state.tree, state.sol,
                              state.best, state.size, state.evals))
    iters, tree, sol, best, size, evals = (int(np.asarray(v).max())
                                           for v in fetched)
    tree += warm_tree
    sol += warm_sol
    spent = spent_now(time.perf_counter() - t0)
    done = size == 0
    row = {"inst": inst, "jobs": jobs, "machines": m, "lb": lb,
           "chunk": CHUNK, "budget_s": BUDGET_S, "ub_mode": UB_MODE,
           "done": done, "elapsed_s": round(spent, 2), "tree": tree,
           "sol": sol, "best": best, "evals": evals, "iters": iters,
           "capacity": capacity, "grows": grows, "pool_at_stop": size,
           "pushed_per_s": round(tree / max(spent, 1e-9), 1),
           "evals_per_s": round(evals / max(spent, 1e-9), 1)}
    row.update(_telemetry_columns(state.telemetry))
    if done and UB_MODE == "opt" and best != ub:
        # a WRONG ANSWER is never a transient — the supervisor must
        # abort the campaign loudly, not retry/skip
        emit({"kind": "fatal",
              "reason": f"wrong answer: best={best} != optimum {ub}",
              **row})
        sys.exit(3)
    emit({"kind": "done", **row})


# ------------------------------------------------------------- supervisor

def read_status(path: str) -> list[dict]:
    if not os.path.exists(path):
        return []
    out = []
    with open(path) as f:
        for ln in f:
            ln = ln.strip()
            if ln:
                try:
                    out.append(json.loads(ln))
                except json.JSONDecodeError:
                    pass  # torn write from a killed worker
    return out


def stall_timeout(fresh: list[dict]) -> float:
    """Adaptive heartbeat timeout: ~STALL_FACTOR x the slowest recent
    inter-heartbeat gap (checkpoint segments are legitimately slower —
    a multi-hundred-MB pool fetch through the tunnel), floored at
    STALL_MIN. Gaps are measured within the CURRENT worker run only —
    a gap spanning a previous kill+respawn would inflate the estimate
    by the very stall it recovered from. Before any gap is measurable,
    STALL_GRACE (cold compile)."""
    ts = [r["t"] for r in fresh[-12:]]
    gaps = [b - a for a, b in zip(ts, ts[1:]) if b > a]
    if not gaps:
        return STALL_GRACE
    return max(STALL_MIN, STALL_FACTOR * max(gaps))


def supervise(inst: int, lb: int) -> dict | None:
    """Run the worker for one instance, restarting it (resume from the
    last checkpoint) whenever its heartbeat goes dead. Returns the final
    row, or None if the instance failed MAX_RESTARTS times."""
    status_path, ckpt_path = paths(inst, lb)
    if os.path.exists(status_path):
        os.unlink(status_path)
    # A checkpoint from a DIFFERENT configuration would silently resume
    # work measured under other settings — but one matching the current
    # (inst, lb, chunk) is durable in-flight progress from a killed
    # campaign supervisor and must be resumed, not discarded. Both the
    # current file and its rotating last-good sibling are screened: a
    # torn current is deleted (the worker would only fall back anyway)
    # while a good last-good survives to be the worker's rollback.
    import numpy as np
    resumable = False
    for cand in (ckpt_path, last_good(ckpt_path)):
        if not os.path.exists(cand):
            continue
        try:
            with np.load(cand) as z:
                match = (int(z["meta_inst"]) == inst
                         and int(z["meta_lb"]) == lb
                         and int(z["meta_chunk"]) == CHUNK
                         and str(z["meta_ub_mode"]) == UB_MODE)
        except (KeyError, OSError, ValueError, EOFError,
                zipfile.BadZipFile, zlib.error):
            # the same error surface checkpoint.load treats as
            # corruption — a torn file must be screened out here, not
            # crash the whole campaign at startup
            match = False
        if match:
            resumable = True
        else:
            os.unlink(cand)
    if resumable:
        print(f"ta{inst:03d} lb{lb}: resuming from existing "
              f"checkpoint {ckpt_path}", flush=True)

    restarts = 0
    iters_at_spawn = -1
    dead_without_progress = 0
    while True:
        n_before = len(read_status(status_path))
        proc = subprocess.Popen(
            [sys.executable, "-u", os.path.abspath(__file__),
             "--worker", str(inst)],
            start_new_session=True)
        spawn_t = time.time()
        outcome = None      # "done" | "exit" | "stall"
        while True:
            time.sleep(1.0)
            recs = read_status(status_path)
            fresh = recs[n_before:]
            for r in fresh:
                if r.get("kind") == "done":
                    outcome = "done"
                    row = r
                    break
            if outcome == "done":
                break
            rc = proc.poll()
            if rc is not None:
                outcome = "exit"
                break
            last_t = fresh[-1]["t"] if fresh else spawn_t
            timeout = stall_timeout(fresh)
            if fresh and fresh[-1].get("kind") == "ckpt_start":
                # a checkpoint save is in flight — legitimately minutes
                # through the tunnel; don't kill it on the segment pace
                timeout = max(timeout, STALL_GRACE)
            if time.time() - last_t > timeout:
                outcome = "stall"
                break
        if outcome == "done":
            try:
                os.killpg(proc.pid, signal.SIGKILL)
            except (ProcessLookupError, PermissionError):
                pass
            proc.wait()
            # ONLY a solved (done=true, drained-pool) run retires its
            # checkpoint — a surviving final checkpoint would make a
            # later re-measurement campaign "resume" it and instantly
            # re-report THESE counters as a fresh result. A
            # budget-exhausted PARTIAL row keeps the checkpoint: it is
            # recoverable in-flight progress, and a rerun with a larger
            # TTS_BUDGET_S extends it instead of starting over
            # (ADVICE.md round 5, the unconditional unlink made partial
            # progress unrecoverable).
            if row.get("done") is True:
                unlink_checkpoint(ckpt_path)
            elif os.path.exists(ckpt_path):
                print(f"ta{inst:03d} lb{lb}: budget exhausted — keeping "
                      f"checkpoint {ckpt_path} for a larger-budget rerun",
                      flush=True)
            row.pop("kind", None)
            row.pop("t", None)
            row["restarts"] = restarts
            return row
        # dead or hung: kill the whole process group and resume
        try:
            os.killpg(proc.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass
        proc.wait()
        recs = read_status(status_path)
        for r in recs[n_before:]:
            if r.get("kind") == "fatal":
                # a wrong answer is never a transient — abort the whole
                # campaign loudly rather than retry or skip
                raise RuntimeError(
                    f"ta{inst:03d} lb{lb}: {r.get('reason', 'fatal')}")
        iters_now = max((r.get("iters", 0) for r in recs), default=0)
        if iters_now <= iters_at_spawn:
            dead_without_progress += 1
        else:
            dead_without_progress = 0
        iters_at_spawn = iters_now
        restarts += 1
        print(f"ta{inst:03d} lb{lb}: worker {outcome} "
              f"(restart {restarts}, iters={iters_now}); resuming from "
              f"checkpoint", flush=True)
        if restarts >= MAX_RESTARTS or dead_without_progress >= DEAD_LIMIT:
            print(f"ta{inst:03d} lb{lb}: giving up after {restarts} "
                  f"restarts ({dead_without_progress} without progress)",
                  flush=True)
            return None
        time.sleep(min(30, 5 * dead_without_progress + 2))


def select_instances(insts: list[int]) -> list[int]:
    """Drop instances already retired by a row in OUT (shared by both
    modes). The skip key includes done/budget, not just (inst, lb,
    chunk): a PARTIAL row only retires its instance up to the budget it
    was measured at — a rerun with a larger TTS_BUDGET_S resumes the
    kept checkpoint and extends it (ADVICE.md round 5: the old key
    silently skipped exactly the reruns partial rows exist for)."""
    done = {}
    if os.path.exists(OUT):
        with open(OUT) as f:
            for ln in f:
                if ln.strip():
                    r = json.loads(ln)
                    # rows from before the chunk field default to the
                    # current CHUNK (they predate configurable rechecks)
                    done[(r["inst"], r["lb"], r.get("chunk", CHUNK))] = r
    out = []
    for inst in insts:
        r = done.get((inst, LB, CHUNK))
        if r is not None and (r.get("done", True)
                              or float(r.get("budget_s", BUDGET_S))
                              >= BUDGET_S):
            tag = "done" if r.get("done", True) else \
                f"partial at budget {r.get('budget_s')}s"
            print(f"ta{inst:03d} lb{LB}: already {tag} "
                  f"(chunk={r.get('chunk', CHUNK)} "
                  f"t={r['elapsed_s']}s tree={r['tree']}), skipping",
                  flush=True)
            continue
        if r is not None:
            print(f"ta{inst:03d} lb{LB}: extending partial row "
                  f"(budget {r.get('budget_s')}s -> {BUDGET_S:.0f}s)",
                  flush=True)
        out.append(inst)
    return out


def append_row(row: dict) -> None:
    with open(OUT, "a") as f:
        f.write(json.dumps(row) + "\n")
    tag = "SOLVED" if row["done"] else "partial"
    print(f"ta{row['inst']:03d} lb{row['lb']}: {tag} "
          f"t={row['elapsed_s']}s tree={row['tree']} "
          f"pushed/s={row['pushed_per_s']} "
          f"restarts={row.get('restarts', 0)}", flush=True)


# ----------------------------------------------------------- serve mode

def serve_main(insts: list[int], n_submeshes: int) -> None:
    """The campaign as the search service's first client: ONE process,
    every instance submitted up front, results polled in order — the
    executable cache compiles each instance CLASS once for the whole
    campaign, and `n_submeshes > 1` solves that many instances
    concurrently. Budget exhaustion is the service's DEADLINE state
    (checkpoint kept under the legacy name, so --no-serve and serve
    runs resume each other's partials)."""
    from tpu_tree_search.utils import compile_cache, device_info

    compile_cache.enable()
    device_info.apply_platform_override()

    import numpy as np  # noqa: F401 (platform init order)

    from tpu_tree_search.obs import tracelog
    from tpu_tree_search.problems import taillard
    from tpu_tree_search.service import SearchRequest, SearchServer

    todo = select_instances(insts)
    if not todo:
        return
    # the campaign's flight recorder: every row points at the JSONL
    # event log that shows its requests' dispatches, preemptions,
    # checkpoints and retries (tools/trace_summary.py renders it;
    # obs/chrome_trace converts it for Perfetto)
    trace_file = _cfg.env_str("TTS_TRACE_FILE") or \
        os.path.join(WORKDIR, "campaign_trace.jsonl")
    tracelog.get().set_sink(trace_file)
    print(f"flight recorder: {trace_file}", flush=True)
    with SearchServer(n_submeshes=n_submeshes, workdir=WORKDIR,
                      max_queue_depth=max(64, len(todo) + 1),
                      segment_iters=SEG,
                      checkpoint_every=CKPT_EVERY) as srv:
        from tpu_tree_search.engine import device

        rids = {}
        for inst in todo:
            p = taillard.processing_times(inst)
            ub = (taillard.optimal_makespan(inst) if UB_MODE == "opt"
                  else None)
            # the legacy worker's capacity floor (4*chunk*jobs headroom
            # above the class default); the distributed driver still
            # grows losslessly on overflow, this just avoids paying the
            # grow+recompile on instances the floor was tuned for
            capacity = _cfg.env_int("TTS_POOL_ROWS") or \
                max(device.default_capacity(p.shape[1], p.shape[0]),
                    4 * CHUNK * p.shape[1])
            rids[inst] = srv.submit(SearchRequest(
                p_times=p, lb_kind=LB, init_ub=ub, chunk=CHUNK,
                capacity=capacity, deadline_s=BUDGET_S,
                # the legacy worker's checkpoint base name AND config
                # meta (inst/lb/chunk/ub_mode): serve-mode campaigns
                # resume --no-serve partials and vice versa — the
                # legacy supervisor's config screen accepts these files
                tag=f"tts_ta{inst:03d}_lb{LB}",
                checkpoint_meta={"inst": inst, "lb": LB, "chunk": CHUNK,
                                 "ub_mode": UB_MODE}))
            print(f"ta{inst:03d} lb{LB}: submitted "
                  f"(budget {BUDGET_S:.0f}s)", flush=True)
        for inst in todo:
            rec = srv.result(rids[inst])
            row = _serve_row(inst, rec, trace_file)
            if row is None:
                continue
            if (row["done"] and UB_MODE == "opt"
                    and row["best"] != taillard.optimal_makespan(inst)):
                raise RuntimeError(
                    f"ta{inst:03d} lb{LB}: wrong answer: "
                    f"best={row['best']} != optimum "
                    f"{taillard.optimal_makespan(inst)}")
            append_row(row)
        snap = srv.status_snapshot()
        print(f"campaign served {snap['counters']['done']} done / "
              f"{snap['counters']['deadline']} partial; executor cache "
              f"{snap['executor_cache']['hits']} hits / "
              f"{snap['executor_cache']['misses']} compiles", flush=True)


def _serve_row(inst: int, rec, trace_file: str | None = None
               ) -> dict | None:
    """A service RequestRecord -> the campaign's JSONL row schema."""
    from tpu_tree_search.problems import taillard

    p = taillard.processing_times(inst)
    m, jobs = p.shape
    res = rec.result
    if res is None or rec.state in ("FAILED", "CANCELLED"):
        print(f"ta{inst:03d} lb{LB}: {rec.state} "
              f"({rec.error or 'no result'}); no row", flush=True)
        return None
    spent = rec.spent_s()
    per = res.per_device
    evals = int(sum(per.get("evals", [0])))
    iters = int(max(per.get("iters", [0])))
    pool = int(sum(per.get("final_size", [0])))
    done = rec.state == "DONE" and res.complete
    return {**_telemetry_columns(getattr(res, "telemetry", None)),
            "inst": inst, "jobs": jobs, "machines": m, "lb": LB,
            "chunk": CHUNK, "budget_s": BUDGET_S, "ub_mode": UB_MODE,
            "done": done, "elapsed_s": round(spent, 2),
            "tree": int(res.explored_tree), "sol": int(res.explored_sol),
            "best": int(res.best), "evals": evals, "iters": iters,
            "capacity": int(rec.request.capacity or 0),
            "grows": 0, "pool_at_stop": pool,
            "pushed_per_s": round(res.explored_tree / max(spent, 1e-9), 1),
            "evals_per_s": round(evals / max(spent, 1e-9), 1),
            "restarts": rec.dispatches - 1,
            # where this row's lifecycle (dispatches, preemptions,
            # checkpoints, retries) is flight-recorded
            "trace_file": trace_file,
            "request_id": rec.id}


# ----------------------------------------------------------- entry point

def legacy_main(insts: list[int]) -> None:
    for inst in select_instances(insts):
        print(f"ta{inst:03d} lb{LB}: solving (budget {BUDGET_S:.0f}s)...",
              flush=True)
        row = supervise(inst, LB)
        if row is None:
            continue
        append_row(row)


def main(argv=None):
    import argparse

    ap = argparse.ArgumentParser(
        description="Solve Taillard instances to a per-instance compute "
                    "budget, writing JSONL result rows. Default mode "
                    "runs ONE in-process search service "
                    "(tpu_tree_search/service/) and submits every "
                    "instance to it — no per-instance process/compile.",
        epilog="Env knobs: TTS_BUDGET_S TTS_LB TTS_CHUNK "
               "TTS_CAMPAIGN_OUT TTS_WORKDIR TTS_SEG TTS_CKPT_EVERY "
               "TTS_UB TTS_SUBMESHES (see the module docstring).")
    ap.add_argument("instances", nargs="+", type=int,
                    help="Taillard instance ids (e.g. 31 32 ... 50)")
    ap.add_argument("--no-serve", action="store_true",
                    help="DEPRECATED: use the legacy process-per-"
                         "instance supervisor (worker subprocess + "
                         "heartbeat stall kill/respawn) instead of the "
                         "search service. Kept for one release for "
                         "runtimes where a hung device dispatch must be "
                         "killed at the process level; it will be "
                         "removed — migrate to the default serve mode.")
    ap.add_argument("--submeshes", type=int,
                    default=_cfg.env_int("TTS_SUBMESHES"),
                    help="serve mode: partition the device mesh into "
                         "this many equal submeshes and solve that many "
                         "instances concurrently (default 1)")
    args = ap.parse_args(argv)
    if args.no_serve:
        print("warning: --no-serve (process-per-instance supervisor) is "
              "deprecated and will be removed after one release; the "
              "service path is the default", flush=True)
        legacy_main(args.instances)
    else:
        serve_main(args.instances, args.submeshes)


if __name__ == "__main__":
    # worker dispatch is positional-flag tolerant ("--no-serve --worker
    # 3" and "--worker 3" both reach worker_main): the supervisor
    # respawns workers with the flags it was launched with
    if "--worker" in sys.argv[1:]:
        worker_main(int(sys.argv[sys.argv.index("--worker") + 1]))
    else:
        main()
