"""Attribute steady-state step time to ops from a jax.profiler trace.

Usage:
    python tools/profile_step.py [--lb 2] [--inst 21] [--chunk 32768]
        [--warm 400] [--iters 30]

Warms the single-device engine past its ramp (underfilled chunks), traces
a short window of the compiled loop through the shared profiler session
(tpu_tree_search/obs/profiler.py — the SAME one-at-a-time session behind
``POST /profile`` and the `profile` CLI subcommand; no direct
``jax.profiler`` calls live in the tools any more), then aggregates
per-op SELF times (exclusive of nested control-flow spans —
tpu_tree_search/obs/chrome_trace.py owns the trace parsing AND the phase
buckets, shared with tools/trace_selftime.py, tools/search_report.py and
tools/validate_attribution.py). The tool's own wall-clock phases
(warm-up, traced window) are flight-recorded as obs/tracelog spans, so a
`TTS_TRACE_FILE=...` run leaves a timeline of the measurement itself.
This is the measurement VERDICT r2 items 8/9 ask for: what the two-phase
LB2 step (resp. the LB1 step) actually spends its time on.
"""

import argparse
import json
import os
import sys
import tempfile

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from tpu_tree_search.engine import device  # noqa: E402
from tpu_tree_search.obs import profiler, tracelog  # noqa: E402
from tpu_tree_search.obs.chrome_trace import (bucket_of,  # noqa: E402
                                              bucketed_self_times,
                                              load_xla_trace, self_times)
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--lb", type=int, default=2)
    ap.add_argument("--inst", type=int, default=21)
    ap.add_argument("--chunk", type=int, default=32768)
    ap.add_argument("--warm", type=int, default=400)
    ap.add_argument("--iters", type=int, default=30)
    ap.add_argument("--top", type=int, default=25)
    ap.add_argument("--logdir", default=None,
                    help="keep the trace here instead of a tempdir")
    args = ap.parse_args()

    p = taillard.processing_times(args.inst)
    ub = taillard.optimal_makespan(args.inst)
    tables = batched.make_tables(p)
    jobs = p.shape[1]
    state = device.init_state(jobs, 1 << 22, ub, p_times=p)
    with tracelog.span("profile_step.warmup", inst=args.inst, lb=args.lb,
                       chunk=args.chunk) as warm_sp:
        state = device.run(tables, state, args.lb, args.chunk,
                           max_iters=args.warm)
        state.size.block_until_ready()
        warm_sp.set(iters=int(state.iters), pool=int(state.size))
    print(f"# warmed: iters={int(state.iters)} pool={int(state.size)} "
          f"evals={int(state.evals)} ({warm_sp.dur:.2f}s)",
          file=sys.stderr)

    log_dir = args.logdir or tempfile.mkdtemp(prefix="tts_trace_")
    with tracelog.span("profile_step.traced_window", logdir=log_dir):
        with profiler.trace(log_dir):
            out = device.run(tables, state, args.lb, args.chunk,
                             max_iters=args.warm + args.iters)
            out.size.block_until_ready()
    n_iters = int(out.iters) - int(state.iters)
    evals = int(out.evals) - int(state.evals)
    print(f"# traced {n_iters} iters, {evals} evals; trace in {log_dir}",
          file=sys.stderr)

    self_us, counts = self_times(load_xla_trace(log_dir))
    total = sum(self_us.values())
    if total == 0:
        raise SystemExit("no device op self-times found in trace "
                         "(thread-name heuristic missed; inspect "
                         f"{log_dir} manually)")

    by_bucket = bucketed_self_times(self_us)

    print(json.dumps({
        "lb": args.lb, "inst": args.inst, "chunk": args.chunk,
        "iters": n_iters, "evals": evals,
        "device_self_ms": round(total / 1e3, 2),
        "per_iter_ms": round(total / 1e3 / max(n_iters, 1), 3),
        "evals_per_sec": round(evals / (total / 1e6), 1) if total else 0,
        "buckets_ms": {k: round(v / 1e3, 2)
                       for k, v in by_bucket.most_common()},
    }))
    print("\n# top ops by device self-time:")
    for name, d in self_us.most_common(args.top):
        print(f"{d/1e3:10.2f} ms  x{counts[name]:<6} "
              f"[{bucket_of(name):>15}]  {name[:100]}")


if __name__ == "__main__":
    main()
