"""Render a tuning-cache directory (tune/cache.TuningCache) as a table.

One row per persisted entry: the tuning key, the winning
chunk/balance_period, the measured node-evals/s, the probe count and
sweep cost, and the fingerprint the entry is pinned to. Quarantined
``*.corrupt`` siblings are listed so an operator sees damage at a
glance. The CI tuner-smoke leg uploads this rendering beside the cache
listing.

    python tools/tune_report.py <cache-dir>
    python tools/tune_report.py <cache-dir> --json
"""

import argparse
import json
import os
import struct
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

_HDR_LEN = struct.Struct("<Q")
MAGIC = b"TTSTUNE1\n"


def read_entry(path: str) -> dict:
    """Parse one cache entry WITHOUT the package (no fingerprint
    check — this is a report, not a consumer): header + payload, or an
    {"error": ...} row for damaged files."""
    try:
        with open(path, "rb") as f:
            blob = f.read()
        if blob[:len(MAGIC)] != MAGIC:
            raise ValueError("bad magic")
        off = len(MAGIC)
        (hdr_len,) = _HDR_LEN.unpack_from(blob, off)
        off += _HDR_LEN.size
        header = json.loads(blob[off:off + hdr_len].decode())
        payload = json.loads(blob[off + hdr_len:].decode())
        return {"file": os.path.basename(path), "header": header,
                "payload": payload}
    except Exception as e:  # noqa: BLE001 — a torn entry is a row,
        return {"file": os.path.basename(path), "error": repr(e)}


def render(entries: list[dict], corrupt: list[str]) -> str:
    lines = ["# Tuning cache", "",
             f"{len(entries)} entr(y/ies), {len(corrupt)} quarantined",
             "",
             "| key | chunk | balance_period | evals/s | probes | "
             "sweep_s | platform | devices |",
             "|---|---|---|---|---|---|---|---|"]
    for e in entries:
        if "error" in e:
            lines.append(f"| {e['file']} | - | - | - | - | - | "
                         f"UNREADABLE: {e['error']} | - |")
            continue
        hdr, pay = e["header"], e["payload"]
        fp = hdr.get("fingerprint") or {}
        rate = pay.get("evals_per_s")
        rate_s = (f"{rate:.4g}" if isinstance(rate, (int, float))
                  else "-")
        lines.append(
            f"| {hdr.get('key') or e['file']} | {pay.get('chunk')} "
            f"| {pay.get('balance_period')} | {rate_s} "
            f"| {len(pay.get('probes') or [])} "
            f"| {pay.get('sweep_seconds', '-')} "
            f"| {fp.get('platform', '-')} "
            f"| {fp.get('device_count', '-')}x"
            f"{'/'.join(fp.get('device_kinds') or ['-'])} |")
    if corrupt:
        lines += ["", "Quarantined (never loaded):"]
        lines += [f"- {c}" for c in corrupt]
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render a tune/cache.TuningCache directory")
    ap.add_argument("cache_dir")
    ap.add_argument("--json", action="store_true",
                    help="dump the parsed entries as JSON")
    args = ap.parse_args(argv)
    if not os.path.isdir(args.cache_dir):
        print(f"error: {args.cache_dir} is not a directory",
              file=sys.stderr)
        return 2
    names = sorted(os.listdir(args.cache_dir))
    entries = [read_entry(os.path.join(args.cache_dir, n))
               for n in names if n.endswith(".tune")]
    corrupt = [n for n in names if n.endswith(".corrupt")]
    if args.json:
        print(json.dumps({"entries": entries, "quarantined": corrupt},
                         indent=1))
    else:
        print(render(entries, corrupt))
    return 0


if __name__ == "__main__":
    sys.exit(main())
