#!/bin/bash
# Round-5 short on-chip measurements, in priority order, one log each.
# Usage: tools/run_r5_shorts.sh [logdir]   (default /tmp/r5_shorts)
set -u
cd "$(dirname "$0")/.."
LOG=${1:-/tmp/r5_shorts}
mkdir -p "$LOG"

echo "== N-Queens on chip (VERDICT r4 #6) =="
for N in 15 16 17; do
  timeout 900 python -m tpu_tree_search nqueens -N $N --chunk 4096 \
    --capacity $((1 << 22)) > "$LOG/nq$N.log" 2>&1
  tail -4 "$LOG/nq$N.log"
done

echo "== Discovery mode (-u 0) ta030 LB2 (VERDICT r4 #5) =="
rm -f /tmp/tts_ta030_lb2.*
TTS_UB=inf TTS_LB=2 TTS_CHUNK=65536 TTS_BUDGET_S=1200 TTS_SEG=2000 \
  TTS_CKPT_EVERY=50 TTS_CAMPAIGN_OUT="$LOG/discovery.jsonl" \
  timeout 1500 python -u tools/run_campaign.py 30 > "$LOG/ta030_inf.log" 2>&1
tail -2 "$LOG/ta030_inf.log"

echo "== 200x20 / 500x20 rate probes (VERDICT r4 #3) =="
for inst in 101 111; do
  rm -f /tmp/tts_ta${inst}_lb2.*
  TTS_LB=2 TTS_CHUNK=4096 TTS_BUDGET_S=240 TTS_SEG=200 TTS_CKPT_EVERY=1000 \
    TTS_CAMPAIGN_OUT="$LOG/wide.jsonl" \
    timeout 900 python -u tools/run_campaign.py $inst \
    > "$LOG/ta${inst}.log" 2>&1
  tail -2 "$LOG/ta${inst}.log"
done

echo "== LB1 attribution error bar (VERDICT r4 #9) =="
timeout 1200 python tools/validate_attribution.py --iters 30 \
  > "$LOG/attribution.log" 2>&1
tail -4 "$LOG/attribution.log"

echo "== bench.py (final headline) =="
timeout 900 python bench.py > "$LOG/bench.log" 2>&1
cat "$LOG/bench.log"

echo "all shorts done; logs in $LOG"
