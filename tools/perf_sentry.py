"""Perf regression sentry over the bench-row trajectory.

Every round the driver runs ``bench.py`` and a multichip dry-run and
archives the result as ``BENCH_r0N.json`` / ``MULTICHIP_r0N.json``.
Until this tool, NOBODY read them: every BENCH row to date was a
silently-ignored ``rc=1`` backend failure. The sentry makes the
trajectory a gate:

- **rc failures are loud**: any row with ``rc != 0`` (or a multichip
  row with ``ok: false``) is a FAIL verdict — a benchmark that did not
  run is a regression of the *measurement*, the worst kind to ignore;
- **rate regressions are caught**: each metric in the latest round is
  compared against the best prior value of the SAME metric across
  earlier rounds (plus any ``published`` number in BASELINE.json),
  with a per-metric relative threshold (default 10%; LB2's window is
  shorter and noisier, so it gets 15%);
- **degraded rows don't lie**: a row stamped ``degraded: true`` (the
  bench ran on a fallback platform, see bench.py's backend bootstrap)
  is never rate-compared against non-degraded history — a CPU rate
  "regressing" from a TPU rate is not a finding — but its rc still
  gates, platform recorded in the report.

Inputs it understands: the driver's wrapper objects
(``{"rc": ..., "tail": ..., "parsed": ...}`` — metric rows are
re-extracted from the tail, the wrapper's single ``parsed`` row drops
the LB2 line), multichip wrappers (``{"n_devices", "rc", "ok",
"skipped", "tail"}``), and raw ``bench.py`` stdout (one JSON row per
line — what the CI leg pipes in).

    python tools/perf_sentry.py                       # latest round in .
    python tools/perf_sentry.py --report-only bench_row.jsonl
    python tools/perf_sentry.py --threshold 0.2 --out sentry.md

Exit status: nonzero when any verdict is FAIL (rc failure, not-ok
multichip, or regression beyond threshold) — unless ``--report-only``,
which always exits 0 and is how CI runs it while the trajectory is
still all-CPU (the markdown lands as a build artifact either way).
"""

import argparse
import glob
import json
import os
import re
import sys

# per-metric relative regression thresholds; _default backstops the rest
THRESHOLDS = {
    "_default": 0.10,
    # LB2 benches on a half-length window (bench.py) — noisier
    "lb2": 0.15,
}

# metric-name substrings whose values regress UPWARD (latencies, idle
# gaps, cold-start executor-ready time, ramp/drain phase seconds and
# the ramp/drain solve wall): the reference best is the MINIMUM prior
# value and a value above it by more than the threshold FAILs.
# Everything else is a rate (higher is better). First matching
# substring wins.
LOWER_IS_BETTER = ("segment_gap", "cold_start", "_seconds", "latency",
                   "_ramp_s", "_drain_s", "_wall_s", "hbm_bytes")

PASS, FAIL, NEW, SKIP = "PASS", "FAIL", "NEW", "SKIP"


def threshold_for(metric: str, overrides: dict) -> float:
    for pat, th in {**THRESHOLDS, **overrides}.items():
        if pat != "_default" and pat in metric:
            return th
    return overrides.get("_default", THRESHOLDS["_default"])


def direction_for(metric: str) -> int:
    """+1 = higher is better (rates, the default); -1 = lower is
    better (the segment-gap / latency family)."""
    return -1 if any(s in metric for s in LOWER_IS_BETTER) else 1


def row_mode(row: dict):
    """The comparison-mode a metric row was measured under, as a
    (channel, value) pair — TTS_OVERLAP for the segment-gap family,
    cache_mode (cold|warm) for the cold-start family, TTS_LADDER for
    the ramp/drain family, and the bench's tuned-chunk mode — or None.
    Rows of different modes are never judged against each other: a
    cold trace+compile latency 'regressing' from a warm disk-replay
    reference is not a finding, it is the cache doing its job; a
    fixed-chunk ramp judged against a laddered ~0 one (or a tuned-
    chunk rate against fixed-chunk history) is the same non-finding.
    The bench stamps "tuned" ONLY on tuned rows, so untuned throughput
    rows stay modeless and keep comparing against their history."""
    if row.get("overlap") is not None:
        return ("overlap", row["overlap"])
    if row.get("cache_mode") is not None:
        return ("cache", row["cache_mode"])
    if row.get("ladder") is not None:
        return ("ladder", row["ladder"])
    if row.get("megabatch") is not None:
        # the serve-rps family (HIGHER is better, the rate default):
        # a batched requests/s figure must never rate-judge against
        # solo serving history — different execution modes entirely
        return ("megabatch", row["megabatch"])
    if row.get("portfolio") is not None:
        # the portfolio-speedup family (service/portfolio): a K=3
        # race ratio must never be judged against a differently-sized
        # race's history — cross-width rows SKIP, never FAIL
        return ("portfolio", row["portfolio"])
    if row.get("fused") is not None:
        # the fused Pallas bound+prune+compact route (TTS_FUSED,
        # ops/pallas_fused): a fused step's allocation profile or rate
        # must never be judged against unfused history — the hbm_bytes
        # family exists precisely to show the two DIFFER
        return ("fused", row["fused"])
    if row.get("tuned") is not None:
        return ("tuned", row["tuned"])
    return None


def _round_of(path: str) -> int:
    m = re.search(r"_r(\d+)\.json$", os.path.basename(path))
    return int(m.group(1)) if m else -1


def _json_lines(text: str) -> list[dict]:
    """Metric rows embedded in free text (bench.py stdout / wrapper
    tails): any line that parses as a JSON object with a 'metric'."""
    rows = []
    for ln in text.splitlines():
        ln = ln.strip()
        if not ln.startswith("{"):
            continue
        try:
            obj = json.loads(ln)
        except json.JSONDecodeError:
            continue
        if isinstance(obj, dict) and "metric" in obj:
            rows.append(obj)
    return rows


def load_source(path: str) -> dict:
    """Normalize one input file to
    {source, rc, ok, skipped, rows: [metric rows]}."""
    with open(path) as f:
        text = f.read()
    out = {"source": os.path.basename(path), "rc": 0, "ok": True,
           "skipped": False, "rows": []}
    try:
        obj = json.loads(text)
    except json.JSONDecodeError:
        obj = None
    if isinstance(obj, dict) and ("rc" in obj or "tail" in obj):
        # driver wrapper (BENCH_rNN / MULTICHIP_rNN)
        out["rc"] = int(obj.get("rc", 0))
        out["ok"] = bool(obj.get("ok", True))
        out["skipped"] = bool(obj.get("skipped", False))
        rows = _json_lines(obj.get("tail") or "")
        if not rows and isinstance(obj.get("parsed"), dict):
            rows = [obj["parsed"]]
        out["rows"] = rows
    elif isinstance(obj, dict) and "metric" in obj:
        out["rows"] = [obj]
    else:
        # raw bench stdout: JSON rows one per line
        out["rows"] = _json_lines(text)
    return out


def load_history(directory: str, before_round: int,
                 baseline_path: str | None,
                 exclude: set | None = None) -> dict:
    """Best prior value per (metric, mode): earlier BENCH_r*.json
    rounds in `directory` plus BASELINE.json's published numbers.
    Keying by mode keeps each measurement family's OWN reference —
    a cold-cache executor-ready row regresses against the best prior
    COLD value, never against the warm disk-replay minimum (which
    would otherwise permanently own a metric-keyed slot and turn
    every later cold row into a SKIP). `exclude` holds the abspaths of
    the files under judgment: explicit-file mode has no round cutoff,
    and a row that can find ITSELF in its mode slot would always PASS
    at +0.0% instead of being judged against real priors."""
    best: dict = {}
    exclude = exclude or set()

    def offer(metric, value, src, platform=None, mode=None):
        if value is None:
            return
        key = (metric, mode)
        better = (value > best[key][0] if direction_for(metric) > 0
                  else value < best[key][0]) \
            if key in best else True
        if better:
            best[key] = (float(value), src, platform, mode)

    for path in sorted(glob.glob(os.path.join(directory,
                                              "BENCH_*.json"))):
        rnd = _round_of(path)
        if before_round >= 0 and rnd >= before_round:
            continue
        if os.path.abspath(path) in exclude:
            continue
        src = load_source(path)
        if src["rc"] != 0:
            continue
        for row in src["rows"]:
            if row.get("degraded"):
                continue            # fallback-platform rate: not a bar
            offer(row.get("metric"), row.get("value"), src["source"],
                  row.get("platform"), row_mode(row))
    if baseline_path and os.path.exists(baseline_path):
        try:
            with open(baseline_path) as f:
                published = json.load(f).get("published") or {}
            for metric, value in published.items():
                if isinstance(value, (int, float)):
                    offer(metric, value,
                          os.path.basename(baseline_path))
        except (OSError, json.JSONDecodeError, AttributeError):
            pass
    return best


def judge(sources: list[dict], history: dict,
          overrides: dict) -> list[dict]:
    """One verdict dict per finding, FAILs first."""
    verdicts = []
    for src in sources:
        name = src["source"]
        if src["skipped"]:
            verdicts.append({"verdict": SKIP, "source": name,
                             "detail": "round marked skipped"})
            continue
        if src["rc"] != 0:
            verdicts.append({
                "verdict": FAIL, "source": name,
                "detail": f"rc={src['rc']} — the benchmark itself "
                          "failed to run (previously ignored "
                          "silently)"})
            continue
        if not src["ok"]:
            verdicts.append({"verdict": FAIL, "source": name,
                             "detail": "ok=false"})
            continue
        if not src["rows"]:
            verdicts.append({"verdict": PASS, "source": name,
                             "detail": "rc=0, no metric rows "
                                       "(smoke-only round)"})
            continue
        for row in src["rows"]:
            metric = row.get("metric", "?")
            value = row.get("value")
            v = {"source": name, "metric": metric, "value": value,
                 "platform": row.get("platform"),
                 "degraded": bool(row.get("degraded"))}
            # rows carry their measurement mode precisely so an
            # overlap-off gap is never judged against an overlap-on
            # ~0.0 reference, and a cold-cache executor-ready latency
            # never against a warm disk-replay one: the same-mode
            # reference is the bar; when only an OTHER mode has
            # history, the row is SKIPped (not FAILed, not NEW — the
            # cross-mode value is stated for context)
            mode = row_mode(row)
            ref = history.get((metric, mode))
            if ref is None and mode is not None:
                ref = next((history[k] for k in sorted(
                    history, key=repr) if k[0] == metric), None)
            refplat = ref[2] if ref is not None else None
            refmode = (ref[3] if ref is not None and len(ref) > 3
                       else None)
            plat_mismatch = (ref is not None and refplat
                             and row.get("platform")
                             and refplat != row["platform"])
            # a MODELESS reference (a BASELINE.json number) counts as
            # a mismatch for a mode-carrying row too: the baseline's
            # measurement mode is unknown, and rate-judging a cold
            # compile against a possibly-warm published number is the
            # exact false-FAIL this machinery exists to prevent
            mode_mismatch = (ref is not None and mode is not None
                             and refmode != mode)
            if ref is not None and (v["degraded"] or plat_mismatch
                                    or mode_mismatch):
                # a fallback-platform (or different-platform, or
                # different-mode) value compared against the reference
                # best would always "regress" — a CPU rate is not a
                # TPU finding, a sync gap not a pipelined one, a cold
                # compile not a warm replay
                ref_mode_desc = (repr(refmode[1]) if refmode
                                 else "unknown (modeless baseline)")
                why = (f"{mode[0]} mode {mode[1]!r} vs "
                       f"reference mode {ref_mode_desc}"
                       if mode_mismatch
                       else f"platform {row.get('platform')!r}"
                       + (" (degraded)" if v["degraded"] else "")
                       + f" vs reference platform {refplat!r}")
                v.update(verdict=SKIP,
                         detail=f"{why}; rate not compared "
                                f"(reference {ref[0]:.4g})")
            elif ref is None:
                v.update(verdict=NEW,
                         detail="no prior value for this metric")
            else:
                refv, refsrc = ref[0], ref[1]
                th = threshold_for(metric, overrides)
                direction = direction_for(metric)
                # a 0.0 reference is REAL for the lower-is-better
                # family (a perfect-overlap gap round); floor the
                # denominator so a later nonzero gap still reads as a
                # huge upward move instead of silently passing
                delta = (value - refv) / max(refv, 1e-9)
                v.update(reference=refv, reference_source=refsrc,
                         delta=delta, threshold=th,
                         direction=("lower" if direction < 0
                                    else "higher"))
                # regression = the metric moved AGAINST its direction
                # by more than the threshold: rates fail below -th,
                # lower-is-better metrics (segment_gap_s) fail above +th
                regressed = (delta < -th if direction > 0
                             else delta > th)
                word = "best" if direction > 0 else "lowest"
                if regressed:
                    sign = "-" if direction > 0 else "+"
                    v.update(verdict=FAIL,
                             detail=f"{delta:+.1%} vs {word} prior "
                                    f"{refv:.4g} ({refsrc}); "
                                    f"threshold {sign}{th:.0%}")
                else:
                    v.update(verdict=PASS,
                             detail=f"{delta:+.1%} vs {word} prior "
                                    f"{refv:.4g} ({refsrc})")
            verdicts.append(v)
    order = {FAIL: 0, NEW: 1, SKIP: 2, PASS: 3}
    verdicts.sort(key=lambda v: (order.get(v["verdict"], 9),
                                 v.get("metric", "")))
    return verdicts


def render_json(verdicts: list[dict], latest_round: int) -> dict:
    """Machine-readable verdict (written next to the markdown report):
    the schema the CI leg uploads and the health layer's `perf` rule
    ingests (obs/health.py, TTS_HEALTH_PERF_JSON)."""
    n_fail = sum(v["verdict"] == FAIL for v in verdicts)
    return {
        "schema": 1,
        "round": latest_round if latest_round >= 0 else None,
        "verdict": FAIL if n_fail else PASS,
        "n_findings": len(verdicts),
        "n_fail": n_fail,
        "reasons": [f"{v.get('source')}: {v.get('metric', '-')} "
                    f"{v['detail']}"
                    for v in verdicts if v["verdict"] == FAIL],
        "metrics": [
            {k: v.get(k) for k in
             ("verdict", "source", "metric", "value", "reference",
              "reference_source", "delta", "threshold", "direction",
              "platform", "degraded", "detail")}
            for v in verdicts],
    }


def render_markdown(verdicts: list[dict]) -> str:
    n_fail = sum(v["verdict"] == FAIL for v in verdicts)
    lines = ["# Perf sentry", "",
             ("**FAIL** — " if n_fail else "**PASS** — ")
             + f"{len(verdicts)} finding(s), {n_fail} failing", "",
             "| verdict | source | metric | value | reference | Δ | "
             "detail |",
             "|---|---|---|---|---|---|---|"]
    for v in verdicts:
        delta = (f"{v['delta']:+.1%}" if v.get("delta") is not None
                 else "-")
        ref = (f"{v['reference']:.4g}" if v.get("reference") is not None
               else "-")
        val = (f"{v['value']:.4g}" if isinstance(v.get("value"),
                                                 (int, float)) else "-")
        lines.append(
            f"| {v['verdict']} | {v['source']} "
            f"| {v.get('metric', '-')} | {val} | {ref} | {delta} "
            f"| {v['detail']} |")
    lines.append("")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="fail loudly on rc!=0 bench rows and >threshold "
                    "rate regressions in the latest BENCH_*/MULTICHIP_* "
                    "round (or explicit row files)")
    ap.add_argument("files", nargs="*",
                    help="row files to judge (driver wrappers or raw "
                         "bench.py stdout); default: the latest "
                         "BENCH_r*/MULTICHIP_r* round in --dir")
    ap.add_argument("--dir", default=".",
                    help="where the round archives live (history is "
                         "always read from here)")
    ap.add_argument("--baseline", default=None,
                    help="BASELINE.json path (its `published` numbers "
                         "join the reference set); default: "
                         "<dir>/BASELINE.json")
    ap.add_argument("--threshold", type=float, default=None,
                    help="override the default relative regression "
                         "threshold (e.g. 0.2 = fail below -20%%)")
    ap.add_argument("--metric-threshold", action="append", default=[],
                    metavar="SUBSTR=FRACTION",
                    help="per-metric threshold override, repeatable "
                         "(e.g. lb2=0.25)")
    ap.add_argument("--report-only", action="store_true",
                    help="always exit 0 (CI mode while the trajectory "
                         "is CPU-only); the report still says FAIL")
    ap.add_argument("--out", default=None,
                    help="also write the markdown summary here")
    ap.add_argument("--json", default=None, dest="json_out",
                    help="also write the machine-readable verdict here "
                         "(schema: round, per-metric deltas, verdict, "
                         "reasons — the health layer's `perf` rule "
                         "ingests it via TTS_HEALTH_PERF_JSON)")
    args = ap.parse_args(argv)

    overrides = {}
    if args.threshold is not None:
        overrides["_default"] = args.threshold
    for spec in args.metric_threshold:
        key, _, val = spec.partition("=")
        overrides[key] = float(val)

    if args.files:
        paths = args.files
        latest_round = -1
    else:
        rounds = [p for p in
                  glob.glob(os.path.join(args.dir, "BENCH_*.json"))
                  + glob.glob(os.path.join(args.dir,
                                           "MULTICHIP_*.json"))
                  if _round_of(p) >= 0]
        if not rounds:
            print(f"error: no BENCH_r*/MULTICHIP_r* rounds in "
                  f"{args.dir} and no files given", file=sys.stderr)
            return 2
        latest_round = max(_round_of(p) for p in rounds)
        paths = sorted(p for p in rounds
                       if _round_of(p) == latest_round)

    sources = [load_source(p) for p in paths]
    baseline = args.baseline or os.path.join(args.dir, "BASELINE.json")
    history = load_history(args.dir, latest_round, baseline,
                           exclude={os.path.abspath(p) for p in paths})
    verdicts = judge(sources, history, overrides)

    md = render_markdown(verdicts)
    print(md)
    if args.out:
        with open(args.out, "w") as f:
            f.write(md)
        print(f"# wrote {args.out}", file=sys.stderr)
    if args.json_out:
        with open(args.json_out, "w") as f:
            json.dump(render_json(verdicts, latest_round), f, indent=1)
            f.write("\n")
        print(f"# wrote {args.json_out}", file=sys.stderr)

    n_fail = sum(v["verdict"] == FAIL for v in verdicts)
    if n_fail and not args.report_only:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
