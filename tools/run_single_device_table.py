"""Headline single-device table: solve ta021-ta030 end-to-end on chip.

VERDICT r3 #7: run every instance of the reference's published
single-GPU campaign (pfsp/data/single-GPU.py) to the proven optimum on
one chip and tabulate against the V100/MI50 columns. LB2 with ub=opt
(the reference's campaign default operating point is ub=opt; its lb
default is LB1 — the repo chooses its strongest bound, which BASELINE.md
allows). Segmented driving keeps dispatches under the remote-TPU
watchdog; appends one JSON line per instance so a crash loses nothing.

    nohup python -u tools/run_single_device_table.py \
        > /tmp/table.log 2>&1 &
"""

import json
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import numpy as np  # noqa: E402

from tpu_tree_search.utils import compile_cache  # noqa: E402

compile_cache.enable()

from tpu_tree_search.engine import checkpoint, device  # noqa: E402
from tpu_tree_search.ops import batched  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402

from tpu_tree_search.utils import config as _cfg  # noqa: E402

OUT = _cfg.env_str("TTS_TABLE_OUT")
CHUNK = 32768
CAPACITY = 1 << 22
SEG = 2000

# V100 single-GPU runtimes, instance order ta29,30,22,27,23,28,25,26,24,21
# (reference pfsp/data/single-GPU.py:6,21)
V100 = {29: 4.18, 30: 4.91, 22: 5.63, 27: 19.82, 23: 41.04, 28: 73.75,
        25: 81.97, 26: 176.40, 24: 738.93, 21: 1308.79}
MI50 = {29: 7.56, 30: 9.14, 22: 10.52, 27: 38.08, 23: 79.44, 28: 140.81,
        25: 159.35, 26: 379.45, 24: 1445.49, 21: 2538.23}


def solve(inst: int) -> dict:
    p = taillard.processing_times(inst)
    ub = taillard.optimal_makespan(inst)
    tables = batched.make_tables(p)
    jobs = p.shape[1]
    state = device.init_state(jobs, CAPACITY, ub, p_times=p)
    t0 = time.perf_counter()

    def run_fn(s, target):
        return device.run(tables, s, 2, CHUNK, max_iters=target)

    def heartbeat(r):
        # segment deltas identify remote-tunnel stalls (host load 0 for
        # minutes) so contaminated rows can be re-run or annotated
        print(f"  [seg {r.segment}] iters={r.iters} tree={r.tree} "
              f"t={r.elapsed:.1f}s", flush=True)

    out = checkpoint.run_segmented(run_fn, state, segment_iters=SEG,
                                  heartbeat=heartbeat)
    elapsed = time.perf_counter() - t0
    assert int(out.size) == 0 and not bool(out.overflow)
    assert int(out.best) == ub, (inst, int(out.best), ub)
    return {"inst": inst, "elapsed_s": round(elapsed, 2),
            "tree": int(out.tree), "sol": int(out.sol),
            "best": int(out.best), "evals": int(out.evals),
            "iters": int(out.iters),
            "v100_s": V100[inst], "mi50_s": MI50[inst],
            "vs_v100": round(V100[inst] / elapsed, 3),
            "vs_mi50": round(MI50[inst] / elapsed, 3)}


def main():
    done = set()
    if os.path.exists(OUT):
        with open(OUT) as f:
            done = {json.loads(ln)["inst"] for ln in f if ln.strip()}
    order = ([int(x) for x in sys.argv[1:]] or
             [29, 30, 22, 27, 23, 28, 25, 26, 24])  # ta021 solved separately
    for inst in order:
        if inst in done:
            print(f"ta{inst:03d}: already done, skipping", flush=True)
            continue
        print(f"ta{inst:03d}: solving...", flush=True)
        row = solve(inst)
        with open(OUT, "a") as f:
            f.write(json.dumps(row) + "\n")
        print(f"ta{inst:03d}: {row['elapsed_s']}s "
              f"(V100 {row['v100_s']}s, x{row['vs_v100']}) "
              f"tree={row['tree']}", flush=True)


if __name__ == "__main__":
    main()
