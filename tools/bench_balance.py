"""Measure the balance exchange at PRODUCTION shapes (VERDICT r3 #6).

Times `_balance_round` on the 8-worker virtual CPU mesh with
20x20-class pools at chunk 32768 and a sweep of transfer_cap values
(including the byte-budgeted default), reporting ms/round and the
all_to_all buffer footprint. Multi-chip hardware is not reachable from
this environment, so absolute times are CPU-mesh numbers — the useful
outputs are the RELATIVE cost vs transfer_cap and the buffer sizes,
which are backend-independent.

    JAX_PLATFORMS=cpu XLA_FLAGS=--xla_force_host_platform_device_count=8 \
        python tools/bench_balance.py
"""

import functools
import os
import sys
import time

os.environ.setdefault("JAX_PLATFORMS", "cpu")
flags = os.environ.get("XLA_FLAGS", "")
if "host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8").strip()

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import jax.numpy as jnp  # noqa: E402
import numpy as np  # noqa: E402

from tpu_tree_search.engine import device, distributed  # noqa: E402
from tpu_tree_search.ops import batched, reference as ref  # noqa: E402
from tpu_tree_search.parallel.mesh import shard_map, worker_mesh  # noqa: E402
from tpu_tree_search.problems import taillard  # noqa: E402
from jax.sharding import PartitionSpec as P  # noqa: E402


def main():
    from tpu_tree_search.utils import config as _cfg
    chunk = _cfg.env_int("TTS_BAL_CHUNK")
    capacity = _cfg.env_int("TTS_BAL_CAP")
    rounds = _cfg.env_int("TTS_BAL_ROUNDS")
    p = taillard.processing_times(21)
    jobs, machines = p.shape[1], p.shape[0]
    mesh = worker_mesh(8)
    D = mesh.devices.size

    # unbalanced production-like pools: worker 0 loaded, rest light —
    # every round has real flow
    rng = np.random.default_rng(0)
    sizes = [int(0.5 * capacity)] + [chunk // 2] * (D - 1)
    prmu = np.zeros((D, jobs, capacity), np.int16)
    depth = np.zeros((D, capacity), np.int16)
    aux = np.zeros((D, machines, capacity), device.aux_dtype(p))
    for d in range(D):
        n = sizes[d]
        pm = np.argsort(rng.random((n, jobs)), axis=1).astype(np.int16)
        dp = rng.integers(4, 12, n).astype(np.int16)
        prmu[d, :, :n] = pm.T
        depth[d, :n] = dp
        aux[d, :, :n] = ref.prefix_front_remain(p, pm, dp)[:, :machines].T

    base = device.init_state(jobs, capacity, 3000, p_times=p)
    leaves = []
    for f in base._fields:
        x = getattr(base, f)
        if f in ("prmu",):
            leaves.append(jnp.asarray(prmu))
        elif f == "depth":
            leaves.append(jnp.asarray(depth))
        elif f == "aux":
            leaves.append(jnp.asarray(aux))
        elif f == "size":
            leaves.append(jnp.asarray(np.asarray(sizes, np.int32)))
        else:
            leaves.append(jnp.broadcast_to(x, (D,) + x.shape).copy())
    specs = device.SearchState(*(P("workers") for _ in base._fields))

    A = machines
    bytes_per_col = 2 * jobs + 4 * A + 2
    caps = sorted({chunk // 2, chunk, 2 * chunk, 4 * chunk,
                   max(min(4 * chunk, distributed.BALANCE_BYTE_BUDGET
                           // (bytes_per_col * D)), 256)})
    for cap in caps:
        limit = device.row_limit(capacity, chunk, jobs) - D * cap

        @functools.partial(jax.jit)
        def run(leaves_):
            def body(*ls):
                s = device.SearchState(*(x[0] for x in ls))
                for _ in range(1):
                    s = distributed._balance_round(s, cap, chunk // 2,
                                                   limit)
                return tuple(x[None] for x in s)
            return shard_map(body, mesh,
                             in_specs=tuple(specs),
                             out_specs=tuple(specs))(*leaves_)

        out = run(tuple(leaves))
        jax.block_until_ready(out)
        t0 = time.perf_counter()
        for _ in range(rounds):
            out = run(tuple(out))
        jax.block_until_ready(out)
        dt = (time.perf_counter() - t0) / rounds * 1e3
        buf_mb = bytes_per_col * D * cap / 2**20
        print(f"transfer_cap={cap:7d}: {dt:8.2f} ms/round  "
              f"buffer {buf_mb:7.1f} MB/worker/way  "
              f"moved<= {D * cap} nodes/worker")


if __name__ == "__main__":
    main()
