"""Compile-cost ledger table from a server status snapshot.

The executor cache (tpu_tree_search/service/executors.ExecutorCache)
records, per cached loop, its trace and compile wall seconds and —
where the backend supports ``compiled.cost_analysis()`` — the
executable's FLOPs / bytes accessed. This tool renders that ledger as
a table from either

- a running server's ``/status`` endpoint (pass the URL), or
- a saved status-snapshot JSON file (``status_snapshot()`` dumped to
  disk; the ledger rides its ``compile_ledger`` key).

    python tools/compile_report.py http://127.0.0.1:9100/status
    python tools/compile_report.py /tmp/status.json

The same numbers feed the ``tts_compile_seconds`` histogram on
``/metrics``; this is the per-entry view (WHICH shapes paid WHAT),
the histogram is the aggregate.

Since the disk AOT tier (service/aot_cache.py) each row also carries
``source`` — ``disk`` (deserialized, zero compiles) vs ``compile``
(fresh trace+compile) — and the deserialize seconds; the snapshot's
``aot_cache`` stats render as a footer. The CI restart-replay leg
asserts ``source=disk`` on every replayed key from exactly this view.
"""

import argparse
import json
import sys


def load_snapshot(source: str) -> dict:
    if source.startswith(("http://", "https://")):
        import urllib.request
        with urllib.request.urlopen(source, timeout=10) as r:
            return json.load(r)
    with open(source) as f:
        return json.load(f)


def _fmt_num(v, scale: float = 1.0, suffix: str = "") -> str:
    if v is None:
        return "-"
    return f"{float(v) / scale:.2f}{suffix}"


def render(ledger: list[dict], cache: dict | None = None,
           aot: dict | None = None) -> str:
    hdr = (f"{'#':>2} {'source':>7} {'build_s':>8} {'trace_s':>8} "
           f"{'compile_s':>9} {'deser_s':>8} {'gflops':>9} "
           f"{'MB_acc':>8} {'method':>10}  key")
    lines = ["compile-cost ledger (one row per cached executable)",
             hdr, "-" * len(hdr)]
    total = deser_total = 0.0
    n_disk = 0
    for i, e in enumerate(ledger):
        tc = (e.get("trace_s") or 0.0) + (e.get("compile_s") or 0.0)
        total += tc
        deser_total += e.get("deserialize_s") or 0.0
        if e.get("source") == "disk":
            n_disk += 1
        lines.append(
            f"{i:>2} {e.get('source') or '-':>7} "
            f"{_fmt_num(e.get('build_s')):>8} "
            f"{_fmt_num(e.get('trace_s')):>8} "
            f"{_fmt_num(e.get('compile_s')):>9} "
            f"{_fmt_num(e.get('deserialize_s')):>8} "
            f"{_fmt_num(e.get('flops'), 1e9):>9} "
            f"{_fmt_num(e.get('bytes_accessed'), 2**20):>8} "
            f"{e.get('method') or 'pending':>10}  "
            f"{str(e.get('key', ''))[:60]}")
    lines.append("")
    summary = (f"{len(ledger)} executable(s), "
               f"{total:.2f} s total trace+compile")
    if n_disk:
        summary += (f"; {n_disk} replayed from disk in "
                    f"{deser_total:.2f} s (zero compiles)")
    if cache:
        hits, misses = cache.get("hits", 0), cache.get("misses", 0)
        served = hits + misses
        summary += (f"; cache {hits} hit(s) / {misses} miss(es)"
                    + (f" — {hits / served:.0%} of lookups reused a "
                       "paid compile" if served else ""))
    lines.append(summary)
    if aot:
        lines.append(
            f"aot disk cache [{aot.get('dir')}]: "
            f"{aot.get('entries')} entr(y/ies), {aot.get('hits')} "
            f"hit(s) / {aot.get('misses')} miss(es), "
            f"{aot.get('mismatches')} fingerprint mismatch(es), "
            f"{aot.get('quarantined')} quarantined, "
            f"{aot.get('writes')} write(s)")
    return "\n".join(lines)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="render the executor cache's compile-cost ledger "
                    "from a /status URL or a saved snapshot JSON")
    ap.add_argument("source", help="http(s)://.../status URL or a "
                                   "status-snapshot JSON file")
    args = ap.parse_args(argv)
    try:
        snap = load_snapshot(args.source)
    except (OSError, json.JSONDecodeError) as e:
        print(f"error: cannot read {args.source}: {e}", file=sys.stderr)
        return 1
    ledger = snap.get("compile_ledger")
    if not ledger:
        print(f"error: no compile_ledger in {args.source} — is this a "
              "status_snapshot() from a server that has served at "
              "least one request?", file=sys.stderr)
        return 1
    print(render(ledger, snap.get("executor_cache"),
                 snap.get("aot_cache")))
    return 0


if __name__ == "__main__":
    sys.exit(main())
