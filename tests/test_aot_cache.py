"""Zero-compile cold start: the disk-persistent AOT executable cache.

The contract, pinned deterministically on the virtual 8-device CPU
mesh:

- a restarted process (fresh ExecutorCache + AOTCache over the same
  directory) replays previously-compiled loops from disk with ZERO
  ``lower()``/``compile()`` calls (``_Entry._compile_fresh`` is
  instrumented to prove it) and bit-identical search results;
- executor-ready latency with a warm cache is >= 5x faster than a cold
  compile (the acceptance bar; measured ~8-10x here);
- a fingerprint-mismatched entry (wrong runtime) is IGNORED and
  recompiled — never loaded — and the recompile overwrites it;
- a corrupt or truncated entry is QUARANTINED (renamed ``*.corrupt``),
  recompiled to bit-identical results, and never loaded again;
- donated vs non-donated loop variants are keyed (and persisted)
  separately;
- boot pre-warm is idempotent, bounded, covers the spool backlog, and
  a pre-warmed shape's first request pays no compile;
- when serialization is unsupported (per-program or probe-wide) the
  cache degrades to in-memory-only, loudly but harmlessly;
- the health layer's compile_storm rule does NOT fire on a boot-time
  disk replay (true unplanned compiles still fire it).
"""

import json
import os
import sys
import time

import pytest

from tpu_tree_search.engine import distributed
from tpu_tree_search.parallel.mesh import worker_mesh
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest, SearchServer
from tpu_tree_search.service.aot_cache import (AOTCache, probe,
                                               runtime_fingerprint)
from tpu_tree_search.service import aot_cache as aot_mod
from tpu_tree_search.service import executors as ex_mod
from tpu_tree_search.service.executors import ExecutorCache

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

KW = dict(chunk=8, capacity=1 << 12, min_seed=4)


def small(seed, jobs=7, machines=3):
    return PFSPInstance.synthetic(jobs=jobs, machines=machines,
                                  seed=seed)


def run_search(p, cache, mesh=None, **kw):
    args = {**KW, **kw}
    got = distributed.search(p, lb_kind=args.pop("lb_kind", 1),
                             mesh=mesh or worker_mesh(4),
                             loop_cache=cache, **args)
    return (got.explored_tree, got.explored_sol, got.best)


@pytest.fixture
def no_fresh_compiles(monkeypatch):
    """Instrument the ONLY trace/compile door in the executor entry;
    the test asserts the recorded list stays empty. (A plain raise
    would be swallowed by the first-call fallback and hide the compile
    it was meant to catch.)"""
    calls = []
    orig = ex_mod._Entry._compile_fresh

    def spy(self, *args):
        calls.append(self.record.get("key"))
        return orig(self, *args)

    monkeypatch.setattr(ex_mod._Entry, "_compile_fresh", spy)
    return calls


def test_probe_supported_on_this_pin():
    """The pinned jax round-trips executables on the CPU backend (when
    this starts failing after a pin bump, the cache degrades to
    in-memory-only by design — see the fallback test below)."""
    assert probe() is True


def test_restart_replay_zero_compiles_bit_identical(tmp_path,
                                                    no_fresh_compiles):
    inst = small(5, jobs=8)
    root = tmp_path / "aot"

    # lifetime 1: cold — compiles (exactly one fresh compile), persists
    aot1 = AOTCache(root)
    c1 = ExecutorCache(aot=aot1)
    ref = run_search(inst.p_times, c1)
    assert no_fresh_compiles and len(no_fresh_compiles) == 1
    led1 = c1.ledger_snapshot()
    assert [e["source"] for e in led1] == ["compile"]
    aot1.drain()
    assert aot1.snapshot()["writes"] == 1
    aot1.close()
    no_fresh_compiles.clear()

    # lifetime 2: fresh in-process caches over the same dir — the
    # restarted server. ZERO lower()/compile() calls, ledger says disk,
    # results bit-identical.
    aot2 = AOTCache(root)
    c2 = ExecutorCache(aot=aot2)
    got = run_search(inst.p_times, c2)
    assert got == ref
    assert no_fresh_compiles == []
    led2 = c2.ledger_snapshot()
    assert [e["source"] for e in led2] == ["disk"]
    assert led2[0]["deserialize_s"] > 0
    assert led2[0]["trace_s"] == 0.0 and led2[0]["compile_s"] == 0.0
    snap = aot2.snapshot()
    assert snap["hits"] == 1 and snap["errors"] == 0
    assert c2.storm_signal() == 0       # a replay is not a compile
    aot2.close()


def test_executor_ready_latency_warm_5x_faster(tmp_path):
    """The acceptance bar: executor-ready latency on the CPU test mesh
    drops >= 5x with a warm cache dir (measured ~8-10x; the margin
    absorbs CI noise). Production shapes compile for minutes while the
    deserialize stays sub-second, so the real-world ratio is larger."""
    p = small(0, jobs=20, machines=10).p_times
    mesh = worker_mesh(8)
    root = tmp_path / "aot"

    def executor_ready(expect):
        # fresh in-process caches each time: every warm measurement is
        # a true restart (disk entry only), never a memo hit
        aot = AOTCache(root)
        cache = ExecutorCache(aot=aot)
        t0 = time.perf_counter()
        how = distributed.prewarm(p, chunk=64, capacity=1 << 14,
                                  mesh=mesh, loop_cache=cache)
        dt = time.perf_counter() - t0
        assert how == expect
        aot.drain()
        aot.close()
        return dt

    cold = executor_ready("compile")
    # best-of-3 on the warm side: the ~0.1 s deserialize is small
    # enough that one unlucky scheduler stall under a loaded test
    # process can halve the measured ratio; the minimum is the honest
    # capability number (the cold compile is seconds — one sample is
    # stable)
    warm = min(executor_ready("disk") for _ in range(3))
    ratio = cold / warm
    assert ratio >= 5.0, f"warm only {ratio:.1f}x faster: " \
                         f"cold={cold:.3f}s warm={warm:.3f}s"


def test_fingerprint_mismatch_ignored_never_loaded(tmp_path,
                                                   no_fresh_compiles):
    inst = small(3, jobs=8)
    root = tmp_path / "aot"

    # runtime A persists an entry
    aot_a = AOTCache(root, fingerprint_extra={"sim_runtime": "A"})
    ca = ExecutorCache(aot=aot_a)
    ref = run_search(inst.p_times, ca)
    aot_a.drain()
    aot_a.close()
    assert len(no_fresh_compiles) == 1
    no_fresh_compiles.clear()

    # runtime B (injected fingerprint drift — the jax-bump/telemetry-
    # flip simulation) must IGNORE it and recompile, bit-identically
    aot_b = AOTCache(root, fingerprint_extra={"sim_runtime": "B"})
    cb = ExecutorCache(aot=aot_b)
    got = run_search(inst.p_times, cb)
    assert got == ref
    assert len(no_fresh_compiles) == 1          # recompiled, once
    assert [e["source"] for e in cb.ledger_snapshot()] == ["compile"]
    snap = aot_b.snapshot()
    assert snap["mismatches"] == 1 and snap["hits"] == 0
    # a mismatch is not corruption: nothing quarantined, and B's
    # recompile OVERWRITES the stale entry (latest runtime wins)
    assert snap["quarantined"] == 0
    aot_b.drain()
    aot_b.close()
    no_fresh_compiles.clear()

    # runtime B restarted: its own entry now loads
    aot_b2 = AOTCache(root, fingerprint_extra={"sim_runtime": "B"})
    cb2 = ExecutorCache(aot=aot_b2)
    assert run_search(inst.p_times, cb2) == ref
    assert no_fresh_compiles == []
    assert aot_b2.snapshot()["hits"] == 1
    aot_b2.close()


@pytest.mark.parametrize("damage", ["flip", "truncate"])
def test_corrupt_entry_quarantined_and_recompiled(tmp_path, damage,
                                                  no_fresh_compiles):
    inst = small(4, jobs=8)
    root = tmp_path / "aot"
    aot1 = AOTCache(root)
    ref = run_search(inst.p_times, ExecutorCache(aot=aot1))
    aot1.drain()
    aot1.close()
    no_fresh_compiles.clear()

    (entry,) = [p for p in root.iterdir() if p.suffix == ".aot"]
    blob = bytearray(entry.read_bytes())
    if damage == "flip":
        blob[len(blob) // 2] ^= 0xFF            # payload bit-flip
        entry.write_bytes(bytes(blob))
    else:
        entry.write_bytes(bytes(blob[:len(blob) // 2]))  # torn write

    aot2 = AOTCache(root)
    c2 = ExecutorCache(aot=aot2)
    got = run_search(inst.p_times, c2)
    assert got == ref                            # bit-identical recompile
    assert len(no_fresh_compiles) == 1
    snap = aot2.snapshot()
    assert snap["errors"] == 1 and snap["quarantined"] == 1
    assert snap["hits"] == 0
    # the poisoned bytes are parked beside the cache, never loadable
    quarantined = [p for p in root.iterdir()
                   if p.name.endswith(".corrupt")]
    assert len(quarantined) == 1
    aot2.drain()     # the recompile re-persisted a clean entry
    assert aot2.snapshot()["writes"] == 1
    aot2.close()
    no_fresh_compiles.clear()

    aot3 = AOTCache(root)
    assert run_search(inst.p_times, ExecutorCache(aot=aot3)) == ref
    assert no_fresh_compiles == []
    assert aot3.snapshot()["hits"] == 1
    aot3.close()


def test_repeat_quarantines_keep_distinct_forensic_copies(tmp_path):
    """Quarantine targets are per-writer unique AND counter-suffixed:
    corrupt incarnations of the SAME entry quarantined twice (same
    process, or N servers racing on shared fleet storage) keep both
    forensic copies instead of os.replace-ing over each other."""
    root = tmp_path / "aot"
    aot = AOTCache(root)
    key = ("probe", "key")
    for round_ in range(2):
        aot.path_for(key).write_bytes(b"\xffnot-an-entry" * 4)
        assert aot.load(key) is None
    quarantined = sorted(p.name for p in root.iterdir()
                         if p.name.endswith(".corrupt"))
    assert len(quarantined) == 2, quarantined
    assert len(set(quarantined)) == 2
    assert aot.snapshot()["quarantined"] == 2
    aot.close()


def test_donated_variant_keyed_separately(tmp_path):
    p = small(0, jobs=8).p_times
    mesh = worker_mesh(4)
    aot = AOTCache(tmp_path / "aot")
    cache = ExecutorCache(aot=aot)
    assert distributed.prewarm(p, chunk=8, capacity=4096, mesh=mesh,
                               loop_cache=cache,
                               donate=False) == "compile"
    assert distributed.prewarm(p, chunk=8, capacity=4096, mesh=mesh,
                               loop_cache=cache,
                               donate=True) == "compile"
    ledger = cache.ledger_snapshot()
    assert len(ledger) == 2
    assert [("donate" in e["key"]) for e in ledger] == [False, True]
    aot.drain()
    assert aot.snapshot()["writes"] == 2         # two distinct files
    assert aot.snapshot()["entries"] == 2
    # idempotent: warming again is a no-op on both variants
    assert distributed.prewarm(p, chunk=8, capacity=4096, mesh=mesh,
                               loop_cache=cache, donate=True) == "warm"
    aot.close()


def test_prewarm_boot_idempotent_spool_and_first_request(tmp_path):
    """serve-boot pre-warm: explicit JxM + spool-backlog shapes are
    readied per submesh before any request; a second boot pass is a
    no-op; the first request of a pre-warmed shape pays no compile."""
    from tpu_tree_search.service import spool

    inst = small(7, jobs=7)
    spool_dir = tmp_path / "spool"
    spool.submit_file(spool_dir, {"p_times": inst.p_times.tolist(),
                                  "lb": 1, "chunk": 8,
                                  "capacity": 4096, "min_seed": 4})
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      segment_iters=256,
                      aot_cache_dir=tmp_path / "aot",
                      share_incumbent=False) as srv:
        s1 = srv.prewarm_boot(spec="spool", spool_dir=spool_dir,
                              concurrency=1)
        assert s1["shapes"] == 1 and s1["warms"] == 2   # per submesh
        assert s1["by"]["compile"] == 2 and s1["errors"] == 0
        # idempotent: the same boot pass again readies nothing new
        s2 = srv.prewarm_boot(spec="spool", spool_dir=spool_dir)
        assert s2["by"] == {"disk": 0, "compile": 0, "warm": 2,
                            "skipped": 0}
        assert len(srv.cache) == 2
        # planned compiles never read as a storm
        assert srv.cache.storm_signal() == 0
        # the pre-warmed shape's first request: in-memory hit, no
        # further build — warm capacity existed before it arrived
        misses0 = srv.cache.snapshot()["misses"]
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE"
        assert srv.cache.snapshot()["misses"] == misses0
        assert srv.status_snapshot()["aot_cache"]["writes"] == 2


def test_server_restart_replay_end_to_end(tmp_path, no_fresh_compiles):
    """The acceptance demo at the service level: a restarted
    SearchServer re-serves a previously-served shape with zero fresh
    compiles (ledger source=disk) and bit-identical results."""
    inst = small(9, jobs=8)
    aot_dir = tmp_path / "aot"

    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd1",
                      segment_iters=256, aot_cache_dir=aot_dir,
                      share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE"
        ref = (rec.result.explored_tree, rec.result.explored_sol,
               rec.result.best)
        assert [e["source"] for e in
                srv.status_snapshot()["compile_ledger"]] == ["compile"]
    assert len(no_fresh_compiles) == 1
    no_fresh_compiles.clear()

    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd2",
                      segment_iters=256, aot_cache_dir=aot_dir,
                      share_incumbent=False) as srv2:
        rid = srv2.submit(SearchRequest(p_times=inst.p_times,
                                        lb_kind=1, **KW))
        rec = srv2.result(rid, timeout=300)
        assert rec.state == "DONE"
        assert (rec.result.explored_tree, rec.result.explored_sol,
                rec.result.best) == ref
        snap = srv2.status_snapshot()
        assert [e["source"] for e in snap["compile_ledger"]] == ["disk"]
        assert snap["aot_cache"]["hits"] == 1
    assert no_fresh_compiles == []


def test_serialize_unsupported_per_program_fallback(tmp_path,
                                                    monkeypatch):
    """A program the pin cannot serialize still serves from memory:
    store counts an error, writes nothing, and the search is green."""
    from jax.experimental import serialize_executable as se

    def boom(compiled):
        raise TypeError("cannot serialize this program (simulated)")

    monkeypatch.setattr(se, "serialize", boom)
    inst = small(2, jobs=8)
    aot = AOTCache(tmp_path / "aot")
    cache = ExecutorCache(aot=aot)
    ref = run_search(inst.p_times, cache)
    aot.drain()
    snap = aot.snapshot()
    assert snap["writes"] == 0 and snap["errors"] == 1
    assert snap["entries"] == 0
    # the in-memory entry still serves the next same-shape request
    assert run_search(inst.p_times, cache) == ref
    assert cache.snapshot()["hits"] >= 1
    aot.close()


def test_probe_failure_degrades_to_memory_only(tmp_path, monkeypatch):
    """When the capability probe says the pin cannot round-trip a
    program, the server constructs NO disk tier (aot is None, the
    snapshot says so) and serves exactly as before PR 8."""
    monkeypatch.setattr(aot_mod, "_probe_result", False)
    inst = small(1, jobs=7)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      segment_iters=256,
                      aot_cache_dir=tmp_path / "aot",
                      share_incumbent=False) as srv:
        assert srv.aot is None
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        assert srv.result(rid, timeout=300).state == "DONE"
        assert srv.status_snapshot()["aot_cache"] is None
    assert not (tmp_path / "aot").exists()


def test_compile_storm_rule_ignores_replay_counts_fresh(tmp_path):
    """The health satellite: a boot-time mass disk replay must not
    fire compile_storm; the same number of true unplanned compiles
    must."""
    import types

    from tpu_tree_search.obs import health as obs_health

    p = small(0, jobs=8).p_times
    mesh = worker_mesh(4)
    root = tmp_path / "aot"
    # seed the disk with both lb variants
    aot0 = AOTCache(root)
    c0 = ExecutorCache(aot=aot0)
    for lb in (1, 2):
        distributed.prewarm(p, lb_kind=lb, chunk=8, capacity=4096,
                            mesh=mesh, loop_cache=c0)
    aot0.drain()
    aot0.close()

    def monitor_for(cache):
        th = obs_health.Thresholds(compile_storm=2)
        return obs_health.HealthMonitor(
            server=types.SimpleNamespace(cache=cache), rules=[
                r for r in obs_health.default_rules(th)
                if r.name == "compile_storm"],
            thresholds=th, interval_s=0, autostart=False)

    # restarted lifetime: 2 disk replays inside one interval -> quiet
    aot1 = AOTCache(root)
    c1 = ExecutorCache(aot=aot1)
    mon = monitor_for(c1)
    mon.evaluate_now()                               # baseline
    for lb in (1, 2):
        distributed.prewarm(p, lb_kind=lb, chunk=8, capacity=4096,
                            mesh=mesh, loop_cache=c1)
    snap = mon.evaluate_now()
    assert snap["firing"] == 0
    assert [e["source"] for e in c1.ledger_snapshot()] == ["disk"] * 2
    aot1.close()

    # same count of TRUE unplanned compiles (no disk tier, request
    # path) -> fires
    c2 = ExecutorCache()
    mon2 = monitor_for(c2)
    mon2.evaluate_now()
    for lb in (1, 2):
        run_search(p, c2, lb_kind=lb)
    snap = mon2.evaluate_now()
    assert snap["firing"] == 1
    assert c2.storm_signal() == 2


def test_compile_report_renders_source_and_deserialize(tmp_path):
    import compile_report

    inst = small(6, jobs=8)
    root = tmp_path / "aot"
    aot1 = AOTCache(root)
    run_search(inst.p_times, ExecutorCache(aot=aot1))
    aot1.drain()
    aot1.close()
    aot2 = AOTCache(root)
    c2 = ExecutorCache(aot=aot2)
    run_search(inst.p_times, c2)
    table = compile_report.render(c2.ledger_snapshot(), c2.snapshot(),
                                  aot2.snapshot())
    assert "source" in table and "deser_s" in table
    assert "disk" in table and "replayed from disk" in table
    assert "aot disk cache" in table
    # the CLI path renders a full status-snapshot dump with the new key
    snap_path = tmp_path / "status.json"
    snap_path.write_text(json.dumps(
        {"compile_ledger": c2.ledger_snapshot(),
         "executor_cache": c2.snapshot(),
         "aot_cache": aot2.snapshot()}))
    assert compile_report.main([str(snap_path)]) == 0
    aot2.close()


def test_fingerprint_contents():
    """The fields a wrong-runtime load is rejected on (the telemetry
    width is the subtle one: the static flag changes traced state
    SHAPES without appearing in the executor key)."""
    fp = runtime_fingerprint()
    assert {"jax", "jaxlib", "platform", "device_count",
            "device_kinds", "process_count",
            "telemetry_width"} <= set(fp)
    assert runtime_fingerprint({"x": 1})["x"] == 1
    assert runtime_fingerprint() == fp           # deterministic
