"""Operational health layer: SLO alert engine, node-conservation
auditor, fleet aggregator, dashboard, doctor CLI — plus the satellite
valves (tracelog rotation, metric cardinality cap, per-request series
retirement on EVERY terminal state, perf_sentry --json).

The load-bearing assertions (ISSUE acceptance):

- an injected `delay_segment` stall is detected within one evaluation
  interval — the `stall` alert reaches `firing`, then `resolved` after
  the request completes, and fires exactly once;
- a synthetically corrupted node count trips the auditor and the
  `audit` alert within one evaluation, resolving after recovery;
- search results stay bit-identical with the health daemon AND the
  auditor enabled;
- `doctor` exits nonzero against a server with a firing alert and zero
  against a healthy fleet; `obs/aggregate` merges 2 concurrent
  servers origin-labeled; /dashboard renders from stdlib only.
"""

import json
import os
import pathlib
import sys
import time
import urllib.request

import numpy as np
import pytest

from tpu_tree_search.engine import checkpoint, distributed
from tpu_tree_search.obs import (aggregate, audit, dashboard, health,
                                 metrics, tracelog)
from tpu_tree_search.obs.httpd import start_http_server
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest, SearchServer

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

KW = dict(chunk=8, capacity=1 << 12, min_seed=4)


@pytest.fixture
def fresh_obs(tmp_path):
    log = tracelog.TraceLog(capacity=1 << 16,
                            sink_path=tmp_path / "trace.jsonl")
    prev_log = tracelog.install(log)
    reg = metrics.Registry()
    prev_reg = metrics.install(reg)
    audit.clear_findings()
    try:
        yield log, reg
    finally:
        tracelog.install(prev_log)
        metrics.install(prev_reg)
        audit.clear_findings()


def wait_until(cond, timeout=60.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timed out on {what}"
        time.sleep(0.02)


# ------------------------------------------------------ alert lifecycle


def test_alert_lifecycle_pending_firing_resolved(fresh_obs):
    log, _ = fresh_obs
    flag = {"on": True}
    rule = health.Rule("toy", lambda ctx: (flag["on"], {"k": 1}),
                       severity="warn", for_s=0.05)
    reg = metrics.Registry()
    mon = health.HealthMonitor(rules=[rule], registry=reg, interval_s=0)
    snap = mon.evaluate_now()
    # dwell not yet served: pending, not firing
    assert snap["alerts"][0]["state"] == "pending"
    assert snap["firing"] == 0
    assert reg.gauge("tts_alerts").value(rule="toy",
                                         severity="warn") == 0.5
    time.sleep(0.06)
    snap = mon.evaluate_now()
    a = snap["alerts"][0]
    assert a["state"] == "firing" and snap["firing"] == 1
    assert a["fired_count"] == 1
    assert reg.gauge("tts_alerts").value(rule="toy",
                                         severity="warn") == 1.0
    flag["on"] = False
    snap = mon.evaluate_now()
    a = snap["alerts"][0]
    assert a["state"] == "resolved" and snap["firing"] == 0
    assert reg.gauge("tts_alerts").value(rule="toy",
                                         severity="warn") == 0.0
    names = [r["name"] for r in log.records()
             if r["name"].startswith("alert.")]
    assert names == ["alert.pending", "alert.firing", "alert.resolved"]
    assert reg.counter("tts_alerts_fired_total").value(rule="toy") == 1


def test_pending_that_clears_is_not_an_incident(fresh_obs):
    log, _ = fresh_obs
    flag = {"on": True}
    rule = health.Rule("maybe", lambda ctx: (flag["on"], {}),
                       for_s=100.0)
    mon = health.HealthMonitor(rules=[rule],
                               registry=metrics.Registry(),
                               interval_s=0)
    mon.evaluate_now()
    flag["on"] = False
    snap = mon.evaluate_now()
    # the unconfirmed pending dropped without a resolved event
    assert snap["alerts"] == []
    assert not any(r["name"] == "alert.resolved" for r in log.records())


def test_broken_rule_does_not_kill_the_monitor(fresh_obs):
    log, _ = fresh_obs

    def boom(ctx):
        raise RuntimeError("rule bug")

    ok = health.Rule("fine", lambda ctx: (True, {}))
    mon = health.HealthMonitor(
        rules=[health.Rule("broken", boom), ok],
        registry=metrics.Registry(), interval_s=0)
    snap = mon.evaluate_now()
    assert snap["firing"] == 1            # the healthy rule still ran
    assert any(r["name"] == "alert.rule_error"
               for r in log.records())


# ------------------------------------- stall detection (delay_segment)


def test_stall_alert_fires_once_and_resolves_bitident(
        fresh_obs, tmp_path, monkeypatch):
    """ISSUE acceptance: a delay_segment stall is detected within one
    evaluation interval (firing), resolves after recovery, fires
    exactly once — and the served result is bit-identical to a
    standalone run, with the health daemon and auditor enabled."""
    # threshold chosen well above a natural CPU-mesh segment (~0.3 s
    # with fetch + collectives) and well below the injected 3 s delay,
    # so exactly the fault fires the rule
    monkeypatch.setenv("TTS_HEALTH_STALL_S", "1.0")
    # under TTS_OVERLAP the injected delay lands at the SPECULATIVE
    # dispatch of segment 2 — before the request's first heartbeat —
    # so the gap is judged against the warmup threshold; keep it above
    # a warm (executor-cache hit) dispatch and below the 3 s delay so
    # the rule still fires exactly once in either mode
    monkeypatch.setenv("TTS_HEALTH_STALL_WARMUP_S", "2.0")
    monkeypatch.setenv("TTS_AUDIT", "1")
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    base = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=8, **KW)
    # share_incumbent pinned off: the warm request below publishes the
    # optimum, and the bit-identity assertion vs `base` defines
    # UNSHARED semantics (sharing is covered by tests/test_overlap.py)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      health_interval_s=0.05,
                      share_incumbent=False) as srv:
        # warm the executor cache so the faulted request's dispatch
        # goes straight into segments — otherwise the first compile
        # itself (seconds on CPU) trips the 0.3 s stall threshold and
        # the exactly-once assertion below becomes timing-dependent
        warm = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16, **KW))
        assert srv.result(warm, timeout=300).state == "DONE"
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="delay_segment=2:3.0", **KW))

        def stall_state():
            return srv.health.alerts.get("stall")

        wait_until(lambda: stall_state() is not None
                   and stall_state().state == health.FIRING,
                   timeout=90, what="stall alert firing")
        a = stall_state()
        assert a.severity == "critical"
        assert a.detail["request_id"] == rid
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        wait_until(lambda: stall_state().state == health.RESOLVED,
                   timeout=90, what="stall alert resolving")
        assert stall_state().fired_count == 1
        # bit-identical with the judge and auditor watching
        res = rec.result
        assert (res.explored_tree, res.explored_sol, res.best) == \
            (base.explored_tree, base.explored_sol, base.best)
    # the auditor saw the served result and found nothing wrong
    assert audit.findings()
    assert all(f.ok for f in audit.findings())


def test_stall_rule_grants_compile_warmup_grace(fresh_obs):
    """Before a request's FIRST heartbeat the dispatch gap includes
    XLA trace+compile; the stall rule must judge it against the
    warmup threshold, not false-fire a critical alert."""

    class FakeServer:
        progress = {}

        def heartbeat_ages(self):
            return {"req-0000": 50.0}

        def status_snapshot(self):
            return {"requests": {"req-0000": {
                "state": "RUNNING", "progress": self.progress}}}

    srv = FakeServer()
    th = health.Thresholds(stall_s=30.0, stall_warmup_s=300.0)
    mon = health.HealthMonitor(
        server=srv, rules=health.default_rules(th),
        registry=metrics.Registry(), interval_s=0)
    snap = mon.evaluate_now()
    # 50 s without a heartbeat: over stall_s but still warming -> quiet
    assert not [a for a in snap["alerts"] if a["rule"] == "stall"]
    # the same age AFTER the first heartbeat is a real stall
    srv.progress = {"segment": 1}
    snap = mon.evaluate_now()
    firing = [a for a in snap["alerts"]
              if a["rule"] == "stall" and a["state"] == "firing"]
    assert firing and firing[0]["detail"]["warming"] is False


# -------------------------------------- auditor: corrupted node count


def test_corrupted_node_count_fires_audit_alert(fresh_obs, monkeypatch):
    monkeypatch.setenv("TTS_SEARCH_TELEMETRY", "1")
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=1)
    res = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                             n_devices=2, **KW)
    assert res.telemetry is not None
    assert all(f.ok for f in audit.findings())
    audit.clear_findings()
    # synthetic corruption: the explored-node counter drifts by one
    res.explored_tree += 1
    findings = audit.check_result(res)
    bad = [f for f in findings if not f.ok]
    assert [f.invariant for f in bad] == ["node_conservation"]
    # ...and the health layer's audit rule fires on the next evaluation
    mon = health.HealthMonitor(
        rules=health.default_rules(health.Thresholds(audit_window_s=60)),
        registry=metrics.Registry(), interval_s=0)
    snap = mon.evaluate_now()
    firing = [a for a in snap["alerts"] if a["state"] == "firing"]
    assert [a["rule"] for a in firing] == ["audit"]
    assert firing[0]["detail"]["invariant"] == "node_conservation"
    # recovery: findings age out / are cleared -> resolved
    audit.clear_findings()
    snap = mon.evaluate_now()
    assert snap["firing"] == 0
    assert snap["alerts"][0]["state"] == "resolved"


def test_telemetry_invariants_and_corrupted_telemetry(
        fresh_obs, monkeypatch):
    monkeypatch.setenv("TTS_SEARCH_TELEMETRY", "1")
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=3)
    res = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                             n_devices=4, **KW)
    audit.clear_findings()
    ok = audit.check_result(res)
    assert {f.invariant for f in ok} >= {
        "node_conservation", "children_conservation",
        "branched_is_tree", "bound_hist_exact", "steal_flow"}
    assert all(f.ok for f in ok)
    # corrupt the telemetry side instead of the counter side
    res.telemetry["steal_sent"] += 7
    bad = [f for f in audit.check_result(res) if not f.ok]
    assert [f.invariant for f in bad] == ["steal_flow"]


def test_audit_hard_mode_raises(monkeypatch):
    monkeypatch.setenv("TTS_AUDIT_HARD", "1")
    with pytest.raises(audit.AuditError):
        audit.record("toy_invariant", False, why="test")
    audit.clear_findings()


# ------------------------------- checkpoint / elastic-resume audit edges


def test_checkpoint_roundtrip_audit_and_prev_rollback(
        fresh_obs, tmp_path, monkeypatch):
    """Satellite edge: roundtrip audit on a good snapshot passes; a
    corrupted current file is a failed finding; resume still rolls
    back to `.prev` and finishes with exact totals."""
    monkeypatch.setenv("TTS_AUDIT_CKPT", "1")
    inst = PFSPInstance.synthetic(jobs=8, machines=3, seed=5)
    base = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=4, **KW)
    path = str(tmp_path / "a.ckpt.npz")
    partial = distributed.search(
        inst.p_times, lb_kind=1, init_ub=None, n_devices=4,
        segment_iters=8, checkpoint_path=path, max_rounds=4,
        heartbeat=None, **KW)
    assert not partial.complete
    assert os.path.exists(path) and os.path.exists(path + ".prev")
    # every roundtrip check during the run passed
    rt = [f for f in audit.findings()
          if f.invariant == "checkpoint_roundtrip"]
    assert rt and all(f.ok for f in rt)
    state, _ = checkpoint.load(path)
    assert audit.check_checkpoint_roundtrip(path, state)[0].ok
    # corrupt the current snapshot: the auditor flags it...
    raw = bytearray(pathlib.Path(path).read_bytes())
    raw[len(raw) // 2:len(raw) // 2 + 64] = b"\0" * 64
    pathlib.Path(path).write_bytes(bytes(raw))
    f = audit.check_checkpoint_roundtrip(path, state)[0]
    assert not f.ok and "error" in f.detail
    # ...and the engine still resumes from .prev to the exact totals
    done = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=4, segment_iters=64,
                              checkpoint_path=path, heartbeat=None,
                              **KW)
    assert done.complete
    assert (done.explored_tree, done.explored_sol, done.best) == \
        (base.explored_tree, base.explored_sol, base.best)


def test_preempt_elastic_resume_other_submesh_size_audited(
        fresh_obs, tmp_path, monkeypatch):
    """Satellite edge: preempt on a 8-device submesh, resume the tag on
    a 4-device submesh of a NEW server — the elastic-resume
    conservation audit passes and totals stay exact."""
    monkeypatch.setenv("TTS_SEARCH_TELEMETRY", "1")
    inst = PFSPInstance.synthetic(jobs=8, machines=3, seed=5)
    base = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=4, **KW)
    wd = tmp_path / "wd"
    with SearchServer(n_submeshes=1, workdir=wd,
                      health_interval_s=0) as srv:
        # small segments + a per-segment delay keep the run alive long
        # enough for the preempt to land mid-search
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, tag="edge",
            segment_iters=8, checkpoint_every=1,
            faults="delay_every=0.25", **KW))
        wait_until(lambda: (srv.status(rid)["progress"] or {})
                   .get("segment", 0) >= 1, what="first checkpoint")
        assert srv.preempt(rid, hold=True)
        wait_until(lambda: srv.status(rid)["state"] == "PREEMPTED",
                   what="preempt")
        assert srv.status(rid)["progress"]["pool"] > 0  # mid-search
    audit.clear_findings()
    with SearchServer(n_submeshes=2, workdir=wd,
                      health_interval_s=0.05) as srv2:
        rid2 = srv2.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, tag="edge", **KW))
        rec = srv2.result(rid2, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        res = rec.result
        # node TOTALS legitimately differ across topologies (incumbent
        # discovery order changes pruning); the optimum does not, and
        # the auditor must prove the edge conserved every counter:
        assert res.best == base.best and res.complete
        assert res.explored_tree > 0
    cons = [f for f in audit.findings()
            if f.invariant == "elastic_resume_conservation"]
    assert cons and all(f.ok for f in cons), \
        [(f.invariant, f.detail) for f in cons if not f.ok]
    # the final result's telemetry-vs-counter identities held ACROSS
    # the checkpoint + 8->4 reshard + resume chain
    assert all(f.ok for f in audit.findings()), \
        [(f.invariant, f.detail) for f in audit.findings() if not f.ok]


# -------------------------------------- fleet aggregation + doctor CLI


def test_aggregate_merges_two_servers_and_doctor_exit_codes(
        fresh_obs, tmp_path):
    from tpu_tree_search import cli

    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=0)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "a",
                      health_interval_s=0.1) as sa, \
            SearchServer(n_submeshes=2, workdir=tmp_path / "b",
                         health_interval_s=0.1) as sb:
        ha = start_http_server(sa)
        hb = start_http_server(sb)
        try:
            rid = sa.submit(SearchRequest(p_times=inst.p_times,
                                          lb_kind=1, **KW))
            assert sa.result(rid, timeout=300).state == "DONE"
            wait_until(lambda: sa.health.evaluations > 0
                       and sb.health.evaluations > 0,
                       what="health evaluations")
            urls = [ha.url, hb.url]
            merged = aggregate.merge(aggregate.scrape(urls))
            origins = {s["origin"] for s in merged["servers"]}
            assert origins == {f"127.0.0.1:{ha.port}",
                               f"127.0.0.1:{hb.port}"}
            assert all(s["ok"] and s["healthz"] == "ok"
                       for s in merged["servers"])
            # every sample is origin-labeled; both origins contribute
            assert {lb["origin"] for _, lb, _ in merged["metrics"]} \
                == origins
            assert any(r["id"] == rid for r in merged["requests"])
            text = aggregate.fleet_to_prometheus(merged)
            assert f'origin="127.0.0.1:{ha.port}"' in text
            ok, reasons = aggregate.verdict(merged)
            assert ok, reasons

            # doctor: zero against the healthy fleet...
            out = tmp_path / "fleet.html"
            mfile = tmp_path / "fleet.prom"
            assert cli.main(["doctor", *urls,
                             "--dashboard", str(out),
                             "--metrics-out", str(mfile)]) == 0
            html = out.read_text()
            assert "fleet health" in html
            for o in origins:
                assert o in html
            # self-contained: no scripts, no external assets
            assert "<script" not in html
            assert "http://" not in html.replace("127.0.0.1", "")
            assert f'origin="127.0.0.1:{hb.port}"' in mfile.read_text()

            # ...nonzero once one member has a firing alert
            sb.health.rules.append(health.Rule(
                "synthetic", lambda ctx: (True, {"injected": True}),
                severity="critical"))
            wait_until(lambda: sb.health.alerts.get("synthetic")
                       is not None
                       and sb.health.alerts["synthetic"].state
                       == health.FIRING, what="synthetic alert")
            assert cli.main(["doctor", *urls, "--json"]) == 1
            merged = aggregate.merge(aggregate.scrape(urls))
            ok, reasons = aggregate.verdict(merged)
            assert not ok
            assert any("synthetic" in r for r in reasons)
        finally:
            ha.close()
            hb.close()
    # doctor against a dead server: nonzero, not an exception
    assert cli.main(["doctor", ha.url, "--timeout", "0.5"]) == 1


def test_dashboard_endpoint_stdlib_only(fresh_obs, tmp_path):
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=1)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      health_interval_s=0.05) as srv:
        httpd = start_http_server(srv)
        try:
            rid = srv.submit(SearchRequest(p_times=inst.p_times,
                                           lb_kind=1, **KW))
            assert srv.result(rid, timeout=300).state == "DONE"
            wait_until(lambda: srv.health.evaluations >= 2,
                       what="history samples")
            r = urllib.request.urlopen(httpd.url + "/dashboard",
                                       timeout=10)
            assert r.status == 200
            assert r.headers["Content-Type"].startswith("text/html")
            html = r.read().decode()
            assert rid in html                    # request table
            assert "<svg" in html                 # sparklines
            assert "<script" not in html          # no JS at all
            assert "@import" not in html and "url(" not in html
            al = json.loads(urllib.request.urlopen(
                httpd.url + "/alerts", timeout=10).read())
            assert al["enabled"] and al["firing"] == 0
            assert {r["name"] for r in al["rules"]} >= {
                "queue_wait", "stall", "pruning_collapse",
                "mem_headroom", "compile_storm", "audit", "perf"}
            # queue-wait SLO instrumentation observed the dispatch
            # (tenant-labeled series since the capacity layer;
            # snapshot_matching merges across tenants)
            h = srv.metrics.histogram("tts_queue_wait_seconds")
            assert h.snapshot_matching()["count"] >= 1
        finally:
            httpd.close()


# ----------------------------------------------- satellites: the valves


def test_tracelog_sink_rotation(tmp_path):
    path = tmp_path / "t.jsonl"
    log = tracelog.TraceLog(sink_path=path, max_sink_bytes=4096)
    for i in range(300):
        log.event("e", i=i, pad="x" * 40)
    assert log.rotations >= 1
    assert (tmp_path / "t.jsonl.1").exists()
    assert path.stat().st_size < 4096 + 512
    # both files are valid JSONL, each starting with a meta line
    for p in (path, tmp_path / "t.jsonl.1"):
        lines = [json.loads(ln) for ln in
                 p.read_text().splitlines()]
        assert lines[0]["kind"] == "meta"
    # rotation preserves the tail: the newest record is in the live file
    assert json.loads(path.read_text().splitlines()[-1])["i"] == 299


def test_metrics_cardinality_valve(fresh_obs):
    reg = metrics.Registry(max_series_per_metric=4)
    g = reg.gauge("tts_leaky", "per-request series")
    for i in range(10):
        g.set(i, request=f"r{i}")
    assert len(g.samples()) == 4
    dropped = reg.counter(reg.DROPPED)
    assert dropped.value(metric="tts_leaky") == 6
    # existing series keep updating under the cap
    g.set(99, request="r0")
    assert g.value(request="r0") == 99
    # histograms and counters valve the same way
    h = reg.histogram("tts_h", buckets=(1.0,))
    c = reg.counter("tts_c")
    for i in range(10):
        h.observe(0.5, request=f"r{i}")
        c.inc(request=f"r{i}")
    assert dropped.value(metric="tts_h") == 6
    assert dropped.value(metric="tts_c") == 6
    # remove_matching frees room for new series again
    g.remove_matching(request="r0")
    g.set(1, request="fresh")
    assert g.value(request="fresh") == 1


def test_every_terminal_state_retires_request_series(fresh_obs,
                                                     tmp_path):
    """DONE, CANCELLED, DEADLINE and FAILED must all pull the
    per-request series valve, not just DONE."""
    from tpu_tree_search.engine import telemetry as tele

    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    # share_incumbent pinned off: all four requests solve the SAME
    # instance, and the DEADLINE one must stay slow enough to exceed
    # its 1 ms budget — a folded optimum from the DONE request would
    # legitimately finish it early (sharing: tests/test_overlap.py)
    srv = SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                       autostart=False, service_retry_attempts=0,
                       health_interval_s=0, share_incumbent=False)
    try:
        rids = {}
        rids["DONE"] = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, **KW))
        rids["FAILED"] = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1,
            faults="fail_host_fetch=99", **KW))
        rids["DEADLINE"] = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, deadline_s=0.001,
            segment_iters=8, **KW))
        rids["CANCELLED"] = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, **KW))
        # pre-populate a per-request series for every request, as the
        # telemetry publisher would
        progress_gauges = ("tts_progress_ratio", "tts_eta_seconds",
                           "tts_est_tree_size")
        for rid in rids.values():
            srv.metrics.gauge(tele.SERIES[0]).set(1, request=rid,
                                                  bucket=0)
            srv.metrics.gauge("tts_phase_seconds").set(
                1, request=rid, phase="kernel")
            for name in progress_gauges:
                srv.metrics.gauge(name).set(1, request=rid, tag=rid,
                                            tenant="-")
        assert srv.cancel(rids["CANCELLED"])
        srv.start()
        for want, rid in rids.items():
            rec = srv.result(rid, timeout=300)
            assert rec.state == want, (want, rec.state, rec.error)
            for name in (tele.SERIES + ("tts_phase_seconds",)
                         + progress_gauges):
                m = srv.metrics.gauge(name)
                assert not [k for _, k, _ in m.samples()
                            if ("request", rid) in k], (want, name)
    finally:
        srv.close()


def test_perf_sentry_json_and_health_perf_rule(fresh_obs, tmp_path):
    import perf_sentry

    bad = tmp_path / "BENCH_r09.json"
    bad.write_text(json.dumps({"rc": 1, "tail": "boom"}))
    jpath = tmp_path / "sentry.json"
    rc = perf_sentry.main([str(bad), "--report-only",
                           "--dir", str(tmp_path),
                           "--json", str(jpath)])
    assert rc == 0                               # report-only
    verdict = json.loads(jpath.read_text())
    assert verdict["schema"] == 1
    assert verdict["verdict"] == "FAIL" and verdict["n_fail"] == 1
    assert verdict["reasons"] and "rc=1" in verdict["reasons"][0]
    assert verdict["metrics"][0]["verdict"] == "FAIL"
    # the health layer's perf rule ingests the verdict file
    th = health.Thresholds(perf_json=str(jpath))
    mon = health.HealthMonitor(rules=health.default_rules(th),
                               registry=metrics.Registry(),
                               interval_s=0)
    snap = mon.evaluate_now()
    firing = {a["rule"] for a in snap["alerts"]
              if a["state"] == "firing"}
    assert "perf" in firing
    # a PASS verdict resolves it
    good = tmp_path / "row.jsonl"
    good.write_text(json.dumps(
        {"metric": "toy_rate", "value": 1.0}) + "\n")
    assert perf_sentry.main([str(good), "--dir", str(tmp_path),
                             "--json", str(jpath)]) == 0
    assert json.loads(jpath.read_text())["verdict"] == "PASS"
    snap = mon.evaluate_now()
    assert not [a for a in snap["alerts"]
                if a["rule"] == "perf" and a["state"] == "firing"]
