"""Subprocess worker for the 2-process multihost (DCN-tier) smoke test.

Each process owns 4 virtual CPU devices; jax.distributed.initialize
joins them into one 8-device global mesh — the single-machine stand-in
for the reference's one-MPI-rank-per-node launch (mpirun --map-by
ppr:1:node, README.md:109-116). The SAME SPMD program then runs
unchanged; only the mesh spans two controllers, which exercises the
multi-controller branches (_to_mesh, _fetch, checkpoint._to_np).

Usage: python tests/_multihost_worker.py PORT PROCESS_ID NUM_PROCESSES \
           [MODE CHECKPOINT_PATH [MAX_ROUNDS]]

MODE "plain" (default) runs to completion without durability. "trunc"
runs the SEGMENTED driver with a checkpoint and a round ceiling (the
kill half of the multihost kill/resume invariant: only process 0 writes
the file — checkpoint.save rank-gating). "resume" loads that checkpoint
on every process and finishes the search.
"""

import json
import os
import sys


def main():
    port, pid, nproc = sys.argv[1], int(sys.argv[2]), int(sys.argv[3])
    mode = sys.argv[4] if len(sys.argv) > 4 else "plain"
    ckpt = sys.argv[5] if len(sys.argv) > 5 else None
    max_rounds = int(sys.argv[6]) if len(sys.argv) > 6 else None
    os.environ["JAX_PLATFORMS"] = "cpu"
    # 4 virtual devices per process: newer jax takes a config knob, the
    # pinned 0.4.x line only reads XLA_FLAGS at first backend init (the
    # same fallback pair as tests/conftest.py)
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        jax.config.update("jax_num_cpu_devices", 4)
    except AttributeError:
        pass
    jax.distributed.initialize(coordinator_address=f"localhost:{port}",
                               num_processes=nproc, process_id=pid)
    assert jax.process_count() == nproc
    assert jax.device_count() == 4 * nproc

    from tpu_tree_search.engine import distributed
    from tpu_tree_search.problems.pfsp import PFSPInstance

    inst = PFSPInstance.synthetic(jobs=8, machines=4, seed=0)
    opt = inst.brute_force_optimum()
    kw = {}
    if mode in ("trunc", "resume"):
        kw = dict(segment_iters=8, checkpoint_path=ckpt, heartbeat=None)
        if mode == "trunc":
            kw["max_rounds"] = max_rounds
    res = distributed.search(inst.p_times, lb_kind=1, init_ub=opt,
                             chunk=8, capacity=1 << 12, min_seed=4, **kw)
    print("RESULT " + json.dumps({
        "process": pid,
        "tree": res.explored_tree,
        "sol": res.explored_sol,
        "best": res.best,
        "complete": res.complete,
    }), flush=True)


if __name__ == "__main__":
    main()
