"""Observability layer: flight recorder, metrics registry, Chrome
export, HTTP front-end — and the end-to-end serve-session acceptance.

The load-bearing assertions:

- a CPU-backend serve session with 3 concurrent requests (one preempted
  and resumed) leaves a JSONL event log whose per-request span SEQUENCE
  is deterministic (admit -> dispatch -> checkpoint.save -> preempt ->
  resume -> checkpoint.load -> done, matching request ids);
- the retry counter increments EXACTLY once per injected transient
  (fail_host_fetch=1 => tts_retries_total == 1);
- /metrics exposes the request-state and executor-cache counters as
  Prometheus text; /status and /trace serve JSON; /healthz flips to 503
  on shutdown;
- tools/trace_summary.py parses both artifact formats (JSONL + Chrome)
  and reports the preemption;
- instrumentation is OBSERVATION-ONLY: served node counts stay
  bit-identical to standalone `distributed.search`.
"""

import json
import os
import pathlib
import shutil
import sys
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from tpu_tree_search.engine import distributed
from tpu_tree_search.obs import chrome_trace, metrics, tracelog
from tpu_tree_search.obs.httpd import start_http_server
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest, SearchServer

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

KW = dict(chunk=8, capacity=1 << 12, min_seed=4)


@pytest.fixture
def fresh_obs(tmp_path):
    """Isolated global recorder (with a JSONL sink) + default registry:
    obs state is process-global by design, so tests swap it."""
    log = tracelog.TraceLog(capacity=1 << 16,
                            sink_path=tmp_path / "trace.jsonl")
    prev_log = tracelog.install(log)
    reg = metrics.Registry()
    prev_reg = metrics.install(reg)
    try:
        yield log, reg
    finally:
        tracelog.install(prev_log)
        metrics.install(prev_reg)


# ------------------------------------------------------------ unit: tracelog

def test_tracelog_span_event_context_and_ring():
    log = tracelog.TraceLog(capacity=4)
    with log.context(request_id="r1", submesh=2):
        with log.span("work", phase="x") as sp:
            log.event("tick", n=1)
        assert sp.dur >= 0
    recs = log.records()
    assert [r["name"] for r in recs] == ["tick", "work"]  # span at exit
    for r in recs:
        assert r["request_id"] == "r1" and r["submesh"] == 2
    assert recs[1]["kind"] == "span" and "dur" in recs[1]
    assert recs[0]["kind"] == "event"
    # ring bound: old records drop, recorder never grows unbounded
    for i in range(10):
        log.event("e", i=i)
    assert len(log) == 4
    assert log.dropped > 0


def test_tracelog_span_records_error_and_reraises():
    log = tracelog.TraceLog()
    with pytest.raises(ValueError):
        with log.span("boom"):
            raise ValueError("nope")
    (rec,) = log.records()
    assert "ValueError" in rec["error"]


def test_tracelog_sink_roundtrip(tmp_path):
    path = tmp_path / "t.jsonl"
    log = tracelog.TraceLog(sink_path=path)
    log.event("a", x=1)
    with log.span("b"):
        pass
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert lines[0]["kind"] == "meta" and "t0_unix" in lines[0]
    recs = chrome_trace.read_jsonl(path)   # meta line filtered
    assert [r["name"] for r in recs] == ["a", "b"]
    # exotic attr values serialize instead of poisoning the sink
    log.event("c", arr=np.int64(3), obj=object())
    assert json.loads(path.read_text().splitlines()[-1])["arr"] == 3


# ------------------------------------------------------------- unit: metrics

def test_metrics_counter_gauge_histogram_expositions():
    reg = metrics.Registry()
    c = reg.counter("tts_requests_total", "by state")
    c.inc(state="done")
    c.inc(2, state="failed")
    with pytest.raises(ValueError):
        c.inc(-1)
    g = reg.gauge("tts_queue_depth", "live")
    g.set_fn(lambda: 7)
    h = reg.histogram("tts_lat_seconds", buckets=(0.1, 1.0))
    h.observe(0.05)
    h.observe(5.0)
    text = reg.to_prometheus()
    assert 'tts_requests_total{state="done"} 1' in text
    assert 'tts_requests_total{state="failed"} 2' in text
    assert "# TYPE tts_requests_total counter" in text
    assert "tts_queue_depth 7" in text
    assert 'tts_lat_seconds_bucket{le="0.1"} 1' in text
    assert 'tts_lat_seconds_bucket{le="+Inf"} 2' in text
    assert "tts_lat_seconds_count 2" in text
    j = reg.to_json()
    assert j["tts_queue_depth"] == 7.0
    assert j["tts_lat_seconds"]["count"] == 2
    json.dumps(j)                      # JSON-safe end to end
    # one name, one type: a re-registration under another type is a bug
    with pytest.raises(TypeError):
        reg.gauge("tts_requests_total")


# -------------------------------------------------------- unit: chrome trace

def test_chrome_trace_tracks_and_event_kinds(tmp_path):
    log = tracelog.TraceLog()
    with log.context(request_id="r0", submesh=1):
        with log.span("request.execute"):
            pass
    log.event("server.start")          # no submesh -> thread lane
    doc = chrome_trace.to_chrome(log.records())
    lanes = {e["args"]["name"] for e in doc["traceEvents"]
             if e["ph"] == "M"}
    assert "submesh-1" in lanes
    xs = [e for e in doc["traceEvents"] if e["ph"] == "X"]
    ins = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(xs) == 1 and xs[0]["name"] == "request.execute"
    assert xs[0]["args"]["request_id"] == "r0"
    assert len(ins) == 1
    out = chrome_trace.write_chrome(tmp_path / "t.json", log.records())
    assert json.loads(pathlib.Path(out).read_text())["traceEvents"]


# ------------------------------------------------- retry counter exactness

def test_retry_counter_counts_each_transient_exactly(fresh_obs):
    log, reg = fresh_obs
    from tpu_tree_search.utils.retry import retry_call

    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] <= 2:
            raise OSError("transient")
        return "ok"

    assert retry_call(flaky, what="unit-op", attempts=5, base_s=0.0,
                      sleep=lambda _: None) == "ok"
    assert reg.counter("tts_retries_total").value(what="unit-op") == 2
    retries = [r for r in log.records() if r["name"] == "retry"]
    assert len(retries) == 2
    assert retries[0]["what"] == "unit-op"


# --------------------------------------------------- phase attribution view

def test_publish_attribution_gauges(fresh_obs):
    _, reg = fresh_obs
    from tpu_tree_search.utils import phase_timing

    att = phase_timing.attribute(
        {"bound": 2e-3, "step": 5e-3, "compact": 3e-3,
         "per_eval": 2e-3 / 128},
        elapsed=1.0, evals=[12800, 3200], iters=[100, 100])
    phase_timing.publish_attribution(att, request="req-0000")
    g = reg.gauge("tts_phase_seconds")
    k0 = g.value(phase="kernel", worker=0, request="req-0000")
    k1 = g.value(phase="kernel", worker=1, request="req-0000")
    assert k0 == pytest.approx(att["kernel_time"][0])
    assert k0 > k1 > 0
    assert 'phase="idle"' in reg.to_prometheus()


# --------------------------------------------------------- e2e serve session

@pytest.fixture(scope="module")
def baselines():
    """Standalone distributed.search totals at 4 workers (the submesh
    size the 2-submesh server serves at) — the bit-identical anchor."""
    out = {}
    for seed, jobs in [(5, 8), (6, 7), (2, 7)]:
        inst = PFSPInstance.synthetic(jobs=jobs, machines=3, seed=seed)
        got = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                                 n_devices=4, **KW)
        out[seed] = (got.explored_tree, got.explored_sol, got.best)
    return out


def _first_index(names, name):
    assert name in names, f"{name} missing from {names}"
    return names.index(name)


def test_serve_session_flight_recorder_end_to_end(fresh_obs, baselines,
                                                  tmp_path):
    """The acceptance run: 3 concurrent requests on 2 submeshes, the
    low-priority victim preempted by a high-priority arrival and
    resumed; one request carries an injected transient. Asserts the
    span sequence, the exact retry count, the HTTP surface, both trace
    artifacts (via tools/trace_summary.py), and bit-identical counts."""
    log, reg = fresh_obs
    slow = PFSPInstance.synthetic(jobs=8, machines=3, seed=5)
    fast = PFSPInstance.synthetic(jobs=7, machines=3, seed=6)
    other = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    # share_incumbent pinned off: ra/rb solve the SAME instance and the
    # test asserts bit-identity vs standalone runs — a cross-request
    # fold would (correctly) shrink one request's tree (sharing
    # semantics are covered by tests/test_overlap.py)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      share_incumbent=False) as srv:
        httpd = start_http_server(srv)
        try:
            # two low-priority requests occupy both submeshes; the
            # delay_every faults keep them running long enough for the
            # high-priority arrival to need a preemption, and the
            # fail_host_fetch on `ra` injects exactly one transient
            ra = srv.submit(SearchRequest(
                p_times=slow.p_times, lb_kind=1, priority=0,
                segment_iters=32, checkpoint_every=1,
                faults="delay_every=0.15,fail_host_fetch=1", **KW))
            rb = srv.submit(SearchRequest(
                p_times=slow.p_times, lb_kind=1, priority=0,
                tag="victim-b", segment_iters=32, checkpoint_every=1,
                faults="delay_every=0.15", **KW))
            t0 = time.monotonic()
            while not all(srv.status(r)["state"] == "RUNNING"
                          for r in (ra, rb)):
                assert time.monotonic() - t0 < 120
                time.sleep(0.02)
            hi = srv.submit(SearchRequest(
                p_times=fast.p_times, lb_kind=1, priority=10,
                segment_iters=256, **KW))
            rec_hi = srv.result(hi, timeout=300)
            assert rec_hi.state == "DONE", (rec_hi.state, rec_hi.error)
            recs = {r: srv.result(r, timeout=600) for r in (ra, rb)}
            assert all(r.state == "DONE" for r in recs.values())

            # ---- observation-only: counts bit-identical to standalone
            for r in recs.values():
                res = r.result
                assert (res.explored_tree, res.explored_sol,
                        res.best) == baselines[5]
            res = rec_hi.result
            assert (res.explored_tree, res.explored_sol,
                    res.best) == baselines[6]

            # ---- the retry counter increments EXACTLY once per
            # injected transient (>= 1 fires; a preempted `ra` re-arms
            # its per-dispatch plan, so count injections, then demand
            # counter == injections)
            faults_fired = [r for r in log.records()
                            if r["name"] == "fault.injected"
                            and r.get("fault") == "fail_host_fetch"]
            assert len(faults_fired) >= 1
            assert all(f["request_id"] == ra for f in faults_fired)
            assert reg.counter("tts_retries_total").value(
                what="per-segment host fetch") == len(faults_fired)

            # ---- the preempted request's span sequence, matching ids
            victim = next(r for r in (ra, rb)
                          if recs[r].preemptions >= 1)
            seq = [r["name"] for r in log.records()
                   if r.get("request_id") == victim]
            order = [_first_index(seq, n) for n in (
                "request.admit", "request.dispatch", "checkpoint.save",
                "request.preempt", "request.resume", "checkpoint.load",
                "request.done")]
            assert order == sorted(order), (victim, seq)
            # the resume really is a SECOND dispatch
            assert seq.count("request.dispatch") >= 2
            # every lifecycle record carries the submesh it happened on
            assert all(r.get("submesh") is not None
                       for r in log.records()
                       if r["name"] == "request.dispatch")

            # ---- HTTP surface
            m = urllib.request.urlopen(httpd.url + "/metrics",
                                       timeout=10).read().decode()
            assert 'tts_requests_total{state="done",tenant="-"} 3' in m
            assert "tts_executor_cache_hits_total" in m
            assert "tts_executor_cache_misses_total" in m
            assert "tts_preemptions_total 1" in m
            assert "tts_checkpoint_saves_total" in m     # engine registry
            s = json.loads(urllib.request.urlopen(
                httpd.url + "/status", timeout=10).read())
            assert s["counters"]["done"] == 3
            assert s["metrics"]["tts_requests_submitted_total"] == 3
            hz = urllib.request.urlopen(httpd.url + "/healthz",
                                        timeout=10)
            assert hz.status == 200
            chrome = json.loads(urllib.request.urlopen(
                httpd.url + "/trace", timeout=10).read())
            assert any(e.get("name") == "request.preempt"
                       for e in chrome["traceEvents"])

            # the snapshot's counters are a view over the SAME registry
            assert srv.counters["done"] == 3
            assert srv.counters["preemptions"] == \
                int(srv.metrics.counter("tts_preemptions_total").value())
        finally:
            httpd.close()

    # ---- both artifacts parse through tools/trace_summary.py
    import trace_summary
    jsonl = tmp_path / "trace.jsonl"
    chrome_path = chrome_trace.write_chrome(tmp_path / "trace.chrome.json",
                                            log.records())
    for artifact in (str(jsonl), chrome_path):
        reqs = trace_summary.summarize(trace_summary.load_records(artifact))
        assert reqs[victim]["preemptions"] >= 1
        assert reqs[victim]["state"] == "DONE"
        assert reqs[victim]["dispatches"] >= 2
        assert trace_summary.main([artifact]) == 0
    # CI artifact hand-off: the workflow uploads this directory
    from tpu_tree_search.utils import config as _cfg
    art = _cfg.env_str("TTS_OBS_ARTIFACT_DIR")
    if art:
        os.makedirs(art, exist_ok=True)
        shutil.copy(jsonl, os.path.join(art, "serve_trace.jsonl"))
        shutil.copy(chrome_path,
                    os.path.join(art, "serve_trace.chrome.json"))


def test_cli_serve_spool_http_smoke(fresh_obs, tmp_path):
    """The ROADMAP follow-on, end to end through the real CLI: `serve
    --http-port --trace-file` over a file spool on the CPU backend —
    /healthz, /metrics and /status answer while a spooled request is
    served, and the trace file holds the session's event log."""
    import socket
    import threading

    from tpu_tree_search import cli
    from tpu_tree_search.service import spool as spool_mod

    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
    spool_dir = tmp_path / "spool"
    trace = tmp_path / "cli_trace.jsonl"
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=1)
    sid = spool_mod.submit_file(
        spool_dir, {"p_times": inst.p_times.tolist(), "lb": 1,
                    "chunk": 8, "capacity": 1 << 12, "min_seed": 4})
    th = threading.Thread(
        target=cli.main,
        args=(["serve", "--spool", str(spool_dir), "--submeshes", "2",
               "--idle-exit", "2", "--status-every", "0",
               "--http-port", str(port), "--trace-file", str(trace)],),
        daemon=True)
    th.start()
    base = f"http://127.0.0.1:{port}"
    deadline = time.monotonic() + 120
    while True:
        try:
            assert urllib.request.urlopen(base + "/healthz",
                                          timeout=2).status == 200
            break
        except (urllib.error.URLError, ConnectionError, OSError):
            assert time.monotonic() < deadline, "HTTP never came up"
            time.sleep(0.1)
    res = spool_mod.wait_result(spool_dir, sid, timeout=300)
    assert res["state"] == "DONE"
    m = urllib.request.urlopen(base + "/metrics",
                               timeout=10).read().decode()
    assert 'tts_requests_total{state="done",tenant="-"} 1' in m
    snap = json.loads(urllib.request.urlopen(base + "/status",
                                             timeout=10).read())
    assert snap["counters"]["done"] == 1
    th.join(timeout=120)
    assert not th.is_alive(), "serve CLI did not idle-exit"
    recs = chrome_trace.read_jsonl(trace)
    assert any(r["name"] == "request.done" for r in recs)


def test_healthz_flips_to_503_on_close(fresh_obs, tmp_path):
    srv = SearchServer(n_submeshes=2, workdir=tmp_path, autostart=False)
    httpd = start_http_server(srv)
    try:
        assert urllib.request.urlopen(httpd.url + "/healthz",
                                      timeout=10).status == 200
        srv.close()
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(httpd.url + "/healthz", timeout=10)
        assert ei.value.code == 503
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(httpd.url + "/nope", timeout=10)
        assert ei.value.code == 404
    finally:
        httpd.close()


def test_live_phase_attribution_via_phase_profile(fresh_obs, tmp_path):
    """Satellite: with `phase_profile` unit costs the server publishes
    per-worker kernel/genchild/balance/idle seconds at every heartbeat
    — live in /metrics and the snapshot while the request RUNS, not
    only in end-of-run CSVs — and retires the per-request series at the
    terminal transition (the gauge-cardinality valve)."""
    _, _ = fresh_obs
    inst = PFSPInstance.synthetic(jobs=8, machines=3, seed=5)
    prof = {"bound": 1e-4, "step": 3e-4, "compact": 2e-4,
            "per_eval": 1e-4 / (8 * 8)}
    with SearchServer(n_submeshes=2, workdir=tmp_path,
                      phase_profile=prof) as srv:
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=32,
            faults="delay_every=0.1", **KW))
        # the LIVE view: per-request series appear while it runs
        t0 = time.monotonic()
        while True:
            text = srv.metrics.to_prometheus()
            if f'request="{rid}"' in text:
                break
            assert time.monotonic() - t0 < 120, "no live phase series"
            time.sleep(0.02)
        snap = srv.status_snapshot()
        assert "tts_phase_seconds" in snap["metrics"]
        # all four phases, one series per worker of the 4-device submesh
        for phase in ("kernel", "gen_child", "balance", "idle"):
            assert f'phase="{phase}"' in text
        assert 'worker="3"' in text
        assert srv.result(rid, timeout=300).state == "DONE"
        # cardinality valve: the request's series retire with it
        assert f'request="{rid}"' not in srv.metrics.to_prometheus()


def test_checkpoint_metrics_and_quarantine_events(fresh_obs, tmp_path):
    """Engine-level instrumentation: saves feed latency/bytes
    histograms; a corrupt current snapshot leaves quarantine +
    rollback events when the last-good sibling serves the resume."""
    log, reg = fresh_obs
    from tpu_tree_search.engine import checkpoint, device
    from tpu_tree_search.utils import faults as faults_mod

    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=1)
    state = device.init_state(7, 1 << 10, None, p_times=inst.p_times)
    path = tmp_path / "ck.npz"
    checkpoint.save(path, state, meta={"x": 1})
    checkpoint.save(path, state, meta={"x": 2})    # rotates .prev
    h = reg.histogram("tts_checkpoint_save_seconds")
    assert h.snapshot()["count"] == 2
    assert reg.histogram("tts_checkpoint_bytes").snapshot()["count"] == 2
    faults_mod.corrupt_file(path)
    st, meta, used = checkpoint.load_resilient(path,
                                               p_times=inst.p_times)
    assert str(used).endswith(".prev")
    names = [r["name"] for r in log.records()]
    assert "checkpoint.quarantine" in names
    assert "checkpoint.rollback" in names
    assert reg.counter("tts_checkpoint_rollbacks_total").value() == 1
    spans = [r for r in log.records() if r["name"] == "checkpoint.save"]
    assert len(spans) == 2 and all(s["bytes"] > 0 for s in spans)
