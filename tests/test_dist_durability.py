"""Distributed durability: lossless overflow growth, stacked
checkpoint/resume, segmented driving with per-worker heartbeat, and the
water-filling balance plan.

This is the layer the reference lacks entirely (SURVEY.md §5:
"Checkpoint/resume: none"; its only stall tooling is a 10-second
"Still Idle" print, pfsp_dist_multigpu_cuda.c:663-668). Round 1 had it
single-device only; a distributed overflow restarted from the warm-up
frontier, discarding all explored work — these tests pin the lossless
behavior that replaced it.
"""

import json
import os
import subprocess
import sys

import numpy as np
import pytest

from tpu_tree_search.engine import checkpoint, distributed, sequential as seq
from tpu_tree_search.parallel import balance as bal
from tpu_tree_search.problems.pfsp import PFSPInstance


def test_exchange_plan_multi_receiver():
    """One hot worker must feed several starving workers in one round
    (the round-1 pairing fed exactly one receiver per donor)."""
    import jax.numpy as jnp

    sizes = jnp.asarray([100, 0, 0, 0], jnp.int32)
    plan = np.asarray(bal.exchange_plan(sizes, cap=64, min_transfer=4))
    assert plan[0].sum() > 0
    assert (plan[0] > 0).sum() >= 2        # multiple receivers
    assert plan[0, 0] == 0                 # no self-flow
    # donors never give more than half their surplus
    assert plan[0].sum() <= (100 - 25) // 2


def test_exchange_plan_balanced_is_empty():
    import jax.numpy as jnp

    sizes = jnp.asarray([50, 52, 49, 51], jnp.int32)
    plan = np.asarray(bal.exchange_plan(sizes, cap=64, min_transfer=8))
    assert plan.sum() == 0


def _counting_grow(monkeypatch):
    calls = []
    orig_grow = checkpoint.grow

    def counting(state, new_capacity):
        calls.append(new_capacity)
        return orig_grow(state, new_capacity)

    monkeypatch.setattr(checkpoint, "grow", counting)
    return calls


def test_dist_overflow_grows_and_resumes_losslessly(monkeypatch):
    """A pool that must overflow mid-run grows and RESUMES with no node
    lost or duplicated. N-Queens is the exact oracle for this: no
    incumbent, so tree/sol counts are invariant to exploration order —
    any lost (or doubled) subtree would shift them. Balancing is
    disabled (huge min_transfer) and the warm-up stripe sized near the
    limit so the pools MUST overflow mid-run."""
    from tpu_tree_search.problems import nqueens as nq

    calls = _counting_grow(monkeypatch)
    kw = dict(chunk=4, n_devices=2, min_seed=200, min_transfer=10**6)
    small = nq.search_distributed(10, capacity=1 << 8, **kw)
    assert calls, "tiny pool never overflowed — capacity too generous " \
                  "for the test to exercise the grow path"
    big = nq.search_distributed(10, capacity=1 << 15, **kw)
    assert (small.explored_tree, small.explored_sol) == \
           (big.explored_tree, big.explored_sol) == (35538, 724)


def test_dist_pfsp_overflow_grow_still_optimal(monkeypatch):
    """PFSP with ub=inf through the overflow-grow path still proves the
    optimum (with a live incumbent the exact tree shape is schedule-
    dependent — as in the reference's threaded runs — so the invariant
    checked is optimality + completion, not node counts)."""
    inst = PFSPInstance.synthetic(jobs=11, machines=4, seed=11)
    kw = dict(lb_kind=0, init_ub=None, chunk=8, transfer_cap=8, min_seed=8)
    big = distributed.search(inst.p_times, capacity=1 << 14, **kw)
    calls = _counting_grow(monkeypatch)
    small = distributed.search(inst.p_times, capacity=1 << 8, **kw)
    assert calls, "tiny pool never overflowed"
    assert small.complete
    assert small.best == big.best


def test_dist_segmented_checkpoint_resume(tmp_path):
    """Kill/resume a multi-device run: a checkpointed truncated run,
    resumed to completion, reproduces the uninterrupted totals exactly."""
    inst = PFSPInstance.synthetic(jobs=9, machines=4, seed=7)
    kw = dict(lb_kind=1, init_ub=None, chunk=4, capacity=1 << 12,
              min_seed=8)
    full = distributed.search(inst.p_times, **kw)

    ckpt = tmp_path / "dist.npz"
    part = distributed.search(inst.p_times, **kw, segment_iters=3,
                              checkpoint_path=str(ckpt), max_rounds=6,
                              heartbeat=None)
    assert ckpt.exists()
    assert not part.complete

    reports = []
    res = distributed.search(inst.p_times, **kw, segment_iters=64,
                             checkpoint_path=str(ckpt),
                             heartbeat=reports.append)
    assert res.complete
    assert (res.explored_tree, res.explored_sol, res.best) == \
           (full.explored_tree, full.explored_sol, full.best)
    # per-worker heartbeat surfaced (8 virtual workers)
    assert reports and reports[0].per_worker is not None
    assert len(reports[0].per_worker["size"]) == 8
    assert len(reports[0].per_worker["steals"]) == 8


def test_dist_checkpoint_elastic_resume_fewer_workers(tmp_path):
    """An 8-worker checkpoint resumes on a 2-worker mesh (elastic
    resume: the pools are concatenated and water-filled across the new
    mesh) and still reaches the exact uninterrupted totals — at ub=opt
    the explored set is exploration-order independent, so any lost or
    duplicated node would shift the counts. (This replaced the hard
    'resume needs the same worker count' error: on real fleets a
    preempted job rarely gets the same topology back.)"""
    inst = PFSPInstance.synthetic(jobs=8, machines=4, seed=7)
    opt = inst.brute_force_optimum()
    want = seq.pfsp_search(inst, lb=1, init_ub=opt)
    ckpt = tmp_path / "dist8.npz"
    part = distributed.search(inst.p_times, lb_kind=1, init_ub=opt,
                              chunk=4, capacity=1 << 12, min_seed=8,
                              segment_iters=2, checkpoint_path=str(ckpt),
                              max_rounds=2, heartbeat=None)
    assert ckpt.exists()
    assert not part.complete, "partial run finished — nothing to resume"
    with pytest.warns(RuntimeWarning, match="resharding"):
        res = distributed.search(inst.p_times, lb_kind=1, init_ub=opt,
                                 n_devices=2, chunk=4, capacity=1 << 12,
                                 checkpoint_path=str(ckpt), heartbeat=None)
    assert res.complete
    assert (res.explored_tree, res.explored_sol, res.best) == \
           (want.explored_tree, want.explored_sol, want.best)


def test_grow_stacked_state():
    """checkpoint.grow re-homes stacked (D, jobs, cap) pools."""
    inst = PFSPInstance.synthetic(jobs=8, machines=4, seed=3)
    res = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                             chunk=4, capacity=1 << 12, min_seed=8)
    del res  # only needed the import path warm; build a tiny fake state
    from tpu_tree_search.engine.device import SearchState

    import jax.numpy as jnp
    D, J, cap, M = 4, 8, 64, 4
    s = SearchState(
        prmu=jnp.zeros((D, J, cap), jnp.int16),
        depth=jnp.zeros((D, cap), jnp.int16),
        aux=jnp.zeros((D, M, cap), jnp.int32),
        size=jnp.full((D,), 5, jnp.int32),
        best=jnp.full((D,), 99, jnp.int32),
        tree=jnp.full((D,), 7, jnp.int64),
        sol=jnp.zeros((D,), jnp.int64),
        iters=jnp.zeros((D,), jnp.int64),
        evals=jnp.zeros((D,), jnp.int64),
        sent=jnp.zeros((D,), jnp.int64),
        recv=jnp.zeros((D,), jnp.int64),
        steals=jnp.zeros((D,), jnp.int64),
        overflow=jnp.ones((D,), bool),
    )
    g = checkpoint.grow(s, 256)
    assert g.prmu.shape == (D, J, 256)
    assert g.depth.shape == (D, 256)
    assert g.aux.shape == (D, M, 256)
    assert not np.asarray(g.overflow).any()
    assert (np.asarray(g.tree) == 7).all()


# ta003 LB2 at ub=opt, chunk 32: the deterministic campaign totals every
# supervisor test asserts bit-identical (tree, best, iters)
CAMPAIGN_GOLDEN = (80062, 1081, 2511)


def _campaign_env(tmp_path, out, **over):
    env = {**os.environ,
           "JAX_PLATFORMS": "cpu",
           "TTS_CAMPAIGN_OUT": str(out),
           "TTS_WORKDIR": str(tmp_path),
           "TTS_LB": "2", "TTS_CHUNK": "32", "TTS_SEG": "600",
           "TTS_CKPT_EVERY": "1", "TTS_BUDGET_S": "600",
           "TTS_POOL_ROWS": "65536"}
    env.pop("XLA_FLAGS", None)   # no need for the 8-device split here
    env.update(over)
    return env


def _campaign_cmd():
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    # the supervisor tests pin the LEGACY process-per-instance path
    # (kept one release behind the deprecated --no-serve flag);
    # serve-mode coverage is test_campaign_serve_mode_same_rows below
    # and tests/test_service.py
    return [sys.executable, "-u",
            os.path.join(repo, "tools", "run_campaign.py"),
            "--no-serve", "3"]


def test_supervisor_stall_resume(tmp_path):
    """The campaign supervisor must survive a dead worker dispatch: the
    worker hangs mid-run (the test hook simulates the ~600 s tunnel
    stalls BENCHMARKS.md documents), the supervisor detects the stale
    heartbeat, kills the process group, respawns resuming from the last
    checkpoint — and the final counters are bit-identical to an unkilled
    run (the same exact-count invariant the multichip dryrun pins)."""
    out = tmp_path / "campaign.jsonl"
    env = _campaign_env(tmp_path, out,
                        TTS_TEST_STALL_AT_SEG="3",
                        TTS_STALL_GRACE="180", TTS_STALL_MIN="4",
                        TTS_STALL_FACTOR="4")
    proc = subprocess.run(_campaign_cmd(), env=env, timeout=900,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1, proc.stdout
    row = rows[0]
    assert row["restarts"] >= 1, (row, proc.stdout)
    assert row["done"], row
    assert (row["tree"], row["best"], row["iters"]) == CAMPAIGN_GOLDEN


def test_supervisor_relaunch_resumes_checkpoint(tmp_path):
    """The CAMPAIGN PROCESS itself dying must not discard durable
    progress: a relaunched supervisor finds a matching-config
    checkpoint, resumes it, and the final counters stay bit-identical
    (r5 review finding: the first version unconditionally deleted any
    existing checkpoint at instance start). The first run uses the
    stall hook to PARK deterministically after segment 3 (checkpoint of
    segment 2 on disk, supervisor held off by a long stall floor), so
    the mid-run kill cannot race a fast solve."""
    out = tmp_path / "campaign.jsonl"
    env = _campaign_env(tmp_path, out,
                        TTS_TEST_STALL_AT_SEG="3",
                        TTS_STALL_GRACE="600", TTS_STALL_MIN="600")
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"

    import time
    proc = subprocess.Popen(_campaign_cmd(), env=env,
                            start_new_session=True,
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL)
    deadline = time.time() + 300
    while time.time() < deadline and not ckpt.exists():
        time.sleep(1.0)
    assert ckpt.exists(), "no checkpoint appeared within 300s"
    # the worker is parked in the stall hook; kill the WHOLE campaign
    import signal as _sig
    try:
        os.killpg(proc.pid, _sig.SIGKILL)
    except ProcessLookupError:
        pytest.fail("campaign exited before the kill — the stall hook "
                    "did not park it")
    proc.wait()
    assert not out.exists() or not out.read_text().strip(), \
        "instance finished before the kill — the stall hook is broken"

    # relaunch WITHOUT the stall hook: must resume, not restart
    env2 = _campaign_env(tmp_path, out)
    r = subprocess.run(_campaign_cmd(), env=env2, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "resuming from existing checkpoint" in r.stdout, r.stdout
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1
    assert rows[0]["done"]
    assert (rows[0]["tree"], rows[0]["best"], rows[0]["iters"]) == \
        CAMPAIGN_GOLDEN
    assert not ckpt.exists(), "completed run must remove its checkpoint"


def test_supervisor_recovers_from_repeated_kill_injection(tmp_path):
    """Preemption torture: TTS_FAULTS=kill_after_segment=2 rides the
    supervisor's env into EVERY respawned worker, so each incarnation
    is killed (exit 137) two segments after it resumes. Progress still
    converges — every death leaves a fresh checkpoint behind — and the
    final counters are bit-identical to an unkilled run."""
    out = tmp_path / "campaign.jsonl"
    env = _campaign_env(tmp_path, out,
                        TTS_FAULTS="kill_after_segment=2",
                        TTS_STALL_GRACE="180", TTS_STALL_MIN="4")
    proc = subprocess.run(_campaign_cmd(), env=env, timeout=900,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1, proc.stdout
    row = rows[0]
    assert row["restarts"] >= 1, (row, proc.stdout)
    assert row["done"], row
    assert (row["tree"], row["best"], row["iters"]) == CAMPAIGN_GOLDEN


def test_campaign_partial_budget_keeps_checkpoint_and_extends(tmp_path):
    """ADVICE r5: the supervisor used to unlink the checkpoint on
    budget-exhausted PARTIAL rows and the rerun skip-key ignored
    budget/done — so a larger-budget rerun silently skipped the
    instance and the in-flight progress was unrecoverable. Now a
    partial row keeps its checkpoint, a same-budget rerun still skips,
    and a larger-budget rerun RESUMES it to the bit-identical solved
    counters."""
    out = tmp_path / "campaign.jsonl"
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"
    env = _campaign_env(tmp_path, out, TTS_BUDGET_S="0.01")
    r = subprocess.run(_campaign_cmd(), env=env, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1 and rows[0]["done"] is False, rows
    assert ckpt.exists(), "partial row must keep its checkpoint"

    # same budget: nothing new to measure — skip, no new row
    r2 = subprocess.run(_campaign_cmd(), env=env, timeout=600,
                        capture_output=True, text=True)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "skipping" in r2.stdout, r2.stdout
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1

    # larger budget: resume the kept checkpoint and finish — counters
    # bit-identical to an uninterrupted run (the stall-test invariant)
    env3 = _campaign_env(tmp_path, out)          # default budget 600 s
    r3 = subprocess.run(_campaign_cmd(), env=env3, timeout=600,
                        capture_output=True, text=True)
    assert r3.returncode == 0, r3.stdout + r3.stderr
    assert "extending partial row" in r3.stdout, r3.stdout
    assert "resuming from existing checkpoint" in r3.stdout, r3.stdout
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 2 and rows[1]["done"], rows
    assert (rows[1]["tree"], rows[1]["best"], rows[1]["iters"]) == \
        CAMPAIGN_GOLDEN
    assert not ckpt.exists(), "solved run must retire its checkpoint"


def test_supervisor_screens_out_corrupt_checkpoint(tmp_path):
    """A mid-file-corrupted checkpoint (torn write: zlib.error /
    BadZipFile on read, neither a KeyError/OSError/ValueError) must be
    screened out and deleted at campaign startup, not crash the
    supervisor."""
    from tpu_tree_search.utils import faults

    out = tmp_path / "campaign.jsonl"
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"
    env = _campaign_env(tmp_path, out, TTS_BUDGET_S="0.01")
    r = subprocess.run(_campaign_cmd(), env=env, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert ckpt.exists()
    faults.corrupt_file(ckpt)

    env2 = _campaign_env(tmp_path, out)
    r2 = subprocess.run(_campaign_cmd(), env=env2, timeout=600,
                        capture_output=True, text=True)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert rows[-1]["done"], rows
    assert (rows[-1]["tree"], rows[-1]["best"], rows[-1]["iters"]) == \
        CAMPAIGN_GOLDEN


def test_campaign_serve_mode_same_rows(tmp_path):
    """The campaign's default path is now the search service
    (tools/run_campaign.py serve_main): one process, every instance
    submitted to an in-process SearchServer, the SAME JSONL row schema.
    The ta003 totals must match the legacy golden (tree/best are
    engine-invariant under ub=opt; iters is not asserted — the service
    runs the distributed engine with a BFS warm-up, the legacy worker
    the root-seeded single-device loop), a solved row must retire its
    checkpoint, and the executable-cache summary line must report the
    compile count."""
    out = tmp_path / "campaign.jsonl"
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"
    env = _campaign_env(tmp_path, out)
    cmd = [c for c in _campaign_cmd() if c != "--no-serve"]
    r = subprocess.run(cmd, env=env, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "executor cache" in r.stdout, r.stdout
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1, r.stdout
    row = rows[0]
    assert row["done"], row
    assert (row["tree"], row["best"]) == CAMPAIGN_GOLDEN[:2]
    # same schema as the legacy supervisor's rows
    for key in ("inst", "jobs", "machines", "lb", "chunk", "budget_s",
                "ub_mode", "done", "elapsed_s", "tree", "sol", "best",
                "evals", "iters", "pool_at_stop", "pushed_per_s",
                "evals_per_s", "restarts"):
        assert key in row, key
    assert not ckpt.exists(), "solved run must retire its checkpoint"

    # rerun: the done row retires the instance in serve mode too
    r2 = subprocess.run(cmd, env=env, timeout=600,
                        capture_output=True, text=True)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "skipping" in r2.stdout, r2.stdout
    assert len(out.read_text().splitlines()) == 1


def test_campaign_serve_partial_budget_extends(tmp_path):
    """Serve-mode budget semantics match the legacy supervisor's: a
    budget-exhausted instance lands a partial row (DEADLINE) keeping a
    checkpoint that carries the legacy config meta (inst/lb/chunk/
    ub_mode — the --no-serve supervisor's screen accepts it) AND the
    cumulative spent_s clock; a larger-budget rerun EXTENDS from the
    checkpoint to the bit-identical solved counters."""
    out = tmp_path / "campaign.jsonl"
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"
    cmd = [c for c in _campaign_cmd() if c != "--no-serve"]
    env = _campaign_env(tmp_path, out, TTS_BUDGET_S="0.01")
    r = subprocess.run(cmd, env=env, timeout=600,
                       capture_output=True, text=True)
    assert r.returncode == 0, r.stdout + r.stderr
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 1 and rows[0]["done"] is False, rows
    assert ckpt.exists(), "partial row must keep its checkpoint"
    with np.load(ckpt) as z:
        assert int(z["meta_inst"]) == 3 and int(z["meta_lb"]) == 2
        assert int(z["meta_chunk"]) == 32
        assert str(z["meta_ub_mode"]) == "opt"
        assert float(z["meta_spent_s"]) > 0.0

    env2 = _campaign_env(tmp_path, out)          # default budget 600 s
    r2 = subprocess.run(cmd, env=env2, timeout=600,
                        capture_output=True, text=True)
    assert r2.returncode == 0, r2.stdout + r2.stderr
    assert "extending partial row" in r2.stdout, r2.stdout
    rows = [json.loads(ln) for ln in out.read_text().splitlines() if ln]
    assert len(rows) == 2 and rows[1]["done"], rows
    assert (rows[1]["tree"], rows[1]["best"]) == CAMPAIGN_GOLDEN[:2]
    # cumulative clock: the second row's elapsed includes the first
    # run's spend (budget continuity across server lifetimes)
    assert rows[1]["elapsed_s"] >= float(np.float64(rows[0]["elapsed_s"]))
    assert not ckpt.exists(), "solved run must retire its checkpoint"


def test_worker_resumes_stacked_distributed_checkpoint(tmp_path):
    """ADVICE r5: worker resume called int(np.asarray(state.iters)) and
    died with TypeError on a stacked distributed checkpoint, turning a
    config mistake into repeated worker deaths. Now it collapses the
    stack onto the single device via the elastic reshard and completes
    with exact accounting (warm-up counters ride the meta)."""
    from tpu_tree_search.problems import taillard

    out = tmp_path / "campaign.jsonl"
    status = tmp_path / "tts_ta003_lb2.status.jsonl"
    ckpt = tmp_path / "tts_ta003_lb2.ckpt.npz"
    p = taillard.processing_times(3)
    opt = taillard.optimal_makespan(3)
    part = distributed.search(p, lb_kind=2, init_ub=opt, n_devices=2,
                              chunk=8, capacity=1 << 16, min_seed=8,
                              segment_iters=20, max_rounds=10,
                              checkpoint_path=str(ckpt), heartbeat=None)
    assert ckpt.exists()
    assert not part.complete, "partial run finished — nothing to resume"

    cmd = _campaign_cmd()[:-1] + ["--worker", "3"]
    env = _campaign_env(tmp_path, out)
    proc = subprocess.run(cmd, env=env, timeout=600,
                          capture_output=True, text=True)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    recs = [json.loads(ln) for ln in status.read_text().splitlines()
            if ln.strip()]
    kinds = [r["kind"] for r in recs]
    assert "reshard" in kinds, kinds
    done = [r for r in recs if r["kind"] == "done"]
    assert done and done[0]["done"], recs
    assert done[0]["best"] == opt == 1081
    # explored-node accounting exact across the 2-worker -> 1-device
    # reshard: warm-up + device counters add up to the campaign golden
    assert done[0]["tree"] == CAMPAIGN_GOLDEN[0]


def test_dist_ub_opt_unchanged_counts():
    """The new balance plan + transactional rounds keep the ub=opt
    deterministic-tree invariant vs the sequential oracle."""
    inst = PFSPInstance.synthetic(jobs=8, machines=4, seed=0)
    opt = inst.brute_force_optimum()
    want = seq.pfsp_search(inst, lb=2, init_ub=opt)
    got = distributed.search(inst.p_times, lb_kind=2, init_ub=opt,
                             chunk=8, capacity=1 << 12, min_seed=4,
                             balance_period=2, min_transfer=2)
    assert (got.explored_tree, got.explored_sol, got.best) == \
           (want.explored_tree, want.explored_sol, want.best)
