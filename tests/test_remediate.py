"""Self-healing service: alert-driven remediation, submesh quarantine,
chaos drills (service/remediate.py + the utils/faults drill kinds).

The load-bearing assertions (ISSUE acceptance):

- full alert lifecycle under remediation: an injected stall ->
  pending -> firing -> AUTO-preempt (no human action) -> elastic
  resume on a different, non-excluded submesh -> resolved, with
  bit-identical node/sol/evals totals against an undisturbed run;
- a request whose failures follow it across >= K distinct submeshes
  dead-letters as FAILED with a complete failure_log after a bounded
  attempt count — never an infinite redispatch loop;
- failures localized to ONE submesh quarantine it (drain, hold out of
  the partition, canary-probe, readmit on success) while requests
  route around it;
- TTS_REMEDIATE off (the default) takes ZERO actions — observe-only
  journaling, bit-identical to the pre-remediation server;
- actions are rate-limited per rule per window; reversals
  (admission resume) are exempt;
- the degraded (quarantined-submesh) configuration is visible on
  /status, in the fleet aggregation, and turns the doctor verdict
  nonzero.
"""

import json
import os
import time

import pytest

from tpu_tree_search.engine import distributed, ladder
from tpu_tree_search.obs import aggregate, dashboard, health, metrics, tracelog
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest, SearchServer
from tpu_tree_search.utils import faults

KW = dict(chunk=8, capacity=1 << 12, min_seed=4)


@pytest.fixture
def fresh_obs(tmp_path):
    log = tracelog.TraceLog(capacity=1 << 16,
                            sink_path=tmp_path / "trace.jsonl")
    prev_log = tracelog.install(log)
    reg = metrics.Registry()
    prev_reg = metrics.install(reg)
    try:
        yield log, reg
    finally:
        tracelog.install(prev_log)
        metrics.install(prev_reg)
        ladder.set_memory_pressure(False)


def wait_until(cond, timeout=120.0, what="condition"):
    t0 = time.monotonic()
    while not cond():
        assert time.monotonic() - t0 < timeout, f"timed out on {what}"
        time.sleep(0.02)


# -------------------------------------------------- chaos-drill faults


def test_fault_drill_parse_and_filters(fresh_obs):
    p = faults.FaultPlan.parse(
        "kill_submesh=2:3@0,oom_segment=1,wedge_executor=3:0.1@1")
    assert p.kill_submesh == (2, 3, 0)
    assert p.oom_segment == (1, 1, None)       # default budget 1
    assert p.wedge_executor == (3, 0.1, 1)
    with pytest.raises(ValueError):
        faults.FaultPlan.parse("kill_submash=2")
    # @0 filter: no ambient submesh context -> never fires
    with faults.scoped("kill_submesh=1:1@0"):
        faults.fire("segment_start", segment=1)     # no context: no-op
        with tracelog.context(submesh=1):
            faults.fire("segment_start", segment=1)  # wrong submesh
        with tracelog.context(submesh=0):
            with pytest.raises(faults.InjectedKill):
                faults.fire("segment_start", segment=1)
            # budget 1 spent: the same point is now clean (the canary
            # probe's readmit contract)
            faults.fire("segment_start", segment=1)
    # oom raises its RESOURCE_EXHAUSTED-shaped transient
    with faults.scoped("oom_segment=2"):
        with pytest.raises(faults.InjectedOOM, match="RESOURCE_EXHAUSTED"):
            faults.fire("segment_start", segment=2)
    # both are TRANSIENT-class: the service retry tier must catch them
    from tpu_tree_search.engine.checkpoint import TRANSIENT_ERRORS
    assert issubclass(faults.InjectedKill, TRANSIENT_ERRORS[1])
    assert issubclass(faults.InjectedOOM, TRANSIENT_ERRORS[1])


# ------------------------------------ the acceptance drill: stall heals


def test_stall_remediation_full_lifecycle(fresh_obs, tmp_path,
                                          monkeypatch):
    """Injected wedge -> stall fires -> controller preempts at the
    segment boundary, checkpoints, requeues with the offending submesh
    excluded -> elastic resume on the OTHER submesh -> DONE with
    bit-identical totals -> alert resolves. No human in the loop."""
    monkeypatch.setenv("TTS_HEALTH_STALL_S", "1.0")
    monkeypatch.setenv("TTS_HEALTH_STALL_WARMUP_S", "5.0")
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    base = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=4, **KW)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      health_interval_s=0.05, remediate=True,
                      share_incumbent=False) as srv:
        # warm the executor cache so the wedged request's dispatch goes
        # straight into segments (a cold compile would eat the drill's
        # timing budget, not change its semantics)
        warm = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16, **KW))
        assert srv.result(warm, timeout=300).state == "DONE"
        # wedge EARLY (segment 2 of a ~5-segment solve) so real work
        # remains after the preempt — a wedge in the last segment
        # would let completion win the race against the stop flag
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            checkpoint_every=1, faults="wedge_executor=2:4.0", **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        # the controller acted: >= 1 auto-preemption, a second dispatch
        # on a submesh OUTSIDE the excluded set, zero failures
        assert rec.preemptions >= 1 and rec.dispatches >= 2
        assert rec.excluded_submeshes, "offender was not excluded"
        assert rec.submesh not in rec.excluded_submeshes
        assert rec.failures == 0 and rec.failure_log == []
        # bit-identical to the undisturbed run (same-size submesh
        # resume is exact)
        res = rec.result
        assert (res.explored_tree, res.explored_sol, res.best) == \
            (base.explored_tree, base.explored_sol, base.best)

        def stall():
            return srv.health.alerts.get("stall")

        wait_until(lambda: stall() is not None
                   and stall().state == health.RESOLVED,
                   what="stall alert resolving")
        assert stall().fired_count >= 1
        snap = srv.status_snapshot()["remediation"]
        assert snap["enabled"] and snap["mode"] == "act"
        applied = [a for a in snap["actions"]
                   if a["action"] == "preempt_requeue"
                   and a["outcome"] == "applied"]
        assert applied and applied[0]["detail"]["request_id"]
    log, _ = fresh_obs
    names = {r["name"] for r in log.records()}
    assert "remediation.applied" in names
    assert "alert.resolved" in names


def test_observe_mode_takes_no_action(fresh_obs, tmp_path, monkeypatch):
    """TTS_REMEDIATE off (default): the same stall is detected and the
    would-be action journaled, but nothing is touched — the request
    rides out the wedge on its original submesh, bit-identically."""
    monkeypatch.setenv("TTS_HEALTH_STALL_S", "0.6")
    monkeypatch.setenv("TTS_HEALTH_STALL_WARMUP_S", "5.0")
    monkeypatch.delenv("TTS_REMEDIATE", raising=False)
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    base = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                              n_devices=4, **KW)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      health_interval_s=0.05,
                      share_incumbent=False) as srv:
        assert not srv.remediation.enabled
        warm = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16, **KW))
        assert srv.result(warm, timeout=300).state == "DONE"
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="wedge_executor=2:2.0", **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        # zero actions: no preemption, no exclusions, single dispatch
        assert rec.preemptions == 0 and rec.dispatches == 1
        assert rec.excluded_submeshes == set()
        res = rec.result
        assert (res.explored_tree, res.explored_sol, res.best) == \
            (base.explored_tree, base.explored_sol, base.best)
        snap = srv.status_snapshot()["remediation"]
        assert snap["mode"] == "observe"
        observed = [a for a in snap["actions"]
                    if a["outcome"] == "observed"
                    and a["action"] == "preempt_requeue"]
        assert observed, snap["actions"]
        assert all(a["outcome"] == "observed" for a in snap["actions"])


# --------------------------------------------- dead-letter vs quarantine


def test_deadletter_after_distinct_submeshes(fresh_obs, tmp_path):
    """A fault that FOLLOWS the request (kill on every submesh) must
    dead-letter after K distinct submeshes — bounded attempts, full
    failure_log — even with retry budget to spare."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=4, workdir=tmp_path / "wd",
                      health_interval_s=0, remediate=True,
                      service_retry_attempts=8,
                      service_retry_base_s=0.01,
                      share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="kill_submesh=1:99", **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "FAILED"
        assert "dead-lettered" in rec.error
        assert rec.dispatches == 3          # bounded: K, not 1+retries
        snap = srv.status(rid)
        flog = snap["failure_log"]
        assert len(flog) == 3
        assert len({f["submesh"] for f in flog}) == 3
        assert all(f["error"] and f["attempt"] == i + 1
                   for i, f in enumerate(flog))
        journal = srv.status_snapshot()["remediation"]["actions"]
        assert any(a["action"] == "deadletter"
                   and a["outcome"] == "applied" for a in journal)


def test_deadletter_threshold_clamps_to_partition(fresh_obs, tmp_path):
    """On a 2-submesh server the default threshold (3) clamps to 2:
    a request that failed on BOTH submeshes has followed its fault
    everywhere it can go and must dead-letter, not ping-pong through
    the whole retry budget."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      health_interval_s=0, remediate=True,
                      service_retry_attempts=8,
                      service_retry_base_s=0.01,
                      share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="kill_submesh=1:99", **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "FAILED" and "dead-lettered" in rec.error
        assert rec.dispatches == 2
        flog = srv.status(rid)["failure_log"]
        assert len({f["submesh"] for f in flog}) == 2


def test_excluded_head_preempts_instead_of_priority_inversion(
        fresh_obs, tmp_path):
    """A free slot only suppresses priority preemption if the head of
    the line can USE it: high-priority H, excluded from the free
    submesh by its own failure there, must preempt low-priority L off
    the submesh H can still run on — not wait behind it."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      health_interval_s=0, remediate=True,
                      service_retry_attempts=4,
                      service_retry_base_s=0.01,
                      share_incumbent=False) as srv:
        lo = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, priority=0,
            segment_iters=8, checkpoint_every=1,
            faults="delay_every=0.3", **KW))
        wait_until(lambda: srv.status(lo)["state"] == "RUNNING",
                   what="low-priority running")
        assert srv.status(lo)["submesh"] == 0
        # H lands on the free submesh 1, dies there once, gets it
        # excluded — and must then preempt L off submesh 0
        hi = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, priority=5,
            segment_iters=16, faults="kill_submesh=1:1@1", **KW))
        rec_hi = srv.result(hi, timeout=300)
        assert rec_hi.state == "DONE", (rec_hi.state, rec_hi.error)
        assert rec_hi.submesh == 0
        assert rec_hi.excluded_submeshes == {1}
        rec_lo = srv.result(lo, timeout=300)
        assert rec_lo.state == "DONE", (rec_lo.state, rec_lo.error)
        assert rec_lo.preemptions >= 1     # it made way for H


def test_quarantine_drains_probes_and_readmits(fresh_obs, tmp_path):
    """Failures LOCALIZED to submesh 0 (a global @0 drill plan)
    quarantine it: requests route around it and complete; the canary
    probe readmits it once the submesh behaves (drill budget spent).
    The degraded window is visible in the snapshot."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    faults.configure("kill_submesh=1:2@0")
    try:
        with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                          health_interval_s=0, remediate=True,
                          service_retry_attempts=4,
                          service_retry_base_s=0.01,
                          share_incumbent=False) as srv:
            srv.remediation.quarantine_fails = 2
            srv.remediation.probe_s = 0.2
            r1 = srv.submit(SearchRequest(
                p_times=inst.p_times, lb_kind=1, segment_iters=16,
                **KW))
            rec1 = srv.result(r1, timeout=300)
            assert rec1.state == "DONE", (rec1.state, rec1.error)
            assert len(srv.status(r1)["failure_log"]) == 1
            r2 = srv.submit(SearchRequest(
                p_times=inst.p_times, lb_kind=1, segment_iters=16,
                **KW))
            rec2 = srv.result(r2, timeout=300)
            assert rec2.state == "DONE", (rec2.state, rec2.error)
            snap = srv.status_snapshot()
            quar = snap["remediation"]["quarantined"]
            assert [q["submesh"] for q in quar] == [0]
            assert snap["submeshes"][0]["quarantined"] is True
            # both requests were healed AROUND the bad submesh
            assert rec1.submesh == 1 and rec2.submesh == 1
            # ...and the canary readmits it (the drill budget is spent,
            # so the synthetic micro-request completes cleanly)
            wait_until(lambda: not srv.slots[0].quarantined,
                       what="canary readmit")
            journal = srv.status_snapshot()["remediation"]["actions"]
            acts = [(a["action"], a["outcome"]) for a in journal]
            assert ("quarantine_submesh", "applied") in acts
            assert ("readmit_submesh", "applied") in acts
            g = srv.metrics.gauge("tts_quarantined_submeshes")
            assert g.value() == 0.0
    finally:
        faults.reset()


def test_spool_holds_backlog_when_pause_lands_mid_iteration(tmp_path):
    """The pause engaging between the serve loop's paused check and
    submit() must HOLD the file for the next poll, never write a
    terminal REJECTED result."""
    from tpu_tree_search.service import spool
    from tpu_tree_search.service.queueing import (AdmissionError,
                                                  AdmissionPaused)

    class StubServer:
        slots = ()

        def __init__(self, exc):
            self.exc = exc
            self.queue = []

        def admission_paused(self):
            return None     # the loop's upfront check sees "admitting"

        def submit(self, request, **kw):   # kw: spool_id (the ledger's
            raise self.exc                 # result-delivery reconnect key)

        def status(self, rid):
            raise AssertionError("nothing should be pending")

    sid = spool.submit_file(tmp_path, {"p_times": [[3, 4], [5, 6]],
                                       "lb": 1})
    srv = StubServer(AdmissionPaused("admission paused: compile storm"))
    served = spool.serve_spool(srv, tmp_path, should_exit=lambda: True)
    assert served == 0
    res = tmp_path / f"{sid}{spool.RES_SUFFIX}"
    assert not res.exists()          # held, not rejected
    # ...while a REAL rejection (queue full) still writes the result
    srv = StubServer(AdmissionError(
        "queue full: depth 64 at the admission bound 64"))
    spool.serve_spool(srv, tmp_path, should_exit=lambda: True)
    assert json.loads(res.read_text())["state"] == "REJECTED"


def test_deadletter_failure_still_quarantines_the_submesh(
        fresh_obs, tmp_path):
    """A failure that dead-letters the request AND trips its submesh's
    localized-failure threshold must do both — the hardware evidence
    stands on its own. Two poisoned requests: the second one's
    failures push BOTH submeshes to the quarantine threshold on the
    same failures that dead-letter it; submesh 0 quarantines (normal
    path), submesh 1 is reached via the DEAD-LETTER branch and then
    refused as the last healthy one."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      health_interval_s=0, remediate=True,
                      service_retry_attempts=8,
                      service_retry_base_s=0.01,
                      share_incumbent=False) as srv:
        srv.remediation.quarantine_fails = 2
        srv.remediation.probe_s = 3600.0     # no readmit mid-test
        r1 = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="kill_submesh=1:2", **KW))
        rec1 = srv.result(r1, timeout=300)
        assert rec1.state == "FAILED" and "dead-lettered" in rec1.error
        assert rec1.dispatches == 2          # clamped threshold: 2
        r2 = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="kill_submesh=1:99", **KW))
        rec2 = srv.result(r2, timeout=300)
        assert rec2.state == "FAILED" and "dead-lettered" in rec2.error
        # submesh 0 hit 2 localized failures -> quarantined; submesh 1
        # hit its 2nd ON the dead-lettering failure -> the quarantine
        # was still attempted (the fix under test) and refused as the
        # last healthy submesh
        assert [s.index for s in srv.slots if s.quarantined] == [0]
        journal = srv.status_snapshot()["remediation"]["actions"]
        acts = [(a["action"], a["outcome"]) for a in journal]
        assert acts.count(("deadletter", "applied")) == 2
        assert ("quarantine_submesh", "applied") in acts
        assert ("quarantine_submesh", "skipped") in acts


def test_quarantine_refuses_last_healthy_submesh(fresh_obs, tmp_path):
    """A single-submesh server must never quarantine itself to zero
    capacity — the decision journals as skipped."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    faults.configure("kill_submesh=1:2@0")
    try:
        with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                          health_interval_s=0, remediate=True,
                          service_retry_attempts=4,
                          service_retry_base_s=0.01,
                          share_incumbent=False) as srv:
            srv.remediation.quarantine_fails = 2
            rid = srv.submit(SearchRequest(
                p_times=inst.p_times, lb_kind=1, segment_iters=16,
                **KW))
            rec = srv.result(rid, timeout=300)
            # two kills, then the budget is spent and the third
            # dispatch (exclusions cleared: nowhere else to run)
            # completes on the sole submesh
            assert rec.state == "DONE", (rec.state, rec.error)
            assert len(srv.status(rid)["failure_log"]) == 2
            assert not srv.slots[0].quarantined
            journal = srv.status_snapshot()["remediation"]["actions"]
            assert any(a["action"] == "quarantine_submesh"
                       and a["outcome"] == "skipped" for a in journal)
    finally:
        faults.reset()


# ----------------------------------------- policy actions, unit-driven


def test_exclusions_covering_all_healthy_slots_do_not_strand(
        fresh_obs, tmp_path):
    """A request excluded from every healthy slot (its exclusions were
    capped against the FULL partition, then a quarantine shrank it)
    must become eligible again instead of sitting QUEUED forever."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      autostart=False, health_interval_s=0,
                      remediate=True, share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        rec = srv.records[rid]
        srv.add_exclusion(rec, 1)          # excluded from submesh 1...
        srv.slots[0].quarantined = True    # ...and submesh 0 held out
        srv.start()
        done = srv.result(rid, timeout=300)
        assert done.state == "DONE", (done.state, done.error)
        assert done.submesh == 1           # least-bad: the healthy slot


def test_pause_admission_on_compile_storm_and_resume(fresh_obs,
                                                     tmp_path):
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      autostart=False, health_interval_s=0,
                      remediate=True, share_incumbent=False) as srv:
        from tpu_tree_search.service.queueing import AdmissionError
        ctl = srv.remediation
        assert ctl.handle("compile_storm", "pause_admission",
                          {"detail": {"compiles_in_interval": 9}}) \
            == "applied"
        assert "compile storm" in srv.admission_paused()
        assert srv.metrics.gauge("tts_admission_paused").value() == 1.0
        with pytest.raises(AdmissionError, match="admission paused"):
            srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                     **KW))
        rejected_before = srv.queue.rejected
        assert rejected_before >= 1
        # the resolution reverses the valve — reversals are NEVER
        # rate-limited
        assert ctl.handle("compile_storm", "resume_admission", {}) \
            == "applied"
        assert srv.admission_paused() is None
        assert srv.metrics.gauge("tts_admission_paused").value() == 0.0
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        assert srv.status(rid)["state"] == "QUEUED"


def test_rate_valve_caps_per_rule_per_window(fresh_obs, tmp_path):
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      autostart=False, health_interval_s=0,
                      remediate=True, share_incumbent=False) as srv:
        ctl = srv.remediation
        ctl.max_per_rule = 1
        ctl.window_s = 3600.0
        assert ctl.handle("compile_storm", "pause_admission",
                          {}) == "applied"
        assert ctl.handle("compile_storm", "resume_admission",
                          {}) == "applied"          # reversal exempt
        assert ctl.handle("compile_storm", "pause_admission",
                          {}) == "rate_limited"
        # the capped action touched nothing
        assert srv.admission_paused() is None
        c = srv.metrics.counter("tts_remediations_total")
        assert c.value(rule="compile_storm", action="pause_admission",
                       outcome="rate_limited") == 1
        # only EXECUTED actions consume the budget: stale noops (the
        # alerted request is gone) must not rate-limit the next real one
        for _ in range(3):
            assert ctl.handle("stall", "preempt_requeue",
                              {"detail": {"request_id": "gone"}}) \
                == "noop"


def test_mem_headroom_sheds_and_raises_ladder_pressure(fresh_obs,
                                                       tmp_path):
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      health_interval_s=0, remediate=True,
                      share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=8,
            checkpoint_every=1, faults="delay_every=0.2", **KW))
        wait_until(lambda: (srv.status(rid)["progress"] or {})
                   .get("segment", 0) >= 1, what="first heartbeat")
        assert srv.remediation.handle("mem_headroom", "shed_memory",
                                      {}) == "applied"
        assert ladder.memory_pressure()
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        assert rec.preemptions >= 1          # it was shed and resumed
        # shed does NOT exclude the submesh — nothing is wrong with it
        assert rec.excluded_submeshes == set()
        assert srv.remediation.handle(
            "mem_headroom", "clear_memory_pressure", {}) == "applied"
        assert not ladder.memory_pressure()


def test_audit_action_quarantines_bad_checkpoint(fresh_obs, tmp_path):
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      autostart=False, health_interval_s=0,
                      remediate=True, share_incumbent=False) as srv:
        bad = tmp_path / "wd" / "t.ckpt.npz"
        bad.write_bytes(b"torn" * 64)
        alert = {"detail": {"invariant": "checkpoint_roundtrip",
                            "detail": {"path": str(bad)}}}
        assert srv.remediation.handle("audit", "quarantine_checkpoint",
                                      alert) == "applied"
        assert not bad.exists()
        assert os.path.exists(str(bad) + ".corrupt")
        # a non-checkpoint audit finding is a noop, not an error
        assert srv.remediation.handle(
            "audit", "quarantine_checkpoint",
            {"detail": {"invariant": "node_conservation"}}) == "noop"


# ------------------------------------------- surfaces: doctor + trace


def test_aggregate_degraded_verdict_and_dashboards(fresh_obs):
    status = {
        "uptime_s": 12.0,
        "queue": {"depth": 0},
        "submeshes": [{"index": 0, "running": None,
                       "quarantined": True},
                      {"index": 1, "running": "req-0001",
                       "quarantined": False}],
        "remediation": {
            "enabled": True, "mode": "act",
            "quarantined": [{"submesh": 0, "since": 1.0,
                             "reason": "localized failures"}],
            "admission_paused": "compile storm",
            "counts": {}, "probes_pending": 1,
            "actions": [{"t": 1.0, "rule": "stall",
                         "action": "preempt_requeue",
                         "outcome": "applied",
                         "detail": {"request_id": "req-0001"}}]},
        "requests": {}}
    fleet = {"t": 0.0, "servers": [{
        "origin": "h:1", "url": "http://h:1", "ok": True,
        "error": None, "healthz": {"code": 200, "status": "ok"},
        "status": status, "alerts": {"firing": 0, "alerts": []},
        "metrics": []}]}
    merged = aggregate.merge(fleet)
    row = merged["servers"][0]
    assert row["quarantined"] == 1
    assert row["admission_paused"] == "compile storm"
    healthy, reasons = aggregate.verdict(merged)
    assert not healthy
    assert any("DEGRADED" in r for r in reasons)
    html = dashboard.render_fleet(merged)
    assert "degraded" in html and "quarantined" in html.lower()
    assert "<script" not in html
    html = dashboard.render_server(status, None, None)
    assert "Self-healing" in html and "preempt_requeue" in html
    assert "paused" in html


def test_trace_summary_failure_log_and_fail_column(fresh_obs):
    import pathlib
    import sys
    sys.path.insert(0, str(pathlib.Path(__file__).parent.parent
                           / "tools"))
    import trace_summary

    records = [
        {"name": "request.admit", "ts": 1.0, "request_id": "req-0000"},
        {"name": "request.dispatch", "ts": 2.0,
         "request_id": "req-0000", "submesh": 0},
        {"name": "request.dispatch_failure", "ts": 3.0,
         "request_id": "req-0000", "submesh": 0, "attempt": 1,
         "error": "transient: InjectedKill('boom')"},
        {"name": "request.redispatch", "ts": 3.1,
         "request_id": "req-0000", "failures": 1,
         "error": "transient: InjectedKill('boom')"},
        {"name": "request.dispatch", "ts": 4.0,
         "request_id": "req-0000", "submesh": 1},
        {"name": "remediation.applied", "ts": 4.5,
         "request_id": "req-0000", "rule": "retry",
         "action": "exclude_submesh"},
        # the TERMINAL failure has no redispatch event — only the
        # dispatch_failure record carries it into the trace
        {"name": "request.dispatch_failure", "ts": 5.0,
         "request_id": "req-0000", "submesh": 1, "attempt": 2,
         "error": "transient: InjectedKill('fatal')"},
        {"name": "request.failed", "ts": 5.1,
         "request_id": "req-0000"},
        # server-level remediation (quarantine) carries no request id
        # but must still reach the footer count
        {"name": "remediation.applied", "ts": 5.2, "rule": "quarantine",
         "action": "quarantine_submesh", "submesh": 1},
    ]
    reqs = trace_summary.summarize(records)
    s = reqs["req-0000"]
    assert s["failures"] == 2 and s["remediations"] == 1
    assert [(e["submesh"], e["attempt"]) for e in s["failure_log"]] \
        == [(0, 1), (1, 2)]
    out = trace_summary.render(reqs)
    assert "fail" in out.splitlines()[0]
    assert "failure log req-0000" in out
    assert "InjectedKill" in out and "fatal" in out
    assert "1 request(s)" in out          # the pseudo-row is not a row
    assert "2 dispatch failure(s)" in out
    assert "2 remediation record(s)" in out


def test_failure_log_snapshot_json_safe(fresh_obs, tmp_path):
    """The failure_log rides /status as plain JSON (the dead-letter
    diagnosis surface) and is bounded."""
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=2)
    with SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                      health_interval_s=0, service_retry_attempts=1,
                      service_retry_base_s=0.01,
                      share_incumbent=False) as srv:
        rid = srv.submit(SearchRequest(
            p_times=inst.p_times, lb_kind=1, segment_iters=16,
            faults="kill_submesh=1:1", **KW))
        rec = srv.result(rid, timeout=300)
        assert rec.state == "DONE", (rec.state, rec.error)
        snap = srv.status(rid)
        assert len(snap["failure_log"]) == 1
        entry = snap["failure_log"][0]
        assert entry["submesh"] == 0 and entry["attempt"] == 1
        assert "InjectedKill" in entry["error"]
        json.dumps(srv.status_snapshot())     # everything serializes
