"""Measured per-phase CSV timing columns (utils/phase_timing).

Round 1 wrote structural zeros into the reference-schema timing columns
(PFSP_statistic.c:69-112); these tests pin the round-2 behavior: unit
phase costs are MEASURED on the real shapes, attributed by counters,
nonzero, and sum to ~the run's wall time — so
data/multigpu-stats-analysis.py has real data to analyze.
"""

import numpy as np
import pytest

from tpu_tree_search.engine import device
from tpu_tree_search.ops import batched
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.utils import analysis, csv_stats, phase_timing


def test_profile_phases_measures_positive_costs():
    inst = PFSPInstance.synthetic(jobs=8, machines=4, seed=1)
    tables = batched.make_tables(inst.p_times)
    state = device.init_state(8, 1 << 12, None, p_times=inst.p_times)
    prof = phase_timing.profile_phases(tables, state, 1, chunk=16,
                                       warm_iters=4)
    assert prof["bound"] > 0
    assert prof["step"] >= prof["bound"]
    assert prof["per_eval"] > 0
    assert prof["compact"] >= 0


def test_attribute_sums_to_elapsed_and_differentiates_workers():
    prof = {"bound": 2e-3, "step": 5e-3, "compact": 3e-3,
            "per_eval": 2e-3 / 128}
    att = phase_timing.attribute(prof, elapsed=1.0,
                                 evals=[12800, 3200], iters=[100, 100],
                                 balance_rounds=10, t_balance=5e-3)
    total0 = (att["kernel_time"][0] + att["gen_child_time"][0]
              + att["balance_time"][0] + att["idle_time"][0])
    assert total0 == pytest.approx(1.0, rel=1e-6)
    # the busier worker gets more kernel time, the starved one more idle
    assert att["kernel_time"][0] > att["kernel_time"][1]
    assert att["idle_time"][1] > att["idle_time"][0]
    assert att["balance_time"] == pytest.approx([0.05, 0.05])


def test_cli_dist_csv_has_real_phase_columns(tmp_path):
    """End-to-end: a single-controller -D 8 CLI run writes the
    reference's INTRA-NODE schema (multigpu.csv,
    PFSP_statistic.c:69-112 — `--multihost` runs write the dist
    schema) with per-worker timing arrays that are nonzero and bounded
    by the run's wall time."""
    from tpu_tree_search import cli

    path = tmp_path / "multigpu.csv"
    rc = cli.main(["pfsp", "-i", "3", "-l", "2", "-u", "1", "-D", "8",
                   "--chunk", "64", "--capacity", str(1 << 15),
                   "--csv", str(path)])
    assert rc == 0
    rows = analysis.read_rows(str(path))
    assert len(rows) == 1
    row = rows[0]
    assert "all_exp_tree_gpu" not in row     # dist-only column family
    kernel = np.asarray(row["gpu_kernel_time"], dtype=float)
    gen = np.asarray(row["gpu_gen_child_time"], dtype=float)
    idle = np.asarray(row["gpu_idle_time"], dtype=float)
    total = float(row["total_time"])
    assert len(kernel) == 8
    assert kernel.sum() > 0
    assert gen.sum() > 0
    # per-worker attribution never exceeds the wall time
    assert (kernel + gen + idle <= total * 1.05 + 1e-6).all()


def test_stats_analysis_consumes_real_breakdown(tmp_path):
    """The ported multigpu-stats-analysis pipeline sees nonzero phase
    data through write_multi."""
    path = tmp_path / "multidevice.csv"
    att = {"kernel_time": [0.5, 0.4], "gen_child_time": [0.2, 0.2],
           "balance_time": [0.1, 0.1], "idle_time": [0.2, 0.3]}
    csv_stats.write_multi(str(path), 21, 1, 2, 0, 1, 2297, 25, 50000,
                          5000, 1.0, 1000, 10,
                          {"tree": [600, 400], "sol": [6, 4],
                           "evals": [6000, 4000], "steals": [1, 2],
                           **att})
    rows = analysis.read_rows(str(path))
    br = analysis.per_pu_breakdown(
        rows, ("gpu_kernel_time", "gpu_gen_child_time", "pool_ops_time",
               "gpu_idle_time"))
    vals = br[0]
    assert vals["gpu_kernel_time"]["sum"] == pytest.approx(0.9)
    assert vals["pool_ops_time"]["sum"] == pytest.approx(0.2)
    assert vals["gpu_idle_time"]["sum"] == pytest.approx(0.5)
