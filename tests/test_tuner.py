"""Adaptive dispatch, tuner half: the measured-defaults table, the
warmed probe harness, the persistent tuning cache and the Autotuner's
cache -> probe -> defaults resolution.

The contracts, pinned deterministically on the CPU backend:

- ONE defaults table (tune/defaults.py) feeds utils/config, bench and
  the serving request model — the three hardcoded constants that used
  to drift are now reads of it;
- a cold tune() probes (warmed same-state measurements) and persists;
  a RESTARTED tuner over the same cache dir replays the winner with
  ZERO probe executions (the probe ledger stays empty);
- the request hot path (allow_probe=False) never probes: cold cache
  resolves straight to the defaults tier;
- a wrong-fingerprint entry is IGNORED (and overwritten by the next
  probe), never consumed; a corrupt/truncated entry is QUARANTINED
  (*.corrupt) and re-probed — the aot_cache discipline at tuning scale;
- distributed.search(chunk=None, tuner=...) consumes the tuned entry
  (the executor key proves which chunk actually compiled);
- spool payloads opt in with {"tuned": true}.
"""

import json
import os
import sys

import numpy as np
import pytest

from tpu_tree_search.engine import distributed
from tpu_tree_search.parallel.mesh import worker_mesh
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest
from tpu_tree_search.service.executors import ExecutorCache
from tpu_tree_search.service.spool import request_from_payload
from tpu_tree_search.tune import (Autotuner, ProbeError, ProbeHarness,
                                  TuningCache, defaults,
                                  measure_balance_periods)

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))

# tiny probe knobs: the contracts are about plumbing and persistence,
# not about measuring real optima on the virtual mesh
TUNE_KW = dict(chunks=(8, 16), periods=(2, 4), window_iters=6,
               warm_iters=20, capacity=1 << 12, repeats=1)


def small(seed=1, jobs=8, machines=3):
    return PFSPInstance.synthetic(jobs=jobs, machines=machines,
                                  seed=seed).p_times


# ------------------------------------------------------------- defaults


def test_defaults_table_is_the_single_source():
    from tpu_tree_search.utils.config import NQueensConfig, PFSPConfig
    assert PFSPConfig().chunk == defaults.CLI_CHUNK_DEFAULT
    assert PFSPConfig().balance_period == defaults.BALANCE_PERIOD_DEFAULT
    assert NQueensConfig().chunk == defaults.CLI_CHUNK_DEFAULT
    req = SearchRequest(p_times=small())
    assert req.chunk == defaults.SERVING_CHUNK_DEFAULT
    assert req.balance_period == defaults.BALANCE_PERIOD_DEFAULT
    # the measured bench row (the r5 single-chip retune) lives in the
    # table, per shape class
    assert defaults.params_for("bench", 20, 20).chunk \
        == defaults.BENCH_CHUNK_DEFAULT
    assert defaults.params_for("serving", 20, 20).chunk \
        == defaults.SERVING_CHUNK_DEFAULT
    with pytest.raises(ValueError):
        defaults.params_for("nonsense")


def test_request_chunk_none_is_valid_auto():
    req = SearchRequest(p_times=small(), chunk=None, balance_period=None)
    assert req.validate() is None
    assert SearchRequest(p_times=small(), chunk=0).validate() is not None


def test_spool_tuned_payload_opens_the_knobs():
    p = small()
    req = request_from_payload({"p_times": p.tolist(), "tuned": True})
    assert req.chunk is None and req.balance_period is None
    # explicit knobs in the same payload win over the tuned flag
    req2 = request_from_payload({"p_times": p.tolist(), "tuned": True,
                                 "chunk": 32})
    assert req2.chunk == 32 and req2.balance_period is None


# ---------------------------------------------------------------- probe


def test_probe_harness_same_state_measurement():
    h = ProbeHarness(small(), lb_kind=1, capacity=1 << 12, warm_chunk=8,
                     warm_iters=20, window_iters=6, repeats=1)
    r = h.measure(8, 4)
    assert r.evals > 0 and r.evals_per_s > 0 and r.ms_per_iter > 0
    assert not r.underfilled
    # a chunk above the warmed pool is flagged: its rate is a ramp
    # rate, and the tuner must deprioritize it
    big = h.measure(256, 4)
    assert big.underfilled
    # a chunk whose scratch margin eats the whole pool is refused
    # loudly (the tuner drops the candidate)
    with pytest.raises(ProbeError):
        h.measure(1 << 11, 4)
    # identical state across candidates: the pool the window started
    # from is the same for every measurement
    assert r.pool_start == big.pool_start


def test_probe_harness_refuses_exhausted_instance():
    # 4 jobs: the warm-up drains the whole tree — no steady state
    with pytest.raises(ProbeError):
        ProbeHarness(small(jobs=4), warm_chunk=8, warm_iters=50,
                     capacity=1 << 12)


def test_measure_balance_periods_legacy_rows():
    rows = measure_balance_periods(small(), 1, 8, (2, 4),
                                   capacity=1 << 12, warm_iters=20,
                                   window_iters=6, repeats=1)
    assert [r["balance_period"] for r in rows] == [2, 4]
    assert all(r["ms_per_iter"] > 0 and r["evals_per_s"] > 0
               for r in rows)


# ---------------------------------------------------------------- tuner


def test_tune_persists_and_warm_boot_replays_zero_probes(tmp_path):
    p = small()
    t1 = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t1.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert params.source == "probe"
    assert params.chunk in TUNE_KW["chunks"]
    assert params.balance_period in TUNE_KW["periods"] + (4,)
    assert t1.probes_run > 0 and len(t1.ledger) == t1.probes_run
    assert t1.cache.snapshot()["writes"] == 1

    # the restarted process: same dir, fresh tuner — the winner replays
    # with ZERO probe executions (the warm-boot contract, ledger-pinned)
    t2 = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    p2 = t2.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert (p2.chunk, p2.balance_period) == (params.chunk,
                                             params.balance_period)
    assert p2.source == "cache"
    assert t2.probes_run == 0 and t2.ledger == []
    assert t2.cache.snapshot()["hits"] == 1


def test_hot_path_never_probes(tmp_path):
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t.resolve(8, 3, 1, allow_probe=False)
    assert params.source == "default"
    assert params.chunk == defaults.SERVING_CHUNK_DEFAULT
    assert t.probes_run == 0
    # and without any cache at all, the same defaults tier answers
    t_nocache = Autotuner(**TUNE_KW)
    assert t_nocache.resolve(8, 3, 1).source == "default"


def test_fingerprint_mismatch_ignored_and_overwritten(tmp_path):
    p = small()
    root = tmp_path / "tune"
    ta = Autotuner(cache_dir=root, **TUNE_KW)
    ta.cache.fingerprint = dict(ta.cache.fingerprint, sim_runtime="A")
    pa = ta.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert pa.source == "probe"

    # runtime B (topology/platform drift simulation) must IGNORE A's
    # entry — a TPU optimum must never drive a CPU mesh — and re-probe
    tb = Autotuner(cache_dir=root, **TUNE_KW)
    tb.cache.fingerprint = dict(tb.cache.fingerprint, sim_runtime="B")
    pb = tb.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert pb.source == "probe" and tb.probes_run > 0
    snap = tb.cache.snapshot()
    assert snap["mismatches"] == 1 and snap["hits"] == 0
    assert snap["quarantined"] == 0     # a mismatch is not corruption

    # B's re-probe OVERWROTE the entry: B restarted now replays it
    tb2 = Autotuner(cache_dir=root, **TUNE_KW)
    tb2.cache.fingerprint = dict(tb2.cache.fingerprint, sim_runtime="B")
    pb2 = tb2.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert pb2.source == "cache" and tb2.probes_run == 0


@pytest.mark.parametrize("damage", ["flip", "truncate"])
def test_corrupt_entry_quarantined_and_reprobed(tmp_path, damage):
    p = small()
    root = tmp_path / "tune"
    t1 = Autotuner(cache_dir=root, **TUNE_KW)
    ref = t1.resolve(8, 3, 1, allow_probe=True, p_times=p)

    (entry,) = [f for f in root.iterdir() if f.suffix == ".tune"]
    blob = bytearray(entry.read_bytes())
    if damage == "flip":
        blob[len(blob) // 2] ^= 0xFF
        entry.write_bytes(bytes(blob))
    else:
        entry.write_bytes(bytes(blob[: len(blob) // 2]))

    t2 = Autotuner(cache_dir=root, **TUNE_KW)
    p2 = t2.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert p2.source == "probe"          # re-probed, never loaded
    snap = t2.cache.snapshot()
    assert snap["errors"] == 1 and snap["quarantined"] == 1
    quarantined = [f for f in root.iterdir()
                   if f.name.endswith(".corrupt")]
    assert len(quarantined) == 1
    # the re-probe re-persisted a clean entry beside the quarantine
    t3 = Autotuner(cache_dir=root, **TUNE_KW)
    p3 = t3.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert p3.source == "cache" and t3.probes_run == 0
    assert (p3.chunk, p3.balance_period) == (ref.chunk,
                                             ref.balance_period) \
        or p3.chunk in TUNE_KW["chunks"]   # a re-probe may pick the
    #   other near-tied candidate; what matters is it came from disk


def test_repeat_quarantines_keep_distinct_forensic_copies(tmp_path):
    """Quarantine names are per-writer unique AND counter-suffixed:
    the same entry corrupted twice (or by N processes racing on shared
    fleet storage) keeps BOTH forensic copies — the second rename must
    not os.replace over the first."""
    from tpu_tree_search.tune.cache import TuningCache

    cache = TuningCache(tmp_path / "tune")
    key = ("pfsp", 8, 3, 1, 4)
    for round_ in range(2):
        cache.store(key, {"chunk": 64, "round": round_})
        path = cache.path_for(key)
        path.write_bytes(b"\xff torn" * 4)
        assert cache.load(key) is None
    quarantined = sorted(f.name for f in (tmp_path / "tune").iterdir()
                         if f.name.endswith(".corrupt"))
    assert len(quarantined) == 2, quarantined     # both copies survive
    assert len(set(quarantined)) == 2
    assert cache.snapshot()["quarantined"] == 2
    # and the cache still works: a clean store replays
    cache.store(key, {"chunk": 128})
    assert cache.load(key)["chunk"] == 128


def test_search_consumes_tuned_entry(tmp_path):
    """distributed.search(chunk=None, tuner=...) compiles the TUNED
    chunk — proven from the executor key, not from a log line."""
    p = small()
    tuner = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    tuned = tuner.resolve(8, 3, 1, n_workers=4, allow_probe=True,
                          p_times=p)
    cache = ExecutorCache()
    got = distributed.search(p, lb_kind=1, mesh=worker_mesh(4),
                             chunk=None, balance_period=None,
                             capacity=1 << 12, min_seed=4,
                             loop_cache=cache, tuner=tuner)
    keys = [e["key"] for e in cache.ledger_snapshot()]
    assert len(keys) == 1
    assert keys[0].startswith(f"pfsp/8/3/1/{tuned.chunk}/")
    # and the tuned run solves to the same optimum as a fixed-knob one
    ref = distributed.search(p, lb_kind=1, mesh=worker_mesh(4),
                             chunk=8, capacity=1 << 12, min_seed=4)
    assert got.best == ref.best


def test_tuning_cache_key_is_stable(monkeypatch):
    from tpu_tree_search.ops import pallas_fused
    monkeypatch.delenv(pallas_fused.FUSED_FLAG, raising=False)
    monkeypatch.delenv(pallas_fused.FUSED_INTERPRET_FLAG,
                       raising=False)
    k1 = Autotuner.key(20, 10, 1, 8)
    assert k1 == ("pfsp", 20, 10, 1, 8)
    c = TuningCache.__new__(TuningCache)   # path_for only needs root
    import pathlib
    c.root = pathlib.Path("/x")
    assert c.path_for(k1) == c.path_for(("pfsp", 20, 10, 1, 8))
    assert c.path_for(k1) != c.path_for(("pfsp", 20, 10, 2, 8))
    # a fused boot keys its own entry (the sweep picks its chunk on
    # the boot pipeline's rates — a matmul boot must never replay a
    # fused-probed optimum, or vice versa); unfused keys keep their
    # exact pre-fused identity
    monkeypatch.setenv(pallas_fused.FUSED_FLAG, "1")
    monkeypatch.setenv(pallas_fused.FUSED_INTERPRET_FLAG, "1")
    assert Autotuner.key(20, 10, 1, 8) \
        == ("pfsp", 20, 10, 1, 8, "fused", "interpret")
    # a problem WITHOUT a fused pipeline (supports_fused False)
    # measures identical rates either way — its key never splits on
    # the env, so one optimum serves both boot modes
    assert Autotuner.key(6, 6, 1, 8, problem="tsp") \
        == ("tsp", 6, 6, 1, 8)


# --------------------------------------------------------------- report


def test_tune_report_renders_entries_and_quarantine(tmp_path):
    import tune_report

    root = tmp_path / "tune"
    t = Autotuner(cache_dir=root, **TUNE_KW)
    t.resolve(8, 3, 1, allow_probe=True, p_times=small())
    (root / "deadbeef.tune.corrupt").write_bytes(b"garbage")
    entries = [tune_report.read_entry(str(f))
               for f in sorted(root.iterdir())
               if f.suffix == ".tune"]
    table = tune_report.render(entries,
                               ["deadbeef.tune.corrupt"])
    assert "pfsp/8/3/1/1" in table
    assert "Quarantined" in table and "deadbeef" in table
    assert tune_report.main([str(root)]) == 0
    assert tune_report.main([str(root), "--json"]) == 0


def test_prewarm_boot_resolves_tuned_spool_shapes(tmp_path):
    """A {"tuned": true} backlog request leaves its knobs open; the
    boot pre-warm must warm the values DISPATCH will resolve to (the
    serving defaults here — no tuned entry, probing off), not crash on
    chunk=None."""
    from tpu_tree_search.service import SearchServer
    from tpu_tree_search.service import spool as spool_mod

    p = small(jobs=7)
    spool_dir = tmp_path / "spool"
    spool_mod.submit_file(spool_dir, {"p_times": p.tolist(), "lb": 1,
                                      "capacity": 4096, "min_seed": 4,
                                      "tuned": True})
    with SearchServer(n_submeshes=2, workdir=tmp_path / "wd",
                      segment_iters=256,
                      tune_cache_dir=tmp_path / "tune",
                      tune_at_boot=False,
                      share_incumbent=False) as srv:
        s = srv.prewarm_boot(spec="spool", spool_dir=spool_dir)
        assert s["shapes"] == 1 and s["errors"] == 0
        assert s["by"]["compile"] == 2          # one per submesh
        # the warmed key is the defaults-tier chunk — exactly what a
        # dispatch-time resolve of the open knobs returns
        keys = [e["key"] for e in srv.cache.ledger_snapshot()]
        assert all(
            k.startswith(f"pfsp/7/3/1/{defaults.SERVING_CHUNK_DEFAULT}/")
            for k in keys)


def test_tuner_snapshot_shape(tmp_path):
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    snap = t.snapshot()
    assert snap["probes_run"] == 0
    assert snap["cache"]["entries"] == 0
    assert snap["chunk_candidates"] == [8, 16]
    t_nocache = Autotuner(**TUNE_KW)
    assert t_nocache.snapshot()["cache"] is None


def test_tuner_metrics_registry(tmp_path):
    from tpu_tree_search.obs import metrics as obs_metrics
    reg = obs_metrics.Registry("tts_test_tuner")
    t = Autotuner(cache_dir=tmp_path / "tune", registry=reg, **TUNE_KW)
    t.resolve(8, 3, 1, allow_probe=True, p_times=small())
    flat = json.dumps(reg.to_json())
    assert "tts_tuner_probes_total" in flat
    assert "tts_tuner_cache_misses_total" in flat
    t2 = Autotuner(cache_dir=tmp_path / "tune", registry=reg, **TUNE_KW)
    t2.resolve(8, 3, 1, allow_probe=True)
    assert "tts_tuner_cache_hits_total" in json.dumps(reg.to_json())


# ------------------------------------- problem-generic probe harness
# (ROADMAP item 2c: TSP/knapsack shapes get MEASURED chunk optima
# instead of silently riding the serving fallback row)


def test_probe_harness_generalizes_to_tsp():
    from tpu_tree_search.problems.tsp import TSPInstance
    inst = TSPInstance.synthetic(9, seed=0)
    h = ProbeHarness(inst.d, lb_kind=1, capacity=1 << 12, warm_chunk=8,
                     warm_iters=10, window_iters=4, repeats=1,
                     problem="tsp")
    r = h.measure(8, 4)
    assert r.evals > 0 and r.evals_per_s > 0 and r.ms_per_iter > 0


def test_probe_harness_generalizes_to_knapsack():
    from tpu_tree_search.problems.knapsack import KnapsackInstance
    inst = KnapsackInstance.synthetic(18, seed=0)
    h = ProbeHarness(inst.table, lb_kind=1, capacity=1 << 12,
                     warm_chunk=8, warm_iters=10, window_iters=4,
                     repeats=1, problem="knapsack")
    r = h.measure(8, 4)
    assert r.evals > 0 and r.evals_per_s > 0


def test_tune_non_pfsp_without_table_falls_to_defaults(tmp_path):
    # the synthetic-table fallback is a PFSP generator: a non-PFSP
    # probe WITHOUT an instance table must degrade to the defaults
    # tier (ProbeError caught), never probe a wrong-problem table
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t.resolve(9, 9, 1, allow_probe=True, problem="tsp")
    assert params.source == "default"
    assert t.probes_run == 0


def test_resolve_probes_tsp_with_table_and_persists(tmp_path):
    from tpu_tree_search import problems
    from tpu_tree_search.problems.tsp import TSPInstance
    inst = TSPInstance.synthetic(9, seed=0)
    prob = problems.get("tsp")
    jobs, mach = prob.slots(inst.d), prob.aux_rows(inst.d)
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t.resolve(jobs, mach, 1, allow_probe=True,
                       p_times=inst.d, problem="tsp")
    assert params.source == "probe"
    assert params.chunk in TUNE_KW["chunks"]
    assert t.probes_run > 0
    # a restarted tuner over the same cache dir replays with ZERO
    # probes — the PFSP contract, now problem-generic
    t2 = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    p2 = t2.resolve(jobs, mach, 1, allow_probe=True,
                    p_times=inst.d, problem="tsp")
    assert p2.source == "cache" and p2.chunk == params.chunk
    assert t2.probes_run == 0


# ------------------------------------------- per-rung profitability


def test_tune_emits_rung_profile_and_cache_roundtrip(tmp_path,
                                                     monkeypatch):
    # the winner's ladder rungs are probed too (below the static rung
    # floor — measured admission subsumes it) and the mask persists
    # with the entry; with TTS_FUSED off every rung's winner is the
    # matmul pipeline and the fused rate column stays unmeasured.
    # TTS_TUNE_RUNGS opts the matmul-only boot in (without it — or
    # the fused route — rung probes are skipped: extra compiles with
    # no pipeline choice to record)
    monkeypatch.delenv("TTS_FUSED", raising=False)
    monkeypatch.delenv("TTS_FUSED_INTERPRET", raising=False)
    monkeypatch.setenv("TTS_TUNE_RUNGS", "1")
    p = small()
    t1 = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t1.resolve(8, 3, 1, allow_probe=True, p_times=p)
    assert params.source == "probe"
    assert params.rung_modes
    chunks = [r["chunk"] for r in params.rung_modes]
    assert params.chunk in chunks
    for row in params.rung_modes:
        assert row["winner"] == "unfused"
        assert row["ms_per_iter"] > 0
        assert row["evals_per_s_fused"] is None
        assert row["evals_per_s_unfused"] > 0
    t2 = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    p2 = t2.resolve(8, 3, 1, allow_probe=False)
    assert p2.source == "cache"
    assert tuple(p2.rung_modes) == tuple(params.rung_modes)


@pytest.mark.slow  # both-pipeline interpret probes; runs in the CI fused leg
def test_tune_rung_profile_measures_fused_pipeline(tmp_path,
                                                   monkeypatch):
    # with the fused route resolvable (interpret on the CPU mesh —
    # the CI fused leg's environment), every rung is probed once per
    # PIPELINE on identical warmed state and the mask records both
    # rates; the winner is whichever measured faster, and the solve
    # counts cannot differ between them (bit-parity), so either
    # verdict is valid — what must hold is that the fused column was
    # actually MEASURED
    monkeypatch.setenv("TTS_FUSED", "1")
    monkeypatch.setenv("TTS_FUSED_INTERPRET", "1")
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t.resolve(8, 3, 1, allow_probe=True, p_times=small())
    assert params.rung_modes
    for row in params.rung_modes:
        assert row["winner"] in ("fused", "unfused")
        assert row["evals_per_s_unfused"] > 0
        assert row["evals_per_s_fused"] is not None
        assert row["evals_per_s_fused"] > 0


def test_rung_probes_skipped_without_pipeline_choice(tmp_path,
                                                     monkeypatch):
    # default boot (fused off, no TTS_TUNE_RUNGS): no rung probes run
    # — each is an extra compile with no kernel-vs-matmul choice to
    # record — and the entry persists without a mask (ladder admission
    # falls back to the static floors, the pre-mask behavior)
    monkeypatch.delenv("TTS_FUSED", raising=False)
    monkeypatch.delenv("TTS_TUNE_RUNGS", raising=False)
    t = Autotuner(cache_dir=tmp_path / "tune", **TUNE_KW)
    params = t.resolve(8, 3, 1, allow_probe=True, p_times=small())
    assert params.source == "probe"
    assert params.rung_modes is None
    probed = {(r["chunk"], r.get("fused")) for r in t.ledger}
    assert all(c in TUNE_KW["chunks"] for c, _ in probed)
