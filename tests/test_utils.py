"""Stats toolkit and CSV schema tests."""

import numpy as np

from tpu_tree_search.utils import csv_stats, stats


def test_boxplot_stats_basics():
    b = stats.compute_boxplot_stats([1, 2, 3, 4, 5, 6, 7, 8, 9])
    assert b.minimum == 1 and b.maximum == 9 and b.median == 5
    assert b.q1 == 2.5 and b.q3 == 7.5          # Tukey hinges, odd n
    assert np.isclose(b.iqr, 5.0)
    assert np.isclose(b.mean, 5.0)


def test_percentile_interpolation():
    v = np.array([10.0, 20.0, 30.0, 40.0])
    assert stats.percentile_sorted(v, 0.5) == 25.0
    assert stats.percentile_sorted(v, 0.0) == 10.0
    assert stats.percentile_sorted(v, 1.0) == 40.0


def test_csv_single_schema(tmp_path):
    import pandas as pd
    path = str(tmp_path / "singlegpu.csv")
    csv_stats.write_single(path, 14, 1, 1377, 25, 50000, 1.5, 1.2, 100, 10)
    csv_stats.write_single(path, 21, 2, 2297, 25, 50000, 2.5, 2.2, 200, 20)
    df = pd.read_csv(path)
    assert list(df.columns) == csv_stats.SINGLE_HEADER.split(",")
    assert len(df) == 2
    assert df.loc[1, "optimum"] == 2297


def test_csv_dist_schema_roundtrip(tmp_path):
    import pandas as pd
    path = str(tmp_path / "dist_multigpu.csv")
    per_device = {"tree": [5, 6], "sol": [1, 2], "evals": [50, 60],
                  "steals": [1, 0], "recv": [10, 0]}
    csv_stats.write_dist(path, 21, 1, 2, 0, 1, 1, 2297, 25, 50000, 5000,
                         3.5, 11, 3, per_device)
    df = pd.read_csv(path)
    assert list(df.columns) == csv_stats.DIST_HEADER.split(",")
    # array cells parse back the way the reference's data scripts do
    assert df.loc[0, "all_exp_tree_gpu"] == "[5,6]"


def test_cli_pfsp_runs(tmp_path, capsys):
    """End-to-end CLI on the smallest real workload shape we can afford in
    CI: truncated ta014 run."""
    from tpu_tree_search.cli import main
    csv = str(tmp_path / "out.csv")
    rc = main(["pfsp", "-i", "14", "-l", "1", "-u", "1", "-D", "1",
               "--chunk", "16", "--capacity", "65536",
               "--max-iters", "5", "--csv", csv])
    assert rc == 0
    captured = capsys.readouterr().out
    assert "ta14" in captured and "Elapsed time" in captured
    assert (tmp_path / "out.csv").exists()
