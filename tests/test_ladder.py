"""Adaptive dispatch, ladder half: chunk-ladder execution in the
segmented distributed driver (TTS_LADDER / search(ladder=...)).

The contracts, pinned on the 8-device virtual CPU mesh:

- ladder OFF (the default) is the pre-ladder single-driver path —
  nothing ladder-related runs (no events, no extra compiles);
- ladder ON at a fixed incumbent (ub = opt) explores the BIT-IDENTICAL
  node set (the explored tree is order-independent when the incumbent
  cannot move) with rung switches in both directions and every audit
  invariant green under TTS_AUDIT_HARD;
- the live rung rides checkpoint meta (``ladder_rung``) and resume
  replays on the recorded rung, with totals exactly matching an
  uninterrupted run;
- rung pre-readies are PLANNED compiles: compile_storm's signal stays
  at zero across a full ladder boot (every rung warms from abstract
  shapes — which also pins the explicit shardings cross-rung state
  handoffs need on the strict AOT path);
- a ramp/drain-heavy workload (small instance vs a big tuned chunk —
  the fixed chunk pops underfilled the whole solve) improves
  END-TO-END wall time >= 15% under the ladder (measured 1.4-2.0x
  here; the margin absorbs CI noise).
"""

import time

import numpy as np

from tpu_tree_search.engine import distributed
from tpu_tree_search.engine.ladder import (LADDER_MIN_CHUNK,
                                           LADDER_MIN_CHUNK_LB2,
                                           RungController, min_rung_for,
                                           rungs_for)
from tpu_tree_search.obs import tracelog
from tpu_tree_search.parallel.mesh import worker_mesh
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service.executors import ExecutorCache

# seed 1, 10x5: proof tree 22081 at its optimum 697 — big enough that
# the pool crosses rung thresholds in both directions (switch coverage)
P_BIG = PFSPInstance.synthetic(jobs=10, machines=5, seed=1).p_times
OPT_BIG = 697
# seed 7, 10x5: proof tree 2827 — the pool never fills a 2048 chunk,
# i.e. the ENTIRE solve is ramp/drain at the big fixed chunk (the
# workload family the ladder exists for)
P_SMALL = PFSPInstance.synthetic(jobs=10, machines=5, seed=7).p_times
OPT_SMALL = 797

KW = dict(capacity=1 << 16, min_seed=8, segment_iters=8)


def totals(res):
    return (res.explored_tree, res.explored_sol, res.best)


def ladder_events(since=0):
    return [r for r in tracelog.get().records()
            if r.get("name", "").startswith("ladder")][since:]


def n_records():
    return len([r for r in tracelog.get().records()
                if r.get("name", "").startswith("ladder")])


# ------------------------------------------------------------- geometry


def test_rung_geometry():
    assert rungs_for(65536) == (4096, 16384, 65536)
    assert rungs_for(2048) == (128, 512, 2048)
    assert rungs_for(1024) == (64, 256, 1024)
    # the floor collapses sub-lane rungs (and tiny chunks ladder not
    # at all — the plain driver serves them)
    assert rungs_for(64) == (64,)
    assert rungs_for(256) == (64, 256)
    assert rungs_for(2048, min_chunk=256) == (256, 512, 2048)
    # LB2's floor is the measured 256 (the pair sweep below the lane
    # width costs 220 ms/iter on the CPU mesh vs 15 at 256)
    assert min_rung_for(2) == LADDER_MIN_CHUNK_LB2
    assert min_rung_for(1) == min_rung_for(0) == LADDER_MIN_CHUNK


def test_controller_covering_policy_and_momentum():
    drivers = {64: "d64", 256: "d256", 1024: "d1024"}
    c = RungController(drivers, n_workers=8)
    c.start(8 * 200)                 # 200/worker -> smallest covering
    assert c.current_chunk == 256
    c.observe(8 * 250)               # no doubling, 256 still covers
    assert c.current_chunk == 256
    c.observe(8 * 600)               # covering 1024 (growth clamps at
    assert c.current_chunk == 1024   # the top anyway)
    c.observe(8 * 100)               # drain: covering exactly
    assert c.current_chunk == 256
    c.observe(8 * 5)                 # drain tail
    assert c.current_chunk == 64
    assert c.switches == {"up": 1, "down": 2}
    # ramp momentum: a pool that DOUBLED inside the segment is already
    # stale at the boundary — go one rung above covering
    c2 = RungController(drivers, n_workers=8)
    c2.start(8 * 20)
    assert c2.current_chunk == 64
    c2.observe(8 * 60)               # covering is still 64, but the
    assert c2.current_chunk == 256   # 3x growth bumps one rung up


# ----------------------------------------------------------- off parity


def test_ladder_off_runs_nothing(monkeypatch):
    monkeypatch.delenv("TTS_LADDER", raising=False)
    before = n_records()
    cache = ExecutorCache()
    res = distributed.search(P_SMALL, lb_kind=1, init_ub=OPT_SMALL,
                             mesh=worker_mesh(8), chunk=2048,
                             loop_cache=cache, **KW)
    assert res.complete
    assert n_records() == before            # no ladder events at all
    assert len(cache.ledger_snapshot()) == 1   # ONE loop, no rungs


def test_single_rung_chunk_degrades_to_plain_driver():
    before = n_records()
    a = distributed.search(P_SMALL, lb_kind=1, init_ub=OPT_SMALL,
                           mesh=worker_mesh(8), chunk=64, ladder=True,
                           **KW)
    b = distributed.search(P_SMALL, lb_kind=1, init_ub=OPT_SMALL,
                           mesh=worker_mesh(8), chunk=64, ladder=False,
                           **KW)
    assert totals(a) == totals(b)
    assert n_records() == before    # rungs_for(64) is one rung: the
    #                                 controller never constructs


def test_ladder_needs_segmented_execution():
    before = n_records()
    res = distributed.search(P_SMALL, lb_kind=1, init_ub=OPT_SMALL,
                             mesh=worker_mesh(8), chunk=2048,
                             capacity=1 << 16, min_seed=8, ladder=True)
    assert res.complete
    assert n_records() == before    # no segments -> no boundaries ->
    #                                 the plain driver ran


# ---------------------------------------------------- on: bit identical


def test_ladder_bit_identical_with_switches_audit_hard(monkeypatch):
    monkeypatch.setenv("TTS_AUDIT", "1")
    monkeypatch.setenv("TTS_AUDIT_HARD", "1")
    off = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                             mesh=worker_mesh(8), chunk=2048,
                             ladder=False, **KW)
    before = n_records()
    on = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                            mesh=worker_mesh(8), chunk=2048,
                            ladder=True, **KW)
    assert totals(off) == totals(on)
    assert off.complete and on.complete
    evs = ladder_events(before)
    assert evs[0]["name"] == "ladder.start"
    assert evs[0]["source"] == "occupancy"
    dirs = {e["direction"] for e in evs if e["name"] == "ladder.switch"}
    assert "up" in dirs and "down" in dirs     # both ways exercised


def test_ladder_lb2_bit_identical(monkeypatch):
    monkeypatch.setenv("TTS_AUDIT_HARD", "1")
    p = PFSPInstance.synthetic(jobs=11, machines=20, seed=1).p_times
    off = distributed.search(p, lb_kind=2, init_ub=1810,
                             mesh=worker_mesh(8), chunk=1024,
                             ladder=False, capacity=1 << 15,
                             min_seed=8, segment_iters=8)
    on = distributed.search(p, lb_kind=2, init_ub=1810,
                            mesh=worker_mesh(8), chunk=1024,
                            ladder=True, capacity=1 << 15,
                            min_seed=8, segment_iters=8)
    assert totals(off) == totals(on)


# ------------------------------------------------------- compile booking


def test_rung_warms_are_planned_compiles():
    cache = ExecutorCache()
    distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                       mesh=worker_mesh(8), chunk=2048, ladder=True,
                       loop_cache=cache, **KW)
    rungs = rungs_for(2048)
    ledger = cache.ledger_snapshot()
    assert len(ledger) == len(rungs)
    # EVERY rung — the current one included — is pre-readied from
    # abstract shapes via="ladder": planned compiles, zero storm
    # signal (a ladder boot must not read as executable-reuse
    # breaking), and every rung executable shares the explicit
    # worker-axis shardings so cross-rung state handoffs never hit
    # the strict-AOT sharding check
    assert cache.storm_signal() == 0
    assert [e.get("via") for e in ledger] == ["ladder"] * len(rungs)
    assert all(e.get("method") == "aot" for e in ledger)


def test_prewarm_readies_every_rung():
    from tpu_tree_search.utils import config as cfg

    p = PFSPInstance.synthetic(jobs=8, machines=3, seed=3).p_times
    cache = ExecutorCache()
    overlap = cfg.env_flag(cfg.OVERLAP_FLAG)
    how = distributed.prewarm(p, chunk=256, capacity=4096,
                              mesh=worker_mesh(4), loop_cache=cache,
                              ladder=True, donate=overlap)
    assert how == "compile"
    n_rungs = len(rungs_for(256))
    assert len(cache.ledger_snapshot()) == n_rungs
    assert cache.storm_signal() == 0      # every warm is planned
    # idempotent, and key-identical to what a ladder search builds: a
    # ladder search of the same shape/knobs compiles NOTHING new
    distributed.search(p, lb_kind=1, mesh=worker_mesh(4), chunk=256,
                       capacity=4096, min_seed=4, segment_iters=8,
                       ladder=True, loop_cache=cache)
    assert cache.storm_signal() == 0
    assert len(cache.ledger_snapshot()) == n_rungs


# ------------------------------------------------------ checkpoint/resume


def test_resume_replays_recorded_rung_exactly(tmp_path, monkeypatch):
    monkeypatch.setenv("TTS_AUDIT_HARD", "1")
    ckpt = str(tmp_path / "ladder.ckpt.npz")
    mesh = worker_mesh(8)
    # uninterrupted ladder reference
    ref = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                             mesh=mesh, chunk=2048, ladder=True, **KW)
    # truncated run: stops after ~2 segments mid-ladder, final state
    # checkpointed with the live rung in its meta
    part = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                              mesh=mesh, chunk=2048, ladder=True,
                              checkpoint_path=ckpt, max_rounds=1, **KW)
    assert not part.complete
    with np.load(ckpt) as z:
        rung = int(z["meta_ladder_rung"])
    assert rung in rungs_for(2048)
    # resume: starts on the RECORDED rung (ladder.start source=meta)
    # and finishes with totals exactly equal to the uninterrupted run
    before = n_records()
    done = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                              mesh=mesh, chunk=2048, ladder=True,
                              checkpoint_path=ckpt, **KW)
    assert done.complete
    assert totals(done) == totals(ref)
    start = [e for e in ladder_events(before)
             if e["name"] == "ladder.start"][0]
    assert start["source"] == "meta" and start["rung"] == rung


def test_cross_mode_resume_ladder_to_plain(tmp_path):
    """A ladder checkpoint resumes on a ladder-OFF run (the meta key
    is just ignored) and vice versa — the flag is a driver choice, not
    a state format."""
    ckpt = str(tmp_path / "cross.ckpt.npz")
    mesh = worker_mesh(8)
    ref = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                             mesh=mesh, chunk=2048, ladder=False, **KW)
    part = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                              mesh=mesh, chunk=2048, ladder=True,
                              checkpoint_path=ckpt, max_rounds=1, **KW)
    assert not part.complete
    done = distributed.search(P_BIG, lb_kind=1, init_ub=OPT_BIG,
                              mesh=mesh, chunk=2048, ladder=False,
                              checkpoint_path=ckpt, **KW)
    assert done.complete and totals(done) == totals(ref)


# ------------------------------------------------------------- the win


def test_ramp_drain_heavy_wall_time_improves_15pct():
    """The acceptance bar: on the 8-device CPU mesh, a ramp/drain-heavy
    workload (a small instance against the big tuned chunk — the pool
    never covers the chunk, so EVERY fixed-chunk step pays 2048-wide
    kernels for a few hundred parents) solves >= 15% faster end to end
    under the ladder. Measured 1.4-2.0x here; best-of-3 with warmed
    executables on both sides keeps compile noise out."""
    mesh = worker_mesh(8)

    def best_of(ladder, n=3):
        cache = ExecutorCache()

        def solve():
            t0 = time.perf_counter()
            r = distributed.search(P_SMALL, lb_kind=1,
                                   init_ub=OPT_SMALL, mesh=mesh,
                                   chunk=2048, ladder=ladder,
                                   loop_cache=cache, **KW)
            return time.perf_counter() - t0, r

        solve()                       # compile pass
        best, res = float("inf"), None
        for _ in range(n):
            dt, res = solve()
            best = min(best, dt)
        return best, res

    t_off, r_off = best_of(False)
    t_on, r_on = best_of(True)
    assert totals(r_off) == totals(r_on)      # same nodes, same answer
    speedup = t_off / t_on
    assert speedup >= 1.15, (
        f"ladder speedup only {speedup:.2f}x on the ramp/drain-heavy "
        f"workload (off={t_off:.3f}s on={t_on:.3f}s) — the >=15% "
        "acceptance bar")
