"""N-Queens: oracle vs known solution counts (OEIS A000170), the generic
plugin engine vs oracle (exact tree/sol counts — the search is unpruned,
so counts are exploration-order independent). The device engines run
through the problem-plugin pipeline (problems/nqueens.NQueensProblem +
engine/device.generic_step) that replaced the deleted
engine/nqueens_device fork; matching the oracle exactly IS the
bit-identical-counts parity pin (the fork matched the same oracle)."""

import pytest

from tpu_tree_search.engine import sequential as seq
from tpu_tree_search.problems import nqueens as nq


@pytest.mark.parametrize("n", [4, 5, 6, 7, 8])
def test_oracle_solution_counts(n):
    r = seq.nqueens_search(n)
    assert r.explored_sol == nq.SOLUTION_COUNTS[n]


@pytest.mark.parametrize("n", [6, 8])
def test_device_matches_oracle(n):
    want = seq.nqueens_search(n)
    got = nq.search(n, chunk=16, capacity=1 << 14)
    assert (got.explored_tree, got.explored_sol) == \
           (want.explored_tree, want.explored_sol)


def test_device_g_invariance():
    a = nq.search(7, g=1, chunk=8)
    b = nq.search(7, g=3, chunk=8)
    assert (a.explored_tree, a.explored_sol) == (b.explored_tree, b.explored_sol)


@pytest.mark.parametrize("n_devices", [2, 8])
def test_distributed_matches_oracle(n_devices):
    want = seq.nqueens_search(8)
    got = nq.search_distributed(8, n_devices=n_devices,
                                chunk=8, capacity=1 << 14,
                                min_seed=8)
    assert (got.explored_tree, got.explored_sol) == \
           (want.explored_tree, want.explored_sol)
