"""Fleet capacity & utilization observability (obs/capacity.py,
``TTS_CAPACITY``): the lane-state ledger, the shape-class capacity
model, and the saturation forecast.

The load-bearing assertions:

- **conservation exactness**: with injected clock stamps, per-lane
  state seconds sum EXACTLY (==, not ~=) to lane lifetime through
  transition/flush/open-interval paths; live servers stay within float
  addition error through preempt->resume, quarantine->readmit, and
  mid-batch member stop;
- **replay**: a second server lifetime on the same durable store seeds
  the ledger from the resumed ``tts_lane_seconds_total`` counters, and
  conservation stays statable (lifetime includes replayed seconds);
- **capacity math**: λ from the admission window, E[S] from tuner
  seed / observed-throughput EWMA / direct measured fallback, ρ,
  headroom, Little's-law W_q, and the partition-invariant what-if
  table — all pinned against hand-computed values;
- **saturation forecast**: the health rule fires from the snapshot's
  overall ρ, and is absent when ``TTS_CAPACITY=0``;
- **off-path bit-identity**: ``TTS_CAPACITY=0`` serves the exact
  standalone totals with no capacity object, snapshot key, metric
  series, or rule — the whole subsystem unplugs.
"""

import json
import pathlib
import sys
import time
import urllib.request

import pytest

from tpu_tree_search.engine import distributed
from tpu_tree_search.obs import capacity, health, metrics, tracelog
from tpu_tree_search.obs.capacity import CapacityModel, LaneLedger
from tpu_tree_search.obs.httpd import start_http_server
from tpu_tree_search.problems.pfsp import PFSPInstance
from tpu_tree_search.service import SearchRequest, SearchServer

sys.path.insert(0, str(pathlib.Path(__file__).parent.parent / "tools"))

KW = dict(chunk=8, capacity=1 << 12, min_seed=4)


def small(seed, jobs=7):
    return PFSPInstance.synthetic(jobs=jobs, machines=3, seed=seed)


@pytest.fixture
def fresh_obs(tmp_path):
    log = tracelog.TraceLog(capacity=1 << 16,
                            sink_path=tmp_path / "trace.jsonl")
    prev_log = tracelog.install(log)
    reg = metrics.Registry()
    prev_reg = metrics.install(reg)
    try:
        yield log, reg
    finally:
        tracelog.install(prev_log)
        metrics.install(prev_reg)


def wait_state(srv, rid, state, timeout=120.0):
    from tpu_tree_search.service import TERMINAL_STATES

    t0 = time.monotonic()
    while True:
        now = srv.status(rid)["state"]
        if now == state:
            return
        assert now not in TERMINAL_STATES, (
            f"{rid} reached terminal {now} waiting for {state}")
        assert time.monotonic() - t0 < timeout, (
            f"{rid} never reached {state}: {srv.status(rid)}")
        time.sleep(0.02)


# ------------------------------------------------- lane ledger (units)


def test_lane_ledger_conservation_is_exact(fresh_obs):
    """With injected stamps the invariant holds with ==: every second
    of [born, now] lands in exactly one state's accumulator."""
    log, reg = fresh_obs
    led = LaneLedger(reg, lanes=[0, 1], now=100.0)
    led.transition(0, "compiling", now=101.0)     # closes idle 1.0s
    led.transition(0, "executing", now=103.0)     # compiling 2.0s
    led.transition(0, "idle", now=106.5)          # executing 3.5s
    led.flush(now=108.0)                          # idle +1.5s, no change
    snap = {r["lane"]: r for r in led.snapshot(now=110.0)}
    r0 = snap[0]
    assert r0["seconds"] == {"compiling": 2.0, "executing": 3.5,
                             "idle": 1.0 + 1.5 + 2.0}
    assert r0["lifetime_s"] == 10.0
    assert r0["conservation_error_s"] == 0.0      # exact, not approx
    assert r0["utilization"] == 3.5 / 10.0
    assert r0["state"] == "idle"
    # the untouched lane conserves too: flush closed 8.0s, open adds 2.0
    r1 = snap[1]
    assert r1["seconds"] == {"idle": 10.0}
    assert r1["conservation_error_s"] == 0.0
    assert led.conservation_errors(now=110.0) == {0: 0.0, 1: 0.0}
    # the counter carries CLOSED intervals (flush() keeps it current)
    c = reg.counter(capacity.LANE_SECONDS_METRIC)
    assert c.value(lane=0, state="compiling") == 2.0
    assert c.value(lane=0, state="executing") == 3.5
    assert c.value(lane=0, state="idle") == 2.5
    assert c.value(lane=1, state="idle") == 8.0
    # each transition emitted a lane.state event carrying the FULL
    # duration of the state being left (the retrospective slice)
    evs = [r for r in log.records() if r["name"] == "lane.state"]
    assert [(e["prev"], e["seconds"]) for e in evs] == [
        ("idle", 1.0), ("compiling", 2.0), ("executing", 3.5)]


def test_lane_ledger_same_state_transition_is_noop(fresh_obs):
    log, reg = fresh_obs
    led = LaneLedger(reg, lanes=[0], now=0.0)
    led.transition(0, "executing", now=1.0)
    led.transition(0, "executing", now=5.0)       # no-op: no event
    evs = [r for r in log.records() if r["name"] == "lane.state"]
    assert len(evs) == 1
    (r,) = led.snapshot(now=6.0)
    assert r["seconds"] == {"idle": 1.0, "executing": 5.0}
    assert r["conservation_error_s"] == 0.0


def test_lane_ledger_seed_replays_without_counter_inc(fresh_obs):
    """seed() adopts prior-lifetime seconds: accumulator and replayed
    move, the counter does NOT (resume_counters already restored it),
    and conservation stays exact with lifetime including the replay."""
    log, reg = fresh_obs
    led = LaneLedger(reg, lanes=[0], now=50.0)
    led.seed(0, "executing", 5.0)
    led.seed(0, "idle", 2.5)
    (r,) = led.snapshot(now=51.0)
    assert r["replayed_s"] == 7.5
    assert r["lifetime_s"] == 1.0 + 7.5
    assert r["seconds"] == {"executing": 5.0, "idle": 2.5 + 1.0}
    assert r["conservation_error_s"] == 0.0
    c = reg.counter(capacity.LANE_SECONDS_METRIC)
    assert c.value_matching(lane=0) == 0.0        # seed never incs


# --------------------------------------------- capacity model (units)


def test_capacity_model_math_pinned(fresh_obs):
    """λ / E[S] / ρ / headroom / W_q / what-if against hand-computed
    values with injected stamps."""
    _, reg = fresh_obs
    m = CapacityModel(reg, window_s=10.0, ewma=0.5, now=0.0)
    for t in (1.0, 2.0, 3.0, 4.0, 5.0):
        m.on_admit("7x3", "acme", now=t)
    m.seed_rate("7x3", 1000.0)
    m.on_terminal("7x3", 500, service_s=0.5)      # E[S] = 500/1000
    doc = m.snapshot(healthy_lanes=2, total_lanes=2, total_devices=8,
                     now=6.0)
    lam = 5 / 6.0                                 # window = now - born
    s = 0.5
    rho = lam * s / 2
    assert doc["window_s"] == 6.0
    assert doc["arrival_per_s"] == pytest.approx(lam)
    (row,) = doc["classes"]
    assert (row["shape"], row["tenant"]) == ("7x3", "acme")
    assert row["service_s"] == pytest.approx(s)
    assert row["utilization"] == pytest.approx(rho)
    assert row["headroom"] == pytest.approx(1 - rho)
    assert row["predicted_wait_s"] == pytest.approx(
        s * rho / (2 * (1 - rho)))
    assert doc["utilization"] == pytest.approx(rho)
    assert doc["predicted_req_per_s"] == pytest.approx(2 / s)
    # what-if: every n | devices partition, throughput invariant under
    # linear per-device scaling, current partition flagged
    wi = doc["what_if"]
    assert [w["lanes"] for w in wi] == [1, 2, 4, 8]
    assert all(w["predicted_req_per_s"] == pytest.approx(2 / s)
               for w in wi)
    assert [w["current"] for w in wi] == [False, True, False, False]
    # fatter lanes wait less at equal throughput (the tradeoff the
    # advisor quantifies)
    waits = [w["predicted_wait_s"] for w in wi]
    assert waits == sorted(waits)
    # gauges published from the snapshot; close() retires them
    text = reg.to_prometheus()
    assert 'tts_capacity_utilization{shape="7x3",tenant="acme"}' in text
    assert "tts_capacity_headroom" in text
    m.close()
    text = reg.to_prometheus()
    assert "tts_capacity_utilization{" not in text   # series retired


def test_capacity_model_saturated_wait_is_none(fresh_obs):
    _, reg = fresh_obs
    m = CapacityModel(reg, window_s=10.0, ewma=0.5, now=0.0)
    for i in range(100):
        m.on_admit("7x3", "-", now=1.0 + i * 0.01)
    m.on_terminal("7x3", 0, service_s=1.0)        # E[S] via fallback
    doc = m.snapshot(healthy_lanes=1, total_lanes=1, total_devices=8,
                     now=2.0)
    assert doc["utilization"] > 1.0
    assert doc["predicted_wait_s"] is None        # unbounded queue
    assert doc["classes"][0]["predicted_wait_s"] is None


def test_capacity_model_rate_sources_and_fallback(fresh_obs):
    """E[S] source precedence: observed-throughput EWMA beats the
    tuner seed; the direct measured-E[S] EWMA is the fallback when
    neither rate nor evals/request exists."""
    _, reg = fresh_obs
    m = CapacityModel(reg, window_s=60.0, ewma=0.5, now=0.0)
    # observed EWMA over the seed
    m.seed_rate("a", 1000.0)
    m.on_progress("a", 800.0)
    m.on_progress("a", 400.0)                     # EWMA -> 600
    m.on_terminal("a", 600)
    st = m._shapes["a"]
    assert st.rate_obs == pytest.approx(600.0)
    assert m._service_s(st) == pytest.approx(1.0)  # 600 / 600
    # fallback: no seed, no heartbeat (request finished inside its
    # first segment) -> direct measured E[S]
    m.on_terminal("b", 0, service_s=2.0)
    m.on_terminal("b", 0, service_s=4.0)          # EWMA -> 3.0
    assert m._service_s(m._shapes["b"]) == pytest.approx(3.0)
    # tenant wait EWMA rides the snapshot
    m.on_queue_wait("acme", 1.0)
    m.on_queue_wait("acme", 3.0)
    doc = m.snapshot(healthy_lanes=1, total_lanes=1, total_devices=8,
                     now=1.0)
    assert doc["tenants"]["acme"]["waits"] == 2
    assert doc["tenants"]["acme"]["observed_wait_s"] == \
        pytest.approx(2.0)


def test_histogram_snapshot_matching_merges_tenant_series(fresh_obs):
    """Satellite: the tenant label on tts_queue_wait_seconds must not
    blind the all-tenants view the queue_wait health rule judges."""
    _, reg = fresh_obs
    h = reg.histogram("tts_queue_wait_seconds", "t")
    h.observe(0.1, tenant="acme")
    h.observe(0.3, tenant="acme")
    h.observe(0.5, tenant="-")
    assert h.snapshot(tenant="acme")["count"] == 2
    merged = h.snapshot_matching()
    assert merged["count"] == 3
    assert merged["sum"] == pytest.approx(0.9)
    assert h.snapshot_matching(tenant="acme")["sum"] == \
        pytest.approx(0.4)


# --------------------------------------------------- saturation rule


def _cap_stub(rho):
    class _Srv:
        def status_snapshot(self):
            return {"capacity": {
                "utilization": rho, "arrival_per_s": 4.0,
                "healthy_lanes": 2, "predicted_wait_s": 1.5,
                "classes": [{"shape": "7x3", "tenant": "acme",
                             "utilization": rho}],
            }}
    return _Srv()


def test_saturation_rule_fires_on_sustained_rho(fresh_obs):
    """The forecast: ρ over threshold fires (after its dwell) from the
    capacity snapshot alone — no queue_wait observation needed."""
    _, reg = fresh_obs
    th = health.Thresholds(saturation=0.85, saturation_for_s=0.0)
    rules = [r for r in health.default_rules(th)
             if r.name == "saturation"]
    assert len(rules) == 1, "saturation rule missing from defaults"
    mon = health.HealthMonitor(server=_cap_stub(0.95), rules=rules,
                               registry=reg, interval_s=0)
    snap = mon.evaluate_now()
    (a,) = snap["alerts"]
    assert a["state"] == "firing"
    assert a["detail"]["utilization"] == 0.95
    assert a["detail"]["worst_class"] == "7x3/acme"
    # below threshold: quiet; unmeasured (rho None): quiet
    for rho in (0.5, None):
        mon2 = health.HealthMonitor(server=_cap_stub(rho), rules=rules,
                                    registry=metrics.Registry(),
                                    interval_s=0)
        assert mon2.evaluate_now()["firing"] == 0


def test_saturation_rule_absent_when_capacity_off(monkeypatch):
    monkeypatch.setenv("TTS_CAPACITY", "0")
    rules = health.default_rules(health.Thresholds())
    assert all(r.name != "saturation" for r in rules)
    monkeypatch.setenv("TTS_CAPACITY", "1")
    rules = health.default_rules(health.Thresholds())
    assert any(r.name == "saturation" for r in rules)


# -------------------------------------------- trace & report tooling


def test_chrome_trace_renders_lane_state_slices(fresh_obs):
    from tpu_tree_search.obs import chrome_trace

    log, reg = fresh_obs
    led = LaneLedger(reg, lanes=[0], now=10.0)
    led.transition(0, "executing", now=12.0)
    led.transition(0, "idle", now=15.5)
    doc = chrome_trace.to_chrome(log.records())
    lanes = {e["name"]: e for e in doc["traceEvents"]
             if e.get("ph") == "X" and e["name"] in capacity.LANE_STATES}
    # each transition became a retrospective slice named for the state
    # LEFT, carrying its full duration
    assert lanes["idle"]["dur"] == pytest.approx(2.0e6)
    assert lanes["executing"]["dur"] == pytest.approx(3.5e6)
    # ...on a dedicated per-lane state track
    tracks = [e["args"]["name"] for e in doc["traceEvents"]
              if e.get("name") == "thread_name"]
    assert "lane-0-state" in tracks


def test_capacity_report_reads_trace_and_store(fresh_obs, tmp_path):
    """Satellite: the offline report accepts both artifact formats —
    the JSONL event log and the durable store directory."""
    import capacity_report

    log, reg = fresh_obs
    led = LaneLedger(reg, lanes=[0], now=0.0)
    led.transition(0, "executing", now=2.0)
    led.transition(0, "idle", now=5.0)
    log.set_sink(None)                            # flush the sink file
    ev_lanes = capacity_report.lane_seconds_from_events(
        capacity_report.load(str(tmp_path / "trace.jsonl"))[0])
    assert ev_lanes[0]["seconds"] == {"idle": 2.0, "executing": 3.0}
    assert ev_lanes[0]["transitions"] == 2
    assert ev_lanes[0]["last_state"] == "idle"

    from tpu_tree_search.obs.store import ObsStore
    store_dir = tmp_path / "store"
    s = ObsStore(store_dir, "w1", fsync=False)
    s.append("event", name="lane.state", submesh=0, state="idle",
             prev="executing", seconds=3.0)
    s.append("sample", counters=[
        ["tts_lane_seconds_total", {"lane": "0", "state": "executing"},
         3.0],
        ["tts_lane_seconds_total", {"lane": "0", "state": "idle"}, 2.0],
    ], gauges=[["tts_capacity_utilization",
                {"shape": "7x3", "tenant": "-"}, 0.4]])
    s.flush()
    s.close()
    events, samples = capacity_report.load(str(store_dir))
    assert capacity_report.lane_seconds_from_events(events)[0][
        "seconds"] == {"executing": 3.0}
    assert capacity_report.lane_seconds_from_samples(samples) == {
        "0": {"executing": 3.0, "idle": 2.0}}
    assert capacity_report.class_utilization(samples) == {
        ("7x3", "-"): 0.4}
    out = capacity_report.report(str(store_dir))
    assert "tts_lane_seconds_total" in out and "rho=0.400" in out
    parsed = json.loads(capacity_report.report(str(store_dir),
                                               as_json=True))
    assert parsed["lane_counters"]["0"]["idle"] == 2.0


# --------------------------------------------- served integration


@pytest.fixture(scope="module")
def baseline7():
    inst = PFSPInstance.synthetic(jobs=7, machines=3, seed=6)
    got = distributed.search(inst.p_times, lb_kind=1, init_ub=None,
                             n_devices=8, **KW)
    return inst, (got.explored_tree, got.explored_sol, got.best)


def test_serve_capacity_conservation_preempt_and_quarantine(
        fresh_obs, tmp_path):
    """The live drill: preempt->resume then quarantine->readmit on one
    lane; conservation holds within float addition error, the expected
    states were all visited, and the /capacity document + tenant-
    labeled queue wait are live."""
    slow, fast = small(5, jobs=8), small(6)
    srv = SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                       share_incumbent=False)
    try:
        assert srv.lane_ledger is not None and srv.capacity is not None
        lo = srv.submit(SearchRequest(
            p_times=slow.p_times, lb_kind=1, priority=0,
            segment_iters=32, checkpoint_every=1, tenant="bulk",
            faults="delay_every=0.15", **KW))
        wait_state(srv, lo, "RUNNING")
        hi = srv.submit(SearchRequest(p_times=fast.p_times, lb_kind=1,
                                      priority=10, segment_iters=256,
                                      tenant="acme", **KW))
        rec_hi = srv.result(hi, timeout=300)
        assert rec_hi.state == "DONE", (rec_hi.state, rec_hi.error)
        assert srv.counters["preemptions"] >= 1
        rec_lo = srv.result(lo, timeout=600)
        assert rec_lo.state == "DONE", (rec_lo.state, rec_lo.error)

        srv.quarantine_submesh(0, "capacity-test")
        time.sleep(0.05)
        assert srv.lane_ledger.state_of(0) == "quarantined"
        srv.readmit_submesh(0)
        assert srv.lane_ledger.state_of(0) == "idle"

        (row,) = srv.lane_ledger.snapshot()
        assert abs(row["conservation_error_s"]) < 1e-6
        for state in ("compiling", "executing", "quarantined", "idle"):
            assert row["seconds"].get(state, 0.0) > 0.0, (
                state, row["seconds"])
        assert 0.0 < row["utilization"] < 1.0

        # capacity document: classes measured, what-if table populated
        doc = srv.capacity_snapshot()
        assert doc["healthy_lanes"] == 1 and doc["devices"] == 8
        shapes = {(c["shape"], c["tenant"]) for c in doc["classes"]}
        assert ("8x3", "bulk") in shapes and ("7x3", "acme") in shapes
        assert any(c["service_s"] for c in doc["classes"])
        assert doc["predicted_req_per_s"] is not None
        assert [w["lanes"] for w in doc["what_if"]] == [1, 2, 4, 8]
        assert doc["lanes_detail"][0]["lane"] == 0
        assert doc["tenants"]["acme"]["waits"] >= 1
        assert srv.status_snapshot()["capacity"]["utilization"] \
            is not None

        # satellite: per-tenant queue-wait series, merged view intact
        qh = srv._m_queue_wait
        assert qh.snapshot_matching(tenant="acme")["count"] >= 1
        assert qh.snapshot_matching(tenant="bulk")["count"] >= 1
        assert qh.snapshot_matching()["count"] >= 2
        text = srv.metrics.to_prometheus()
        assert 'tenant="acme"' in text.split("tts_queue_wait_seconds",
                                             1)[1]
        assert "tts_lane_seconds_total" in text
        assert "tts_capacity_utilization" in text

        # GET /capacity serves the same document
        httpd = start_http_server(srv)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.port}/capacity",
                timeout=10).read())
            assert body["enabled"] is True
            assert body["healthy_lanes"] == 1
            assert {(c["shape"], c["tenant"])
                    for c in body["classes"]} >= {("7x3", "acme")}
        finally:
            httpd.close()
    finally:
        srv.close()
    # close flushed the final interval: counters sum to the accumulators
    c = srv.metrics.counter(capacity.LANE_SECONDS_METRIC)
    assert c.value_matching(lane=0) > 0.0


@pytest.mark.slow
def test_mid_batch_member_stop_freezes_lane_and_counts_drain_idle():
    """A cancelled batch member finalizes at its next boundary while
    the batchmate drains: the lane ledger visits batch-frozen and the
    frozen tail lands in tts_batch_drain_idle_seconds."""
    tables = [PFSPInstance.synthetic(10, 5, seed=s).p_times
              for s in (21, 22)]
    kw = dict(chunk=16, capacity=1 << 12, min_seed=8, segment_iters=16)
    srv = SearchServer(n_submeshes=1, megabatch=True, batch_max=2,
                       batch_age_s=0.05, autostart=False)
    try:
        ids = [srv.submit(SearchRequest(p_times=t, lb_kind=1, **kw))
               for t in tables]
        srv.start()
        # cancel only after the batch is PAST compile and heartbeating
        # (a cancel inside the compile window would stop the member at
        # its first boundary and the lane would never read executing)
        deadline = time.time() + 120
        while time.time() < deadline:
            sts = [srv.status(r) for r in ids]
            if all(s["state"] == "RUNNING"
                   and (s["progress"] or {}).get("segment")
                   for s in sts):
                break
            time.sleep(0.005)
        assert srv.cancel(ids[0])
        rec0 = srv.result(ids[0], timeout=120)
        assert rec0.state == "CANCELLED"
        rec1 = srv.result(ids[1], timeout=600)
        assert rec1.state == "DONE", (rec1.state, rec1.error)
        # result() unblocks at the member's finalize; the drain-idle
        # observation lands in the batch thread's tail — wait for the
        # slot to release before reading it
        deadline = time.time() + 60
        while time.time() < deadline and srv.slots[0].record is not None:
            time.sleep(0.01)
        (row,) = srv.lane_ledger.snapshot()
        assert row["seconds"].get("batch-frozen", 0.0) > 0.0, \
            row["seconds"]
        assert abs(row["conservation_error_s"]) < 1e-6
        hist = srv.metrics.to_json().get("tts_batch_drain_idle_seconds")
        assert hist and hist["count"] >= 1 and hist["sum"] > 0.0
    finally:
        srv.close()


def test_capacity_off_is_bit_identical_and_series_free(
        fresh_obs, baseline7, tmp_path, monkeypatch):
    """TTS_CAPACITY=0: exact standalone totals, no ledger/model object,
    no snapshot key, no tts_lane/tts_capacity series, no /capacity
    body, no saturation rule."""
    inst, base = baseline7
    monkeypatch.setenv("TTS_CAPACITY", "0")
    srv = SearchServer(n_submeshes=1, workdir=tmp_path / "wd")
    try:
        assert srv.lane_ledger is None and srv.capacity is None
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        out = srv.result(rid, timeout=300)
        assert out.state == "DONE"
        res = out.result
        assert (res.explored_tree, res.explored_sol, res.best) == base
        snap = srv.status_snapshot()
        assert "capacity" not in snap
        assert srv.capacity_snapshot() is None
        text = srv.metrics.to_prometheus()
        assert "tts_lane_seconds_total" not in text
        assert "tts_capacity_" not in text
        assert all(r.name != "saturation" for r in srv.health.rules)
        httpd = start_http_server(srv)
        try:
            body = json.loads(urllib.request.urlopen(
                f"http://127.0.0.1:{httpd.port}/capacity",
                timeout=10).read())
            assert body == {"enabled": False}
        finally:
            httpd.close()
    finally:
        srv.close()


def test_restart_replays_lane_seconds_from_store(fresh_obs, tmp_path,
                                                 monkeypatch):
    """kill-and-return drill (in-process twin of the CI hard-kill):
    lifetime 2 on the same store seeds the ledger from the resumed
    tts_lane_seconds_total counters — utilization history survives and
    conservation stays exact including the replayed seconds."""
    inst = small(3)
    store_dir = tmp_path / "store"
    monkeypatch.setenv("TTS_OBS_STORE", str(store_dir))
    srv = SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                       ledger_dir=str(tmp_path / "led"))
    try:
        assert srv.obs_store is not None
        rid = srv.submit(SearchRequest(p_times=inst.p_times, lb_kind=1,
                                       **KW))
        assert srv.result(rid, timeout=300).state == "DONE"
    finally:
        srv.close()
    served = srv.metrics.counter(capacity.LANE_SECONDS_METRIC) \
        .value_matching(lane=0)
    assert served > 0.0

    srv2 = SearchServer(n_submeshes=1, workdir=tmp_path / "wd",
                        ledger_dir=str(tmp_path / "led"))
    try:
        (row,) = srv2.lane_ledger.snapshot()
        assert row["replayed_s"] == pytest.approx(served)
        assert row["seconds"].get("executing", 0.0) > 0.0
        assert abs(row["conservation_error_s"]) < 1e-6
        # the resumed counter continues, never restarts
        assert srv2.metrics.counter(capacity.LANE_SECONDS_METRIC) \
            .value_matching(lane=0) >= served
        # and the offline report reads the persisted story
        import capacity_report
        _, samples = capacity_report.load(str(store_dir))
        lanes = capacity_report.lane_seconds_from_samples(samples)
        assert sum(lanes.get("0", {}).values()) > 0.0
    finally:
        srv2.close()
