"""Test configuration: run JAX on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; the collective logic is
validated on host-platform virtual devices instead — the "fake backend"
the reference never had (SURVEY.md §4).

Note: the environment preloads jax via sitecustomize and pins
JAX_PLATFORMS to the TPU plugin, so flipping the platform must go through
`jax.config.update` (env vars alone are read too early/late).
"""

import os

if os.environ.get("TTS_TEST_TPU"):
    # hardware mode: keep the attached TPU backend so the pallas-kernel
    # parity tests (tests/test_pallas_tpu.py) run; tests that need the
    # 8-device virtual mesh are skipped below when fewer chips exist
    import jax  # noqa: F401

    def pytest_collection_modifyitems(config, items):
        import jax as _jax

        import pytest as _pytest
        if _jax.device_count() >= 8:
            return
        skip = _pytest.mark.skip(
            reason="needs the 8-device mesh (CPU mode or a full slice)")
        for item in items:
            if ("distributed" in item.nodeid
                    or "test_engine_distributed" in item.nodeid):
                item.add_marker(skip)
else:
    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = os.environ.get("XLA_FLAGS", "")
    if "xla_force_host_platform_device_count" not in flags:
        os.environ["XLA_FLAGS"] = (
            flags + " --xla_force_host_platform_device_count=8"
        ).strip()

    import jax

    jax.config.update("jax_platforms", "cpu")
    try:
        # newer jax: the device count is a config knob (env flags are
        # read too early when sitecustomize preloads jax)
        jax.config.update("jax_num_cpu_devices", 8)
    except AttributeError:
        # older jax reads XLA_FLAGS above at first backend init instead
        pass

    assert jax.device_count() == 8, jax.devices()
